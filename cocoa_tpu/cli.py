"""Command-line driver — drop-in for the reference CLI
(hingeDriver.scala:11-115).

Accepts the same ``--key=value`` flag set, loads train/test LIBSVM data,
computes H = max(1, localIterFrac·n/K), then runs the same algorithm menu:
CoCoA+ and CoCoA always; mini-batch CD, mini-batch SGD, local SGD and DistGD
when ``--justCoCoA=false`` (hingeDriver.scala:84-110).  ``--master`` (the
Spark cluster-manager flag, hingeDriver.scala:23) keeps its meaning:
``local``/``local[k]`` runs single-process; ``host:port`` joins the pod's
multi-controller runtime via ``jax.distributed.initialize`` (with
``--processId`` / ``--numProcesses`` or auto-detection on TPU pods).

TPU-native additions (no reference analogue): ``--dtype``, ``--layout``,
``--rng`` (reference | jax | permuted — permuted is random reshuffling,
~5x fewer comm-rounds to the same certified gap at epsilon scale; see
solvers/base.IndexSampler), ``--mesh`` (dp size; defaults to the largest
divisor of numSplits that fits the device count — K shards multiplex
m = K/D per device when D < K, the Spark coalesce analogue;
``--mesh=1`` forces the single-chip vmap path), ``--trajOut`` (JSONL
trajectory dump), ``--gapTarget`` (early stop on duality gap — with a
divergence guard: the run bails out and reports DIVERGED when the best
gap stalls across a ~300-round window, at least 12 evals; see
solvers/base.stall_window),
``--math`` (exact | fast: margins-decomposition inner loop with
auto-Pallas on TPU, CoCoA/CoCoA+ only), ``--deviceLoop`` (whole train
loop as one on-device while_loop; incompatible with checkpointing),
``--loss`` (hinge | smooth_hinge | logistic — all solvers and the
duality-gap certificate generalize; see ops/losses.py), ``--smoothing``
(the smooth_hinge parameter s), ``--blockSize`` (block-coordinate MXU
inner loop for the SDCA family — same index stream and math as
--math=fast via cached block Gram matrices; see
ops/local_sdca.local_sdca_block; ``auto`` picks the measured-best block
size per data layout — sparse layouts whose densified tile cannot ride
the fused kernel use the in-kernel CSR Gram path of ops/pallas_sparse
when it fits, and keep the sequential kernel otherwise, since
SPLIT-path densified sparse blocks lose to it),
``--blockPipeline=auto|on|off`` (the two-phase software-pipelined block
scan: block b+1's row-tile gather overlapped with block b's chain
kernel — bit-identical schedules, auto = on for multi-block rounds;
``off`` is the serial A/B control benchmarks/kernels.py measures
against.  Dense/densified block paths only: the sparse CSR Gram path
always runs serial and the flag is inert there),
``--divergenceGuard=auto|on|off`` (the
gap-target stall watch; auto arms it only when σ′ is overridden below
the safe K·γ bound — see solvers/base.resolve_divergence_guard),
``--sigma`` (σ′ override — below the
safe K·γ it buys comm-rounds on randomly partitioned data; ``auto``
starts at the aggressive K·γ/2, needs --gapTarget),
``--sigmaSchedule=anneal|trial`` (how --sigma=auto reacts when the stall
watch fires: ``anneal`` — the default — backs σ′ off multiplicatively
toward the safe K·γ *inside* the device loop, continuing from the
current iterate with no restart; ``trial`` is the pre-schedule
trial-then-rerun A/B control, preserved bit-exact.  ``anneal`` with an
explicit sub-safe ``--sigma=<float>`` anneals from that start),
``--warmStart=<s>,<rounds>`` (smooth_hinge(s) warm phase handing off to
hinge at the first debugIter boundary ≥ rounds, inside the same device
loop; requires --loss=hinge), ``--elastic=N`` (gang supervisor: N worker
processes, restart-from-checkpoint on any death; after ``max_restarts``
consecutive failed same-size generations the gang is REFORMED at the
largest P′ < P whose devices divide numSplits — shrink-to-survivors,
cocoa_tpu/elastic.py, docs/DESIGN.md §13), ``--elastic=shrink`` /
``--elastic=N,shrink`` (shrink immediately on the first worker loss —
for deployments whose dead host is not coming back; the bare ``shrink``
form takes the gang size from ``--numProcesses``), and
``--stallTimeout=S`` (with --elastic: also restart a gang that stops
making checkpoint progress for S seconds without any process dying).

``--hotCols=auto|off|<n>`` (sparse layout only) builds the HYBRID
hot/cold column-split layout (data/hybrid.py, docs/DESIGN.md §3b-vi):
the globally hottest columns move into a dense MXU-friendly panel and
the padded-CSR keeps only the cold residual — the scalar-issue-bound
stream merges (97.8% of the measured rcv1 round) shrink by the
coverage fraction.  ``auto`` resolves a 75%-coverage panel under an
explicit HBM budget (panel bytes reported); ``off`` keeps the stream
layout bit-exactly as the A/B control.  ``--evalDense`` additionally
accepts ``auto``: materialize the dense eval twin only when it fits
the HBM budget, otherwise (with a hot panel) the certificate margins
ride the panel matvec + residual stream.

``--ingest=stream|whole|auto`` picks how the LIBSVM text reaches the
device (data/ingest.py, docs/DESIGN.md §12).  ``whole`` is the original
path: every process parses the entire file, then slices out its shards.
``stream`` is the two-pass byte-range pipeline: a parallel index scan
(1/P of the file per process, partial column histograms assembled over
the jax.distributed KV store) followed by each process parsing ONLY the
byte ranges of its local devices' shards, built straight into the
target layout — multiplexed dp meshes (D < K devices), ``--hotCols``
and ``--evalDense`` are all first-class, and per-process peak host RSS
drops to ~1/P of the dataset plus the index.  ``auto`` streams exactly
where it wins: multi-process svm runs on a dp mesh.  The built shards
are bit-identical either way (the whole-file build stays the A/B
control); fp meshes and ``--objective=lasso`` are whole-file only and
reject ``--ingest=stream`` loudly.

``--ingestCache=DIR`` (round 20, docs/DESIGN.md §18) makes ingest free
after first touch: a cold run writes each built shard's device-ready
slabs (plus the pass-1 index/histogram and the hybrid layout meta) as
memmap-able artifacts under DIR — atomic rename, one writer wins,
keyed by the source file's (size, mtime_ns, inode) and the full layout
resolution — and every later run of the same file/config ``np.load``\\ s
them straight into ``device_put``: zero parse, page-cache-shared RSS.
The key is the SHARD, not the process geometry, so an elastic shrink's
survivors re-ingest warm and the supervisor forwards the flag to every
relaunched generation unchanged.  With the cache armed, ``--ingest=auto``
routes every svm run through the shard-granular pipeline (bit-identical
shards, pinned); cold pass-2 parses fan out over an intra-process thread
pool when the native parser is available.  Torn or stale artifacts fall
back to a cold parse with a typed ``ingest_cache_corrupt`` event —
never a crash, never a silently wrong slab.  lasso column shards and fp
meshes have no shard-keyed artifact and reject the flag loudly.

``--fleet=manifest.jsonl`` (round 18, docs/DESIGN.md §16) trains a
FLEET: one tenant model per manifest line (dataset ref / λ / gap
target — a schema-validated JSONL dialect, data/fleet.py), all of them
through ONE compiled vmapped round (solvers/fleet.py): per-tenant λ·n
rides the unchanged SDCA kernels as a traced scalar, each tenant's σ′
schedule / secant bank / gap watch is an independent lane, certified
tenants mask out bitwise-frozen, and the whole fleet costs one compile,
one dispatch and one fetch (256 tenants measured at 173× the serial
solo path's models/s on CPU).  ``--fleetLanes=vmap|map`` picks batched
lanes (throughput) vs sequential lanes in the same jit (bit-parity with
the solo path at any T).  The fleet surface is deliberately narrow:
every flag that cannot mean anything on the one-dispatch path
(--elastic, --staleRounds>0, --hotCols, --warmStart, checkpointing,
--testFile, ...) is rejected loudly with a pointer.

``--serve=PORT`` (round 19, docs/DESIGN.md §17) turns this process into
the production SCORING loop (cocoa_tpu/serving/): batched margin
queries ``x·w`` answered on a TCP line protocol through a compiled
scoring path with statically-shaped batch buckets (``--serveBatch``,
default 64/256/1024 — one XLA compile per bucket, ever), an adaptive
micro-batcher admitting requests under the ``--serveSlaMs`` p99 budget,
and double-buffered model slots a watcher hot-swaps ATOMICALLY from the
newest *validated* checkpoint generation in ``--chkptDir`` — so a
background trainer (a separate process, e.g. an ``--elastic`` gang
pointed at the same directory) keeps the served model fresh without
ever dropping or blocking a query.  Freshness is exported as gap age
(``cocoa_model_gap_age_seconds``: seconds since the serving model's
certificate was produced).  The serve surface is a whitelist — every
training flag passed alongside ``--serve`` is rejected loudly.

``--objective=lasso`` switches to the ProxCoCoA+ L1 family
(solvers/prox_cocoa.py): labels become the regression target b,
``--lambda`` the L1 weight, ``--l2`` the optional elastic-net weight;
A's columns are sharded over the workers and the printed certificate is
the lasso duality gap.

Observability (round 15, docs/DESIGN.md §14): ``--trace`` arms
gang-wide span tracing (per-phase, per-worker timing through the
``--events`` stream; assemble with
``python -m cocoa_tpu.telemetry.trace_report``),
``--flightRecorder=auto|on|off`` the crash flight recorder (last-N
events dumped to ``<events>.flightrec`` on divergence / unhandled
exception / SIGTERM, and by the ``--elastic`` supervisor when a worker
dies), ``--eventsMaxMB=N`` size-caps the event JSONL with an atomic
``.1`` rollover, and ``--metricsInterval=S`` debounces the metrics
textfile rewrites.  Multi-process runs stream events per process
(worker 0 owns ``<events>``, worker p ``<events>.p<p>``).
"""

from __future__ import annotations

import dataclasses
import sys

import jax
import jax.numpy as jnp

from cocoa_tpu.config import REFERENCE_FLAGS, RunConfig
from cocoa_tpu.data import load_libsvm, shard_dataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_cocoa, run_dist_gd, run_minibatch_cd, run_sgd

_TPU_FLAGS = ("dtype", "layout", "rng", "math", "loss",
              "smoothing", "sampling", "sigma")  # same-named RunConfig fields
_EXTRA_FLAGS = ("mesh", "fp", "trajOut", "gapTarget", "resume", "scanChunk",
                "deviceLoop", "master", "processId", "numProcesses",
                "profile", "objective", "l2", "blockSize",
                "blockPipeline", "divergenceGuard",
                "sigmaSchedule", "warmStart", "accel", "theta",
                "elastic", "stallTimeout", "evalDense", "hotCols",
                "ingest", "ingestCache", "metrics", "events", "quiet",
                "trace", "flightRecorder", "eventsMaxMB",
                "metricsInterval", "overlapComm",
                "staleRounds", "fleet", "fleetLanes",
                "serve", "serveBatch", "serveSlaMs",
                "serveMaxNnz", "serveDtype", "serveReplicas",
                "serveRoute", "traceSample", "statusPort")  # run-level

_BOOL_FIELDS = {"just_cocoa"}
_INT_FIELDS = {"num_features", "num_splits", "chkpt_iter", "num_rounds",
               "debug_iter", "seed"}
_FLOAT_FIELDS = {"lam", "local_iter_frac", "beta", "gamma", "smoothing",
                 "sigma"}


def _resolve_auto_block(ds_active, mesh, k: int, dtype,
                        quiet: bool = False) -> int:
    """``--blockSize=auto`` against the ACTIVE dataset (rows for svm,
    columns for lasso): the measured-best B per layout, or 0 to keep the
    sequential kernels (solvers/cocoa.auto_block_size)."""
    from cocoa_tpu.parallel.fanout import shards_per_device
    from cocoa_tpu.solvers.cocoa import auto_block_size

    m_local = shards_per_device(mesh, k) if mesh is not None else k
    bs = auto_block_size(ds_active, m_local, dtype)
    if not quiet:
        print(f"blockSize=auto: using {bs or 'the sequential path'} for the "
              f"{ds_active.layout} layout")
    return bs


def parse_args(argv: list[str]):
    """--key=value (or bare --flag == true, hingeDriver.scala:13-19)."""
    options: dict[str, str] = {}
    for arg in argv:
        stripped = arg.lstrip("-")
        if "=" in stripped:
            key, val = stripped.split("=", 1)
        else:
            key, val = stripped, "true"
        options[key] = val

    cfg = RunConfig()
    extras = {k: None for k in _EXTRA_FLAGS}
    for key, val in options.items():
        if key in _EXTRA_FLAGS:
            extras[key] = val
            continue
        if key in REFERENCE_FLAGS:
            field = REFERENCE_FLAGS[key]
        elif key in _TPU_FLAGS:
            field = key
        else:
            raise SystemExit(f"Invalid argument: --{key}")
        if field in _BOOL_FIELDS:
            if val.lower() not in ("true", "false"):
                # Scala's String.toBoolean rejects anything else too
                raise SystemExit(f"Invalid argument: --{key}={val} (expected true/false)")
            setattr(cfg, field, val.lower() == "true")
        elif field in _INT_FIELDS:
            setattr(cfg, field, int(val))
        elif field in _FLOAT_FIELDS:
            if field == "sigma" and val == "auto":
                # σ′ auto-tuning: try the aggressive K·γ/2, fall back to
                # the safe K·γ if the divergence guard fires (run_cocoa)
                setattr(cfg, field, "auto")
            else:
                setattr(cfg, field, float(val))
        else:
            setattr(cfg, field, val)
    # which flags the USER actually passed (vs dataclass defaults) — what
    # lets the fleet path reject explicitly-given-but-meaningless
    # reference flags (--lambda, --numFeatures) instead of silently
    # training on different values.  A non-field attribute: asdict() and
    # the config hash never see it.
    cfg._explicit = frozenset(options)
    return cfg, extras


def main(argv=None) -> int:
    import os

    # honor JAX_PLATFORMS even when a sitecustomize force-selected a platform
    # via jax.config (which outranks the env var); must happen before the
    # first jax.devices() call locks the backend in
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # persistent XLA compilation cache: repeat CLI runs of the same config
    # skip the 20-60 s first compile (COCOA_NO_COMPILE_CACHE=1 opts out)
    from cocoa_tpu.utils import compile_cache

    compile_cache.enable()

    argv = sys.argv[1:] if argv is None else argv
    cfg, extras = parse_args(argv)

    # --quiet: silence the console (flag echo, per-round lines, summaries).
    # The telemetry sinks (--events/--metrics/--trajOut) are unaffected —
    # a quiet run still leaves the full machine-readable trace.
    quiet = (extras["quiet"] is not None
             and str(extras["quiet"]).lower() != "false")

    # --trace: gang-wide span tracing (telemetry/tracing.py) — per-phase,
    # per-worker timing through the event stream; --flightRecorder: the
    # bounded last-N-events ring dumped to `<events>.flightrec` on
    # divergence / unhandled exception / SIGTERM (and by the --elastic
    # supervisor on a worker death); --eventsMaxMB: size-capped JSONL
    # with atomic `.1` rollover; --metricsInterval: the metrics-textfile
    # write debounce.  Validated up front so a typo fails before the run.
    trace_on = (extras["trace"] is not None
                and str(extras["trace"]).lower() != "false")
    if trace_on and not (extras["events"] or extras["metrics"]):
        print("error: --trace records spans through the telemetry sinks "
              "and needs --events (for trace_report/Perfetto) or "
              "--metrics (for the phase-seconds gauges)", file=sys.stderr)
        return 2
    flightrec_mode = (extras["flightRecorder"] or "auto").lower()
    if flightrec_mode == "true":
        flightrec_mode = "on"   # bare --flightRecorder
    if flightrec_mode not in ("auto", "on", "off"):
        print(f"error: --flightRecorder must be auto|on|off, got "
              f"{extras['flightRecorder']!r}", file=sys.stderr)
        return 2
    if flightrec_mode == "on" and not extras["events"]:
        print("error: --flightRecorder=on needs --events (the dump lands "
              "at <events>.flightrec, and the supervisor-side dump tails "
              "the per-process event streams)", file=sys.stderr)
        return 2
    events_max_bytes = None
    if extras["eventsMaxMB"]:
        try:
            events_max_bytes = int(extras["eventsMaxMB"]) << 20
        except ValueError:
            events_max_bytes = 0
        if events_max_bytes <= 0:
            print(f"error: --eventsMaxMB takes a positive integer of "
                  f"mebibytes, got {extras['eventsMaxMB']!r}",
                  file=sys.stderr)
            return 2
        if not extras["events"]:
            print("error: --eventsMaxMB caps the --events JSONL and "
                  "needs --events", file=sys.stderr)
            return 2
    metrics_interval = 0.0
    if extras["metricsInterval"]:
        try:
            metrics_interval = float(extras["metricsInterval"])
        except ValueError:
            metrics_interval = -1.0
        if metrics_interval < 0:
            print(f"error: --metricsInterval takes seconds >= 0, got "
                  f"{extras['metricsInterval']!r}", file=sys.stderr)
            return 2
        if not extras["metrics"]:
            print("error: --metricsInterval debounces the --metrics "
                  "textfile and needs --metrics", file=sys.stderr)
            return 2

    # --overlapComm: the round-barrier levers (docs/DESIGN.md §15).  On
    # this compiled-collective CLI path the in-round Δw aggregation is a
    # fused psum — already as overlapped as XLA schedules it — so the
    # flag's CLI consumer is the host-side IO at super-block boundaries:
    # checkpoint writes ride a writer thread concurrent with the next
    # dispatch (solvers/base.drive_device_full).  auto = on for
    # single-process runs (a multi-process save allgathers alpha — a
    # collective that must not race a training dispatch); off (default)
    # is bit-identical to pre-flag behavior by construction.
    overlap_flag = (extras["overlapComm"] or "off").lower()
    if overlap_flag == "true":
        overlap_flag = "on"   # bare --overlapComm
    if overlap_flag not in ("auto", "on", "off"):
        print(f"error: --overlapComm must be auto|on|off, got "
              f"{extras['overlapComm']!r}", file=sys.stderr)
        return 2
    # --staleRounds=S: bounded-staleness CoCoA+ aggregation — a round-r
    # contribution may join up to S rounds late under the safe-γ rule
    # (solvers/cocoa.StaleJoinWindow, docs/DESIGN.md §15).  S=0 (the
    # default) is today's synchronous barrier.  S>0 needs the HOST-side
    # exchange aggregation path (the gang harness, tests/_gang_worker.py
    # --real=cocoa); this CLI path aggregates inside the compiled
    # collective, where a round cannot be split — reject loudly instead
    # of accepting a flag that silently does nothing.
    stale_rounds = 0
    if extras["staleRounds"] is not None:
        try:
            stale_rounds = int(extras["staleRounds"])
        except ValueError:
            stale_rounds = -1
        if stale_rounds < 0:
            print(f"error: --staleRounds takes an integer >= 0, got "
                  f"{extras['staleRounds']!r}", file=sys.stderr)
            return 2
        if stale_rounds > 0:
            print("error: --staleRounds > 0 rides the host-exchange "
                  "aggregation path (the chaos gang harness, "
                  "tests/_gang_worker.py --real=cocoa); this CLI path "
                  "aggregates Δw inside the compiled collective, which "
                  "is synchronous by construction — drop the flag "
                  "(docs/DESIGN.md §15)", file=sys.stderr)
            return 2

    # --fleet=manifest.jsonl: thousands of tenant models through ONE
    # compiled vmapped round (solvers/fleet.py, docs/DESIGN.md §16).
    # The fleet surface is deliberately narrow — every flag that cannot
    # mean anything on the one-dispatch tenant-vmapped path is rejected
    # LOUDLY here with a pointer, never accepted as a silent no-op.
    fleet_path = extras["fleet"]
    fleet_lanes = (extras["fleetLanes"] or "vmap").lower()
    if extras["fleetLanes"] and not fleet_path:
        print("error: --fleetLanes picks the fleet's lane execution and "
              "needs --fleet", file=sys.stderr)
        return 2
    if fleet_lanes not in ("vmap", "map"):
        print(f"error: --fleetLanes must be vmap|map, got "
              f"{extras['fleetLanes']!r}", file=sys.stderr)
        return 2
    if fleet_path:
        if extras["serve"]:
            # checked before the fleet's own prerequisite checks so the
            # combination names the real conflict, not a side effect
            # (--serve needs --chkptDir, which the fleet also rejects)
            print("error: --serve does not combine with --fleet: the "
                  "fleet is one training dispatch, serving is a "
                  "long-lived query loop — run them as separate "
                  "processes (docs/DESIGN.md §17)", file=sys.stderr)
            return 2
        rejected = {
            "elastic": "the elastic supervisor gang-restarts one model's "
                       "training; a fleet is thousands of independent "
                       "models in one dispatch — shrinking a gang "
                       "mid-fleet has no defined tenant semantics "
                       "(docs/DESIGN.md §16)",
            "resume": "fleet checkpoint/resume is not in the v1 surface",
            "warmStart": "the warm-start loss handoff is a solo-path "
                         "schedule; fleets share one loss phase "
                         "(docs/DESIGN.md §16)",
            "hotCols": "fleet v1 is dense-layout only",
            "evalDense": "fleet v1 is dense-layout only",
            "ingestCache": "the slab cache is keyed to the solo shard "
                           "layout; fleet tenants sharing a dataset ref "
                           "already dedupe through the in-process memo "
                           "(data/fleet.py — one parse per distinct "
                           "ref)",
            "blockSize": "the block/Pallas kernels own their shard axes "
                         "and cannot ride the tenant vmap",
            "blockPipeline": "the block/Pallas kernels own their shard "
                             "axes and cannot ride the tenant vmap",
        }
        if cfg.test_file:
            print("error: --testFile does not combine with --fleet: "
                  "per-tenant test sets are not in the fleet v1 surface",
                  file=sys.stderr)
            return 2
        if cfg.chkpt_dir:
            print("error: --chkptDir does not combine with --fleet: fleet "
                  "checkpoint/resume is not in the v1 surface (the run is "
                  "one dispatch; rerun the fleet instead)", file=sys.stderr)
            return 2
        for flag, why in rejected.items():
            if extras[flag]:
                print(f"error: --{flag} does not combine with --fleet: "
                      f"{why}", file=sys.stderr)
                return 2
        if cfg.train_file:
            print("error: --fleet names per-tenant datasets in the "
                  "manifest; drop --trainFile", file=sys.stderr)
            return 2
        explicit = getattr(cfg, "_explicit", frozenset())
        if "lambda" in explicit:
            print("error: --lambda does not combine with --fleet: λ is "
                  "per-tenant and comes from the manifest — a global "
                  "--lambda would silently train different models than "
                  "asked for", file=sys.stderr)
            return 2
        if "numFeatures" in explicit:
            print("error: --numFeatures does not combine with --fleet: "
                  "the feature dimension comes from each tenant's "
                  "dataset ref (manifest num_features for file-backed "
                  "tenants)", file=sys.stderr)
            return 2
        if (extras["objective"] or "svm").lower() != "svm":
            print("error: --fleet runs the SVM dual family only "
                  "(--objective=lasso has no fleet path yet)",
                  file=sys.stderr)
            return 2
        if extras["overlapComm"] and overlap_flag != "off":
            print("error: --overlapComm does not combine with --fleet: "
                  "the whole fleet is ONE dispatch and one fetch — there "
                  "is no per-round exchange or checkpoint write to "
                  "overlap (docs/DESIGN.md §16)", file=sys.stderr)
            return 2

    # --serve=PORT (0/bare = ephemeral): the production scoring loop
    # (cocoa_tpu/serving/, docs/DESIGN.md §17) — answer batched margin
    # queries from the newest VALIDATED checkpoint generation in
    # --chkptDir while a background trainer (a separate process, e.g.
    # an --elastic supervised gang pointed at the same directory) keeps
    # it fresh.  The serve surface is a WHITELIST: serving answers
    # queries, it does not train, so every training flag explicitly
    # passed alongside --serve is rejected loudly with a pointer —
    # never accepted as a silent no-op.
    serve_flag = extras["serve"]
    for dep, what in (("serveBatch", "sets the static batch buckets"),
                      ("serveSlaMs", "sets the p99 latency budget"),
                      ("serveMaxNnz", "sets the per-query nonzero "
                                      "budget"),
                      ("serveDtype", "sets the serving precision"),
                      ("serveReplicas", "scales the scorer fleet"),
                      ("serveRoute", "selects the fleet routing "
                                     "policy"),
                      ("traceSample", "samples per-query distributed "
                                      "traces"),
                      ("statusPort", "serves the live ops plane")):
        if extras[dep] and not serve_flag:
            print(f"error: --{dep} {what} of the serving loop and needs "
                  f"--serve", file=sys.stderr)
            return 2
    if serve_flag:
        if fleet_path:
            print("error: --serve does not combine with --fleet: the "
                  "fleet is one training dispatch, serving is a "
                  "long-lived query loop — run them as separate "
                  "processes (docs/DESIGN.md §17)", file=sys.stderr)
            return 2
        pointers = {
            "elastic": "supervise the background TRAINER with --elastic "
                       "and point --serve's --chkptDir at its "
                       "checkpoints — the server must stay outside the "
                       "gang so a resize can never wedge a query "
                       "(docs/DESIGN.md §17)",
            "sigmaSchedule": "σ′ schedules belong to the trainer "
                             "process (--sigmaSchedule=trial is a "
                             "training A/B control; the server only "
                             "reads validated checkpoints)",
            "gapTarget": "the trainer certifies the gap; the server "
                         "reports it as freshness "
                         "(cocoa_model_gap_age_seconds)",
            "resume": "the server always serves the newest validated "
                      "generation; there is nothing to resume",
            "ingestCache": "the slab cache serves TRAINING ingest; put "
                           "--ingestCache on the background trainer's "
                           "command line (the serve-side --trainFile "
                           "parse only derives the query nonzero "
                           "budget)",
            "dtype": "--dtype is the TRAINING precision; the serving "
                     "stack quantizes the model at swap time — set "
                     "--serveDtype=f32|bf16|int8 instead "
                     "(docs/DESIGN.md §20)",
        }
        allowed = {
            # the documented serve surface (README flag table): the
            # serve flags, the model source, the query-side layout, and
            # the observability flags every mode shares
            "serve", "serveBatch", "serveSlaMs", "serveMaxNnz",
            "serveDtype", "serveReplicas", "serveRoute", "chkptDir",
            "numFeatures", "trainFile", "hotCols", "quiet",
            "metrics", "events", "trace", "flightRecorder",
            "eventsMaxMB", "metricsInterval", "seed",
            "traceSample", "statusPort",
        }
        explicit = getattr(cfg, "_explicit", frozenset())
        for key in sorted(explicit - allowed):
            why = pointers.get(
                key, "serving answers queries from the checkpoints in "
                     "--chkptDir; training flags belong to the "
                     "background trainer process (docs/DESIGN.md §17)")
            print(f"error: --{key} does not combine with --serve: {why}",
                  file=sys.stderr)
            return 2
        if not cfg.chkpt_dir:
            print("error: --serve needs --chkptDir (the checkpoint "
                  "directory the hot-swap watcher polls — point it at "
                  "the background trainer's --chkptDir)",
                  file=sys.stderr)
            return 2
        if extras["hotCols"] is not None and not cfg.train_file:
            print("error: --serve with --hotCols needs --trainFile: the "
                  "hot panel is the TRAINED column split, resolved from "
                  "the training data's column histogram "
                  "(data/hybrid.py)", file=sys.stderr)
            return 2
        # --serveReplicas=N scales the scorer fleet behind a router
        # front door (serving/fleet.py + router.py, docs/DESIGN.md
        # §21); --serveRoute picks its routing policy.  Validated HERE
        # (before any JAX work) so a typo fails in milliseconds
        n_replicas = 1
        if extras["serveReplicas"]:
            import os
            try:
                n_replicas = int(extras["serveReplicas"])
            except ValueError:
                n_replicas = 0
            if n_replicas < 1:
                print(f"error: --serveReplicas takes a replica count "
                      f">= 1, got {extras['serveReplicas']!r}",
                      file=sys.stderr)
                return 2
            cores = os.cpu_count() or 1
            if n_replicas > cores:
                print(f"warning: --serveReplicas={n_replicas} "
                      f"oversubscribes the {cores} detected core(s): "
                      f"replicas time-share cores and per-replica "
                      f"scaling efficiency degrades — measure before "
                      f"trusting a fleet this wide", file=sys.stderr)
        if extras["serveRoute"]:
            from cocoa_tpu.serving.router import Router as _Router
            if extras["serveRoute"] not in _Router.ROUTES:
                print(f"error: --serveRoute takes one of "
                      f"{'/'.join(_Router.ROUTES)}, got "
                      f"{extras['serveRoute']!r}", file=sys.stderr)
                return 2
            if n_replicas < 2:
                print("error: --serveRoute picks how the fleet router "
                      "spreads queries and needs --serveReplicas>=2 "
                      "(one replica has nothing to route between)",
                      file=sys.stderr)
                return 2
        if n_replicas >= 2 and extras["hotCols"] is not None:
            print("error: --hotCols does not combine with "
                  "--serveReplicas>=2: per-replica hot panels are not "
                  "in the fleet v1 surface — serve the hybrid layout "
                  "from a single process, or drop --hotCols "
                  "(docs/DESIGN.md §21)", file=sys.stderr)
            return 2

    # --profile=DIR traces the whole run; --profile=DIR,START,STOP traces
    # the round window [START, STOP) by riding the telemetry event stream
    # (telemetry/profiling.py) — validated here so a typo fails before the
    # run, not after it
    profile_dir = profile_window = None
    if extras["profile"]:
        from cocoa_tpu.telemetry.profiling import parse_profile_flag

        try:
            profile_dir, p_start, p_stop = parse_profile_flag(
                extras["profile"])
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if p_start is not None:
            profile_window = (p_start, p_stop)

    if not cfg.train_file and not fleet_path and not serve_flag:
        print("error: --trainFile is required", file=sys.stderr)
        return 2
    if cfg.num_features <= 0 and not fleet_path:
        # serving needs it too: the query width the compiled scoring
        # path is built for (and the width checkpoints must match)
        print("error: --numFeatures must be positive", file=sys.stderr)
        return 2
    from cocoa_tpu.ops import losses as losses_mod

    try:
        losses_mod.validate(cfg.loss, cfg.smoothing)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if cfg.loss not in losses_mod.LOSSES:
        # prox rules (lasso) are selected by --objective, never by --loss —
        # the SVM solvers would run garbage updates and crash at first eval
        print(f"error: --loss must be one of {losses_mod.LOSSES}; "
              f"use --objective=lasso for the L1 family", file=sys.stderr)
        return 2
    if cfg.math not in ("exact", "fast"):
        print(f"error: --math must be exact|fast, got {cfg.math!r}",
              file=sys.stderr)
        return 2

    if cfg.sigma == "auto" and not extras["gapTarget"] and not fleet_path:
        # fail at the CLI boundary with the standard message/exit-code —
        # run_cocoa would raise the same requirement later as a traceback.
        # (--fleet runs accept manifest-supplied per-tenant targets
        # instead; the fleet runner validates per-tenant coverage.)
        print("error: --sigma=auto requires --gapTarget (the σ′ fallback "
              "triggers on the divergence guard, which runs on the "
              "gap-target path)", file=sys.stderr)
        return 2

    sigma_schedule = extras["sigmaSchedule"]
    if sigma_schedule is not None and sigma_schedule not in ("trial",
                                                             "anneal"):
        print(f"error: --sigmaSchedule must be trial|anneal, got "
              f"{extras['sigmaSchedule']!r}", file=sys.stderr)
        return 2
    if sigma_schedule == "trial" and cfg.sigma != "auto":
        print("error: --sigmaSchedule=trial is the --sigma=auto A/B "
              "control and needs --sigma=auto", file=sys.stderr)
        return 2
    anneal_engages = (cfg.sigma == "auto"
                      or (isinstance(cfg.sigma, float)
                          and 0 < cfg.sigma < cfg.num_splits * cfg.gamma))
    if (sigma_schedule == "anneal" and anneal_engages
            and not extras["gapTarget"] and not fleet_path):
        # the anneal backoff rides the stall watch, which only runs on the
        # gap-target path (with no sub-safe σ′ the schedule is inert and
        # the flag is accepted as a no-op)
        print("error: --sigmaSchedule=anneal requires --gapTarget (the "
              "in-loop backoff triggers on the stall watch, which runs "
              "on the gap-target path)", file=sys.stderr)
        return 2

    accel_flag = (extras["accel"] or "auto").lower()
    if accel_flag not in ("auto", "on", "off"):
        print(f"error: --accel must be auto|on|off, got "
              f"{extras['accel']!r}", file=sys.stderr)
        return 2
    theta_flag = (extras["theta"] or "fixed").lower()
    if theta_flag not in ("fixed", "adaptive"):
        print(f"error: --theta must be fixed|adaptive, got "
              f"{extras['theta']!r}", file=sys.stderr)
        return 2
    if accel_flag == "on" and not extras["gapTarget"] and not fleet_path:
        # momentum's restart rule monitors the eval-cadence gap; without
        # a target the run is a fixed-round benchmark path that must stay
        # bit-comparable — require the gap-target regime explicitly.
        # (--fleet accel accepts manifest-supplied per-tenant targets;
        # the fleet runner validates every tenant carries one.)
        print("error: --accel=on requires --gapTarget (the momentum "
              "restart rule monitors the gap trajectory; fixed-round "
              "benchmark runs stay unaccelerated)", file=sys.stderr)
        return 2
    if accel_flag == "on" and sigma_schedule == "trial":
        print("error: --accel cannot ride --sigmaSchedule=trial (the "
              "trial is the bit-exact A/B control); use "
              "--sigmaSchedule=anneal", file=sys.stderr)
        return 2
    if theta_flag == "adaptive" and (accel_flag == "off"
                                     or sigma_schedule == "trial"
                                     or not extras["gapTarget"]):
        print("error: --theta=adaptive requires an accelerated "
              "gap-targeted run (--accel=auto|on with --gapTarget, "
              "not --sigmaSchedule=trial)", file=sys.stderr)
        return 2

    warm_start = None
    if extras["warmStart"]:
        parts = str(extras["warmStart"]).split(",")
        try:
            if len(parts) != 2:
                raise ValueError
            warm_start = (float(parts[0]), int(parts[1]))
        except ValueError:
            print(f"error: --warmStart takes <smoothing>,<rounds> (e.g. "
                  f"0.1,300), got {extras['warmStart']!r}", file=sys.stderr)
            return 2
        if warm_start[0] <= 0 or warm_start[1] < 1:
            print("error: --warmStart needs smoothing > 0 and rounds >= 1",
                  file=sys.stderr)
            return 2
        if cfg.loss != "hinge":
            print("error: --warmStart hands a smooth_hinge phase off to "
                  "hinge and requires --loss=hinge", file=sys.stderr)
            return 2
        if cfg.debug_iter <= 0:
            print("error: --warmStart requires --debugIter > 0 (the "
                  "in-loop handoff lands on the eval cadence)",
                  file=sys.stderr)
            return 2

    if extras["stallTimeout"] and not extras["elastic"]:
        # without a supervisor there is no watchdog to act on the timeout —
        # silently ignoring it would leave the user believing stall
        # protection is active on a run that can still wedge forever
        print("error: --stallTimeout only acts under --elastic=N (the "
              "supervisor is what kills and restarts a wedged gang)",
              file=sys.stderr)
        return 2

    if extras["elastic"]:
        # --elastic=N: this process becomes the SUPERVISOR — it launches N
        # worker copies of this command line (each with its own processId
        # and a supervisor-chosen coordinator port) and gang-restarts them
        # from the latest checkpoint when any worker dies.  The Spark-
        # lineage-recovery analogue for an all-reduce runtime
        # (cocoa_tpu/elastic.py).  When the same-size gang cannot be kept
        # alive (max_restarts consecutive failures — or immediately with
        # the "shrink" spec), the supervisor reforms it at P′ < P
        # survivors: numSplits shards re-divide over the smaller gang and
        # each survivor streams in only its inherited shards
        # (docs/DESIGN.md §13).
        from cocoa_tpu import elastic

        shrink_mode = "auto"
        n_workers = None
        devices_per_worker = 1
        for part in str(extras["elastic"]).split(","):
            part = part.strip()
            if part == "shrink":
                shrink_mode = "now"
            elif part.startswith("devices="):
                # local devices each worker owns (the per-host chip count
                # on TPU; 1 for a localhost CPU gang) — the granularity
                # shrink must keep K divisible by.  Declared, not probed:
                # the supervisor must never initialize a backend itself
                # (on a TPU host it would steal the chips from its own
                # workers)
                try:
                    devices_per_worker = int(part[len("devices="):])
                except ValueError:
                    devices_per_worker = 0
                if devices_per_worker < 1:
                    print(f"error: --elastic devices= takes a positive "
                          f"per-worker device count, got {part!r}",
                          file=sys.stderr)
                    return 2
            elif part:
                try:
                    n_workers = int(part)
                except ValueError:
                    print("error: --elastic takes an integer worker count "
                          "and/or 'shrink' and/or 'devices=D' "
                          "(--elastic=4, --elastic=4,shrink, "
                          "--elastic=shrink, --elastic=4,shrink,devices=4), "
                          f"got {extras['elastic']!r}",
                          file=sys.stderr)
                    return 2
        if n_workers is None:
            # bare --elastic=shrink: the gang size comes from
            # --numProcesses (the flag that already names it)
            if not extras["numProcesses"]:
                print("error: --elastic=shrink needs a gang size; pass "
                      "--elastic=N,shrink or add --numProcesses=N",
                      file=sys.stderr)
                return 2
            try:
                n_workers = int(extras["numProcesses"])
            except ValueError:
                print("error: --numProcesses must be an integer",
                      file=sys.stderr)
                return 2
        if n_workers < 1:
            print("error: --elastic needs at least 1 worker", file=sys.stderr)
            return 2
        try:
            elastic_fp = int(extras["fp"]) if extras["fp"] else 1
        except ValueError:
            print(f"error: --fp must be an integer, got {extras['fp']!r}",
                  file=sys.stderr)
            return 2
        if elastic_fp > 1:
            # the fp axis pins w's column split to the device grid — a
            # resized gang cannot restore the old checkpoints' placement.
            # Explicit shrink is rejected loudly; the default degrades to
            # the pre-shrink same-size supervision with a note.
            if shrink_mode == "now":
                print("error: --elastic=shrink does not support "
                      "feature-parallel (fp) meshes: w's column split is "
                      "pinned to the device grid, so a reformed gang "
                      "cannot resume the checkpoints; drop --fp or use "
                      "--elastic=N", file=sys.stderr)
                return 2
            shrink_mode = "off"
            print("note: --elastic with --fp keeps same-size restarts "
                  "only (an fp gang cannot shrink; see docs/DESIGN.md "
                  "§13)", file=sys.stderr)
        if not cfg.chkpt_dir:
            print("warning: --elastic without --chkptDir restarts from "
                  "round 1 on failure (no checkpoints to resume from)",
                  file=sys.stderr)

        def progress_token():
            # the restart budget bounds CONSECUTIVE failures: any new or
            # renamed checkpoint file since the last generation means the
            # run advanced, so the streak resets.  The worker's --metrics
            # textfile (refreshed per event, or per --metricsInterval
            # window under the debounce — see the warning above) is a
            # FINER progress signal than checkpoint files — it advances
            # on every eval, so the stall watchdog can catch a wedge
            # well inside a long chkptIter interval.
            ckpts = None
            if cfg.chkpt_dir and os.path.isdir(cfg.chkpt_dir):
                ckpts = tuple(sorted(
                    f for f in os.listdir(cfg.chkpt_dir)
                    if f.endswith(".npz")))
            metrics = None
            if extras["metrics"]:
                try:
                    with open(extras["metrics"]) as f:
                        metrics = f.read()
                except OSError:
                    pass
            if ckpts is None and metrics is None:
                return None
            return (ckpts, metrics)

        stall = None
        if extras["stallTimeout"]:
            # --stallTimeout=SECONDS: also restart a gang that WEDGES
            # without any process dying (dead device tunnel, one worker
            # exiting 0 while peers block in a collective).  Progress =
            # new round-stamped checkpoint files, so it needs --chkptDir
            # and a sensible --chkptIter cadence.
            try:
                stall = float(extras["stallTimeout"])
            except ValueError:
                print("error: --stallTimeout must be seconds (float), got "
                      f"{extras['stallTimeout']!r}", file=sys.stderr)
                return 2
            if stall <= 0:
                print("error: --stallTimeout must be > 0", file=sys.stderr)
                return 2
            if not cfg.chkpt_dir and not extras["metrics"]:
                print("error: --stallTimeout watches checkpoint/metrics "
                      "progress — it needs --chkptDir or --metrics",
                      file=sys.stderr)
                return 2
            if stall < 120:
                # the watchdog cannot tell "compiling" from "wedged": a
                # generation's first token change needs first-compile
                # (20-60 s through a tunneled device, see
                # utils/compile_cache.py) PLUS chkptIter rounds — a tight
                # timeout SIGKILLs healthy gangs until the restart budget
                # burns (round-5 review finding)
                print(f"warning: --stallTimeout={stall:g}s is shorter than "
                      f"a typical first-compile + first-checkpoint budget; "
                      f"healthy gangs may be killed as stalled — consider "
                      f">= 120s (and a --chkptIter the gang can reach "
                      f"within the timeout)", file=sys.stderr)
            if (extras["metrics"] and metrics_interval > 0
                    and metrics_interval * 2 > stall):
                # the watchdog's finest progress signal is worker 0's
                # metrics textfile, and the debounce delays its rewrites
                # by up to one interval — an interval near (or past) the
                # stall timeout blinds the watchdog to live progress and
                # SIGKILLs healthy gangs
                print(f"warning: --metricsInterval={metrics_interval:g}s "
                      f"debounces the metrics progress signal the "
                      f"--stallTimeout={stall:g}s watchdog reads; keep "
                      f"the interval well under half the timeout (or "
                      f"rely on --chkptDir progress)", file=sys.stderr)

        if extras["events"] or extras["metrics"]:
            # the supervisor's gang-restart/resize events land in the SAME
            # event JSONL worker 0 writes (whole-line appends interleave
            # safely) — one machine-readable stream for the whole
            # supervised run.  The gang gauges (cocoa_gang_size,
            # cocoa_gang_generations_total, cocoa_restart_backoff_seconds)
            # land in a SIBLING textfile `<metrics>.gang` rendering ONLY
            # those families: worker 0 owns `<metrics>` and rewrites it
            # per event, so sharing one file would have two processes
            # flip-flopping its contents — and duplicating the worker
            # families here would break textfile collectors that glob
            # the directory
            from cocoa_tpu import telemetry

            bus_sup = telemetry.get_bus()
            # no max_bytes here: the supervisor shares worker 0's file,
            # and a file must have exactly ONE rotating owner (two
            # emitters racing os.replace would clobber the fresh `.1`
            # archive) — worker 0 rotates; the supervisor's handful of
            # restart/resize events ride whichever file is current
            bus_sup.configure(jsonl_path=extras["events"])
            if extras["metrics"]:
                from cocoa_tpu.telemetry.metrics import MetricsWriter

                bus_sup.subscribe(MetricsWriter(
                    extras["metrics"] + ".gang", families="gang",
                    flush_interval_s=metrics_interval))
            if trace_on:
                # supervisor spans (gang generations, restart backoffs)
                # join the same stream; no worker tag — trace_report
                # attributes them by pid
                from cocoa_tpu.telemetry import tracing

                tracing.configure(enabled=True)
        return elastic.supervise(
            elastic.strip_elastic_flags(argv), n_workers,
            resume=bool(cfg.chkpt_dir), progress_token=progress_token,
            stall_timeout_s=stall,
            num_splits=cfg.num_splits, shrink=shrink_mode,
            devices_per_worker=devices_per_worker,
        )

    # multi-host: --master=host:port connects this process to the pod's
    # coordinator (the Spark-master analogue) BEFORE any backend use, so
    # jax.devices() below is the global device set
    from cocoa_tpu.parallel import distributed

    try:
        proc_id = int(extras["processId"]) if extras["processId"] else None
        n_procs = int(extras["numProcesses"]) if extras["numProcesses"] else None
    except ValueError:
        print("error: --processId/--numProcesses must be integers",
              file=sys.stderr)
        return 2
    try:
        distributed.maybe_initialize(extras["master"], proc_id, n_procs)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # echo flags, as the reference does (hingeDriver.scala:41-48) — with its
    # gamma-prints-beta bug (quirk #2) fixed
    if not quiet:
        for f in dataclasses.fields(cfg):
            print(f"{f.name}: {getattr(cfg, f.name)}")

    dtype = jnp.dtype(cfg.dtype)
    # jaxlint: allow=f64 -- explicit --dtype=float64 opt-in: the
    # reference (Breeze) is f64 throughout, and parity runs reproduce it
    if dtype == jnp.float64:
        # jaxlint: allow=f64 -- same opt-in: x64 only flips when the user
        # asked for the f64 parity configuration
        jax.config.update("jax_enable_x64", True)

    # telemetry: the event bus + metrics textfile are owned by process 0
    # (worker 0 of an elastic gang / host 0 of a pod inherits stdout the
    # same way); the run manifest is the FULL flag surface — reference
    # flags and TPU-native extras alike — so the config hash identifies
    # the run end to end.  The ``run_start`` emit itself waits until the
    # data layout is resolved (below) so the manifest can record the
    # hot/cold split provenance; cfg/extras are not mutated in between.
    from cocoa_tpu import telemetry
    from cocoa_tpu.telemetry import recorder as flightrec_lib
    from cocoa_tpu.telemetry import tracing

    bus = telemetry.get_bus()
    is_primary = (proc_id or 0) == 0
    # per-process event streams: worker 0 owns `<events>` (shared with the
    # elastic supervisor's appends, as before); worker p > 0 streams to
    # `<events>.p<p>` — so every worker's spans and events survive its own
    # death for the supervisor's flight-recorder dump, and
    # telemetry/trace_report.py can merge the gang's streams into one
    # timeline.  The metrics textfile stays worker-0-only (the
    # supervisor's `.gang` sibling carries the gang families).
    events_path = None
    if extras["events"]:
        events_path = flightrec_lib.worker_stream_path(
            extras["events"], proc_id or 0)
    if events_path or (is_primary and extras["metrics"]):
        bus.configure(
            jsonl_path=events_path,
            metrics_path=extras["metrics"] if is_primary else None,
            max_bytes=events_max_bytes,
            metrics_interval_s=metrics_interval)
    if trace_on:
        tracing.configure(enabled=True, worker=proc_id or 0)
    if events_path and flightrec_mode != "off":
        # the in-process half of the flight recorder: ring of the last N
        # events, dumped on divergence / unhandled exception / SIGTERM
        # (telemetry/recorder.py; the supervisor covers SIGKILL)
        flightrec_lib.install(bus, events_path)
    cfg_manifest = {**dataclasses.asdict(cfg),
                    **{k: v for k, v in extras.items() if v is not None}}
    run_meta = {"dataset": cfg.train_file, "seed": cfg.seed,
                "config_hash": telemetry.events.config_hash(cfg_manifest)}

    if fleet_path:
        return _run_fleet_cli(cfg, extras, quiet, bus, cfg_manifest,
                              fleet_lanes, sigma_schedule, accel_flag,
                              theta_flag)

    if serve_flag:
        return _run_serve_cli(cfg, extras, quiet, bus, cfg_manifest,
                              serve_flag)

    k = cfg.num_splits

    # mesh selection: K shards ride a D-device dp mesh whenever D divides K
    # (m = K/D logical shards multiplex per device — the Spark ``coalesce``
    # analogue, OptUtils.scala:14); K=D is the 1:1 case, D=1 runs the
    # single-chip vmap path (all K logical shards on one device).  An
    # explicit --mesh that can't be honored is an error; inferred sizes
    # fall back silently.  --fp=F adds a feature axis: a (D, F) mesh over
    # D*F devices, w and X columns split over fp.
    mesh = None
    try:
        fp = int(extras["fp"]) if extras["fp"] else 1
    except ValueError:
        print(f"error: --fp must be an integer, got {extras['fp']!r}",
              file=sys.stderr)
        return 2
    if fp < 1:
        print(f"error: --fp must be >= 1, got {fp}", file=sys.stderr)
        return 2
    explicit = extras["mesh"] is not None
    if explicit:
        try:
            mesh_size = int(extras["mesh"])
        except ValueError:
            print(f"error: --mesh must be an integer, got {extras['mesh']!r}",
                  file=sys.stderr)
            return 2
    else:
        # largest divisor of K that fits the device budget
        mesh_size = max(
            (d for d in range(1, min(k, len(jax.devices()) // fp) + 1)
             if k % d == 0), default=1,
        )
    if explicit and (mesh_size * fp > len(jax.devices())
                     or (mesh_size > 1 and k % mesh_size != 0)):
        print(f"error: --mesh={mesh_size} (x fp={fp}) needs a divisor of "
              f"numSplits={k} and mesh x fp devices (have "
              f"{len(jax.devices())}); use --mesh=1 for the single-chip "
              f"path", file=sys.stderr)
        return 2
    if fp > 1 and explicit and mesh_size == 1 and k > 1:
        print(f"error: --fp={fp} needs a device mesh and is incompatible "
              f"with the --mesh=1 single-chip path; drop --mesh or pass "
              f"--mesh={k}", file=sys.stderr)
        return 2
    if fp > 1 and mesh_size != k:
        print(f"error: --fp={fp} requires a {k}x{fp}-device mesh "
              f"(numSplits x fp; shard multiplexing is dp-only; have "
              f"{len(jax.devices())} devices)", file=sys.stderr)
        return 2
    if not explicit and not quiet and mesh_size * fp < len(jax.devices()):
        # inferred mesh leaves devices idle (prime/coprime K falls to the
        # largest divisor, worst case 1 — all shards on one chip).  A perf
        # cliff the user can fix by aligning K, so say so.
        print(f"note: inferred mesh uses {mesh_size} of "
              f"{len(jax.devices())} devices (largest divisor of "
              f"numSplits={k} that fits); a numSplits divisible by "
              f"{len(jax.devices())} would use every device")
    if mesh_size > 1 or fp > 1:
        mesh = make_mesh(mesh_size, fp=fp)

    objective = (extras["objective"] or "svm").lower()
    if objective not in ("svm", "lasso"):
        print(f"error: --objective must be svm|lasso, got {objective!r}",
              file=sys.stderr)
        return 2

    # same bare-flag/boolean convention as --deviceLoop: present (or any
    # value except "false") enables it — except the new "auto", which
    # resolves per dataset below (twin only when it fits the HBM budget)
    ed_spec = ("false" if extras["evalDense"] is None
               else str(extras["evalDense"]).lower())
    eval_dense = ed_spec not in ("false", "auto")

    # --ingest=stream|whole|auto: how the LIBSVM text reaches the device
    # (data/ingest.py).  Resolved against the mesh/objective BEFORE any
    # parse so a streamed run never pays a whole-file pass by accident.
    from cocoa_tpu.data import ingest as ingest_lib

    # --ingestCache=DIR: the shard-granular persistent slab cache
    # (data/slab_cache.py, docs/DESIGN.md §18).  Armed BEFORE mode
    # resolution: with a cache, auto routes svm runs through the
    # shard-granular pipeline so warm shards load with zero parse.
    ingest_cache = None
    if extras["ingestCache"]:
        if objective == "lasso":
            print("error: --ingestCache does not apply to "
                  "--objective=lasso (the column shards transpose the "
                  "row slabs per run — nothing shard-keyed to cache); "
                  "drop the flag", file=sys.stderr)
            return 2
        from cocoa_tpu.parallel.mesh import has_fp as _has_fp

        if _has_fp(mesh):
            print("error: --ingestCache does not support "
                  "feature-parallel (fp) meshes (the fp column split "
                  "re-buckets rows per device grid — the shard "
                  "artifacts are geometry-free by contract); drop --fp "
                  "or the cache flag", file=sys.stderr)
            return 2
        from cocoa_tpu.data import slab_cache as slab_cache_lib

        try:
            ingest_cache = slab_cache_lib.SlabCache(
                str(extras["ingestCache"]))
        except OSError as e:
            print(f"error: --ingestCache={extras['ingestCache']!r}: "
                  f"{e}", file=sys.stderr)
            return 2
        if bus.active():
            ingest_cache.on_corrupt = (
                lambda **kw: bus.emit("ingest_cache_corrupt", **kw))

    try:
        ingest_mode = ingest_lib.resolve_ingest_mode(
            extras["ingest"], mesh, objective=objective,
            cached=ingest_cache is not None)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from cocoa_tpu.data import resolve_hot_cols, resolve_layout

    hot_n = 0
    layout_split = None
    ingest_reports = []
    cache_events = []

    def record_cache(path, status, info):
        """One typed ``ingest_cache`` record per file (``info`` is the
        StreamBuildInfo both ingest paths produce) — the single appender
        every branch shares, so the event's field set cannot drift."""
        if ingest_cache is not None:
            cache_events.append(dict(
                path=path, status=status,
                shards_cached=info.shards_cached,
                shards_total=info.shards_total,
                bytes_mapped=info.cache_bytes_mapped,
                seconds_saved=info.seconds_saved))

    def cache_snap():
        """Counter snapshot bracketing one whole-path build."""
        if ingest_cache is None:
            return (0, 0, 0)
        return (ingest_cache.shard_hits, ingest_cache.shard_misses,
                ingest_cache.bytes_mapped)

    data = None
    ds = test_ds = None
    if objective == "lasso" and extras["hotCols"] is not None:
        # column shards transpose the roles (the shard "rows" ARE
        # columns); a row-space hot panel has no meaning there
        print("error: --hotCols does not apply to --objective=lasso "
              "(column shards already partition the feature axis)",
              file=sys.stderr)
        return 2

    def announce_eval(eval_dense, hot_n):
        if not quiet:
            fallback = ("hot panel + residual stream" if hot_n
                        else "per-nonzero gather (no hot panel — "
                             "consider --hotCols=auto)")
            print(f"evalDense=auto: "
                  f"{'dense twin' if eval_dense else fallback} "
                  f"for the certificate margins")

    def announce_hot(layout_split, hot_n):
        if hot_n and not quiet:
            print(f"hotCols={layout_split['spec']}: panel {hot_n} "
                  f"columns, {layout_split['coverage'] * 100:.1f}% "
                  f"nonzero coverage, "
                  f"{layout_split['panel_bytes'] / 2**20:.1f} MiB HBM, "
                  f"residual mean nnz "
                  f"{layout_split['residual_mean_nnz']:.1f} (max "
                  f"{layout_split['residual_max_nnz']})")

    def resolve_stats_knobs(n_, total_nnz_, hist_):
        """``--layout``/``--hotCols``/``--evalDense=auto`` resolved from
        dataset STATS alone — ONE implementation shared by the streaming
        pass-1 path and the whole-path warm loader so the two cannot
        drift (the cold whole path resolves from the parsed data via
        resolve_hot_cols: the pinned A/B control of this resolution).
        Returns ``(resolved_layout, hot_width, eval_dense)``; raises
        ValueError for the --hotCols-vs-layout rejection and the
        over-budget explicit panel."""
        from cocoa_tpu.data import hybrid as hybrid_knobs
        from cocoa_tpu.data.sharding import (eval_dense_fits,
                                             resolve_layout_stats)

        lay = resolve_layout_stats(n_, cfg.num_features, total_nnz_,
                                   cfg.layout, mesh)
        if extras["hotCols"] is not None and lay != "sparse":
            raise ValueError("--hotCols (the hot/cold column split) "
                             "only applies to the sparse layout")
        hot_w, ed = 0, eval_dense
        if lay == "sparse":
            hot_w = hybrid_knobs.resolve_hot_width(
                extras["hotCols"], hist_, n_, k, dtype)
            if ed_spec == "auto":
                ed = eval_dense_fits(n_, cfg.num_features, k, dtype)
        return lay, hot_w, ed

    import time as time_mod

    if ingest_mode == "stream":
        # streaming sharded ingest (svm only — resolve_ingest_mode
        # rejects lasso/fp): pass 1 builds the row index + global column
        # histogram from per-process partial scans, --hotCols resolves
        # from that histogram bit-identically to the whole-file build,
        # pass 2 parses only this process's shard byte ranges
        from cocoa_tpu.data import hybrid as hybrid_lib

        def stream_cache_status(index, sinfo):
            # one file's cache outcome: the shard status degraded to
            # "partial" when the index itself had to be re-scanned (a
            # warm run pays zero scan AND zero parse)
            if ingest_cache is None:
                return "off"
            if sinfo.cache_status == "hit" and index.scan_bytes:
                return "partial"
            return sinfo.cache_status

        try:
            index = ingest_lib.build_index(cfg.train_file,
                                           cfg.num_features,
                                           cache=ingest_cache)
            n = index.n
            resolved_layout, hot_n, eval_dense = resolve_stats_knobs(
                n, index.total_nnz, index.hist)
            if resolved_layout == "sparse" and ed_spec == "auto":
                announce_eval(eval_dense, hot_n)
            ds, sinfo = ingest_lib.stream_shard_dataset(
                cfg.train_file, cfg.num_features, k, layout=cfg.layout,
                dtype=dtype, mesh=mesh, eval_dense=eval_dense,
                hot_cols=hot_n, index=index, cache=ingest_cache)
            if resolved_layout == "sparse":
                layout_split = hybrid_lib.stats_from_counts(
                    extras["hotCols"], index.hist, hot_n,
                    (sinfo.residual_max_nnz if hot_n
                     else int(index.row_nnz.max(initial=0))),
                    n, k, dtype)
                announce_hot(layout_split, hot_n)
            ingest_reports.append(ingest_lib.IngestReport(
                mode="stream", path=cfg.train_file,
                file_bytes=index.file_bytes,
                processes=jax.process_count(),
                parse_seconds=index.scan_seconds + sinfo.parse_seconds,
                bytes_read=index.scan_bytes + sinfo.bytes_read,
                rows=sinfo.rows, nnz=sinfo.nnz,
                n=n, total_nnz=index.total_nnz,
                peak_rss_bytes=ingest_lib.peak_rss_bytes(),
                cache=stream_cache_status(index, sinfo)))
            record_cache(cfg.train_file,
                         stream_cache_status(index, sinfo), sinfo)
            if cfg.test_file:
                tindex = ingest_lib.build_index(cfg.test_file,
                                                cfg.num_features,
                                                cache=ingest_cache)
                test_ds, tinfo = ingest_lib.stream_shard_dataset(
                    cfg.test_file, cfg.num_features, k,
                    layout=cfg.layout, dtype=dtype, mesh=mesh,
                    eval_dense=eval_dense, hot_cols=hot_n, index=tindex,
                    cache=ingest_cache)
                ingest_reports.append(ingest_lib.IngestReport(
                    mode="stream", path=cfg.test_file,
                    file_bytes=tindex.file_bytes,
                    processes=jax.process_count(),
                    parse_seconds=(tindex.scan_seconds
                                   + tinfo.parse_seconds),
                    bytes_read=tindex.scan_bytes + tinfo.bytes_read,
                    rows=tinfo.rows, nnz=tinfo.nnz,
                    n=tindex.n, total_nnz=tindex.total_nnz,
                    peak_rss_bytes=ingest_lib.peak_rss_bytes(),
                    cache=stream_cache_status(tindex, tinfo)))
                record_cache(cfg.test_file,
                             stream_cache_status(tindex, tinfo), tinfo)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        # whole-file ingest: every process parses the full file, then
        # slices out its shards (the bit-exact A/B control; multi-process
        # dp runs still materialize only their local shards host-side).
        # An explicit --ingest=whole with --ingestCache still consults
        # AND populates the slab cache (docs/DESIGN.md §18): a warm full
        # hit skips the parse entirely (data/ingest.load_cached_dataset),
        # a cold parse publishes every built shard plus the file's stats
        # artifact for the next process.
        import numpy as _np

        from cocoa_tpu.data.sharding import resolve_layout_stats as _rls

        t_load = time_mod.perf_counter()

        def whole_handle(path):
            if ingest_cache is None or objective != "svm":
                return None
            try:
                return ingest_cache.for_file(path, cfg.num_features)
            except OSError:
                return None  # a vanished file fails the parse below
                # with its own clean error

        def whole_report(path, parsed, seconds, cache="off"):
            # one report per loaded file, like the stream branch, so the
            # stream-vs-whole telemetry is an apples-to-apples A/B;
            # parse seconds cover parse + shard/slab build, same span the
            # streamed pass-2 timer covers
            try:
                fsize = os.path.getsize(path)
            except OSError:
                fsize = 0
            return ingest_lib.IngestReport(
                mode="whole", path=path, file_bytes=fsize,
                processes=jax.process_count(), parse_seconds=seconds,
                bytes_read=fsize, rows=parsed.n,
                nnz=int(parsed.indptr[-1]), n=parsed.n,
                total_nnz=int(parsed.indptr[-1]),
                peak_rss_bytes=ingest_lib.peak_rss_bytes(), cache=cache)

        def warm_whole(handle, stats, path, hot_w, ed, t0):
            """(ds, report) served entirely from cache artifacts, or
            None — the caller cold-parses, which re-populates."""
            if handle is None or stats is None:
                return None
            lay = _rls(stats.n, cfg.num_features, stats.total_nnz,
                       cfg.layout, mesh)
            got = ingest_lib.load_cached_dataset(
                handle, stats, k, layout=lay, dtype=dtype, mesh=mesh,
                eval_dense=ed, hot_cols=hot_w)
            if got is None:
                return None
            ds_w, winfo = got
            record_cache(path, "hit", winfo)
            rep = ingest_lib.IngestReport(
                mode="whole", path=path, file_bytes=stats.file_bytes,
                processes=jax.process_count(),
                parse_seconds=time_mod.perf_counter() - t0,
                bytes_read=0, rows=0, nnz=0, n=stats.n,
                total_nnz=stats.total_nnz,
                peak_rss_bytes=ingest_lib.peak_rss_bytes(),
                cache="hit")
            return ds_w, winfo, rep

        def populate_whole(handle, parsed, path, snap, t0):
            """After a cold whole parse+build: store the file's stats
            artifact + (on a full miss) the cold cost, and emit the
            cache outcome (the shard slabs were published inside
            shard_dataset; ``snap`` is the :func:`cache_snap` taken
            before the build)."""
            handle.store_index(
                hist=_np.bincount(parsed.indices,
                                  minlength=cfg.num_features),
                n=parsed.n, total_nnz=int(parsed.indptr[-1]),
                max_row_nnz=int(parsed.max_nnz))
            hits = ingest_cache.shard_hits - snap[0]
            misses = ingest_cache.shard_misses - snap[1]
            if hits == 0:
                # only a FULL miss records the cold cost — a partial run
                # re-paid its missed shards only, and that sliver would
                # corrupt the seconds_saved estimate for good
                handle.store_cost(time_mod.perf_counter() - t0)
            status = "partial" if hits else "miss"
            record_cache(path, status, ingest_lib.StreamBuildInfo(
                rows=0, nnz=0, bytes_read=0, parse_seconds=0.0,
                residual_max_nnz=0, shards_cached=hits,
                shards_total=hits + misses,
                cache_bytes_mapped=ingest_cache.bytes_mapped - snap[2],
                cache_status=status))
            return status

        # the warm attempt resolves --layout/--hotCols/--evalDense=auto
        # from the CACHED stats — bit-identical to the parsed-data
        # resolution below (the stream-resolution parity pin) — so a
        # full hit never reads a byte of text
        train_handle = whole_handle(cfg.train_file)
        train_stats = (train_handle.load_index()
                       if train_handle is not None else None)
        warm = None
        if objective == "svm" and train_stats is not None:
            n = train_stats.n
            try:
                resolved_layout, hot_n, eval_dense = resolve_stats_knobs(
                    n, train_stats.total_nnz, train_stats.hist)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            warm = warm_whole(train_handle, train_stats, cfg.train_file,
                              hot_n, eval_dense, t_load)
            if warm is not None:
                ds, winfo, rep = warm
                ingest_reports.append(rep)
                if resolved_layout == "sparse":
                    from cocoa_tpu.data import hybrid as hybrid_mod
                    if ed_spec == "auto":
                        announce_eval(eval_dense, hot_n)
                    layout_split = hybrid_mod.stats_from_counts(
                        extras["hotCols"], train_stats.hist, hot_n,
                        (winfo.residual_max_nnz if hot_n
                         else int(train_stats.max_row_nnz)),
                        n, k, dtype)
                    announce_hot(layout_split, hot_n)

        if warm is None:
            try:
                data = load_libsvm(cfg.train_file, cfg.num_features)
            except (OSError, ValueError) as e:  # missing file, bad
                # numFeatures
                print(f"error: {e}", file=sys.stderr)
                return 2
            n = data.n

            # --hotCols=auto|off|<n>: the hot/cold column split (sparse
            # layout only, data/hybrid.py).  Resolved HERE — against the
            # measured column histogram, with the panel's HBM bytes
            # accounted explicitly — so the run_start manifest records
            # the split the run actually trains on.
            if objective == "svm":
                resolved_layout = resolve_layout(data, cfg.layout, mesh)
                if (extras["hotCols"] is not None
                        and resolved_layout != "sparse"):
                    print("error: --hotCols (the hot/cold column split) "
                          "only applies to the sparse layout",
                          file=sys.stderr)
                    return 2
                if resolved_layout == "sparse":
                    try:
                        hot_n, layout_split = resolve_hot_cols(
                            extras["hotCols"], data, k, dtype)
                    except ValueError as e:
                        print(f"error: {e}", file=sys.stderr)
                        return 2
                    if ed_spec == "auto":
                        # materialize the dense eval twin only when it
                        # fits the HBM budget; otherwise (with a hot
                        # panel) the certificate margins ride the panel
                        # matvec + residual stream (ops/rows.eval_margins)
                        from cocoa_tpu.data.sharding import eval_dense_fits

                        eval_dense = eval_dense_fits(n, cfg.num_features,
                                                     k, dtype)
                        announce_eval(eval_dense, hot_n)
                    announce_hot(layout_split, hot_n)

            try:
                if objective == "svm":
                    # --evalDense: dense eval twin for sparse layouts —
                    # the duality-gap certificate's full margins pass as
                    # one MXU matvec instead of an every-nonzero
                    # w-gather (31% of the rcv1 production round); costs
                    # K*n_shard*d*itemsize HBM
                    snap = cache_snap()
                    ds = shard_dataset(data, k=k, layout=cfg.layout,
                                       dtype=dtype, mesh=mesh,
                                       eval_dense=eval_dense,
                                       hot_cols=hot_n,
                                       cache=train_handle)
                    status = "off"
                    if train_handle is not None:
                        status = populate_whole(
                            train_handle, data, cfg.train_file, snap,
                            t_load)
                    ingest_reports.append(whole_report(
                        cfg.train_file, data,
                        time_mod.perf_counter() - t_load, cache=status))
                else:
                    ingest_reports.append(whole_report(
                        cfg.train_file, data,
                        time_mod.perf_counter() - t_load))
            except (OSError, ValueError) as e:  # e.g. --layout=sparse
                # + --fp>1
                print(f"error: {e}", file=sys.stderr)
                return 2

        if objective == "svm" and cfg.test_file:
            try:
                t_test = time_mod.perf_counter()
                test_handle = whole_handle(cfg.test_file)
                test_stats = (test_handle.load_index()
                              if test_handle is not None else None)
                test_warm = warm_whole(test_handle, test_stats,
                                       cfg.test_file, hot_n, eval_dense,
                                       t_test)
                if test_warm is not None:
                    test_ds, _, rep = test_warm
                    ingest_reports.append(rep)
                else:
                    test_data = load_libsvm(cfg.test_file,
                                            cfg.num_features)
                    snap = cache_snap()
                    test_ds = shard_dataset(test_data, k=k,
                                            layout=cfg.layout,
                                            dtype=dtype, mesh=mesh,
                                            eval_dense=eval_dense,
                                            hot_cols=hot_n,
                                            cache=test_handle)
                    status = "off"
                    if test_handle is not None:
                        status = populate_whole(
                            test_handle, test_data, cfg.test_file,
                            snap, t_test)
                    ingest_reports.append(whole_report(
                        cfg.test_file, test_data,
                        time_mod.perf_counter() - t_test, cache=status))
            except (OSError, ValueError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 2

    if layout_split is not None:
        cfg_manifest["layout_split"] = layout_split
        run_meta["config_hash"] = telemetry.events.config_hash(cfg_manifest)
    if bus.active():
        manifest = telemetry.events.run_manifest(cfg_manifest,
                                                 dataset=cfg.train_file)
        if layout_split is not None:
            manifest["layout_split"] = dict(layout_split)
        if ingest_reports:
            # the TRAIN file's ingest record rides the manifest next to
            # layout_split (stats like parse seconds/RSS are run facts,
            # not config — they stay out of the config hash)
            manifest["ingest"] = ingest_reports[0].as_fields()
        bus.emit("run_start", manifest=manifest)
        for rep in ingest_reports:
            bus.emit("ingest", **rep.as_fields())
        for ev_fields in cache_events:
            bus.emit("ingest_cache", **ev_fields)

    params = cfg.to_params(n, k)
    debug = cfg.to_debug()
    gap_target = float(extras["gapTarget"]) if extras["gapTarget"] else None
    if gap_target is not None and dtype == jnp.bfloat16:
        # the duality gap sits below bf16's ~2^-8 relative resolution, so
        # a gap-targeted bf16 run cannot certify (docs/DESIGN.md §6;
        # measured in tests/test_bf16.py) — reject up front with the
        # remedy instead of burning the round budget
        print("error: --gapTarget cannot be certified at --dtype=bfloat16 "
              "(the gap is below bf16 resolution); use --dtype=float32 or "
              "drop --gapTarget", file=sys.stderr)
        return 2
    cfg.device_loop = (
        extras["deviceLoop"] is not None
        and str(extras["deviceLoop"]).lower() != "false"
    )
    if extras["scanChunk"]:
        try:
            cfg.scan_chunk = int(extras["scanChunk"])
        except ValueError:
            print(f"error: --scanChunk must be an integer, got "
                  f"{extras['scanChunk']!r}", file=sys.stderr)
            return 2
    elif not cfg.device_loop and cfg.scan_chunk <= 0:
        # default to device-side blocks at the eval cadence: the math and
        # the observable trajectory are identical to per-round stepping
        # (pinned by tests), but a tunneled device pays ~10 ms of dispatch
        # latency PER ROUND on the host-stepped path.  Capped so one
        # chunk's (C, K, H) int32 index table stays modest even when
        # debugIter is huge (--scanChunk=1 restores per-round dispatch).
        cap = max(1, 32_000_000 // max(1, k * params.local_iters))
        cfg.scan_chunk = min(cfg.debug_iter if cfg.debug_iter > 0 else 50,
                             cap)
    if cfg.device_loop and cfg.debug_iter <= 0:
        print("error: --deviceLoop requires --debugIter > 0 (the eval "
              "cadence is the device loop's chunk axis)", file=sys.stderr)
        return 2
    # --deviceLoop + --chkptDir/--chkptIter is supported: the device-loop
    # driver saves at its super-block boundaries, every chkptIter rounds
    # rounded up to the debugIter chunk cadence (base.drive_device_full)
    resume = extras["resume"] is not None and str(extras["resume"]).lower() != "false"
    if resume and not cfg.chkpt_dir:
        print("error: --resume requires --chkptDir", file=sys.stderr)
        return 2
    block_auto = (extras["blockSize"] or "").lower() == "auto"
    block_size = 0
    if extras["blockSize"] and not block_auto:
        try:
            block_size = int(extras["blockSize"])
        except ValueError:
            print(f"error: --blockSize must be an integer or 'auto', got "
                  f"{extras['blockSize']!r}", file=sys.stderr)
            return 2
    if block_size < 0:
        print(f"error: --blockSize must be >= 0, got {block_size}",
              file=sys.stderr)
        return 2
    if (block_size or block_auto) and cfg.math != "fast":
        print("error: --blockSize requires --math=fast (the block kernel is "
              "a margins-decomposition variant)", file=sys.stderr)
        return 2
    if ds is not None and block_auto:
        # dense always blocks; sparse blocks only when the in-kernel CSR
        # Gram path fits (a densified sparse block LOSES to the sequential
        # sparse kernel, benchmarks/KERNELS.md)
        block_size = _resolve_auto_block(ds, mesh, k, dtype, quiet=quiet)

    bp = (extras["blockPipeline"] or "auto").lower()
    if bp not in ("auto", "on", "off"):
        print(f"error: --blockPipeline must be auto|on|off, got "
              f"{extras['blockPipeline']!r}", file=sys.stderr)
        return 2
    if bp != "auto" and not (block_size or block_auto):
        print("error: --blockPipeline controls the block-coordinate scan "
              "schedule and needs --blockSize", file=sys.stderr)
        return 2
    block_pipeline = None if bp == "auto" else (bp == "on")

    guard = (extras["divergenceGuard"] or "auto").lower()
    if guard not in ("auto", "on", "off"):
        print(f"error: --divergenceGuard must be auto|on|off, got "
              f"{extras['divergenceGuard']!r}", file=sys.stderr)
        return 2
    if guard == "off" and (
            cfg.sigma == "auto"
            or (sigma_schedule == "anneal" and anneal_engages)):
        # the guard's firing IS the schedule's only exit from a bad σ′
        # guess (trial restart or in-loop anneal backoff alike)
        print("error: --sigma=auto / --sigmaSchedule=anneal require the "
              "divergence guard; drop --divergenceGuard=off",
              file=sys.stderr)
        return 2

    if objective == "lasso":
        # --objective=lasso: ProxCoCoA+ on 0.5||Ax-b||^2 + lambda||x||_1
        # (+ l2/2 ||x||^2), labels as the regression target; A's columns
        # sharded over the workers (data/columns.py)
        if fp > 1:
            print("error: --objective=lasso already shards the feature "
                  "axis over workers; --fp does not apply", file=sys.stderr)
            return 2
        if cfg.test_file:
            print("error: --testFile does not apply to --objective=lasso "
                  "(no classification error to report)", file=sys.stderr)
            return 2
        try:
            l2 = float(extras["l2"]) if extras["l2"] else 0.0
        except ValueError:
            print(f"error: --l2 must be a float, got {extras['l2']!r}",
                  file=sys.stderr)
            return 2
        if l2 < 0.0:
            print(f"error: --l2 is the elastic-net weight, needs >= 0, "
                  f"got {l2}", file=sys.stderr)
            return 2
        from cocoa_tpu.data.columns import shard_columns
        from cocoa_tpu.solvers import run_prox_cocoa

        try:
            ds_c, b = shard_columns(data, k, dtype=dtype, mesh=mesh,
                                    layout=cfg.layout)
        except ValueError as e:  # e.g. sparse columns + fp mesh
            print(f"error: {e}", file=sys.stderr)
            return 2
        if block_auto:
            block_size = _resolve_auto_block(ds_c, mesh, k, dtype,
                                             quiet=quiet)
        d = data.num_features
        # same H = max(1, localIterFrac·n/K) law, over coordinates
        lasso_params = dataclasses.replace(
            cfg.to_params(d, k), loss="lasso", smoothing=l2,
        )
        resume_kw = {}
        if resume:
            from cocoa_tpu import checkpoint as ckpt_lib

            path = ckpt_lib.latest(cfg.chkpt_dir, "ProxCoCoA+")
            if path is not None:
                meta, r0, x0 = ckpt_lib.load(path)
                print(f"resuming ProxCoCoA+ from round {meta['round']} "
                      f"({path})")
                resume_kw = dict(r_init=r0, x_init=x0,
                                 start_round=meta["round"] + 1)
        x, r, traj = run_prox_cocoa(
            ds_c, b, lasso_params, cfg.to_debug(), mesh=mesh, rng=cfg.rng,
            sampling=cfg.sampling, quiet=quiet,
            gap_target=gap_target, scan_chunk=cfg.scan_chunk,
            math=cfg.math, device_loop=cfg.device_loop,
            block_size=block_size, block_pipeline=block_pipeline,
            divergence_guard=guard, **resume_kw,
        )
        from cocoa_tpu.solvers.prox_cocoa import _metrics_fn

        final = [float(v) for v in
                 _metrics_fn(mesh, cfg.lam, l2)(r, x, ds_c.shard_arrays(), b)]
        traj.meta.update(run_meta)
        traj.summary(final[0], gap=final[1], test_error=None)
        if extras["trajOut"]:
            traj.dump_jsonl(f"{extras['trajOut']}.ProxCoCoA+.jsonl")
        return 0

    def restore(algorithm):
        """(w_init, alpha_init, start_round[, sched_init]) from the latest
        checkpoint.  ``sched_init`` (present on --sigmaSchedule/--warmStart
        runs) restores the σ′-schedule stage and stall-watch counters so a
        mid-schedule resume is bit-identical to the uninterrupted run."""
        if not resume:
            return dict()
        import numpy as _np

        from cocoa_tpu import checkpoint as ckpt_lib

        path = ckpt_lib.latest(cfg.chkpt_dir, algorithm)
        if path is None:
            return dict()
        meta, arrays = ckpt_lib.load_full(path)
        print(f"resuming {algorithm} from round {meta['round']} ({path})")
        out = dict(w_init=arrays["w"], start_round=meta["round"] + 1)
        if arrays.get("alpha") is not None:
            out["alpha_init"] = arrays["alpha"]
        if meta.get("sched") is not None:
            out["sched_init"] = _np.asarray(meta["sched"], _np.float32)
        if arrays.get("hist") is not None:
            # the --accel secant window bank: restoring it (with the
            # sched accel slots) makes a mid-momentum resume bit-identical
            out["hist_init"] = arrays["hist"]
        return out

    def finish(traj, w, alpha=None):
        primal = objectives.primal_objective(ds, w, params.lam,
                                             params.loss, params.smoothing)
        gap = (
            primal - objectives.dual_objective(ds, w, alpha, params.lam,
                                               params.loss, params.smoothing)
            if alpha is not None
            else None
        )
        err = (
            objectives.classification_error(test_ds, w)
            if test_ds is not None
            else None
        )
        traj.meta.update(run_meta)
        traj.summary(primal, gap=gap, test_error=err)
        if extras["trajOut"]:
            path = f"{extras['trajOut']}.{traj.algorithm.replace(' ', '_')}.jsonl"
            traj.dump_jsonl(path)

    common = dict(mesh=mesh, test_ds=test_ds, rng=cfg.rng,
                  sampling=cfg.sampling, quiet=quiet)

    # resolve --overlapComm for this process: the checkpoint-write
    # overlap engages only where it is race-free — single process (the
    # multi-process save's alpha allgather is a collective that must not
    # run concurrently with a training dispatch)
    overlap_io = False
    if overlap_flag in ("on", "auto"):
        overlap_io = jax.process_count() == 1 and cfg.device_loop
        if overlap_flag == "on" and not overlap_io:
            # the flag must never pass silently inert (the same
            # loud-behavior principle as the --staleRounds rejection):
            # say exactly which precondition is missing
            if jax.process_count() != 1:
                print("overlapComm: checkpoint-write overlap disabled on "
                      "the multi-process path (the save's alpha allgather "
                      "is a collective); exchanges overlap via the gang "
                      "host-aggregation path instead", file=sys.stderr)
            else:
                print("note: --overlapComm's CLI consumer is the "
                      "device-resident driver's checkpoint-write overlap "
                      "— pass --deviceLoop (the host-stepped path has no "
                      "effect to enable)", file=sys.stderr)

    cocoa_kw = dict(gap_target=gap_target, scan_chunk=cfg.scan_chunk,
                    math=cfg.math, device_loop=cfg.device_loop,
                    block_size=block_size, block_pipeline=block_pipeline,
                    divergence_guard=guard, sigma_schedule=sigma_schedule,
                    warm_start=warm_start, accel=accel_flag,
                    theta=theta_flag, overlap_io=overlap_io)

    def run_all():
        w, alpha, traj = run_cocoa(ds, params, debug, plus=True,
                                   **cocoa_kw, **restore("CoCoA+"), **common)
        finish(traj, w, alpha)

        w, alpha, traj = run_cocoa(ds, params, debug, plus=False,
                                   **cocoa_kw, **restore("CoCoA"), **common)
        finish(traj, w, alpha)

        if not cfg.just_cocoa:  # hingeDriver.scala:93-110
            loop_kw = dict(scan_chunk=cfg.scan_chunk,
                           device_loop=cfg.device_loop)
            w, alpha, traj = run_minibatch_cd(
                ds, params, debug, math=cfg.math, block_size=block_size,
                block_pipeline=block_pipeline, divergence_guard=guard,
                **loop_kw, **restore("Mini-batch CD"), **common)
            finish(traj, w, alpha)

            w, traj = run_sgd(ds, params, debug, local=False, **loop_kw,
                              **restore("Mini-batch SGD"), **common)
            finish(traj, w)

            w, traj = run_sgd(ds, params, debug, local=True, **loop_kw,
                              **restore("Local SGD"), **common)
            finish(traj, w)

            w, traj = run_dist_gd(ds, params, debug, mesh=mesh,
                                  test_ds=test_ds, quiet=quiet, **loop_kw,
                                  **restore("Dist SGD"))
            finish(traj, w)

    if profile_window is not None:
        # --profile=DIR,START,STOP: trace only the round window, triggered
        # by the telemetry event stream — on the device-resident driver
        # the io_callback bridge is what makes a mid-while_loop trigger
        # possible at all (telemetry/profiling.py).  The windower is a bus
        # subscriber, which also activates the bus (and with it the
        # device event stream) for the duration of the run.
        from cocoa_tpu.telemetry.profiling import RoundWindowProfiler

        windower = RoundWindowProfiler(profile_dir, *profile_window)
        bus.subscribe(windower)
        try:
            run_all()
        finally:
            windower.close()
            bus.unsubscribe(windower)
            if not quiet:
                print(f"profiler trace of rounds "
                      f"[{profile_window[0]}, {profile_window[1]}) "
                      f"written to {profile_dir}")
    elif profile_dir:
        # --profile=DIR: capture a device trace of the whole run, viewable
        # in TensorBoard/Perfetto (the reference has no profiler at all —
        # SURVEY.md §5 requires one as a debug flag).  try/finally so the
        # trace — the artifact needed to debug a failing run — still flushes
        # when a solver raises.
        from jax import profiler

        profiler.start_trace(profile_dir)
        try:
            run_all()
        finally:
            profiler.stop_trace()
            if not quiet:
                print(f"profiler trace written to {profile_dir}")
    else:
        run_all()

    return 0


def _run_fleet_cli(cfg, extras, quiet, bus, cfg_manifest, fleet_lanes,
                   sigma_schedule, accel_flag, theta_flag):
    """The ``--fleet`` execution path: load + validate the manifest,
    stack the tenants, run the one compiled vmapped round
    (solvers/fleet.py), and report per-tenant certification + the
    models-per-second headline.  Reached from :func:`main` after the
    flag surface is validated; every remaining fleet-specific
    incompatibility is rejected here with a pointer."""
    import numpy as np

    from cocoa_tpu import telemetry
    from cocoa_tpu.data import build_fleet, load_fleet_manifest
    from cocoa_tpu.solvers import run_cocoa_fleet

    if extras["mesh"] and str(extras["mesh"]) != "1":
        print("error: --mesh does not combine with --fleet in v1: fleet "
              "lanes ride the tenant vmap on one chip; the multi-chip "
              "direction is the tenant mesh axis "
              "(parallel/mesh.make_fleet_mesh, docs/DESIGN.md §16)",
              file=sys.stderr)
        return 2
    if extras["fp"] and str(extras["fp"]) != "1":
        print("error: --fp does not combine with --fleet (feature "
              "sharding splits one model's columns; fleet lanes are "
              "whole independent models)", file=sys.stderr)
        return 2
    if cfg.sampling == "device":
        print("error: --sampling=device does not combine with --fleet "
              "(the fleet loop host-samples its stacked index tables "
              "once per run — solvers/fleet.py); use --sampling=auto",
              file=sys.stderr)
        return 2
    if theta_flag == "adaptive":
        print("error: --theta=adaptive does not combine with --fleet "
              "(the Θ ladder slices static index-table widths; fleet "
              "lanes share one table shape — docs/DESIGN.md §16)",
              file=sys.stderr)
        return 2
    if cfg.sigma == "auto" and sigma_schedule == "trial":
        print("error: --sigmaSchedule=trial does not combine with "
              "--fleet (the trial's restart is a solo-path control; "
              "fleets anneal in place — --sigmaSchedule=anneal)",
              file=sys.stderr)
        return 2

    gap_target = None
    if extras["gapTarget"]:
        try:
            gap_target = float(extras["gapTarget"])
        except ValueError:
            print(f"error: --gapTarget must be a float, got "
                  f"{extras['gapTarget']!r}", file=sys.stderr)
            return 2
    accel_on = accel_flag == "on"   # auto resolves OFF for fleets: the
    # plain certified path is the fleet default; opt in explicitly
    anneal_on = (cfg.sigma == "auto"
                 or (sigma_schedule == "anneal"
                     and isinstance(cfg.sigma, float)
                     and 0 < cfg.sigma < cfg.num_splits * cfg.gamma))
    if accel_on and anneal_on:
        print("error: --accel does not combine with --sigma=auto/"
              "--sigmaSchedule=anneal on --fleet (fleet accel rides the "
              "fixed safe σ′; drop one of the two)", file=sys.stderr)
        return 2
    drive_mode = ("accel" if accel_on
                  else "anneal" if anneal_on else "plain")

    try:
        specs = load_fleet_manifest(extras["fleet"])
        fleet = build_fleet(specs, k=cfg.num_splits,
                            dtype=jnp.dtype(cfg.dtype),
                            local_iter_frac=cfg.local_iter_frac,
                            default_gap_target=gap_target)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if fleet.loss not in ("hinge", "smooth_hinge"):
        print(f"error: fleet v1 runs the hinge family only (manifest "
              f"loss {fleet.loss!r}); the logistic dual rule divides by "
              f"λn in a way the traced-λ lane cannot mirror bit-exactly "
              f"(docs/DESIGN.md §16)", file=sys.stderr)
        return 2
    if cfg.loss != "hinge" and cfg.loss != fleet.loss:
        print(f"error: the fleet's loss comes from the manifest "
              f"({fleet.loss!r}); drop --loss={cfg.loss} or make them "
              f"agree", file=sys.stderr)
        return 2

    if bus.active():
        manifest = telemetry.events.run_manifest(cfg_manifest,
                                                 dataset=extras["fleet"])
        manifest["fleet"] = {"tenants": fleet.t, "k": fleet.k,
                             "n_shard": fleet.n_shard,
                             "d": fleet.num_features,
                             "h": fleet.local_iters,
                             "drive_mode": drive_mode,
                             "lane_exec": fleet_lanes}
        bus.emit("run_start", manifest=manifest)

    params = dataclasses.replace(
        cfg.to_params(0, fleet.k), local_iters=fleet.local_iters,
        loss=fleet.loss, smoothing=fleet.smoothing)
    debug = cfg.to_debug()
    try:
        result = run_cocoa_fleet(
            fleet, params, debug, plus=True, drive_mode=drive_mode,
            rng=cfg.rng, math=cfg.math, lane_exec=fleet_lanes,
            quiet=quiet)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    certified = int(result.certified.sum())
    if bus.active():
        bus.emit("run_end", algorithm=result.algorithm,
                 stopped=("target" if certified == fleet.t else None))
    if not quiet:
        # one host array fetch BEFORE the loop (the fleet-hygiene rule:
        # never a per-tenant device fetch inside a tenant loop)
        gaps = np.asarray(result.final_gap)
        rounds = np.asarray(result.cert_round)
        for ti, tenant in enumerate(result.tenants):
            status = (f"certified @ round {int(rounds[ti])}"
                      if result.certified[ti]
                      else "DIVERGED (stall watch)" if result.stalled[ti]
                      else "not certified")
            print(f"  {tenant}: lambda={fleet.lams[ti]:g} "
                  f"gap={gaps[ti]:.3e} {status}")
        print(f"fleet: {certified}/{fleet.t} tenants certified, "
              f"{result.rounds_run} rounds, {result.wall_s:.2f}s, "
              f"{result.models_per_second:.1f} models/s "
              f"(drive_mode={drive_mode}, lanes={fleet_lanes})")
    if extras["trajOut"]:
        import json as _json

        path = f"{extras['trajOut']}.fleet.jsonl"
        with open(path, "w") as f:
            f.write(_json.dumps({
                "config": "fleet", "type": "fleet",
                "tenants": fleet.t, "certified": certified,
                "rounds": int(result.rounds_run),
                "models_per_second": result.models_per_second,
                "stopped": ("target" if certified == fleet.t else None),
            }) + "\n")
            gaps = np.asarray(result.final_gap)
            rounds = np.asarray(result.cert_round)
            for ti, tenant in enumerate(result.tenants):
                f.write(_json.dumps({
                    "config": f"fleet/{tenant}", "type": "fleet-tenant",
                    "lam": float(fleet.lams[ti]),
                    "gap": float(gaps[ti]),
                    "rounds": int(rounds[ti]) or int(result.rounds_run),
                    "stopped": ("target" if result.certified[ti]
                                else None),
                }) + "\n")
    return 0


def _run_serve_fleet(cfg, extras, quiet, bus, port, buckets, sla_ms,
                     max_nnz, serve_dtype, n_replicas, route,
                     algorithm, n_tenants, trace_sample=0,
                     status_port=None):
    """The ``--serveReplicas>=2`` execution path (docs/DESIGN.md §21):
    spawn N ordinary single-process serve replicas against the same
    validated --chkptDir (each hot-swaps independently; slabs and
    checkpoints share the host page cache, so RSS stays ~one copy),
    put the router front door on the requested port, and relay the
    line protocol until ``shutdown`` or SIGTERM.  The front door holds
    no model and no JAX — replica death is a requeue, never a failed
    query, and the monitor respawns the dead.

    Tracing and the ops plane (docs/DESIGN.md §22) both live at the
    front door: the ROUTER samples ``trace=``-prefixed lines (it sees
    the whole lifecycle — queue, forward, requeues), and the
    ``--statusPort`` plane scrapes the front door's textfile plus every
    replica's ``.r<i>`` slot file with the router's own liveness map."""
    import signal

    from cocoa_tpu.serving.fleet import ServeFleet
    from cocoa_tpu.serving.router import Router

    rep_argv = [f"--chkptDir={cfg.chkpt_dir}",
                f"--numFeatures={cfg.num_features}",
                "--serveBatch=" + ",".join(str(b) for b in buckets),
                f"--serveSlaMs={sla_ms:g}",
                f"--serveMaxNnz={max_nnz}",
                f"--serveDtype={serve_dtype}", "--quiet"]
    # per-replica telemetry sinks ride the front door's --events and
    # --metrics paths with an .r<i> suffix — how the smoke counts
    # compiles per replica, and how the ops plane attributes merged
    # /metrics samples.  The suffix is the replica's SLOT: a respawn
    # reuses index i, so the new process inherits (atomically
    # overwrites) the dead one's files — two writers never interleave
    ev_path = extras["events"]
    metrics_path = extras["metrics"]
    extra_fn = None
    if ev_path or metrics_path:
        def extra_fn(i):
            argv = []
            if ev_path:
                argv.append(f"--events={ev_path}.r{i}")
            if metrics_path:
                argv.append(f"--metrics={metrics_path}.r{i}")
            return argv

    def echo(s):
        # replica pid/port notes are operational plumbing (the smoke
        # parses them for the SIGKILL drill) — printed even under
        # --quiet, like the announce line
        print(f"serve: {s}", flush=True)

    fleet = ServeFleet(rep_argv, n_replicas, extra_argv_fn=extra_fn,
                       echo=echo)
    try:
        members = fleet.start()
    except RuntimeError as e:
        fleet.stop()
        print(f"error: {e}", file=sys.stderr)
        return 1
    router = Router(members, sla_s=sla_ms / 1000.0, route=route,
                    port=port, algorithm=algorithm,
                    trace_sample=trace_sample)
    fleet.attach(router)
    router.emit_initial_state()
    host, bound = router.address[0], router.address[1]
    catalogue = ("" if n_tenants is None
                 else f", tenants={n_tenants}")
    print(f"serve: fleet listening on {host}:{bound} "
          f"(replicas={n_replicas}, route={route}, "
          f"buckets={','.join(str(b) for b in buckets)}, "
          f"slaMs={sla_ms:g}, maxNnz={max_nnz}, dtype={serve_dtype}"
          f"{catalogue})", flush=True)

    writer = getattr(bus, "metrics_writer", None)
    if writer is not None:
        writer.start_heartbeat(5.0)

    # --statusPort: the fleet ops plane — scrape the front door's own
    # textfile plus every replica's .r<i> slot file, with the router's
    # live map driving /healthz (a SIGKILLed replica shows live=false
    # until the monitor's respawn re-registers it)
    status = None
    if status_port is not None:
        from cocoa_tpu.telemetry.aggregate import StatusServer

        def _sources():
            out = {"router": metrics_path}
            for i in range(n_replicas):
                out[f"r{i}"] = f"{metrics_path}.r{i}"
            return out

        status = StatusServer(
            _sources, sla_s=sla_ms / 1000.0, port=status_port,
            algorithm=algorithm,
            liveness_fn=lambda: {r.name: r.live
                                 for r in router.replicas}).start()
        print(f"serve: status listening on "
              f"{status.address[0]}:{status.address[1]}", flush=True)

    def _stop(signum, frame):
        router.stop()

    prev = [signal.signal(signal.SIGTERM, _stop),
            signal.signal(signal.SIGINT, _stop)]
    try:
        router.serve_forever()
    finally:
        signal.signal(signal.SIGTERM, prev[0])
        signal.signal(signal.SIGINT, prev[1])
        if status is not None:
            status.stop()
        if writer is not None:
            writer.stop_heartbeat()
        fleet.stop()
        router.close()
    if bus.active():
        bus.emit("run_end", algorithm=algorithm, stopped="shutdown")
    if not quiet:
        print(f"serve: fleet shut down after {router.forwarded_total} "
              f"forwarded line(s), {router.shed_total} shed, "
              f"{router.requeue_total} requeued, "
              f"{router.failed_total} failed")
    return 0


def _run_serve_cli(cfg, extras, quiet, bus, cfg_manifest, serve_flag):
    """The ``--serve`` execution path (cocoa_tpu/serving/,
    docs/DESIGN.md §17): wait for the first VALIDATED checkpoint
    generation, build the compiled bucket scorer + double-buffered model
    slots, start the hot-swap watcher and the adaptive micro-batcher,
    and answer margin queries on a TCP line protocol until ``shutdown``
    (protocol line) or SIGTERM/SIGINT.  Reached from :func:`main` after
    the whitelist hardening; every remaining rejection here carries the
    numbers."""
    import signal

    import numpy as np

    from cocoa_tpu import serving, telemetry
    from cocoa_tpu.telemetry import tracing

    # --serve=PORT: 0 (or bare --serve) binds an ephemeral port and
    # announces it — what the smoke tests parse
    try:
        port = 0 if str(serve_flag).lower() == "true" else int(serve_flag)
    except ValueError:
        port = -1
    if port < 0 or port > 65535:
        print(f"error: --serve takes a TCP port (0 = ephemeral), got "
              f"{serve_flag!r}", file=sys.stderr)
        return 2
    buckets = serving.DEFAULT_BUCKETS
    if extras["serveBatch"]:
        try:
            buckets = tuple(sorted({int(b) for b in
                                    str(extras["serveBatch"]).split(",")}))
            if not buckets or buckets[0] < 1 or buckets[-1] > 8192:
                raise ValueError
        except ValueError:
            print(f"error: --serveBatch takes ascending bucket sizes in "
                  f"[1, 8192] (e.g. 64,256,1024), got "
                  f"{extras['serveBatch']!r}", file=sys.stderr)
            return 2
    sla_ms = 50.0
    if extras["serveSlaMs"]:
        try:
            sla_ms = float(extras["serveSlaMs"])
        except ValueError:
            sla_ms = -1.0
        if sla_ms <= 0:
            print(f"error: --serveSlaMs takes a positive latency budget "
                  f"in ms, got {extras['serveSlaMs']!r}", file=sys.stderr)
            return 2
    # --serveDtype: the serving precision (docs/DESIGN.md §20) — the
    # model is quantized ONCE per swap with a margin-error certificate;
    # queries and the compiled reduction stay f32
    serve_dtype = "f32"
    if extras["serveDtype"]:
        try:
            serve_dtype = serving.resolve_serve_dtype(
                extras["serveDtype"])
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    # --serveReplicas/--serveRoute (validated in main(), parsed again
    # here): >= 2 switches to the fleet branch — a router front door
    # over N spawned single-process replicas (docs/DESIGN.md §21)
    n_replicas = (int(extras["serveReplicas"])
                  if extras["serveReplicas"] else 1)
    route = extras["serveRoute"] or "rr"
    # --traceSample=N: 1 in N trace=-prefixed lines gets a sampled
    # query trace (docs/DESIGN.md §22); 0 disarms — the prefix is
    # peeled and answers stay byte-identical.  Bare --traceSample is
    # the documented default of 64.
    trace_sample = 0
    if extras["traceSample"]:
        raw = str(extras["traceSample"])
        try:
            trace_sample = 64 if raw.lower() == "true" else int(raw)
        except ValueError:
            trace_sample = -1
        if trace_sample < 0:
            print(f"error: --traceSample takes a sampling divisor "
                  f">= 0 (1 in N traced; 0 = off; bare flag = 64), "
                  f"got {extras['traceSample']!r}", file=sys.stderr)
            return 2
    # --statusPort=PORT (0/bare = ephemeral): the live ops plane
    # (telemetry/aggregate.py, docs/DESIGN.md §22) — /metrics /healthz
    # /slo over the metrics textfiles the serve processes write
    status_port = None
    if extras["statusPort"] is not None:
        raw = str(extras["statusPort"])
        try:
            status_port = 0 if raw.lower() == "true" else int(raw)
        except ValueError:
            status_port = -1
        if status_port < 0 or status_port > 65535:
            print(f"error: --statusPort takes a TCP port (0 = "
                  f"ephemeral), got {extras['statusPort']!r}",
                  file=sys.stderr)
            return 2
        if not extras["metrics"]:
            print("error: --statusPort serves the ops plane by "
                  "scraping the metrics textfile(s) and needs "
                  "--metrics", file=sys.stderr)
            return 2

    d = cfg.num_features
    dtype = jnp.dtype(cfg.dtype)
    algorithm = "CoCoA+"   # the production trainer's checkpoint key

    # optional hybrid query path: resolve the TRAINED hot/cold column
    # split from the training data's histogram, exactly like the trainer
    # does — queries then ride the same panel+residual kernels.  The
    # training data is parsed ONLY when --hotCols asks for the split (a
    # --trainFile alone would pay a full LIBSVM parse for nothing).
    hot_ids = None
    max_nnz = min(serving.DEFAULT_MAX_NNZ, d)
    if cfg.train_file and extras["hotCols"] is not None:
        from cocoa_tpu.data import hybrid as hybrid_lib

        try:
            data = load_libsvm(cfg.train_file, d)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        # queries are not training rows — the data's max row nnz only
        # ever RAISES the default budget, never tightens it
        max_nnz = min(d, max(max_nnz, int(data.max_nnz)))
        counts = hybrid_lib.column_counts(data)
        try:
            hot_n = hybrid_lib.resolve_hot_width(
                extras["hotCols"], counts, data.n, 1, dtype)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if hot_n:
            hot_ids = hybrid_lib.hottest_columns(counts, hot_n)
            if not quiet:
                print(f"serve: hot panel over {hot_n} columns — "
                      f"queries ride panel + residual")
    if extras["serveMaxNnz"]:
        try:
            max_nnz = int(extras["serveMaxNnz"])
        except ValueError:
            max_nnz = 0
        if max_nnz < 1:
            print(f"error: --serveMaxNnz takes a positive per-query "
                  f"nonzero budget, got {extras['serveMaxNnz']!r}",
                  file=sys.stderr)
            return 2
        max_nnz = min(max_nnz, d)

    path = serving.wait_for_model(cfg.chkpt_dir, algorithm,
                                  timeout_s=300.0, quiet=quiet)
    if path is None:
        print(f"error: no validated {algorithm} checkpoint appeared in "
              f"{cfg.chkpt_dir} within 300s — is the background trainer "
              f"running with --chkptDir pointed here?", file=sys.stderr)
        return 1
    w, info = serving.load_model(path)
    w = np.asarray(w)
    # the trained width may exceed --numFeatures by lane padding (the
    # loader pads d up; the pad columns carry no data, so their w slots
    # are inert) — queries only ever gather ids < numFeatures.  A model
    # NARROWER than the query surface is a real mismatch.  A 2-D (T, d)
    # checkpoint is a served CATALOGUE of T tenant models (the fleet
    # trainer's stacked w, docs/DESIGN.md §21): queries then carry a
    # tenant=<id>; prefix and the width rule applies to each row.
    n_tenants = int(w.shape[0]) if w.ndim == 2 else None
    if w.ndim not in (1, 2) or w.shape[-1] < d \
            or (w.ndim == 2 and w.shape[0] < 1):
        print(f"error: the serving checkpoint {path} carries w of shape "
              f"{tuple(w.shape)} but --numFeatures={d} — the query "
              f"width must fit inside the trained width, as a (d,) "
              f"model or a (T, d) tenant catalogue (fix the flag "
              f"or point --chkptDir at the right model)",
              file=sys.stderr)
        return 2
    if n_tenants is not None and serve_dtype != "f32":
        print(f"error: --serveDtype={serve_dtype} does not combine "
              f"with a (T, d) tenant catalogue (this checkpoint: "
              f"{tuple(w.shape)}): per-tenant quantization "
              f"certificates are not in the fleet v1 surface — serve "
              f"the catalogue at f32 (docs/DESIGN.md §21)",
              file=sys.stderr)
        return 2
    if n_tenants is not None and hot_ids is not None:
        print(f"error: --hotCols does not combine with a (T, d) tenant "
              f"catalogue (this checkpoint: {tuple(w.shape)}): "
              f"per-tenant hot panels are not in the fleet v1 surface "
              f"(docs/DESIGN.md §21)", file=sys.stderr)
        return 2

    if bus.active():
        manifest = telemetry.events.run_manifest(cfg_manifest,
                                                 dataset=cfg.chkpt_dir)
        manifest["serve"] = {
            "algorithm": algorithm, "buckets": list(buckets),
            "sla_ms": sla_ms, "max_nnz": max_nnz, "num_features": d,
            "hot_cols": 0 if hot_ids is None else int(len(hot_ids)),
            "serve_dtype": serve_dtype, "replicas": n_replicas,
            "route": route,
            "tenants": 0 if n_tenants is None else n_tenants,
        }
        bus.emit("run_start", manifest=manifest)

    if n_replicas >= 2:
        return _run_serve_fleet(cfg, extras, quiet, bus, port, buckets,
                                sla_ms, max_nnz, serve_dtype,
                                n_replicas, route, algorithm,
                                n_tenants, trace_sample, status_port)

    # the calibration ring the per-swap certificate is computed over:
    # warmup-seeded now, refilled by real traffic as it arrives
    calib = (serving.CalibrationBuffer(d, max_nnz=max_nnz,
                                       seed=cfg.seed)
             if serve_dtype != "f32" else None)
    slots = serving.ModelSlots(w, info, dtype=serve_dtype,
                               calibration=calib, algorithm=algorithm)
    scorer = serving.BatchScorer(d, dtype=serve_dtype, buckets=buckets,
                                 max_nnz=max_nnz, hot_ids=hot_ids,
                                 model_width=int(w.shape[-1]),
                                 n_tenants=n_tenants)
    serving.watcher.emit_model_swap(algorithm, info)   # the initial load
    with tracing.span("serve_warmup", buckets=len(buckets)):
        w_dev, scale, _ = slots.current()
        n_exec = scorer.warmup(w_dev, scale)
    if not quiet:
        print(f"serve: model {algorithm} r{info.round} "
              f"(gap={info.gap if info.gap is not None else 'n/a'}) — "
              f"{n_exec} bucket executables compiled, swaps are "
              f"compile-free from here")
        if serve_dtype != "f32":
            print(f"serve: quantized to {slots.served_dtype} at load "
                  f"(serveDtype={serve_dtype}, margin error bound "
                  f"{slots.last_bound:.3g} over the warmup calibration "
                  f"batch)" if slots.served_dtype != "f32" else
                  f"serve: certificate fallback at load — the "
                  f"{serve_dtype} margin error bound "
                  f"{slots.last_bound:.3g} could flip a calibrated "
                  f"sign; serving f32 until a generation certifies",
                  flush=True)

    batcher = serving.MicroBatcher(scorer, slots, sla_s=sla_ms / 1000.0,
                                   algorithm=algorithm,
                                   calibration=calib)

    def note_swap(inf):
        if not quiet:
            print(f"serve: hot-swapped to r{inf.round} "
                  f"(gap={inf.gap if inf.gap is not None else 'n/a'}, "
                  f"swap #{inf.seq})", flush=True)

    watcher = serving.SwapWatcher(slots, cfg.chkpt_dir, algorithm,
                                  poll_s=0.25, on_swap=note_swap).start()
    server = serving.MarginServer(batcher, d, max_nnz, port=port,
                                  n_tenants=n_tenants,
                                  trace_sample=trace_sample,
                                  algorithm=algorithm)
    host, bound = server.address[0], server.address[1]
    # the announce line is operational plumbing (the smoke parses it),
    # not chatter — it prints even under --quiet
    catalogue = ("" if n_tenants is None
                 else f", tenants={n_tenants}")
    print(f"serve: listening on {host}:{bound} "
          f"(buckets={','.join(str(b) for b in buckets)}, "
          f"slaMs={sla_ms:g}, maxNnz={max_nnz}, dtype={serve_dtype}"
          f"{catalogue})", flush=True)

    # gap-age heartbeat: the freshness gauge renders `now - birth` at
    # WRITE time, and writes are otherwise event-driven — a dead trainer
    # plus an idle server (the exact alert scenario) would freeze the
    # textfile.  A periodic unconditional rewrite keeps it climbing.
    writer = getattr(bus, "metrics_writer", None)
    if writer is not None:
        writer.start_heartbeat(5.0)

    # --statusPort: the solo ops plane — one source (this process's
    # own textfile), no router liveness to merge
    status = None
    if status_port is not None:
        from cocoa_tpu.telemetry.aggregate import StatusServer

        metrics_path = extras["metrics"]
        status = StatusServer(lambda: {"server": metrics_path},
                              sla_s=sla_ms / 1000.0, port=status_port,
                              algorithm=algorithm).start()
        print(f"serve: status listening on "
              f"{status.address[0]}:{status.address[1]}", flush=True)

    def _stop(signum, frame):
        server.stop()

    prev = [signal.signal(signal.SIGTERM, _stop),
            signal.signal(signal.SIGINT, _stop)]
    try:
        server.serve_forever()
    finally:
        signal.signal(signal.SIGTERM, prev[0])
        signal.signal(signal.SIGINT, prev[1])
        if status is not None:
            status.stop()
        if writer is not None:
            writer.stop_heartbeat()
        watcher.stop()
        batcher.stop()
        server.close()
    if bus.active():
        bus.emit("run_end", algorithm=algorithm, stopped="shutdown")
    if not quiet:
        print(f"serve: shut down after {batcher.requests_total} "
              f"request(s) in {batcher.batches_total} batch(es), "
              f"{watcher.swaps_total} hot-swap(s), final gap age "
              f"{slots.gap_age_s():.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
