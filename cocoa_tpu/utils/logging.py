"""Run trajectory logging and reference-style console output.

The reference's observability is println-only: per-``debugIter`` lines
(CoCoA.scala:51-56) and end-of-run summaries (OptUtils.scala:102-126).  We
keep that exact console format (so trajectories are eyeball-comparable) and
add what the baseline work actually needs (SURVEY.md §5-6): a structured
per-round record (round, wall-clock, comm-rounds, primal, gap, test error)
that can be dumped as JSONL — the benchmark artifact.

Since the telemetry subsystem landed, :class:`Trajectory` is a thin
CONSUMER of the event bus (cocoa_tpu/telemetry/events.py): every record it
collects is mirrored as a typed ``round_eval`` / ``divergence`` /
``run_end`` event (a no-op while the bus is unconfigured), and the console
prints are the same bus data rendered in the reference format.  The
``--quiet`` policy silences the console ONLY — a quiet run still leaves
the machine-readable event trace, which is the point.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional

from cocoa_tpu.telemetry import events as _events


@dataclasses.dataclass
class RoundRecord:
    round: int
    wall_time: Optional[float]  # seconds since run start; None when per-round
                                # timing is unobservable (device-resident loop
                                # fetches the whole trajectory in one sync)
    primal: Optional[float] = None
    gap: Optional[float] = None
    test_error: Optional[float] = None
    sigma: Optional[float] = None  # σ′ in effect AFTER this eval's schedule
                                   # update (--sigmaSchedule=anneal runs only;
                                   # a change between consecutive records IS
                                   # the in-loop backoff event)


class Trajectory:
    """Collects per-round records; one comm-round == one outer round (the
    baseline's #comm-rounds metric counts these)."""

    def __init__(self, algorithm: str, quiet: bool = False):
        self.algorithm = algorithm
        self.records: list[RoundRecord] = []
        self.quiet = quiet
        # why the run ended: None = ran its full round budget;
        # "target" = duality gap reached the gap_target early stop;
        # "diverged" = the gap stopped improving for STALL_EVALS straight
        # evals (the σ′-override guardrail — solvers/base.py)
        self.stopped: Optional[str] = None
        # extra manifest fields for dump_jsonl (dataset path, config hash,
        # seed, ...) — the CLI fills this in; library callers may too
        self.meta: dict = {}
        self._t0 = time.perf_counter()

    def _console(self, msg: str):
        """The one quiet/console policy every trajectory print routes
        through (log_round's reference-format lines, mark_diverged's
        bail-out notice, the end-of-run summary)."""
        if not self.quiet:
            print(msg)

    def mark_diverged(self, t: int, n_evals: int):
        """Record (and report) a divergence/stall bail-out at round ``t``.
        The ``divergence`` event is emitted regardless of ``quiet`` — a
        silenced console must still leave a machine-readable trace of the
        bail-out."""
        self.stopped = "diverged"
        _events.get_bus().emit("divergence", algorithm=self.algorithm,
                               t=int(t), n_evals=int(n_evals))
        self._console(f"{self.algorithm}: DIVERGED — best duality gap made no "
                      f"material progress over {n_evals} consecutive "
                      f"evaluations; stopped at round {t} "
                      f"(σ′ set below the safe K·γ bound? see --sigma)")

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    _STAMP = object()  # sentinel: stamp elapsed() unless overridden

    def log_round(self, t, primal=None, gap=None, test_error=None,
                  wall_time=_STAMP, sigma=None, emit=True, sigma_stage=None,
                  stall=None):
        """``wall_time=None`` marks the round's timing as unobservable (the
        device-resident driver syncs once for the whole run).

        ``emit=False`` suppresses the ``round_eval`` bus event — used by
        the device-resident driver, whose events were already emitted
        in-flight by the io_callback bridge (or replayed from the fetch)
        before this record is built.  ``sigma_stage``/``stall`` ride the
        event only (the σ′ ladder index and the stall-watch counter after
        this eval's update — the host drivers' twin of the device row)."""
        self.records.append(
            RoundRecord(
                round=t,
                wall_time=self.elapsed() if wall_time is self._STAMP else wall_time,
                primal=primal,
                gap=gap,
                test_error=test_error,
                sigma=sigma,
            )
        )
        if emit:
            _events.get_bus().emit(
                "round_eval", algorithm=self.algorithm, t=int(t),
                primal=primal, gap=gap, test_error=test_error, sigma=sigma,
                sigma_stage=sigma_stage, stall=stall,
            )
        if not self.quiet:
            # reference console format (CoCoA.scala:52-55)
            print(f"Iteration: {t}")
            if primal is not None:
                print(f"primal objective: {primal}")
            if gap is not None:
                print(f"primal-dual gap: {gap}")
            if test_error is not None:
                print(f"test error: {test_error}")

    def summary(self, primal, gap=None, test_error=None):
        """End-of-run block (OptUtils.scala:102-126 format) + the
        ``run_end`` event (emitted even under ``quiet``)."""
        _events.get_bus().emit(
            "run_end", algorithm=self.algorithm, primal=primal, gap=gap,
            test_error=test_error, stopped=self.stopped,
            rounds=self.records[-1].round if self.records else 0,
            elapsed_s=self.elapsed(),
        )
        if self.quiet:
            return
        out = f"{self.algorithm} has finished running. Summary Stats: "
        out += f"\n Total Objective Value: {primal}"
        if gap is not None:
            out += f"\n Duality Gap: {gap}"
        if test_error is not None:
            out += f"\n Test Error: {test_error}"
        print(out + "\n")

    def manifest(self) -> dict:
        """The dump header: algorithm + run provenance (jax/device info,
        plus whatever the caller put in ``self.meta`` — dataset, config
        hash, seed).  ``config_hash`` defaults to a hash of the meta
        itself so the header always carries a run identity."""
        man = {"algorithm": self.algorithm,
               "records": len(self.records),
               **_events.environment_manifest(),
               **self.meta}
        man.setdefault("config_hash", _events.config_hash(
            {"algorithm": self.algorithm, **self.meta}))
        return man

    def dump_jsonl(self, path: str):
        """One manifest header line, then one line per record; the FINAL
        record carries the ``stopped`` reason (null = full round budget) —
        without it a dumped trajectory could not distinguish 'certified
        the target' from 'budget exhausted' from 'bailed out diverged'."""
        with open(path, "w") as f:
            f.write(json.dumps({"manifest": _events._clean(self.manifest())})
                    + "\n")
            for j, r in enumerate(self.records):
                d = {"algorithm": self.algorithm, **dataclasses.asdict(r)}
                if j == len(self.records) - 1:
                    d["stopped"] = self.stopped
                f.write(json.dumps(_events._clean(d)) + "\n")
