"""Run trajectory logging and reference-style console output.

The reference's observability is println-only: per-``debugIter`` lines
(CoCoA.scala:51-56) and end-of-run summaries (OptUtils.scala:102-126).  We
keep that exact console format (so trajectories are eyeball-comparable) and
add what the baseline work actually needs (SURVEY.md §5-6): a structured
per-round record (round, wall-clock, comm-rounds, primal, gap, test error)
that can be dumped as JSONL — the benchmark artifact.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional


@dataclasses.dataclass
class RoundRecord:
    round: int
    wall_time: Optional[float]  # seconds since run start; None when per-round
                                # timing is unobservable (device-resident loop
                                # fetches the whole trajectory in one sync)
    primal: Optional[float] = None
    gap: Optional[float] = None
    test_error: Optional[float] = None
    sigma: Optional[float] = None  # σ′ in effect AFTER this eval's schedule
                                   # update (--sigmaSchedule=anneal runs only;
                                   # a change between consecutive records IS
                                   # the in-loop backoff event)


class Trajectory:
    """Collects per-round records; one comm-round == one outer round (the
    baseline's #comm-rounds metric counts these)."""

    def __init__(self, algorithm: str, quiet: bool = False):
        self.algorithm = algorithm
        self.records: list[RoundRecord] = []
        self.quiet = quiet
        # why the run ended: None = ran its full round budget;
        # "target" = duality gap reached the gap_target early stop;
        # "diverged" = the gap stopped improving for STALL_EVALS straight
        # evals (the σ′-override guardrail — solvers/base.py)
        self.stopped: Optional[str] = None
        self._t0 = time.perf_counter()

    def mark_diverged(self, t: int, n_evals: int):
        """Record (and report) a divergence/stall bail-out at round ``t``."""
        self.stopped = "diverged"
        if not self.quiet:
            print(f"{self.algorithm}: DIVERGED — best duality gap made no "
                  f"material progress over {n_evals} consecutive "
                  f"evaluations; stopped at round {t} "
                  f"(σ′ set below the safe K·γ bound? see --sigma)")

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    _STAMP = object()  # sentinel: stamp elapsed() unless overridden

    def log_round(self, t, primal=None, gap=None, test_error=None,
                  wall_time=_STAMP, sigma=None):
        """``wall_time=None`` marks the round's timing as unobservable (the
        device-resident driver syncs once for the whole run)."""
        self.records.append(
            RoundRecord(
                round=t,
                wall_time=self.elapsed() if wall_time is self._STAMP else wall_time,
                primal=primal,
                gap=gap,
                test_error=test_error,
                sigma=sigma,
            )
        )
        if not self.quiet:
            # reference console format (CoCoA.scala:52-55)
            print(f"Iteration: {t}")
            if primal is not None:
                print(f"primal objective: {primal}")
            if gap is not None:
                print(f"primal-dual gap: {gap}")
            if test_error is not None:
                print(f"test error: {test_error}")

    def summary(self, primal, gap=None, test_error=None):
        """End-of-run block (OptUtils.scala:102-126 format)."""
        if self.quiet:
            return
        out = f"{self.algorithm} has finished running. Summary Stats: "
        out += f"\n Total Objective Value: {primal}"
        if gap is not None:
            out += f"\n Duality Gap: {gap}"
        if test_error is not None:
            out += f"\n Test Error: {test_error}"
        print(out + "\n")

    def dump_jsonl(self, path: str):
        with open(path, "w") as f:
            for r in self.records:
                f.write(json.dumps({"algorithm": self.algorithm, **dataclasses.asdict(r)}) + "\n")
