"""Random sampling, with a reference-faithful mode.

The reference draws local example indices with ``new scala.util.Random(seed)``
(CoCoA.scala:144,151), where the per-round seed is ``debug.seed + t``
(CoCoA.scala:45) and — crucially — **every shard uses the same seed in the same
round**, so index draws are correlated across workers.  ``scala.util.Random``
delegates to ``java.util.Random``, whose 48-bit LCG is fixed by spec, so we can
reproduce the exact index sequences here without a JVM.

Two modes (selected by ``RunConfig.rng``):

- ``reference``: host-side ``JavaRandom`` precomputes the (T, H) index table,
  identical across shards — bit-faithful to the Scala behavior.  Index draws
  are data-independent (uniform), so precomputing them does not change the
  algorithm; it just moves RNG off the device hot path.
- ``jax``: ``jax.random`` keyed by (seed, round) and folded per shard —
  decorrelated across workers, the statistically preferable mode.
"""

from __future__ import annotations

import numpy as np

_MULT = 0x5DEECE66D
_ADD = 0xB
_MASK = (1 << 48) - 1


class JavaRandom:
    """Bit-exact java.util.Random (the engine behind scala.util.Random).

    Implements the linear congruential generator specified in the Java SE
    docs: seed' = (seed * 0x5DEECE66D + 0xB) mod 2^48.
    """

    def __init__(self, seed: int):
        self._seed = (seed ^ _MULT) & _MASK

    def _next(self, bits: int) -> int:
        self._seed = (self._seed * _MULT + _ADD) & _MASK
        # top `bits` bits, as a signed 32-bit int when bits == 32
        val = self._seed >> (48 - bits)
        if bits == 32 and val >= (1 << 31):
            val -= 1 << 32
        return val

    def next_int(self, bound: int | None = None) -> int:
        if bound is None:
            return self._next(32)
        if bound <= 0:
            raise ValueError("bound must be positive")
        if (bound & -bound) == bound:  # power of two
            return (bound * self._next(31)) >> 31
        while True:
            bits = self._next(31)
            val = bits % bound
            if bits - val + (bound - 1) < (1 << 31):  # no int32 overflow
                return val

    def next_double(self) -> float:
        return ((self._next(26) << 27) + self._next(27)) * (2.0 ** -53)


# ---- vectorized LCG (numpy uint64, 48-bit multiply done in two 24-bit
# halves so nothing overflows 64 bits) ----

_U_MULT = np.uint64(_MULT)
_U_ADD = np.uint64(_ADD)
_U_MASK = np.uint64(_MASK)
_LO24 = np.uint64((1 << 24) - 1)
_S24 = np.uint64(24)
_S17 = np.uint64(17)  # 48 - 31: top 31 bits for next(31)


def _scramble(seeds: np.ndarray) -> np.ndarray:
    return (seeds.astype(np.uint64) ^ _U_MULT) & _U_MASK


def _advance(states: np.ndarray) -> np.ndarray:
    hi = states >> _S24
    lo = states & _LO24
    prod = (((hi * _U_MULT) & _LO24) << _S24) + lo * _U_MULT
    return (prod + _U_ADD) & _U_MASK


def _next_int_vec(states: np.ndarray, bounds: np.ndarray):
    """One java.util.Random.nextInt(bound) per lane; returns (values, states).

    Handles the power-of-two fast path and the modulo-rejection loop per lane
    (lanes that reject advance their own state and redraw; accepted lanes
    don't), exactly as the scalar spec does.
    """
    bounds = bounds.astype(np.int64)
    is_pow2 = (bounds & -bounds) == bounds
    states = _advance(states)
    bits = (states >> _S17).astype(np.int64)  # next(31)
    val_pow2 = (bounds * bits) >> np.int64(31)
    val_mod = bits % bounds
    reject = (~is_pow2) & (bits - val_mod + (bounds - 1) >= (1 << 31))
    while np.any(reject):
        states = np.where(reject, _advance(states), states)
        new_bits = (states >> _S17).astype(np.int64)
        bits = np.where(reject, new_bits, bits)
        val_mod = np.where(reject, bits % bounds, val_mod)
        reject = (~is_pow2) & (bits - val_mod + (bounds - 1) >= (1 << 31))
    return np.where(is_pow2, val_pow2, val_mod).astype(np.int32), states


def sample_indices(seed: int, rounds: range, h: int, n_local: int) -> np.ndarray:
    """Index table for the reference RNG mode.

    For round t the reference seeds ``Random(seed + t)`` and draws H indices
    uniform in [0, n_local) (CoCoA.scala:148-151).  Returns int32 array of
    shape (len(rounds), H).  All shards share this table (the reference's
    correlated-across-workers behavior); callers wanting per-shard tables pass
    a shard-adjusted seed.  Vectorized over rounds (rounds reseed
    independently, so their LCG streams are independent lanes).
    """
    return sample_indices_per_shard(seed, rounds, h, [n_local])[0]


def sample_indices_per_shard(
    seed: int, rounds: range, h: int, n_locals: "list[int] | np.ndarray"
) -> np.ndarray:
    """Reference-mode index table for K shards of (possibly) unequal size.

    Shard k replays ``Random(seed + t)`` against its own ``n_local`` — exactly
    what each Spark task does with its partition (CoCoA.scala:144,151).  Shape
    (K, len(rounds), H).  Equal-size shards see identical draws (the
    reference's correlated-across-workers behavior).
    """
    n_locals = np.asarray(n_locals, dtype=np.int64)
    if np.any(n_locals <= 0):
        raise ValueError(f"all shards must be non-empty, got sizes {n_locals}")
    t0 = np.asarray([seed + t for t in rounds], dtype=np.int64)
    k = n_locals.shape[0]
    states = np.broadcast_to(_scramble(t0)[None, :], (k, len(t0))).copy()
    bounds = np.broadcast_to(n_locals[:, None], states.shape)
    out = np.empty((k, len(t0), h), dtype=np.int32)
    for j in range(h):
        out[:, :, j], states = _next_int_vec(states, bounds)
    return out
