"""Persistent XLA compilation cache.

First compiles through the tunneled device cost 20-60 s per executable
and a full benchmark regeneration pays dozens of them — compile time, not
compute, dominated the suite's wall clock and helped round 4's bench run
past its hard deadline.  jax's persistent compilation cache removes that
cost across PROCESSES (measured here: 1.19 s first-process compile,
0.01 s second-process) — the cache key covers the HLO, compile flags, and
backend, so correctness is jax's contract, not ours.

Enabled by default by bench.py, benchmarks/{run,kernels,trace}.py and the
CLI; set ``COCOA_NO_COMPILE_CACHE=1`` to opt out (e.g. when measuring
compile time itself).
"""

from __future__ import annotations

import os
import tempfile


def enable(cache_dir: str | None = None) -> str | None:
    """Enable the persistent compilation cache (idempotent).  Returns the
    cache directory, or None when disabled via COCOA_NO_COMPILE_CACHE."""
    if os.environ.get("COCOA_NO_COMPILE_CACHE"):
        return None
    import jax

    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.join(tempfile.gettempdir(), "cocoa_jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything: the suite's executables are exactly the small-once
    # big-often mix the default thresholds would skip
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return cache_dir
