from cocoa_tpu.utils.prng import JavaRandom, sample_indices  # noqa: F401
