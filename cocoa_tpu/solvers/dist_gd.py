"""Distributed (sub)gradient descent (reference: DistGD.scala).

Per round: every worker takes one deterministic full pass over its shard
(the one inner solver with no sequential dependency — a pure MXU matvec
pair, see ops/subgradient.py), adds its −λ·w regularizer term, then the
driver applies the gradient-direction-normalized step
w += Δw·(η/‖Δw‖) with η = 1/(β·t) (DistGD.scala:35,40-41).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import subgradient_pass
from cocoa_tpu.solvers import base


def make_round_step(mesh, params: Params, k: int):
    lam = params.lam
    beta = params.beta

    def per_shard(w, shard_k):
        return (subgradient_pass(w, shard_k, lam,
                                 loss=params.loss,
                                 smoothing=params.smoothing),)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(w, t, shard_arrays):
        eta = 1.0 / (beta * t)  # DistGD.scala:35
        (dw_sum,) = base.fanout(per_shard, mesh, w, shard_arrays)
        norm = jnp.linalg.norm(dw_sum)  # DistGD.scala:40
        return w + dw_sum * (eta / norm)  # DistGD.scala:41

    return round_step


def run_dist_gd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    w_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
):
    """Train; returns (w, Trajectory)."""
    base.check_shards(ds)
    k = ds.k
    if not quiet:
        print(f"\nRunning DistGD on {params.n} data examples, "
              f"distributed over {k} workers")

    dtype = ds.labels.dtype
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding

        w = jax.device_put(w, primal_sharding(mesh))

    step = make_round_step(mesh, params, k)
    shard_arrays = ds.shard_arrays()

    def round_fn(t, state):
        (w,) = state
        return (step(w, jnp.asarray(float(t), dtype=dtype), shard_arrays),)

    def eval_fn(state):
        (w,) = state
        return objectives.evaluate(ds, w, None, params.lam, test_ds=test_ds,
                                   loss=params.loss, smoothing=params.smoothing)

    (w,), traj = base.drive(
        "Dist SGD", params, debug, (w,), round_fn, eval_fn,
        quiet=quiet, start_round=start_round,
    )
    return w, traj
