"""Distributed (sub)gradient descent (reference: DistGD.scala).

Per round: every worker takes one deterministic full pass over its shard
(the one inner solver with no sequential dependency — a pure MXU matvec
pair, see ops/subgradient.py), adds its −λ·w regularizer term, then the
driver applies the gradient-direction-normalized step
w += Δw·(η/‖Δw‖) with η = 1/(β·t) (DistGD.scala:35,40-41).

The η(t) schedule rides through the device-side paths as a scanned (C,)
``t`` leaf (base.TsSampler with no index table — the pass is
deterministic), so ``scan_chunk`` and ``device_loop`` work as for the
other solvers.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import subgradient_pass
from cocoa_tpu.solvers import base


def _gd_parts(params: Params, k: int):
    lam = params.lam
    beta = params.beta

    def per_shard_round(w, carry, x, shard_k):
        return (
            subgradient_pass(w, shard_k, lam, loss=params.loss,
                             smoothing=params.smoothing),
            carry,
        )

    def apply_fn(w, dw_sum, x):
        eta = 1.0 / (beta * x["t"])  # DistGD.scala:35
        norm = jnp.linalg.norm(dw_sum)  # DistGD.scala:40
        return w + dw_sum * (eta / norm)  # DistGD.scala:41

    return per_shard_round, apply_fn


def make_round_step(mesh, params: Params, k: int):
    per_shard_round, apply_fn = _gd_parts(params, k)

    def per_shard(w, shard_k):
        return (per_shard_round(w, (), {}, shard_k)[0],)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(w, t, shard_arrays):
        (dw_sum,) = base.fanout(per_shard, mesh, w, shard_arrays)
        return apply_fn(w, dw_sum, {"t": t})

    return round_step


_CHUNK_STEPS: dict = base.ExecutableCache()


def _make_chunk_kernel(mesh, params: Params, k: int):
    """(w, xs, shard_arrays) -> w'; xs = {"t": (C,)} (no index table)."""
    from cocoa_tpu.parallel.fanout import chunk_fanout

    per_shard_round, apply_fn = _gd_parts(params, k)

    def chunk_kernel(w, xs, shard_arrays):
        w2, _ = chunk_fanout(
            mesh, per_shard_round, apply_fn, w, (), xs, shard_arrays
        )
        return w2

    return chunk_kernel


def make_chunk_step(mesh, params: Params, k: int):
    key = ("distgd", mesh, k, params.lam, params.n, params.beta,
           params.loss, params.smoothing)
    step = _CHUNK_STEPS.get(key)
    if step is None:
        step = jax.jit(_make_chunk_kernel(mesh, params, k),
                       donate_argnums=(0,))
        _CHUNK_STEPS[key] = step
    return step


def run_dist_gd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    w_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
    scan_chunk: int = 0,
    device_loop: bool = False,
):
    """Train; returns (w, Trajectory)."""
    base.check_shards(ds)
    k = ds.k
    if not quiet:
        print(f"\nRunning DistGD on {params.n} data examples, "
              f"distributed over {k} workers")

    dtype = ds.labels.dtype
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding

        w = jax.device_put(w, primal_sharding(mesh))

    ts_sampler = base.TsSampler(None, dtype, counts=ds.counts)
    shard_arrays = ds.shard_arrays()

    def eval_fn(state):
        (w,) = state
        return objectives.evaluate(ds, w, None, params.lam, test_ds=test_ds,
                                   loss=params.loss, smoothing=params.smoothing)

    if device_loop or scan_chunk > 0:
        raw_kernel = _make_chunk_kernel(mesh, params, k)

        def chunk_kernel(state, xs, shard_arrays):
            return (raw_kernel(state[0], xs, shard_arrays),)

        chunk_step = make_chunk_step(mesh, params, k)

        def chunk_fn(t0, c, state):
            return (chunk_step(state[0], ts_sampler.chunk_indices(t0, c),
                               shard_arrays),)

        cache_key = (
            "distgd", k, mesh, params.lam, params.n, params.beta,
            params.loss, params.smoothing, params.num_rounds,
            debug.debug_iter, start_round, ds.layout, str(dtype),
        )
        (w,), traj = base.drive_device_paths(
            "Dist SGD", params, debug, (w,), chunk_kernel, chunk_fn,
            eval_fn, ts_sampler, shard_arrays, alpha_in_state=False,
            mesh=mesh, test_ds=test_ds, quiet=quiet,
            start_round=start_round, scan_chunk=scan_chunk,
            device_loop=device_loop, cache_key=cache_key,
        )
        return w, traj

    step = make_round_step(mesh, params, k)

    def round_fn(t, state):
        (w,) = state
        return (step(w, jnp.asarray(float(t), dtype=dtype), shard_arrays),)

    (w,), traj = base.drive(
        "Dist SGD", params, debug, (w,), round_fn, eval_fn,
        quiet=quiet, start_round=start_round,
    )
    return w, traj
