"""Fleet training: thousands of tenant models through one compiled round.

The solo path pays a full compile + dispatch + eval round-trip per
problem; a regularization-path sweep or a per-tenant model fleet pays it
T times.  This module runs the whole fleet as ONE vmapped drive* ladder
(solvers/base.py ``drive_fleet_on_device``): per-tenant λ·n and σ′ enter
the SAME local-SDCA kernels the solo path runs — as traced scalars
instead of baked-in constants — so one executable serves every tenant,
every σ′ stage, and every round, and the per-tenant duality-gap
certificate stays the solo certificate evaluated lane-wise.

Three drive modes (the fleet mirror of the solo ladder):

- ``plain``  — fixed σ′ (the safe K·γ, or an explicit override);
- ``anneal`` — the per-tenant σ′ schedule: each tenant's sched leaf
  carries its own stage/stall/best, and σ′ = levels[stage_t] is read
  from the static ladder as DATA (a vmapped ``lax.switch`` would
  execute every branch for every lane — docs/DESIGN.md §16);
- ``accel``  — the per-tenant secant (Anderson-1) outer loop: each
  tenant banks its own dual windows, arms and takes its own jumps, and
  restarts on its own gap rises (fixed-Θ; the adaptive-Θ ladder slices
  static index-table widths and stays solo-only).

A T=1 fleet run is bit-identical to the solo path in all three modes
(pinned by tests/test_fleet.py); a certified tenant's (w, α) is
bitwise-frozen from its certifying eval while the rest of the fleet
trains on (the masking contract, solvers/base.py).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.fleet import FleetDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops.local_sdca import local_sdca, local_sdca_fast
from cocoa_tpu.solvers import base

DRIVE_MODES = ("plain", "anneal", "accel")


@dataclasses.dataclass
class FleetResult:
    """One fleet run's outcome, per tenant and aggregate."""

    algorithm: str
    tenants: list                 # T tenant ids
    certified: np.ndarray         # (T,) bool — gap target reached
    stalled: np.ndarray           # (T,) bool — divergence watch fired
    cert_round: np.ndarray        # (T,) int — certifying round, 0 = never
    final_primal: np.ndarray      # (T,)
    final_gap: np.ndarray         # (T,)
    rounds_run: int               # rounds the loop actually executed
    evals: int
    wall_s: float                 # dispatch-to-fetch wall-clock
    w: "jax.Array"                # (T, d) final primal iterates
    alpha: "jax.Array"            # (T, K, n_shard) final duals
    traj: np.ndarray              # (evals, T, base.FLEET_N_COLS)

    @property
    def models_per_second(self) -> float:
        return float(self.certified.sum()) / max(self.wall_s, 1e-9)


def _tenant_chunk_parts(params: Params, mode: str, scaling: float,
                        math: str):
    """The per-shard update + driver apply with TRACED λ·n / σ′ — the
    fleet twin of ``solvers/cocoa._sdca_round_parts`` (exact/fast math
    only; the Pallas and block kernels own their shard axes and cannot
    ride the tenant vmap).  Returns ``make(lam_n, sigma) ->
    (per_shard, apply_fn)`` so the vmapped kernel can close over its
    lane's scalars."""
    if math not in ("exact", "fast"):
        raise ValueError(f"fleet math must be 'exact' or 'fast', got "
                         f"{math!r}")

    def make(lam_n, sigma):
        def apply_fn(w, dw_sum, x=None):
            return w + scaling * dw_sum

        if math == "exact":
            def per_shard(w, alpha_k, idxs_k, shard_k):
                da, dw = local_sdca(
                    w, alpha_k, shard_k, idxs_k, 0.0, 0, mode=mode,
                    sigma=sigma, loss=params.loss,
                    smoothing=params.smoothing, lam_n=lam_n)
                return dw, alpha_k + scaling * da
        else:
            from cocoa_tpu.ops.rows import shard_margins

            def per_shard(w, alpha_k, idxs_k, shard_k):
                m0 = shard_margins(w, shard_k)
                da, dw = local_sdca_fast(
                    m0, alpha_k, shard_k, idxs_k, 0.0, 0,
                    jnp.zeros_like(w), mode=mode, sigma=sigma,
                    loss=params.loss, smoothing=params.smoothing,
                    lam_n=lam_n)
                return dw, alpha_k + scaling * da
        return per_shard, apply_fn

    return make


def run_cocoa_fleet(
    fleet: FleetDataset,
    params: Params,
    debug: DebugParams,
    plus: bool = True,
    drive_mode: str = "plain",
    rng: str = "reference",
    math: str = "exact",
    lane_exec: str = "vmap",
    quiet: bool = False,
    divergence_guard: str = "auto",
    start_round: int = 1,
) -> FleetResult:
    """Train every tenant of ``fleet`` through one compiled vmapped
    round loop.  ``params.lam`` is ignored — λ is per-tenant
    (``fleet.lams``); ``params.local_iters`` must equal the fleet's
    common H.  ``debug.debug_iter`` is the eval/chunk cadence and must
    divide ``params.num_rounds`` (the fleet loop has no sub-cadence
    tail).  Returns a :class:`FleetResult`; also emits the typed
    ``fleet_progress`` / ``tenant_certified`` events when the telemetry
    bus is active."""
    from cocoa_tpu.parallel.fanout import chunk_fanout
    from cocoa_tpu.telemetry import events as _tele

    if drive_mode not in DRIVE_MODES:
        raise ValueError(f"fleet drive mode must be one of {DRIVE_MODES}, "
                         f"got {drive_mode!r}")
    if lane_exec not in ("vmap", "map"):
        raise ValueError(f"fleet lane_exec must be vmap|map, got "
                         f"{lane_exec!r}")
    c = debug.debug_iter
    if c <= 0:
        raise ValueError("the fleet loop requires debugIter > 0 (the eval "
                         "cadence is its chunk axis)")
    if params.num_rounds % c != 0:
        raise ValueError(
            f"fleet numRounds ({params.num_rounds}) must be a multiple of "
            f"debugIter ({c}) — the vmapped loop has no sub-cadence tail")
    if params.local_iters != fleet.local_iters:
        raise ValueError(
            f"params.local_iters ({params.local_iters}) disagrees with "
            f"the fleet's common H ({fleet.local_iters})")
    t_fleet, k, h = fleet.t, fleet.k, fleet.local_iters
    dtype = fleet.dtype
    mode = "plus" if plus else "cocoa"
    name = ("CoCoA+" if plus else "CoCoA") + " fleet"
    scaling = params.gamma if plus else params.beta / k
    safe = k * params.gamma
    sigma_fixed = safe
    if params.sigma is not None and params.sigma != "auto":
        sigma_fixed = float(params.sigma)

    # jaxlint: allow=f64 -- host-side EXACT per-tenant scalar staging:
    # float32(float64(λ)·n) is bitwise the value the solo kernels bake
    # in as a constant, which is what the T=1 ≡ solo pin rests on
    lam_n64 = fleet.lams.astype(np.float64) * fleet.n.astype(np.float64)
    scal = {
        "lam_n": jnp.asarray(lam_n64.astype(np.float32)),
        "lam": jnp.asarray(fleet.lams.astype(np.dtype(dtype))),
        # the eval's /n as the f32 reciprocal the solo jit folds it into
        # (eval_metrics inv_n contract — bit-identity with the solo
        # certificate)
        "inv_n": jnp.asarray(np.float32(1.0)
                             / fleet.n.astype(np.float32)),
        # the accel jump's 1/(λn), host-f64 then cast — exactly the
        # constant the solo accel_kernel bakes in
        "inv_lam_n": jnp.asarray((1.0 / lam_n64).astype(np.float32)),
    }
    tgts_np = np.where(np.isnan(fleet.gap_targets), -np.inf,
                       fleet.gap_targets).astype(np.dtype(dtype))
    gap_targets = jnp.asarray(tgts_np)
    has_targets = bool(np.all(np.isfinite(tgts_np)))

    levels = None
    n_stages = 0
    if drive_mode == "anneal":
        if not has_targets:
            raise ValueError(
                "fleet drive_mode='anneal' needs a gap target for every "
                "tenant (the backoff rides the per-tenant stall watch, "
                "which runs on the gap-target path)")
        start = (sigma_fixed if sigma_fixed < safe else safe / 2.0)
        levels = base.anneal_levels(start, safe)
        n_stages = len(levels)
    if drive_mode == "accel" and not has_targets:
        raise ValueError(
            "fleet drive_mode='accel' needs a gap target for every tenant "
            "(the momentum restart rule monitors each lane's gap)")
    guard_on = (n_stages > 1) or base.resolve_divergence_guard(
        divergence_guard, mode, sigma_fixed, k, params.gamma)

    # --- index tables: host-sampled, shared across tenants whenever the
    # per-tenant (seed, counts) streams coincide (equal-sized tenants —
    # the common fleet shape); otherwise stacked per tenant on axis 2
    n_chunks = params.num_rounds // c
    counts0 = fleet.counts[0]
    shared_tables = bool(np.all(fleet.counts == counts0[None]))
    per_round_ints = (1 if shared_tables else t_fleet) * k * h
    table_bytes = 4 * params.num_rounds * per_round_ints
    if table_bytes > base.MAX_IDX_TABLE_BYTES:
        raise ValueError(
            f"fleet index tables would need {table_bytes >> 20} MiB "
            f"(> {base.MAX_IDX_TABLE_BYTES >> 20} MiB): lower numRounds "
            f"or localIterFrac, or split the fleet")

    def tenant_tables(counts):
        sampler = base.IndexSampler(rng, debug.seed, h, counts)
        tab = sampler.chunk_indices(start_round, params.num_rounds)
        return np.asarray(tab).reshape(n_chunks, c, k, h)

    if shared_tables:
        idxs_all = jnp.asarray(tenant_tables(counts0))
        per_tenant_idxs = False
    else:
        stacked = np.stack([tenant_tables(fleet.counts[ti])
                            for ti in range(t_fleet)], axis=2)
        idxs_all = jnp.asarray(stacked)    # (n_chunks, C, T, K, H)
        per_tenant_idxs = True

    # --- the per-tenant kernels (vmapped by the driver) ----------------
    # σ′ stays a STATIC per-branch constant, exactly as on the solo path:
    # the per-stage lax.switch grows a leading T axis under the driver's
    # vmap (a batched branch index runs every branch and selects per
    # lane — each branch is then the bit-stable batched fixed-σ′ kernel,
    # so an anneal fleet lane is bit-identical to the solo branch it
    # selects).  λ·n is the one traced scalar (local_sdca's lam_n
    # contract).
    make_parts = _tenant_chunk_parts(params, mode, scaling, math)

    def run_chunk(w, alpha, idxs_ckh, data, lam_n, sigma):
        per_shard, apply_fn = make_parts(lam_n, sigma)
        return chunk_fanout(None, per_shard, apply_fn, w, alpha,
                            idxs_ckh, data)

    if drive_mode == "plain":
        def chunk_kernel(state, idxs_ckh, data, scal_t):
            w, alpha = run_chunk(state[0], state[1], idxs_ckh, data,
                                 scal_t["lam_n"], sigma_fixed)
            return (w, alpha)

        state0 = ()
    elif drive_mode == "anneal":
        branches = [
            (lambda w, a, idxs, data, lam_n, lv=lv:
             run_chunk(w, a, idxs, data, lam_n, lv))
            for lv in levels
        ]

        def chunk_kernel(state, idxs_ckh, data, scal_t):
            w, alpha, sched = state
            c_len = idxs_ckh.shape[0]
            br = jnp.clip(sched[0].astype(jnp.int32), 0, n_stages - 1)
            w2, a2 = jax.lax.switch(br, branches, w, alpha, idxs_ckh,
                                    data, scal_t["lam_n"])
            return (w2, a2, sched.at[4].add(jnp.float32(c_len)))

        state0 = (np.tile(np.asarray(
            base.sched_init_array(start_round))[None], (t_fleet, 1)),)
    else:   # accel
        def chunk_kernel(state, idxs_ckh, data, scal_t):
            w, alpha, hist, sched = state
            c_len = idxs_ckh.shape[0]
            w2, a2 = run_chunk(w, alpha, idxs_ckh, data, scal_t["lam_n"],
                               sigma_fixed)
            return (w2, a2, hist, sched.at[4].add(jnp.float32(c_len)))

        def jump_kernel(state, data, scal_t):
            # the solo accel_kernel's chunk-head secant jump, lane-local
            # (run through lax.map by the driver so its einsums lower
            # exactly as the solo executable's — base._build_fleet_run):
            # the jumped α is box-clipped and padding-masked, and w
            # advances by the exact correspondence update, so the lane's
            # (w, α) stays a feasible certified pair
            w, alpha, hist, sched = state

            def take_jump(w, alpha):
                from cocoa_tpu.ops import rows as _rows

                d1 = hist[1] - hist[0]
                den = jnp.vdot(d1, d1)
                rho = jnp.where(
                    den > 0,
                    jnp.vdot(d1, alpha - hist[1])
                    / jnp.where(den > 0, den, jnp.float32(1)),
                    jnp.float32(0))
                cj = base.secant_coef(jnp, rho)
                a_ext = jnp.clip(alpha + cj * (alpha - hist[1]),
                                 0.0, 1.0) * data["mask"]
                coefs = (data["labels"] * (a_ext - alpha)
                         * scal_t["inv_lam_n"])
                return _rows.shards_axpy(coefs, data, w), a_ext

            w, alpha = jax.lax.cond(
                sched[base.A_JUMP] > 0, take_jump,
                lambda w, a: (w, a), w, alpha)
            return (w, alpha, hist,
                    sched.at[base.A_JUMP].set(jnp.float32(0)))

        state0 = (
            np.zeros((t_fleet, 2, k, fleet.n_shard), np.dtype(dtype)),
            np.tile(np.asarray(base.sched_init_array(
                start_round, accel=True))[None], (t_fleet, 1)),
        )

    def eval_kernel(state, data, scal_t):
        return objectives.eval_metrics(
            state[0], state[1], data, scal_t["lam"], 0,
            mesh=None, loss=params.loss, smoothing=params.smoothing,
            inv_n=scal_t["inv_n"])

    w0 = jnp.zeros((t_fleet, fleet.num_features), dtype=dtype)
    alpha0 = jnp.zeros((t_fleet, k, fleet.n_shard), dtype=dtype)
    state = (w0, alpha0, *(jnp.asarray(s) for s in state0))
    shard_arrays = fleet.shard_arrays()

    cache_key = (
        "cocoa-fleet", mode, drive_mode, math, rng, t_fleet, k,
        fleet.n_shard, fleet.num_features, h, c, n_chunks,
        params.loss, params.smoothing, scaling, sigma_fixed, levels,
        guard_on, str(dtype), per_tenant_idxs, lane_exec,
    )
    if not quiet:
        print(f"\nRunning {name}: {t_fleet} tenants x (K={k}, "
              f"n_shard={fleet.n_shard}, d={fleet.num_features}, H={h}) "
              f"— one compiled round, drive_mode={drive_mode}")
    t0 = time.perf_counter()
    state, carry, n_done, traj_host = base.drive_fleet_on_device(
        name, state, chunk_kernel, eval_kernel, idxs_all, shard_arrays,
        scal, gap_targets, quiet=quiet, start_round=start_round,
        cache_key=cache_key, stall_evals=base.stall_window(c),
        divergence_guard=guard_on, n_stages=n_stages,
        accel=(drive_mode == "accel"),
        per_tenant_idxs=per_tenant_idxs,
        jump_kernel=(jump_kernel if drive_mode == "accel" else None),
        lane_exec=lane_exec)
    wall_s = time.perf_counter() - t0

    from cocoa_tpu.analysis import sanitize as _sanitize

    with _sanitize.intended_fetch("fleet_result_fetch"):
        certified = np.asarray(carry.done_tgt)
        stalled = np.asarray(carry.done_stall)
        cert_chunk = np.asarray(carry.cert_chunk)
        stall_chunk = np.asarray(carry.stall_chunk)
    cert_round = np.where(cert_chunk > 0,
                          start_round - 1 + cert_chunk * c, 0)
    last = traj_host[n_done - 1] if n_done else np.full(
        (t_fleet, base.FLEET_N_COLS), np.nan)
    result = FleetResult(
        algorithm=name, tenants=list(fleet.tenants), certified=certified,
        stalled=stalled, cert_round=cert_round.astype(np.int64),
        final_primal=last[:, 0].copy(), final_gap=last[:, 1].copy(),
        rounds_run=n_done * c, evals=n_done, wall_s=wall_s,
        w=state[0], alpha=state[1], traj=traj_host)

    bus = _tele.get_bus()
    if bus.active():
        for j in range(n_done):
            t_round = start_round - 1 + (j + 1) * c
            cum = int(((cert_chunk > 0) & (cert_chunk <= j + 1)).sum())
            # active = lanes still UPDATING: certified and stalled-out
            # lanes are both masked frozen from their done eval on
            inactive = int((((cert_chunk > 0) & (cert_chunk <= j + 1))
                            | ((stall_chunk > 0)
                               & (stall_chunk <= j + 1))).sum())
            newly = np.nonzero(cert_chunk == j + 1)[0]
            for ti in newly:
                bus.emit("tenant_certified", algorithm=name,
                         tenant=fleet.tenants[int(ti)], t=t_round,
                         gap=float(traj_host[j, int(ti), 1]))
            bus.emit(
                "fleet_progress", algorithm=name, t=t_round,
                active=t_fleet - inactive, certified_total=cum,
                models_per_second=(result.models_per_second
                                   if j == n_done - 1 else None))
    if not quiet:
        done_n = int(certified.sum())
        print(f"{name}: {done_n}/{t_fleet} tenants certified in "
              f"{result.rounds_run} rounds, {wall_s:.2f}s wall — "
              f"{result.models_per_second:.1f} models/s")
    return result
