"""Shared outer-loop machinery for all solvers.

Every algorithm's round has the same communication shape (the reference's
``mapPartitions`` → ``reduce`` skeleton, CoCoA.scala:45-47):

    fan out (w replicated, shard-local state pinned)
    → per-shard local solver
    → one O(d) sum-reduce of Δw
    → replicated driver-side w update

``fanout`` carries that shape on two execution paths with identical math:

- **mesh path** (K devices): ``shard_map`` over the dp axis; the Δw reduce is
  one ``lax.psum`` over ICI — the whole point of CoCoA's communication
  efficiency maps to exactly one collective per round.
- **local path** (mesh=None, e.g. a single TPU chip holding all K logical
  shards): ``vmap`` over the leading shard axis + an in-device sum.  Same
  numbers, no collective — used for single-chip benchmarking and as the
  K-logical-shards-on-1-device analogue of the reference's ``local[4]`` mode.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

import jax
import numpy as np

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.parallel.fanout import fanout  # noqa: F401  (re-export)
from cocoa_tpu.telemetry import tracing as _tracing
from cocoa_tpu.utils.logging import Trajectory
from cocoa_tpu.utils.prng import sample_indices_per_shard


# σ′-override guardrail (VERDICT r4): a σ′ below the problem's tolerance
# stops the duality gap from converging — the exact certificate reports it,
# but (before this guard) only after the full round budget burned.  The
# box constraint keeps α ∈ [0,1]^n, so "divergence" manifests as the gap
# OSCILLATING at a high level (measured: σ′=1 at K=4 on adversarially
# coherent shards bounces in [0.1, 20] forever), not as monotone growth —
# a consecutive-rise test never fires.  The robust detector is windowed
# no-improvement: a converging run keeps improving its best-seen gap
# (even the slow λ=1e-4 rcv1 tail improves ~6%/eval ⇒ ~50% per 10 evals),
# while an oscillating run's best barely moves.  Bail out when the best
# gap has not improved to ≤ STALL_REL × (best at the last reset) within
# the stall window.
#
# The window is denominated in ROUNDS, not evaluations: per-eval progress
# scales with the eval cadence (at --debugIter=1 a healthy run improves
# ~1/25th as much per eval as at the calibration cadence 25), so a fixed
# eval count would make the guard ~25x stricter at fine cadences and
# kill slow-but-converging runs (round-5 review finding).  STALL_EVALS
# is the floor so coarse cadences still get a meaningful window.
STALL_EVALS = 12
STALL_ROUNDS = 300     # = STALL_EVALS at the calibration cadence 25
STALL_REL = 0.75


def stall_window(debug_iter: int) -> int:
    """The no-improvement window in EVALS for this eval cadence."""
    return max(STALL_EVALS, -(-STALL_ROUNDS // max(1, int(debug_iter))))


# --- device-resident σ′ schedule (--sigmaSchedule=anneal) -------------------
#
# The sigma=auto trial-and-rerun (solvers/cocoa.run_cocoa, --sigmaSchedule=
# trial) pays for a wrong aggressive guess twice: the guarded trial burns a
# stall window AND the safe rerun restarts from round 1.  The anneal
# schedule instead carries σ′ IN the driver ladder's loop state: start
# aggressive, and when the stall watch fires, multiply σ′ toward the safe
# K·γ bound IN PLACE and keep going from the current iterate.  That is
# sound because the primal-dual correspondence w = (1/λn)·Σ y·α·x and the
# box constraint α ∈ [0,1]^n — everything the exact duality-gap
# certificate rests on — are maintained by the update rule under ANY σ′:
# σ′ only scales the local subproblem's coupling term, so any (w, α) pair
# a σ′-a run produced is a feasible starting point for a σ′-b run and the
# certificate stays exact across the switch.  The cost of a wrong guess
# drops from (stall window + full restart) to (stall window), and the
# iterate progress made before the backoff is kept, not discarded.
#
# The schedule state is a tiny float32 vector riding the solver state
# tuple (so it is donated, checkpointed, and resumed with w and α — a
# mid-schedule --resume is bit-identical):
#
#   sched[0] = stage       index into the static σ′ ladder
#   sched[1] = stall       consecutive no-improvement evals at this stage
#   sched[2] = best        best gap seen since the stage started
#   sched[3] = best_prev   best at the last watch reset (the _GapWatch twin)
#   sched[4] = t_next      1-based round the NEXT chunk starts at (the
#                          chunk kernels advance it; the warm-start loss
#                          handoff reads it — solvers/cocoa.py)
#
# All five values are small integers or f32 gaps, so float32 carries them
# exactly; stage/stall arithmetic in f32 is exact far beyond any real
# ladder or window length.
SCHED_LEN = 5
MAX_SIGMA_LEVELS = 8

# --- accelerated outer loop (--accel, round 12) -----------------------------
#
# Secant (Anderson-1) extrapolation on the DUAL at eval-window boundaries,
# plus adaptive local accuracy Θ (the outer-acceleration + inexact-local-
# solve structure of Smith et al., arXiv:1711.05305 — PAPERS.md).  The
# solver state gains a (2, K, n_shard) dual-history leaf ``hist`` — the
# two previous eval-boundary α snapshots — and EIGHT more f32 slots
# appended to the sched vector, so an accelerated state tuple is
#
#   state = (w, alpha, hist, sched)     len(sched) = SCHED_LEN + ACCEL_LEN
#
#   sched[5]  = hist_len   valid α-window snapshots banked (0, 1, 2)
#   sched[6]  = jump       a secant jump is armed for the next chunk head
#   sched[7]  = restarts   cumulative gap-monitored momentum restarts
#   sched[8]  = last_gap   the previous eval's gap (the restart trigger)
#   sched[9]  = th_stage   Θ ladder index (inner steps per round)
#   sched[10] = th_stall   Θ watch: consecutive no-improvement evals
#   sched[11] = th_best    Θ watch best gap since the stage started
#   sched[12] = th_best_prev
#
# The jump itself (solvers/cocoa.py accel_kernel head): with the two
# banked snapshots h1, h2 and the current α, the window displacements
# δ₁ = h2−h1 and δ₂ = α−h2 give the autocorrelation ρ = ⟨δ₁,δ₂⟩/⟨δ₁,δ₁⟩
# of the outer iteration's limiting mode, and the secant/Anderson-1
# fixed-point jump α ← α + c·δ₂ with c = ρ/(1−ρ) lands where the
# geometric tail α + δ₂·(ρ + ρ² + …) is heading.  c is SIGNED and
# data-derived: oscillation (ρ ≈ −1) makes it pairwise averaging
# (c ≈ −½), slow drift (ρ → 1) aggressive extrapolation, clipped to
# [ACCEL_CMIN, ACCEL_CMAX].  The jumped α is clipped back to the dual
# box and masked, and w is advanced by the EXACT correspondence update
# Σ y·Δα·x/(λn) (ops/rows.shards_axpy) — so (w, α) remains a feasible
# primal-dual pair and the unmodified gap evaluation in
# evals/objectives.py stays the certificate.  A gap RISE at an eval
# boundary discards the bank (restart): damage from a bad jump is
# bounded to one eval cadence.  All slots are small integers or f32
# gaps — exact in float32, exact in the checkpoint meta JSON round trip.
#
# Measured-out alternatives on the rcv1-synth λ=1e-4 config (SWEEPS.md
# "accelerated outer loop"): per-round growing-β Nesterov momentum on w
# DIVERGES (54 restarts, never certifies — one CoCoA+ round is a large
# contraction step, and 25 unmonitored β→1 extrapolations overshoot the
# dual box); eval-windowed fixed β down to 0.05 still diverges; damped
# (negative-β) extrapolation cannot stabilize σ′ < K/2; Polyak–Ruppert
# window averaging never beats the raw iterate; raising H near the
# target buys only ~1.1×.  The tail has a MIXED spectrum — measured
# ρ_α ≈ +0.73 drift with oscillatory modes on top — which is exactly
# the regime the signed secant coefficient adapts to: measured 1.76×
# fewer rounds to the 1e-4 certificate on full rcv1-synth at the safe
# σ′ = K·γ (1100 → 625), 1.38× at σ′ = K/2 — the ratio grows with the
# control's round count (benchmarks/SWEEPS.md).
ACCEL_LEN = 8
A_HIST = SCHED_LEN
A_JUMP = SCHED_LEN + 1
A_RESTARTS = SCHED_LEN + 2
A_LASTGAP = SCHED_LEN + 3
A_TH_STAGE = SCHED_LEN + 4
A_TH_STALL = SCHED_LEN + 5
A_TH_BEST = SCHED_LEN + 6
A_TH_BPREV = SCHED_LEN + 7

# c = ρ/(1−min(ρ, RHO_CAP)) clipped to [CMIN, CMAX]: the cap keeps the
# pole at ρ→1 finite before the clip, CMIN = −0.5 is exact pairwise
# averaging (the stable limit for a pure oscillation), CMAX = 3 the
# measured knee — the rcv1-synth sweep resolved c ≈ 2.2–2.7 under a cap
# of 3 and of 8 identically (same 800-round trajectory), so 3 bounds a
# bad estimate without binding the good ones.
ACCEL_CMIN = -0.5
ACCEL_CMAX = 3.0
ACCEL_RHO_CAP = 0.9


def secant_coef(xp, rho):
    """The shared jump-coefficient rule (xp = jnp when traced, np for
    tests): c = ρ/(1−ρ) with the ρ-cap and [CMIN, CMAX] clip.  Exact f32
    ops only (one divide, min, clip)."""
    den = xp.float32(1.0) - xp.minimum(rho, xp.float32(ACCEL_RHO_CAP))
    return xp.clip(rho / den, xp.float32(ACCEL_CMIN),
                   xp.float32(ACCEL_CMAX))

# Θ (local accuracy) schedule: early rounds run H/divisor inner SDCA
# steps — cheap, imprecise local solves while the gap is far from the
# target — and the ladder tightens toward the full H as the run
# approaches certification.  Two advance triggers, both device-computable
# from the current gap estimate:
#   - near-target: gap ≤ THETA_NEAR × gap_target jumps straight to the
#     final (full-H) stage, so certification always happens at full
#     local accuracy;
#   - stall: the per-stage watch (same _watch_update arithmetic as the
#     σ′ anneal, rel = THETA_REL) fires after THETA_EVALS consecutive
#     evals without the best gap HALVING — a deliberately strict bar:
#     loose stages are only worth keeping while the gap is in its early
#     fast-decay phase (measured: an H/4 stage that merely *improves*
#     ~30%/eval never fires a 0.9-rel watch and the run crawls; the
#     0.5-rel watch moves it up within two evals).
# The ladder starts at H/2, not lower: H has strongly diminishing
# returns at the top (2×/10× MORE local work buys only 1.06–1.10×
# fewer rounds, SWEEPS.md), so halving it costs almost nothing per
# round — but an H/4 stage was measured to push the λ=1e-4 rcv1-synth
# A/B from 800 to 925 rounds (the early fast-decay rounds ARE
# productive, and their secant windows degrade too: 6 restarts vs 2).
# A Θ stage advance also clears the secant window bank (the two banked
# windows came from a DIFFERENT round map — a jump across the seam
# extrapolates the wrong geometric tail).
THETA_DIVS = (2, 1)
THETA_REL = 0.5
THETA_EVALS = 1
THETA_NEAR = 10.0


def theta_ladder(h: int, adaptive: bool) -> tuple:
    """Per-Θ-stage inner-iteration counts, coarse → exact.  The final
    rung is always the full ``h`` (certification runs at full local
    accuracy); a small ``h`` collapses duplicate rungs away."""
    if not adaptive:
        return (int(h),)
    out = []
    for dv in THETA_DIVS:
        hs = min(int(h), max(1, int(h) // dv))
        if not out or hs > out[-1]:
            out.append(hs)
    return tuple(out)


class AccelConfig:
    """Static accelerated-loop configuration threaded through the drive*
    ladder: the Θ ladder (per-stage inner-iteration counts) and the gap
    target the near-target jump keys on.  Hashable (rides cache keys)."""

    def __init__(self, theta_hs: tuple, gap_target=None):
        self.theta_hs = tuple(int(v) for v in theta_hs)
        self.n_theta = len(self.theta_hs)
        self.gap_target = gap_target

    def token(self):
        return ("accel", self.theta_hs)


def accel_host_step(sched, gap, n_theta: int, gap_target,
                    seam: bool = False):
    """Host twin of the device loop's per-eval accel update (same float32
    arithmetic, so host-stepped and device drivers make identical
    restart/arm/Θ decisions — the σ′ ``sched_host_step`` pattern).
    ``seam`` marks a σ′ anneal backoff committed at this same eval
    boundary — a round-map seam exactly like a Θ stage advance, with the
    same bank treatment (see below).
    Returns (new sched ndarray, restarted, theta_staged).

    Window bookkeeping only — the secant jump ACTION runs at the head of
    the next chunk dispatch (solvers/cocoa.py accel_kernel consumes the
    armed ``A_JUMP`` flag, where the shard data the correspondence update
    needs is in scope).  Three mutually exclusive outcomes per eval:

    - gap ROSE: restart — the snapshot bank is discarded and restarts
      from this eval's α (the caller banks it, see :func:`_accel_replace`);
    - two windows banked and the gap still improving: ARM the jump — the
      bank is frozen for the kernel head to consume, nothing is pushed;
    - otherwise: bank this eval's α as the newest window snapshot."""
    s = np.asarray(sched, dtype=np.float32).copy()
    gv = (np.float32(np.inf) if gap is None or np.isnan(gap)
          else np.float32(gap))
    restarted = bool(gv > s[A_LASTGAP])
    if restarted:
        s[A_RESTARTS] += 1.0
        s[A_HIST] = 1.0
    elif s[A_HIST] >= 2.0:
        s[A_JUMP] = 1.0
        s[A_HIST] = 0.0
    else:
        s[A_HIST] = min(s[A_HIST] + 1.0, 2.0)
    s[A_LASTGAP] = gv
    staged = False
    if n_theta > 1:
        s[A_TH_BEST], s[A_TH_BPREV], s[A_TH_STALL] = _watch_update(
            np, gv, s[A_TH_BEST], s[A_TH_BPREV], s[A_TH_STALL],
            np.float32(THETA_REL))
        tgt32 = (np.float32(-np.inf) if gap_target is None
                 else np.float32(gap_target))
        near = bool(gv <= np.float32(THETA_NEAR) * tgt32)
        fire = bool(s[A_TH_STALL] >= np.float32(THETA_EVALS))
        if s[A_TH_STAGE] < n_theta - 1 and (near or fire):
            s[A_TH_STAGE] = (np.float32(n_theta - 1) if near
                             else s[A_TH_STAGE] + 1)
            s[A_TH_STALL] = 0.0
            s[A_TH_BEST] = np.float32(np.inf)
            s[A_TH_BPREV] = np.float32(np.inf)
            # windows banked BEFORE the seam measured the old stage's
            # round map — a secant ρ mixing maps extrapolates the wrong
            # tail, so the bank drops to (at most) the α just banked,
            # which is a valid anchor for the new map's first window.
            # An already-armed jump stays armed: all three of its points
            # predate the seam, so its extrapolation is consistent.
            s[A_HIST] = min(s[A_HIST], 1.0)
            staged = True
    if seam:
        # a σ′ backoff changed the round map at this boundary: cap the
        # bank the same way a Θ stage advance does (armed jump stays
        # armed — all its points predate the seam)
        s[A_HIST] = min(s[A_HIST], np.float32(1.0))
    return s, restarted, staged


def _accel_replace(state, sched_np):
    """Commit a host accel step back into the (w, alpha, hist, sched)
    state: the sched leaf via :func:`_sched_replace`, plus — unless this
    eval ARMED a jump (the bank is then frozen for the kernel head to
    consume) — banking the current α as the newest window snapshot,
    hist ← [hist[1], α].  ``jnp.stack`` materializes a fresh buffer, so
    the hist leaf never aliases the separately-donated α arg."""
    import jax
    import jax.numpy as jnp

    armed = float(sched_np[A_JUMP]) > 0.0
    state = _sched_replace(state, sched_np)
    if not armed:
        hist = jnp.stack([state[2][1], state[1]])
        sharding = getattr(state[2], "sharding", None)
        if sharding is not None:
            hist = jax.device_put(hist, sharding)
        state = (*state[:2], hist, *state[3:])
    return state


def _emit_accel_events(name, t, restarted, restarts_total, staged, stage,
                       accel: "AccelConfig", quiet):
    """The typed momentum_restart / theta_stage events for one eval
    boundary (emitted regardless of ``quiet`` — same policy as
    :func:`_emit_backoff`)."""
    from cocoa_tpu.telemetry import events as _tele

    bus = _tele.get_bus()
    if restarted:
        bus.emit("momentum_restart", algorithm=name, t=int(t),
                 restarts_total=int(restarts_total))
        if not quiet:
            print(f"{name}: momentum restart at round {t} (gap rose; "
                  f"secant window bank discarded)")
    if staged:
        bus.emit("theta_stage", algorithm=name, t=int(t), stage=int(stage),
                 h=int(accel.theta_hs[int(stage)]))
        if not quiet:
            print(f"{name}: Θ schedule — local accuracy raised to "
                  f"H={accel.theta_hs[int(stage)]} at round {t}")


def anneal_levels(start: float, safe: float, factor: float = 2.0,
                  max_levels: int = MAX_SIGMA_LEVELS) -> tuple:
    """The static σ′ ladder: geometric from the aggressive ``start`` up to
    the paper-safe ``safe`` = K·γ (always the final rung — the schedule can
    never anneal PAST safety; a ladder that would exceed ``max_levels``
    jumps straight to safe on its last step)."""
    if start >= safe:
        return (float(safe),)
    levels = [float(start)]
    while levels[-1] * factor < safe and len(levels) < max_levels - 1:
        levels.append(levels[-1] * factor)
    levels.append(float(safe))
    return tuple(levels)


def sched_init_array(start_round: int, sched_init=None, accel: bool = False):
    """The initial sched vector (see the layout notes above): a restored
    mid-schedule state, or a fresh stage-0 watch starting at
    ``start_round``.  With ``accel`` the vector carries the ACCEL_LEN
    momentum/Θ tail too; a restored plain (SCHED_LEN,) state is extended
    with fresh accel slots (resuming a pre-accel checkpoint restarts the
    momentum sequence — sound: any (w, α) is a valid primal-dual pair),
    and an accel-length state resumed WITHOUT accel keeps its σ′ head."""
    import jax.numpy as jnp

    head = np.array([0.0, 0.0, np.inf, np.inf, float(start_round)],
                    dtype=np.float32)
    tail = np.array([0.0, 0.0, 0.0, np.inf, 0.0, 0.0, np.inf, np.inf],
                    dtype=np.float32)
    if sched_init is not None:
        s = np.asarray(sched_init, dtype=np.float32)
        if s.shape not in ((SCHED_LEN,), (SCHED_LEN + ACCEL_LEN,)):
            raise ValueError(
                f"restored sigma-schedule state has shape {s.shape}, "
                f"expected ({SCHED_LEN},) or ({SCHED_LEN + ACCEL_LEN},) — "
                f"was the checkpoint written by an incompatible version?")
        if accel and s.shape == (SCHED_LEN,):
            s = np.concatenate([s, tail])
        elif not accel and s.shape == (SCHED_LEN + ACCEL_LEN,):
            s = s[:SCHED_LEN]
        return jnp.asarray(s)
    return jnp.asarray(np.concatenate([head, tail]) if accel else head)


def _watch_update(xp, gv, best, best_prev, stall, rel):
    """ONE windowed no-improvement step — the single arithmetic behind
    every in-loop stall watch (the legacy device twin, the anneal device
    branch, and :func:`sched_host_step`; ``xp`` is jnp when traced, np on
    the host).  Callers pass ``rel`` at the dtype the comparison must run
    in (float32 for the anneal twins — host and device must make
    IDENTICAL backoff decisions for bit-identical resume).  Returns
    (best, best_prev, stall)."""
    best = xp.minimum(best, gv)
    improved = best <= rel * best_prev
    stall = xp.where(improved, xp.zeros_like(stall), stall + 1)
    best_prev = xp.where(improved, best, best_prev)
    return best, best_prev, stall


def _sched_replace(state, sched_np):
    """Swap the host-updated sched vector back into the state tuple (the
    sched leaf is by convention the LAST leaf of a scheduled state, and
    the only 3rd leaf any driver state carries — the checkpoint savers
    below rely on the same invariant).  The replacement keeps the old
    leaf's placement: under an explicit mesh the initialization committed
    sched with a replicated NamedSharding, and a bare jnp.asarray would
    re-enter the donating jitted step with mismatched sharding typing."""
    import jax
    import jax.numpy as jnp

    arr = jnp.asarray(sched_np)
    sharding = getattr(state[-1], "sharding", None)
    if sharding is not None:
        arr = jax.device_put(arr, sharding)
    return (*state[:-1], arr)


def sched_host_step(sched, gap, stall_evals: int, n_stages: int):
    """Host twin of the device-side schedule/watch update (same float32
    arithmetic via :func:`_watch_update`, so the host-stepped drivers and
    the device loop make identical backoff decisions).  Returns
    (new sched ndarray, backed_off)."""
    s = np.asarray(sched, dtype=np.float32).copy()
    gv = (np.float32(np.inf) if gap is None or np.isnan(gap)
          else np.float32(gap))
    s[2], s[3], s[1] = _watch_update(np, gv, s[2], s[3], s[1],
                                     np.float32(STALL_REL))
    backed = bool(s[1] >= np.float32(stall_evals) and s[0] < n_stages - 1)
    if backed:
        # fresh watch at the new stage; the iterate (w, α) carries over
        s[0] += 1.0
        s[1] = 0.0
        s[2] = np.float32(np.inf)
        s[3] = np.float32(np.inf)
    return s, backed


def _emit_backoff(name, t, sigma_levels, stage, quiet, message=None):
    """One σ′-anneal backoff: the typed ``sigma_backoff`` event (emitted
    regardless of ``quiet`` — the machine-readable trace survives a
    silenced console) plus the optional console line.  The host schedule
    step bumps exactly one rung, so ``from_sigma`` is stage-1."""
    from cocoa_tpu.telemetry import events as _tele

    _tele.get_bus().emit(
        "sigma_backoff", algorithm=name, t=int(t),
        sigma=sigma_levels[stage], from_sigma=sigma_levels[stage - 1],
        stage=int(stage))
    if message and not quiet:
        print(message)


def resolve_divergence_guard(flag: str, mode: str, sigma: float, k: int,
                             gamma: float) -> bool:
    """Resolve the ``--divergenceGuard`` flag to an armed/disarmed bool.

    ``on``/``off`` force it.  ``auto`` (default) arms the guard only when
    σ′ is overridden BELOW the paper-safe K·γ bound — the one regime where
    certified divergence is an expected outcome the run should bail out of
    (the --sigma sweep / sigma=auto trials).  A safe-σ′ run that converges
    slowly is left to its round budget instead of being mislabeled
    DIVERGED (ADVICE r5: the always-armed guard killed slow-but-converging
    problems).  Modes whose subproblem never reads σ′ (cocoa's advancing
    local view, frozen's plain gradient) never arm on auto."""
    if flag not in ("auto", "on", "off"):
        raise ValueError(
            f"divergence guard must be auto|on|off, got {flag!r}")
    if flag != "auto":
        return flag == "on"
    return mode in ("plus", "prox") and sigma < k * gamma


def _last_gap(traj):
    """The most recent eval-cadence duality gap the trajectory holds
    (None before the first eval / on gap-less solvers) — what every
    checkpoint save stamps into its meta so the serving hot-swap
    watcher can report which certificate the model it publishes
    carries (cocoa_tpu/serving/, docs/DESIGN.md §17)."""
    for rec in reversed(traj.records):
        if rec.gap is not None:
            return float(rec.gap)
    return None


class _GapWatch:
    """Windowed no-improvement watch over eval-cadence gap values;
    ``update(gap)`` returns True when the run should bail out (diverged or
    irrecoverably stalled — the gap certificate is exact either way)."""

    def __init__(self, n_evals: int = STALL_EVALS, rel: float = STALL_REL):
        self.n = n_evals
        self.rel = rel
        self.best = float("inf")
        self.best_prev = float("inf")   # best at the last reset
        self.stall = 0

    def update(self, gap) -> bool:
        if gap is None:
            return False
        self.best = min(self.best, float(gap))
        if self.best <= self.rel * self.best_prev:
            self.stall = 0
            self.best_prev = self.best
        else:
            self.stall += 1
        return self.stall >= self.n


def drive(
    name: str,
    params: Params,
    debug: DebugParams,
    state: tuple,
    round_fn: Callable[[int, tuple], tuple],
    eval_fn: Callable[[tuple], tuple],
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
    divergence_guard: bool = True,
):
    """The outer driver loop shared by every solver (CoCoA.scala:39-63
    skeleton): run rounds, gate evaluation to every ``debugIter`` rounds,
    checkpoint every ``chkptIter`` rounds, optionally stop early on a
    duality-gap target (or on measured divergence — see STALL_EVALS;
    ``divergence_guard=False`` disarms the stall watch, see
    :func:`resolve_divergence_guard`).

    ``state`` is ``(w,)`` or ``(w, alpha)``; ``round_fn(t, state) -> state``;
    ``eval_fn(state) -> (primal, gap_or_None, test_error_or_None)``.
    Returns (state, Trajectory).
    """
    traj = Trajectory(name, quiet=quiet)
    watch = _GapWatch(n_evals=stall_window(debug.debug_iter))
    for t in range(start_round, params.num_rounds + 1):
        state = round_fn(t, state)

        if debug.debug_iter > 0 and t % debug.debug_iter == 0:
            with _tracing.span("eval", algorithm=name, round=t):
                primal, gap, test_err = eval_fn(state)
            traj.log_round(t, primal=primal, gap=gap, test_error=test_err)
            if gap_target is not None and gap is not None and gap <= gap_target:
                traj.stopped = "target"
                break
            if (gap_target is not None and divergence_guard
                    and watch.update(gap)):
                traj.mark_diverged(t, watch.n)
                break

        if debug.chkpt_dir and debug.chkpt_iter > 0 and t % debug.chkpt_iter == 0:
            ckpt_lib.save(
                debug.chkpt_dir, name, t, state[0],
                state[1] if len(state) > 1 else None, seed=debug.seed,
                sched=state[-1] if len(state) > 2 else None,
                hist=state[2] if len(state) > 3 else None,
                gap=_last_gap(traj),
            )
    return state, traj


def drive_chunked(
    name: str,
    params: Params,
    debug: DebugParams,
    state: tuple,
    chunk_fn: Callable[[int, int, tuple], tuple],
    eval_fn: Callable[[tuple], tuple],
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
    chunk: int = 50,
    divergence_guard: bool = True,
    sigma_levels: Optional[tuple] = None,
    accel: Optional["AccelConfig"] = None,
):
    """Chunked variant of :func:`drive`: rounds run device-side in blocks of
    up to ``chunk`` via ``lax.scan`` (one dispatch per block instead of one
    per round), with blocks aligned to the ``debugIter`` evaluation cadence
    so the observable trajectory is identical to the per-round driver.

    ``chunk_fn(t0, c, state) -> state`` advances rounds t0..t0+c-1.

    ``sigma_levels`` (more than one): the run carries the σ′-anneal
    schedule in ``state[-1]`` (layout note at :data:`SCHED_LEN`); the
    stall watch then BACKS OFF σ′ in place — :func:`sched_host_step`, the
    host twin of the device loop's in-state update — instead of bailing
    out, and the final (safe K·γ) stage simply runs to its round budget:
    a scheduled run never reports DIVERGED, because its last rung is the
    paper-safe bound.
    """
    if chunk <= 0:
        raise ValueError(f"chunk must be positive, got {chunk}")
    anneal = sigma_levels is not None and len(sigma_levels) > 1
    traj = Trajectory(name, quiet=quiet)
    watch = _GapWatch(n_evals=stall_window(debug.debug_iter))
    t = start_round
    total = params.num_rounds
    ckpt_on = bool(debug.chkpt_dir) and debug.chkpt_iter > 0
    while t <= total:
        # advance to the next eval/checkpoint boundary (or ``chunk`` rounds,
        # whichever is nearest) so observable behavior matches the per-round
        # driver and same-size blocks share one compiled executable
        end = min(total, t + chunk - 1)
        if debug.debug_iter > 0:
            end = min(end, ((t - 1) // debug.debug_iter + 1) * debug.debug_iter)
        if ckpt_on:
            end = min(end, ((t - 1) // debug.chkpt_iter + 1) * debug.chkpt_iter)
        c = end - t + 1
        with _tracing.span("local_solve", algorithm=name, round=end,
                           t0=t, rounds=c):
            state = chunk_fn(t, c, state)
        t = end + 1

        if debug.debug_iter > 0 and end % debug.debug_iter == 0:
            with _tracing.span("eval", algorithm=name, round=end):
                primal, gap, test_err = eval_fn(state)
            anneal_on = (gap_target is not None and divergence_guard
                         and anneal)
            hit = (gap_target is not None and gap is not None
                   and gap <= gap_target)
            sigma_val = stage = stall_v = None
            backed = False
            if anneal_on:
                if hit:
                    # the σ′ this eval ran under: on a target hit the
                    # schedule update is moot — the run ends and the state
                    # is NOT advanced — but the emitted stall counter must
                    # still be the device twin's (the device loop runs the
                    # watch arithmetic before it notices done_tgt, with
                    # the backoff suppressed), so preview it un-committed
                    s = np.asarray(state[-1], dtype=np.float32)
                    gv = (np.float32(np.inf)
                          if gap is None or np.isnan(gap)
                          else np.float32(gap))
                    _, _, stl = _watch_update(np, gv, s[2], s[3], s[1],
                                              np.float32(STALL_REL))
                    stage = int(s[0])
                    stall_v = int(stl)
                else:
                    sched, backed = sched_host_step(
                        state[-1], gap, watch.n, len(sigma_levels))
                    state = _sched_replace(state, sched)
                    stage = int(sched[0])
                    stall_v = int(sched[1])
                sigma_val = sigma_levels[stage]
            if accel is not None and not hit:
                # accelerated outer loop: the restart/arm/bank step + Θ
                # step at the same eval boundary (accel_host_step is the
                # device loop's bit-twin; the σ′ update above already
                # committed, so state[-1] carries its fresh head).  An
                # armed jump executes at the head of the NEXT chunk
                # dispatch — the kernel has the shard data in scope.
                sched_a, restarted, staged = accel_host_step(
                    state[-1], gap, accel.n_theta, gap_target, seam=backed)
                state = _accel_replace(state, sched_a)
                _emit_accel_events(name, end, restarted,
                                   int(sched_a[A_RESTARTS]), staged,
                                   int(sched_a[A_TH_STAGE]), accel, quiet)
            traj.log_round(end, primal=primal, gap=gap, test_error=test_err,
                           sigma=sigma_val, sigma_stage=stage, stall=stall_v)
            if backed:
                _emit_backoff(name, end, sigma_levels, stage, quiet,
                              f"{name}: σ′ anneal — gap stalled for "
                              f"{watch.n} evals; backing off to "
                              f"σ′={sigma_levels[stage]:g} at round "
                              f"{end} (iterate kept, certificate exact)")
            if hit:
                traj.stopped = "target"
                break
            if (not anneal_on and gap_target is not None and divergence_guard
                    and watch.update(gap)):
                traj.mark_diverged(end, watch.n)
                break

        if ckpt_on and end % debug.chkpt_iter == 0:
            ckpt_lib.save(
                debug.chkpt_dir, name, end, state[0],
                state[1] if len(state) > 1 else None, seed=debug.seed,
                sched=state[-1] if len(state) > 2 else None,
                hist=state[2] if len(state) > 3 else None,
                gap=_last_gap(traj),
            )
    return state, traj


class ExecutableCache(OrderedDict):
    """Bounded LRU for jitted executables (VERDICT r4: the per-config
    caches grew forever in the long-lived bench process, which sweeps
    dozens of configs).  Eviction drops the Python reference; XLA frees
    the underlying executable when the last reference dies.  The cap is
    sized so no realistic single run ever evicts (a run touches a handful
    of configs) while a sweep stays bounded."""

    def __init__(self, cap: int = 64):
        super().__init__()
        self.cap = cap

    def get(self, key, default=None):
        v = super().get(key, default)
        if key in self:
            self.move_to_end(key)
        return v

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self.cap:
            self.popitem(last=False)


_DEVICE_RUNS: dict = ExecutableCache()

# cap on the resident (n_chunks, C, K, H) int32 index table per device-loop
# dispatch; runs needing more split into super-blocks (tests shrink this)
MAX_IDX_TABLE_BYTES = 256 << 20

# index-table size (ints) below which host-side sampling is cheap enough
# (~tens of ms) to do eagerly in one block — the geometric early-stop
# schedule only pays off above this
SMALL_TABLE_INTS = 4_000_000


class _Prefetch:
    """Run fn(*args) on a daemon thread; .result() joins and returns (or
    re-raises).  Used to overlap index sampling with device execution —
    daemon so an abandoned speculative block can never delay process
    exit."""

    def __init__(self, fn, *args):
        import threading

        self._out = self._err = None
        self._t = threading.Thread(target=self._run, args=(fn, args),
                                   daemon=True)
        self._t.start()

    def _run(self, fn, args):
        try:
            self._out = fn(*args)
        except BaseException as e:  # re-raised on the consumer side
            self._err = e

    def result(self):
        self._t.join()
        if self._err is not None:
            raise self._err
        return self._out


def _build_device_run(chunk_kernel, eval_kernel, gap_target, n_state,
                      mesh=None, stall_evals=STALL_EVALS,
                      divergence_guard=True, n_stages=0, stream=False,
                      accel=None):
    import functools

    import jax.numpy as jnp
    from jax import lax

    tgt = -jnp.inf if gap_target is None else float(gap_target)
    # divergence bail-out rides the loop carry only for gap-targeted runs
    # with the guard armed: fixed-round runs are the benchmark timing paths
    # and must execute exactly their round budget
    check_div = gap_target is not None and divergence_guard
    # n_stages > 1: σ′-anneal mode — the stall watch lives in the state
    # tuple's sched leaf (persisting across super-block dispatches and
    # into checkpoints), and firing BACKS OFF the schedule stage in place
    # instead of stopping the loop; the final stage is the safe K·γ bound,
    # so a scheduled run never stops "diverged" (see sched_host_step, the
    # host twin).
    anneal = check_div and n_stages > 1
    # every eval writes one [primal, gap, test_err, sigma_stage, stall,
    # theta_stage, restarts] row: cols 0-2 are the eval metrics, col 3 the
    # post-update σ′ ladder stage (NaN outside anneal mode), col 4 the
    # post-update stall-watch counter, col 5 the post-update Θ ladder
    # stage and col 6 the cumulative momentum-restart count (both NaN
    # outside --accel runs).  The row feeds the trajectory buffer AND —
    # with ``stream`` — an ordered io_callback that posts it to the
    # telemetry bus while the loop is still on device (side-effect-only:
    # nothing in the loop carry reads it, so a streaming run is
    # bit-identical to a non-streaming one — the fetch-fallback replays
    # the same buffer).
    n_cols = 7

    @functools.partial(jax.jit, donate_argnums=tuple(range(n_state)))
    def run(*args):
        state = args[:n_state]
        idxs_all, shard_arrays, test_arrays = args[n_state:]
        # idxs_all is a pytree (a bare (n_chunks, C, K, H) table, or a dict
        # also carrying a per-round (n_chunks, C) t leaf for η(t) solvers);
        # static at trace time — a different block length just retraces
        n_chunks = jax.tree.leaves(idxs_all)[0].shape[0]

        def cond(s):
            i, done_tgt, done_stall, stall, best, best_prev, state, traj = s
            return (i < n_chunks) & jnp.logical_not(done_tgt | done_stall)

        def body(s):
            i, done_tgt, done_stall, stall, best, best_prev, state, traj = s
            chunk = jax.tree.map(lambda a: a[i], idxs_all)
            state = chunk_kernel(state, chunk, shard_arrays)
            metrics = eval_kernel(state, shard_arrays, test_arrays)
            done_tgt = metrics[1] <= tgt
            nanv = jnp.asarray(jnp.nan, metrics.dtype)
            if anneal:
                # in-state schedule/watch update (float32, exactly the
                # sched_host_step arithmetic): a fired window at a
                # non-final stage bumps the stage — the NEXT chunk's
                # kernel reads it and runs the backed-off σ′ — and
                # resets the watch; at the final (safe) stage the watch
                # is inert and the run continues to target or budget
                sched = state[-1]
                gv = jnp.where(jnp.isnan(metrics[1]), jnp.inf,
                               metrics[1]).astype(jnp.float32)
                stg, stl, bst, bpv = sched[0], sched[1], sched[2], sched[3]
                bst, bpv, stl = _watch_update(jnp, gv, bst, bpv, stl,
                                              jnp.float32(STALL_REL))
                fired = stl >= jnp.float32(stall_evals)
                bo = (fired & (stg < jnp.float32(n_stages - 1))
                      & jnp.logical_not(done_tgt))
                inf32 = jnp.float32(jnp.inf)
                stg = jnp.where(bo, stg + 1, stg)
                stl = jnp.where(bo, jnp.float32(0), stl)
                bst = jnp.where(bo, inf32, bst)
                bpv = jnp.where(bo, inf32, bpv)
                head = jnp.stack([stg, stl, bst, bpv, sched[4]])
                state = (*state[:-1],
                         jnp.concatenate([head, sched[SCHED_LEN:]])
                         if accel is not None else head)
                extra = jnp.stack([stg.astype(metrics.dtype),
                                   stl.astype(metrics.dtype)])
            elif check_div:
                # windowed no-improvement watch (the _GapWatch twin): NaN
                # gaps (primal-only eval) map to +inf, leaving best — and
                # the always-true inf <= rel·inf reset — untouched
                gv = jnp.where(jnp.isnan(metrics[1]),
                               jnp.asarray(jnp.inf, best.dtype), metrics[1])
                best, best_prev, stall = _watch_update(
                    jnp, gv, best, best_prev, stall, STALL_REL)
                # the target wins a tie (the host drivers check that order)
                done_stall = (stall >= stall_evals) & jnp.logical_not(done_tgt)
                extra = jnp.stack([nanv, stall.astype(metrics.dtype)])
            else:
                extra = jnp.stack([nanv, jnp.zeros((), metrics.dtype)])
            if accel is not None:
                # accelerated outer loop: the per-eval restart/arm/bank +
                # Θ-schedule update, in-state (the accel_host_step twin —
                # identical f32 arithmetic).  State-changing ACTIONS are
                # suppressed on a target hit (the host drivers stop
                # without committing), matching the σ′ backoff policy;
                # the watch arithmetic itself commits either way.  An
                # armed jump executes at the head of the next chunk
                # (solvers/cocoa.py accel_kernel — the shard data the
                # correspondence update needs is in scope there).
                sched = state[-1]
                gv = jnp.where(jnp.isnan(metrics[1]), jnp.inf,
                               metrics[1]).astype(jnp.float32)
                hl, rst, lg = (sched[A_HIST], sched[A_RESTARTS],
                               sched[A_LASTGAP])
                restart = (gv > lg) & jnp.logical_not(done_tgt)
                arm = ((hl >= jnp.float32(2)) & jnp.logical_not(restart)
                       & jnp.logical_not(done_tgt))
                rst = jnp.where(restart, rst + 1, rst)
                hl = jnp.where(
                    done_tgt, hl,
                    jnp.where(arm, jnp.float32(0),
                              jnp.where(restart, jnp.float32(1),
                                        jnp.minimum(hl + 1,
                                                    jnp.float32(2)))))
                jmp = jnp.where(arm, jnp.float32(1), jnp.float32(0))
                lg = jnp.where(done_tgt, lg, gv)
                push = jnp.logical_not(arm) & jnp.logical_not(done_tgt)
                thst = sched[A_TH_STAGE]
                thstl, thb, thbp = (sched[A_TH_STALL], sched[A_TH_BEST],
                                    sched[A_TH_BPREV])
                if accel.n_theta > 1:
                    thb, thbp, thstl = _watch_update(
                        jnp, gv, thb, thbp, thstl, jnp.float32(THETA_REL))
                    tgt32 = jnp.float32(tgt)
                    near = gv <= jnp.float32(THETA_NEAR) * tgt32
                    fire = thstl >= jnp.float32(THETA_EVALS)
                    can = thst < jnp.float32(accel.n_theta - 1)
                    step = (near | fire) & can & jnp.logical_not(done_tgt)
                    thst = jnp.where(
                        step,
                        jnp.where(near, jnp.float32(accel.n_theta - 1),
                                  thst + 1),
                        thst)
                    inf32 = jnp.float32(jnp.inf)
                    thstl = jnp.where(step, jnp.float32(0), thstl)
                    thb = jnp.where(step, inf32, thb)
                    thbp = jnp.where(step, inf32, thbp)
                    # a stage advance caps the secant bank at the α just
                    # banked: pre-seam window displacements measured the
                    # old stage's round map (an armed jump stays armed —
                    # all its points predate the seam; base layout note)
                    hl = jnp.where(step, jnp.minimum(hl, jnp.float32(1)),
                                   hl)
                if anneal:
                    # a σ′ backoff committed above is a round-map seam
                    # exactly like a Θ stage advance: same bank cap
                    # (accel_host_step's ``seam`` is the host twin)
                    hl = jnp.where(bo, jnp.minimum(hl, jnp.float32(1)),
                                   hl)
                tail = jnp.stack([hl, jmp, rst, lg, thst, thstl, thb,
                                  thbp])
                # the bank action: unless this eval armed a jump (the
                # bank is then frozen for the kernel head to consume),
                # the current α joins as the newest window snapshot;
                # state is (w, alpha, hist, sched)
                hist_leaf = jnp.where(
                    push, jnp.stack([state[2][1], state[1]]), state[2])
                state = (state[0], state[1], hist_leaf,
                         jnp.concatenate([state[-1][:SCHED_LEN], tail]))
                extra2 = jnp.stack([thst.astype(metrics.dtype),
                                    rst.astype(metrics.dtype)])
            else:
                extra2 = jnp.stack([nanv, nanv])
            row = jnp.concatenate([metrics, extra, extra2])
            if stream:
                # side-effect-only event bridge: post this eval's row to
                # the host WHILE THE LOOP RUNS.  Ordered, so the host sees
                # evals in execution order; nothing downstream reads it,
                # so the compute is untouched (telemetry/events.py).
                from jax.experimental import io_callback

                from cocoa_tpu.telemetry import events as _tele

                io_callback(_tele._device_sink, None, i, row, ordered=True)
            traj = lax.dynamic_update_index_in_dim(traj, row, i, 0)
            return (i + jnp.int32(1), done_tgt, done_stall, stall, best,
                    best_prev, state, traj)

        traj0 = jnp.full((n_chunks, n_cols), jnp.nan, dtype=state[0].dtype)
        if mesh is not None:
            # metrics coming out of the shard_mapped eval carry the (Explicit)
            # mesh in their sharding type; the update target must match
            from jax.sharding import NamedSharding, PartitionSpec as P

            traj0 = lax.with_sharding_constraint(
                traj0, NamedSharding(mesh, P(None, None))
            )
        (i, done_tgt, done_stall, stall, best, best_prev, state,
         traj) = lax.while_loop(
            cond, body,
            (jnp.int32(0), jnp.asarray(False), jnp.asarray(False),
             jnp.int32(0),
             jnp.asarray(jnp.inf, dtype=state[0].dtype),
             jnp.asarray(jnp.inf, dtype=state[0].dtype), state, traj0),
        )
        return i, done_tgt, done_stall, state, traj

    return run


def drive_on_device(
    name: str,
    state: tuple,
    chunk_kernel: Callable,   # (state, idxs_ckh, shard_arrays) -> state, traceable
    eval_kernel: Callable,    # (state, shard_arrays, test_arrays) -> (3,) metrics
    idxs_all,                 # (n_chunks, C, K, H) int32, C = eval cadence
    shard_arrays,
    test_arrays=None,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
    cache_key=None,
    mesh=None,
    stall_evals: int = STALL_EVALS,
    divergence_guard: bool = True,
    sigma_levels: Optional[tuple] = None,
    accel: Optional["AccelConfig"] = None,
):
    """Fully device-resident outer driver: the ENTIRE run — every round,
    every ``debugIter`` evaluation, and the gap-target early-stop test — is
    one ``lax.while_loop`` inside one jit.  One dispatch, one host fetch.

    ``sigma_levels`` (more than one): σ′-anneal mode — the stall watch and
    schedule stage ride ``state[-1]`` (see :data:`SCHED_LEN`) and a fired
    window backs the σ′ stage off IN the loop instead of stopping it; the
    per-eval σ′ is decoded into the trajectory records.

    Rationale: the per-round device compute of these solvers is microseconds,
    so the wall-clock of the host-stepped drivers is pure host/device
    round-trip latency (~100ms per scalar fetch through a tunneled device —
    measured; see bench.py).  The reference has the same structure (driver
    JVM ⇄ executors every round, CoCoA.scala:39-63) and pays it; riding the
    whole loop device-side is the TPU-native answer, not a benchmark trick —
    the observable trajectory (eval cadence, stopping round, printed lines)
    is identical to :func:`drive_chunked`.

    ``idxs_all`` carries the eval cadence as its chunk axis (chunks of
    exactly C = debugIter rounds; the caller finishes any num_rounds % C
    remainder through the host-stepped path).  Trajectory metrics land in a
    preallocated device buffer, fetched once.

    Checkpointing is host-side by nature, so it is NOT done here — the
    wrapper :func:`drive_device_full` saves at its super-block boundaries
    (where this function returns and the state is host-reachable).

    ``cache_key``: any hashable token fully determining the closures
    (algorithm + params + flags + mesh + chunk geometry + gap target).  When
    given, the built jit executable is reused across calls — without it every
    call re-jits (closures have fresh identity) and pays ~1s of recompile.
    """
    from cocoa_tpu.telemetry import events as _tele

    c = int(jax.tree.leaves(idxs_all)[0].shape[1])
    tgt = gap_target
    n_state = len(state)
    n_stages = len(sigma_levels) if sigma_levels is not None else 0
    anneal = (tgt is not None and divergence_guard and n_stages > 1)

    # telemetry: with the bus active, each eval's row leaves the while_loop
    # through an ordered io_callback AS IT HAPPENS (single-device paths;
    # the callback placement under an explicit mesh is runtime-dependent,
    # so mesh runs use the fetch replay below).  Where ordered callbacks
    # are unsupported, the SAME tap replays the fetched buffer — identical
    # events, emitted at the end-of-run sync instead of live.
    from cocoa_tpu.analysis import sanitize as _sanitize

    bus = _tele.get_bus()
    emit = bus.active()
    stream = emit and mesh is None and _tele.io_callback_supported()
    tap = None
    if emit:
        # seed backoff/restart/Θ detection with the values this dispatch
        # ENTERS at (the sched leaf rides super-block boundaries), so a
        # resumed or later-block run never fabricates a transition event
        # on its first eval
        init_stage = init_theta = init_restarts = None
        if anneal or accel is not None:
            with _sanitize.intended_fetch("sched_stage"):
                s0 = np.asarray(state[-1])
            if anneal:
                init_stage = int(s0[0])
            if accel is not None:
                init_theta = int(s0[A_TH_STAGE])
                init_restarts = int(s0[A_RESTARTS])
        tap = _tele.DeviceTap(bus, name, start_round, c,
                              sigma_levels if anneal else None,
                              init_stage=init_stage,
                              theta_hs=(accel.theta_hs
                                        if accel is not None else None),
                              init_theta_stage=init_theta,
                              init_restarts=init_restarts)

    run_key = None if cache_key is None else (cache_key, stream)
    run = _DEVICE_RUNS.get(run_key) if run_key is not None else None
    if run is None:
        run = _build_device_run(
            chunk_kernel, eval_kernel, tgt, n_state, mesh=mesh,
            stall_evals=stall_evals, divergence_guard=divergence_guard,
            n_stages=n_stages, stream=stream, accel=accel,
        )
        if run_key is not None:
            _DEVICE_RUNS[run_key] = run

    # the sanitizer's device-loop contract (analysis/sanitize.py): from
    # dispatch to the sanctioned fetch, nothing crosses host↔device on
    # this thread.  Inert unless a strict sanitizer armed it.  The one
    # exception is the streaming dispatch itself: the ordered
    # io_callback's zero-byte effect token rides h2d with the args —
    # sanctioned tap machinery, not a leak.
    import contextlib as _ctx

    # the super-block span: one dispatch + the run's single host fetch —
    # the drive* ladder's host boundary.  Per-eval timing INSIDE the
    # device loop is unobservable by construction (one dispatch, one
    # sync; docs/DESIGN.md clock model), so this span is the finest
    # local-solve timing the device-resident path can honestly report.
    n_chunks = int(jax.tree.leaves(idxs_all)[0].shape[0])
    with _tracing.span("local_solve", algorithm=name, t0=start_round,
                       round=start_round - 1 + n_chunks * c,
                       rounds=n_chunks * c, cadence=c), \
            _sanitize.device_loop_guard(), \
            _tele.device_tap(tap if stream else None):
        with (_sanitize.allow_transfers() if stream
              else _ctx.nullcontext()):
            i, done_tgt, done_stall, state, traj_buf = run(
                *state, idxs_all, shard_arrays, test_arrays)
        # the single host sync of the whole run — marked as the
        # sanctioned fetch point, so the transfer-guard sanitizer
        # (analysis/sanitize.py) can disallow every OTHER device→host
        # path and production --metrics runs count it
        # (host_transfers_total: ~1 per super-block, never per round)
        with _sanitize.intended_fetch("device_loop_fetch"):
            n_done = int(i)
            stop_tgt = bool(done_tgt)
            stop_stall = bool(done_stall)
            traj_host = np.asarray(traj_buf[:n_done])
        if stream:
            # join the callback stream before leaving the tap context —
            # the fetch orders the computation, not the host callbacks
            jax.effects_barrier()
    if tap is not None and not stream:
        # fetch-fallback bridge: replay the buffer through the same tap
        # the stream path uses — same rows, same decode, same events
        for j in range(n_done):
            tap(j, traj_host[j])

    traj = Trajectory(name, quiet=quiet)
    prev_sigma = None
    for j in range(n_done):
        end = start_round - 1 + (j + 1) * c
        primal, gap, test_err = (float(v) for v in traj_host[j, :3])
        sigma = (sigma_levels[int(traj_host[j, 3])] if anneal else None)
        traj.log_round(
            end, primal=primal,
            # NaN slots mean "not applicable" (no dual state / no test set)
            # — decode to None exactly as objectives.evaluate does
            gap=None if np.isnan(gap) else gap,
            test_error=None if np.isnan(test_err) else test_err,
            # per-round wall-clock is unobservable here: the whole run is one
            # dispatch and one fetch — don't fabricate flat timestamps
            wall_time=None,
            sigma=sigma,
            # events for this run were already emitted by the tap (live
            # stream or fetch replay) — don't double-emit
            emit=False,
        )
        if (not quiet and anneal and prev_sigma is not None
                and sigma != prev_sigma):
            print(f"{name}: σ′ anneal — backed off to σ′={sigma:g} in the "
                  f"device loop at round {end} (iterate kept, certificate "
                  f"exact)")
        prev_sigma = sigma
    # classify from the device-side stop flags themselves (not from
    # n_done < n_chunks, which misses a guard fire on the FINAL chunk —
    # ADVICE r5): the while_loop carried exactly why it stopped
    if tgt is not None:
        if stop_stall:
            traj.stopped = "diverged"   # caller reports (with the round)
        elif stop_tgt:
            traj.stopped = "target"
    return state, traj


def drive_device_full(
    name: str,
    params: Params,
    debug: DebugParams,
    state: tuple,
    chunk_kernel: Callable,   # (state, idxs_ckh, shard_arrays) -> state
    eval_kernel: Callable,    # (state, shard_arrays, test_arrays) -> (3,)
    chunk_fn: Callable,       # (t0, c, state) -> state, host-stepped (jitted)
    eval_fn: Callable,        # (state) -> (primal, gap|None, test_err|None)
    sampler: "IndexSampler",
    shard_arrays,
    test_arrays=None,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
    cache_key=None,
    mesh=None,
    divergence_guard: bool = True,
    sigma_levels: Optional[tuple] = None,
    accel: Optional["AccelConfig"] = None,
    overlap_io: bool = False,
):
    """Cadence-aligned wrapper around :func:`drive_on_device`, usable by any
    solver whose round has the (state, idxs, shards) shape: host-steps the
    off-cadence head (a resumed ``start_round`` is usually not on a
    ``debugIter`` boundary), rides all full eval-cadence chunks device-side
    as one dispatch, then host-steps the sub-cadence tail (num_rounds %
    debugIter remainder, no eval — same observable behavior as
    :func:`drive_chunked`).  Returns (state, Trajectory).

    With ``sigma_levels`` (σ′ anneal) the stall watch rides ``state[-1]``
    ACROSS super-block boundaries — the host-twin watch below is then
    unnecessary (and skipped): the device loop's counters are the single
    source of truth, and the checkpoints written at block boundaries carry
    them, which is what makes a mid-schedule resume bit-identical."""
    if debug.debug_iter <= 0:
        raise ValueError(
            "the device loop requires debug_iter > 0 (the eval cadence is "
            "its chunk axis)"
        )
    c = debug.debug_iter
    anneal = (sigma_levels is not None and len(sigma_levels) > 1
              and gap_target is not None and divergence_guard)
    traj = Trajectory(name, quiet=quiet)
    watch = _GapWatch(n_evals=stall_window(debug.debug_iter))
    # ^ spans super-block boundaries (see block loop); inert under anneal
    # Device-loop checkpointing (reference anchor CoCoA.scala:59-62: the
    # production path checkpoints): state is host-reachable at every
    # super-block boundary (each drive_on_device return is the block's one
    # host sync), so save there — every chkptIter rounds, rounded UP to the
    # block boundary.  Block sizes are capped below so a boundary occurs at
    # least every ceil(chkptIter / debugIter) chunks.
    ckpt_on = bool(debug.chkpt_dir) and debug.chkpt_iter > 0
    last_saved = start_round - 1
    # --overlapComm on the device-resident path: the checkpoint WRITE —
    # the one host-side exchange this driver performs at super-block
    # boundaries — rides a daemon thread so its serialization + disk IO
    # overlaps the NEXT super-block's dispatch (and the index-table
    # prefetch already running alongside it) instead of extending the
    # boundary.  The state snapshot happens synchronously on THIS thread
    # as an OWNED host copy (a zero-copy view would alias the device
    # buffer the next dispatch donates — the same
    # nothing-shared-crosses-the-thread contract as
    # distributed._require_host_bytes), so the written bytes are
    # bit-identical to a synchronous save; only the write's timing
    # moves.  One write in flight at a time; the final join below makes
    # the function's completion imply every checkpoint landed.  Gated to
    # single-process runs by the callers: ckpt_lib.save's alpha
    # allgather is a collective that must not race a training dispatch.
    pending_io: list = []

    def _join_io():
        while pending_io:
            pending_io.pop().result()

    def maybe_ckpt(done_round):
        nonlocal last_saved
        if ckpt_on and done_round - last_saved >= debug.chkpt_iter:
            args = (debug.chkpt_dir, name, done_round, state[0],
                    state[1] if len(state) > 1 else None)
            kwargs = dict(
                seed=debug.seed,
                sched=state[-1] if len(state) > 2 else None,
                hist=state[2] if len(state) > 3 else None,
                gap=_last_gap(traj),
            )
            if overlap_io:
                _join_io()
                # copy=True is load-bearing: np.asarray of a CPU jax
                # array is a zero-copy VIEW of the device buffer, and
                # the very next dispatch DONATES that buffer — the
                # writer thread must serialize an owned snapshot, not a
                # view of memory the run is about to reuse
                args = tuple(np.array(a, copy=True) if a is not None
                             and not isinstance(a, (str, int)) else a
                             for a in args)
                kwargs = {k2: (np.array(v, copy=True)
                               if k2 in ("sched", "hist")
                               and v is not None else v)
                          for k2, v in kwargs.items()}
                pending_io.append(_Prefetch(
                    lambda a, kw: ckpt_lib.save(*a, **kw), args, kwargs))
            else:
                ckpt_lib.save(*args, **kwargs)
            last_saved = done_round

    def hit_target():
        return (
            gap_target is not None and traj.records
            and traj.records[-1].gap is not None
            and traj.records[-1].gap <= gap_target
        )

    t = start_round
    # head: advance to the absolute debugIter boundary so eval rounds stay
    # anchored to t % debugIter == 0 exactly like the host drivers
    head_end = min(params.num_rounds, ((t - 1) // c + 1) * c)
    if (t - 1) % c != 0 and head_end >= t:
        with _tracing.span("local_solve", algorithm=name, round=head_end,
                           t0=t, rounds=head_end - t + 1):
            state = chunk_fn(t, head_end - t + 1, state)
        t = head_end + 1
        if head_end % c == 0:
            with _tracing.span("eval", algorithm=name, round=head_end):
                primal, gap, test_err = eval_fn(state)
            sigma_val = stage = stall_v = None
            backed = False
            hit = (gap_target is not None and gap is not None
                   and gap <= gap_target)
            if anneal:
                # host-stepped eval feeds the SAME in-state watch the
                # device loop reads (sched_host_step is its bit-twin)
                sched, backed = sched_host_step(state[-1], gap, watch.n,
                                                len(sigma_levels))
                state = _sched_replace(state, sched)
                stage = int(sched[0])
                sigma_val = sigma_levels[stage]
                stall_v = int(sched[1])
            else:
                watch.update(gap)
            if accel is not None and not hit:
                sched_a, restarted, staged = accel_host_step(
                    state[-1], gap, accel.n_theta, gap_target, seam=backed)
                state = _accel_replace(state, sched_a)
                _emit_accel_events(name, head_end, restarted,
                                   int(sched_a[A_RESTARTS]), staged,
                                   int(sched_a[A_TH_STAGE]), accel, quiet)
            traj.log_round(head_end, primal=primal, gap=gap,
                           test_error=test_err, sigma=sigma_val,
                           sigma_stage=stage, stall=stall_v)
            if backed:
                _emit_backoff(name, head_end, sigma_levels, stage, quiet,
                              f"{name}: σ′ anneal — gap stalled for "
                              f"{watch.n} evals; backing off to "
                              f"σ′={sigma_levels[stage]:g} at round "
                              f"{head_end} (iterate kept, certificate "
                              f"exact)")
        maybe_ckpt(head_end)

    n_full = max(0, (params.num_rounds - (t - 1)) // c)
    if n_full > 0 and not hit_target():
        # bound the resident index table: one (n_chunks, C, K, H) int32 array
        # per dispatch.  With localIterFrac=1, H = n/K, so a whole-run table
        # is num_rounds × n ints — a memory cliff the chunked driver doesn't
        # have.  Split into super-blocks of at most ~256 MB of indices;
        # the early-stop test between blocks costs one host sync per block.
        chunk_ints = c * sampler.ints_per_round()
        max_block = max(1, MAX_IDX_TABLE_BYTES // (4 * chunk_ints))
        if ckpt_on:
            # a boundary (host sync + save opportunity) at least every
            # chkptIter rounds, rounded up to the chunk cadence
            max_block = min(max_block, max(1, -(-debug.chkpt_iter // c)))
        if gap_target is None or n_full * chunk_ints <= SMALL_TABLE_INTS:
            # no early stop possible (or the whole table is cheap anyway):
            # equal blocks → one executable, one host sync per ~256 MB
            n_blocks = -(-n_full // max_block)
            per_block = -(-n_full // n_blocks)
            g = per_block
        else:
            # a gap-targeted run may stop at a small fraction of num_rounds,
            # and host-side index sampling for rounds never executed is pure
            # waste (the whole-run table can cost seconds at epsilon scale).
            # Grow blocks geometrically in powers of two from a sampling-
            # cost-sized start — bounded distinct shapes, so the handful of
            # while-loop executables is reused across runs, and each block
            # costs one extra host sync (the early-stop check).
            per_block = None
            g = max(1, SMALL_TABLE_INTS // chunk_ints)
        sizes = []
        remaining = n_full
        while remaining > 0:
            b = min(per_block or g, max_block, remaining)
            g = min(g * 2, max_block)
            sizes.append(b)
            remaining -= b

        done = t - 1
        # one-ahead sampling WITH pre-staged index specs: block i+1's
        # tables are generated on a daemon host thread while the device
        # executes block i — hiding the numpy LCG cost behind device time
        # (at epsilon scale both are ~ms/round) — and the thread also
        # reshapes them to the (n_chunks, C, ...) chunk layout and commits
        # them to the device, so the table's h2d transfer overlaps the
        # previous block's execution instead of landing on the next
        # dispatch's critical path (a tunneled device moves these tables
        # at ~10 MB/s — see IndexSampler).  On early stop the in-flight
        # speculative block is abandoned — bounded waste, overlapped with
        # the final device block either way, and the daemon thread cannot
        # delay interpreter exit.
        start = done + 1

        def stage(t0, nb):
            flat = sampler.chunk_indices(t0, nb * c)
            reshaped = jax.tree.map(
                lambda a: a.reshape(nb, c, *a.shape[1:]), flat)
            if mesh is not None:
                # committing to the default device would conflict with
                # the mesh-sharded state at dispatch ("incompatible
                # devices"); on a mesh let jit place the tables as before
                return reshaped
            return jax.tree.map(jax.device_put, reshaped)

        fut = _Prefetch(stage, start, sizes[0])
        for bi, b in enumerate(sizes):
            idxs_all = fut.result()
            if bi + 1 < len(sizes):
                fut = _Prefetch(stage, start + b * c, sizes[bi + 1])
            state, dev_traj = drive_on_device(
                name, state, chunk_kernel, eval_kernel, idxs_all,
                shard_arrays, test_arrays, quiet=quiet,
                gap_target=gap_target, start_round=start,
                cache_key=cache_key, mesh=mesh, stall_evals=watch.n,
                divergence_guard=divergence_guard,
                sigma_levels=sigma_levels, accel=accel,
            )
            traj.records.extend(dev_traj.records)
            if dev_traj.records:
                # the block's single host sync just happened — stamp it on
                # the block's final record.  Rounds inside the block keep
                # wall_time=None (genuinely unobservable: one dispatch, one
                # fetch); these block-boundary stamps give the benchmark
                # JSONL its monotone (round, time) pairs without fabricating
                # flat per-round times.
                traj.records[-1].wall_time = traj.elapsed()
            # rounds actually executed: a gap-target run can stop the
            # device while_loop mid-block, after fewer than b chunks —
            # each executed chunk logged exactly one eval record.  Saving
            # the nominal block end would overstate the checkpoint round
            # and a later --resume would skip never-executed rounds.
            done = start - 1 + len(dev_traj.records) * c
            start += b * c
            maybe_ckpt(done)
            # target first: a block can cross the target on a later eval
            # than the one that trips the stall window — reaching the
            # target always wins (the host drivers check in this order too)
            if hit_target():
                traj.stopped = "target"
                break
            # the in-loop watch state is per-block; the host twin spans
            # block boundaries (geometric blocks start with < STALL_EVALS
            # evals, where the in-loop watch alone could never fire).
            # Under σ′ anneal the watch rides state[-1] across blocks
            # instead, and a fired window backs off rather than stops —
            # so there is no twin to run and nothing to mark diverged.
            diverged = not anneal and divergence_guard and (
                dev_traj.stopped == "diverged"
                or any(watch.update(r.gap) for r in dev_traj.records)
            )
            if gap_target is not None and diverged:
                traj.mark_diverged(done, watch.n)
                break
        t = done + 1

    rem = params.num_rounds - (t - 1)
    if rem > 0 and not hit_target() and traj.stopped is None:
        # sub-cadence tail: run it, no eval (off the debugIter cadence)
        with _tracing.span("local_solve", algorithm=name,
                           round=params.num_rounds, t0=t, rounds=rem):
            state = chunk_fn(t, rem, state)
        maybe_ckpt(params.num_rounds)
    # every overlapped checkpoint write must have LANDED before this
    # driver reports done (a caller may read/validate the files next)
    _join_io()
    return state, traj


def align_alpha(alpha_init, ds: ShardedDataset, dtype):
    """(K, n_shard) alpha from a restored ``alpha_init``, zero-padding the
    shard axis when the checkpoint predates a larger padded ``n_shard``
    (rows ≥ counts[k] are never sampled, so zero padding is exact).  A clear
    error beats the opaque XLA shape mismatch it would otherwise hit."""
    import jax.numpy as jnp

    a = jnp.array(alpha_init, dtype=dtype, copy=True)
    if a.ndim != 2 or a.shape[0] != ds.k:
        raise ValueError(
            f"alpha_init shape {a.shape} is incompatible with K={ds.k} shards"
        )
    if a.shape[1] < int(ds.counts.max()) or a.shape[1] > ds.n_shard:
        raise ValueError(
            f"alpha_init has {a.shape[1]} rows per shard but the dataset "
            f"shards to counts={ds.counts.tolist()} (n_shard={ds.n_shard}) — "
            f"was the checkpoint written with different data or numSplits?"
        )
    if a.shape[1] < ds.n_shard:
        a = jnp.pad(a, ((0, 0), (0, ds.n_shard - a.shape[1])))
    return a


def check_shards(ds: ShardedDataset) -> None:
    """Reject empty shards up front: the reference crashes inside the task
    (``nextInt(0)``) when numSplits > rows; we fail with a clear message."""
    if np.any(ds.counts <= 0):
        raise ValueError(
            f"every shard needs at least one example; shard sizes are "
            f"{ds.counts.tolist()} (n={ds.n} over K={ds.k} shards) — "
            f"lower numSplits"
        )


class IndexSampler:
    """Per-round local-coordinate sampling, in one of three modes.

    - ``reference``: java.util.Random replay — identical draws to the Scala
      code per (seed+t, n_local), correlated across equal-size shards
      exactly as the reference is (CoCoA.scala:45,144).
    - ``jax``: stateless counter-hash draws keyed per (seed, round, shard,
      position) — decorrelated across shards (statistical improvement, not
      reference-faithful).  NOT jax.random: batched-key threefry costs
      ~100 ms per dispatch through this device path (utils/prng.py module
      note); the mode's contract is decorrelation, not a specific stream.
    - ``permuted``: random reshuffling — each shard walks a fresh
      per-epoch permutation of its rows, so every coordinate is touched
      exactly once per n_local draws.  With-replacement sampling leaves
      ~1/e of the duals untouched per epoch-equivalent, and untouched
      duals stall the gap; measured on the epsilon config this reaches
      the 1e-4 duality gap in 20 rounds vs 100 (the decorrelation alone
      accounts for 100→90 — the reshuffle is the win).  A documented
      deviation from the reference's with-replacement draws
      (CoCoA.scala:151); the duality-gap certificate is computed exactly
      from (w, α) and stays valid under ANY index stream, which is what
      makes this safe to flag-gate.

    **Where the tables are generated** (``device`` attr): index draws are
    data-independent, so generation can happen anywhere; what matters on a
    tunneled TPU is that the tables NOT cross the host↔device link — with
    multi-GB shards resident, h2d collapses to ~10 MB/s and the per-round
    (K, H) table upload costs more than the entire fused kernel round
    (measured round 4; the reference itself draws inside each partition's
    task, CoCoA.scala:144).  With ``device=True`` (the production default —
    solvers auto-enable it for the chunked/device-loop paths)
    :meth:`chunk_indices` returns a tiny ``{"t": (C,) int32}`` spec and the
    solver's jitted chunk generates the (C, K, H) tables in-jit via
    :meth:`tables_from_ts` — bit-identical to the host tables for every
    mode (reference replay validated in tests/test_device_sampling.py; jax
    and permuted draw from the same counter-hash / Feistel-bijection
    streams (utils/prng.py) whether expanded on host or in-jit — host ≡
    device because it is literally one integer-arithmetic implementation,
    not because any PRNG library is backend-invariant)."""

    MODES = ("reference", "jax", "permuted")

    def __init__(self, mode: str, seed: int, h: int, counts: np.ndarray,
                 device: bool = False):
        if mode not in self.MODES:
            raise ValueError(f"rng mode must be one of {self.MODES}, got {mode!r}")
        self.mode = mode
        self.seed = seed
        self.h = h
        self.counts = np.asarray(counts)
        self.device = device
        if np.any(self.counts <= 0):
            raise ValueError(
                f"all shards must be non-empty, got sizes {self.counts}")

    def cache_token(self):
        """Hashable identity of the in-jit generation closure (device mode
        bakes the sampling configuration into the executable)."""
        return (self.mode, self.seed, self.h, tuple(self.counts.tolist()),
                self.device)

    def device_capable(self, max_round: int) -> bool:
        """Whether in-jit generation is exact for this run.  Permuted mode
        walks global steps (t-1)·H..; int32 arithmetic bounds both it and
        the host twin (one implementation), so an overflowing config is
        rejected eagerly rather than degraded."""
        from cocoa_tpu.utils.prng import device_replay_ok

        if self.mode == "reference":
            return device_replay_ok(self.seed, max_round)
        if self.mode == "permuted":
            return (max_round + 1) * self.h < (1 << 31)
        return True

    def ints_per_round(self) -> int:
        """Index-table ints crossing the host↔device link per round — what
        the device-loop driver sizes its super-blocks by."""
        k = self.counts.shape[0]
        return 1 if self.device else k * self.h

    def round_indices(self, t: int) -> jax.Array:
        """(K, H) int32 index table for round t (1-based, as the reference).
        Always concrete (host-stepped drivers)."""
        return self._tables(t, 1)[0]

    def chunk_indices(self, t0: int, c: int):
        """Tables for rounds t0..t0+c-1: a concrete (C, K, H) int32 array,
        or — in device mode — the ``{"t": (C,) int32}`` spec the solver
        kernels expand in-jit via :meth:`tables_from_ts`."""
        import jax.numpy as jnp

        if self.device:
            return {"t": jnp.arange(t0, t0 + c, dtype=jnp.int32)}
        return self._tables(t0, c)

    def _tables(self, t0: int, c: int) -> jax.Array:
        import jax.numpy as jnp

        if self.mode == "reference":
            # numpy replay (handles the full java long seed range)
            tab = sample_indices_per_shard(
                self.seed, range(t0, t0 + c), self.h, self.counts
            )  # (K, C, H)
            return jnp.asarray(np.swapaxes(tab, 0, 1))
        # jax/permuted: one counter-hash/Feistel implementation for host
        # and device tables, so eager-vs-jit agree bitwise by construction
        return self.tables_from_ts(jnp.arange(t0, t0 + c, dtype=jnp.int32))

    def tables_from_ts(self, ts) -> jax.Array:
        """Traceable: (C,) int32 round numbers -> (C, K, H) int32 tables.
        The in-jit twin of :meth:`_tables`; rounds must be consecutive
        (chunk calls always are — the permuted stream slices on ts[0])."""
        from cocoa_tpu.utils import prng

        if self.mode == "reference":
            return prng.device_sample_per_shard(self.seed, ts, self.h,
                                                self.counts)
        if self.mode == "permuted":
            return prng.permuted_tables(self.seed, ts, self.h, self.counts)
        return prng.hash_tables(self.seed, ts, self.h, self.counts)


def resolve_sampling(sampling: str, sampler: "IndexSampler",
                     max_round: int) -> bool:
    """Resolve the ``--sampling`` flag to the sampler's ``device`` switch.

    ``auto`` (default) generates index tables in-jit on the device whenever
    the mode's in-jit arithmetic is exact for this run — the production
    choice: with multi-GB shards resident, a tunneled device moves index
    tables at ~10 MB/s, costing more per round than the kernels themselves
    (see IndexSampler).  ``host`` forces concrete host-side tables (the
    validation/debug path); ``device`` asserts in-jit generation is usable.
    """
    if sampling not in ("auto", "device", "host"):
        raise ValueError(
            f"sampling must be auto|device|host, got {sampling!r}")
    capable = sampler.device_capable(max_round)
    if not capable and sampler.mode == "permuted":
        # permuted has ONE implementation (host tables are the same int32
        # jnp stream evaluated eagerly), so an overflowing config has no
        # exact fallback — reject it eagerly rather than silently wrap
        raise ValueError(
            f"rng=permuted overflows int32 global-step arithmetic for "
            f"num_rounds={max_round}, localIters={sampler.h} "
            f"((rounds+1)*H must stay below 2^31); split the run via "
            f"checkpoint/resume or lower localIterFrac"
        )
    if sampling == "host":
        return False
    if sampling == "device" and not capable:
        raise ValueError(
            f"device sampling is not exact for rng={sampler.mode!r} with "
            f"seed={sampler.seed}, num_rounds={max_round} (int32 range); "
            f"use --sampling=host"
        )
    return capable


def drive_device_paths(
    name: str,
    params: Params,
    debug: DebugParams,
    state: tuple,
    chunk_kernel: Callable,   # (state, xs, shard_arrays) -> state, traceable
    chunk_fn: Callable,       # (t0, c, state) -> state, host-stepped (jitted)
    eval_fn: Callable,
    sampler,
    shard_arrays,
    *,
    alpha_in_state: bool,
    mesh=None,
    test_ds=None,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
    scan_chunk: int = 0,
    device_loop: bool = False,
    cache_key=None,
    eval_kernel=None,
    divergence_guard: bool = True,
    sigma_levels: Optional[tuple] = None,
    accel: Optional["AccelConfig"] = None,
    overlap_io: bool = False,
):
    """The scan_chunk / device_loop dispatch shared by every solver: builds
    the fused eval kernel (dual state iff ``alpha_in_state``; overridable
    for non-classification objectives) and routes to
    :func:`drive_device_full` or :func:`drive_chunked`.  Returns
    (state, Trajectory)."""
    from cocoa_tpu.evals import objectives

    if device_loop:
        test_arrays = test_ds.shard_arrays() if test_ds is not None else None
        test_n = test_ds.n if test_ds is not None else 0

        if eval_kernel is None:
            def eval_kernel(state, shard_arrays, test_arrays):
                alpha = state[1] if alpha_in_state else None
                return objectives.eval_metrics(
                    state[0], alpha, shard_arrays, params.lam, params.n,
                    mesh=mesh, test_shard_arrays=test_arrays, test_n=test_n,
                    loss=params.loss, smoothing=params.smoothing,
                )

        return drive_device_full(
            name, params, debug, state, chunk_kernel, eval_kernel, chunk_fn,
            eval_fn, sampler, shard_arrays, test_arrays, quiet=quiet,
            gap_target=gap_target, start_round=start_round,
            cache_key=None if cache_key is None
            else (*cache_key, test_n, divergence_guard),
            mesh=mesh, divergence_guard=divergence_guard,
            sigma_levels=sigma_levels, accel=accel,
            overlap_io=overlap_io,
        )
    return drive_chunked(
        name, params, debug, state, chunk_fn, eval_fn, quiet=quiet,
        gap_target=gap_target, start_round=start_round, chunk=scan_chunk,
        divergence_guard=divergence_guard, sigma_levels=sigma_levels,
        accel=accel,
    )


# --- fleet: the vmapped drive* ladder (--fleet, round 18) -------------------
#
# T independent tenants (per-tenant λ / dataset / gap target) run as ONE
# compiled round loop: every solver-state leaf, the sched vector, the
# accel hist bank, and the gap watch grow a leading T axis, the
# per-tenant chunk/eval kernels ride a jax.vmap over that axis, and the
# whole fleet anneals, extrapolates, and certifies inside one
# lax.while_loop — one dispatch, one compile, one fetch for the entire
# fleet.  Certified tenants MASK OUT of the update: the chunk still
# computes their lane (a masked lane, not a dispatch), but a lane-wise
# jnp.where discards its result so a finished tenant's (w, α, hist,
# sched) is bitwise-frozen from the eval that certified it, and the
# loop's stop predicate is the conjunction of per-tenant done flags.
#
# Independence argument: the adding-vs-averaging machinery
# (arXiv:1502.03508) makes every tenant's σ′/γ scaling self-contained —
# no cross-tenant term exists anywhere in the round — and the general
# CoCoA framework (arXiv:1611.02189) is local-solver/objective agnostic,
# so the per-tenant duality-gap certificate is exactly the solo
# certificate evaluated on that lane's (w, α).  A T=1 fleet run is
# bit-identical to the solo path (pinned by tests/test_fleet.py): the
# per-tenant kernels receive λ·n and σ′ as TRACED scalars carrying
# exactly the float32 values the solo path bakes in as constants, and
# IEEE arithmetic does not distinguish the two.
#
# The σ′ anneal ladder lowers from branch selection to data here: the
# solo path statically specializes one chunk kernel per σ′ stage and
# lax.switches between them, but a vmapped switch with a batched index
# executes EVERY branch for EVERY lane — so the fleet kernel instead
# reads σ′ = levels[stage_t] from the (L,) ladder array (same f32
# values, same update arithmetic) and one kernel serves every stage of
# every tenant.  Docs: docs/DESIGN.md §16 "Fleet execution model".

FLEET_N_COLS = 7   # the solo traj row layout, per tenant


def _build_fleet_run(chunk_kernel, eval_kernel, n_state,
                     per_tenant_idxs=False, stall_evals=STALL_EVALS,
                     divergence_guard=True, n_stages=0, accel=False,
                     jump_kernel=None, lane_exec="vmap"):
    """The fleet twin of :func:`_build_device_run`: one jitted
    while_loop advancing every tenant lane per chunk.

    ``chunk_kernel(state_t, idxs_ckh, data_t, scal_t) -> state_t`` and
    ``eval_kernel(state_t, data_t, scal_t) -> (3,)`` are PER-TENANT
    traceables (solvers/fleet.py builds them with traced λ·n/σ′ from the
    ``scal_t`` leaves); the batching over T happens here.

    ``lane_exec`` picks how tenant lanes execute inside the loop:

    - ``"vmap"`` (the throughput default): the hot chunk path batches
      across lanes — on CPU the per-step row ops vectorize across the
      whole fleet.  Batched reductions may round differently from the
      solo executable by ~1 ulp at T > 1 (a batched dot's accumulation
      order is the backend's choice), so per-lane trajectories match
      solo to ulps, bit-exactly at T=1.
    - ``"map"`` — lanes run sequentially via ``lax.map`` inside the SAME
      single compiled while_loop: each lane's body is the solo HLO
      exactly, so every lane is bit-identical to its solo run at ANY T
      (the parity/debug mode; pinned by tests/test_fleet.py).  The
      compile/dispatch amortization — the fleet's headline win — is
      identical in both modes.

    The EVAL (and the accel ``jump_kernel``, when given) always ride
    ``lax.map``: the certificate reduction is the bit-sensitive piece,
    and per-lane evaluation keeps it the solo computation.  The watch
    vectors (done/stall/best/cert) are explicit donated arguments so
    super-block chaining carries them across dispatches without a
    recompile."""
    import functools

    import jax.numpy as jnp
    from jax import lax

    check_div = divergence_guard
    anneal = check_div and n_stages > 1
    idx_axis = 1 if per_tenant_idxs else None

    @functools.partial(jax.jit, donate_argnums=tuple(range(7 + n_state)))
    def run(done_tgt0, done_stall0, stall0, best0, best_prev0, cert0,
            stall_chunk0, *args):
        state0 = args[:n_state]
        idxs_all, shard_arrays, scal, tgts = args[n_state:]
        n_chunks = jax.tree.leaves(idxs_all)[0].shape[0]
        t_fleet = tgts.shape[0]

        from cocoa_tpu.parallel.fanout import lane_fanout

        vchunk = lane_fanout(chunk_kernel, lane_exec=lane_exec,
                             idx_axis=idx_axis)

        def veval(state, data, scal_):
            return lax.map(lambda a: eval_kernel(*a), (state, data, scal_))

        def vjump(state, data, scal_):
            return lax.map(lambda a: jump_kernel(*a), (state, data, scal_))

        def bmask(flag, like):
            return flag.reshape(flag.shape + (1,) * (like.ndim - 1))

        def cond(s):
            i, done_tgt, done_stall = s[0], s[1], s[2]
            return ((i < n_chunks)
                    & jnp.logical_not(jnp.all(done_tgt | done_stall)))

        def body(s):
            (i, done_tgt, done_stall, stall, best, best_prev, cert,
             stall_chunk, state, traj) = s
            done0 = done_tgt | done_stall
            if jump_kernel is not None:
                # the accel secant jump, per lane at the chunk head —
                # the solo accel_kernel's position and arithmetic (an
                # unarmed or done lane's jump is the identity)
                state = vjump(state, shard_arrays, scal)
            chunk = jax.tree.map(lambda a: a[i], idxs_all)
            new_state = vchunk(state, chunk, shard_arrays, scal)
            # finished-tenant masking: a done lane's whole state is
            # bitwise-frozen — the lane still computes, its result is
            # discarded; live lanes see exactly the solo update
            state = tuple(
                jnp.where(bmask(done0, nw), o, nw)
                for o, nw in zip(state, new_state))
            metrics = veval(state, shard_arrays, scal)   # (T, 3)
            gap = metrics[:, 1]
            # the solo body's done_tgt, lane-wise (a frozen lane's gap
            # re-evaluates identically, so done_now stays true for it)
            done_now = (gap <= tgts) | done0
            newly = (gap <= tgts) & jnp.logical_not(done0)
            nans = jnp.full((t_fleet,), jnp.nan, metrics.dtype)
            if anneal:
                # per-tenant σ′ schedule/watch — the solo anneal branch
                # with every scalar a (T,) column; frozen lanes keep
                # their sched head bitwise (the watch must not keep
                # counting a lane that stopped updating)
                sched = state[-1]
                gv = jnp.where(jnp.isnan(gap), jnp.inf,
                               gap).astype(jnp.float32)
                stg, stl = sched[:, 0], sched[:, 1]
                bst, bpv = sched[:, 2], sched[:, 3]
                bst, bpv, stl = _watch_update(jnp, gv, bst, bpv, stl,
                                              jnp.float32(STALL_REL))
                fired = stl >= jnp.float32(stall_evals)
                bo = (fired & (stg < jnp.float32(n_stages - 1))
                      & jnp.logical_not(done_now))
                inf32 = jnp.float32(jnp.inf)
                stg = jnp.where(bo, stg + 1, stg)
                stl = jnp.where(bo, jnp.float32(0), stl)
                bst = jnp.where(bo, inf32, bst)
                bpv = jnp.where(bo, inf32, bpv)
                head = jnp.stack([stg, stl, bst, bpv, sched[:, 4]],
                                 axis=1)
                sched_new = (jnp.concatenate(
                    [head, sched[:, SCHED_LEN:]], axis=1)
                    if accel else head)
                sched_new = jnp.where(done0[:, None], sched, sched_new)
                state = (*state[:-1], sched_new)
                extra = jnp.stack([stg, stl], axis=1).astype(metrics.dtype)
            elif check_div:
                # per-tenant no-improvement watch; only gap-targeted
                # lanes can stop diverged (the solo guard is tied to a
                # target's existence — lane-wise here)
                gv = jnp.where(jnp.isnan(gap),
                               jnp.asarray(jnp.inf, best.dtype), gap)
                bst, bpv, stl = _watch_update(jnp, gv, best, best_prev,
                                              stall, STALL_REL)
                best = jnp.where(done0, best, bst)
                best_prev = jnp.where(done0, best_prev, bpv)
                stall = jnp.where(done0, stall, stl)
                has_tgt = tgts > -jnp.inf
                newly_stalled = ((stall >= stall_evals) & has_tgt
                                 & jnp.logical_not(done_now)
                                 & jnp.logical_not(done_stall))
                done_stall = done_stall | newly_stalled
                # the eval a lane stalled OUT at (1-based chunk index;
                # 0 = never) — what lets the host decode a per-eval
                # still-training count without re-deriving the watch
                stall_chunk = jnp.where(newly_stalled, i + jnp.int32(1),
                                        stall_chunk)
                extra = jnp.stack([nans, stall.astype(metrics.dtype)],
                                  axis=1)
            else:
                extra = jnp.stack([nans, jnp.zeros_like(nans)], axis=1)
            if accel:
                # the per-tenant secant window bookkeeping — the solo
                # accel branch with (T,) columns.  done_now gates every
                # action exactly as the solo done_tgt does, which is
                # also what freezes an already-done lane's tail.  The
                # fleet runs the fixed-Θ ladder (n_theta == 1): the Θ
                # slots ride unchanged.
                sched = state[-1]
                gv = jnp.where(jnp.isnan(gap), jnp.inf,
                               gap).astype(jnp.float32)
                hl, rst, lg = (sched[:, A_HIST], sched[:, A_RESTARTS],
                               sched[:, A_LASTGAP])
                restart = (gv > lg) & jnp.logical_not(done_now)
                arm = ((hl >= jnp.float32(2)) & jnp.logical_not(restart)
                       & jnp.logical_not(done_now))
                rst = jnp.where(restart, rst + 1, rst)
                hl = jnp.where(
                    done_now, hl,
                    jnp.where(arm, jnp.float32(0),
                              jnp.where(restart, jnp.float32(1),
                                        jnp.minimum(hl + 1,
                                                    jnp.float32(2)))))
                jmp = jnp.where(arm, jnp.float32(1), jnp.float32(0))
                lg = jnp.where(done_now, lg, gv)
                push = jnp.logical_not(arm) & jnp.logical_not(done_now)
                if anneal:
                    # a committed σ′ backoff is a round-map seam: same
                    # bank cap as the solo device loop
                    hl = jnp.where(bo, jnp.minimum(hl, jnp.float32(1)),
                                   hl)
                tail = jnp.stack(
                    [hl, jmp, rst, lg, sched[:, A_TH_STAGE],
                     sched[:, A_TH_STALL], sched[:, A_TH_BEST],
                     sched[:, A_TH_BPREV]], axis=1)
                hist_leaf = jnp.where(
                    push[:, None, None, None],
                    jnp.stack([state[2][:, 1], state[1]], axis=1),
                    state[2])
                state = (state[0], state[1], hist_leaf,
                         jnp.concatenate([sched[:, :SCHED_LEN], tail],
                                         axis=1))
                extra2 = jnp.stack(
                    [sched[:, A_TH_STAGE], rst],
                    axis=1).astype(metrics.dtype)
            else:
                extra2 = jnp.stack([nans, nans], axis=1)
            done_tgt = done_tgt | newly
            cert = jnp.where(newly, i + jnp.int32(1), cert)
            row = jnp.concatenate([metrics, extra, extra2], axis=1)
            traj = lax.dynamic_update_index_in_dim(traj, row, i, 0)
            return (i + jnp.int32(1), done_tgt, done_stall, stall, best,
                    best_prev, cert, stall_chunk, state, traj)

        traj0 = jnp.full((n_chunks, t_fleet, FLEET_N_COLS), jnp.nan,
                         dtype=state0[0].dtype)
        (i, done_tgt, done_stall, stall, best, best_prev, cert,
         stall_chunk, state, traj) = lax.while_loop(
            cond, body,
            (jnp.int32(0), done_tgt0, done_stall0, stall0, best0,
             best_prev0, cert0, stall_chunk0, state0, traj0))
        return (i, done_tgt, done_stall, stall, best, best_prev, cert,
                stall_chunk, state, traj)

    return run


class FleetCarry:
    """The per-tenant watch vectors chained across fleet super-block
    dispatches (all donated run arguments; fresh via :meth:`init`).
    ``cert_chunk`` / ``stall_chunk`` record the 1-based eval a lane
    certified / stalled out at (0 = never) — what the host decodes
    per-eval active-lane counts and per-tenant outcomes from."""

    def __init__(self, done_tgt, done_stall, stall, best, best_prev,
                 cert_chunk, stall_chunk):
        self.done_tgt = done_tgt
        self.done_stall = done_stall
        self.stall = stall
        self.best = best
        self.best_prev = best_prev
        self.cert_chunk = cert_chunk
        self.stall_chunk = stall_chunk

    @classmethod
    def init(cls, t: int, dtype):
        import jax.numpy as jnp

        return cls(
            jnp.zeros((t,), bool), jnp.zeros((t,), bool),
            jnp.zeros((t,), jnp.int32),
            jnp.full((t,), jnp.inf, dtype),
            jnp.full((t,), jnp.inf, dtype),
            jnp.zeros((t,), jnp.int32), jnp.zeros((t,), jnp.int32))

    def args(self):
        return (self.done_tgt, self.done_stall, self.stall, self.best,
                self.best_prev, self.cert_chunk, self.stall_chunk)


def drive_fleet_on_device(
    name: str,
    state: tuple,
    chunk_kernel: Callable,   # per-tenant: (state, idxs_ckh, data, scal)
    eval_kernel: Callable,    # per-tenant: (state, data, scal) -> (3,)
    idxs_all,                 # (n_chunks, C, [T,] K, H) int32 tables
    shard_arrays,             # (T, K, ...) pytree
    scal,                     # (T,) per-tenant scalar pytree (λ·n, ...)
    gap_targets,              # (T,) targets in state dtype, -inf = none
    quiet: bool = False,
    start_round: int = 1,
    cache_key=None,
    stall_evals: int = STALL_EVALS,
    divergence_guard: bool = True,
    n_stages: int = 0,
    accel: bool = False,
    per_tenant_idxs: bool = False,
    carry: Optional["FleetCarry"] = None,
    jump_kernel: Optional[Callable] = None,
    lane_exec: str = "vmap",
):
    """Dispatch one fleet super-block: every chunk, every per-tenant
    eval, the per-tenant anneal/accel schedules, the per-tenant gap
    watch, and the all-lanes-done stop test ride ONE ``lax.while_loop``
    in one jit — one dispatch and one host fetch for the whole fleet.

    Returns ``(state, carry, n_done, traj_host)``: ``carry`` holds the
    per-tenant done/watch/cert vectors (chainable into the next block —
    the executable is cached per ``cache_key``, so a multi-block fleet
    still compiles exactly once), ``traj_host`` is the fetched
    ``(n_done, T, FLEET_N_COLS)`` eval buffer in the solo row layout."""
    from cocoa_tpu.analysis import sanitize as _sanitize

    t_fleet = int(gap_targets.shape[0])
    if carry is None:
        carry = FleetCarry.init(t_fleet, state[0].dtype)
    n_state = len(state)
    run_key = None if cache_key is None else ("fleet", cache_key)
    run = _DEVICE_RUNS.get(run_key) if run_key is not None else None
    if run is None:
        run = _build_fleet_run(
            chunk_kernel, eval_kernel, n_state,
            per_tenant_idxs=per_tenant_idxs, stall_evals=stall_evals,
            divergence_guard=divergence_guard, n_stages=n_stages,
            accel=accel, jump_kernel=jump_kernel, lane_exec=lane_exec)
        if run_key is not None:
            _DEVICE_RUNS[run_key] = run
    n_chunks = int(jax.tree.leaves(idxs_all)[0].shape[0])
    c = int(jax.tree.leaves(idxs_all)[0].shape[1])
    with _tracing.span("local_solve", algorithm=name, t0=start_round,
                       round=start_round - 1 + n_chunks * c,
                       rounds=n_chunks * c, cadence=c, tenants=t_fleet), \
            _sanitize.device_loop_guard():
        out = run(*carry.args(), *state, idxs_all, shard_arrays, scal,
                  gap_targets)
        (i, done_tgt, done_stall, stall, best, best_prev, cert,
         stall_chunk, state, traj_buf) = out
        # the single host sync of the whole fleet block
        with _sanitize.intended_fetch("fleet_loop_fetch"):
            n_done = int(i)
            traj_host = np.asarray(traj_buf[:n_done])
    carry = FleetCarry(done_tgt, done_stall, stall, best, best_prev,
                       cert, stall_chunk)
    return state, carry, n_done, traj_host


class TsSampler:
    """Sampler adapter whose chunk tables also carry the round number.

    η(t)-scheduled solvers (SGD: η = 1/(λt), SGD.scala:44; DistGD:
    η = 1/(βt), DistGD.scala:35) need t inside the device-side scan.  The
    table becomes a dict pytree: ``{"idxs": (C, K, H), "t": (C,)}`` — the
    (C,) leaf is treated as a replicated per-round scalar by
    ``chunk_fanout`` and by the pytree-aware device-loop drivers.

    ``sampler=None`` (DistGD — deterministic full passes, no index draws)
    emits only the ``t`` leaf; ``h``/``counts`` then size the index-table
    memory cap as zero-ish (h=1).
    """

    def __init__(self, sampler: "IndexSampler | None", dtype, counts=None):
        self.sampler = sampler
        self.dtype = dtype
        self.h = sampler.h if sampler is not None else 1
        self.counts = sampler.counts if sampler is not None else np.asarray(counts)

    @property
    def device(self) -> bool:
        return self.sampler is not None and self.sampler.device

    def cache_token(self):
        return None if self.sampler is None else self.sampler.cache_token()

    def ints_per_round(self) -> int:
        return 1 if self.sampler is None else self.sampler.ints_per_round()

    def chunk_indices(self, t0: int, c: int):
        import jax.numpy as jnp

        out = {"t": jnp.arange(t0, t0 + c, dtype=self.dtype)}
        if self.sampler is not None:
            if self.sampler.device:
                # exact int32 round numbers for in-jit generation — the
                # float ``t`` leaf rides the compute dtype for the η(t)
                # schedules and cannot carry them (bf16 collapses integers
                # past 256)
                out["ti"] = jnp.arange(t0, t0 + c, dtype=jnp.int32)
            else:
                out["idxs"] = self.sampler.chunk_indices(t0, c)
        return out

    def materialize(self, xs):
        """Traceable: fill the ``idxs`` leaf from the int32 ``ti`` leaf
        when the inner sampler generates on device (the chunk tables are
        otherwise passed through untouched; the extra (C,) ``ti`` leaf
        scans as an inert per-round scalar)."""
        if self.sampler is None or "idxs" in xs:
            return xs
        return {**xs, "idxs": self.sampler.tables_from_ts(xs["ti"])}
