"""Shared outer-loop machinery for all solvers.

Every algorithm's round has the same communication shape (the reference's
``mapPartitions`` → ``reduce`` skeleton, CoCoA.scala:45-47):

    fan out (w replicated, shard-local state pinned)
    → per-shard local solver
    → one O(d) sum-reduce of Δw
    → replicated driver-side w update

``fanout`` carries that shape on two execution paths with identical math:

- **mesh path** (K devices): ``shard_map`` over the dp axis; the Δw reduce is
  one ``lax.psum`` over ICI — the whole point of CoCoA's communication
  efficiency maps to exactly one collective per round.
- **local path** (mesh=None, e.g. a single TPU chip holding all K logical
  shards): ``vmap`` over the leading shard axis + an in-device sum.  Same
  numbers, no collective — used for single-chip benchmarking and as the
  K-logical-shards-on-1-device analogue of the reference's ``local[4]`` mode.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.parallel.fanout import fanout  # noqa: F401  (re-export)
from cocoa_tpu.utils.logging import Trajectory
from cocoa_tpu.utils.prng import sample_indices_per_shard


def drive(
    name: str,
    params: Params,
    debug: DebugParams,
    state: tuple,
    round_fn: Callable[[int, tuple], tuple],
    eval_fn: Callable[[tuple], tuple],
    quiet: bool = False,
    gap_target: Optional[float] = None,
    start_round: int = 1,
):
    """The outer driver loop shared by every solver (CoCoA.scala:39-63
    skeleton): run rounds, gate evaluation to every ``debugIter`` rounds,
    checkpoint every ``chkptIter`` rounds, optionally stop early on a
    duality-gap target.

    ``state`` is ``(w,)`` or ``(w, alpha)``; ``round_fn(t, state) -> state``;
    ``eval_fn(state) -> (primal, gap_or_None, test_error_or_None)``.
    Returns (state, Trajectory).
    """
    traj = Trajectory(name, quiet=quiet)
    for t in range(start_round, params.num_rounds + 1):
        state = round_fn(t, state)

        if debug.debug_iter > 0 and t % debug.debug_iter == 0:
            primal, gap, test_err = eval_fn(state)
            traj.log_round(t, primal=primal, gap=gap, test_error=test_err)
            if gap_target is not None and gap is not None and gap <= gap_target:
                break

        if debug.chkpt_dir and debug.chkpt_iter > 0 and t % debug.chkpt_iter == 0:
            ckpt_lib.save(
                debug.chkpt_dir, name, t, state[0],
                state[1] if len(state) > 1 else None, seed=debug.seed,
            )
    return state, traj


def check_shards(ds: ShardedDataset) -> None:
    """Reject empty shards up front: the reference crashes inside the task
    (``nextInt(0)``) when numSplits > rows; we fail with a clear message."""
    if np.any(ds.counts <= 0):
        raise ValueError(
            f"every shard needs at least one example; shard sizes are "
            f"{ds.counts.tolist()} (n={ds.n} over K={ds.k} shards) — "
            f"lower numSplits"
        )


class IndexSampler:
    """Per-round local-coordinate sampling, in one of two modes.

    - ``reference``: host-side java.util.Random replay — identical draws to
      the Scala code per (seed+t, n_local), correlated across equal-size
      shards exactly as the reference is (CoCoA.scala:45,144).
    - ``jax``: device-friendly ``jax.random`` folded per (seed, round, shard)
      — decorrelated across shards (statistical improvement, not
      reference-faithful).
    """

    def __init__(self, mode: str, seed: int, h: int, counts: np.ndarray):
        if mode not in ("reference", "jax"):
            raise ValueError(f"rng mode must be 'reference' or 'jax', got {mode!r}")
        self.mode = mode
        self.seed = seed
        self.h = h
        self.counts = np.asarray(counts)
        self._key = None
        if mode == "jax":
            self._key = jax.random.key(seed)

    def round_indices(self, t: int) -> jax.Array:
        """(K, H) int32 index table for round t (1-based, as the reference)."""
        if self.mode == "reference":
            tab = sample_indices_per_shard(
                self.seed, range(t, t + 1), self.h, self.counts
            )[:, 0, :]
            return jax.numpy.asarray(tab)
        k = self.counts.shape[0]
        key = jax.random.fold_in(self._key, t)
        bounds = jax.numpy.asarray(self.counts, dtype=jax.numpy.int32)
        return jax.random.randint(
            key, (k, self.h), minval=0, maxval=bounds[:, None], dtype=jax.numpy.int32
        )
