"""ProxCoCoA+ — communication-efficient L1-regularized regression (lasso /
elastic net).

No reference analogue (the reference is hinge-SVM only) — this is the
framework's follow-up-paper extension (arXiv:1512.04011 structure),
included because the reference is explicitly designed for swappable local
solvers/objectives (README.md:14, CoCoA.scala:13-14) and the L1 primal
family is the canonical "swap".

Problem:  min_x  0.5·‖A·x − b‖² + λ·‖x‖₁ (+ η/2·‖x‖²  elastic net)

Structure — the exact mirror of the dual solvers with examples↔features
swapped:

- A's **columns** are sharded (data/columns.py); worker k owns coordinate
  block x_[k] and columns A_[k].
- The replicated state is the residual r = A·x − b (an n-vector — the
  analogue of w); the shard-local state is x_[k] (the analogue of α).
- One round: each worker runs H prox coordinate-descent steps against the
  frozen r₀ with σ′-scaled reads of its accumulated Δv = A_[k]·Δx_[k]
  (exactly CoCoA+'s subproblem structure, mode="prox"), then ONE psum of
  Δv per round: r += γ·ΣΔv.  The per-step soft-threshold rule lives in
  ops/losses.py ("lasso").

Because the structure is identical, the entire SDCA-family machinery —
fast-math margins decomposition, both Pallas kernels, device-side chunked
rounds and the device-resident loop, gap-target early stop — is reused
verbatim via run_sdca_family with mode="prox" and a duality-gap
certificate for the WHOLE family (the reference's principle: every
primal-dual method certifies, OptUtils.scala:89-91 / README.md:14):

- pure lasso (η = 0): gap = P(x) − D(s·r) with the dual-feasible scaling
  s = min(1, λ/‖Aᵀr‖∞), D(u) = −½‖u‖² − uᵀb — the conjugate of λ|·| is
  the indicator of [−λ, λ], so u must be scaled into the feasible box.
- elastic net (η > 0): the l2 term smooths the conjugate —
  h(t) = λ|t| + (η/2)t² has h*(s) = ([|s| − λ]₊)²/(2η), finite
  everywhere — so the residual itself is dual-feasible and
  gap = P(x) − D(r), D(u) = −½‖u‖² − uᵀb − Σ_j ([|a_jᵀu| − λ]₊)²/(2η).
  Weak duality gives gap ≥ 0 for any x; at the optimum u* = r* makes it
  0 (validated against the NumPy oracle in tests/test_prox.py).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.ops.rows import shard_margins
from cocoa_tpu.parallel.fanout import fanout
from cocoa_tpu.solvers.cocoa import run_sdca_family


def lasso_metrics(r, x, shard_arrays, b, l1: float, l2: float, mesh=None):
    """(primal, gap, NaN) for the elastic-net objective, as one stacked
    device array — one fan-out over the column shards (Σ|x|, Σx², the
    per-shard max |a_jᵀr| for the lasso dual-feasible scaling, and the
    Σ([|a_jᵀr| − λ]₊)² the smoothed elastic-net conjugate needs), zero
    host syncs.  The certificate is exact for both cases (module
    docstring); weak duality makes it ≥ 0 at every iterate."""
    def per_shard(rw, x_k, shard):
        m = shard["mask"]
        corr = jnp.abs(shard_margins(rw, shard)) * m
        excess = jnp.maximum(corr - l1, 0.0)
        sums = jnp.stack([
            jnp.sum(jnp.abs(x_k) * m),
            jnp.sum(x_k * x_k * m),
            jnp.sum(excess * excess),
        ])
        return sums, jnp.max(corr)

    sums, corr_max_k = fanout(per_shard, mesh, r, x, shard_arrays)
    rr = r @ r
    primal = 0.5 * rr + l1 * sums[0] + 0.5 * l2 * sums[1]
    if l2 == 0.0:
        inf_norm = jnp.max(corr_max_k)
        s = jnp.minimum(1.0, l1 / jnp.maximum(inf_norm, 1e-30))
        u = s * r
        dual = -0.5 * (u @ u) - u @ b
    else:
        # h*(s) = ([|s|-λ]₊)²/(2η): finite for any s, so u = r is feasible
        dual = -0.5 * rr - r @ b - sums[2] / (2.0 * l2)
    gap = primal - dual
    return jnp.stack([primal, gap, jnp.asarray(jnp.nan, primal.dtype)])


@functools.lru_cache(maxsize=None)
def _metrics_fn(mesh, l1: float, l2: float):
    @jax.jit
    def f(r, x, shard_arrays, b):
        return lasso_metrics(r, x, shard_arrays, b, l1, l2, mesh=mesh)

    return f


def run_prox_cocoa(
    ds: ShardedDataset,
    b: jax.Array,
    params: Params,
    debug: DebugParams,
    mesh=None,
    rng: str = "reference",
    x_init: Optional[jax.Array] = None,
    r_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    scan_chunk: int = 0,
    math: str = "fast",
    pallas=None,
    block_size: int = 0,
    block_chain=None,
    block_pipeline=None,
    device_loop: bool = False,
    sampling: str = "auto",
    divergence_guard: str = "auto",
):
    """Train; returns (x, r, Trajectory) with x (K, d_shard) the sharded
    coordinates and r = A·x − b the replicated residual (v = r + b).

    ``ds``/``b`` come from :func:`cocoa_tpu.data.columns.shard_columns`.
    ``params.lam`` is the L1 weight λ, ``params.smoothing`` the elastic-net
    l2 weight η (0 = pure lasso), ``params.gamma`` the aggregation γ
    (γ=1 additive, σ′ = K·γ — the CoCoA+ safe default), ``params.local_iters``
    the per-round coordinate steps H.  ``gap_target`` stops at the duality
    gap (certified for both lasso and elastic net — module docstring).
    Execution options (``scan_chunk``,
    ``math``, ``pallas``, ``device_loop``) as in run_sdca_family — all
    paths incl. both Pallas kernels work on the transposed layout."""
    l1, l2 = float(params.lam), float(params.smoothing)
    # mode="prox" has no λn factor: clone with n=1 so the shared parts'
    # lam_n == λ exactly, and select the lasso prox rule
    parts_params = dataclasses.replace(params, n=1, loss="lasso")
    alg = ("prox", params.gamma, ds.k * params.gamma)
    dtype = ds.labels.dtype
    b = jnp.asarray(b, dtype)
    metrics = _metrics_fn(mesh, l1, l2)

    def eval_fn(state):
        r, x = state
        out = np.asarray(metrics(r, x, ds.shard_arrays(), b))
        primal, gap, _ = (float(v) for v in out)
        return primal, (None if np.isnan(gap) else gap), None

    def eval_kernel(state, shard_arrays, test_arrays):
        # b arrives as the (otherwise unused) test_arrays ARGUMENT, not a
        # closure constant: device-loop executables are cached per config
        # (base._DEVICE_RUNS), and a baked-in b would make a cached
        # executable evaluate against the wrong dataset
        r, x = state
        return lasso_metrics(r, x, shard_arrays, test_arrays, l1, l2,
                             mesh=mesh)

    class _BCarrier:
        """Quacks like a test dataset so drive_device_paths ships b as the
        eval kernel's test_arrays argument."""
        n = 0

        def shard_arrays(self):
            return b

    w_init = -b if r_init is None else jnp.asarray(r_init, dtype)
    r, x, traj = run_sdca_family(
        ds, parts_params, debug, "ProxCoCoA+", alg, mesh=mesh,
        test_ds=_BCarrier(),
        rng=rng, w_init=w_init, alpha_init=x_init, start_round=start_round,
        quiet=quiet, gap_target=gap_target, scan_chunk=scan_chunk,
        math=math, pallas=pallas, block_size=block_size,
        block_chain=block_chain, block_pipeline=block_pipeline,
        device_loop=device_loop,
        eval_fn=eval_fn, eval_kernel=eval_kernel, sampling=sampling,
        divergence_guard=divergence_guard,
    )
    return x, r, traj
