from cocoa_tpu.solvers.cocoa import run_cocoa  # noqa: F401
from cocoa_tpu.solvers.minibatch_cd import run_minibatch_cd  # noqa: F401
from cocoa_tpu.solvers.sgd import run_sgd  # noqa: F401
from cocoa_tpu.solvers.dist_gd import run_dist_gd  # noqa: F401
from cocoa_tpu.solvers.prox_cocoa import run_prox_cocoa  # noqa: F401
from cocoa_tpu.solvers.fleet import FleetResult, run_cocoa_fleet  # noqa: F401
