"""Distributed SGD: local-SGD and mini-batch variants (reference: SGD.scala).

- local=True (Local SGD): workers run H Pegasos steps on a private w; the
  driver averages Δw = w_local − w_init with β/K (SGD.scala:34-37,55-56).
- local=False (mini-batch SGD): the driver pre-scales w by (1 − ηλ) with
  η = 1/(λt) (SGD.scala:44-50), workers sum raw hinge subgradients, and the
  driver applies w += Δw·η·β/(K·H) (SGD.scala:38,57-59).

No dual state → primal-objective-only trajectory (no duality-gap
certificate), as in the reference (SGD.scala:62-66).

The η(t) schedule rides through the device-side paths as a scanned (C,)
``t`` leaf in the chunk tables (parallel/fanout.py chunk_fanout,
base.TsSampler) — ``scan_chunk`` and ``device_loop`` work exactly as they
do for the SDCA family.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import local_sgd
from cocoa_tpu.solvers import base


def _sgd_parts(params: Params, k: int, local: bool):
    """per-shard round + driver apply shared by every execution path.

    ``x`` is the per-round input dict {"idxs": (H,), "t": scalar}."""
    h = params.local_iters
    lam = params.lam
    scaling = params.beta / k if local else params.beta / (k * h)  # SGD.scala:34-39

    def pre_scale(w, t):
        if local:
            return w
        eta = 1.0 / (lam * t)  # SGD.scala:44
        return w * (1.0 - eta * lam)  # driver-side pre-scale (SGD.scala:46-50)

    def per_shard_round(w, carry, x, shard_k):
        t = x["t"]
        t_global = (t - 1.0) * h * k  # SGD.scala:53
        dw = local_sgd(pre_scale(w, t), shard_k, x["idxs"], lam, t_global,
                       local, loss=params.loss, smoothing=params.smoothing)
        return dw, carry

    def apply_fn(w, dw_sum, x):
        if local:
            return w + dw_sum * scaling  # SGD.scala:55-56
        t = x["t"]
        eta = 1.0 / (lam * t)
        return pre_scale(w, t) + dw_sum * (eta * scaling)  # SGD.scala:57-59

    return per_shard_round, apply_fn


def make_round_step(mesh, params: Params, k: int, local: bool):
    per_shard_round, apply_fn = _sgd_parts(params, k, local)

    def per_shard(w, idxs_k, t_k, shard_k):
        return (per_shard_round(w, (), {"idxs": idxs_k, "t": t_k}, shard_k)[0],)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(w, idxs, t, shard_arrays):
        (dw_sum,) = base.fanout(
            per_shard, mesh, w, idxs, _rep(t, k), shard_arrays
        )
        return apply_fn(w, dw_sum, {"t": t})

    return round_step


def _rep(scalar, k):
    """Broadcast a traced scalar to a (K,) sharded arg for fanout."""
    return jnp.broadcast_to(scalar, (k,))


_CHUNK_STEPS: dict = base.ExecutableCache()


def _make_chunk_kernel(mesh, params: Params, k: int, local: bool,
                       ts_sampler=None):
    """(w, xs, shard_arrays) -> w', C rounds as one ``lax.scan``; xs is the
    TsSampler table {"idxs": (C, K, H), "t": (C,)} — or just the ``t`` leaf
    in device-sampling mode, with ``idxs`` generated in-jit."""
    from cocoa_tpu.parallel.fanout import chunk_fanout

    per_shard_round, apply_fn = _sgd_parts(params, k, local)

    def chunk_kernel(w, xs, shard_arrays):
        if ts_sampler is not None:
            xs = ts_sampler.materialize(xs)
        w2, _ = chunk_fanout(
            mesh, per_shard_round, apply_fn, w, (), xs, shard_arrays
        )
        return w2

    return chunk_kernel


def make_chunk_step(mesh, params: Params, k: int, local: bool,
                    ts_sampler=None):
    key = ("sgd", mesh, k, local, params.lam, params.n, params.local_iters,
           params.beta, params.loss, params.smoothing,
           None if ts_sampler is None else ts_sampler.cache_token())
    step = _CHUNK_STEPS.get(key)
    if step is None:
        step = jax.jit(_make_chunk_kernel(mesh, params, k, local,
                                          ts_sampler=ts_sampler),
                       donate_argnums=(0,))
        _CHUNK_STEPS[key] = step
    return step


def run_sgd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    local: bool,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    rng: str = "reference",
    w_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
    scan_chunk: int = 0,
    device_loop: bool = False,
    sampling: str = "auto",
):
    """Train; returns (w, Trajectory).  ``scan_chunk > 0`` runs rounds
    device-side in blocks via ``lax.scan``; ``device_loop=True`` rides the
    whole run — rounds, evals — as one on-device ``lax.while_loop`` (see
    run_sdca_family for semantics; SGD has no duality gap so there is no
    gap-target early stop)."""
    base.check_shards(ds)
    k = ds.k
    if not quiet:
        print(f"\nRunning SGD (with local updates = {local}) on {params.n} "
              f"data examples, distributed over {k} workers")

    dtype = ds.labels.dtype
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding

        w = jax.device_put(w, primal_sharding(mesh))

    sampler = base.IndexSampler(rng, debug.seed, params.local_iters, ds.counts)
    sampler.device = base.resolve_sampling(sampling, sampler,
                                           params.num_rounds)
    ts_sampler = base.TsSampler(sampler, dtype)
    shard_arrays = ds.shard_arrays()
    name = "Local SGD" if local else "Mini-batch SGD"

    def eval_fn(state):
        (w,) = state
        return objectives.evaluate(ds, w, None, params.lam, test_ds=test_ds,
                                   loss=params.loss, smoothing=params.smoothing)

    if device_loop or scan_chunk > 0:
        raw_kernel = _make_chunk_kernel(mesh, params, k, local,
                                        ts_sampler=ts_sampler)

        def chunk_kernel(state, xs, shard_arrays):
            return (raw_kernel(state[0], xs, shard_arrays),)

        chunk_step = make_chunk_step(mesh, params, k, local,
                                     ts_sampler=ts_sampler)

        def chunk_fn(t0, c, state):
            return (chunk_step(state[0], ts_sampler.chunk_indices(t0, c),
                               shard_arrays),)

        cache_key = (
            "sgd", local, ts_sampler.cache_token(), k, mesh,
            params.lam, params.n, params.local_iters,
            params.beta, params.loss, params.smoothing, params.num_rounds,
            debug.debug_iter, start_round, ds.layout, str(dtype),
        )
        (w,), traj = base.drive_device_paths(
            name, params, debug, (w,), chunk_kernel, chunk_fn, eval_fn,
            ts_sampler, shard_arrays, alpha_in_state=False, mesh=mesh,
            test_ds=test_ds, quiet=quiet, start_round=start_round,
            scan_chunk=scan_chunk, device_loop=device_loop,
            cache_key=cache_key,
        )
        return w, traj

    step = make_round_step(mesh, params, k, local)

    def round_fn(t, state):
        (w,) = state
        idxs = sampler.round_indices(t)
        return (step(w, idxs, jnp.asarray(float(t), dtype=dtype), shard_arrays),)

    (w,), traj = base.drive(
        name, params, debug, (w,), round_fn, eval_fn,
        quiet=quiet, start_round=start_round,
    )
    return w, traj
