"""Distributed SGD: local-SGD and mini-batch variants (reference: SGD.scala).

- local=True (Local SGD): workers run H Pegasos steps on a private w; the
  driver averages Δw = w_local − w_init with β/K (SGD.scala:34-37,55-56).
- local=False (mini-batch SGD): the driver pre-scales w by (1 − ηλ) with
  η = 1/(λt) (SGD.scala:44-50), workers sum raw hinge subgradients, and the
  driver applies w += Δw·η·β/(K·H) (SGD.scala:38,57-59).

No dual state → primal-objective-only trajectory (no duality-gap
certificate), as in the reference (SGD.scala:62-66).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import local_sgd
from cocoa_tpu.solvers import base


def make_round_step(mesh, params: Params, k: int, local: bool):
    h = params.local_iters
    lam = params.lam
    scaling = params.beta / k if local else params.beta / (k * h)  # SGD.scala:34-39

    def per_shard(w, idxs_k, t_global, shard_k):
        return (local_sgd(w, shard_k, idxs_k, lam, t_global, local,
                          loss=params.loss, smoothing=params.smoothing),)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def round_step(w, idxs, t, shard_arrays):
        eta = 1.0 / (lam * t)  # SGD.scala:44
        if not local:
            w = w * (1.0 - eta * lam)  # driver-side pre-scale (SGD.scala:46-50)
        t_global = (t - 1.0) * h * k  # SGD.scala:53
        (dw_sum,) = base.fanout(
            per_shard, mesh, w, idxs, _rep(t_global, k), shard_arrays
        )
        if local:
            return w + dw_sum * scaling  # SGD.scala:55-56
        return w + dw_sum * (eta * scaling)  # SGD.scala:57-59

    return round_step


def _rep(scalar, k):
    """Broadcast a traced scalar to a (K,) sharded arg for fanout."""
    return jnp.broadcast_to(scalar, (k,))


def run_sgd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    local: bool,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    rng: str = "reference",
    w_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
):
    """Train; returns (w, Trajectory)."""
    base.check_shards(ds)
    k = ds.k
    if not quiet:
        print(f"\nRunning SGD (with local updates = {local}) on {params.n} "
              f"data examples, distributed over {k} workers")

    dtype = ds.labels.dtype
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding

        w = jax.device_put(w, primal_sharding(mesh))

    sampler = base.IndexSampler(rng, debug.seed, params.local_iters, ds.counts)
    step = make_round_step(mesh, params, k, local)
    shard_arrays = ds.shard_arrays()
    name = "Local SGD" if local else "Mini-batch SGD"

    def round_fn(t, state):
        (w,) = state
        idxs = sampler.round_indices(t)
        return (step(w, idxs, jnp.asarray(float(t), dtype=dtype), shard_arrays),)

    def eval_fn(state):
        (w,) = state
        return objectives.evaluate(ds, w, None, params.lam, test_ds=test_ds,
                                   loss=params.loss, smoothing=params.smoothing)

    (w,), traj = base.drive(
        name, params, debug, (w,), round_fn, eval_fn,
        quiet=quiet, start_round=start_round,
    )
    return w, traj
