"""CoCoA / CoCoA+ outer driver (reference: CoCoA.scala:22-66).

One outer round = one jitted step: fan out the replicated w, run H local
SDCA coordinate steps per shard, psum the Δw, apply the scaling law —
γ for CoCoA+ (additive) or β/K for CoCoA (averaging) (CoCoA.scala:37).
The Python loop over rounds mirrors the reference's driver loop
(CoCoA.scala:39); per-``debugIter`` evaluation is gated off the hot path
exactly as the reference gates it (CoCoA.scala:51).

State lives device-side across rounds: w replicated, alpha (K, n_shard)
pinned per-shard — donated through the jitted step so XLA updates it in
place in HBM (the analogue of ``preservesPartitioning=true`` RDD reuse).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import local_sdca
from cocoa_tpu.solvers import base


def _pallas_batched(w, alpha, idxs_kh, shards, params, mode, sigma,
                    interpret):
    """One Pallas SDCA round over all K shards: dense kernel (margins
    precomputed as one MXU matvec, folded-row X) or sparse kernel (margins
    read in-kernel from the VMEM-resident w).  Returns (dw (K, d),
    alpha_inner (K, n_shard))."""
    common = dict(mode=mode, sigma=sigma, interpret=interpret,
                  loss=params.loss, smoothing=params.smoothing)
    if "sp_indices" in shards:
        from cocoa_tpu.ops.pallas_sparse import pallas_sparse_sdca_round

        # hybrid layouts (--hotCols) pass the hot panel through: the
        # kernel then streams each step's panel slice through VMEM and
        # merges only the cold residual (docs/DESIGN.md §3b-vi)
        return pallas_sparse_sdca_round(
            w, alpha, shards["sp_indices"], shards["sp_values"],
            shards["labels"], shards["sq_norms"], idxs_kh,
            params.lam, params.n, row_len=shards.get("sp_row_len"),
            hot_cols=shards.get("hot_cols"), hot_panel=shards.get("X_hot"),
            **common,
        )
    from cocoa_tpu.ops.pallas_sdca import pallas_sdca_round

    # margins are computed in-kernel against the VMEM-resident w (round 4;
    # the sampled row is DMA'd for the axpy anyway — precomputing X·w read
    # ALL of X per round, ~10x the rows the round touches at
    # localIterFrac=0.1)
    Xf = shards.get("X_folded", shards["X"])
    return pallas_sdca_round(
        w, alpha, Xf, shards["labels"], shards["sq_norms"], idxs_kh,
        params.lam, params.n, **common,
    )


def auto_block_size(ds: ShardedDataset, m_local: int, dtype) -> int:
    """Resolve ``--blockSize=auto`` per data layout, mirroring EXACTLY the
    path local_sdca_block_batched would dispatch to.

    Candidates are walked in the MEASURED ranking from the
    benchmarks/kernels.py B sweep (pallas_chain.BLOCK_SIZE_PREFERENCE,
    recorded in KERNELS.md) — the first candidate that passes the same
    fit accounting the dispatch layer uses wins, so auto picks the
    measured-best tile, not just the largest that fits:

    - dense: a candidate fits when the lockstep chain kernel fits VMEM
      (chain_fits);
    - sparse: a candidate needs a WINNING block kernel — the fused kernel
      holding the (small-d) densified tile, or otherwise the in-kernel CSR
      Gram path (ops/pallas_sparse.sparse_chain_fits).  When neither fits
      any candidate, 0: a SPLIT-path densified sparse block loses to the
      sequential sparse kernel, so those configs keep the sequential
      default;
    - anything the f32 chain kernel cannot serve (2/8-byte dtypes,
      oversized VMEM at every candidate): 0, the sequential path.
    """
    from cocoa_tpu.ops.pallas_chain import (
        BLOCK_SIZE_PREFERENCE, chain_fits, fused_fits,
    )
    from cocoa_tpu.ops.pallas_sparse import hybrid_fits, sparse_chain_fits

    itemsize = jnp.dtype(dtype).itemsize
    if itemsize != 4:
        return 0
    for b in BLOCK_SIZE_PREFERENCE:
        if not chain_fits(m_local, b, itemsize):
            continue
        if ds.layout == "sparse":
            # same precedence as the block dispatch: the fused kernel
            # first (densify is cheap when the half-tile fits), the CSR
            # Gram path when it cannot (the rcv1 regime); hybrid layouts
            # gate on the RESIDUAL streams + panel alignment
            # (hybrid_fits), which the narrower residual only loosens
            width = int(ds.sp_indices.shape[-1])
            stream_ok = (
                hybrid_fits(m_local, ds.n_shard, ds.num_features, width,
                            b, ds.n_hot, itemsize)
                if ds.n_hot else
                sparse_chain_fits(m_local, ds.n_shard, ds.num_features,
                                  width, b, itemsize)
            )
            if not (
                fused_fits(m_local, b, ds.num_features, itemsize,
                           ds.n_shard)
                or stream_ok
            ):
                continue
        return b
    return 0


def _alg_config(params: Params, k: int, plus: Optional[bool], mode=None):
    """(mode, scaling, sigma) for the three SDCA-family algorithms.

    scaling law: γ (CoCoA+, additive) | β/K (CoCoA, averaging) —
    CoCoA.scala:37, with σ′ = K·γ (CoCoA.scala:45); β/(K·H) for
    mini-batch CD (MinibatchCD.scala:32, w frozen so σ is unused).

    ``params.sigma`` overrides σ′ (extension, --sigma): K·γ is the paper's
    safe bound for ADVERSARIAL shard coherence; randomly-partitioned data
    tolerates a smaller σ′ = bigger effective local steps, and the exact
    duality-gap certificate reports divergence if pushed too far
    (measured: σ′=K/2 halves rcv1's certified comm-rounds; anything below
    K/2 — already σ′=3.5 at K=8 — diverges visibly)."""
    if mode == "frozen":
        # σ is unused by the frozen subproblem (MinibatchCD.scala:104 reads
        # only the frozen w), so even sigma="auto" is fine to ignore here —
        # the reference driver runs mini-batch CD from the same flag set
        return "frozen", params.beta / (k * params.local_iters), 1.0
    if params.sigma == "auto":
        raise ValueError("sigma='auto' is resolved by run_cocoa (it needs "
                         "the retry loop); it cannot reach _alg_config")
    sig = k * params.gamma if params.sigma is None else float(params.sigma)
    if plus:
        return "plus", params.gamma, sig
    return "cocoa", params.beta / k, sig


def _sdca_round_parts(
    params: Params,
    k: int,
    mode: str,
    scaling: float,
    sigma: float,
    math: str = "exact",
    pallas: bool = False,
    pallas_interpret: bool = False,
    block: int = 0,
    block_chain: str = "xla",
    block_distinct: bool = False,
    block_sparse_gram=None,
    block_pipeline=None,
):
    """The per-shard local update and driver-side apply shared by the
    per-round and chunked builders (so the two paths cannot diverge), for
    all three SDCA-family algorithms (CoCoA, CoCoA+, mini-batch CD — see
    :func:`_alg_config` for the scaling laws).

    ``math="fast"`` uses the margins decomposition (ops/local_sdca.py
    ``mode_factors``): one MXU matvec per round + an incremental Δw dot per
    step — equal in real arithmetic, rounds differently than the reference
    order.  ``pallas=True`` further runs the inner loop as a Pallas TPU
    kernel — ops/pallas_sdca.py for the dense layout, ops/pallas_sparse.py
    for padded-CSR.  ``block > 0`` runs the fast inner loop as the
    block-coordinate MXU kernel (ops/local_sdca.local_sdca_block) with that
    block size; ``block_pipeline`` (None = auto) controls the two-phase
    software-pipelined block scan — next block's row-tile gather overlapped
    with the current chain kernel, bit-identical schedules (see
    local_sdca_block_batched).  Returns (per_shard, per_round_batched |
    None, apply_fn)."""
    if math not in ("exact", "fast"):
        raise ValueError(f"math must be 'exact' or 'fast', got {math!r}")
    if block and pallas:
        raise ValueError("block-coordinate mode replaces the Pallas kernel; "
                         "pass pallas=False with block > 0")
    if block and math == "exact":
        raise ValueError("block > 0 requires math='fast' (the block kernel "
                         "is a margins-decomposition variant)")

    def apply_fn(w, dw_sum, x=None):
        # CoCoA.scala:47-48 / MinibatchCD.scala:42-43 (x unused: no η(t))
        return w + scaling * dw_sum

    if math == "exact":
        if pallas:
            raise ValueError("the Pallas kernel implies math='fast'")

        def per_shard(w, alpha_k, idxs_k, shard_k):
            da, dw = local_sdca(
                w, alpha_k, shard_k, idxs_k, params.lam, params.n,
                mode=mode, sigma=sigma,
                loss=params.loss, smoothing=params.smoothing,
            )
            # CoCoA.scala:101 / MinibatchCD.scala:127-128
            return dw, alpha_k + scaling * da

        return per_shard, None, apply_fn

    from cocoa_tpu.ops.local_sdca import (
        local_sdca_block, local_sdca_block_batched, local_sdca_fast,
    )
    from cocoa_tpu.ops.rows import shard_margins

    def block_round(w, alpha, idxs_kh, shards):
        """The batched block kernel with this algorithm's parameters — the
        one call site per_shard (mesh) and per_round_batched (single chip)
        share."""
        return local_sdca_block_batched(
            w, alpha, shards, idxs_kh, params.lam, params.n, mode=mode,
            sigma=sigma, loss=params.loss, smoothing=params.smoothing,
            block=block, interpret=(block_chain == "pallas_interpret"),
            distinct=block_distinct, sparse_gram=block_sparse_gram,
            pipeline=block_pipeline,
        )

    def per_shard(w, alpha_k, idxs_k, shard_k):
        if pallas:
            # only reached inside the chunked mesh driver, which runs its
            # shard_map with check_vma=False (pallas_call's internal slices
            # confuse the VMA checker)
            batched = jax.tree.map(lambda a: a[None], shard_k)
            dw, a_inner = _pallas_batched(
                w, alpha_k[None], idxs_k[None], batched, params, mode,
                sigma, pallas_interpret,
            )
            da = a_inner[0] - alpha_k
            return dw[0], alpha_k + scaling * da
        if block and block_chain != "xla":
            # single-shard view of the batched block kernel (the mesh path:
            # one shard per device under shard_map, check_vma=False)
            da, dw = block_round(
                w, alpha_k[None], idxs_k[None],
                jax.tree.map(lambda a: a[None], shard_k),
            )
            return dw[0], alpha_k + scaling * da[0]
        m0 = shard_margins(w, shard_k)
        inner = local_sdca_fast if not block else functools.partial(
            local_sdca_block, block=block
        )
        da, dw = inner(
            m0, alpha_k, shard_k, idxs_k, params.lam, params.n,
            jnp.zeros_like(w), mode=mode, sigma=sigma,
            loss=params.loss, smoothing=params.smoothing,
        )
        return dw, alpha_k + scaling * da

    per_round_batched = None
    if pallas:
        # the Pallas kernels own the shard axis via their (K, H) grids —
        # used on the single-chip path instead of vmap(per_shard)
        def per_round_batched(w, alpha, idxs_kh, shards):
            dw, a_inner = _pallas_batched(
                w, alpha, idxs_kh, shards, params, mode, sigma,
                pallas_interpret,
            )
            alpha_new = alpha + scaling * (a_inner - alpha)
            return dw.sum(axis=0), alpha_new
    elif block and block_chain != "xla":
        # the batched block kernel advances every shard's chain inside one
        # Pallas instance — vmap(per_shard) would serialize K kernel
        # instances through the grid instead
        def per_round_batched(w, alpha, idxs_kh, shards):
            da, dw = block_round(w, alpha, idxs_kh, shards)
            return dw.sum(axis=0), alpha + scaling * da

    return per_shard, per_round_batched, apply_fn


def make_round_step(mesh, params: Params, k: int, alg, **parts_kw):
    """Build the jitted (w, alpha, idxs, shard_arrays) -> (w', alpha') step.
    ``alg`` = (mode, scaling, sigma), see :func:`_alg_config`."""
    per_shard, _, apply_fn = _sdca_round_parts(params, k, *alg, **parts_kw)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def round_step(w, alpha, idxs, shard_arrays):
        dw_sum, alpha_new = base.fanout(
            per_shard, mesh, w, alpha, idxs, shard_arrays
        )
        return apply_fn(w, dw_sum), alpha_new

    return round_step


def _make_chunk_kernel(mesh, params: Params, k: int, alg, sampler=None,
                       **parts_kw):
    """The un-jitted traceable chunk body shared by :func:`make_chunk_step`
    and the device-resident driver (so the two cannot diverge):
    (w, alpha, idxs_ckh, shard_arrays) -> (w', alpha'), C rounds as one
    ``lax.scan`` (parallel/fanout.py chunk_fanout).  On Pallas configs the
    caller (_run_sdca) pre-folds ``shard_arrays["X_folded"]`` once per run —
    the kernel itself never folds, so no per-dispatch relayout.

    ``idxs_ckh`` is a concrete (C, K, H) table, or — device-sampling mode —
    the ``{"t": (C,)}`` spec expanded in-jit through ``sampler`` (index
    draws stay on device; see base.IndexSampler)."""
    from cocoa_tpu.parallel.fanout import chunk_fanout

    per_shard, per_round_batched, apply_fn = _sdca_round_parts(
        params, k, *alg, **parts_kw
    )

    def chunk_kernel(w, alpha, idxs_ckh, shard_arrays):
        if isinstance(idxs_ckh, dict):
            idxs_ckh = sampler.tables_from_ts(idxs_ckh["t"])
        return chunk_fanout(
            mesh, per_shard, apply_fn, w, alpha, idxs_ckh, shard_arrays,
            per_round_batched=per_round_batched,
            # pallas_call's internal slices confuse shard_map's VMA type
            # checker; the manual pvary/psum handling makes it safe to skip
            check_vma=not (parts_kw.get("pallas", False)
                           or parts_kw.get("block_chain", "xla") != "xla"),
        )

    return chunk_kernel


_CHUNK_STEPS: dict = base.ExecutableCache()


def make_chunk_step(mesh, params: Params, k: int, alg, sampler=None,
                    **parts_kw):
    """Build the jitted chunked step: C rounds as one device-side lax.scan
    (see parallel/fanout.py chunk_fanout) — same math as make_round_step,
    one host dispatch per chunk instead of per round.  Executables are cached
    per configuration so repeated run_* calls don't pay a re-jit."""
    key = (
        mesh, k, alg, params.lam, params.n, params.local_iters,
        params.beta, params.gamma, params.loss, params.smoothing,
        None if sampler is None else sampler.cache_token(),
        tuple(sorted(parts_kw.items())),
    )
    step = _CHUNK_STEPS.get(key)
    if step is None:
        kernel = _make_chunk_kernel(mesh, params, k, alg, sampler=sampler,
                                    **parts_kw)
        step = jax.jit(kernel, donate_argnums=(0, 1))
        _CHUNK_STEPS[key] = step
    return step


def run_sdca_family(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    alg_name: str,
    alg,   # (mode, scaling, sigma) — _alg_config
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    rng: str = "reference",
    w_init: Optional[jax.Array] = None,
    alpha_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    scan_chunk: int = 0,
    math: str = "exact",
    pallas=None,
    block_size: int = 0,
    block_chain=None,
    block_sparse_gram=None,
    block_pipeline=None,
    device_loop: bool = False,
    eval_fn=None,
    eval_kernel=None,
    sampling: str = "auto",
    divergence_guard: str = "auto",
    sigma_levels=None,
    warm_start=None,
    sched_init=None,
    accel: bool = False,
    theta: str = "fixed",
    hist_init=None,
    overlap_io: bool = False,
):
    """Shared driver for the SDCA-family algorithms (CoCoA, CoCoA+,
    mini-batch CD — they differ only in their ``alg`` scaling triple, see
    :func:`_alg_config`) and, with eval overrides, the primal prox family
    (solvers/prox_cocoa.py).  Train; returns (w, alpha, Trajectory).

    ``sigma_levels`` / ``warm_start`` select the SCHEDULED path (the
    --sigmaSchedule=anneal / --warmStart machinery, normally reached via
    :func:`run_cocoa`): the solver state gains a tiny float32 schedule
    leaf (base.SCHED_LEN layout) carried through the drive* ladder —
    donated, checkpointed and resumed with (w, α) — and the chunk kernel
    becomes a ``lax.switch`` over statically-specialized per-(σ′ stage,
    loss phase) kernels, selected by the traced stage/round in the
    schedule leaf.  σ′ therefore changes IN the device while_loop with no
    re-dispatch, no retrace and no restart; each branch is exactly the
    fixed-configuration kernel, so a run that never backs off is
    bit-identical to the corresponding fixed-σ′ run.  ``sigma_levels`` is
    the static σ′ ladder (base.anneal_levels; the stall watch fires →
    stage += 1); ``warm_start=(s, warm_end)`` runs smooth_hinge(s) for
    rounds ≤ warm_end (a ``debugIter`` multiple — the chunk/eval cadence
    boundary the in-scan handoff lands on) before the final loss;
    ``sched_init`` restores a mid-schedule checkpoint (base.sched layout,
    bit-identical resume).

    ``eval_fn(state) -> (primal, gap|None, test_err|None)`` and
    ``eval_kernel(state, shard_arrays, test_arrays) -> (3,) metrics``
    override the classification objectives (needed when the state has
    different semantics — e.g. ProxCoCoA+'s residual/coordinates).

    Extensions over the reference: ``gap_target`` stops early once the
    duality gap — checked at the ``debugIter`` cadence — falls below the
    target (the baseline metric counts comm-rounds and wall-clock to reach
    it); ``w_init``/``alpha_init``/``start_round`` resume from a checkpoint
    (see cocoa_tpu.checkpoint) — round-indexed RNG makes the resumed
    trajectory identical to an uninterrupted run; ``scan_chunk > 0`` runs
    rounds device-side in blocks of that size via ``lax.scan`` (fewer host
    dispatches, same math and observable trajectory).

    ``math="fast"`` enables the margins-decomposition inner loop (equal in
    real arithmetic; floating-point rounds differ from the reference order —
    trajectories agree to ~1e-6, convergence behavior is unchanged).
    ``pallas`` (None = auto: fast math + f32 + TPU backend + fits on-chip)
    runs the inner loop as a Pallas TPU kernel — the folded-row dense
    kernel or the lane-blocked sparse (padded-CSR) kernel, by layout;
    requires ``math="fast"``.

    ``block_size > 0`` (flag ``--blockSize``) runs the fast inner loop as
    the block-coordinate MXU kernel (ops/local_sdca.local_sdca_block):
    same sampled index stream, margins via cached block Gram matrices —
    identical in real arithmetic to the sequential fast path, restructured
    so the per-coordinate critical path is O(B) scalar work instead of an
    O(d) dot.  Requires ``math="fast"``; mutually exclusive with the
    Pallas sequential kernels.

    ``device_loop=True`` runs the ENTIRE training loop — all rounds, the
    ``debugIter``-cadence evaluations, and the gap-target early-stop — as
    one ``lax.while_loop`` on device: one dispatch, one host fetch (see
    base.drive_on_device).  Observable trajectory identical to the
    host-stepped drivers; requires debug_iter > 0, not compatible with
    checkpointing (chkpt_iter).

    ``block_sparse_gram`` (None = auto by layout and fit) selects the
    sparse block-chain path for padded-CSR data: the block Gram and margin
    base come from SMEM CSR streams in-kernel and the Δw apply is a sparse
    scatter (ops/pallas_sparse) — no (K, B, d) densify.

    ``block_pipeline`` (None = auto: on for multi-block rounds; flag
    ``--blockPipeline``) software-pipelines the dense block scan: block
    b+1's row-tile gather rides block b's scan iteration with no data
    dependence on its chain kernel, so the gather traffic can hide behind
    the kernel.  Bit-identical to the serial schedule
    (local_sdca_block_batched; parity pinned by tests/test_block.py);
    ``False`` is the A/B control benchmarks/kernels.py measures against.

    ``overlap_io=True`` (flag ``--overlapComm``, single-process runs
    only — resolved by the CLI): checkpoint WRITES on the device-loop
    path ride a daemon writer thread so their serialization + disk IO
    overlaps the next super-block's dispatch (base.drive_device_full);
    the state snapshot stays synchronous, so the written bytes are
    bit-identical to a synchronous save.

    ``divergence_guard`` ("auto" | "on" | "off", flag --divergenceGuard)
    controls the gap-target stall watch: auto arms it only when σ′ is
    overridden below the safe K·γ bound (base.resolve_divergence_guard).

    ``accel=True`` (flag ``--accel``, resolved by :func:`run_cocoa`) runs
    the ACCELERATED outer loop (docs/DESIGN.md "Accelerated outer loop";
    the outer-acceleration structure of Smith et al., arXiv:1711.05305
    with a measured secant extrapolation in place of fixed momentum):
    the state gains a (2, K, n_shard) dual-history leaf ``hist`` and the
    bank/jump/Θ slots on the sched vector (base.ACCEL_LEN layout).  At
    each eval boundary the drivers bank the current α as a window
    snapshot; once two consecutive improving windows are banked, the
    next chunk dispatch opens with a secant (Anderson-1) jump — α moves
    by c·(α − h2) with the signed, data-derived c = ρ/(1−ρ) from the
    window displacements' autocorrelation (base.secant_coef), clipped
    back into the dual box, and w advanced by the exact correspondence
    update Σ y·Δα·x/(λn) (ops/rows.shards_axpy) — so the certified pair
    (w, α) stays a feasible primal-dual pair and the unmodified gap
    evaluation stays the certificate.  A gap rise at an eval boundary
    restarts the bank (one-eval-cadence damage bound).
    ``theta="adaptive"`` additionally runs the Θ local-accuracy ladder
    (base.theta_ladder): early rounds run H/2 inner steps, resolved ON
    DEVICE from the current gap estimate via the same
    statically-specialized ``lax.switch`` branch machinery as the σ′
    stages, tightening to the full H near the target.  ``hist_init``
    restores the window bank from a checkpoint (bit-identical
    mid-momentum resume).
    """
    base.check_shards(ds)
    guard_on = base.resolve_divergence_guard(
        divergence_guard, alg[0], alg[2], ds.k, params.gamma)
    k = ds.k
    if not quiet:
        # ds.n, not params.n: the prox family clones params with n=1 (its
        # update has no λn factor) while ds.n stays the coordinate count
        print(f"\nRunning {alg_name} on {ds.n} data examples, "
              f"distributed over {k} workers")

    dtype = ds.labels.dtype
    if gap_target is not None and dtype == jnp.bfloat16:
        # bf16 cannot certify a small duality gap: the dual objective's
        # Σα/n accumulation and the primal−dual cancellation both sit
        # below bf16's ~2^-8 relative resolution, so the computed gap is
        # noise at 1e-4 scale and the trajectory stalls far above it
        # (measured in tests/test_bf16.py; predicted by docs/DESIGN.md
        # §6).  A gap-targeted bf16 run would either burn its whole round
        # budget or "certify" on rounding artifacts — reject it instead.
        raise ValueError(
            "gap-targeted runs cannot certify in bfloat16 (the duality "
            "gap is below bf16 resolution — docs/DESIGN.md §6); use "
            "--dtype=float32, or drop --gapTarget for an uncertified "
            "bf16 run"
        )
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    alpha = (
        jnp.zeros((k, ds.n_shard), dtype=dtype)
        if alpha_init is None
        else base.align_alpha(alpha_init, ds, dtype)
    )
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding, sharded_rows

        w = jax.device_put(w, primal_sharding(mesh))
        alpha = jax.device_put(alpha, sharded_rows(mesh, extra_dims=1))

    from cocoa_tpu.parallel.mesh import has_fp
    from cocoa_tpu.parallel.fanout import shards_per_device

    # logical shards resident per device: k on the single-chip path, K/D on
    # a (possibly multiplexed) dp mesh — the unit the VMEM fit checks see
    m_local = shards_per_device(mesh, k) if mesh is not None else k
    platform = jax.devices()[0].platform
    if pallas is None and block_size > 0:
        # the block-coordinate kernel is an alternative inner loop — it and
        # the Pallas sequential kernels are mutually exclusive by design
        pallas = False
    if pallas is None:
        # auto: the Pallas kernels need fast math + f32 + a real TPU
        # backend (measured vs the fori_loop path: ~4x faster rounds at
        # epsilon scale dense — folded rows run the O(d) work at full VPU
        # width; ~25x faster steps at rcv1 scale sparse — lane-blocked
        # w/Δw make a nonzero's access O(128) and margins never leave
        # VMEM) — AND the kernel's VMEM-resident
        # working set must fit (pallas_sdca.vmem_estimate/pick_unroll own
        # that accounting — pick_unroll also chooses how many row DMAs to
        # batch per grid step).  Oversized runs keep the fori_loop fast path
        # (explicit pallas=True overrides, and Mosaic then reports the
        # allocation failure itself).
        from cocoa_tpu.ops.pallas_sdca import pick_unroll
        from cocoa_tpu.ops.pallas_sparse import sparse_kernel_fits

        itemsize = jnp.dtype(dtype).itemsize
        if ds.layout == "dense":
            fits = pick_unroll(ds.n_shard, ds.num_features, itemsize,
                               params.local_iters) > 0
        else:
            # sparse kernel: the SMEM feature-index table and the
            # lane-blocked d-vectors must fit (pallas_sparse docstring);
            # hybrid layouts additionally account the hot panel's VMEM
            # (per-shard Δw_hot + the per-step panel row buffers)
            fits = sparse_kernel_fits(
                m_local, ds.n_shard, ds.num_features,
                int(ds.sp_indices.shape[-1]), params.local_iters, itemsize,
                n_hot=ds.n_hot,
            )
        pallas = (
            math == "fast"
            and itemsize == 4
            and platform in ("tpu", "axon")
            and fits
            # the kernels' VMEM blocks assume the full d per device;
            # feature-parallel runs keep the fori_loop fast path
            and not has_fp(mesh)
        )
    if pallas and has_fp(mesh):
        raise ValueError(
            "the Pallas SDCA kernel does not support feature-parallel (fp) "
            "meshes; use pallas=False"
        )
    if pallas and math != "fast":
        raise ValueError("pallas=True requires math='fast'")
    if pallas and platform not in ("tpu", "axon", "cpu"):
        raise ValueError(
            f"the Pallas SDCA kernel needs a TPU backend (or CPU interpret "
            f"mode); current platform is {platform!r}"
        )
    # the block recurrence rides its own Pallas kernel when it can (TPU,
    # f32, whole lane tiles, no feature-parallel axis, fits VMEM —
    # ops/pallas_chain.py); otherwise the portable XLA fori_loop chain
    # (also what the x64 CPU validation tests compare).  ``block_chain``
    # overrides the auto choice (tests use "pallas_interpret" to exercise
    # the driver-integrated kernel path on CPU).
    if block_chain is not None:
        if block_chain not in ("xla", "pallas", "pallas_interpret"):
            raise ValueError(f"block_chain must be xla|pallas|"
                             f"pallas_interpret, got {block_chain!r}")
        if block_chain != "xla" and has_fp(mesh):
            raise ValueError("the Pallas block-chain kernel does not "
                             "support feature-parallel (fp) meshes")
    else:
        from cocoa_tpu.ops.pallas_chain import chain_fits

        block_chain = "xla"
        if (
            block_size > 0
            and block_size % 128 == 0
            and jnp.dtype(dtype).itemsize == 4
            and platform in ("tpu", "axon")
            # the kernel assumes the full d per device
            and not has_fp(mesh)
            # VMEM working set: K/D shards per device on the mesh path
            # (1 when 1:1), all K logical shards on the single-chip path
            and chain_fits(m_local, block_size, 4)
        ):
            block_chain = "pallas"
    parts_kw = dict(
        math=math, pallas=pallas,
        pallas_interpret=(pallas and platform == "cpu"),
        block=block_size, block_chain=block_chain,
        block_sparse_gram=block_sparse_gram,
        block_pipeline=block_pipeline,
        # permuted sampling with n_local % H == 0 keeps every round inside
        # one epoch's permutation, so the round's H draws are pairwise
        # distinct per shard — the license for the block kernel's
        # one-scatter-per-round α update (local_sdca_block_batched)
        block_distinct=(
            block_size > 0
            and rng == "permuted"
            and bool(np.all(np.asarray(ds.counts) % params.local_iters == 0))
        ),
    )
    # the Pallas kernels (sequential and block-chain) own the shard axis
    # themselves, which neither the per-round driver's vmap path nor its
    # plain fanout shard_map can express — route through the chunked driver
    if (pallas or block_chain != "xla") and scan_chunk <= 0:
        scan_chunk = 1

    sampler = base.IndexSampler(rng, debug.seed, params.local_iters, ds.counts)
    sampler.device = base.resolve_sampling(sampling, sampler,
                                           params.num_rounds)
    shard_arrays = ds.shard_arrays()
    if pallas and ds.layout == "dense":
        # fold X for the dense kernel ONCE per DATASET (cached on the ds
        # object): folding inside the round loop would relayout the whole
        # X every round, and folding per RUN was a measured fixed cost a
        # process that reuses the dataset — the bench slope pair, sweep
        # loops, the sigma=auto trial+safe pair — paid on every call
        # (bench.py's fixed-cost breakdown, VERDICT r5 weak #6).  Safe to
        # share: the folded tile is a jit INPUT (never donated), so no
        # dispatch can overwrite it.
        folded = getattr(ds, "_x_folded_cache", None)
        if folded is None:
            from cocoa_tpu.ops.pallas_sdca import fold_rows

            folded = fold_rows(shard_arrays["X"])
            ds._x_folded_cache = folded
        shard_arrays = {**shard_arrays, "X_folded": folded}
    if (pallas or block_size > 0) and ds.layout == "sparse":
        # per-row nnz counts for the kernels' group early exit (sequential
        # sparse kernel AND the sparse block-chain path) — same per-dataset
        # cache rationale as the dense fold above (per round it would
        # re-read the whole values array inside the scan)
        row_len = getattr(ds, "_row_len_cache", None)
        if row_len is None:
            from cocoa_tpu.ops.pallas_sparse import row_lengths

            row_len = row_lengths(shard_arrays["sp_values"])
            ds._row_len_cache = row_len
        shard_arrays = {**shard_arrays, "sp_row_len": row_len}

    if eval_fn is None:
        def eval_fn(state):
            # state[0:2] — the scheduled path appends the sched leaf; the
            # duality-gap certificate reads only (w, α) and is exact under
            # any σ′/loss stage (which is the backoff's soundness argument)
            return objectives.evaluate(
                ds, state[0], state[1], params.lam, test_ds=test_ds,
                loss=params.loss, smoothing=params.smoothing)

    if theta not in ("fixed", "adaptive"):
        raise ValueError(f"theta must be fixed|adaptive, got {theta!r}")
    if accel:
        if debug.debug_iter <= 0:
            raise ValueError(
                "--accel requires --debugIter > 0 (the momentum restart "
                "rule rides the eval cadence)")
        if theta == "adaptive" and gap_target is None:
            raise ValueError(
                "--theta=adaptive requires --gapTarget (the Θ ladder's "
                "final full-accuracy stage is keyed to the target)")
    scheduled = ((sigma_levels is not None and len(sigma_levels) > 1)
                 or warm_start is not None)
    if (scheduled or accel) and scan_chunk <= 0 and not device_loop:
        # the schedule leaf rides the chunked/device drivers' state; the
        # per-round driver path is equivalent at chunk=1 (pinned by tests)
        scan_chunk = 1

    if device_loop or scan_chunk > 0:
        import dataclasses as _dc

        sched_token = None
        accel_cfg = None
        if scheduled or accel:
            levels = (tuple(float(v) for v in sigma_levels)
                      if sigma_levels is not None else (float(alg[2]),))
            warm_end = 0
            branch_params = [params]
            if warm_start is not None:
                warm_s, warm_end = warm_start
                if debug.debug_iter <= 0:
                    raise ValueError(
                        "warm_start needs debug_iter > 0 (the loss handoff "
                        "lands on the eval-cadence chunk boundary)")
                if warm_end % debug.debug_iter != 0:
                    raise ValueError(
                        f"warm_start rounds ({warm_end}) must be a multiple "
                        f"of debugIter ({debug.debug_iter}) — the CLI "
                        f"rounds up for you")
                branch_params = [
                    _dc.replace(params, loss="smooth_hinge",
                                smoothing=float(warm_s)),
                    params,
                ]
            n_phases = len(branch_params)
            n_levels = len(levels)
        if accel:
            # --- the accelerated outer loop ------------------------------
            # Branch table = (σ′ stage × loss phase × Θ stage), every
            # branch the SAME statically-specialized chunk the plain
            # scheduled path builds (_make_chunk_kernel): the Θ stage
            # slices the sampled index tables to its H_s prefix — every
            # mode's draw stream is prefix-stable, so a stage only runs
            # FEWER of the reference draws, never different ones — and
            # the traced schedule state picks which branch runs, exactly
            # the σ′ anneal pattern.  The chunk head additionally
            # consumes an armed secant jump (A_JUMP, set by the drivers'
            # eval-boundary bookkeeping): the rounds themselves are
            # UNMODIFIED CoCoA+ — acceleration lives entirely between
            # windows, so the certificate arithmetic never changes.
            from cocoa_tpu.ops import rows as _rows

            accel_cfg = base.AccelConfig(
                base.theta_ladder(params.local_iters, theta == "adaptive"),
                gap_target)
            n_theta = accel_cfg.n_theta
            full_h = params.local_iters
            if n_theta > 1 and (parts_kw.get("pallas")
                                or parts_kw.get("block", 0) > 0):
                raise ValueError(
                    "--theta=adaptive slices the sequential (C, K, H) "
                    "index tables and is not available on the Pallas/"
                    "--blockSize paths (their kernels and the "
                    "block-distinct sampling license are keyed to the "
                    "full H); drop --theta=adaptive or the block flags")

            def _accel_branch(bp, lv, hs):
                bph = (bp if hs >= full_h
                       else _dc.replace(bp, local_iters=int(hs)))
                kern = _make_chunk_kernel(mesh, bph, k,
                                          (alg[0], alg[1], lv),
                                          sampler=sampler, **parts_kw)

                def branch(w, alpha, idxs_ckh, shard_arrays):
                    idxs = (idxs_ckh if hs >= full_h
                            else idxs_ckh[:, :, :hs])
                    return kern(w, alpha, idxs, shard_arrays)

                return branch

            branches = [_accel_branch(bp, lv, hs)
                        for lv in levels for bp in branch_params
                        for hs in accel_cfg.theta_hs]
            inv_lam_n = 1.0 / (params.lam * params.n)

            def accel_kernel(w, alpha, hist, sched, idxs_ckh,
                             shard_arrays):
                if isinstance(idxs_ckh, dict):
                    idxs_ckh = sampler.tables_from_ts(idxs_ckh["t"])
                c_len = idxs_ckh.shape[0]

                def take_jump(w, alpha):
                    # secant (Anderson-1) jump from the banked window
                    # displacements (solvers/base.py layout note): the
                    # jumped α is clipped to the hinge-family dual box
                    # and padding-masked, and w advances by the EXACT
                    # correspondence update — (w, α) stays a feasible
                    # certified pair
                    d1 = hist[1] - hist[0]
                    den = jnp.vdot(d1, d1)
                    rho = jnp.where(
                        den > 0,
                        jnp.vdot(d1, alpha - hist[1])
                        / jnp.where(den > 0, den, jnp.float32(1)),
                        jnp.float32(0))
                    cj = base.secant_coef(jnp, rho)
                    a_ext = jnp.clip(alpha + cj * (alpha - hist[1]),
                                     0.0, 1.0) * shard_arrays["mask"]
                    coefs = (shard_arrays["labels"] * (a_ext - alpha)
                             * jnp.float32(inv_lam_n))
                    return _rows.shards_axpy(coefs, shard_arrays, w), a_ext

                w, alpha = jax.lax.cond(
                    sched[base.A_JUMP] > 0, take_jump,
                    lambda w, a: (w, a), w, alpha)
                sched = sched.at[base.A_JUMP].set(jnp.float32(0))
                stage = jnp.clip(sched[0].astype(jnp.int32), 0,
                                 n_levels - 1)
                th = jnp.clip(sched[base.A_TH_STAGE].astype(jnp.int32), 0,
                              n_theta - 1)
                if n_phases == 2:
                    # same invariant as the scheduled branch below:
                    # chunks never straddle an eval-cadence boundary, so
                    # one phase test per chunk is exact (keep the two
                    # branch-index computations in sync)
                    warm_now = (sched[4] + (c_len - 1)
                                <= jnp.float32(warm_end))
                    ph = jnp.where(warm_now, 0, 1)
                else:
                    ph = 0
                br = (stage * n_phases + ph) * n_theta + th
                w2, a2 = jax.lax.switch(br, branches, w, alpha, idxs_ckh,
                                        shard_arrays)
                sched2 = sched.at[4].add(jnp.float32(c_len))
                return w2, a2, hist, sched2

            def chunk_kernel(state, idxs_ckh, shard_arrays):
                return accel_kernel(state[0], state[1], state[2], state[3],
                                    idxs_ckh, shard_arrays)

            sched_token = ("accel", levels, warm_end,
                           branch_params[0].loss,
                           branch_params[0].smoothing,
                           accel_cfg.theta_hs)
            step_key = (
                "accel", mesh, k, alg[0], alg[1], sched_token,
                params.lam, params.n, params.local_iters, params.beta,
                params.gamma, params.loss, params.smoothing,
                sampler.cache_token(), tuple(sorted(parts_kw.items())),
            )
            chunk_step = _CHUNK_STEPS.get(step_key)
            if chunk_step is None:
                # hist is read-only in the kernel (the drivers rebind it
                # at eval boundaries), so it stays un-donated
                chunk_step = jax.jit(accel_kernel,
                                     donate_argnums=(0, 1, 3))
                _CHUNK_STEPS[step_key] = chunk_step

            def chunk_fn(t0, c, state):
                return chunk_step(state[0], state[1], state[2], state[3],
                                  sampler.chunk_indices(t0, c),
                                  shard_arrays)

            hist0 = (jnp.zeros((2,) + alpha.shape, dtype=dtype)
                     if hist_init is None
                     else jnp.array(hist_init, dtype=dtype, copy=True))
            sched0 = base.sched_init_array(start_round, sched_init,
                                           accel=True)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from cocoa_tpu.parallel.mesh import DP_AXIS

                hist0 = jax.device_put(
                    hist0, NamedSharding(mesh, P(None, DP_AXIS)))
                sched0 = jax.device_put(sched0, NamedSharding(mesh, P()))
            state0 = (w, alpha, hist0, sched0)
        elif scheduled:
            # one statically-specialized kernel per (σ′ stage, loss phase):
            # every Pallas/block configuration keeps its baked-in scalars,
            # and the traced schedule state only picks WHICH one runs
            branches = [
                _make_chunk_kernel(mesh, bp, k, (alg[0], alg[1], lv),
                                   sampler=sampler, **parts_kw)
                for lv in levels for bp in branch_params
            ]

            def sched_kernel(w, alpha, sched, idxs_ckh, shard_arrays):
                c_len = jax.tree.leaves(idxs_ckh)[0].shape[0]
                stage = jnp.clip(sched[0].astype(jnp.int32), 0, n_levels - 1)
                if n_phases == 2:
                    # the chunk is warm iff it ends at or before warm_end;
                    # chunks never straddle an eval-cadence boundary (the
                    # drivers cut them there), so this is exact for every
                    # driver and chunk split
                    warm_now = sched[4] + (c_len - 1) <= jnp.float32(warm_end)
                    br = stage * 2 + jnp.where(warm_now, 0, 1)
                else:
                    br = stage
                w2, a2 = jax.lax.switch(br, branches, w, alpha, idxs_ckh,
                                        shard_arrays)
                return w2, a2, sched.at[4].add(jnp.float32(c_len))

            def chunk_kernel(state, idxs_ckh, shard_arrays):
                return sched_kernel(state[0], state[1], state[2], idxs_ckh,
                                    shard_arrays)

            sched_token = (levels, warm_end,
                           branch_params[0].loss, branch_params[0].smoothing)
            step_key = (
                "sched", mesh, k, alg[0], alg[1], sched_token,
                params.lam, params.n, params.local_iters, params.beta,
                params.gamma, params.loss, params.smoothing,
                sampler.cache_token(), tuple(sorted(parts_kw.items())),
            )
            chunk_step = _CHUNK_STEPS.get(step_key)
            if chunk_step is None:
                chunk_step = jax.jit(sched_kernel, donate_argnums=(0, 1, 2))
                _CHUNK_STEPS[step_key] = chunk_step

            def chunk_fn(t0, c, state):
                return chunk_step(state[0], state[1], state[2],
                                  sampler.chunk_indices(t0, c), shard_arrays)

            sched0 = base.sched_init_array(start_round, sched_init)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                sched0 = jax.device_put(sched0, NamedSharding(mesh, P()))
            state0 = (w, alpha, sched0)
        else:
            levels = None
            raw_kernel = _make_chunk_kernel(mesh, params, k, alg,
                                            sampler=sampler, **parts_kw)

            def chunk_kernel(state, idxs_ckh, shard_arrays):
                return raw_kernel(state[0], state[1], idxs_ckh, shard_arrays)

            chunk_step = make_chunk_step(mesh, params, k, alg,
                                         sampler=sampler, **parts_kw)

            def chunk_fn(t0, c, state):
                return chunk_step(state[0], state[1],
                                  sampler.chunk_indices(t0, c), shard_arrays)

            state0 = (w, alpha)

        cache_key = (
            "sdca", alg_name, alg, math, pallas, block_size, block_chain,
            block_sparse_gram, block_pipeline, sched_token,
            sampler.cache_token(), k, mesh,
            params.lam, params.n, params.local_iters, params.beta,
            params.gamma, params.loss, params.smoothing,
            params.num_rounds, debug.debug_iter, start_round,
            gap_target, ds.layout, str(dtype),
        )
        state, traj = base.drive_device_paths(
            alg_name, params, debug, state0, chunk_kernel, chunk_fn,
            eval_fn, sampler, shard_arrays, alpha_in_state=True, mesh=mesh,
            test_ds=test_ds, quiet=quiet, gap_target=gap_target,
            start_round=start_round, scan_chunk=scan_chunk,
            device_loop=device_loop, cache_key=cache_key,
            eval_kernel=eval_kernel, divergence_guard=guard_on,
            sigma_levels=levels, accel=accel_cfg,
            overlap_io=overlap_io,
        )
        return state[0], state[1], traj

    step = make_round_step(mesh, params, k, alg, **parts_kw)

    def round_fn(t, state):
        w, alpha = state
        return step(w, alpha, sampler.round_indices(t), shard_arrays)

    (w, alpha), traj = base.drive(
        alg_name, params, debug, (w, alpha), round_fn, eval_fn,
        quiet=quiet, gap_target=gap_target, start_round=start_round,
        divergence_guard=guard_on,
    )
    return w, alpha, traj


def run_cocoa(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    plus: bool,
    sigma_schedule: Optional[str] = None,
    warm_start=None,
    accel: Optional[str] = None,
    theta: Optional[str] = None,
    **kw,
):
    """CoCoA (plus=False, averaging, scaling β/K) / CoCoA+ (plus=True,
    additive, scaling γ with σ′ = K·γ) — CoCoA.scala:22-66.  Train; returns
    (w, alpha, Trajectory).  See :func:`run_sdca_family` for the keyword
    options (mesh, rng, gap_target, scan_chunk, math, pallas, device_loop,
    checkpoint/resume).

    ``params.sigma="auto"`` (flag ``--sigma=auto``) exploits the measured
    σ′ trade-off (benchmarks/SWEEPS.md: the aggressive σ′ = K·γ/2 HALVES
    the certified comm-rounds on randomly partitioned data, while σ′
    pushed below the data's coherence diverges) in one of two ways,
    selected by ``sigma_schedule`` (flag ``--sigmaSchedule``):

    - ``"anneal"`` (the default): a DEVICE-RESIDENT schedule — start at
      K·γ/2 and, when the stall watch fires, back σ′ off multiplicatively
      toward the safe K·γ *inside* the driver loop, continuing from the
      current iterate (sound: the primal-dual correspondence and the α
      box are σ′-independent, so the exact gap certificate survives the
      switch).  A wrong guess costs one stall window, never a restart.
    - ``"trial"`` (the A/B control — the pre-schedule behavior, bit-exact):
      run a guarded trial at K·γ/2 and, if the divergence guard fires,
      RESTART from scratch at the safe K·γ.

    ``sigma_schedule="anneal"`` with an explicit ``--sigma=<float>`` below
    the safe bound anneals from that σ′ instead (the deliberately
    divergence-prone configs in the tests start there).

    ``warm_start=(s, rounds)`` (flag ``--warmStart=<s>,<rounds>``): run a
    smooth_hinge(s) phase for the first ``rounds`` rounds (rounded up to
    the ``debugIter`` cadence), handing off to hinge inside the same
    device loop — the measured-but-manual SWEEPS.md "warm smooth_hinge"
    procedure as a flag.  Requires ``--loss=hinge``; the handoff is exact
    because the smooth-hinge dual keeps α in the hinge dual's [0,1] box,
    and the reported gap is the hinge certificate throughout.

    ``accel`` ("auto" | "on" | "off", flag ``--accel``): the accelerated
    outer loop — a secant (Anderson-1) extrapolation of the dual at
    eval-window boundaries, with a gap-monitored restart (see
    :func:`run_sdca_family`).  ``auto`` enables it for gap-targeted
    CoCoA+ runs (the regime the round-count win is measured in);
    ``off`` (the library default) is bit-identical to the
    pre-acceleration code.  ``theta`` ("fixed" | "adaptive", flag
    ``--theta``): the adaptive local-accuracy ladder — early rounds run
    far fewer inner SDCA steps, resolved on device from the current gap
    estimate; requires an accelerated gap-targeted run.  Not available
    with ``--sigmaSchedule=trial`` (the trial is the bit-exact
    pre-schedule A/B control and stays untouched)."""
    import dataclasses as _dc

    if sigma_schedule not in (None, "trial", "anneal"):
        raise ValueError(f"sigma schedule must be trial|anneal, got "
                         f"{sigma_schedule!r}")
    accel = "off" if accel is None else str(accel).lower()
    if accel not in ("auto", "on", "off"):
        raise ValueError(f"accel must be auto|on|off, got {accel!r}")
    theta = "fixed" if theta is None else str(theta).lower()
    if theta not in ("fixed", "adaptive"):
        raise ValueError(f"theta must be fixed|adaptive, got {theta!r}")
    if sigma_schedule == "trial":
        # the trial path is preserved bit-exact as the pre-schedule A/B
        # control — acceleration on top would change what it controls for
        if accel == "on":
            raise ValueError(
                "--accel cannot ride --sigmaSchedule=trial (the trial is "
                "the bit-exact A/B control); use --sigmaSchedule=anneal")
        accel = "off"
    # resolve auto HERE (before the sigma=auto recursion, whose inner
    # calls see sigma already replaced): on for gap-targeted CoCoA+ runs
    # — the regime where momentum's round-count win is measured and the
    # restart rule has a gap to monitor
    accel_on = (accel == "on"
                or (accel == "auto" and plus
                    and kw.get("gap_target") is not None))
    if theta == "adaptive" and not accel_on:
        if accel == "off":
            raise ValueError(
                "--theta=adaptive requires an accelerated run: pass "
                "--accel=on, or --accel=auto with --gapTarget on CoCoA+")
        # accel=auto resolved OFF for this run (plain-CoCoA leg of the
        # CLI's run_all, or no gap target): Θ is an accelerated-run
        # knob, so it degrades to the full-H schedule instead of
        # rejecting a run the caller never asked to accelerate
        theta = "fixed"
    accel_kw = dict(accel="on" if accel_on else "off", theta=theta)
    if warm_start is not None:
        s_w, r_w = warm_start
        if params.loss != "hinge":
            raise ValueError(
                "--warmStart hands a smooth_hinge phase off to hinge and "
                "requires --loss=hinge")
        if not float(s_w) > 0:
            raise ValueError(
                f"--warmStart smoothing must be > 0, got {s_w}")
        if int(r_w) < 1:
            raise ValueError(
                f"--warmStart rounds must be >= 1, got {r_w}")
        if debug.debug_iter <= 0:
            raise ValueError(
                "--warmStart requires --debugIter > 0 (the in-loop "
                "handoff lands on the eval-cadence chunk boundary)")
        r_al = -(-int(r_w) // debug.debug_iter) * debug.debug_iter
        if r_al != int(r_w) and not kw.get("quiet", False):
            print(f"warmStart: handoff rounded up to round {r_al} "
                  f"(the debugIter={debug.debug_iter} cadence the device "
                  f"loop chunks on)")
        warm_start = (float(s_w), r_al)

    safe = ds.k * params.gamma
    if params.sigma == "auto":
        if not plus:
            # σ′ only enters the plus-mode subproblem (CoCoA.scala:158-160);
            # plain CoCoA ignores it, so auto degenerates to the default —
            # important because the reference driver runs BOTH algorithms
            # from one flag set (hingeDriver.scala:84-89)
            return run_cocoa(ds, _dc.replace(params, sigma=None), debug,
                             plus, warm_start=warm_start, **accel_kw, **kw)
        if (sigma_schedule or "anneal") == "anneal":
            return _run_cocoa_anneal(
                ds, params, debug, plus,
                base.anneal_levels(safe / 2.0, safe), warm_start, accel_kw,
                kw)
        if kw.get("gap_target") is None:
            # the divergence guard rides the gap-target early-stop path; a
            # fixed-round auto run could burn its whole budget diverged
            # and never trigger the fallback
            raise ValueError("--sigma=auto requires --gapTarget (the "
                             "σ′ fallback triggers on the divergence "
                             "guard, which runs on the gap-target path)")
        if kw.get("divergence_guard", "auto") == "off":
            # the trial's only exit from a bad guess IS the guard
            raise ValueError("--sigma=auto requires the divergence guard "
                             "(drop --divergenceGuard=off)")
        quiet = kw.get("quiet", False)
        if kw.get("w_init") is not None or kw.get("start_round", 1) > 1:
            # a RESUMED run must not re-experiment: the restored state may
            # be mid-trial (possibly diverging), and a trial verdict from
            # it is meaningless.  Continue with the safe σ′ — any (w, α)
            # is a valid primal-dual pair, so the safe run converges from
            # the restored state and the certificate stays exact.
            if not quiet:
                print("sigma=auto: resumed run continues with the safe "
                      f"σ′=K·γ={ds.k * params.gamma:g} (no re-trial from "
                      "restored state)")
            return run_cocoa(ds, _dc.replace(params, sigma=None), debug,
                             plus, warm_start=warm_start, **accel_kw, **kw)
        import os as _os

        ckpt_dir = debug.chkpt_dir if debug.chkpt_iter > 0 else ""
        before = (set(_os.listdir(ckpt_dir))
                  if ckpt_dir and _os.path.isdir(ckpt_dir) else set())
        trial = _dc.replace(params, sigma=ds.k * params.gamma / 2.0)
        w, alpha, traj = run_cocoa(ds, trial, debug, plus,
                                   warm_start=warm_start, **kw)
        if traj.stopped != "diverged":
            return w, alpha, traj
        if ckpt_dir and _os.path.isdir(ckpt_dir):
            # the diverged trial's checkpoints must not survive: the safe
            # rerun restarts from round 1, and a later --resume would
            # otherwise pick the trial's (higher-round, diverged) state.
            # Deletion is scoped to THIS run's files only — the exact
            # algorithm prefix the trial's checkpoint writer used and the
            # round range it actually reached — so a concurrent CoCoA /
            # CoCoA+ run sharing the directory (elastic workers, parallel
            # sweeps) can never lose its checkpoints to our cleanup
            # (ADVICE r5: the bare 'CoCoA' prefix matched them all).
            import re as _re

            algo = ("CoCoA+" if plus else "CoCoA").replace(" ", "_")
            last = traj.records[-1].round if traj.records else 0
            stamp = _re.compile(
                _re.escape(algo) + r"-r(\d+)\.(npz|npz\.json|json)$")
            for f in sorted(set(_os.listdir(ckpt_dir)) - before):
                m = stamp.match(f)
                if m and int(m.group(1)) <= last:
                    _os.remove(_os.path.join(ckpt_dir, f))
        from cocoa_tpu.telemetry import events as _tele

        _tele.get_bus().emit(
            "restart", reason="sigma_trial_diverged",
            algorithm="CoCoA+" if plus else "CoCoA",
            sigma_trial=trial.sigma, sigma_safe=ds.k * params.gamma,
            round=traj.records[-1].round if traj.records else 0)
        if not quiet:
            print(f"sigma=auto: σ′=K·γ/2={trial.sigma:g} diverged; "
                  f"restarting with the safe σ′=K·γ={ds.k * params.gamma:g}")
        safe_params = _dc.replace(params, sigma=None)
        # from SCRATCH: strip any resume state so the safe run cannot
        # inherit the diverged trial's iterates (belt to the resumed-run
        # guard's suspenders above)
        safe_kw = {k2: v for k2, v in kw.items()
                   if k2 not in ("w_init", "alpha_init", "start_round",
                                 "sched_init", "hist_init")}
        return run_cocoa(ds, safe_params, debug, plus,
                         warm_start=warm_start, **accel_kw, **safe_kw)

    if sigma_schedule == "trial":
        raise ValueError(
            "sigma schedule 'trial' is the --sigma=auto A/B control; it "
            "needs --sigma=auto")
    if (sigma_schedule == "anneal" and plus and params.sigma is not None
            and float(params.sigma) < safe):
        # anneal from an explicit aggressive σ′ (the divergence-prone
        # configs the schedule exists to rescue start here)
        return _run_cocoa_anneal(
            ds, params, debug, plus,
            base.anneal_levels(float(params.sigma), safe), warm_start,
            accel_kw, kw)

    alg = _alg_config(params, ds.k, plus)
    return run_sdca_family(
        ds, params, debug, "CoCoA+" if plus else "CoCoA", alg,
        warm_start=warm_start, accel=accel_on, theta=theta, **kw
    )


# --- bounded-staleness CoCoA+ aggregation (--staleRounds, round 17) ---------
#
# The bulk-synchronous round pays the slowest worker's wall-clock at
# every barrier.  Bounded staleness relaxes the barrier, not the math:
# a worker may start round t+1 with peer contributions for rounds
# (t-S, t] still outstanding, as long as every round-r contribution is
# APPLIED before round r+S+1's local solve begins (the join window).
#
# Safety (the adding-vs-averaging analysis, Ma et al. arXiv:1502.03508):
# every local subproblem is solved against σ′ = K·γ — the bound that
# makes SIMULTANEOUS additive aggregation of all K contributions safe.
# Applying a SUBSET of m ≤ K contributions with the same γ is strictly
# inside that safety region (the subset's mutual interference is
# bounded by m/K of what σ′ already covers), and a late contribution
# joining alone later is the m = 1 case.  The scale must be the SAME γ
# for every contribution regardless of when it joins: the owner already
# advanced its α by γ·Δα at solve time, so any other Δw scale would
# break the primal-dual correspondence w = (1/λn)·Σ y·α·x that the
# exact duality-gap certificate rests on (:func:`partial_gamma` is
# where that argument lives).  The trajectory changes — a late joiner's
# peers ran a few rounds on a w missing its Δw — but the certificate
# does not: the gap is evaluated on the ACTUAL (w, α) at a drained
# boundary, where every contribution has landed and w = w(α) holds
# exactly again (the general-CoCoA inexactness argument,
# arXiv:1611.02189 — the certificate never assumed a particular
# trajectory).
#
# Determinism: the join window is ROUND-indexed, never arrival-indexed.
# Which contribution joins at which round is a pure function of round
# numbers (round r joins at round r+S), so the trajectory is
# bit-reproducible run to run and the asynchrony moves the WAITING off
# the critical path, not the data.  Whoever arrives early is simply
# already in the collector's buffer when its join round comes due.
#
# Docs: docs/DESIGN.md §15 "Asynchrony model".


def partial_gamma(gamma: float, k: int, m: int) -> float:
    """The safe aggregation scale for applying ``m`` of ``k`` CoCoA+
    contributions whose local subproblems were solved against
    σ′ = K·γ.

    Returns γ unchanged — deliberately.  σ′ ≥ γ·m holds for every
    m ≤ K, so the subset application is safe at γ (the adding analysis
    bounds the interference of ν simultaneous updates by σ′ ≥ γ·ν, and
    a subset has less interference than the full gang σ′ was sized
    for).  An UP-scaled subset (γ·K/m — also admissible by the bound)
    is rejected by design: the owner applied α += γ·Δα at solve time
    without knowing which peers would make the same on-time subset, so
    any size-dependent Δw scale would need a gang-wide agreement
    protocol to keep w = w(α) — and a disagreement breaks the exact
    certificate, the one thing this mode must never do."""
    if not 1 <= m <= k:
        raise ValueError(f"partial aggregation needs 1 <= m <= K, got "
                         f"m={m}, K={k}")
    return float(gamma)


class StaleJoinWindow:
    """Bounded-staleness join-window bookkeeping for a host-exchange
    gang round (the policy half of ``--staleRounds``; the transport is
    parallel/distributed.py's :class:`ExchangeHandle`).

    Per round ``t`` the caller posts its contribution, wraps the
    exchange in a handle, and calls :meth:`admit` followed by
    :meth:`join_due` — which joins exactly the rounds whose window
    expires at ``t`` (round r at t = r + S) and returns their payloads
    for application.  :meth:`drain` force-joins everything pending (the
    eval/checkpoint boundaries — the points where w = w(α) must hold
    exactly for the certificate and for a resumable checkpoint).
    ``stale_rounds=0`` degenerates to today's synchronous barrier:
    round t joins at round t.

    **Gap-rise collapse** (:meth:`on_eval`): a gap rise at an eval
    boundary collapses the window to synchronous (S = 0) until a later
    eval improves again — the ``momentum_restart`` pattern: damage from
    staleness-hurt progress is bounded to one eval cadence, and the
    collapse discards the permission for further stale joins rather
    than any applied contribution (an applied Δw can never be unwound
    without breaking w = w(α)).

    **Elastic interaction** (:meth:`abort`): a gang teardown or resize
    drops pending handles without joining them — the collector daemons
    die with the process, the bounded KV budget caps any straggling
    get, and the next generation resumes from a DRAINED checkpoint, so
    no half-joined round can ever leak across generations.

    Emits one typed ``stale_join`` event per late-joined round
    (``rounds_late >= 1``); synchronous joins are not events.
    """

    def __init__(self, stale_rounds: int, algorithm: str = "CoCoA+"):
        s = int(stale_rounds)
        if s < 0:
            raise ValueError(f"staleRounds must be >= 0, got {stale_rounds}")
        self.stale_rounds = s
        self.algorithm = algorithm
        self.collapsed = False   # gap-rise: window forced to 0
        self._last_gap = None
        self._pending: dict = {}   # round -> ExchangeHandle | payload list

    def effective_window(self) -> int:
        return 0 if self.collapsed else self.stale_rounds

    def pending_rounds(self) -> list:
        return sorted(self._pending)

    def admit(self, t: int, handle) -> None:
        """Register round ``t``'s in-flight exchange (an ExchangeHandle,
        or an already-collected payload list on the synchronous path)."""
        if t in self._pending:
            raise ValueError(f"round {t} already has a pending exchange")
        self._pending[t] = handle

    def join_due(self, t: int) -> list:
        """Join every round whose window expires by round ``t`` (rounds
        r <= t - S).  Returns ``[(round, payloads, rounds_late), ...]``
        in round order; ``rounds_late = t - r`` is bounded by the
        CONFIGURED window (never admits later than S — pinned)."""
        cut = t - self.effective_window()
        return self._join([r for r in sorted(self._pending) if r <= cut], t)

    def drain(self, t: int) -> list:
        """Force-join everything pending (eval/checkpoint boundary): the
        returned contributions must be applied before the gap is
        evaluated, restoring exact w = w(α)."""
        return self._join(sorted(self._pending), t)

    def abort(self) -> None:
        """Drop pending handles without joining (teardown/resize): the
        daemon collectors die with the process; nothing is applied."""
        self._pending.clear()

    def _join(self, rounds: list, t: int) -> list:
        from cocoa_tpu.telemetry import events as _tele

        out = []
        for r in rounds:
            h = self._pending.pop(r)
            payloads = h.join() if hasattr(h, "join") else h
            late = max(0, t - r)
            if late > self.stale_rounds:
                # the user-facing bound (and what keeps the
                # rounds_late metrics label set finite) — a caller that
                # skipped join_due for some round must fail loudly, not
                # silently apply an arbitrarily stale contribution
                raise RuntimeError(
                    f"round {r} would join {late} rounds late — past "
                    f"the --staleRounds={self.stale_rounds} window; a "
                    f"caller skipped join_due for it")
            if late >= 1:
                _tele.get_bus().emit(
                    "stale_join", algorithm=self.algorithm, t=int(t),
                    round=int(r), rounds_late=int(late),
                    workers=len(payloads) if payloads is not None else None)
            out.append((r, payloads, late))
        return out

    def on_eval(self, gap) -> bool:
        """The gap-rise rule at an eval boundary (call AFTER
        :meth:`drain` + gap evaluation): a rise collapses the window to
        synchronous until an improving eval restores it.  Returns True
        when this eval changed the collapse state."""
        if gap is None:
            return False
        g = float(gap)
        prev = self._last_gap
        self._last_gap = g
        if prev is None:
            return False
        if g > prev and not self.collapsed:
            self.collapsed = True
            return True
        if g <= prev and self.collapsed:
            self.collapsed = False
            return True
        return False


def _run_cocoa_anneal(ds, params, debug, plus, levels, warm_start,
                      accel_kw, kw):
    """The scheduled (device-resident) σ′ anneal entry: validate, resolve
    resume, and hand the static ladder to :func:`run_sdca_family`."""
    import dataclasses as _dc

    quiet = kw.get("quiet", False)
    if kw.get("gap_target") is None:
        raise ValueError(
            "the σ′ anneal schedule requires --gapTarget (the backoff "
            "triggers on the stall watch, which runs on the gap-target "
            "path)")
    if kw.get("divergence_guard", "auto") == "off":
        raise ValueError(
            "the σ′ anneal schedule IS the divergence guard's backoff "
            "action; drop --divergenceGuard=off")
    resumed = kw.get("w_init") is not None or kw.get("start_round", 1) > 1
    if resumed and kw.get("sched_init") is None:
        # resumed without schedule state (a pre-schedule checkpoint, or a
        # bare w_init): the restored iterate may sit mid-stage at an
        # unknown σ′ — continue with the safe bound, exactly like the
        # trial path's resumed-run rule (any (w, α) is a valid primal-dual
        # pair under any σ′, so the certificate stays exact)
        if not quiet:
            print("sigma anneal: resumed run has no schedule state; "
                  f"continuing with the safe σ′=K·γ={ds.k * params.gamma:g}")
        return run_cocoa(ds, _dc.replace(params, sigma=None), debug, plus,
                         warm_start=warm_start, **accel_kw, **kw)
    p = _dc.replace(params, sigma=levels[0])
    alg = _alg_config(p, ds.k, plus)
    return run_sdca_family(
        ds, p, debug, "CoCoA+" if plus else "CoCoA", alg,
        sigma_levels=levels, warm_start=warm_start,
        accel=accel_kw["accel"] == "on", theta=accel_kw["theta"], **kw
    )
