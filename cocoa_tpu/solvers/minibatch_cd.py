"""Mini-batch SDCA / dual coordinate descent (reference: MinibatchCD.scala).

Same skeleton as CoCoA but the local solver runs against a *frozen* w
(mode="frozen"; MinibatchCD.scala:104) and both the dual and primal updates
are scaled by β/(K·H) (MinibatchCD.scala:32,43,128).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import local_sdca
from cocoa_tpu.solvers import base


def make_round_step(mesh, params: Params, k: int):
    scaling = params.beta / (k * params.local_iters)  # MinibatchCD.scala:32

    def per_shard(w, alpha_k, idxs_k, shard_k):
        da, dw = local_sdca(
            w, alpha_k, shard_k, idxs_k, params.lam, params.n, mode="frozen",
            loss=params.loss, smoothing=params.smoothing,
        )
        return dw, alpha_k + scaling * da  # MinibatchCD.scala:127-128

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def round_step(w, alpha, idxs, shard_arrays):
        dw_sum, alpha_new = base.fanout(
            per_shard, mesh, w, alpha, idxs, shard_arrays
        )
        return w + scaling * dw_sum, alpha_new  # MinibatchCD.scala:42-43

    return round_step


def run_minibatch_cd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    rng: str = "reference",
    w_init: Optional[jax.Array] = None,
    alpha_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
):
    """Train; returns (w, alpha, Trajectory)."""
    base.check_shards(ds)
    k = ds.k
    if not quiet:
        print(f"\nRunning Mini-batch CD on {params.n} data examples, "
              f"distributed over {k} workers")

    dtype = ds.labels.dtype
    w = jnp.zeros(ds.num_features, dtype=dtype) if w_init is None else jnp.array(w_init, dtype=dtype, copy=True)
    alpha = (
        jnp.zeros((k, ds.n_shard), dtype=dtype)
        if alpha_init is None
        else base.align_alpha(alpha_init, ds, dtype)
    )
    if mesh is not None:
        from cocoa_tpu.parallel.mesh import primal_sharding, sharded_rows

        w = jax.device_put(w, primal_sharding(mesh))
        alpha = jax.device_put(alpha, sharded_rows(mesh, extra_dims=1))

    sampler = base.IndexSampler(rng, debug.seed, params.local_iters, ds.counts)
    step = make_round_step(mesh, params, k)
    shard_arrays = ds.shard_arrays()

    def round_fn(t, state):
        w, alpha = state
        return step(w, alpha, sampler.round_indices(t), shard_arrays)

    def eval_fn(state):
        w, alpha = state
        return objectives.evaluate(ds, w, alpha, params.lam, test_ds=test_ds,
                                   loss=params.loss, smoothing=params.smoothing)

    (w, alpha), traj = base.drive(
        "Mini-batch CD", params, debug, (w, alpha), round_fn, eval_fn,
        quiet=quiet, start_round=start_round,
    )
    return w, alpha, traj
