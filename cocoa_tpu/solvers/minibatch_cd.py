"""Mini-batch SDCA / dual coordinate descent (reference: MinibatchCD.scala).

Same skeleton as CoCoA but the local solver runs against a *frozen* w
(mode="frozen"; MinibatchCD.scala:104) and both the dual and primal updates
are scaled by β/(K·H) (MinibatchCD.scala:32,43,128).

Implemented as the ``mode="frozen"`` member of the shared SDCA family
driver (solvers/cocoa.py ``run_sdca_family``), which gives mini-batch CD
the same execution paths as CoCoA: fast-math margins decomposition, the
Pallas dense/sparse kernels, device-side chunked rounds (``scan_chunk``),
the fully device-resident loop (``device_loop``), gap-target early stop,
and checkpoint/resume.
"""

from __future__ import annotations

from typing import Optional

import jax

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.solvers.cocoa import _alg_config, run_sdca_family


def run_minibatch_cd(
    ds: ShardedDataset,
    params: Params,
    debug: DebugParams,
    mesh=None,
    test_ds: Optional[ShardedDataset] = None,
    rng: str = "reference",
    w_init: Optional[jax.Array] = None,
    alpha_init: Optional[jax.Array] = None,
    start_round: int = 1,
    quiet: bool = False,
    gap_target: Optional[float] = None,
    scan_chunk: int = 0,
    math: str = "exact",
    pallas=None,
    block_size: int = 0,
    block_chain=None,
    block_pipeline=None,
    device_loop: bool = False,
    sampling: str = "auto",
    divergence_guard: str = "auto",
):
    """Train; returns (w, alpha, Trajectory)."""
    alg = _alg_config(params, ds.k, None, mode="frozen")
    return run_sdca_family(
        ds, params, debug, "Mini-batch CD", alg, mesh=mesh, test_ds=test_ds,
        rng=rng, w_init=w_init, alpha_init=alpha_init,
        start_round=start_round, quiet=quiet, gap_target=gap_target,
        scan_chunk=scan_chunk, math=math, pallas=pallas,
        block_size=block_size, block_chain=block_chain,
        block_pipeline=block_pipeline,
        device_loop=device_loop, sampling=sampling,
        divergence_guard=divergence_guard,
    )
