"""CI bench-regression gate: fresh CPU runs vs the committed baselines.

The benchmark lineage (BENCH_r01..r05.json at the repo root, distilled
into benchmarks/results.jsonl) records, per config, the comm-ROUND count
to the certified duality-gap target.  Rounds are the one benchmark axis
that is backend-independent (the math is bit-exact per platform and
platform-stable to within a few evals), so CI can guard it on plain CPU
runners without the TPU the wallclock columns need:

    python benchmarks/check_regression.py --report=report.jsonl

re-runs each gated config through the real CLI (fresh process, CPU),
reads the trajectory artifact, and FAILS (exit 1) when

- the run no longer certifies its gap target at all (``stopped`` is not
  ``"target"``), or
- the fresh round count exceeds the committed baseline round count by
  more than the config's explicit tolerance (a convergence regression —
  the kind a bad σ′ default, sampling change, or accel bug causes).

``--fresh=PATH`` skips the runs and checks an existing results.jsonl
(rows matched by ``config``) against the same committed bounds — the
mode for wiring an already-produced benchmark artifact into the gate.

The report is one JSONL row per gated config in the benchmarks-results
dialect, schema-validated (telemetry/schema.py) before the gate exits —
a malformed report is itself a failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(ROOT, "benchmarks", "results.jsonl")

# run as `python benchmarks/check_regression.py`: sys.path[0] is
# benchmarks/, so the package needs the repo root added for the schema
# validation import
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

# The gated configs.  ``flags`` reproduce the committed results.jsonl
# row's run through the CLI (benchmarks/run.py bench_demo is the
# producer: dense layout, H=50, λ=1e-3, 1e-4 gap target — the BENCH_r*
# lineage headline config).  ``rounds_tol`` is the explicit relative
# slack on the committed round count: float32 reduction order differs
# across CPU microarchitectures by a few evals, never by 15%.
GATES = (
    {
        "config": "demo-cocoa+",
        "algorithm": "CoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "flags": [
            "--trainFile=data/small_train.dat", "--numFeatures=9947",
            "--numSplits=4", "--numRounds=600", "--debugIter=10",
            "--localIterFrac=0.1", "--lambda=0.001", "--layout=dense",
            "--math=fast", "--deviceLoop", "--gapTarget=1e-4",
            "--justCoCoA=true", "--quiet",
        ],
    },
    {
        "config": "demo-cocoa+(permuted)",
        "algorithm": "CoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "flags": [
            "--trainFile=data/small_train.dat", "--numFeatures=9947",
            "--numSplits=4", "--numRounds=600", "--debugIter=10",
            "--localIterFrac=0.1", "--lambda=0.001", "--layout=dense",
            "--math=fast", "--deviceLoop", "--gapTarget=1e-4",
            "--rng=permuted", "--justCoCoA=true", "--quiet",
        ],
    },
)


def committed_baselines(path: str = RESULTS) -> dict:
    """config -> committed row from benchmarks/results.jsonl."""
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            # perf-accounting rows share the config name but carry no
            # round count — only rows with BOTH fields can anchor the
            # gate, regardless of row order in the file
            if isinstance(row, dict) and "config" in row \
                    and "rounds" in row:
                # first qualifying row per config wins (the file appends
                # refreshed rows last in regen; the gate keys on the
                # curated head)
                out.setdefault(row["config"], row)
    return out


def run_fresh(gate: dict, workdir: str) -> dict:
    """One fresh CPU run of the gate's config through the real CLI (own
    process: clean jit caches, clean telemetry); returns the fresh row.
    Never raises: a hung/torn run becomes a per-config ``error`` row so
    the gate still evaluates the remaining configs and writes its
    report."""
    traj_base = os.path.join(workdir, gate["config"].replace("/", "_"))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "cocoa_tpu.cli", *gate["flags"],
             f"--trajOut={traj_base}"],
            cwd=ROOT, env=env, capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            return {"config": gate["config"], "error":
                    f"CLI exited {proc.returncode}: {proc.stderr[-500:]}"}
        traj_path = (f"{traj_base}."
                     f"{gate['algorithm'].replace(' ', '_')}.jsonl")
        with open(traj_path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        # line 0 is the manifest header; a run killed before its first
        # eval leaves no record lines at all
        records = [ln for ln in lines if "round" in ln]
        if not records:
            return {"config": gate["config"], "error":
                    f"trajectory {traj_path} carries no round records"}
        last = records[-1]
        return {
            "config": gate["config"],
            "rounds": int(last["round"]),
            "gap": float(last["gap"]),
            "stopped": last.get("stopped"),
            "gap_target": gate["gap_target"],
            "type": "bench-regression-fresh",
        }
    except (subprocess.TimeoutExpired, OSError, ValueError, KeyError,
            TypeError) as e:
        return {"config": gate["config"], "error":
                f"{type(e).__name__}: {e}"}


def evaluate(gate: dict, fresh: dict, committed: dict) -> list:
    """Failure strings for one gate (empty = pass)."""
    cfg = gate["config"]
    if "error" in fresh:
        return [f"{cfg}: fresh run failed — {fresh['error']}"]
    failures = []
    if fresh.get("stopped") != "target":
        failures.append(
            f"{cfg}: fresh run no longer certifies the "
            f"{gate['gap_target']:g} gap target within its round budget "
            f"(stopped={fresh.get('stopped')!r}, gap={fresh.get('gap')})")
    base = committed.get(cfg)
    if base is None:
        failures.append(f"{cfg}: no committed baseline row in "
                        f"benchmarks/results.jsonl — the gate has nothing "
                        f"to compare against")
        return failures
    bound = int(base["rounds"] * (1.0 + gate["rounds_tol"]))
    if fresh.get("rounds", 0) > bound:
        failures.append(
            f"{cfg}: ROUND REGRESSION — fresh {fresh['rounds']} rounds vs "
            f"committed {base['rounds']} (+{gate['rounds_tol'] * 100:.0f}% "
            f"tolerance = {bound}); a convergence change must update the "
            f"baseline deliberately (benchmarks/regen.py), not ride in")
    return failures


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    report_path = None
    fresh_path = None
    only = None
    for a in argv:
        if a.startswith("--report="):
            report_path = a.split("=", 1)[1]
        elif a.startswith("--fresh="):
            fresh_path = a.split("=", 1)[1]
        elif a.startswith("--only="):
            only = a.split("=", 1)[1]
        else:
            print(f"usage: python benchmarks/check_regression.py "
                  f"[--report=PATH] [--fresh=results.jsonl] "
                  f"[--only=CONFIG]  (got {a!r})", file=sys.stderr)
            return 2
    committed = committed_baselines()
    gates = [g for g in GATES if only is None or g["config"] == only]
    if not gates:
        print(f"no gated config named {only!r}", file=sys.stderr)
        return 2

    rows = []
    failures = []
    if fresh_path:
        fresh_rows = committed_baselines(fresh_path)  # same config keying
        for gate in gates:
            row = fresh_rows.get(gate["config"])
            if row is None:
                failures.append(f"{gate['config']}: no row in "
                                f"{fresh_path}")
                continue
            fresh = {"config": gate["config"],
                     "rounds": int(row["rounds"]),
                     "gap": (float(row["gap"])
                             if row.get("gap") is not None else None),
                     # results.jsonl rows certify by construction; honor
                     # an explicit stopped column when present
                     "stopped": row.get("stopped", "target")}
            rows.append({**fresh, "type": "bench-regression-fresh"})
            failures += evaluate(gate, fresh, committed)
    else:
        workdir = tempfile.mkdtemp(prefix="bench-regress-")
        for gate in gates:
            print(f"check_regression: running {gate['config']} "
                  f"(committed baseline "
                  f"{committed.get(gate['config'], {}).get('rounds')} "
                  f"rounds)", flush=True)
            fresh = run_fresh(gate, workdir)
            rows.append(fresh)
            failures += evaluate(gate, fresh, committed)

    if report_path:
        with open(report_path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        from cocoa_tpu.telemetry import schema as tele_schema

        errs = tele_schema.check_file(report_path, kind="results")
        if errs:
            failures.append(f"report schema violations: {errs[:5]}")

    for row in rows:
        if "error" not in row:
            print(f"check_regression: {row['config']}: "
                  f"{row.get('rounds')} rounds, gap {row.get('gap')}, "
                  f"stopped={row.get('stopped')}", flush=True)
    if failures:
        for msg in failures:
            print(f"check_regression FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"check_regression: OK — {len(rows)} config(s) within "
          f"tolerance of the committed baselines")
    return 0


if __name__ == "__main__":
    sys.exit(main())
