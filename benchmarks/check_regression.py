"""CI bench-regression gate: fresh CPU runs vs the committed baselines.

The benchmark lineage (BENCH_r01..r05.json at the repo root, distilled
into benchmarks/results.jsonl) records, per config, the comm-ROUND count
to the certified duality-gap target.  Rounds are the one benchmark axis
that is backend-independent (the math is bit-exact per platform and
platform-stable to within a few evals), so CI can guard it on plain CPU
runners without the TPU the wallclock columns need:

    python benchmarks/check_regression.py --report=report.jsonl

re-runs each gated config through the real CLI (fresh process, CPU),
reads the trajectory artifact, and FAILS (exit 1) when

- the run no longer certifies its gap target at all (``stopped`` is not
  ``"target"``), or
- the fresh round count exceeds the committed baseline round count by
  more than the config's explicit tolerance (a convergence regression —
  the kind a bad σ′ default, sampling change, or accel bug causes).

``--fresh=PATH`` skips the runs and checks an existing results.jsonl
(rows matched by ``config``) against the same committed bounds — the
mode for wiring an already-produced benchmark artifact into the gate.

The report is one JSONL row per gated config in the benchmarks-results
dialect, schema-validated (telemetry/schema.py) before the gate exits —
a malformed report is itself a failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(ROOT, "benchmarks", "results.jsonl")

# run as `python benchmarks/check_regression.py`: sys.path[0] is
# benchmarks/, so the package needs the repo root added for the schema
# validation import
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

# The gated configs.  ``flags`` reproduce the committed results.jsonl
# row's run through the CLI (benchmarks/run.py bench_demo is the
# producer: dense layout, H=50, λ=1e-3, 1e-4 gap target — the BENCH_r*
# lineage headline config).  ``rounds_tol`` is the explicit relative
# slack on the committed round count: float32 reduction order differs
# across CPU microarchitectures by a few evals, never by 15%.
GATES = (
    {
        "config": "demo-cocoa+",
        "algorithm": "CoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "flags": [
            "--trainFile=data/small_train.dat", "--numFeatures=9947",
            "--numSplits=4", "--numRounds=600", "--debugIter=10",
            "--localIterFrac=0.1", "--lambda=0.001", "--layout=dense",
            "--math=fast", "--deviceLoop", "--gapTarget=1e-4",
            "--justCoCoA=true", "--quiet",
        ],
    },
    {
        "config": "demo-cocoa+(permuted)",
        "algorithm": "CoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "flags": [
            "--trainFile=data/small_train.dat", "--numFeatures=9947",
            "--numSplits=4", "--numRounds=600", "--debugIter=10",
            "--localIterFrac=0.1", "--lambda=0.001", "--layout=dense",
            "--math=fast", "--deviceLoop", "--gapTarget=1e-4",
            "--rng=permuted", "--justCoCoA=true", "--quiet",
        ],
    },
    # The round-barrier levers (ISSUE 12, docs/DESIGN.md §15): a REAL
    # 2-process host-exchange CoCoA+ gang (tests/_gang_worker.py
    # --real=cocoa), synchronous control vs --overlapComm=on
    # --staleRounds=1.  Round counts are fully deterministic here —
    # round-keyed sampling AND round-indexed join windows — so the
    # committed baselines are exact; the tolerance only absorbs future
    # deliberate solver changes.  sleeps are zero: the gate guards the
    # comm-ROUND axis, wall-clock belongs to the slow A/B test.
    {
        "config": "gang-cocoa+sync",
        "algorithm": "GangCoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "runner": "gang",
        "flags": [
            "--real=cocoa", "--numSplits=2", "--numRounds=400",
            "--debugIter=5", "--gapTarget=1e-4", "--lambda=0.01",
            "--rowsPerShard=64", "--numFeatures=32", "--localIters=16",
            "--overlapComm=off", "--staleRounds=0",
        ],
    },
    {
        "config": "gang-cocoa+overlap-stale1",
        "algorithm": "GangCoCoA+",
        "gap_target": 1e-4,
        "rounds_tol": 0.15,
        "runner": "gang",
        "flags": [
            "--real=cocoa", "--numSplits=2", "--numRounds=400",
            "--debugIter=5", "--gapTarget=1e-4", "--lambda=0.01",
            "--rowsPerShard=64", "--numFeatures=32", "--localIters=16",
            "--overlapComm=on", "--staleRounds=1",
        ],
    },
    # The fleet row (ISSUE 13): 256 synthetic tenants (a log-spaced λ
    # path over 256 distinct planted-separator problems) through the ONE
    # compiled vmapped round (benchmarks/fleet_bench.py).  The gate
    # re-runs the fleet side only — rounds-to-certify-every-tenant and
    # full certification are the backend-independent axes; the
    # models-per-second and the 173x-vs-serial speedup live in the
    # committed row (CPU-measured, re-measured by fleet_bench --row).
    {
        "config": "fleet-256-synth",
        "algorithm": "CoCoA+ fleet",
        "gap_target": 1e-2,
        "rounds_tol": 0.25,
        "runner": "fleet",
        "flags": ["--fleet-only", "--tenants=256"],
    },
    # The serving row (ISSUE 14, docs/DESIGN.md §17): queries/s at a
    # pinned p99 SLA with a mid-bench hot-swap, measured by
    # benchmarks/serve_bench.py on CPU.  The environment-robust axes the
    # gate pins hard: the p99 SLA holds (the row IS "queries/s at p99 <=
    # SLA"), the scoring path compiled exactly once per bucket, and the
    # hot-swap happened ("stopped" == "target" requires zero failed
    # queries + >= 1 swap).  Throughput itself is wall-clock on a shared
    # CI runner, so only a catastrophic collapse fails: fresh qps must
    # stay above qps_floor_frac x the committed row.
    {
        "config": "serve-cpu-synth",
        "algorithm": "CoCoA+",
        "gap_target": 1e-2,
        "rounds_tol": 0.25,
        "runner": "serve",
        "kind": "serve",
        "qps_floor_frac": 0.25,
        "expected_compiles": 2,
        "flags": ["--duration=3", "--threads=4"],
    },
    # The low-precision serving row (ISSUE 16, docs/DESIGN.md §20): the
    # packed-bf16 compiled scoring path vs the SAME-harness f32 control
    # at the L2-straddle geometry (benchmarks/serve_bench.py
    # --serveDtype=bf16).  The committed row must hold the acceptance
    # bar (qps_ratio >= 1.7, zero sign flips beyond 2x the certified
    # bound); the fresh CI re-run — interleaved-pass wall-clock on a
    # shared runner — is gated at a catastrophic floor plus the
    # environment-robust axes: zero flips, the quantized form actually
    # served ("stopped" == "target" requires swap >= 1 + no certificate
    # fallback), and exactly one compile per (bucket, dtype) per scorer
    # (3 = control f32 + packed bf16 + the f32 fallback form).
    {
        "config": "serve-cpu-synth-bf16",
        "runner": "serve",
        "kind": "serve_quant",
        "min_qps_ratio": 1.7,
        "fresh_ratio_floor": 1.3,
        "expected_compiles": 3,
        "flags": ["--serveDtype=bf16", "--duration=3",
                  "--ratio-bar=1.3"],
    },
    # The int8 serving row (ISSUE 16 residue): same A/B harness as the
    # bf16 row, committed under --correctness-only — XLA's CPU backend
    # emulates the int8 unpack, so CPU throughput is not the claim (the
    # committed row records the honest ratio); what the gate pins is
    # the certificate machinery at the narrower dtype: zero sign flips
    # beyond 2x the certified bound, the quantized form actually
    # served through a mid-measure swap, and one compile per
    # (bucket, dtype) per scorer.  Both ratio bars sit at 0.0 —
    # correctness-only by construction.
    {
        "config": "serve-cpu-synth-int8",
        "runner": "serve",
        "kind": "serve_quant",
        "min_qps_ratio": 0.0,
        "fresh_ratio_floor": 0.0,
        "expected_compiles": 3,
        "flags": ["--serveDtype=int8", "--duration=3",
                  "--correctness-only"],
    },
    # The fleet-serving row (ISSUE 17, docs/DESIGN.md §21): R real CLI
    # scorer replicas serving a (T, d) tenant catalogue behind the
    # router (benchmarks/serve_bench.py --serveReplicas).  The
    # COMMITTED row must beat the committed single-process serve row's
    # qps by min_qps_ratio_committed (the horizontal-scaling acceptance
    # bar); the fresh CI re-run — three process spawns of wall-clock on
    # a shared runner — is gated on the environment-robust axes hard
    # (zero failed queries through a SIGKILL, one compile per bucket
    # per replica process, every replica hot-swapped, the victim
    # respawned) plus a catastrophic throughput floor.
    # The tracing A/B rides the fleet row (ISSUE 19, docs/DESIGN.md
    # §22): the COMMITTED row's tracing-on window must stay within
    # max_trace_overhead_committed of its untraced twin (serve_bench's
    # own 5% self-gate produced it); the fresh re-run — two more
    # wall-clock windows on a shared runner — is held to a
    # catastrophic bound only, plus the environment-robust axes: the
    # sampled query_trace stream is schema-clean and assembled into a
    # waterfall that names a dominant hop (tracing never goes dark).
    {
        "config": "serve-cpu-fleet",
        "runner": "serve",
        "kind": "serve_fleet",
        "replicas": 2,
        "min_qps_ratio_committed": 1.5,
        "baseline_config": "serve-cpu-synth",
        "qps_floor_frac": 0.25,
        "expected_compiles": 2,
        "max_trace_overhead_committed": 5.0,
        "fresh_trace_overhead_bar": 25.0,
        "flags": ["--serveReplicas=2", "--duration=3",
                  "--trace-bar=25"],
    },
    # The warm-ingest row (ISSUE 15, docs/DESIGN.md §18): --ingestCache
    # serves device-ready shard slabs from memmap-able artifacts with
    # ZERO parse.  The gate re-measures the full rcv1-synth warm-vs-
    # streamed-cold A/B (benchmarks/run.py bench_ingest) and fails when
    # the warm map drops below the ≥10× acceptance bar — wall-clock on a
    # shared runner, so the bar IS the bound (the committed row shows
    # 64×; a cache that has regressed to re-parsing or re-validating
    # per byte lands well under 10×, timer noise never costs 6×).
    {
        "config": "ingest/warm-p2",
        "runner": "ingest",
        "kind": "ingest",
        "min_speedup": 10.0,
        "flags": [],
    },
)

# bounded-staleness round overhead vs the synchronous control (the
# ISSUE-12 acceptance bar): the stale gang config may use at most this
# multiple of the sync gang config's fresh rounds
STALE_ROUNDS_RATIO = 1.25
_GANG_PAIR = ("gang-cocoa+sync", "gang-cocoa+overlap-stale1")


def committed_baselines(path: str = RESULTS) -> dict:
    """config -> committed row from benchmarks/results.jsonl."""
    out = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            # perf-accounting rows share the config name but carry no
            # round count — only rows with an anchoring metric (rounds,
            # warm_speedup for the ingest gate, qps_ratio for the
            # low-precision serving gate, or scaling_eff for the
            # fleet-serving gate) can anchor the gate, regardless of
            # row order in the file
            if isinstance(row, dict) and "config" in row \
                    and ("rounds" in row or "warm_speedup" in row
                         or "qps_ratio" in row
                         or "scaling_eff" in row):
                # first qualifying row per config wins (the file appends
                # refreshed rows last in regen; the gate keys on the
                # curated head)
                out.setdefault(row["config"], row)
    return out


def run_fresh(gate: dict, workdir: str) -> dict:
    """One fresh CPU run of the gate's config through the real CLI (own
    process: clean jit caches, clean telemetry); returns the fresh row.
    Never raises: a hung/torn run becomes a per-config ``error`` row so
    the gate still evaluates the remaining configs and writes its
    report."""
    traj_base = os.path.join(workdir, gate["config"].replace("/", "_"))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "cocoa_tpu.cli", *gate["flags"],
             f"--trajOut={traj_base}"],
            cwd=ROOT, env=env, capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            return {"config": gate["config"], "error":
                    f"CLI exited {proc.returncode}: {proc.stderr[-500:]}"}
        traj_path = (f"{traj_base}."
                     f"{gate['algorithm'].replace(' ', '_')}.jsonl")
        with open(traj_path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
        # line 0 is the manifest header; a run killed before its first
        # eval leaves no record lines at all
        records = [ln for ln in lines if "round" in ln]
        if not records:
            return {"config": gate["config"], "error":
                    f"trajectory {traj_path} carries no round records"}
        last = records[-1]
        return {
            "config": gate["config"],
            "rounds": int(last["round"]),
            "gap": float(last["gap"]),
            "stopped": last.get("stopped"),
            "gap_target": gate["gap_target"],
            "type": "bench-regression-fresh",
        }
    except (subprocess.TimeoutExpired, OSError, ValueError, KeyError,
            TypeError) as e:
        return {"config": gate["config"], "error":
                f"{type(e).__name__}: {e}"}


def run_fresh_gang(gate: dict, workdir: str) -> dict:
    """One fresh 2-process host-exchange gang run (tests/_gang_worker.py
    --real=cocoa) under the in-process elastic supervisor; the fresh
    rounds/gap come from the worker-0 events stream.  Same never-raises
    contract as :func:`run_fresh`."""
    # the gang workers need the repo + tests/ importable, and must not
    # inherit a virtual-device XLA flag (they use no devices).  The
    # supervisor spawns them with the AMBIENT environment, so the tweaks
    # go through os.environ — saved and restored, so later gates (and
    # the caller) see the environment they started with.
    saved = {k: os.environ.get(k)
             for k in ("PYTHONPATH", "XLA_FLAGS", "JAX_PLATFORMS")}
    try:
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in (ROOT, os.path.join(ROOT, "tests"),
                        os.environ.get("PYTHONPATH", "")) if p)
        os.environ["XLA_FLAGS"] = " ".join(
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        tests_dir = os.path.join(ROOT, "tests")
        if tests_dir not in sys.path:
            sys.path.insert(0, tests_dir)
        from _gang_worker import supervise_gang  # the shared launch contract

        ev = os.path.join(workdir,
                          gate["config"].replace("/", "_") + ".jsonl")
        rc, records = supervise_gang(gate["flags"], events=ev)
        if rc != 0:
            return {"config": gate["config"],
                    "error": f"gang exited {rc}"}
        evals = [r for r in records if r.get("event") == "round_eval"]
        end = next((r for r in reversed(records)
                    if r.get("event") == "run_end"), None)
        if not evals or end is None:
            return {"config": gate["config"],
                    "error": f"events stream {ev} carries no run"}
        return {
            "config": gate["config"],
            "rounds": int(evals[-1]["t"]),
            "gap": float(evals[-1]["gap"]),
            "stopped": end.get("stopped"),
            "gap_target": gate["gap_target"],
            "type": "bench-regression-fresh",
        }
    except (OSError, ValueError, KeyError, TypeError) as e:
        return {"config": gate["config"],
                "error": f"{type(e).__name__}: {e}"}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_fresh_fleet(gate: dict, workdir: str) -> dict:
    """One fresh CPU fleet run (benchmarks/fleet_bench.py --fleet-only):
    the row comes from the bench driver's own --row artifact, so the
    gate and the benchmark can never disagree about what a fleet row
    means.  Same never-raises contract as :func:`run_fresh`."""
    row_path = os.path.join(workdir,
                            gate["config"].replace("/", "_") + ".jsonl")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "benchmarks",
                                          "fleet_bench.py"),
             *gate["flags"], f"--row={row_path}"],
            cwd=ROOT, env=env, capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            return {"config": gate["config"], "error":
                    f"fleet bench exited {proc.returncode}: "
                    f"{proc.stderr[-500:]}"}
        with open(row_path) as f:
            row = json.loads(f.readline())
        return {
            "config": gate["config"],
            "rounds": int(row["rounds"]),
            "gap": float(row["gap"]),
            # "target" iff EVERY tenant certified (fleet_bench sets it)
            "stopped": row.get("stopped"),
            "gap_target": gate["gap_target"],
            "type": "bench-regression-fresh",
        }
    except (subprocess.TimeoutExpired, OSError, ValueError, KeyError,
            TypeError) as e:
        return {"config": gate["config"], "error":
                f"{type(e).__name__}: {e}"}


def run_fresh_serve(gate: dict, workdir: str) -> dict:
    """One fresh CPU serving bench (benchmarks/serve_bench.py): the row
    comes from the bench driver's own --row artifact, like the fleet
    gate.  Same never-raises contract as :func:`run_fresh`."""
    row_path = os.path.join(workdir,
                            gate["config"].replace("/", "_") + ".jsonl")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "benchmarks",
                                          "serve_bench.py"),
             *gate["flags"], f"--row={row_path}"],
            cwd=ROOT, env=env, capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            return {"config": gate["config"], "error":
                    f"serve bench exited {proc.returncode}: "
                    f"{proc.stderr[-500:]}"}
        with open(row_path) as f:
            row = json.loads(f.readline())
        return {**row, "type": "bench-regression-fresh"}
    except (subprocess.TimeoutExpired, OSError, ValueError, KeyError,
            TypeError) as e:
        return {"config": gate["config"], "error":
                f"{type(e).__name__}: {e}"}


def run_fresh_ingest(gate: dict, workdir: str) -> dict:
    """One fresh warm-vs-cold ingest A/B at full rcv1-synth scale
    (benchmarks/run.py bench_ingest, the producer of the committed
    ingest/* rows, P=2 only — the gated config).  Same never-raises
    contract as :func:`run_fresh`."""
    try:
        bench_dir = os.path.join(ROOT, "benchmarks")
        if bench_dir not in sys.path:
            sys.path.insert(0, bench_dir)
        import run as bench_run

        results: list = []
        bench_run.bench_ingest(results, quick=False, processes=(2,))
        row = next((r for r in results
                    if r["config"] == gate["config"]), None)
        if row is None:
            return {"config": gate["config"], "error":
                    f"bench_ingest produced no {gate['config']} row"}
        return {**row, "type": "bench-regression-fresh"}
    except (OSError, ValueError, KeyError, TypeError,
            ImportError) as e:
        return {"config": gate["config"], "error":
                f"{type(e).__name__}: {e}"}


def ingest_failures(gate: dict, fresh: dict, committed: dict) -> list:
    """The warm-ingest bounds: the warm map stays ≥ min_speedup× faster
    than the streamed cold parse of the same file/geometry, and warm
    really parses nothing (the row carries mapped bytes, never read
    bytes)."""
    cfg = gate["config"]
    if "error" in fresh:
        return [f"{cfg}: fresh run failed — {fresh['error']}"]
    failures = []
    speedup = fresh.get("warm_speedup")
    if speedup is None:
        failures.append(f"{cfg}: fresh warm row carries no warm_speedup")
    elif speedup < gate["min_speedup"]:
        failures.append(
            f"{cfg}: WARM INGEST REGRESSION — warm map only "
            f"{speedup}× the streamed cold parse (bar ≥ "
            f"{gate['min_speedup']:g}×); the cache is re-parsing or "
            f"re-validating per byte")
    if fresh.get("bytes_read_mb"):
        failures.append(
            f"{cfg}: warm ingest READ {fresh['bytes_read_mb']} MB of "
            f"text — the zero-parse contract broke")
    if committed.get(cfg) is None:
        failures.append(f"{cfg}: no committed baseline row in "
                        f"benchmarks/results.jsonl")
    return failures


def serve_failures(gate: dict, fresh: dict, committed: dict) -> list:
    """The serve-specific bounds (on top of :func:`evaluate`'s
    certification + round checks): the p99 SLA holds, the compile count
    equals the bucket count, and throughput has not collapsed below the
    floor fraction of the committed row."""
    cfg = gate["config"]
    failures = []
    p99, sla = fresh.get("p99_ms"), fresh.get("sla_ms")
    if p99 is None or sla is None:
        failures.append(f"{cfg}: fresh serve row carries no p99/SLA")
    elif p99 > sla:
        failures.append(
            f"{cfg}: SLA VIOLATION — fresh p99 {p99}ms exceeds the "
            f"pinned {sla}ms bound; the row is queries/s AT p99 <= SLA")
    if fresh.get("compiles") != gate["expected_compiles"]:
        failures.append(
            f"{cfg}: COMPILE LEAK — {fresh.get('compiles')} scoring "
            f"compiles for {gate['expected_compiles']} buckets; the "
            f"one-compile-per-bucket contract broke")
    base = committed.get(cfg)
    if base is not None and base.get("qps") is not None:
        floor = base["qps"] * gate["qps_floor_frac"]
        if (fresh.get("qps") or 0) < floor:
            failures.append(
                f"{cfg}: THROUGHPUT COLLAPSE — fresh {fresh.get('qps')} "
                f"qps vs committed {base['qps']} (floor "
                f"{gate['qps_floor_frac']}x = {floor:.0f}); CI noise "
                f"never costs 4x")
    return failures


def serve_quant_failures(gate: dict, fresh: dict,
                         committed: dict) -> list:
    """The low-precision serving bounds.  The COMMITTED row carries the
    acceptance bar (qps_ratio >= min_qps_ratio at zero flips — it was
    produced by serve_bench's own 1.7 self-gate); the fresh re-run is
    held to the environment-robust axes hard (flips, compile count,
    quantized-form-served) and to a catastrophic ratio floor only,
    because absolute wall-clock on a shared CI runner is noise the
    cache-footprint mechanism itself is not."""
    cfg = gate["config"]
    if "error" in fresh:
        return [f"{cfg}: fresh run failed — {fresh['error']}"]
    failures = []
    base = committed.get(cfg)
    if base is None:
        failures.append(f"{cfg}: no committed baseline row in "
                        f"benchmarks/results.jsonl")
    else:
        if (base.get("qps_ratio") or 0) < gate["min_qps_ratio"]:
            failures.append(
                f"{cfg}: COMMITTED ROW BELOW BAR — qps_ratio "
                f"{base.get('qps_ratio')} < {gate['min_qps_ratio']:g}; "
                f"regen the row (serve_bench --serveDtype) on a quiet "
                f"machine, never commit one under the bar")
        if base.get("flips") != 0:
            failures.append(
                f"{cfg}: COMMITTED ROW CARRIES {base.get('flips')} sign "
                f"flips beyond 2x the certified bound — the certificate "
                f"understated the quantization error")
    if fresh.get("stopped") != "target":
        failures.append(
            f"{cfg}: fresh run did not serve the quantized form to "
            f"target (stopped={fresh.get('stopped')!r}: needs >= 1 "
            f"hot-swap, zero flips, and no certificate fallback)")
    if fresh.get("flips") != 0:
        failures.append(
            f"{cfg}: SIGN FLIPS — {fresh.get('flips')} of "
            f"{fresh.get('flip_checked')} audited margins flipped at "
            f"|m32| > 2x the certified bound "
            f"{fresh.get('margin_err_bound')}")
    if fresh.get("compiles") != gate["expected_compiles"]:
        failures.append(
            f"{cfg}: COMPILE LEAK — {fresh.get('compiles')} scoring "
            f"compiles, expected {gate['expected_compiles']} (control "
            f"f32 + packed form + the f32 certificate-fallback form); "
            f"a quantized swap must never compile mid-flight")
    if (fresh.get("qps_ratio") or 0) < gate["fresh_ratio_floor"]:
        failures.append(
            f"{cfg}: RATIO COLLAPSE — fresh qps_ratio "
            f"{fresh.get('qps_ratio')} under the "
            f"{gate['fresh_ratio_floor']:g} catastrophic floor "
            f"(committed {base.get('qps_ratio') if base else '?'}); "
            f"the packed path lost its cache-footprint mechanism, not "
            f"just runner speed")
    return failures


def serve_fleet_failures(gate: dict, fresh: dict,
                         committed: dict) -> list:
    """The fleet-serving bounds.  The COMMITTED row must beat the
    committed single-process serving row's qps by the horizontal-
    scaling acceptance bar; the fresh re-run is held hard to the axes
    a shared runner cannot excuse — zero failed queries through the
    SIGKILL drill, one compile per bucket per replica process, every
    replica hot-swapped, the victim respawned — plus a catastrophic
    qps floor vs the committed fleet row."""
    cfg = gate["config"]
    if "error" in fresh:
        return [f"{cfg}: fresh run failed — {fresh['error']}"]
    failures = []
    base = committed.get(cfg)
    single = committed.get(gate["baseline_config"])
    if base is None:
        failures.append(f"{cfg}: no committed baseline row in "
                        f"benchmarks/results.jsonl")
    else:
        bar = gate["min_qps_ratio_committed"]
        if single is None or single.get("qps") is None:
            failures.append(
                f"{cfg}: no committed {gate['baseline_config']} row to "
                f"anchor the scaling bar against")
        elif (base.get("qps") or 0) < bar * single["qps"]:
            failures.append(
                f"{cfg}: COMMITTED ROW BELOW BAR — fleet qps "
                f"{base.get('qps')} < {bar:g}x the committed "
                f"{gate['baseline_config']} qps {single['qps']}; regen "
                f"the row on a quiet machine, never commit one under "
                f"the bar")
        if base.get("failed") != 0:
            failures.append(
                f"{cfg}: COMMITTED ROW CARRIES {base.get('failed')} "
                f"failed queries — a dead replica must requeue, never "
                f"fail")
        if (base.get("trace_overhead_pct") is not None
                and base["trace_overhead_pct"]
                > gate["max_trace_overhead_committed"]):
            failures.append(
                f"{cfg}: COMMITTED ROW OVER THE TRACING BAR — "
                f"{base['trace_overhead_pct']:g}% qps overhead with "
                f"sampled tracing on (bar "
                f"{gate['max_trace_overhead_committed']:g}%); regen on "
                f"a quiet machine, never commit one over the bar")
        floor = (base.get("qps") or 0) * gate["qps_floor_frac"]
        if (fresh.get("qps") or 0) < floor:
            failures.append(
                f"{cfg}: THROUGHPUT COLLAPSE — fresh "
                f"{fresh.get('qps')} qps vs committed {base.get('qps')} "
                f"(floor {gate['qps_floor_frac']}x = {floor:.0f}); CI "
                f"noise never costs 4x")
    if fresh.get("failed") != 0:
        failures.append(
            f"{cfg}: {fresh.get('failed')} FAILED queries — the "
            f"SIGKILLed replica must cost latency, never an answer")
    if fresh.get("compiles") != gate["expected_compiles"]:
        failures.append(
            f"{cfg}: COMPILE LEAK — {fresh.get('compiles')} scoring "
            f"compiles per replica process, expected "
            f"{gate['expected_compiles']} (one per bucket; the tenant "
            f"catalogue must ride the same executables)")
    if (fresh.get("swaps") or 0) < gate["replicas"]:
        failures.append(
            f"{cfg}: only {fresh.get('swaps')}/{gate['replicas']} "
            f"replicas observed the injected catalogue generation")
    if fresh.get("stopped") != "target":
        failures.append(
            f"{cfg}: fresh fleet run did not reach target "
            f"(stopped={fresh.get('stopped')!r}: needs zero failures, "
            f"every replica swapped, the compile pin, and the "
            f"SIGKILLed replica respawned into routing)")
    if fresh.get("trace_schema_errors"):
        failures.append(
            f"{cfg}: {fresh['trace_schema_errors']} schema violations "
            f"in the sampled query_trace stream — the trace artifact "
            f"stopped being machine-readable")
    if "trace_overhead_pct" in fresh and fresh.get("dominant_hop") \
            is None:
        failures.append(
            f"{cfg}: no sampled query_trace assembled into a "
            f"waterfall — tracing went dark under the committed "
            f"sampling rate")
    if (fresh.get("trace_overhead_pct") or 0) \
            > gate["fresh_trace_overhead_bar"]:
        failures.append(
            f"{cfg}: TRACING OVERHEAD COLLAPSE — fresh "
            f"{fresh['trace_overhead_pct']:g}% qps overhead with "
            f"sampled tracing on, over the "
            f"{gate['fresh_trace_overhead_bar']:g}% catastrophic "
            f"bound; the peel/stamp path got hot, not just the runner")
    return failures


def gang_ratio_failures(rows: list) -> list:
    """The cross-config staleness bound: overlap+stale rounds <=
    STALE_ROUNDS_RATIO x sync rounds (evaluated only when both gang
    rows ran cleanly — a per-config error already failed the gate)."""
    by_cfg = {r.get("config"): r for r in rows if "error" not in r}
    sync, stale = (by_cfg.get(c) for c in _GANG_PAIR)
    if not sync or not stale:
        return []
    bound = int(sync["rounds"] * STALE_ROUNDS_RATIO)
    if stale["rounds"] > bound:
        return [f"{_GANG_PAIR[1]}: STALENESS OVERHEAD — "
                f"{stale['rounds']} rounds vs the synchronous control's "
                f"{sync['rounds']} (bound {STALE_ROUNDS_RATIO}x = "
                f"{bound}); the bounded-staleness trajectory regressed"]
    return []


def evaluate(gate: dict, fresh: dict, committed: dict) -> list:
    """Failure strings for one gate (empty = pass)."""
    cfg = gate["config"]
    if "error" in fresh:
        return [f"{cfg}: fresh run failed — {fresh['error']}"]
    failures = []
    if fresh.get("stopped") != "target":
        failures.append(
            f"{cfg}: fresh run no longer certifies the "
            f"{gate['gap_target']:g} gap target within its round budget "
            f"(stopped={fresh.get('stopped')!r}, gap={fresh.get('gap')})")
    base = committed.get(cfg)
    if base is None:
        failures.append(f"{cfg}: no committed baseline row in "
                        f"benchmarks/results.jsonl — the gate has nothing "
                        f"to compare against")
        return failures
    bound = int(base["rounds"] * (1.0 + gate["rounds_tol"]))
    if fresh.get("rounds", 0) > bound:
        failures.append(
            f"{cfg}: ROUND REGRESSION — fresh {fresh['rounds']} rounds vs "
            f"committed {base['rounds']} (+{gate['rounds_tol'] * 100:.0f}% "
            f"tolerance = {bound}); a convergence change must update the "
            f"baseline deliberately (benchmarks/regen.py), not ride in")
    return failures


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    report_path = None
    fresh_path = None
    only = None
    for a in argv:
        if a.startswith("--report="):
            report_path = a.split("=", 1)[1]
        elif a.startswith("--fresh="):
            fresh_path = a.split("=", 1)[1]
        elif a.startswith("--only="):
            only = a.split("=", 1)[1]
        else:
            print(f"usage: python benchmarks/check_regression.py "
                  f"[--report=PATH] [--fresh=results.jsonl] "
                  f"[--only=CONFIG]  (got {a!r})", file=sys.stderr)
            return 2
    committed = committed_baselines()
    gates = [g for g in GATES if only is None or g["config"] == only]
    if not gates:
        print(f"no gated config named {only!r}", file=sys.stderr)
        return 2

    rows = []
    failures = []
    if fresh_path:
        fresh_rows = committed_baselines(fresh_path)  # same config keying
        for gate in gates:
            row = fresh_rows.get(gate["config"])
            if row is None:
                failures.append(f"{gate['config']}: no row in "
                                f"{fresh_path}")
                continue
            if gate.get("kind") == "ingest":
                fresh = {**row, "config": gate["config"]}
                rows.append({**fresh, "type": "bench-regression-fresh"})
                failures += ingest_failures(gate, fresh, committed)
                continue
            if gate.get("kind") == "serve_quant":
                # quant rows anchor on qps_ratio, not rounds — the
                # generic convergence evaluate() does not apply
                fresh = {**row, "config": gate["config"]}
                rows.append({**fresh, "type": "bench-regression-fresh"})
                failures += serve_quant_failures(gate, fresh, committed)
                continue
            if gate.get("kind") == "serve_fleet":
                # fleet rows anchor on scaling_eff/qps, not rounds
                fresh = {**row, "config": gate["config"]}
                rows.append({**fresh, "type": "bench-regression-fresh"})
                failures += serve_fleet_failures(gate, fresh, committed)
                continue
            fresh = {**row,
                     "config": gate["config"],
                     "rounds": int(row["rounds"]),
                     "gap": (float(row["gap"])
                             if row.get("gap") is not None else None),
                     # results.jsonl rows certify by construction; honor
                     # an explicit stopped column when present
                     "stopped": row.get("stopped", "target")}
            rows.append({**fresh, "type": "bench-regression-fresh"})
            failures += evaluate(gate, fresh, committed)
            if gate.get("kind") == "serve":
                failures += serve_failures(gate, fresh, committed)
        # the cross-row staleness bound applies to artifact-checked rows
        # exactly like fresh runs — an overhead regression must not ride
        # in through --fresh mode
        failures += gang_ratio_failures(rows)
    else:
        workdir = tempfile.mkdtemp(prefix="bench-regress-")
        for gate in gates:
            base = committed.get(gate["config"], {})
            if "scaling_eff" in base:
                anchor = (f"qps {base.get('qps')} at scaling_eff "
                          f"{base.get('scaling_eff')}")
            elif "qps_ratio" in base:
                anchor = f"qps_ratio {base.get('qps_ratio')}"
            else:
                anchor = f"{base.get('rounds')} rounds"
            print(f"check_regression: running {gate['config']} "
                  f"(committed baseline {anchor})", flush=True)
            runner = {"gang": run_fresh_gang,
                      "fleet": run_fresh_fleet,
                      "serve": run_fresh_serve,
                      "ingest": run_fresh_ingest}.get(
                          gate.get("runner"), run_fresh)
            fresh = runner(gate, workdir)
            rows.append(fresh)
            if gate.get("kind") == "ingest":
                failures += ingest_failures(gate, fresh, committed)
                continue
            if gate.get("kind") == "serve_quant":
                failures += serve_quant_failures(gate, fresh, committed)
                continue
            if gate.get("kind") == "serve_fleet":
                failures += serve_fleet_failures(gate, fresh, committed)
                continue
            failures += evaluate(gate, fresh, committed)
            if gate.get("kind") == "serve" and "error" not in fresh:
                failures += serve_failures(gate, fresh, committed)
        failures += gang_ratio_failures(rows)

    if report_path:
        with open(report_path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        from cocoa_tpu.telemetry import schema as tele_schema

        errs = tele_schema.check_file(report_path, kind="results")
        if errs:
            failures.append(f"report schema violations: {errs[:5]}")

    for row in rows:
        if "error" in row:
            continue
        if "scaling_eff" in row:
            print(f"check_regression: {row['config']}: "
                  f"{row.get('qps')} qps x {row.get('replicas')} "
                  f"replicas (eff {row.get('scaling_eff')}), "
                  f"shed {row.get('shed')} / requeued "
                  f"{row.get('requeued')} / failed {row.get('failed')}, "
                  f"stopped={row.get('stopped')}", flush=True)
        elif "qps_ratio" in row:
            print(f"check_regression: {row['config']}: "
                  f"qps_ratio {row.get('qps_ratio')}, "
                  f"flips {row.get('flips')}/{row.get('flip_checked')}, "
                  f"stopped={row.get('stopped')}", flush=True)
        else:
            print(f"check_regression: {row['config']}: "
                  f"{row.get('rounds')} rounds, gap {row.get('gap')}, "
                  f"stopped={row.get('stopped')}", flush=True)
    if failures:
        for msg in failures:
            print(f"check_regression FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"check_regression: OK — {len(rows)} config(s) within "
          f"tolerance of the committed baselines")
    return 0


if __name__ == "__main__":
    sys.exit(main())
