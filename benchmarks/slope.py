"""Shared slope-method timing for tunneled-device benchmarks.

A single run through the axon-tunneled TPU carries hundreds of ms of
dispatch+fetch latency varying run-to-run — often more than the measured
workload.  The slope method cancels it: time the same workload at R and
m·R rounds and take

    per_round = (T(mR) − T(R)) / ((m − 1)·R)
    steady    = per_round · R          (the number to report)
    fixed     = T(R) − steady          (the cancelled overhead)

``m`` escalates adaptively until the span T(mR) − T(R) dominates the
jitter: sizing m from T(R) alone fails exactly when the fixed cost
dominates T(R) (the regime the method exists for).
"""

from __future__ import annotations

import time
from typing import Callable, NamedTuple


class SlopeResult(NamedTuple):
    steady_s: float
    fixed_s: float
    # measurement-quality telemetry (ADVICE r3): when escalation exits at
    # max_mult with span < min_span_s, the estimate may still be dominated
    # by tunnel jitter — callers should mark such rows as noisy instead of
    # recording them silently (the round-3 rcv1-permuted row's clamped
    # fixed_s=0 had exactly this signature)
    span_s: float = 0.0
    degraded: bool = False


def slope_time(
    make_run: Callable[[int], Callable[[], object]],
    rounds: int,
    min_span_s: float = 1.0,
    reps: int = 3,
    max_mult: int = 32,
) -> SlopeResult:
    """SlopeResult(steady_s for ``rounds``, fixed_s, span_s, degraded).
    ``make_run(nr)`` returns a 0-arg callable executing exactly ``nr``
    rounds (compiled on first call; each point is best-of-``reps`` warm
    runs)."""

    def best(fn):
        fn()  # compile / warm
        b = None
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            b = dt if b is None or dt < b else b
        return b

    t_lo = best(make_run(rounds))
    m = 4
    while True:
        t_hi = best(make_run(m * rounds))
        span = t_hi - t_lo
        if span >= min_span_s or m >= max_mult:
            break
        m *= 2
    per_round = max(0.0, span / ((m - 1) * rounds))
    steady = per_round * rounds
    return SlopeResult(steady, max(0.0, t_lo - steady), span,
                       degraded=span < min_span_s)
