"""Measure the fp (feature-parallel) axis overhead on the virtual CPU mesh.

fp is documented as a CAPACITY axis (fit d/F of w + the matching X column
block per device when d forces it), not a speed axis: the sequential SDCA
inner loop pays one fp-reduction per coordinate step (SURVEY.md §2.2;
parallel/mesh.py module note).  This script puts a number on that claim —
the only place an fp mesh exists in this environment is the virtual CPU
backend (the attached TPU is one chip), so the measured RATIO between a
(K,) dp mesh and a (K, 2) dp×fp mesh on identical work is the artifact,
not the absolute times.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python benchmarks/fp_bench.py
Writes a paragraph-ready line to stdout; recorded in benchmarks/SWEEPS.md.
"""

from __future__ import annotations

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# self-sufficient: the bare command must work (jax reads these at first
# import, which happens inside main())
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.synth import synth_dense
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.parallel import make_mesh
    from cocoa_tpu.solvers import run_cocoa

    n, d, k = 8192, 2048, 4
    data = synth_dense(n, d, seed=0)
    debug = DebugParams(debug_iter=100, seed=0)
    h = n // k // 10
    rounds = 30

    def ms_per_round(fp):
        mesh = make_mesh(k, fp=fp)
        ds = shard_dataset(data, k=k, layout="dense", dtype=jnp.float32,
                           mesh=mesh)
        p = Params(n=n, num_rounds=rounds, local_iters=h, lam=1e-3)
        kw = dict(plus=True, quiet=True, math="fast", mesh=mesh,
                  scan_chunk=10)
        jax.block_until_ready(run_cocoa(ds, p, debug, **kw)[0])  # warm
        t0 = time.perf_counter()
        w, a, traj = run_cocoa(ds, p, debug, **kw)
        jax.block_until_ready(w)   # async dispatch: sync before the clock
        dt = (time.perf_counter() - t0) / rounds * 1e3
        return dt, float(jnp.linalg.norm(w))

    dp_ms, dp_norm = ms_per_round(1)
    fp_ms, fp_norm = ms_per_round(2)
    assert abs(dp_norm - fp_norm) < 1e-3 * max(1.0, dp_norm), \
        (dp_norm, fp_norm)   # same math on both meshes
    print(f"fp overhead (CPU mesh, n={n} d={d} K={k} H={h}, "
          f"{rounds} rounds, fori fast path): "
          f"dp(4)={dp_ms:.1f} ms/round vs dp4xfp2={fp_ms:.1f} ms/round "
          f"-> {fp_ms / dp_ms:.2f}x per round ("
          f"||w|| match {dp_norm:.6f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
