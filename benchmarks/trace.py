"""Capture and summarize a device profiler trace of the hot rounds.

VERDICT r3 item 8: the roofline table (perf.py) ATTRIBUTES round time from
an analytic FLOP/byte model; this records what the hardware actually did.
``python benchmarks/trace.py`` runs a few chunks of the two flagship
configs — the fused block kernel at epsilon scale and the grouped sparse
kernel at rcv1 scale — under ``jax.profiler.trace``, parses the captured
Perfetto trace, and writes the per-op device-time table to
benchmarks/TRACE.md (the committed artifact).

The capture directory itself (hundreds of MB of .xplane.pb) is not
committed; TRACE.md carries the summarized table plus enough provenance
(device, config, date, total device time vs wall) to re-check the
latency-bound claim.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from cocoa_tpu.utils import compile_cache

compile_cache.enable()   # persistent XLA cache: regen compiles once, ever

# the capture/summarize core moved to cocoa_tpu/telemetry/profiling.py so
# production runs (--profile) and this benchmark driver share ONE
# implementation; re-exported here for existing importers
from cocoa_tpu.telemetry.profiling import (  # noqa: E402,F401
    capture, device_table, parse_trace,
)


def main():
    import time

    import jax.numpy as jnp
    import numpy as np

    from cocoa_tpu.config import Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_dense_sharded, synth_sparse
    from cocoa_tpu.ops.pallas_sdca import fold_rows
    from cocoa_tpu.ops.pallas_sparse import row_lengths
    from cocoa_tpu.solvers.base import IndexSampler
    from cocoa_tpu.solvers.cocoa import _alg_config, make_chunk_step

    out_root = os.environ.get("COCOA_TRACE_DIR", "/tmp/cocoa_traces")
    sections = []

    def chunked_runner(ds, params, k, n_rounds, rng="reference", **kw):
        alg = _alg_config(params, k, True)
        sampler = IndexSampler(rng, 0, params.local_iters,
                               ds.counts, device=True)
        step = make_chunk_step(None, params, k, alg, sampler=sampler,
                               math="fast", **kw)
        sa = ds.shard_arrays()
        if kw.get("pallas") and ds.layout == "dense":
            sa = {**sa, "X_folded": fold_rows(sa["X"])}
        if kw.get("pallas") and ds.layout == "sparse":
            sa = {**sa, "sp_row_len": row_lengths(sa["sp_values"])}
        spec = sampler.chunk_indices(1, n_rounds)

        def run():
            w = jnp.zeros(ds.num_features, jnp.float32)
            a = jnp.zeros((k, ds.n_shard), jnp.float32)
            w, a = step(w, a, spec, sa)
            return float(w.sum())

        run()  # compile OUTSIDE the trace
        return run

    # epsilon fused block round
    n, d, k = 400_000, 2000, 8
    eps = synth_dense_sharded(n, d, k, seed=0)
    p_eps = Params(n=n, num_rounds=400, local_iters=n // k // 10, lam=1e-3)
    # the shipped flagship mode: permuted sampling licenses the distinct
    # one-scatter-per-round fused path (docs/DESIGN.md §3b-iii) — the
    # license the production gate (run_sdca_family) checks, asserted here
    # so a config edit cannot silently profile an unsound path
    assert np.all(np.asarray(eps.counts) % p_eps.local_iters == 0), \
        "distinct fused path needs counts % H == 0 (one epoch per round)"
    run_eps = chunked_runner(eps, p_eps, k, 20, rng="permuted",
                             pallas=False, block=128,
                             block_chain="pallas", block_distinct=True)
    t0 = time.perf_counter()
    tdir = capture("epsilon_block128", run_eps, out_root)
    wall = time.perf_counter() - t0
    sections.append(("epsilon block128 (20 rounds, fused kernel, "
                     "permuted/distinct)", parse_trace(tdir), wall, 20))

    # rcv1 grouped sparse round
    n2, d2 = 20242, 47236
    data = synth_sparse(n2, d2, nnz_mean=75, seed=0)
    rc = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32)
    p_rc = Params(n=n2, num_rounds=1500, local_iters=n2 // k // 10, lam=1e-4)
    run_rc = chunked_runner(rc, p_rc, k, 50, pallas=True)
    t0 = time.perf_counter()
    tdir = capture("rcv1_sparse", run_rc, out_root)
    wall = time.perf_counter() - t0
    sections.append(("rcv1 sparse (50 rounds, grouped SMEM kernel)",
                     parse_trace(tdir), wall, 50))

    md = os.path.join(os.path.dirname(os.path.abspath(__file__)), "TRACE.md")
    import datetime

    with open(md, "w") as f:
        f.write(
            "# Device profiler traces — hot-round attribution\n\n"
            "Produced by `python benchmarks/trace.py` on the attached TPU "
            "(jax.profiler capture of a warm fixed-round chunk; compile "
            "excluded).  Hardware-counter companion to the analytic "
            "roofline in RESULTS.md: per-op total device time over the "
            "traced chunk, top ops first.  Caveat: the tunneled capture "
            "emits overlapping op streams, so ABSOLUTE totals can "
            "double-count (~2x vs the slope-measured round times, which "
            "remain the ground truth); the per-op SHARES within a table "
            "are what this artifact pins.  Captured "
            f"{datetime.date.today().isoformat()}.\n")
        for title, tracks, wall, rounds in sections:
            rows, total_us = device_table(tracks)
            f.write(f"\n## {title}\n\n")
            f.write(f"wall {wall:.2f} s for {rounds} rounds; device-op "
                    f"time {total_us / 1e6:.3f} s "
                    f"({total_us / 1e3 / rounds:.2f} ms/round)\n\n")
            f.write("| op | device ms | ms/round | % of device time |\n")
            f.write("|---|---|---|---|\n")
            for track, name, us in rows:
                f.write(f"| `{name[:60]}` | {us / 1e3:.2f} | "
                        f"{us / 1e3 / rounds:.3f} | "
                        f"{100 * us / max(total_us, 1e-9):.1f}% |\n")
            if not rows:
                f.write("| (no device op track captured) | | | |\n")
                # keep the raw track names for debugging capture problems
                f.write("\ncaptured tracks: "
                        + ", ".join(sorted(tracks)) + "\n")
    print(f"wrote {md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
