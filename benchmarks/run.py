"""Benchmark suite — generates the numbers BASELINE.md says this rebuild
must produce (the reference publishes none; see BASELINE.md).

Configs (BASELINE.json "eval" list):

- ``demo``     — the reference's only in-repo baseline: CoCoA+ on
  data/small_train.dat (n=2000, d=9947, K=4, H=50, λ=1e-3,
  run-demo-local.sh:2-9), wall-clock + comm-rounds to a 1e-4 duality gap.
- ``epsilon``  — epsilon-like dense synthetic (400K×2000, unit rows,
  data/synth.py), K=8, H=0.1·n/K, λ=1e-3, to 1e-4 gap.
- ``rcv1``     — rcv1.binary-like sparse synthetic (20242×47236, ~75
  nnz/row), K=8, H=0.1·n/K, λ=1e-4, to 1e-3 and 1e-4 gaps.
- ``mbcd-rcv1`` / ``sgd-epsilon`` — the baseline algorithms on the same
  data (fixed round budgets; they have no duality-gap certificate to
  target — SGD is primal-only, and mini-batch CD's β/(K·H) scaling makes
  gap progress per round much slower than CoCoA's, exactly the point the
  CoCoA papers make).

Each timed run is warm (the first run compiles, the second is measured).
``--quick`` shrinks the synthetic sizes ~10x for smoke-testing the suite.

The ``vs_oracle`` column is the speedup over the literal NumPy oracle of
the Scala update rules (tests/oracle.py) executing the same number of
rounds single-threaded — measured directly for the demo config and
extrapolated from 3 oracle rounds at the big scales (the oracle is the
reference's *math* without Spark overhead, so this flatters the
reference).

Writes one JSON line per config to benchmarks/results.jsonl and a
markdown table to benchmarks/RESULTS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

DEMO_TRAIN = "/root/reference/data/small_train.dat"
DEMO_TEST = "/root/reference/data/small_test.dat"
DEMO_D = 9947


def _time_warm(fn, reps=2):
    """Warm (compiled) best-of-``reps`` timing: the tunneled device's
    dispatch+fetch latency varies by whole seconds run-to-run, so a single
    sample badly overstates small configs."""
    fn()  # compile
    best, out = None, None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return best, out


def _perf(tag, secs, rounds, *, n, d, k, h, layout="dense", nnz=None,
          path="fast", block=0, debug_iter=10, test_n=0):
    """Fold a measured run into the perf-accounting columns (benchmarks/
    perf.py): FLOP model, achieved FLOP/s, MFU, µs per coordinate step,
    HBM floor, and the roofline bound classification."""
    import perf

    model = perf.sdca_round_model(n, d, k, h, layout=layout, nnz=nnz,
                                  path=path, block=block)
    return perf.account(
        tag, secs / max(1, rounds), model, steps=k * h,
        evals_per_round=1.0 / debug_iter,
        eval_fl=perf.eval_flops(n, d, nnz=nnz, test_n=test_n),
    )


def _oracle_rounds_per_s_csr(data, lam, h, k, n, rounds=2, mode="plus"):
    """Single-thread oracle round rate on a SPARSE problem, from the raw
    CSR arrays — the literal per-step math (sparse dot, box projection,
    sparse axpy) without ever densifying X.  Fills the vs_oracle cells the
    r1 benchmarks left empty (dense oracle needs n×d memory)."""
    from cocoa_tpu.data.sharding import split_sizes
    from cocoa_tpu.utils.prng import sample_indices

    sizes = split_sizes(n, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    indptr, indices, values, y = (data.indptr, data.indices, data.values,
                                  data.labels)
    d = data.num_features
    w = np.zeros(d)
    alphas = [np.zeros(sizes[s]) for s in range(k)]
    sigma = float(k)
    plus = mode == "plus"
    lam_n = lam * n
    t0 = time.perf_counter()
    for t in range(1, rounds + 1):
        dw_sum = np.zeros(d)
        for s in range(k):
            idxs = sample_indices(0, range(t, t + 1), h, sizes[s])[0]
            a = alphas[s]
            dw = np.zeros(d)
            # "cocoa": each worker advances a PRIVATE copy of w (the
            # reference ships w in the task closure, CoCoA.scala:142,183);
            # the local advances are discarded, only dw is reduced
            wl = w.copy() if mode == "cocoa" else w
            for li in idxs:
                gi = offs[s] + li
                cols = indices[indptr[gi]:indptr[gi + 1]]
                vals = values[indptr[gi]:indptr[gi + 1]]
                yy = y[gi]
                if plus:
                    grad = (yy * (vals @ w[cols] + sigma * (vals @ dw[cols]))
                            - 1.0) * lam_n
                else:  # "cocoa" (locally-advancing wl) and "frozen" (MbCD)
                    grad = (yy * (vals @ wl[cols]) - 1.0) * lam_n
                proj = grad
                if a[li] <= 0.0:
                    proj = min(grad, 0.0)
                elif a[li] >= 1.0:
                    proj = max(grad, 0.0)
                if proj != 0.0:
                    qii = float(vals @ vals) * (sigma if plus else 1.0)
                    new_a = 1.0 if qii == 0.0 else min(
                        max(a[li] - grad / qii, 0.0), 1.0)
                    coef = yy * (new_a - a[li]) / lam_n
                    dw[cols] += coef * vals
                    if mode == "cocoa":
                        wl[cols] += coef * vals
                    a[li] = new_a
            dw_sum += dw
        w = w + dw_sum  # gamma=1 additive
    return rounds / (time.perf_counter() - t0)


def _oracle_rounds_per_s(ds_like, lam, h, k, n, rounds=3):
    """Single-thread NumPy oracle round rate on this problem (CoCoA+,
    additive), measured over a few rounds."""
    import oracle

    from cocoa_tpu.utils.prng import sample_indices

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    t0 = time.perf_counter()
    for t in range(1, rounds + 1):
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), h, Xk.shape[0])[0]
            da, dw = oracle.local_sdca(
                Xk, yk, w, alphas[s], idxs, lam, n, True, float(k)
            )
            alphas[s] += da
            dw_sum += dw
        w += dw_sum
    return rounds / (time.perf_counter() - t0)


def bench_demo(results, perf_rows):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import load_libsvm, shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    data = load_libsvm(DEMO_TRAIN, DEMO_D)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32)
    params = Params(n=data.n, num_rounds=600, local_iters=50, lam=1e-3)
    debug = DebugParams(debug_iter=10, seed=0)

    def go():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", device_loop=True, gap_target=1e-4)

    secs, (w, a, traj) = _time_warm(go)
    rec = traj.records[-1]
    rate = _oracle_rounds_per_s(
        (data.to_dense(), data.labels), 1e-3, 50, 4, data.n
    )
    results.append(dict(
        config="demo-cocoa+", n=data.n, d=DEMO_D, k=4, h=50,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3),
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis="measured (3 rounds)",
    ))
    perf_rows.append(_perf("demo-cocoa+", secs, rec.round, n=data.n,
                           d=DEMO_D, k=4, h=50, path="pallas"))

    # random reshuffling (--rng=permuted): fewer comm-rounds to the same
    # certified gap — the certificate is exact under any index stream
    def go_perm():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", device_loop=True, gap_target=1e-4,
                         rng="permuted")

    secs_p, (w_p, a_p, traj_p) = _time_warm(go_perm)
    rec_p = traj_p.records[-1]
    results.append(dict(
        config="demo-cocoa+(permuted)", n=data.n, d=DEMO_D, k=4, h=50,
        lam=1e-3, gap_target=1e-4, rounds=rec_p.round,
        gap=float(rec_p.gap), wallclock_s=round(secs_p, 3),
        vs_oracle=round(rec.round / rate / secs_p, 1),
        oracle_basis="oracle rounds = reference-mode rounds",
    ))


def bench_epsilon(results, perf_rows, quick):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.synth import synth_dense_sharded
    from cocoa_tpu.solvers import run_cocoa

    n, d, k = (40_000, 2000, 8) if quick else (400_000, 2000, 8)
    h = n // k // 10
    ds = synth_dense_sharded(n, d, k, seed=0)
    params = Params(n=n, num_rounds=400, local_iters=h, lam=1e-3)
    debug = DebugParams(debug_iter=10, seed=0)

    def go():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", device_loop=True, gap_target=1e-4)

    secs, (w, a, traj) = _time_warm(go)
    rec = traj.records[-1]
    # oracle rate on a small same-d subsample, scaled by n (per-round work
    # is O(H·d) per shard with H ∝ n — linear in n at fixed d, k)
    n_sub = min(n, 20_000)
    rng = np.random.default_rng(0)
    Xs = rng.standard_normal((n_sub, d))
    Xs /= np.linalg.norm(Xs, axis=1, keepdims=True)
    ys = np.where(Xs @ rng.standard_normal(d) >= 0, 1.0, -1.0)
    rate_sub = _oracle_rounds_per_s((Xs, ys), 1e-3, n_sub // k // 10, k, n_sub)
    rate = rate_sub * n_sub / n
    results.append(dict(
        config="epsilon-cocoa+", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3),
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis=f"extrapolated from n={n_sub} subsample",
    ))
    perf_rows.append(_perf("epsilon-cocoa+", secs, rec.round, n=n, d=d,
                           k=k, h=h, path="pallas"))

    # the block-coordinate inner solver (--blockSize=256): same index
    # stream and math, restructured for the MXU (ops/pallas_chain.py)
    def go_block():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", block_size=256, device_loop=True,
                         gap_target=1e-4)

    secs_b, (w_b, a_b, traj_b) = _time_warm(go_block)
    rec_b = traj_b.records[-1]
    results.append(dict(
        config="epsilon-cocoa+(block256)", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec_b.round,
        gap=float(rec_b.gap), wallclock_s=round(secs_b, 3),
        vs_oracle=round(rec_b.round / rate / secs_b, 1),
        oracle_basis=f"extrapolated from n={n_sub} subsample",
    ))
    perf_rows.append(_perf("epsilon-cocoa+(block256)", secs_b, rec_b.round,
                           n=n, d=d, k=k, h=h, path="block", block=256))

    # reshuffled sampling + block kernel: the TPU-first mode — same
    # certified 1e-4 gap in ~5x fewer comm-rounds (see tests/test_permuted)
    def go_pb():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", block_size=256, device_loop=True,
                         gap_target=1e-4, rng="permuted")

    secs_pb, (w_pb, a_pb, traj_pb) = _time_warm(go_pb)
    rec_pb = traj_pb.records[-1]
    results.append(dict(
        config="epsilon-cocoa+(permuted+block256)", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec_pb.round,
        gap=float(rec_pb.gap), wallclock_s=round(secs_pb, 3),
        vs_oracle=round(rec.round / rate / secs_pb, 1),
        oracle_basis="oracle rounds = reference-mode rounds",
    ))
    # no perf row: at ~20 rounds the whole run is tunnel fixed cost and a
    # ms_per_round quotient would be meaningless — the kernel numbers are
    # identical to the block256 row (same executable, different tables)

    # Local SGD on the same data (primal-only baseline; fixed 100 rounds)
    from cocoa_tpu.solvers import run_sgd

    p2 = Params(n=n, num_rounds=100, local_iters=h, lam=1e-3)
    d2 = DebugParams(debug_iter=100, seed=0)

    def go_sgd():
        return run_sgd(ds, p2, d2, local=True, quiet=True, device_loop=True)

    secs2, (w2, traj2) = _time_warm(go_sgd)
    rec2 = traj2.records[-1]
    results.append(dict(
        config="epsilon-localsgd", n=n, d=d, k=k, h=h, lam=1e-3,
        rounds=rec2.round, primal=float(rec2.primal),
        wallclock_s=round(secs2, 3),
    ))
    # SGD.scala:117-129 per step: O(d) rescale + conditional axpy — the
    # "exact"-path model (4·d per step, no margins pass) is the right count
    perf_rows.append(_perf("epsilon-localsgd", secs2, rec2.round, n=n, d=d,
                           k=k, h=h, path="exact", debug_iter=100))


def bench_rcv1(results, perf_rows, quick):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_sparse
    from cocoa_tpu.solvers import run_cocoa, run_minibatch_cd

    n, d, k = (4000, 47236, 8) if quick else (20242, 47236, 8)
    data = synth_sparse(n, d, nnz_mean=75, seed=0)
    ds = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32)
    h = n // k // 10
    debug = DebugParams(debug_iter=25, seed=0)
    nnz = len(data.values) / n
    rate_plus = _oracle_rounds_per_s_csr(data, 1e-4, h, k, n, mode="plus")

    for gap_target in (1e-3, 1e-4):
        params = Params(n=n, num_rounds=1500, local_iters=h, lam=1e-4)

        def go():
            return run_cocoa(ds, params, debug, plus=True, quiet=True,
                             math="fast", device_loop=True,
                             gap_target=gap_target)

        secs, (w, a, traj) = _time_warm(go)
        rec = traj.records[-1]
        results.append(dict(
            config=f"rcv1-cocoa+({gap_target:g})", n=n, d=d, k=k, h=h,
            lam=1e-4, gap_target=gap_target, rounds=rec.round,
            gap=float(rec.gap), wallclock_s=round(secs, 3),
            vs_oracle=round(rec.round / rate_plus / secs, 1),
            oracle_basis="measured (2 rounds, CSR)",
        ))
        perf_rows.append(_perf(f"rcv1-cocoa+({gap_target:g})", secs,
                               rec.round, n=n, d=d, k=k, h=h,
                               layout="sparse", nnz=nnz, path="pallas",
                               debug_iter=25))
        def go_perm():
            return run_cocoa(ds, params, debug, plus=True, quiet=True,
                             math="fast", device_loop=True,
                             gap_target=gap_target, rng="permuted")

        secs_p, (w_p, a_p, traj_p) = _time_warm(go_perm)
        rec_p = traj_p.records[-1]
        results.append(dict(
            config=f"rcv1-cocoa+({gap_target:g}, permuted)", n=n, d=d,
            k=k, h=h, lam=1e-4, gap_target=gap_target,
            rounds=rec_p.round, gap=float(rec_p.gap),
            wallclock_s=round(secs_p, 3),
            vs_oracle=round(rec.round / rate_plus / secs_p, 1),
            oracle_basis="oracle rounds = reference-mode rounds",
        ))

    # Mini-batch CD on the same data (fixed 100 rounds; its β/(K·H)
    # scaling needs far more rounds per unit of gap progress — the CoCoA
    # papers' point)
    p2 = Params(n=n, num_rounds=100, local_iters=h, lam=1e-4)
    d2 = DebugParams(debug_iter=100, seed=0)

    def go_mbcd():
        return run_minibatch_cd(ds, p2, d2, quiet=True, math="fast",
                                device_loop=True)

    secs2, (w2, a2, traj2) = _time_warm(go_mbcd)
    rec2 = traj2.records[-1]
    rate_f = _oracle_rounds_per_s_csr(data, 1e-4, h, k, n, mode="frozen")
    results.append(dict(
        config="rcv1-mbcd", n=n, d=d, k=k, h=h, lam=1e-4,
        rounds=rec2.round, gap=float(rec2.gap), primal=float(rec2.primal),
        wallclock_s=round(secs2, 3),
        vs_oracle=round(rec2.round / rate_f / secs2, 1),
        oracle_basis="measured (2 rounds, CSR)",
    ))
    perf_rows.append(_perf("rcv1-mbcd", secs2, rec2.round, n=n, d=d, k=k,
                           h=h, layout="sparse", nnz=nnz, path="pallas",
                           debug_iter=100))


def _oracle_rounds_per_s_lasso(A, bvec, lam, h, k, rounds=2):
    """Single-thread literal prox-CD oracle round rate (ProxCoCoA+ lasso,
    gamma=1): per step one column dot against r, one against the local
    Δv, a soft-threshold, one column axpy."""
    from cocoa_tpu.data.sharding import split_sizes
    from cocoa_tpu.utils.prng import sample_indices

    n, d = A.shape
    A = np.asfortranarray(A)  # contiguous columns — the unit of access,
                              # as Breeze column vectors are materialized
    sizes = split_sizes(d, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    sigma = float(k)
    r = -bvec.astype(np.float64)
    x = np.zeros(d)
    t0 = time.perf_counter()
    for t in range(1, rounds + 1):
        dv_sum = np.zeros(n)
        for sh in range(k):
            idxs = sample_indices(0, range(t, t + 1), h, sizes[sh])[0]
            dv = np.zeros(n)
            for lj in idxs:
                gj = offs[sh] + lj
                aj = A[:, gj]
                a = x[gj]
                z = aj @ r + sigma * (aj @ dv)
                q = sigma * float(aj @ aj)
                if q <= 0.0:
                    continue
                u = (q * a - z) / q
                tstar = np.sign(u) * max(abs(u) - lam / q, 0.0)
                dv += aj * (tstar - a)
                x[gj] = tstar
            dv_sum += dv
        r = r + dv_sum
    return rounds / (time.perf_counter() - t0)


def bench_lasso(results, perf_rows, quick):
    """ProxCoCoA+ lasso (the L1 extension, no reference analogue): dense
    Gaussian design with a planted 64-sparse x*, λ = 0.3·λ_max, to a
    RELATIVE duality gap of 1e-3 (gap ≤ 1e-3 · ½‖b‖² — lasso objectives
    are scale-dependent, so an absolute target would be meaningless)."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.columns import shard_columns
    from cocoa_tpu.data.libsvm import LibsvmData
    from cocoa_tpu.solvers import run_prox_cocoa

    n, d, k = (2048, 8192, 8) if quick else (8192, 32768, 8)
    rng = np.random.default_rng(0)
    A = rng.standard_normal((n, d)).astype(np.float32) / np.sqrt(n)
    x_true = np.zeros(d, np.float32)
    x_true[rng.choice(d, 64, replace=False)] = \
        rng.standard_normal(64).astype(np.float32) * 3
    bvec = A @ x_true + 0.01 * rng.standard_normal(n).astype(np.float32)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    # values stay f32: shard_columns casts to the compute dtype anyway, and
    # an f64 copy of the dense design would be a ~2 GB host transient
    data = LibsvmData(labels=bvec.astype(np.float64), indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=A.reshape(-1), num_features=d)
    ds, b = shard_columns(data, k, dtype=jnp.float32)
    lam = 0.3 * float(np.max(np.abs(A.T @ bvec)))
    p0 = 0.5 * float(bvec @ bvec)
    h = d // k // 10
    params = Params(n=d, num_rounds=3000, local_iters=h, lam=lam,
                    loss="lasso", smoothing=0.0)
    debug = DebugParams(debug_iter=50, seed=0)

    def go():
        return run_prox_cocoa(ds, b, params, debug, quiet=True, math="fast",
                              device_loop=True, gap_target=1e-3 * p0)

    secs, (x, r, traj) = _time_warm(go)
    rec = traj.records[-1]
    rate = _oracle_rounds_per_s_lasso(A, bvec, lam, h, k)
    results.append(dict(
        config="lasso-proxcocoa+", n=n, d=d, k=k, h=h,
        lam=round(lam, 5), gap_target=f"1e-3 relative", rounds=rec.round,
        gap=float(rec.gap), wallclock_s=round(secs, 3),
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis="measured (2 rounds)",
    ))
    # roles swapped: d coordinates play the example axis, dense columns of
    # length n play the rows (see solvers/prox_cocoa.py)
    perf_rows.append(_perf("lasso-proxcocoa+", secs, rec.round, n=d, d=n,
                           k=k, h=h, path="pallas", debug_iter=50))

    def go_perm():
        return run_prox_cocoa(ds, b, params, debug, quiet=True, math="fast",
                              device_loop=True, gap_target=1e-3 * p0,
                              rng="permuted")

    secs_p, (x_p, r_p, traj_p) = _time_warm(go_perm)
    rec_p = traj_p.records[-1]
    results.append(dict(
        config="lasso-proxcocoa+(permuted)", n=n, d=d, k=k, h=h,
        lam=round(lam, 5), gap_target=f"1e-3 relative",
        rounds=rec_p.round, gap=float(rec_p.gap),
        wallclock_s=round(secs_p, 3),
        vs_oracle=round(rec.round / rate / secs_p, 1),
        oracle_basis="oracle rounds = reference-mode rounds",
    ))


def write_results(results, perf_rows, out_dir, partial=False):
    """Full runs own results.jsonl / RESULTS.md (the artifacts BASELINE.md
    cites); --quick / --only runs write to *.partial.* so they can never
    clobber the recorded numbers."""
    suffix = ".partial" if partial else ""
    jl = os.path.join(out_dir, f"results{suffix}.jsonl")
    with open(jl, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
        for r in perf_rows:
            f.write(json.dumps({"type": "perf", **r}) + "\n")
    md = os.path.join(out_dir, f"RESULTS{suffix}.md")
    cols = ["config", "n", "d", "k", "h", "lam", "gap_target", "rounds",
            "gap", "primal", "wallclock_s", "vs_oracle"]
    with open(md, "w") as f:
        f.write("# Benchmark results\n\n")
        f.write("Produced by `python benchmarks/run.py` on the attached "
                "TPU device (single chip, K logical shards).  See the "
                "module docstring for config definitions and the "
                "`vs_oracle` methodology.\n\n")
        f.write("| " + " | ".join(cols) + " |\n")
        f.write("|" + "---|" * len(cols) + "\n")
        for r in results:
            f.write("| " + " | ".join(
                str(r.get(c, "")) if not isinstance(r.get(c), float)
                else f"{r[c]:.4g}" for c in cols
            ) + " |\n")
        if perf_rows:
            f.write(
                "\n## Perf accounting (VERDICT r1 item 1)\n\n"
                "FLOP/byte models in `benchmarks/perf.py`; the accounting "
                "contract is the reference hot loop CoCoA.scala:148-188 "
                "(4·nnz useful FLOPs per coordinate step) plus the margins "
                "and eval passes of the measured path.  `useful` counts the "
                "reference's math; `physical` adds the FLOPs the TPU "
                "formulation spends to buy parallelism (block Gram work, "
                "lane padding).  MFU is against the chip's public dense "
                "bf16 peak — a conservative lower bound for f32 work.  "
                "Times include the per-`debugIter` eval amortized in, and "
                "the tunneled device's dispatch+fetch overhead — hundreds "
                "of ms to several seconds, varying run to run — spread "
                "over the run's rounds, which can dominate ms_per_round "
                "at small round counts; benchmarks/KERNELS.md carries the "
                "slope-measured per-round kernel times with that overhead "
                "cancelled.\n\n"
            )
            pcols = ["config", "device", "ms_per_round", "us_per_step",
                     "useful_gflops", "physical_gflops", "mfu_pct",
                     "physical_mfu_pct", "hbm_floor_ms", "hbm_bound_pct",
                     "bound"]
            f.write("| " + " | ".join(pcols) + " |\n")
            f.write("|" + "---|" * len(pcols) + "\n")
            for r in perf_rows:
                f.write("| " + " | ".join(str(r.get(c, "")) for c in pcols)
                        + " |\n")
            f.write(
                "\nEvery config is latency-bound: the measured round time "
                "sits far above both the HBM-traffic floor and the FLOP "
                "floor, because the algorithm's hot loop is a sequential "
                "chain of O(nnz) coordinate steps (CoCoA.scala:148-188) — "
                "per-step chain latency (~1-4 µs across the kernels, "
                "~0.9 µs for the block-coordinate kernel), not bandwidth "
                "or MXU throughput, sets the ceiling.  Corollary: rcv1's "
                "1450 rounds to the 1e-4 gap is λ=1e-4 *conditioning* "
                "(2.6 µs/step is already near the chain floor; the same "
                "kernel reaches the 1e-3 gap in 325 rounds), not a sparse-"
                "kernel inefficiency.\n"
                "\nRoofline reading, per config:\n\n"
            )
            for r in perf_rows:
                hbm = r.get("hbm_bound_pct")
                f.write(
                    f"- **{r['config']}** — {r['ms_per_round']} ms/round, "
                    f"{r['us_per_step']} µs per coordinate step "
                    f"(amortized over the K parallel shards); useful "
                    f"{r['useful_gflops']} GFLOP/s ≈ "
                    f"{r.get('mfu_pct', '?')}% MFU "
                    f"(physical {r.get('physical_mfu_pct', '?')}%).  The "
                    f"HBM-traffic model floor is {r.get('hbm_floor_ms', '?')} "
                    f"ms ({hbm}% of measured) → **{r.get('bound', '?')}-"
                    f"bound**.\n"
                )
    print(f"wrote {jl} and {md}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="~10x smaller synthetic sizes (smoke test)")
    ap.add_argument("--only", default="",
                    help="comma-separated subset: demo,epsilon,rcv1,lasso")
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None

    results = []
    perf_rows = []
    if only is None or "demo" in only:
        bench_demo(results, perf_rows)
        print(json.dumps(results[-1]))
    if only is None or "epsilon" in only:
        bench_epsilon(results, perf_rows, args.quick)
        for r in results[-3:]:
            print(json.dumps(r))
    if only is None or "rcv1" in only:
        bench_rcv1(results, perf_rows, args.quick)
        for r in results[-3:]:
            print(json.dumps(r))
    if only is None or "lasso" in only:
        bench_lasso(results, perf_rows, args.quick)
        print(json.dumps(results[-1]))
    for r in perf_rows:
        print(json.dumps({"type": "perf", **r}))
    write_results(results, perf_rows,
                  os.path.dirname(os.path.abspath(__file__)),
                  partial=args.quick or only is not None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
