"""Benchmark suite — generates the numbers BASELINE.md says this rebuild
must produce (the reference publishes none; see BASELINE.md).

Configs (BASELINE.json "eval" list):

- ``demo``     — the reference's only in-repo baseline: CoCoA+ on
  data/small_train.dat (n=2000, d=9947, K=4, H=50, λ=1e-3,
  run-demo-local.sh:2-9), wall-clock + comm-rounds to a 1e-4 duality gap.
- ``epsilon``  — epsilon-like dense synthetic (400K×2000, unit rows,
  data/synth.py), K=8, H=0.1·n/K, λ=1e-3, to 1e-4 gap.
- ``rcv1``     — rcv1.binary-like sparse synthetic (20242×47236, ~75
  nnz/row), K=8, H=0.1·n/K, λ=1e-4, to 1e-3 and 1e-4 gaps.
- ``mbcd-rcv1`` / SGD-family / DistGD rows — the remaining reference
  algorithms on the same data (fixed round budgets; they have no duality-
  gap certificate to target — SGD/DistGD are primal-only, and mini-batch
  CD's β/(K·H) scaling makes gap progress per round much slower than
  CoCoA's, exactly the point the CoCoA papers make).  All six reference
  algorithms (hingeDriver.scala:84-110) have a row.
- ``lasso`` / ``elastic`` — ProxCoCoA+ on the L1 / L1+L2 objectives.

**Timing is slope-measured** (VERDICT r2 item 2): the raw wall-clock of a
run through a tunneled device carries hundreds of ms of dispatch+fetch
noise — more than many whole configs.  For each config the gap-targeted
run determines the round count R (and verifies the certificate); two
fixed-round runs at R and m·R then give per_round = (T(mR) − T(R))/((m−1)R),
``wallclock_s`` = per_round·R (the steady state), and ``fixed_s`` =
T(R) − wallclock_s (the dispatch overhead, reported separately).  m is
sized so the span dominates the noise.  ``--quick`` shrinks the synthetic
sizes ~10x for smoke-testing the suite.

The ``vs_oracle`` column is the speedup over the literal NumPy oracle of
the Scala update rules (tests/oracle.py) executing the same number of
rounds single-threaded — measured directly for the demo config and
extrapolated from a few oracle rounds at the big scales (the oracle is
the reference's *math* without Spark overhead, so this flatters the
reference).  Permuted-sampling rows reach the same certified gap in
FEWER rounds; their cross-mode speedup (oracle at reference-mode rounds
vs the permuted run's wall-clock) is reported in a separate
``vs_oracle_same_gap`` column so ``vs_oracle`` keeps one meaning
(ADVICE r2).

Writes one JSON line per config to benchmarks/results.jsonl, a markdown
table to benchmarks/RESULTS.md, and regenerates the marked perf blocks
in BASELINE.md and PARITY.md from the same rows (one source of truth).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

from cocoa_tpu.utils import compile_cache

compile_cache.enable()   # persistent XLA cache: regen compiles once, ever

_REPO_DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")
_REF_DATA = "/root/reference/data"


def _demo_file(name):
    # per-file probe: a partial reference checkout falls back to the
    # identical committed twin (same rule as tests/conftest.py, bench.py)
    ref = os.path.join(_REF_DATA, name)
    return ref if os.path.exists(ref) else os.path.join(_REPO_DATA, name)


DEMO_TRAIN = _demo_file("small_train.dat")
DEMO_TEST = _demo_file("small_test.dat")
DEMO_D = 9947

# published shapes of the real datasets (the integrity pin the air-gapped
# build CAN carry — see benchmarks/fetch_data.sh for the sha256 story).
# (n, d, nnz/row range): epsilon is dense (exactly d per row); rcv1's
# published average is ~73.2 cosine-normalized tf-idf terms per document
# (ADVICE r3: shape alone passes for any same-line-count file — also pin
# density, which a corrupted/wrong file of the same n would not match).
REAL_SHAPES = {
    "rcv1_train.binary": (20_242, 47_236, (60.0, 90.0)),
    "epsilon_normalized": (400_000, 2_000, (2000.0, 2000.0)),
}


def _maybe_real(data_dir, fname):
    """Load benchmarks/data/<fname> when present (fetched by
    fetch_data.sh), validating the published (n, d) shape and nnz/row
    density; None when absent (the synthetic stand-in is used and labeled
    as such)."""
    path = os.path.join(data_dir, fname)
    if not os.path.exists(path):
        return None
    from cocoa_tpu.data import load_libsvm

    n_want, d_want, (nz_lo, nz_hi) = REAL_SHAPES[fname]
    data = load_libsvm(path, d_want)
    nnz_row = len(data.values) / max(1, data.n)
    if data.n != n_want or not (nz_lo <= nnz_row <= nz_hi):
        raise ValueError(
            f"{path}: expected the published shape n={n_want} (d={d_want}) "
            f"with {nz_lo}-{nz_hi} nnz/row, parsed n={data.n} "
            f"nnz/row={nnz_row:.1f} — corrupt or wrong file"
        )
    print(f"using real dataset {fname}: n={data.n} d={d_want} "
          f"nnz/row={nnz_row:.1f}")
    return data


def _dense_subsample(data, n_sub):
    """(X, y) dense NumPy arrays of the first n_sub rows (oracle input)."""
    X = np.zeros((n_sub, data.num_features))
    for i in range(n_sub):
        lo, hi = data.indptr[i], data.indptr[i + 1]
        X[i, data.indices[lo:hi]] = data.values[lo:hi]
    return X, data.labels[:n_sub].astype(np.float64)


from slope import slope_time as _slope_time  # noqa: E402


def _timed(make_run, rounds, **kw):
    """(steady_s, fixed_s, quality-dict) — rows carry ``noisy``/``span_s``
    when the slope escalation exited without the span dominating the
    tunnel jitter (ADVICE r3: a degraded measurement must not look like a
    clean one; the round-3 rcv1-permuted anomaly had that signature)."""
    sr = _slope_time(make_run, rounds, **kw)
    q = ({"noisy": True, "span_s": round(sr.span_s, 3)}
         if sr.degraded else {})
    return sr.steady_s, sr.fixed_s, q


def _perf(tag, secs, rounds, *, n, d, k, h, layout="dense", nnz=None,
          path="fast", block=0, debug_iter=10, test_n=0):
    """Fold a measured run into the perf-accounting columns (benchmarks/
    perf.py): FLOP model, achieved FLOP/s, MFU, µs per coordinate step,
    HBM floor, and the roofline bound classification."""
    import perf

    model = perf.sdca_round_model(n, d, k, h, layout=layout, nnz=nnz,
                                  path=path, block=block)
    return perf.account(
        tag, secs / max(1, rounds), model, steps=k * h,
        evals_per_round=1.0 / debug_iter,
        eval_fl=perf.eval_flops(n, d, nnz=nnz, test_n=test_n),
    )


def _round_rate(run_round, rounds, reps=3):
    """rounds/sec of ``run_round(t)`` (t 1-based), with round 1 executed
    as an UNTIMED warm-up: the first NumPy round pays allocation/BLAS
    warm-up and a 2-3 round window would otherwise overstate vs_oracle
    ~3x vs the pinned bench.py rate.

    BEST of ``reps`` windows: single-thread NumPy timing swings ~2x with
    concurrent host load (observed across same-day regens: identical
    rcv1 configs read 13.2x and 9.3x vs_oracle purely from oracle-rate
    noise), and the best window is the least-contended — i.e. the
    fairest — estimate of the oracle's true speed."""
    run_round(1)
    t = 2
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(rounds):
            run_round(t)
            t += 1
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return rounds / best


def _oracle_rounds_per_s_csr(data, lam, h, k, n, rounds=2, mode="plus"):
    """Single-thread oracle round rate on a SPARSE problem, from the raw
    CSR arrays — the literal per-step math (sparse dot, box projection,
    sparse axpy) without ever densifying X.  Fills the vs_oracle cells the
    r1 benchmarks left empty (dense oracle needs n×d memory)."""
    from cocoa_tpu.data.sharding import split_sizes
    from cocoa_tpu.utils.prng import sample_indices

    sizes = split_sizes(n, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    indptr, indices, values, y = (data.indptr, data.indices, data.values,
                                  data.labels)
    d = data.num_features
    w = np.zeros(d)
    alphas = [np.zeros(sizes[s]) for s in range(k)]
    sigma = float(k)
    plus = mode == "plus"
    lam_n = lam * n

    def step(t):
        nonlocal w
        dw_sum = np.zeros(d)
        for s in range(k):
            idxs = sample_indices(0, range(t, t + 1), h, sizes[s])[0]
            a = alphas[s]
            dw = np.zeros(d)
            # "cocoa": each worker advances a PRIVATE copy of w (the
            # reference ships w in the task closure, CoCoA.scala:142,183);
            # the local advances are discarded, only dw is reduced
            wl = w.copy() if mode == "cocoa" else w
            for li in idxs:
                gi = offs[s] + li
                cols = indices[indptr[gi]:indptr[gi + 1]]
                vals = values[indptr[gi]:indptr[gi + 1]]
                yy = y[gi]
                if plus:
                    grad = (yy * (vals @ w[cols] + sigma * (vals @ dw[cols]))
                            - 1.0) * lam_n
                else:  # "cocoa" (locally-advancing wl) and "frozen" (MbCD)
                    grad = (yy * (vals @ wl[cols]) - 1.0) * lam_n
                proj = grad
                if a[li] <= 0.0:
                    proj = min(grad, 0.0)
                elif a[li] >= 1.0:
                    proj = max(grad, 0.0)
                if proj != 0.0:
                    qii = float(vals @ vals) * (sigma if plus else 1.0)
                    new_a = 1.0 if qii == 0.0 else min(
                        max(a[li] - grad / qii, 0.0), 1.0)
                    coef = yy * (new_a - a[li]) / lam_n
                    dw[cols] += coef * vals
                    if mode == "cocoa":
                        wl[cols] += coef * vals
                    a[li] = new_a
            dw_sum += dw
        w = w + dw_sum  # gamma=1 additive

    return _round_rate(step, rounds)


def _oracle_rounds_per_s(ds_like, lam, h, k, n, rounds=3):
    """Single-thread NumPy oracle round rate on this problem (CoCoA+,
    additive), measured over a few rounds."""
    import oracle

    from cocoa_tpu.utils.prng import sample_indices

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]

    def step(t):
        nonlocal w
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), h, Xk.shape[0])[0]
            da, dw = oracle.local_sdca(
                Xk, yk, w, alphas[s], idxs, lam, n, True, float(k)
            )
            alphas[s] += da
            dw_sum += dw
        w += dw_sum

    return _round_rate(step, rounds)


def _oracle_rounds_per_s_sgd(ds_like, lam, h, k, rounds=3, local=True):
    """Single-thread oracle round rate for the SGD family (SGD.scala):
    per round each shard runs H Pegasos-style steps (local) or sums raw
    subgradients (mini-batch); driver applies the scaling law."""
    import oracle

    from cocoa_tpu.utils.prng import sample_indices

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])

    def step(t):
        nonlocal w
        if not local:
            eta = 1.0 / (lam * t)
            w = w * (1.0 - eta * lam)
        dw_sum = np.zeros_like(w)
        for sidx, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), h, Xk.shape[0])[0]
            t_global = (t - 1) * h * k
            dw_sum += oracle.sgd_partition(Xk, yk, w, idxs, lam, t_global,
                                           local)
        if local:
            w = w + dw_sum / k           # beta/K, beta=1 (SGD.scala:36,55)
        else:
            w = w + dw_sum * (eta / (k * h))   # eta*beta/(K*H) (:38,57-59)

    return _round_rate(step, rounds)


def _oracle_rounds_per_s_distgd(ds_like, lam, k, rounds=2):
    """Single-thread oracle round rate for DistGD (DistGD.scala): one
    deterministic full pass per shard per round + the normalized step."""
    import oracle

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])

    def step(t):
        nonlocal w
        dw = np.zeros_like(w)
        for Xk, yk in shards:
            dw += oracle.dist_gd_partition(Xk, yk, w, lam)
        nrm = np.linalg.norm(dw)
        if nrm > 0:
            w = w + dw * ((1.0 / t) / nrm)    # eta = 1/(beta*t), beta=1

    return _round_rate(step, rounds)


def bench_demo(results, perf_rows):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import load_libsvm, shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    data = load_libsvm(DEMO_TRAIN, DEMO_D)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32)
    debug = DebugParams(debug_iter=10, seed=0)

    def make_run(nr, rng="reference"):
        p = Params(n=data.n, num_rounds=nr, local_iters=50, lam=1e-3)
        return lambda: run_cocoa(ds, p, debug, plus=True, quiet=True,
                                 math="fast", device_loop=True, rng=rng)

    def gap_run(rng="reference"):
        p = Params(n=data.n, num_rounds=600, local_iters=50, lam=1e-3)
        return run_cocoa(ds, p, debug, plus=True, quiet=True, math="fast",
                         device_loop=True, gap_target=1e-4, rng=rng)

    w, a, traj = gap_run()
    rec = traj.records[-1]
    # the demo workload is tiny (~0.03 ms/round after the round-4 kernels);
    # the default escalation cap cannot build a jitter-dominating span, so
    # raise it for the demo rows rather than record them as noisy
    secs, fixed, q = _timed(make_run, rec.round, max_mult=256)
    rate = _oracle_rounds_per_s(
        (data.to_dense(), data.labels), 1e-3, 50, 4, data.n
    )
    results.append(dict(
        config="demo-cocoa+", n=data.n, d=DEMO_D, k=4, h=50,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3), fixed_s=round(fixed, 3), **q,
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis="measured (3 rounds)",
    ))
    perf_rows.append(_perf("demo-cocoa+", secs, rec.round, n=data.n,
                           d=DEMO_D, k=4, h=50, path="pallas"))

    # random reshuffling (--rng=permuted): fewer comm-rounds to the same
    # certified gap — the certificate is exact under any index stream
    w_p, a_p, traj_p = gap_run("permuted")
    rec_p = traj_p.records[-1]
    secs_p, fixed_p, q_p = _timed(
        lambda nr: make_run(nr, "permuted"), rec_p.round, max_mult=256)
    results.append(dict(
        config="demo-cocoa+(permuted)", n=data.n, d=DEMO_D, k=4, h=50,
        lam=1e-3, gap_target=1e-4, rounds=rec_p.round,
        gap=float(rec_p.gap), wallclock_s=round(secs_p, 3),
        fixed_s=round(fixed_p, 3), **q_p,
        vs_oracle_same_gap=round(rec.round / rate / secs_p, 1),
        oracle_basis="same-gap: oracle at reference-mode rounds",
    ))


def bench_epsilon(results, perf_rows, quick, data_dir=""):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.synth import synth_dense_sharded
    from cocoa_tpu.solvers import run_cocoa, run_dist_gd, run_sgd

    real = None if quick else _maybe_real(data_dir, "epsilon_normalized")
    tag = "epsilon(real)" if real is not None else "epsilon"
    if real is not None:
        from cocoa_tpu.data import shard_dataset as _shard

        import jax.numpy as _jnp

        n, d, k = real.n, real.num_features, 8
        ds = _shard(real, k=k, layout="dense", dtype=_jnp.float32)
    else:
        n, d, k = (40_000, 2000, 8) if quick else (400_000, 2000, 8)
        ds = synth_dense_sharded(n, d, k, seed=0)
    h = n // k // 10
    debug = DebugParams(debug_iter=10, seed=0)

    def make_run(nr, rng="reference", block=0):
        p = Params(n=n, num_rounds=nr, local_iters=h, lam=1e-3)
        return lambda: run_cocoa(ds, p, debug, plus=True, quiet=True,
                                 math="fast", device_loop=True, rng=rng,
                                 block_size=block)

    def gap_run(rng="reference", block=0):
        p = Params(n=n, num_rounds=400, local_iters=h, lam=1e-3)
        return run_cocoa(ds, p, debug, plus=True, quiet=True, math="fast",
                         device_loop=True, gap_target=1e-4, rng=rng,
                         block_size=block)

    w, a, traj = gap_run()
    rec = traj.records[-1]
    secs, fixed, q = _timed(make_run, rec.round)
    # oracle rate on a small same-d subsample, scaled by n (per-round work
    # is O(H·d) per shard with H ∝ n — linear in n at fixed d, k)
    n_sub = min(n, 20_000)
    if real is not None:
        Xs, ys = _dense_subsample(real, n_sub)
    else:
        rng = np.random.default_rng(0)
        Xs = rng.standard_normal((n_sub, d))
        Xs /= np.linalg.norm(Xs, axis=1, keepdims=True)
        ys = np.where(Xs @ rng.standard_normal(d) >= 0, 1.0, -1.0)
    rate_sub = _oracle_rounds_per_s((Xs, ys), 1e-3, n_sub // k // 10, k, n_sub)
    rate = rate_sub * n_sub / n
    basis = f"extrapolated from n={n_sub} subsample"
    results.append(dict(
        config=f"{tag}-cocoa+", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3), fixed_s=round(fixed, 3), **q,
        vs_oracle=round(rec.round / rate / secs, 1), oracle_basis=basis,
    ))
    perf_rows.append(_perf(f"{tag}-cocoa+", secs, rec.round, n=n, d=d,
                           k=k, h=h, path="pallas"))

    # the block-coordinate inner solver (--blockSize=128): same index
    # stream and math, restructured for the MXU — the fused per-block
    # kernel (ops/pallas_chain.fused_block)
    w_b, a_b, traj_b = gap_run(block=128)
    rec_b = traj_b.records[-1]
    secs_b, fixed_b, q_b = _timed(lambda nr: make_run(nr, block=128),
                                  rec_b.round)
    results.append(dict(
        config=f"{tag}-cocoa+(block128)", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec_b.round,
        gap=float(rec_b.gap), wallclock_s=round(secs_b, 3),
        fixed_s=round(fixed_b, 3), **q_b,
        vs_oracle=round(rec_b.round / rate / secs_b, 1), oracle_basis=basis,
    ))
    perf_rows.append(_perf(f"{tag}-cocoa+(block128)", secs_b, rec_b.round,
                           n=n, d=d, k=k, h=h, path="block", block=128))

    # reshuffled sampling + block kernel: the TPU-first mode — same
    # certified 1e-4 gap in ~5x fewer comm-rounds (see tests/test_permuted)
    w_pb, a_pb, traj_pb = gap_run("permuted", block=128)
    rec_pb = traj_pb.records[-1]
    secs_pb, fixed_pb, q_pb = _timed(
        lambda nr: make_run(nr, "permuted", block=128), rec_pb.round)
    results.append(dict(
        config=f"{tag}-cocoa+(permuted+block128)", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec_pb.round,
        gap=float(rec_pb.gap), wallclock_s=round(secs_pb, 3),
        fixed_s=round(fixed_pb, 3), **q_pb,
        vs_oracle_same_gap=round(rec.round / rate / secs_pb, 1),
        oracle_basis="same-gap: oracle at reference-mode rounds",
    ))
    # the permuted+distinct block round in the ACCOUNTING table too
    # (VERDICT r5 weak #3: the measured distinct-path ms/round appeared
    # in no citable perf row — only the wall-clock table)
    perf_rows.append(_perf(f"{tag}-cocoa+(permuted+block128)", secs_pb,
                           rec_pb.round, n=n, d=d, k=k, h=h, path="block",
                           block=128))

    # Local SGD on the same data (primal-only baseline; fixed 100 rounds)
    d2 = DebugParams(debug_iter=100, seed=0)

    def make_sgd(nr, local=True):
        p = Params(n=n, num_rounds=nr, local_iters=h, lam=1e-3)
        return lambda: run_sgd(ds, p, d2, local=local, quiet=True,
                               device_loop=True)

    w2, traj2 = make_sgd(100)()
    rec2 = traj2.records[-1]
    secs2, fixed2, q2 = _timed(make_sgd, 100)
    rate_lsgd = _oracle_rounds_per_s_sgd((Xs, ys), 1e-3, n_sub // k // 10,
                                         k, local=True) * n_sub / n
    results.append(dict(
        config=f"{tag}-localsgd", n=n, d=d, k=k, h=h, lam=1e-3,
        rounds=rec2.round, primal=float(rec2.primal),
        wallclock_s=round(secs2, 3), fixed_s=round(fixed2, 3), **q2,
        vs_oracle=round(100 / rate_lsgd / secs2, 1), oracle_basis=basis,
    ))
    # SGD.scala:117-129 per step: O(d) rescale + conditional axpy — the
    # "exact"-path model (4·d per step, no margins pass) is the right count
    perf_rows.append(_perf(f"{tag}-localsgd", secs2, rec2.round, n=n, d=d,
                           k=k, h=h, path="exact", debug_iter=100))

    # Mini-batch SGD (SGD.scala local=false; fixed 100 rounds)
    w3, traj3 = make_sgd(100, local=False)()
    rec3 = traj3.records[-1]
    secs3, fixed3, q3 = _timed(lambda nr: make_sgd(nr, local=False), 100)
    rate_mbsgd = _oracle_rounds_per_s_sgd((Xs, ys), 1e-3, n_sub // k // 10,
                                          k, local=False) * n_sub / n
    results.append(dict(
        config=f"{tag}-mbsgd", n=n, d=d, k=k, h=h, lam=1e-3,
        rounds=rec3.round, primal=float(rec3.primal),
        wallclock_s=round(secs3, 3), fixed_s=round(fixed3, 3), **q3,
        vs_oracle=round(100 / rate_mbsgd / secs3, 1), oracle_basis=basis,
    ))
    perf_rows.append(_perf(f"{tag}-mbsgd", secs3, rec3.round, n=n, d=d,
                           k=k, h=h, path="exact", debug_iter=100))

    # DistGD (full deterministic subgradient pass per round; fixed 50
    # rounds — its per-round cost is a whole-shard pass, H-independent)
    from cocoa_tpu.config import Params as _P

    d3 = DebugParams(debug_iter=50, seed=0)

    def make_dgd(nr):
        p = _P(n=n, num_rounds=nr, local_iters=h, lam=1e-3)
        return lambda: run_dist_gd(ds, p, d3, quiet=True, device_loop=True)

    w4, traj4 = make_dgd(50)()
    rec4 = traj4.records[-1]
    secs4, fixed4, q4 = _timed(make_dgd, 50)
    # per-round cost is one full shard pass: rate scales 1/n at fixed d, k
    rate_dgd = _oracle_rounds_per_s_distgd((Xs, ys), 1e-3, k) * n_sub / n
    results.append(dict(
        config=f"{tag}-distgd", n=n, d=d, k=k, h="n/K",
        lam=1e-3, rounds=rec4.round, primal=float(rec4.primal),
        wallclock_s=round(secs4, 3), fixed_s=round(fixed4, 3), **q4,
        vs_oracle=round(50 / rate_dgd / secs4, 1), oracle_basis=basis,
    ))
    # DistGD reads every row once per round: model it as one "margins
    # pass" with zero coordinate steps
    import perf as _perfmod

    model = _perfmod.sdca_round_model(n, d, k, 0, path="fast")
    perf_rows.append(_perfmod.account(
        f"{tag}-distgd", secs4 / max(1, rec4.round), model,
        steps=n,   # one subgradient evaluation per example per round
        evals_per_round=1.0 / 50,
        eval_fl=_perfmod.eval_flops(n, d),
    ))


def bench_rcv1(results, perf_rows, quick, data_dir=""):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_sparse
    from cocoa_tpu.solvers import run_cocoa, run_minibatch_cd

    real = None if quick else _maybe_real(data_dir, "rcv1_train.binary")
    rtag = "rcv1(real)" if real is not None else "rcv1"
    if real is not None:
        data, (n, d, k) = real, (real.n, real.num_features, 8)
    else:
        n, d, k = (4000, 47236, 8) if quick else (20242, 47236, 8)
        data = synth_sparse(n, d, nnz_mean=75, seed=0)
    # eval_dense: the certificate's full margins pass rides the MXU
    # instead of the every-nonzero w-gather — production A/B at this
    # config: 9.42 -> 6.46 ms/round (the gather eval was 31% of the round)
    ds = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32,
                       eval_dense=True)
    h = n // k // 10
    debug = DebugParams(debug_iter=25, seed=0)
    nnz = len(data.values) / n
    rate_plus = _oracle_rounds_per_s_csr(data, 1e-4, h, k, n, mode="plus")

    def make_run(nr, rng="reference", sigma=None):
        p = Params(n=n, num_rounds=nr, local_iters=h, lam=1e-4, sigma=sigma)
        return lambda: run_cocoa(ds, p, debug, plus=True, quiet=True,
                                 math="fast", device_loop=True, rng=rng)

    for gap_target in (1e-3, 1e-4):
        def gap_run(rng="reference", sigma=None, gap_target=gap_target,
                    accel=None, theta=None):
            p = Params(n=n, num_rounds=1500, local_iters=h, lam=1e-4,
                       sigma=sigma)
            return run_cocoa(ds, p, debug, plus=True, quiet=True,
                             math="fast", device_loop=True,
                             gap_target=gap_target, rng=rng, accel=accel,
                             theta=theta)

        w, a, traj = gap_run()
        rec = traj.records[-1]
        secs, fixed, q = _timed(make_run, rec.round)
        results.append(dict(
            config=f"{rtag}-cocoa+({gap_target:g})", n=n, d=d, k=k, h=h,
            lam=1e-4, gap_target=gap_target, rounds=rec.round,
            gap=float(rec.gap), wallclock_s=round(secs, 3),
            fixed_s=round(fixed, 3), **q,
            vs_oracle=round(rec.round / rate_plus / secs, 1),
            oracle_basis="measured (2 rounds, CSR)",
        ))
        perf_rows.append(_perf(f"{rtag}-cocoa+({gap_target:g})", secs,
                               rec.round, n=n, d=d, k=k, h=h,
                               layout="sparse", nnz=nnz, path="pallas",
                               debug_iter=25))

        w_p, a_p, traj_p = gap_run("permuted")
        rec_p = traj_p.records[-1]
        secs_p, fixed_p, q_p = _timed(
            lambda nr: make_run(nr, "permuted"), rec_p.round)
        results.append(dict(
            config=f"{rtag}-cocoa+({gap_target:g}, permuted)", n=n, d=d,
            k=k, h=h, lam=1e-4, gap_target=gap_target,
            rounds=rec_p.round, gap=float(rec_p.gap),
            wallclock_s=round(secs_p, 3), fixed_s=round(fixed_p, 3), **q_p,
            vs_oracle_same_gap=round(rec.round / rate_plus / secs_p, 1),
            oracle_basis="same-gap: oracle at reference-mode rounds",
        ))

        if gap_target == 1e-4:
            # the comm-round attack (VERDICT r3 item 3): comm-rounds IS
            # the baseline metric, and at λ=1e-4 the safe σ′=K needs
            # ~1150 of them.  Every lever was measured: 10x local work
            # (localIterFrac=1) saturates at ~2.8x fewer rounds-to-7e-4
            # then stalls; γ<1 is strictly worse; a smooth-hinge warm
            # start moves nothing (±25 rounds); σ′ < K/2 diverges
            # (σ′=3.5 at K=8 — visibly, the certificate is exact).
            # σ′ = K/2 (--sigma) HALVES the certified rounds — the one
            # lever that pays, recorded as its own row.
            _, _, traj_s = gap_run("permuted", sigma=k / 2.0)
            rec_s = traj_s.records[-1]
            secs_s, fixed_s_, q_s = _timed(
                lambda nr: make_run(nr, "permuted", sigma=k / 2.0),
                rec_s.round)
            results.append(dict(
                config=f"{rtag}-cocoa+({gap_target:g}, permuted, "
                       f"sigma=K/2)",
                n=n, d=d, k=k, h=h, lam=1e-4, gap_target=gap_target,
                rounds=rec_s.round, gap=float(rec_s.gap),
                wallclock_s=round(secs_s, 3), fixed_s=round(fixed_s_, 3),
                **q_s,
                vs_oracle_same_gap=round(
                    rec.round / rate_plus / secs_s, 1),
                oracle_basis="same-gap: oracle at reference-mode rounds",
            ))

            # the HEADLINED rcv1 production row (VERDICT r5 next #2):
            # permuted sampling + σ′=auto (the guarded K·γ/2 trial with
            # the safe fallback) + the dense eval twin (ds above is built
            # with eval_dense=True) — the config the production CLI flags
            # select, stated in the table next to the reference-faithful
            # rows whose parallel-oracle column reads sub-parity.
            _, _, traj_pr = gap_run("permuted", sigma="auto")
            rec_pr = traj_pr.records[-1]
            # time fixed-round runs at the σ′ the auto procedure settled
            # on.  sigma=auto rides the in-loop anneal schedule now
            # (--sigmaSchedule=anneal, the default): it starts at K·γ/2
            # and backs off in place only if the stall watch fires.  On
            # this config the aggressive start holds (the explicit K·γ/2
            # row above certifies — same seed, same config), so the
            # anneal run is bit-identical to fixed σ′=K/2 and that is
            # the right σ′ for the timing runs; were the K·γ/2 row
            # diverging, auto would have annealed toward safe K·γ.
            sig_used = None if traj_s.stopped == "diverged" else k / 2.0
            secs_pr, fixed_pr, q_pr = _timed(
                lambda nr: make_run(nr, "permuted", sigma=sig_used),
                rec_pr.round)
            results.append(dict(
                config=f"{rtag}-cocoa+(production: permuted+sigma=auto"
                       f"+evalDense)",
                n=n, d=d, k=k, h=h, lam=1e-4, gap_target=gap_target,
                rounds=rec_pr.round, gap=float(rec_pr.gap),
                wallclock_s=round(secs_pr, 3), fixed_s=round(fixed_pr, 3),
                **q_pr,
                vs_oracle_same_gap=round(
                    rec.round / rate_plus / secs_pr, 1),
                oracle_basis="same-gap: oracle at reference-mode rounds",
            ))
            perf_rows.append(_perf(
                f"{rtag}-cocoa+(production)", secs_pr, rec_pr.round,
                n=n, d=d, k=k, h=h, layout="sparse", nnz=nnz,
                path="pallas", debug_iter=25))

            # rounds-to-gap A/B for the accelerated outer loop (round
            # 12): --accel=on --theta=adaptive against the production
            # row above as the --accel=off control — identical data,
            # sampler, σ′ policy, gap target and the UNMODIFIED gap
            # evaluator; the only change is the momentum/Θ machinery.
            # `rounds` is the headline column (in the distributed regime
            # comm-rounds ARE the cost, so the ratio multiplies every
            # per-round win already recorded).  `accel_floor_rounds` is
            # the theoretical Nesterov floor from the control's own
            # contraction rate (perf.predict_accel_rounds) — measured
            # sits between the control and it.
            import perf

            def accel_floor(rounds_plain, traj_ctrl):
                # the control's first logged gap can be NaN (a transient
                # divergence at an aggressive σ′ start) or already past
                # the target — either would make predict_accel_rounds
                # raise and lose the sweep's accumulated rows, so the
                # floor cell degrades to None instead
                g0 = (float(traj_ctrl.records[0].gap)
                      if traj_ctrl.records and traj_ctrl.records[0].gap
                      else 1.0)
                if not (np.isfinite(g0) and g0 > gap_target):
                    return None
                return perf.predict_accel_rounds(rounds_plain, g0,
                                                 gap_target)

            _, _, traj_ac = gap_run("permuted", sigma="auto", accel="on",
                                    theta="adaptive")
            rec_ac = traj_ac.records[-1]
            results.append(dict(
                config=f"{rtag}-cocoa+(accel: on+theta=adaptive)",
                n=n, d=d, k=k, h=h, lam=1e-4, gap_target=gap_target,
                rounds=rec_ac.round, gap=float(rec_ac.gap),
                stopped=traj_ac.stopped,
                control_rounds=rec_pr.round,
                rounds_ratio=round(rec_pr.round / max(1, rec_ac.round), 2),
                accel_floor_rounds=accel_floor(rec_pr.round, traj_pr),
                oracle_basis="comm-rounds A/B vs the production row "
                             "(accel=off control, same gap target)",
            ))

            # the same A/B at the reference's safe σ′ = K·γ (the
            # `(0.0001, permuted)` row above as control) — the
            # worse-conditioned regime where acceleration pays the most
            # (measured 1.76× vs the production point's 1.38×; the
            # κ→√κ floor says the ratio must grow with control rounds)
            _, _, traj_as = gap_run("permuted", accel="on")
            rec_as = traj_as.records[-1]
            results.append(dict(
                config=f"{rtag}-cocoa+({gap_target:g}, permuted, "
                       f"accel=on)",
                n=n, d=d, k=k, h=h, lam=1e-4, gap_target=gap_target,
                rounds=rec_as.round, gap=float(rec_as.gap),
                stopped=traj_as.stopped,
                control_rounds=rec_p.round,
                rounds_ratio=round(rec_p.round / max(1, rec_as.round), 2),
                accel_floor_rounds=accel_floor(rec_p.round, traj_p),
                oracle_basis="comm-rounds A/B vs the permuted safe-σ′ "
                             "row (accel=off control, same gap target)",
            ))

        if gap_target == 1e-3:
            # the in-loop σ′ backoff demonstration (round 8): start the
            # anneal schedule at a deliberately divergence-prone σ′ =
            # K·γ/8 = 1 (anything below K/2 diverges on this data — the
            # sweep above) and let the device-resident controller back
            # off toward safe K·γ inside the while_loop.  The row's
            # `rounds` is the WHOLE story: detection window + in-place
            # recovery, zero restarts, versus the trial-style
            # window + full restart + rerun (benchmarks/SWEEPS.md
            # "anneal vs trial").  1e-3 target keeps the recovery tail
            # out of the λ=1e-4 conditioning regime.
            p_an = Params(n=n, num_rounds=1600, local_iters=h, lam=1e-4,
                          sigma=1.0)
            _, _, traj_an = run_cocoa(
                ds, p_an, debug, plus=True, quiet=True, math="fast",
                device_loop=True, gap_target=gap_target, rng="permuted",
                sigma_schedule="anneal")
            rec_an = traj_an.records[-1]
            sig_path = sorted({r.sigma for r in traj_an.records
                               if r.sigma is not None})
            results.append(dict(
                config=f"{rtag}-cocoa+({gap_target:g}, permuted, "
                       f"anneal from sigma'=1)",
                n=n, d=d, k=k, h=h, lam=1e-4, gap_target=gap_target,
                rounds=rec_an.round, gap=float(rec_an.gap),
                stopped=traj_an.stopped,
                sigma_ladder="->".join(f"{s:g}" for s in sig_path),
                oracle_basis="comm-rounds only (in-loop backoff demo; "
                             "wall-clock tracks the fixed-σ′ rows)",
            ))

    # Mini-batch CD on the same data (fixed 100 rounds; its β/(K·H)
    # scaling needs far more rounds per unit of gap progress — the CoCoA
    # papers' point)
    d2 = DebugParams(debug_iter=100, seed=0)

    def make_mbcd(nr):
        p = Params(n=n, num_rounds=nr, local_iters=h, lam=1e-4)
        return lambda: run_minibatch_cd(ds, p, d2, quiet=True, math="fast",
                                        device_loop=True)

    w2, a2, traj2 = make_mbcd(100)()
    rec2 = traj2.records[-1]
    secs2, fixed2, q2 = _timed(make_mbcd, 100)
    rate_f = _oracle_rounds_per_s_csr(data, 1e-4, h, k, n, mode="frozen")
    results.append(dict(
        config=f"{rtag}-mbcd", n=n, d=d, k=k, h=h, lam=1e-4,
        rounds=rec2.round, gap=float(rec2.gap), primal=float(rec2.primal),
        wallclock_s=round(secs2, 3), fixed_s=round(fixed2, 3), **q2,
        vs_oracle=round(rec2.round / rate_f / secs2, 1),
        oracle_basis="measured (2 rounds, CSR)",
    ))
    perf_rows.append(_perf(f"{rtag}-mbcd", secs2, rec2.round, n=n, d=d, k=k,
                           h=h, layout="sparse", nnz=nnz, path="pallas",
                           debug_iter=100))

def _np_alpha_step(loss, a, z, qii, lam_n, smoothing):
    """NumPy twin of ops/losses.alpha_step (scalar), for the loss-variant
    oracle rates."""
    if loss == "smooth_hinge":
        s = smoothing
        grad = (z - 1.0 + s * a) * lam_n
        return min(max(a - grad / (qii + s * lam_n), 0.0), 1.0)
    if loss == "logistic":
        ac = min(max(a, 1e-12), 1.0 - 1e-12)
        q = qii / lam_n
        u = min(max(np.log(ac / (1.0 - ac)), -35.0), 35.0)
        for _ in range(10):
            sig = 1.0 / (1.0 + np.exp(-u))
            g = u + z + q * (sig - ac)
            gp = 1.0 + q * sig * (1.0 - sig)
            u = min(max(u - g / gp, -35.0), 35.0)
        return 1.0 / (1.0 + np.exp(-u))
    raise ValueError(loss)


def _oracle_rounds_per_s_loss(ds_like, lam, h, k, n, loss, smoothing,
                              rounds=3):
    """Single-thread oracle round rate for the non-hinge dual-ascent
    losses (CoCoA+ additive): the same per-step structure as
    oracle.local_sdca with the loss's coordinate update."""
    from cocoa_tpu.utils.prng import sample_indices

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    sigma = float(k)
    lam_n = lam * n

    def step(t):
        nonlocal w
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), h, Xk.shape[0])[0]
            a = alphas[s]
            dw = np.zeros_like(w)
            for li in idxs:
                x = Xk[li]
                z = yk[li] * (x @ w + sigma * (x @ dw))
                qii = sigma * float(x @ x)
                new_a = _np_alpha_step(loss, a[li], z, qii, lam_n, smoothing)
                coef = yk[li] * (new_a - a[li]) / lam_n
                dw += coef * x
                a[li] = new_a
            dw_sum += dw
        w = w + dw_sum

    return _round_rate(step, rounds)


def bench_losses(results, perf_rows, quick):
    """The fifth BASELINE.json config (VERDICT r3 item 2): the
    smoothed-hinge and logistic local-solver variants — the reference's
    explicit extensibility promise (README.md:14, CoCoA.scala:13-14) —
    measured gap-targeted at epsilon scale through the fused block kernel,
    exercising the non-hinge chain (smooth-hinge's shifted clip, the
    10-iteration unrolled Newton for logistic) at scale."""
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.synth import synth_dense_sharded
    from cocoa_tpu.solvers import run_cocoa

    n, d, k = (40_000, 2000, 8) if quick else (400_000, 2000, 8)
    ds = synth_dense_sharded(n, d, k, seed=0)
    h = n // k // 10
    debug = DebugParams(debug_iter=10, seed=0)
    n_sub = min(n, 20_000)
    rng = np.random.default_rng(0)
    Xs = rng.standard_normal((n_sub, d))
    Xs /= np.linalg.norm(Xs, axis=1, keepdims=True)
    ys = np.where(Xs @ rng.standard_normal(d) >= 0, 1.0, -1.0)

    for loss, smoothing, gap_target in (
        ("smooth_hinge", 1.0, 1e-4),
        ("logistic", 1.0, 1e-4),
    ):
        def make_run(nr, loss=loss, smoothing=smoothing):
            p = Params(n=n, num_rounds=nr, local_iters=h, lam=1e-3,
                       loss=loss, smoothing=smoothing)
            return lambda: run_cocoa(ds, p, debug, plus=True, quiet=True,
                                     math="fast", device_loop=True,
                                     block_size=128)

        p = Params(n=n, num_rounds=600, local_iters=h, lam=1e-3,
                   loss=loss, smoothing=smoothing)
        w, a, traj = run_cocoa(ds, p, debug, plus=True, quiet=True,
                               math="fast", device_loop=True,
                               gap_target=gap_target, block_size=128)
        rec = traj.records[-1]
        if rec.gap is None or rec.gap > gap_target:
            # record honestly as a budget-capped row, never as a
            # gap-certified one
            q_miss = {"gap_miss": True}
        else:
            q_miss = {}
        secs, fixed, q = _timed(make_run, rec.round)
        q = {**q, **q_miss}
        rate = _oracle_rounds_per_s_loss(
            (Xs, ys), 1e-3, n_sub // k // 10, k, n_sub, loss, smoothing
        ) * n_sub / n
        results.append(dict(
            config=f"epsilon-{loss}(block128)", n=n, d=d, k=k, h=h,
            lam=1e-3, gap_target=gap_target, rounds=rec.round,
            gap=None if rec.gap is None else float(rec.gap),
            wallclock_s=round(secs, 3),
            fixed_s=round(fixed, 3), **q,
            vs_oracle=round(rec.round / rate / secs, 1),
            oracle_basis=f"extrapolated from n={n_sub} subsample",
        ))
        perf_rows.append(_perf(f"epsilon-{loss}(block128)", secs, rec.round,
                               n=n, d=d, k=k, h=h, path="block", block=128))


def _oracle_rounds_per_s_lasso(A, bvec, lam, h, k, rounds=2, l2=0.0):
    """Single-thread literal prox-CD oracle round rate (ProxCoCoA+ lasso /
    elastic net, gamma=1): per step one column dot against r, one against
    the local Δv, a soft-threshold, one column axpy."""
    from cocoa_tpu.data.sharding import split_sizes
    from cocoa_tpu.utils.prng import sample_indices

    n, d = A.shape
    A = np.asfortranarray(A)  # contiguous columns — the unit of access,
                              # as Breeze column vectors are materialized
    sizes = split_sizes(d, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    sigma = float(k)
    # jaxlint: allow=f64 -- the pinned CPU oracle is Breeze-faithful f64
    # by definition; it is what the f32 TPU runs are measured against
    r = -bvec.astype(np.float64)
    x = np.zeros(d)

    def step(t):
        nonlocal r
        dv_sum = np.zeros(n)
        for sh in range(k):
            idxs = sample_indices(0, range(t, t + 1), h, sizes[sh])[0]
            dv = np.zeros(n)
            for lj in idxs:
                gj = offs[sh] + lj
                aj = A[:, gj]
                a = x[gj]
                z = aj @ r + sigma * (aj @ dv)
                q = sigma * float(aj @ aj)
                if q <= 0.0:
                    continue
                u = (q * a - z) / (q + l2)
                tstar = np.sign(u) * max(abs(u) - lam / (q + l2), 0.0)
                dv += aj * (tstar - a)
                x[gj] = tstar
            dv_sum += dv
        r = r + dv_sum

    return _round_rate(step, rounds)


def bench_lasso(results, perf_rows, quick):
    """ProxCoCoA+ lasso + elastic net (the L1 extension, no reference
    analogue): dense Gaussian design with a planted 64-sparse x*,
    λ = 0.3·λ_max, to a RELATIVE duality gap of 1e-3 (gap ≤ 1e-3·½‖b‖² —
    these objectives are scale-dependent, so an absolute target would be
    meaningless).  The elastic-net row exercises the smoothed-conjugate
    certificate (VERDICT r2 item 4)."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.columns import shard_columns
    from cocoa_tpu.data.libsvm import LibsvmData
    from cocoa_tpu.solvers import run_prox_cocoa

    n, d, k = (2048, 8192, 8) if quick else (8192, 32768, 8)
    rng = np.random.default_rng(0)
    A = rng.standard_normal((n, d)).astype(np.float32) / np.sqrt(n)
    x_true = np.zeros(d, np.float32)
    x_true[rng.choice(d, 64, replace=False)] = \
        rng.standard_normal(64).astype(np.float32) * 3
    bvec = A @ x_true + 0.01 * rng.standard_normal(n).astype(np.float32)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    # values stay f32: shard_columns casts to the compute dtype anyway, and
    # an f64 copy of the dense design would be a ~2 GB host transient
    # jaxlint: allow=f64 -- LibsvmData labels ride the container's f64
    # host contract (cast at shard time)
    data = LibsvmData(labels=bvec.astype(np.float64), indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=A.reshape(-1), num_features=d)
    ds, b = shard_columns(data, k, dtype=jnp.float32)
    lam = 0.3 * float(np.max(np.abs(A.T @ bvec)))
    p0 = 0.5 * float(bvec @ bvec)
    h = d // k // 10
    debug = DebugParams(debug_iter=50, seed=0)

    for tag, l2 in (("lasso-proxcocoa+", 0.0), ("elastic-proxcocoa+", 0.1)):
        def make_run(nr, rng_mode="reference", l2=l2):
            p = Params(n=d, num_rounds=nr, local_iters=h, lam=lam,
                       loss="lasso", smoothing=l2)
            return lambda: run_prox_cocoa(ds, b, p, debug, quiet=True,
                                          math="fast", device_loop=True,
                                          rng=rng_mode)

        def gap_run(rng_mode="reference", l2=l2):
            p = Params(n=d, num_rounds=3000, local_iters=h, lam=lam,
                       loss="lasso", smoothing=l2)
            return run_prox_cocoa(ds, b, p, debug, quiet=True, math="fast",
                                  device_loop=True, gap_target=1e-3 * p0,
                                  rng=rng_mode)

        x, r, traj = gap_run()
        rec = traj.records[-1]
        secs, fixed, q = _timed(make_run, rec.round)
        rate = _oracle_rounds_per_s_lasso(A, bvec, lam, h, k, l2=l2)
        results.append(dict(
            config=tag, n=n, d=d, k=k, h=h,
            lam=round(lam, 5), l2=l2, gap_target="1e-3 relative",
            rounds=rec.round, gap=float(rec.gap),
            wallclock_s=round(secs, 3), fixed_s=round(fixed, 3), **q,
            vs_oracle=round(rec.round / rate / secs, 1),
            oracle_basis="measured (2 rounds)",
        ))
        # roles swapped: d coordinates play the example axis, dense columns
        # of length n play the rows (see solvers/prox_cocoa.py)
        perf_rows.append(_perf(tag, secs, rec.round, n=d, d=n,
                               k=k, h=h, path="pallas", debug_iter=50))

        if l2 == 0.0:
            x_p, r_p, traj_p = gap_run("permuted")
            rec_p = traj_p.records[-1]
            secs_p, fixed_p, q_p = _timed(
                lambda nr: make_run(nr, "permuted"), rec_p.round)
            results.append(dict(
                config="lasso-proxcocoa+(permuted)", n=n, d=d, k=k, h=h,
                lam=round(lam, 5), gap_target="1e-3 relative",
                rounds=rec_p.round, gap=float(rec_p.gap),
                wallclock_s=round(secs_p, 3), fixed_s=round(fixed_p, 3), **q_p,
                vs_oracle_same_gap=round(rec.round / rate / secs_p, 1),
                oracle_basis="same-gap: oracle at reference-mode rounds",
            ))


# Each ingest bench worker is a PLAIN subprocess (no jax import): its
# ru_maxrss then reflects the parse artifacts — the cost the A/B is
# about — not the ~350 MB backend baseline.  Device placement is
# identical in both modes (HBM on a real TPU, excluded here); the worker
# replays exactly the per-process parse work of the two ingest paths
# over ranges the parent derives from the real pass-1 index.
_INGEST_WORKER = r"""
import importlib.util, json, os, resource, sys, time, types
spec = json.load(open(sys.argv[1]))
import numpy as np

# load the parser modules by FILE PATH, not through the package: the
# cocoa_tpu package __init__ imports jax, whose ~350 MB import peak
# would swallow the parse-artifact RSS this worker exists to measure
def _load(name, relpath):
    s = importlib.util.spec_from_file_location(
        name, os.path.join(spec["root"], relpath))
    m = importlib.util.module_from_spec(s)
    sys.modules[name] = m
    s.loader.exec_module(m)
    return m

sys.modules["cocoa_tpu"] = types.ModuleType("cocoa_tpu")
sys.modules["cocoa_tpu.data"] = types.ModuleType("cocoa_tpu.data")
_libsvm = _load("cocoa_tpu.data.libsvm", "cocoa_tpu/data/libsvm.py")
sys.modules["cocoa_tpu.data"].native_loader = _load(
    "cocoa_tpu.data.native_loader", "cocoa_tpu/data/native_loader.py")
sys.modules["cocoa_tpu.data"].libsvm = _libsvm
# slab_cache is deliberately numpy-only (no jax), so the warm mode loads
# it the same file-path way — its mmap'd artifacts ARE this worker's RSS
_slab_cache = _load("cocoa_tpu.data.slab_cache",
                    "cocoa_tpu/data/slab_cache.py")
load_libsvm, load_libsvm_range = _libsvm.load_libsvm, _libsvm.load_libsvm_range

def rss_kb():
    # current resident set from statm — ru_maxrss is unusable here (this
    # kernel carries the PARENT's high-water mark across fork+exec).
    # Sampled while the parse artifacts are live, so it reads the
    # held-CSR peak the A/B is about.
    pages = int(open("/proc/self/statm").read().split()[1])
    return pages * os.sysconf("SC_PAGE_SIZE") // 1024

path, d, mode = spec["path"], spec["d"], spec["mode"]
rss0 = rss_kb()
rss_peak = 0
t0 = time.perf_counter()
bytes_read = rows = nnz = 0
bytes_mapped = 0
if mode == "warm":
    # --ingestCache warm ingest (data/slab_cache.py): map + validate
    # this process's shards' device-ready slab artifacts — zero parse.
    # Device placement is excluded exactly as in the other modes (the
    # device_put cost is identical cold or warm).  bytes_read stays the
    # TEXT bytes parsed (0 by contract — the regression gate fails a
    # warm row that ever reads text); the mapped artifact bytes report
    # separately as bytes_mapped.
    cache = _slab_cache.SlabCache(spec["cache_dir"])
    handle = cache.for_file(path, d)
    view = handle.view(layout="sparse", k=spec["k"],
                       n_shard=spec["n_shard"], width=spec["width"],
                       n_hot=0, d=d, dtype=np.float32, eval_dense=False)
    for s in spec["shards"]:
        slab = view.load(s)
        assert slab is not None, f"warm bench: shard {s} missed"
        rows += int((slab["mask"] > 0).sum())
        rss_peak = max(rss_peak, rss_kb())
    bytes_mapped = cache.bytes_mapped
elif mode == "whole":
    # whole-file ingest: every process parses the entire file and holds
    # the full CSR before slicing out its shards (load_libsvm ->
    # _shard_dataset_distributed)
    data = load_libsvm(path, d)
    rss_peak = rss_kb()
    rows, nnz = data.n, int(data.indptr[-1])
    bytes_read = os.path.getsize(path)
else:
    # pass 1 (data/ingest.build_index): windowed range scan of this
    # process's 1/P — stats kept, rows dropped
    lo, hi = spec["scan_range"]
    hist = np.zeros(d, np.int64)
    nnz_parts = []
    w = lo
    while w < hi:
        piece, off = load_libsvm_range(path, d, w, min(w + spec["window"], hi))
        hist += np.bincount(piece.indices, minlength=d)
        nnz_parts.append(np.diff(piece.indptr))
        rss_peak = max(rss_peak, rss_kb())
        w = min(w + spec["window"], hi)
    bytes_read += hi - lo
    # pass 2 (stream_shard_dataset): parse ONLY this process's local
    # devices' shard byte ranges, held one device-piece at a time
    for blo, bhi in spec["piece_ranges"]:
        piece, _ = load_libsvm_range(path, d, blo, bhi)
        rss_peak = max(rss_peak, rss_kb())
        rows += piece.n
        nnz += len(piece.values)
        bytes_read += bhi - blo
secs = time.perf_counter() - t0
json.dump(dict(secs=secs, bytes_read=bytes_read,
               bytes_mapped=bytes_mapped, rows=rows, nnz=nnz,
               rss0_kb=rss0, rss1_kb=rss_peak),
          open(spec["out"], "w"))
"""


def bench_ingest(results, quick, processes=(2, 8)):
    """Streaming vs whole-file ingest A/B at rcv1-synth scale (the ISSUE 8
    acceptance row): per-PROCESS parse wallclock, bytes read, and peak
    host RSS for a P-process run, measured by replaying each process's
    exact parse work in a clean subprocess.

    ``whole``: every process parses the entire file and holds the full
    CSR.  ``stream`` (data/ingest.py): pass-1 range scan of 1/P of the
    file + pass-2 parse of only its own shards' byte ranges.  The
    wallclock win scales as ~P/2 (at P=2 the streamed path parses the
    same total bytes, split across passes); the RSS win is the point at
    P=2 already — the held CSR drops to ~1/P of the dataset plus the
    index (the ``rss_vs_whole`` column, acceptance bar ≤ ~0.6 at P=2).
    Model predictions from perf.ingest_model ride each row.

    ``warm`` (--ingestCache, data/slab_cache.py, the ISSUE 15 row): the
    parent primes the cache with one cold streamed build, then each
    process maps + validates ONLY its own shards' slab artifacts — zero
    parse.  Acceptance bar: ≥10× faster than the streamed cold parse of
    the same geometry (the ``warm_speedup`` column,
    check_regression-gated).  Device placement is excluded in every
    mode — it is identical cold or warm.
    """
    import subprocess
    import sys as _sys
    import tempfile

    import jax.numpy as jnp

    import perf
    from cocoa_tpu.data import SlabCache, stream_shard_dataset
    from cocoa_tpu.data.ingest import PASS1_WINDOW, build_index
    from cocoa_tpu.data.sharding import pad_rows, split_sizes
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    n, d, nnz_mean, k = ((2024, 4724, 20, 8) if quick
                        else (20242, 47236, 75, 8))
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "rcv1_synth.svm")
        write_libsvm(synth_sparse(n, d, nnz_mean=nnz_mean, seed=0), path)
        fsize = os.path.getsize(path)
        index = build_index(path, d)
        sizes = split_sizes(index.n, k)
        offsets = np.concatenate([[0], np.cumsum(sizes)])
        # prime the slab cache once (a cold streamed build through the
        # real pipeline) so the warm rows measure EXACTLY what a second
        # process pays: map + validate, zero parse
        cache_dir = os.path.join(tmp, "icache")
        stream_shard_dataset(path, d, k, layout="sparse",
                             dtype=jnp.float32,
                             cache=SlabCache(cache_dir))
        n_shard = pad_rows(int(sizes.max()))
        width = int(max(1, index.row_nnz.max(initial=1)))

        def run_worker(spec):
            spec_path = spec["out"] + ".spec"
            json.dump(spec, open(spec_path, "w"))
            subprocess.run([_sys.executable, "-c", _INGEST_WORKER,
                            spec_path], check=True, cwd=tmp)
            return json.load(open(spec["out"]))

        for nproc in processes:
            if k % nproc:
                continue
            m = k // nproc  # shards multiplexed per process's device
            rows = {}
            for mode in ("whole", "stream", "warm"):
                reps = []
                for p in range(nproc):
                    r0, r1 = int(offsets[p * m]), int(offsets[(p + 1) * m])
                    reps.append(run_worker(dict(
                        root=ROOT, path=path, d=d, mode=mode,
                        window=PASS1_WINDOW,
                        scan_range=[p * fsize // nproc,
                                    (p + 1) * fsize // nproc],
                        piece_ranges=[[int(index.row_off[r0]),
                                       int(index.row_off[r1])]],
                        cache_dir=cache_dir, k=k, n_shard=n_shard,
                        width=width,
                        shards=list(range(p * m, (p + 1) * m)),
                        out=os.path.join(tmp, f"{mode}{nproc}_{p}.json"),
                    )))
                row = dict(
                    config=f"ingest/{mode}-p{nproc}"
                           + ("(quick)" if quick else ""),
                    n=index.n, d=d, k=k, mode=mode, processes=nproc,
                    file_mb=round(fsize / 2**20, 1),
                    parse_s=round(max(r["secs"] for r in reps), 4),
                    bytes_read_mb=round(
                        max(r["bytes_read"] for r in reps) / 2**20, 1),
                    peak_rss_mb=round(
                        max(r["rss1_kb"] for r in reps) / 1024, 1),
                    rss_delta_mb=round(
                        max(r["rss1_kb"] - r["rss0_kb"] for r in reps)
                        / 1024, 1),
                )
                if mode == "warm":
                    # bytes_read_mb is TEXT parsed on the warm path —
                    # 0.0 by contract, kept in the row so the
                    # check_regression gate can fail a warm mode that
                    # ever starts reading text; the mapped artifact
                    # bytes report separately
                    row["bytes_mapped_mb"] = round(
                        max(r["bytes_mapped"] for r in reps) / 2**20, 1)
                else:
                    pred = perf.ingest_model(fsize, index.n,
                                             index.total_nnz,
                                             nproc, mode=mode, d=d)
                    row["predicted_parse_s"] = round(
                        pred["parse_seconds"], 3)
                    row["predicted_csr_mb"] = round(
                        pred["csr_peak_bytes"] / 2**20, 1)
                rows[mode] = row
                results.append(row)
            ratio = (rows["stream"]["rss_delta_mb"]
                     / max(rows["whole"]["rss_delta_mb"], 1e-9))
            rows["stream"]["rss_vs_whole"] = round(ratio, 2)
            speedup = (rows["stream"]["parse_s"]
                       / max(rows["warm"]["parse_s"], 1e-9))
            rows["warm"]["warm_speedup"] = round(speedup, 1)
            print(f"bench: ingest p={nproc} — whole "
                  f"{rows['whole']['parse_s']}s/"
                  f"{rows['whole']['rss_delta_mb']}MB vs stream "
                  f"{rows['stream']['parse_s']}s/"
                  f"{rows['stream']['rss_delta_mb']}MB "
                  f"(rss ratio {ratio:.2f}, bar ≤0.6 at p=2) vs warm "
                  f"{rows['warm']['parse_s']}s "
                  f"({speedup:.0f}× stream, bar ≥10×)")


def write_results(results, perf_rows, out_dir, partial=False, final=False):
    """Full runs own results.jsonl / RESULTS.md (the artifacts BASELINE.md
    cites); --quick / --only runs write to *.partial.* so they can never
    clobber the recorded numbers.  Mid-suite flushes of a FULL run write
    to *.inprogress.* and only the ``final`` write owns the canonical
    files: a tunnel death mid-suite (the round-4 failure mode) then leaves
    the recorded artifacts untouched while the sections already measured
    survive in the inprogress files.  The BASELINE.md/PARITY.md/README.md
    doc blocks likewise sync only on ``final``."""
    suffix = ".partial" if partial else ("" if final else ".inprogress")
    for r in results:
        # ideal-parallel-oracle columns (VERDICT r5 next #2): the
        # single-thread oracle ratio divided by the row's K — the speedup
        # against an IDEAL K-way-parallel CPU run of the reference math
        # (zero scheduling cost; real Spark sits below it, so the truth
        # lies between the two columns).  This is the honest denominator
        # for the ≥10x north star (BASELINE.json argues against an
        # 8-executor cluster, which can use at most K-way parallelism).
        kk = r.get("k")
        if kk:
            if (r.get("vs_oracle") is not None
                    and r.get("vs_oracle_parallel") is None):
                r["vs_oracle_parallel"] = round(r["vs_oracle"] / kk, 2)
            if (r.get("vs_oracle_same_gap") is not None
                    and r.get("vs_oracle_parallel_same_gap") is None):
                r["vs_oracle_parallel_same_gap"] = round(
                    r["vs_oracle_same_gap"] / kk, 2)
    jl = os.path.join(out_dir, f"results{suffix}.jsonl")
    with open(jl, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
        for r in perf_rows:
            f.write(json.dumps({"type": "perf", **r}) + "\n")
    md = os.path.join(out_dir, f"RESULTS{suffix}.md")
    cols = ["config", "n", "d", "k", "h", "lam", "l2", "gap_target",
            "rounds", "gap", "primal", "wallclock_s", "fixed_s",
            "vs_oracle", "vs_oracle_parallel", "vs_oracle_same_gap",
            "vs_oracle_parallel_same_gap"]
    with open(md, "w") as f:
        f.write("# Benchmark results\n\n")
        f.write("Produced by `python benchmarks/run.py` on the attached "
                "TPU device (single chip, K logical shards).  "
                "`wallclock_s` is the SLOPE-MEASURED steady-state time "
                "for the row's rounds (fixed dispatch/fetch costs cancel "
                "between an R-round and an mR-round run); `fixed_s` is "
                "the cancelled per-run overhead — a raw stopwatch on one "
                "run reads ≈ wallclock_s + fixed_s ± the tunnel's "
                "run-to-run jitter.  `vs_oracle` compares equal rounds "
                "against the single-thread NumPy oracle of the reference "
                "math; permuted-sampling rows instead report "
                "`vs_oracle_same_gap` (oracle at reference-mode rounds vs "
                "this row's wall-clock — a cross-mode comparison).  "
                "`vs_oracle_parallel` (and its same-gap twin) divides by "
                "the row's K: the speedup against an IDEAL K-way-parallel "
                "CPU run of the reference math — the honest denominator "
                "for the ≥10x north star (real Spark adds scheduling "
                "overhead on top, so the truth sits between the two "
                "columns).  Where that column reads < 1 the row is "
                "SUB-PARITY against an ideal parallel CPU baseline — "
                "true today of the reference-faithful rcv1 rows (~0.7x: "
                "single-thread CPUs are genuinely good at ~75-nnz "
                "sequential CSR steps); the headlined rcv1 config is the "
                "production row (permuted + σ′=auto + evalDense), which "
                "clears the bar on the comm-round levers.  See "
                "the module docstring for config definitions.\n\n"
                "Rows whose config lacks a `(real)` tag use the "
                "distribution-faithful **synthetic stand-in** from "
                "`data/synth.py` (matched n, d, nnz/row, row norms): "
                "`benchmarks/fetch_data.sh` is re-attempted every round "
                "and the build machine has no network route to the LIBSVM "
                "mirror, so the real rcv1/epsilon files cannot be "
                "fetched.  Real files dropped into benchmarks/data/ are "
                "picked up automatically and validated against the "
                "published (n, d, nnz/row) pins.  The fp "
                "(feature-parallel) capacity axis has no row here — it "
                "needs a multi-device mesh, and the attached TPU is one "
                "chip; its measured CPU-mesh per-round overhead ratio "
                "(one collective per coordinate step vs the dp path's "
                "one per round) is recorded in benchmarks/SWEEPS.md "
                "(benchmarks/fp_bench.py regenerates it).\n\n")
        f.write("| " + " | ".join(cols) + " |\n")
        f.write("|" + "---|" * len(cols) + "\n")
        for r in results:
            f.write("| " + " | ".join(
                "" if r.get(c) is None            # absent OR present-as-None
                else f"{r[c]:.4g}" if isinstance(r[c], float)
                else str(r[c]) for c in cols
            ) + " |\n")
        if perf_rows:
            f.write(
                "\n## Perf accounting (VERDICT r1 item 1)\n\n"
                "FLOP/byte models in `benchmarks/perf.py`; the accounting "
                "contract is the reference hot loop CoCoA.scala:148-188 "
                "(4·nnz useful FLOPs per coordinate step) plus the margins "
                "and eval passes of the measured path.  `useful` counts the "
                "reference's math; `physical` adds the FLOPs the TPU "
                "formulation spends to buy parallelism (block Gram work, "
                "lane padding).  MFU is against the chip's public dense "
                "bf16 peak — a conservative lower bound for f32 work.  "
                "Times include the per-`debugIter` eval amortized in; "
                "ms_per_round derives from the slope-measured steady "
                "state, so the tunnel's dispatch+fetch overhead is "
                "already cancelled (it is reported separately as the "
                "result table's fixed_s).\n\n"
            )
            pcols = ["config", "device", "ms_per_round", "us_per_step",
                     "useful_gflops", "physical_gflops", "mfu_pct",
                     "physical_mfu_pct", "hbm_floor_ms", "hbm_bound_pct",
                     "bound"]
            f.write("| " + " | ".join(pcols) + " |\n")
            f.write("|" + "---|" * len(pcols) + "\n")
            for r in perf_rows:
                f.write("| " + " | ".join(str(r.get(c, "")) for c in pcols)
                        + " |\n")
            bounds = [r.get("bound", "?") for r in perf_rows]
            n_lat = sum(1 for b in bounds if b == "latency")
            n_hbm = sum(1 for b in bounds if b == "HBM")
            n_mxu = sum(1 for b in bounds if b == "MXU")
            if n_lat == len(bounds):
                verdict = ("Every config is latency-bound: the measured "
                           "round time sits far above both the HBM-traffic "
                           "floor and the FLOP floor")
            else:
                # enumerate the actual mix — a fixed two-way phrasing
                # mislabeled MXU-bound rows as latency-bound (round-5
                # review finding)
                parts = []
                if n_hbm:
                    parts.append(f"{n_hbm} at the HBM-traffic floor")
                if n_mxu:
                    parts.append(f"{n_mxu} MXU-bound")
                if n_lat:
                    parts.append(f"{n_lat} latency-bound")
                other = len(bounds) - n_hbm - n_mxu - n_lat
                if other:
                    parts.append(f"{other} unclassified")
                verdict = (f"Of {len(bounds)} configs: "
                           + ", ".join(parts))
            f.write(
                f"\n{verdict}.  Where latency binds, the cause is the "
                "algorithm's hot loop — a sequential chain of O(nnz) "
                "coordinate steps (CoCoA.scala:148-188): per-step chain "
                "latency (see the us_per_step column and "
                "benchmarks/KERNELS.md), not bandwidth or MXU throughput, "
                "sets the ceiling.  Corollary: rcv1's round count to the "
                "1e-4 gap is λ=1e-4 *conditioning*, not sparse-kernel "
                "inefficiency — the same kernel reaches the 1e-3 gap in "
                "a fraction of the rounds.  Honest footnote on the rcv1 "
                "vs_oracle column: single-thread CPUs are genuinely good "
                "at ~75-nnz sequential CSR steps (sub-µs per step, all "
                "cache-resident), so the TPU's margin there is modest — "
                "the TPU case for sparse problems rests on the "
                "comm-round levers (σ′, reshuffling) and on scaling, "
                "not on beating a CPU at tiny sequential gathers; the "
                "dense configs are where the hardware's 100-1000× shows.\n"
                "\nRoofline reading, per config:\n\n"
            )
            for r in perf_rows:
                hbm = r.get("hbm_bound_pct")
                f.write(
                    f"- **{r['config']}** — {r['ms_per_round']} ms/round, "
                    f"{r['us_per_step']} µs per coordinate step "
                    f"(amortized over the K parallel shards); useful "
                    f"{r['useful_gflops']} GFLOP/s ≈ "
                    f"{r.get('mfu_pct', '?')}% MFU "
                    f"(physical {r.get('physical_mfu_pct', '?')}%).  The "
                    f"HBM-traffic model floor is {r.get('hbm_floor_ms', '?')} "
                    f"ms ({hbm}% of measured) → **{r.get('bound', '?')}-"
                    f"bound**.\n"
                )
    print(f"wrote {jl} and {md}")
    if not partial and final:
        for stale in ("results.inprogress.jsonl", "RESULTS.inprogress.md"):
            p = os.path.join(out_dir, stale)
            if os.path.exists(p):
                os.remove(p)
        _sync_docs(results)


def _sync_doc_block(path, text):
    """Replace the GENERATED:bench block in ``path`` (between the marker
    comments) with ``text``; no-op with a warning if markers are absent."""
    start = "<!-- GENERATED:bench -->"
    end = "<!-- /GENERATED:bench -->"
    with open(path) as f:
        s = f.read()
    if start not in s or end not in s:
        print(f"warning: {path} has no GENERATED:bench markers; skipped")
        return
    head, rest = s.split(start, 1)
    _, tail = rest.split(end, 1)
    with open(path, "w") as f:
        f.write(head + start + "\n" + text + end + tail)
    print(f"synced {path}")


def _sync_docs(results):
    """Regenerate the perf claims BASELINE.md and PARITY.md carry from the
    measured rows — one source of truth (VERDICT r2 item 2: three documents
    had three generations of numbers)."""
    by = {r["config"]: r for r in results}

    def lookup(cfg):
        # real-dataset runs label their configs e.g. rcv1(real)-... — the
        # claims should follow whichever variant actually ran
        return by.get(cfg.replace("epsilon", "epsilon(real)")
                      .replace("rcv1", "rcv1(real)")) or by.get(cfg)

    def row(cfg, label, extra=""):
        r = lookup(cfg)
        if r is None:
            return ""
        vs = r.get("vs_oracle")
        vs_s = f"≈{vs}× single-thread oracle" if vs is not None else \
            f"≈{r.get('vs_oracle_same_gap')}× same-gap vs oracle"
        par = (r.get("vs_oracle_parallel")
               if r.get("vs_oracle_parallel") is not None
               else r.get("vs_oracle_parallel_same_gap"))
        if par is not None:
            vs_s += f", ≈{par}× ideal-{r['k']}-way-parallel"
        fixed = r.get("fixed_s")
        return (f"| TPU rebuild: {label} | **{r['wallclock_s']} s steady "
                f"(+{fixed} s dispatch), {r['rounds']} comm-rounds** "
                f"({vs_s}{extra}) | 1 TPU chip, K={r['k']} | "
                f"benchmarks/RESULTS.md |\n")

    base_rows = [
        row("demo-cocoa+", "demo config to 1e-4 gap"),
        row("epsilon-cocoa+(block128)",
            "epsilon-like 400K×2000 to 1e-4 gap (block kernel)",
            extra="; λ=1e-3, H=0.1·n/K"),
        row("epsilon-cocoa+(permuted+block128)",
            "epsilon, reshuffled sampling + block kernel"),
        row("rcv1-cocoa+(0.001)", "rcv1-like 20242×47236 sparse to 1e-3 gap"),
        row("rcv1-cocoa+(0.0001)", "rcv1-like sparse to 1e-4 gap"),
        row("rcv1-cocoa+(production: permuted+sigma=auto+evalDense)",
            "rcv1 production config (permuted + σ′=auto + evalDense) "
            "to 1e-4 gap"),
        row("lasso-proxcocoa+",
            "lasso 8192×32768 (ProxCoCoA+, λ=0.3λmax) to 1e-3 rel. gap"),
        row("elastic-proxcocoa+", "elastic net (l2=0.1), same design"),
    ]
    if all(base_rows):
        _sync_doc_block(os.path.join(ROOT, "BASELINE.md"),
                        "".join(base_rows))
    else:
        # a subset regen must never erase recorded rows (the other doc
        # blocks already guard this way)
        print("warning: BASELINE.md sync skipped — result set is missing "
              f"{sum(1 for r in base_rows if not r)} of the recorded "
              "configs")

    d = lookup("demo-cocoa+")
    e = lookup("epsilon-cocoa+(block128)")
    rc = lookup("rcv1-cocoa+(0.001)")
    if d and e and rc:
        par = (
            f"See BASELINE.md / benchmarks/RESULTS.md (all numbers are the "
            f"slope-measured steady state; the tunneled device's "
            f"dispatch+fetch overhead is reported separately as fixed_s):\n"
            f"demo config to the 1e-4 duality gap in {d['wallclock_s']} s "
            f"({d['rounds']} comm-rounds) on one TPU chip — "
            f"≈{d['vs_oracle']}× the single-threaded NumPy oracle of the "
            f"reference math (the Spark stack itself cannot run here; the "
            f"oracle has zero scheduler overhead, so the true Spark-vs-TPU "
            f"gap is larger); epsilon-scale (400K×2000) in "
            f"{e['wallclock_s']} s; rcv1-scale sparse (20242×47236) to "
            f"1e-3 in {rc['wallclock_s']} s.\n"
        )
        _sync_doc_block(os.path.join(ROOT, "PARITY.md"), par)

    eb = lookup("epsilon-cocoa+(block128)")
    ep = lookup("epsilon-cocoa+(permuted+block128)")
    r3 = lookup("rcv1-cocoa+(0.001)")
    r4 = lookup("rcv1-cocoa+(0.0001)")
    la = lookup("lasso-proxcocoa+")
    el = lookup("elastic-proxcocoa+")
    d0 = lookup("demo-cocoa+")
    dp = lookup("demo-cocoa+(permuted)")
    if all(x for x in (eb, ep, r3, r4, la, el, d0, dp)):
        readme = (
            f"Recorded single-chip results (benchmarks/RESULTS.md; "
            f"wall-clocks are the slope-measured steady state — the "
            f"tunneled device's per-run dispatch overhead, reported "
            f"separately as fixed_s, would otherwise swamp the small "
            f"configs): the reference demo config in "
            f"**{d0['wallclock_s']} s** ({d0['rounds']} comm-rounds "
            f"reference-faithful, {dp['rounds']} with `--rng=permuted`); "
            f"epsilon-like dense 400K×2000 in **{eb['wallclock_s']} s** "
            f"({eb['rounds']} rounds with the fused block kernel; "
            f"**{ep['rounds']} rounds** with `--rng=permuted`, same "
            f"certified 1e-4 gap — comm-rounds are the baseline metric); "
            f"rcv1-like sparse 20242×47236 in **{r3['wallclock_s']} s** "
            f"to 1e-3 / **{r4['wallclock_s']} s** to 1e-4 "
            f"({r3['rounds']} / {r4['rounds']} rounds — the 1e-4 count "
            f"is λ=1e-4 conditioning, not kernel speed); lasso "
            f"8192×32768 via ProxCoCoA+ in **{la['wallclock_s']} s** to "
            f"a 1e-3 relative gap ({la['rounds']} rounds), elastic net "
            f"(l2={el.get('l2')}) in **{el['wallclock_s']} s** "
            f"({el['rounds']} rounds) with its smoothed-conjugate gap "
            f"certificate.  RESULTS.md also carries the perf-accounting "
            f"table (FLOPs, MFU, µs/coordinate-step, HBM floor, roofline "
            f"bound per config — the sequential coordinate chain is the "
            f"latency ceiling the `--blockSize` kernel attacks, and the "
            f"per-config roofline bullets record which configs have "
            f"reached their HBM floor); benchmarks/KERNELS.md "
            f"records the controlled per-round kernel comparison.\n"
        )
        _sync_doc_block(os.path.join(ROOT, "README.md"), readme)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="~10x smaller synthetic sizes (smoke test)")
    ap.add_argument("--only", default="",
                    help="comma-separated subset: "
                         "demo,epsilon,rcv1,losses,lasso,ingest")
    ap.add_argument("--data-dir",
                    default=os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), "data"),
                    help="directory holding real datasets (fetch_data.sh); "
                         "real files are preferred over synthetic stand-ins "
                         "and rows are labeled e.g. rcv1(real)")
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None

    results = []
    perf_rows = []
    out_dir = os.path.dirname(os.path.abspath(__file__))
    partial = args.quick or only is not None

    printed = [0]

    def flush():
        # write after EVERY section: a tunnel hang mid-suite (it happens —
        # round 4 lost a 47-minute run to one) must not lose the sections
        # already measured.  Print every not-yet-printed row (sections
        # append variable row counts; a fixed tail length dropped rows —
        # ADVICE r4).
        for r in results[printed[0]:]:
            print(json.dumps(r))
        printed[0] = len(results)
        write_results(results, perf_rows, out_dir, partial=partial)

    if only is None or "demo" in only:
        bench_demo(results, perf_rows)
        flush()
    if only is None or "epsilon" in only:
        bench_epsilon(results, perf_rows, args.quick, args.data_dir)
        flush()
    if only is None or "rcv1" in only:
        bench_rcv1(results, perf_rows, args.quick, args.data_dir)
        flush()
    if only is None or "losses" in only:
        bench_losses(results, perf_rows, args.quick)
        flush()
    if only is None or "lasso" in only:
        bench_lasso(results, perf_rows, args.quick)
        flush()
    if only is None or "ingest" in only:
        bench_ingest(results, args.quick)
        flush()
    write_results(results, perf_rows, out_dir, partial=partial, final=True)
    for r in perf_rows:
        print(json.dumps({"type": "perf", **r}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
