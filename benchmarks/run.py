"""Benchmark suite — generates the numbers BASELINE.md says this rebuild
must produce (the reference publishes none; see BASELINE.md).

Configs (BASELINE.json "eval" list):

- ``demo``     — the reference's only in-repo baseline: CoCoA+ on
  data/small_train.dat (n=2000, d=9947, K=4, H=50, λ=1e-3,
  run-demo-local.sh:2-9), wall-clock + comm-rounds to a 1e-4 duality gap.
- ``epsilon``  — epsilon-like dense synthetic (400K×2000, unit rows,
  data/synth.py), K=8, H=0.1·n/K, λ=1e-3, to 1e-4 gap.
- ``rcv1``     — rcv1.binary-like sparse synthetic (20242×47236, ~75
  nnz/row), K=8, H=0.1·n/K, λ=1e-4, to 1e-3 and 1e-4 gaps.
- ``mbcd-rcv1`` / ``sgd-epsilon`` — the baseline algorithms on the same
  data (fixed round budgets; they have no duality-gap certificate to
  target — SGD is primal-only, and mini-batch CD's β/(K·H) scaling makes
  gap progress per round much slower than CoCoA's, exactly the point the
  CoCoA papers make).

Each timed run is warm (the first run compiles, the second is measured).
``--quick`` shrinks the synthetic sizes ~10x for smoke-testing the suite.

The ``vs_oracle`` column is the speedup over the literal NumPy oracle of
the Scala update rules (tests/oracle.py) executing the same number of
rounds single-threaded — measured directly for the demo config and
extrapolated from 3 oracle rounds at the big scales (the oracle is the
reference's *math* without Spark overhead, so this flatters the
reference).

Writes one JSON line per config to benchmarks/results.jsonl and a
markdown table to benchmarks/RESULTS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

DEMO_TRAIN = "/root/reference/data/small_train.dat"
DEMO_TEST = "/root/reference/data/small_test.dat"
DEMO_D = 9947


def _time_warm(fn):
    fn()  # compile
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def _oracle_rounds_per_s(ds_like, lam, h, k, n, rounds=3):
    """Single-thread NumPy oracle round rate on this problem (CoCoA+,
    additive), measured over a few rounds."""
    import oracle

    from cocoa_tpu.utils.prng import sample_indices

    X, y = ds_like
    sizes = np.full(k, X.shape[0] // k)
    sizes[: X.shape[0] % k] += 1
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [
        (X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)
    ]
    w = np.zeros(X.shape[1])
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    t0 = time.perf_counter()
    for t in range(1, rounds + 1):
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), h, Xk.shape[0])[0]
            da, dw = oracle.local_sdca(
                Xk, yk, w, alphas[s], idxs, lam, n, True, float(k)
            )
            alphas[s] += da
            dw_sum += dw
        w += dw_sum
    return rounds / (time.perf_counter() - t0)


def bench_demo(results):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import load_libsvm, shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    data = load_libsvm(DEMO_TRAIN, DEMO_D)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32)
    params = Params(n=data.n, num_rounds=600, local_iters=50, lam=1e-3)
    debug = DebugParams(debug_iter=10, seed=0)

    def go():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", device_loop=True, gap_target=1e-4)

    secs, (w, a, traj) = _time_warm(go)
    rec = traj.records[-1]
    rate = _oracle_rounds_per_s(
        (data.to_dense(), data.labels), 1e-3, 50, 4, data.n
    )
    results.append(dict(
        config="demo-cocoa+", n=data.n, d=DEMO_D, k=4, h=50,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3),
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis="measured (3 rounds)",
    ))


def bench_epsilon(results, quick):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.synth import synth_dense_sharded
    from cocoa_tpu.solvers import run_cocoa

    n, d, k = (40_000, 2000, 8) if quick else (400_000, 2000, 8)
    h = n // k // 10
    ds = synth_dense_sharded(n, d, k, seed=0)
    params = Params(n=n, num_rounds=400, local_iters=h, lam=1e-3)
    debug = DebugParams(debug_iter=10, seed=0)

    def go():
        return run_cocoa(ds, params, debug, plus=True, quiet=True,
                         math="fast", device_loop=True, gap_target=1e-4)

    secs, (w, a, traj) = _time_warm(go)
    rec = traj.records[-1]
    # oracle rate on a small same-d subsample, scaled by n (per-round work
    # is O(H·d) per shard with H ∝ n — linear in n at fixed d, k)
    n_sub = min(n, 20_000)
    rng = np.random.default_rng(0)
    Xs = rng.standard_normal((n_sub, d))
    Xs /= np.linalg.norm(Xs, axis=1, keepdims=True)
    ys = np.where(Xs @ rng.standard_normal(d) >= 0, 1.0, -1.0)
    rate_sub = _oracle_rounds_per_s((Xs, ys), 1e-3, n_sub // k // 10, k, n_sub)
    rate = rate_sub * n_sub / n
    results.append(dict(
        config="epsilon-cocoa+", n=n, d=d, k=k, h=h,
        lam=1e-3, gap_target=1e-4, rounds=rec.round, gap=float(rec.gap),
        wallclock_s=round(secs, 3),
        vs_oracle=round(rec.round / rate / secs, 1),
        oracle_basis=f"extrapolated from n={n_sub} subsample",
    ))

    # Local SGD on the same data (primal-only baseline; fixed 100 rounds)
    from cocoa_tpu.solvers import run_sgd

    p2 = Params(n=n, num_rounds=100, local_iters=h, lam=1e-3)
    d2 = DebugParams(debug_iter=100, seed=0)

    def go_sgd():
        return run_sgd(ds, p2, d2, local=True, quiet=True)

    secs2, (w2, traj2) = _time_warm(go_sgd)
    rec2 = traj2.records[-1]
    results.append(dict(
        config="epsilon-localsgd", n=n, d=d, k=k, h=h, lam=1e-3,
        rounds=rec2.round, primal=float(rec2.primal),
        wallclock_s=round(secs2, 3),
    ))


def bench_rcv1(results, quick):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_sparse
    from cocoa_tpu.solvers import run_cocoa, run_minibatch_cd

    n, d, k = (4000, 47236, 8) if quick else (20242, 47236, 8)
    data = synth_sparse(n, d, nnz_mean=75, seed=0)
    ds = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32)
    h = n // k // 10
    debug = DebugParams(debug_iter=25, seed=0)

    for gap_target in (1e-3, 1e-4):
        params = Params(n=n, num_rounds=1500, local_iters=h, lam=1e-4)

        def go():
            return run_cocoa(ds, params, debug, plus=True, quiet=True,
                             math="fast", device_loop=True,
                             gap_target=gap_target)

        secs, (w, a, traj) = _time_warm(go)
        rec = traj.records[-1]
        results.append(dict(
            config=f"rcv1-cocoa+({gap_target:g})", n=n, d=d, k=k, h=h,
            lam=1e-4, gap_target=gap_target, rounds=rec.round,
            gap=float(rec.gap), wallclock_s=round(secs, 3),
        ))

    # Mini-batch CD on the same data (fixed 100 rounds; its β/(K·H)
    # scaling needs far more rounds per unit of gap progress — the CoCoA
    # papers' point)
    p2 = Params(n=n, num_rounds=100, local_iters=h, lam=1e-4)
    d2 = DebugParams(debug_iter=100, seed=0)

    def go_mbcd():
        return run_minibatch_cd(ds, p2, d2, quiet=True)

    secs2, (w2, a2, traj2) = _time_warm(go_mbcd)
    rec2 = traj2.records[-1]
    results.append(dict(
        config="rcv1-mbcd", n=n, d=d, k=k, h=h, lam=1e-4,
        rounds=rec2.round, gap=float(rec2.gap), primal=float(rec2.primal),
        wallclock_s=round(secs2, 3),
    ))


def bench_lasso(results, quick):
    """ProxCoCoA+ lasso (the L1 extension, no reference analogue): dense
    Gaussian design with a planted 64-sparse x*, λ = 0.3·λ_max, to a
    RELATIVE duality gap of 1e-3 (gap ≤ 1e-3 · ½‖b‖² — lasso objectives
    are scale-dependent, so an absolute target would be meaningless)."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.columns import shard_columns
    from cocoa_tpu.data.libsvm import LibsvmData
    from cocoa_tpu.solvers import run_prox_cocoa

    n, d, k = (2048, 8192, 8) if quick else (8192, 32768, 8)
    rng = np.random.default_rng(0)
    A = rng.standard_normal((n, d)).astype(np.float32) / np.sqrt(n)
    x_true = np.zeros(d, np.float32)
    x_true[rng.choice(d, 64, replace=False)] = \
        rng.standard_normal(64).astype(np.float32) * 3
    bvec = A @ x_true + 0.01 * rng.standard_normal(n).astype(np.float32)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    # values stay f32: shard_columns casts to the compute dtype anyway, and
    # an f64 copy of the dense design would be a ~2 GB host transient
    data = LibsvmData(labels=bvec.astype(np.float64), indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=A.reshape(-1), num_features=d)
    ds, b = shard_columns(data, k, dtype=jnp.float32)
    lam = 0.3 * float(np.max(np.abs(A.T @ bvec)))
    p0 = 0.5 * float(bvec @ bvec)
    h = d // k // 10
    params = Params(n=d, num_rounds=3000, local_iters=h, lam=lam,
                    loss="lasso", smoothing=0.0)
    debug = DebugParams(debug_iter=50, seed=0)

    def go():
        return run_prox_cocoa(ds, b, params, debug, quiet=True, math="fast",
                              device_loop=True, gap_target=1e-3 * p0)

    secs, (x, r, traj) = _time_warm(go)
    rec = traj.records[-1]
    results.append(dict(
        config="lasso-proxcocoa+", n=n, d=d, k=k, h=h,
        lam=round(lam, 5), gap_target=f"1e-3 relative", rounds=rec.round,
        gap=float(rec.gap), wallclock_s=round(secs, 3),
    ))


def write_results(results, out_dir, partial=False):
    """Full runs own results.jsonl / RESULTS.md (the artifacts BASELINE.md
    cites); --quick / --only runs write to *.partial.* so they can never
    clobber the recorded numbers."""
    suffix = ".partial" if partial else ""
    jl = os.path.join(out_dir, f"results{suffix}.jsonl")
    with open(jl, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    md = os.path.join(out_dir, f"RESULTS{suffix}.md")
    cols = ["config", "n", "d", "k", "h", "lam", "gap_target", "rounds",
            "gap", "primal", "wallclock_s", "vs_oracle"]
    with open(md, "w") as f:
        f.write("# Benchmark results\n\n")
        f.write("Produced by `python benchmarks/run.py` on the attached "
                "TPU device (single chip, K logical shards).  See the "
                "module docstring for config definitions and the "
                "`vs_oracle` methodology.\n\n")
        f.write("| " + " | ".join(cols) + " |\n")
        f.write("|" + "---|" * len(cols) + "\n")
        for r in results:
            f.write("| " + " | ".join(
                str(r.get(c, "")) if not isinstance(r.get(c), float)
                else f"{r[c]:.4g}" for c in cols
            ) + " |\n")
    print(f"wrote {jl} and {md}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="~10x smaller synthetic sizes (smoke test)")
    ap.add_argument("--only", default="",
                    help="comma-separated subset: demo,epsilon,rcv1,lasso")
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None

    results = []
    if only is None or "demo" in only:
        bench_demo(results)
        print(json.dumps(results[-1]))
    if only is None or "epsilon" in only:
        bench_epsilon(results, args.quick)
        for r in results[-2:]:
            print(json.dumps(r))
    if only is None or "rcv1" in only:
        bench_rcv1(results, args.quick)
        for r in results[-3:]:
            print(json.dumps(r))
    if only is None or "lasso" in only:
        bench_lasso(results, args.quick)
        print(json.dumps(results[-1]))
    write_results(results, os.path.dirname(os.path.abspath(__file__)),
                  partial=args.quick or only is not None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
