"""Re-run exactly the ``needs-TPU-regen`` benchmark rows in one command.

Several sessions landed kernel-level changes (the sparse block-chain
kernel, the pipelined block round) with no TPU attached, so
KERNELS.md/RESULTS.md still carry rows measured on the PRE-change kernels,
marked with a ``needs-TPU-regen`` banner and per-row ``⚠`` flags.  This
script is the one-command refresh for the next session that has hardware:

    python benchmarks/regen.py            # refuses off-TPU, lists stale rows
    python benchmarks/regen.py --list     # just list the stale rows

What it runs (exactly the marked surface, nothing else):

- ``benchmarks/kernels.py`` — regenerates KERNELS.md including the
  pipelined-vs-serial A/B rows (``block-128`` vs ``block-128-serial``,
  distinct twins), the B ∈ {128, 256, 512} sweep behind
  ``--blockSize=auto``'s measured ranking, and the round-10 hot/cold
  split A/B rows (``rcv1/hybrid-seq`` vs ``rcv1/pallas-seq``,
  ``rcv1/hybrid-block`` vs ``rcv1/sparse-block`` — currently model
  predictions, never measured);
- ``benchmarks/run.py --only epsilon,losses`` — the ⚠ block rows
  (epsilon-cocoa+(block128), permuted+block128, smooth_hinge/logistic
  block rows);
- ``benchmarks/run.py --only rcv1`` — the rcv1 production headline row
  whose vs_oracle_parallel columns are currently derived, not measured.

On success the ``needs-TPU-regen`` banners and per-row ⚠ marks are
dropped from both files (the regenerated tables ARE the fresh
measurement).  ``--only`` restricts the run; banners are only stripped on
a full pass.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
DOCS = ("KERNELS.md", "RESULTS.md")
MARK = "needs-TPU-regen"


def stale_rows():
    """(file, row-config) pairs still carrying the ⚠ mark."""
    out = []
    for doc in DOCS:
        path = os.path.join(HERE, doc)
        if not os.path.exists(path):
            continue
        for line in open(path):
            if line.startswith("|") and "⚠" in line:
                out.append((doc, line.split("|")[1].strip()))
    return out


def tpu_attached() -> bool:
    import jax

    return jax.devices()[0].platform in ("tpu", "axon")


def strip_banners():
    """Drop the needs-TPU-regen blockquote banners and per-row ⚠ marks —
    only called after a successful FULL regen, when the tables just
    rewritten are the fresh measurement."""
    for doc in DOCS:
        path = os.path.join(HERE, doc)
        if not os.path.exists(path):
            continue
        src = open(path).read()
        src = re.sub(r"^> \*\*⚠ " + MARK + r":\*\*.*?\n\n", "", src,
                     flags=re.S | re.M)
        src = src.replace(" ⚠ |", " |").replace(" ⚠|", "|")
        open(path, "w").write(src)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--list", action="store_true",
                    help="list the stale rows and exit")
    ap.add_argument("--only", default="",
                    help="restrict to a subset: kernels,epsilon,losses,rcv1 "
                         "(banner stripping then stays off)")
    args = ap.parse_args()

    rows = stale_rows()
    print(f"{len(rows)} row(s) marked {MARK}:")
    for doc, cfg in rows:
        print(f"  {doc}: {cfg}")
    if args.list:
        return 0
    if not tpu_attached():
        print(f"\nno TPU attached — refusing to overwrite the marked rows "
              f"with CPU numbers.  Attach hardware and rerun "
              f"`python {os.path.relpath(__file__)}`.", file=sys.stderr)
        return 1

    only = set(args.only.split(",")) if args.only else None
    py = sys.executable

    def run(argv):
        print("+", " ".join(argv), flush=True)
        subprocess.run(argv, check=True)

    if only is None or "kernels" in only:
        run([py, os.path.join(HERE, "kernels.py")])
    run_only = [s for s in ("epsilon", "losses", "rcv1")
                if only is None or s in only]
    if run_only:
        run([py, os.path.join(HERE, "run.py"),
             f"--only={','.join(run_only)}"])

    if only is None:
        strip_banners()
        print("regen complete — banners and ⚠ marks dropped from "
              + ", ".join(DOCS))
    else:
        print("partial regen complete — banners left in place "
              "(rerun without --only for the full pass)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
