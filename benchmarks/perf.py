"""Performance accounting for the benchmark suite (VERDICT r1 item 1).

Gives every benchmark config a FLOP model, a memory-traffic model, achieved
FLOP/s + MFU against the attached chip's public peak, µs per coordinate
step, and a roofline classification of what bounds the round — so the
"sequential SDCA is latency-bound" claim is measured, not asserted.

Accounting contract (what counts as useful work): the reference hot loop
CoCoA.scala:148-188 — per coordinate step one sparse/dense row·w dot, one
row axpy, O(1) scalar logic — plus the per-round margins pass where a path
precomputes it and the eval passes at the debugIter cadence.  Useful FLOPs
are the 4·nnz(x) per step the reference's math does; extra physical FLOPs a
TPU path spends to buy parallelism (the block path's B·nnz Gram work per
step, lane-padding in the sparse kernel) are reported separately as
``physical_flops`` so MFU can be read both ways (useful-MFU is the honest
headline; physical-MFU shows how hard the MXU is actually running).

Peaks are per-chip dense bf16 from Google's public specs; f32 work runs at
a fraction of that (TPU matmuls decompose f32 into bf16 passes), so MFU
against bf16 peak is a conservative lower bound.
"""

from __future__ import annotations

import jax

# per-chip dense bf16 peak FLOP/s (public spec sheets)
PEAKS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 137e12,
    "TPU v5": 459e12,          # v5p
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,     # v5e
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,     # v6e / Trillium
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,          # Ironwood (fp8 4614; bf16 half)
}

# single-chip HBM bandwidth, bytes/s (public spec sheets)
HBM_BW = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1200e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
    "TPU7x": 7370e9,           # Ironwood: 7.37 TB/s HBM3e (public specs)
}


def device_info():
    """(device_kind, peak_flops|None, hbm_bytes_per_s|None) of chip 0."""
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    return kind, PEAKS.get(kind), HBM_BW.get(kind)


# Calibrated per-slot cost of the sparse stream merge loops: the kernel
# is scalar-issue-bound (~6 scalar ops per nonzero slot, docs/DESIGN.md
# §3d), and TRACE.md measured the rcv1 stream round at 6.16 ms over
# 2024 steps of 96 GROUP-rounded slots each (mean 73.6 nnz/row ->
# ceil(73.6/32)*32 = 96) — so one slot costs ~31.7 ns regardless of W.
SEQ_SLOT_NS = 6.16e6 / (2024 * 96)
# one whole-(1, 128)-lane-row VPU op (the hybrid panel's unit of work)
PANEL_LANE_ROW_NS = 3.0


def predict_sparse_round_ms(steps, nnz, *, n_hot=0, coverage=0.0,
                            group=32):
    """Latency prediction for the scalar-issue-bound sparse sequential
    paths, from the calibrated per-slot cost: per step the stream loops
    pay ceil(nnz_cold / GROUP)·GROUP slots at :data:`SEQ_SLOT_NS`, and a
    hot panel (``n_hot > 0``, the hybrid layout) adds two whole-array
    VPU passes over n_hot/128 lane-rows (margin reduce + Δw axpy).  The
    pure-stream case (coverage 0) reproduces the measured 6.16 ms rcv1
    round by construction; the hybrid prediction is what the split is
    expected to buy before a TPU measures it (benchmarks/kernels.py
    ``rcv1/hybrid-seq``)."""
    import math

    cold = nnz * (1.0 - coverage)
    slots = math.ceil(cold / group) * group if cold > 0 else 0
    panel_ns = 2.0 * (n_hot / 128.0) * PANEL_LANE_ROW_NS if n_hot else 0.0
    return steps * (slots * SEQ_SLOT_NS + panel_ns) * 1e-6


def sdca_round_model(n, d, k, h, *, layout="dense", nnz=None, path="fast",
                     block=0, itemsize=4, max_nnz=None, n_hot=0,
                     coverage=0.0):
    """FLOP and HBM-byte model of ONE outer round of the SDCA family.

    Returns a dict with ``useful_flops``, ``physical_flops``, ``hbm_bytes``.
    ``nnz`` is the mean nonzeros per example for the sparse layout (dense ⇒
    nnz = d).  ``path``:

    - ``"fast"`` — XLA margins decomposition: one whole-shard X·w matvec
      (2·n·nnz) + per step one row·Δw dot and one axpy (4·nnz).  HBM: the
      margins pass reads all of X once; each step reads its row.
    - ``"pallas"`` — the round-4+ kernels compute margins IN-KERNEL from
      the sampled row against the VMEM-resident w/Δw
      (ops/pallas_sdca.py, ops/pallas_sparse.py) — there is NO whole-X
      pass; per step one margin dot (2·nnz), one row·Δw/axpy pair
      (4·nnz).  HBM: each step reads its sampled row, nothing else scales
      with n.  (Before round 4 this path shared the "fast" formula, which
      overcounted HBM by the retired full-X margins pass — the floors
      read impossibly above the measured times.)
    - ``"block"`` — no whole-shard pass; per step one row·(w+σΔw) dot, one
      axpy, and the B·nnz Gram work that buys the MXU formulation
      (physical only).  HBM: each step reads its row once (margins and
      Gram both come from the same gathered tile).
    - ``"sparse-block"`` — the in-kernel CSR Gram block path
      (ops/pallas_sparse.sparse_block_gram): same useful work as ``block``
      but NO densified tile — HBM moves only the CSR streams (re-prefetched
      once per SMEM segment pair, sized from ``max_nnz``) and the
      lane-blocked [w|Δw] operand per tile call; the Gram merge/scatter ops
      each touch a 128-lane block (physical, like the sparse sequential
      kernel).
    - ``"exact"`` — like fast but the margin dot reads w directly (same
      counts; no margins pass, the x·w dot replaces the x·Δw dot).
    - ``"hybrid-seq"`` / ``"hybrid-block"`` — the hot/cold column split
      (``--hotCols``; ``n_hot`` panel lanes covering ``coverage`` of the
      nonzeros): the panel majority runs at MXU/VPU rates, only the
      residual tail (``nnz·(1−coverage)`` mean, padded width ``max_nnz``
      = the RESIDUAL width) pays the 128x-physical stream price.  Useful
      work is the reference's per-nonzero math — the split permutes
      sums, it never adds math.
    """
    nnz = d if nnz is None else nnz
    row_bytes = (2 * itemsize if layout == "sparse" else itemsize) * nnz
    steps = k * h
    useful = 4.0 * nnz * steps          # CoCoA.scala:157-185: dot + axpy
    if path == "fast":
        margins = 2.0 * n * nnz
        physical = useful + margins
        hbm = n * row_bytes + steps * row_bytes
        return dict(useful_flops=useful + margins, physical_flops=physical,
                    hbm_bytes=hbm)
    if path == "pallas":
        margins = 2.0 * nnz * steps     # in-kernel, from the sampled row
        physical = useful + margins
        if layout == "sparse":
            # the lane-blocked sparse kernel touches a 128-lane block per
            # nonzero (ops/pallas_sparse.py) — physical VPU work is 128x
            # the useful scalar work of each dot/axpy lane
            physical = (useful + margins) * 128
        return dict(useful_flops=useful + margins, physical_flops=physical,
                    hbm_bytes=steps * row_bytes)
    if path == "block":
        b = max(1, block)
        gram = 2.0 * b * nnz * steps    # B x B Gram per B steps: B·nnz/step
        margins = 2.0 * nnz * steps     # in-block x·(w+σΔw), from the tile
        physical = useful + margins + gram
        # gathered row tile read once per step (margins+Gram+apply reuse it);
        # sparse blocks densify: the tile write+read is B·d dense
        tile_bytes = steps * (d * itemsize * 3 if layout == "sparse"
                              else row_bytes)
        return dict(useful_flops=useful + margins, physical_flops=physical,
                    hbm_bytes=tile_bytes)
    if path == "sparse-block":
        from cocoa_tpu.ops.pallas_sparse import seg_rows

        b = max(1, block)
        gram = 2.0 * b * nnz * steps    # B·nnz merge MACs per step
        margins = 2.0 * nnz * steps     # in-kernel x·(w+σΔw) from [w|Δw]
        # every SMEM-addressed pick/scatter is a (1, 128) masked lane-row
        # op — same 128x physical factor as the sparse sequential kernel
        physical = (useful + margins + gram) * 128
        s = seg_rows(b, int(max_nnz if max_nnz is not None else nnz)) or b
        ns = b // s
        pairs = ns * (ns + 1) // 2
        d_pad = -(-d // 128) * 128
        blocks = steps / b              # shard-blocks per round (all K)
        # CSR streams cross SMEM once per segment pair they appear in
        # (~(ns+1)/2 pairs each), plus the lane-blocked [w|Δw] operand per
        # tile call: read-only for each Gram pair, read+write for each
        # apply segment
        wd_bytes = 2 * d_pad * itemsize
        hbm = (steps * row_bytes * (pairs + ns) / ns
               + blocks * (pairs * wd_bytes + ns * 2 * wd_bytes))
        return dict(useful_flops=useful + margins, physical_flops=physical,
                    hbm_bytes=hbm)
    if path == "exact":
        return dict(useful_flops=useful, physical_flops=useful,
                    hbm_bytes=steps * row_bytes)
    if path in ("hybrid-seq", "hybrid-block"):
        # the hot/cold column split (--hotCols, docs/DESIGN.md §3b-vi):
        # ``coverage`` of the nonzeros ride the dense hot panel (n_hot
        # lanes) at MXU/VPU rates; only the residual tail pays the
        # 128x-physical scalar-port stream price.  Useful work is the
        # reference's per-nonzero math, unchanged by the split.
        nnz_cold = nnz * (1.0 - coverage)
        margins = 2.0 * nnz * steps
        cold_bytes = 2 * itemsize * nnz_cold        # residual CSR idx+val
        panel_row = n_hot * itemsize                # one row's panel slice
        if path == "hybrid-seq":
            # per step: residual margin dot + axpy on the streams
            # ((4+2)·nnz_cold slots, each a 128-lane masked op) + the
            # panel's margin reduce (2 passes of n_hot MACs: w and Δw)
            # and Δw axpy (1 pass) as whole-array VPU work.  HBM: the
            # residual stream tables + the gathered panel row
            # (gather write + kernel read).
            physical = (6.0 * nnz_cold * 128 + 6.0 * n_hot) * steps
            hbm = steps * (cold_bytes + 2 * panel_row)
            return dict(useful_flops=useful + margins, physical_flops=physical,
                        hbm_bytes=hbm)
        from cocoa_tpu.ops.pallas_sparse import seg_rows

        # hybrid-block: the residual streams run the sparse-block Gram
        # machinery (same accounting as "sparse-block", on the COLD
        # width), and the panel adds per step 2·B·n_hot Gram MACs +
        # 2·n_hot margin + 2·n_hot apply on the MXU.  HBM: residual
        # streams per segment pair + [w|Δw] operands + the panel tile
        # (gather write + the three einsums' reads).
        b = max(1, block)
        gram_cold = 2.0 * b * nnz_cold * steps
        physical = ((4.0 * nnz_cold + 2.0 * nnz_cold + gram_cold / steps)
                    * 128 + 2.0 * b * n_hot + 4.0 * n_hot) * steps
        s = seg_rows(b, int(max_nnz if max_nnz is not None else nnz_cold)) \
            or b
        ns = b // s
        pairs = ns * (ns + 1) // 2
        d_pad = -(-d // 128) * 128
        blocks = steps / b
        wd_bytes = 2 * d_pad * itemsize
        hbm = (steps * cold_bytes * (pairs + ns) / ns
               + blocks * (pairs * wd_bytes + ns * 2 * wd_bytes)
               + steps * 4 * panel_row)
        return dict(useful_flops=useful + margins, physical_flops=physical,
                    hbm_bytes=hbm)
    raise ValueError(f"unknown path {path!r}")


def predict_accel_rounds(rounds_plain, gap0, gap_target, *,
                         restart_overhead=0.1):
    """Theoretical round-count floor for the accelerated outer loop
    (--accel, Smith et al. arXiv:1711.05305 structure).

    The plain run's certified trajectory implies a per-round linear
    contraction q = (gap_target/gap0)^(1/rounds_plain); Nesterov-class
    outer momentum improves a q-rate scheme to q_acc = 1 − √(1−q) (the
    κ → √κ dependence), so the accelerated floor is
    log(gap_target/gap0) / log(q_acc), inflated by ``restart_overhead``
    for the gap-monitored restarts (each costs at most one eval window).

    This is the FLOOR the A/B row in RESULTS.md is read against, not a
    prediction of the measured ratio: the implementation is a secant
    (Anderson-1) jump with a data-derived coefficient at eval-window
    cadence (solvers/base.secant_coef), not an oracle 1/√κ momentum
    schedule, so measured sits between plain and this bound (measured
    on rcv1-synth: 1.76× vs the safe-σ′ control, 1.38× vs the
    better-conditioned σ′=K/2 control — the ratio grows with the
    control's round count exactly as this floor's κ → √κ shape says
    it should; the floor predicts what a perfectly-scheduled outer
    momentum could reach).
    """
    import math

    if not (0 < gap_target < gap0):
        raise ValueError(
            f"need 0 < gap_target < gap0, got gap0={gap0}, "
            f"gap_target={gap_target}")
    if rounds_plain < 1:
        raise ValueError(f"rounds_plain must be >= 1, got {rounds_plain}")
    decades = math.log(gap_target / gap0)
    q = math.exp(decades / rounds_plain)
    q_acc = 1.0 - math.sqrt(1.0 - q)
    return int(math.ceil(decades / math.log(q_acc)
                         * (1.0 + restart_overhead)))


# Calibrated LIBSVM text-parse throughput, bytes/s per process (the
# strtod-bound native scanner measured on the container's single core at
# rcv1-synth scale; the Python fallback is ~20x slower and the model is
# read against the native path).  Both ingest passes share this rate —
# pass 1 parses-and-drops, pass 2 parses-and-keeps.
PARSE_BYTES_PER_S = 90e6
# jax.distributed KV-store exchange throughput for the pass-1 partials
# (base64 through the coordinator's gRPC store — small payloads, so this
# is a latency-flavored effective rate, not a link speed)
KV_BYTES_PER_S = 50e6


def csr_host_bytes(n, nnz):
    """Host bytes of a parsed LIBSVM CSR: f64 labels + i64 indptr +
    i32 indices + f64 values (data/libsvm.LibsvmData)."""
    return 8 * n + 8 * (n + 1) + 4 * nnz + 8 * nnz


def ingest_model(file_bytes, n, nnz, processes, *, mode, d):
    """Per-PROCESS cost model of one ingest (benchmarks/run.py ``ingest``
    A/B row; docs/DESIGN.md §12 RSS accounting).

    - ``whole``: every process reads and parses the ENTIRE file once and
      holds the full host CSR — P redundant parses, full-dataset RSS per
      process, no exchange.
    - ``stream``: pass 1 range-parses this process's 1/P of the file
      (stats kept, rows dropped), the partial index/histogram is
      exchanged over the KV store (~(8·n + 8·d) per process, gathered
      from P−1 peers), pass 2 parses the ~1/P of the file its own shards
      occupy — so ~2/P of the file is parsed per process and the held
      CSR shrinks to ~1/P of the dataset plus the global index.

    Returns ``{bytes_read, parse_seconds, csr_peak_bytes}``; seconds are
    parse work at :data:`PARSE_BYTES_PER_S` plus the exchange at
    :data:`KV_BYTES_PER_S`.  The predicted stream:whole ratios — wallclock
    ~2/P, resident CSR ~1/P + index — are what the measured bench row is
    read against (RESULTS.md fixed-cost breakdown).
    """
    if mode not in ("whole", "stream"):
        raise ValueError(f"mode must be whole|stream, got {mode!r}")
    index_bytes = 8 * (n + 1) + 8 * n + 8 * d  # row_off + row_nnz + hist
    if mode == "whole":
        return dict(
            bytes_read=float(file_bytes),
            parse_seconds=file_bytes / PARSE_BYTES_PER_S,
            csr_peak_bytes=float(csr_host_bytes(n, nnz)),
        )
    share = file_bytes / processes
    exchange = (processes - 1) * (8 * n + 8 * d)
    return dict(
        bytes_read=2.0 * share,
        parse_seconds=(2.0 * share / PARSE_BYTES_PER_S
                       + exchange / KV_BYTES_PER_S),
        csr_peak_bytes=(csr_host_bytes(n, nnz) / processes + index_bytes),
    )


def eval_flops(n, d, *, nnz=None, test_n=0):
    """One duality-gap + test-error evaluation: a full-data margins pass
    (2·n·nnz), the O(n) loss reductions, and the test pass."""
    nnz = d if nnz is None else nnz
    return 2.0 * (n + test_n) * nnz + 5.0 * (n + test_n)


def account(tag, secs_per_round, model, *, steps, evals_per_round=0.0,
            eval_fl=0.0):
    """Fold a measured per-round time against the model into the reported
    perf columns."""
    kind, peak, bw = device_info()
    useful = model["useful_flops"] + evals_per_round * eval_fl
    physical = model["physical_flops"] + evals_per_round * eval_fl
    out = dict(
        config=tag,
        device=kind,
        ms_per_round=round(secs_per_round * 1e3, 3),
        us_per_step=round(secs_per_round / max(1, steps) * 1e6, 3),
        useful_gflops=round(useful / secs_per_round / 1e9, 1),
        physical_gflops=round(physical / secs_per_round / 1e9, 1),
    )
    if peak:
        out["mfu_pct"] = round(useful / secs_per_round / peak * 100, 3)
        out["physical_mfu_pct"] = round(
            physical / secs_per_round / peak * 100, 3)
    if bw:
        hbm = model["hbm_bytes"]
        out["hbm_floor_ms"] = round(hbm / bw * 1e3, 3)
        out["hbm_bound_pct"] = round(hbm / bw / secs_per_round * 100, 1)
    elif peak:
        # a chip in PEAKS but not HBM_BW would silently drop the roofline
        # columns — say so instead of weakening the "latency-bound is
        # measured" claim (ADVICE r2)
        out["hbm_floor_ms"] = "bw unknown"
    if peak and bw:
        flop_floor = physical / peak
        hbm_floor = model["hbm_bytes"] / bw
        measured = secs_per_round
        if hbm_floor >= 0.5 * measured:
            out["bound"] = "HBM"
        elif flop_floor >= 0.5 * measured:
            out["bound"] = "MXU"
        else:
            out["bound"] = "latency"
    return out
