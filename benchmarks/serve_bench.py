"""The serving benchmark: queries/s at a pinned p99 latency bound, plus
model freshness (gap age), on CPU.

The headline claim of the ``--serve`` path (docs/DESIGN.md §17): batched
margin queries ride a compiled scoring path with statically-shaped
buckets — one XLA compile per bucket, ever — behind an adaptive
micro-batcher, while the model hot-swaps under traffic without dropping
a request.  The bench trains a small model to a certified gap, serves
it from real checkpoint generations (one mid-run hot-swap, so the swap
machinery is in the measured path), hammers the batcher from G client
threads for the duration, and reports

- ``qps``       — answered requests / wall-clock of the traffic window
- ``p50/p99_ms``— per-request latency percentiles (submit → answer),
  measured exactly (every request's own enqueue timestamp)
- ``sla_ms``    — the pinned bound: the run FAILS (exit 1) if p99
  exceeds it — the row is "queries/s AT p99 ≤ SLA", not queries/s alone
- ``gap_age_s`` — the serving model's certificate age at measurement
  end (freshness, the cocoa_model_gap_age_seconds gauge's value)
- ``compiles``  — measured XLA compiles of the scoring executable
  (must equal the bucket count: the one-compile-per-bucket pin)

    python benchmarks/serve_bench.py                 # print the row
    python benchmarks/serve_bench.py --row=out.jsonl # write it (CI gate)

Latency/qps are CPU-measured host wall-clock (no TPU column: serving
latency is dominated by dispatch+fetch, which the tunnel distorts —
the needs-TPU-regen convention applies to the wallclock the day a TPU
is attached).  benchmarks/check_regression.py gates the SLA, the
compile count, and a catastrophic-throughput floor against the
committed row.

``--serveDtype=bf16|int8`` switches to the low-precision A/B mode
(docs/DESIGN.md §20): compiled-path margin throughput of the packed
quantized model vs the SAME-harness f32 control, at a serving-scale
geometry chosen so the mechanism under test is the real one — the f32
model (2.5 MB) spills L2 while the packed bf16 form (1.25 MB) fits, so
halving the gather stream is what the ratio measures.  XLA's CPU
backend EMULATES narrow arithmetic (a plain bf16 model is SLOWER than
f32), which is why the small-model serving row above would show ~1.0x:
the win appears exactly when the model stops fitting in cache, and on
TPU the same packed layout halves the HBM stream instead.  The A/B row
(``serve-cpu-synth-bf16``) carries the same-harness control
(``f32_qps``), the measured ``qps_ratio``, the per-swap certificate
(``margin_err_bound`` over the calibration batch) and a sign-flip
audit over a disjoint validation set (``flips`` beyond 2x the bound
must be 0); the mid-bench hot-swap quantizes IN the measured path and
the compile count pins one executable per (bucket, dtype) per scorer.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import socket
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

CONFIG = "serve-cpu-synth"
# the canonical serving workload: a small certified model, sparse
# queries (nnz ~ 12 of d=256), two buckets, a 50 ms p99 SLA
N, D, K = 2048, 256, 2
LAM, GAP_TARGET = 1e-3, 1e-2
BUCKETS = (64, 256)
MAX_NNZ = 32
SLA_MS = 50.0
QUERY_NNZ = 12

# the --serveDtype A/B geometry: one saturated bucket of nnz-512
# queries against a model sized at the L2 knife edge of this class of
# serving CPU — f32 w = 2.5 MB spills a ~2 MB L2, packed bf16 = 1.25 MB
# fits — so the measured ratio is the gather-stream halving, the same
# mechanism that halves the HBM stream at TPU scale
D_Q = 640 * 1024
BUCKET_Q = 1024
NNZ_Q = 512
N_BATCHES_Q = 8     # distinct preassembled query batches cycled through
CALIB_N = 64        # calibration queries the certificate is bound over
# one executable per (bucket, dtype) per scorer instance: the f32
# control scorer compiles its one form; the quantized scorer compiles
# its packed form plus the f32 certificate-fallback form
EXPECTED_COMPILES_Q = 3

# the --serveReplicas fleet mode (docs/DESIGN.md §21): R real CLI
# replica processes serving a (T, d) tenant catalogue behind the
# in-bench router, hammered over real sockets with tenant-tagged
# multi-query lines; the headline is aggregate answered queries/s vs
# the SAME-harness 1-replica control (scaling_eff), plus the open-loop
# overload window's shed accounting and the SIGKILL recovery drill
T_FLEET = 4
Q_PER_LINE = 16     # ';'-separated queries per protocol line
FLEET_LINES = 64    # distinct preassembled lines cycled per client
# the tracing A/B (docs/DESIGN.md §22): the committed fleet row carries
# a tracing-on closed-loop window (every line trace=-prefixed, the
# router samples 1 in TRACE_SAMPLE into query_trace events) against a
# back-to-back untraced window of the same shape; the overhead of the
# always-paid prefix peel + the sampled stamp/emit path must stay
# under the --trace-bar (default 5%)
TRACE_SAMPLE = 64
TRACE_BAR_PCT = 5.0


def train_checkpoints(ck: str):
    """Train the model to its certified gap and leave TWO checkpoint
    generations (the second is the mid-bench hot-swap target)."""
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_dense
    from cocoa_tpu.solvers import run_cocoa

    data = synth_dense(N, D, seed=7)
    ds = shard_dataset(data, k=K, layout="dense")
    params = Params(n=N, num_rounds=300, local_iters=max(1, N // K // 10),
                    lam=LAM, gamma=1.0, loss="hinge")
    debug = DebugParams(debug_iter=10, seed=0, chkpt_iter=301,
                        chkpt_dir="")
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                               gap_target=GAP_TARGET)
    gap = traj.records[-1].gap if traj.records else None
    rounds = traj.records[-1].round if traj.records else 0
    w = np.asarray(w)
    # generation 1: the model the server starts on; generation 2: the
    # fresher state the watcher hot-swaps in mid-bench (a genuinely
    # different iterate — here the final w vs a perturbed older one)
    ckpt_lib.save(ck, "CoCoA+", max(1, rounds - 10),
                  (w * 0.95).astype(np.float32), None, gap=gap)
    return w.astype(np.float32), rounds, gap


def measure(ck, w_final, rounds, gap, duration_s: float, threads: int,
            sla_ms: float):
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import serving
    from cocoa_tpu.analysis import sanitize

    with sanitize.watch_compiles() as compiles:
        w0, info = serving.load_model(ckpt_lib.latest(ck, "CoCoA+"))
        slots = serving.ModelSlots(w0, info, dtype=np.float32)
        scorer = serving.BatchScorer(D, dtype=np.float32,
                                     buckets=BUCKETS, max_nnz=MAX_NNZ)
        scorer.warmup(slots.current()[0])
        batcher = serving.MicroBatcher(scorer, slots,
                                       sla_s=sla_ms / 1000.0)
        watcher = serving.SwapWatcher(slots, ck, "CoCoA+",
                                      poll_s=0.05).start()
        stop = threading.Event()
        lock = threading.Lock()
        lats = []
        failed = [0]

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                idx = np.sort(rng.choice(D, size=QUERY_NNZ,
                                         replace=False)).astype(np.int32)
                val = rng.standard_normal(QUERY_NNZ)
                t0 = time.monotonic()
                try:
                    batcher.score_sync(idx, val, timeout=10.0)
                except Exception:
                    with lock:
                        failed[0] += 1
                    continue
                with lock:
                    lats.append(time.monotonic() - t0)

        workers = [threading.Thread(target=client, args=(s,),
                                    daemon=True)
                   for s in range(threads)]
        t_start = time.monotonic()
        for t in workers:
            t.start()
        # the mid-bench hot-swap: the trainer "catches up" halfway in
        time.sleep(duration_s / 2)
        ckpt_lib.save(ck, "CoCoA+", rounds, w_final, None, gap=gap)
        time.sleep(duration_s / 2)
        stop.set()
        for t in workers:
            t.join(10)
        wall = time.monotonic() - t_start
        watcher.stop()
        gap_age = slots.gap_age_s()
        swaps = watcher.swaps_total
        batcher.stop()
    serve_compiles = sum(1 for c in compiles
                         if "serve_margins" in c.name)
    lats.sort()

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0

    return {
        "config": CONFIG, "type": "serve", "device": "cpu",
        "n": N, "d": D, "k": K, "lam": LAM,
        "gap": gap, "gap_target": GAP_TARGET, "rounds": int(rounds),
        "queries": len(lats), "threads": threads,
        "qps": round(len(lats) / wall, 1),
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "sla_ms": sla_ms,
        "fill": round(batcher.requests_total
                      / max(1, batcher.slots_total), 3),
        "buckets": "/".join(str(b) for b in BUCKETS),
        "compiles": serve_compiles, "swaps": swaps,
        "gap_age_s": round(gap_age, 3),
        "wallclock_s": round(wall, 3),
        "stopped": ("target" if failed[0] == 0 and swaps >= 1
                    else None),
    }


def _quant_batches(rng, n_batches):
    """Preassembled nnz-512 query batches (host f32/int32 pairs)."""
    import numpy as np

    batches = []
    for _ in range(n_batches):
        idx = rng.integers(0, D_Q, size=(BUCKET_Q, NNZ_Q),
                           dtype=np.int64).astype(np.int32)
        val = rng.standard_normal((BUCKET_Q, NNZ_Q)).astype(np.float32)
        batches.append((idx, val))
    return batches


def _pass_qps(scorer, slots, batches, pass_s, lats=None):
    """One timed pass: sustained rows/s of the compiled path, cycling
    the preassembled batches; each dispatch blocks on the fetched
    margins so the number is end-to-end dispatch+compute+fetch."""
    import numpy as np

    w_dev, scale, _ = slots.current()
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < pass_s:
        idx, val = batches[n % len(batches)]
        t1 = time.perf_counter()
        np.asarray(scorer.score(w_dev, idx, val, scale=scale))
        if lats is not None:
            lats.append(time.perf_counter() - t1)
        n += 1
    return n * BUCKET_Q / (time.perf_counter() - t0)


def measure_quant(serve_dtype: str, duration_s: float, sla_ms: float):
    """The --serveDtype A/B row: packed-``serve_dtype`` compiled-path
    throughput vs the same-harness f32 control, with the mid-measure
    hot-swap (quantize-at-swap in the measured path), the calibration
    certificate, and the disjoint sign-flip audit."""
    import jax
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import serving
    from cocoa_tpu.analysis import sanitize
    from cocoa_tpu.serving import quantize as quant_lib

    rng = np.random.default_rng(11)
    # a synthetic serving-scale model (training to certification at
    # d=640K is a training bench, not a serving one) shipped through
    # real checkpoint generations so load/swap stay the product path
    w_final = (rng.standard_normal(D_Q) * 0.05).astype(np.float32)
    ck = tempfile.mkdtemp(prefix="serve-bench-quant-")
    ckpt_lib.save(ck, "CoCoA+", 1, (w_final * 0.97).astype(np.float32),
                  None, gap=GAP_TARGET)
    batches = _quant_batches(rng, N_BATCHES_Q)
    pass_s = max(0.2, duration_s / 10.0)

    with sanitize.watch_compiles() as compiles:
        w0, info = serving.load_model(ckpt_lib.latest(ck, "CoCoA+"))
        # calibration from the bench's own query stream: the first
        # CALIB_N rows of batch 0 (the flip audit below uses the OTHER
        # batches — bound and audit are disjoint)
        calib = serving.CalibrationBuffer(D_Q, max_nnz=NNZ_Q,
                                          capacity=CALIB_N, seed=11)
        for r in range(CALIB_N):
            calib.record(batches[0][0][r], batches[0][1][r])
        slots_f32 = serving.ModelSlots(w0, info, dtype="f32")
        scorer_f32 = serving.BatchScorer(D_Q, dtype="f32",
                                         buckets=(BUCKET_Q,),
                                         max_nnz=NNZ_Q)
        scorer_f32.warmup(slots_f32.current()[0])
        slots_q = serving.ModelSlots(w0, info, dtype=serve_dtype,
                                     calibration=calib)
        scorer_q = serving.BatchScorer(D_Q, dtype=serve_dtype,
                                       buckets=(BUCKET_Q,),
                                       max_nnz=NNZ_Q)
        wq_dev, q_scale, _ = slots_q.current()
        scorer_q.warmup(wq_dev, q_scale)
        watcher = serving.SwapWatcher(slots_q, ck, "CoCoA+",
                                      poll_s=0.05)

        t_start = time.monotonic()
        dev_batches = [(jax.device_put(i), jax.device_put(v))
                       for i, v in batches]
        # one steady-state dispatch per arm before timing
        np.asarray(scorer_f32.score(slots_f32.current()[0],
                                    *dev_batches[0]))
        np.asarray(scorer_q.score(wq_dev, *dev_batches[0],
                                  scale=q_scale))
        # the arms INTERLEAVE pass-by-pass and the gate is the median
        # of the pairwise ratios: the f32 control straddles L2 by
        # design, so its absolute rate is bimodal with machine state —
        # pairing each quantized pass with an adjacent control pass
        # cancels the slow drift a best-of-separated-arms design
        # mistakes for a precision effect
        pairs = 6
        lats = []
        f32_rates, q_rates = [], []
        for p in range(pairs):
            f32_rates.append(_pass_qps(scorer_f32, slots_f32,
                                       dev_batches, pass_s))
            q_rates.append(_pass_qps(scorer_q, slots_q, dev_batches,
                                     pass_s, lats=lats))
            if p == pairs // 2 - 1:
                # the mid-measure hot-swap: gen-2 lands, slots_q
                # quantizes and re-certifies it, and the remaining
                # passes serve the new bytes
                ckpt_lib.save(ck, "CoCoA+", 2, w_final, None,
                              gap=GAP_TARGET)
                watcher.poll_once()
        ratios = sorted(q / f for q, f in zip(q_rates, f32_rates))
        qps_ratio = ratios[len(ratios) // 2]
        qps = sorted(q_rates)[len(q_rates) // 2]
        f32_qps = sorted(f32_rates)[len(f32_rates) // 2]
        wall = time.monotonic() - t_start
        swaps = watcher.swaps_total
        served = slots_q.served_dtype
        bound = slots_q.last_bound
    serve_compiles = sum(1 for c in compiles
                         if "serve_margins" in c.name)

    # the sign-flip audit, host f64, on batches DISJOINT from the
    # calibration the bound came from: a flip at |m32| > 2x bound means
    # the certificate understated the error — the gate is 0
    qm = quant_lib.quantize(w_final, serve_dtype)
    # jaxlint: allow=f64 -- host-side certificate audit arithmetic
    w_served = quant_lib.dequantize(qm, D_Q).astype(np.float64)
    w64 = w_final.astype(np.float64)  # jaxlint: allow=f64 -- audit
    flips = 0
    flip_checked = 0
    for idx, val in batches[1:]:
        m32 = (w64[idx] * val).sum(axis=1)
        mq = (w_served[idx] * val).sum(axis=1)
        flip_checked += len(m32)
        guarded = np.abs(m32) > 2.0 * float(bound)
        flips += int(np.sum(guarded & (np.sign(m32) != np.sign(mq))))

    lats.sort()

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0

    return {
        "config": f"{CONFIG}-{serve_dtype}", "type": "serve",
        "device": "cpu", "d": D_Q, "serve_dtype": serve_dtype,
        "queries": flip_checked + len(batches[0][0]),
        "qps": round(qps, 1), "f32_qps": round(f32_qps, 1),
        "qps_ratio": round(qps_ratio, 3),
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "sla_ms": sla_ms,
        "buckets": str(BUCKET_Q),
        "compiles": serve_compiles, "swaps": swaps,
        "margin_err_bound": float(bound),
        "flips": flips, "flip_checked": flip_checked,
        "calib_n": CALIB_N,
        "wallclock_s": round(wall, 3),
        "stopped": ("target" if swaps >= 1 and flips == 0
                    and served == serve_dtype else None),
    }


def _fleet_lines(rng, n_lines):
    """Preassembled tenant-tagged protocol lines: each carries
    ``Q_PER_LINE`` nnz-12 queries for one tenant, tenants round-robin
    across lines so every window is cross-tenant traffic."""
    import numpy as np

    lines = []
    for j in range(n_lines):
        qs = []
        for _ in range(Q_PER_LINE):
            idx = np.sort(rng.choice(D, size=QUERY_NNZ, replace=False))
            val = rng.standard_normal(QUERY_NNZ)
            qs.append(" ".join(f"{int(i)}:{float(v):.5f}"
                               for i, v in zip(idx, val)))
        lines.append((f"tenant={j % T_FLEET};" + ";".join(qs)
                      + "\n").encode())
    return lines


def _traced_lines(lines):
    """The tracing-on A/B variant: the SAME preassembled lines with a
    client-chosen ``trace=<hex>;`` id prefixed (docs/DESIGN.md §22) —
    the router peels every prefix and samples 1 in ``TRACE_SAMPLE``
    into ``query_trace`` events."""
    return [b"trace=%08x;" % j + ln for j, ln in enumerate(lines)]


class _ClientStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.answered = 0    # queries (lines x Q_PER_LINE)
        self.shed = 0        # lines refused at admission
        self.failed = 0      # lines that got an error / dead socket
        self.lats = []       # per-line seconds, answered lines only

    def record(self, resp, dt):
        with self.lock:
            if isinstance(resp, list):
                self.answered += len(resp)
                self.lats.append(dt)
            elif isinstance(resp, dict) and resp.get("shed"):
                self.shed += 1
            else:
                self.failed += 1


def _ask_lines(addr, lines, stats, stop_ev, stride, offset):
    """One closed-loop client connection: send, read, classify, repeat
    until stopped."""
    try:
        s = socket.create_connection(addr, timeout=30)
        s.settimeout(60)
    except OSError:
        with stats.lock:
            stats.failed += 1
        return
    f = s.makefile("rwb")
    n = offset
    while not stop_ev.is_set():
        line = lines[n % len(lines)]
        n += stride
        t0 = time.monotonic()
        try:
            f.write(line)
            f.flush()
            resp = json.loads(f.readline())
        except (OSError, ValueError):
            with stats.lock:
                stats.failed += 1
            break
        stats.record(resp, time.monotonic() - t0)
    try:
        s.close()
    except OSError:
        pass


def _closed_window(addr, lines, n_conn, duration_s, midpoint=None):
    """Closed-loop capacity window: ``n_conn`` connections back to
    back; ``midpoint`` (if given) runs at the half mark — the mid-bench
    catalogue hot-swap rides it."""
    stats = _ClientStats()
    stop_ev = threading.Event()
    workers = [threading.Thread(target=_ask_lines,
                                args=(addr, lines, stats, stop_ev,
                                      n_conn, c), daemon=True)
               for c in range(n_conn)]
    t0 = time.monotonic()
    for t in workers:
        t.start()
    time.sleep(duration_s / 2)
    if midpoint is not None:
        midpoint()
    time.sleep(duration_s / 2)
    stop_ev.set()
    for t in workers:
        t.join(30)
    return stats, time.monotonic() - t0


def _open_window(addr, lines, n_senders, duration_s, rate_qps):
    """Open-loop overload window: a pacer enqueues line tickets at the
    offered rate regardless of completions (no coordinated omission);
    senders drain against the router, whose admission control sheds
    rather than queueing into an SLA violation."""
    stats = _ClientStats()
    stop_ev = threading.Event()
    tickets: "queue.Queue" = queue.Queue()
    offered = [0]

    def pacer():
        period = Q_PER_LINE / rate_qps
        nxt = time.monotonic()
        end = nxt + duration_s
        while time.monotonic() < end:
            tickets.put(offered[0])
            offered[0] += 1
            nxt += period
            pause = nxt - time.monotonic()
            if pause > 0:
                time.sleep(pause)
        stop_ev.set()

    def sender():
        try:
            s = socket.create_connection(addr, timeout=30)
            s.settimeout(60)
        except OSError:
            with stats.lock:
                stats.failed += 1
            return
        f = s.makefile("rwb")
        while True:
            try:
                i = tickets.get(timeout=0.2)
            except queue.Empty:
                if stop_ev.is_set():
                    break
                continue
            t0 = time.monotonic()
            try:
                f.write(lines[i % len(lines)])
                f.flush()
                resp = json.loads(f.readline())
            except (OSError, ValueError):
                with stats.lock:
                    stats.failed += 1
                break
            stats.record(resp, time.monotonic() - t0)
        try:
            s.close()
        except OSError:
            pass

    threads = [threading.Thread(target=pacer, daemon=True)]
    threads += [threading.Thread(target=sender, daemon=True)
                for _ in range(n_senders)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 120)
    return stats, time.monotonic() - t0, offered[0] * Q_PER_LINE


def _paired_window(addr, lines_a, lines_b, n_conn, duration_s):
    """The A/B inside ONE window: every connection strictly alternates
    an A line and a B line, so both arms sample identical machine
    conditions — scheduler drift, background compiles, and neighbor
    load cancel exactly instead of landing on one arm (the interleaved
    back-to-back form showed ±10% between identical windows on a busy
    runner).  Per-arm closed-loop throughput is reconstructed from the
    per-arm service time (the sum of that arm's own latencies)."""
    stats = (_ClientStats(), _ClientStats())
    stop_ev = threading.Event()

    def worker(offset):
        try:
            s = socket.create_connection(addr, timeout=30)
            s.settimeout(60)
        except OSError:
            with stats[0].lock:
                stats[0].failed += 1
            return
        f = s.makefile("rwb")
        arms = (lines_a, lines_b)
        n, k = offset, 0
        while not stop_ev.is_set():
            arm = k % 2
            k += 1
            line = arms[arm][n % len(arms[arm])]
            if arm == 1:
                n += n_conn
            t0 = time.monotonic()
            try:
                f.write(line)
                f.flush()
                resp = json.loads(f.readline())
            except (OSError, ValueError):
                with stats[arm].lock:
                    stats[arm].failed += 1
                break
            stats[arm].record(resp, time.monotonic() - t0)
        try:
            s.close()
        except OSError:
            pass

    workers = [threading.Thread(target=worker, args=(c,), daemon=True)
               for c in range(n_conn)]
    for t in workers:
        t.start()
    time.sleep(duration_s)
    stop_ev.set()
    for t in workers:
        t.join(30)
    return stats


def _fleet_harness(ck, n_replicas, route, sla_ms, evdir, tag,
                   trace_sample=0):
    """Spawn ``n_replicas`` REAL CLI serve processes against the
    catalogue and put a router in front (the same classes the CLI
    fleet path composes)."""
    from cocoa_tpu.serving.fleet import ServeFleet
    from cocoa_tpu.serving.router import Router

    fleet = ServeFleet(
        [f"--chkptDir={ck}", f"--numFeatures={D}",
         "--serveBatch=" + ",".join(str(b) for b in BUCKETS),
         f"--serveSlaMs={sla_ms:g}", f"--serveMaxNnz={MAX_NNZ}",
         "--quiet"],
        n_replicas,
        extra_argv_fn=lambda i: [f"--events={evdir}/{tag}{i}.jsonl"],
        # the persistent XLA cache would hide warmup compiles from the
        # one-compile-per-bucket accounting — count real compiles
        env={"JAX_PLATFORMS": "cpu", "COCOA_NO_COMPILE_CACHE": "1"})
    router = Router(fleet.start(), sla_s=sla_ms / 1000.0, route=route,
                    trace_sample=trace_sample)
    fleet.attach(router)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    return fleet, router


def _replica_stream_counts(path):
    """(serve_margins compiles, injected-swap events) in one replica's
    event stream."""
    compiles = swaps = 0
    if os.path.exists(path):
        for ln in open(path):
            r = json.loads(ln)
            if (r.get("event") == "compile"
                    and "serve_margins" in r.get("name", "")):
                compiles += 1
            elif (r.get("event") == "model_swap"
                  and r.get("round") == 2):
                swaps += 1
    return compiles, swaps


def measure_fleet(n_replicas, route, duration_s, threads, sla_ms,
                  rate_qps):
    """The ``--serveReplicas`` row: aggregate socket-path qps of R
    replicas vs the same-harness 1-replica control, the open-loop
    overload window's shed accounting, and the SIGKILL recovery drill
    (requeue, respawn, zero failed queries)."""
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib

    from cocoa_tpu.telemetry import events as tele_events

    rng = np.random.default_rng(23)
    w_cat = (rng.standard_normal((T_FLEET, D)) * 0.05).astype(
        np.float32)
    ck = tempfile.mkdtemp(prefix="serve-bench-fleet-")
    # per-tenant certification metadata rides the catalogue checkpoint
    # (docs/DESIGN.md §22): the replicas' tenant-labelled gap-age
    # gauges are fed from it, so the bench writes what a fleet trainer
    # would
    ckpt_lib.save(ck, "CoCoA+", 1, (w_cat * 0.95).astype(np.float32),
                  None, gap=GAP_TARGET,
                  tenant_gaps=[GAP_TARGET] * T_FLEET,
                  tenant_cert_ts=[time.time()] * T_FLEET)
    evdir = tempfile.mkdtemp(prefix="serve-bench-fleet-ev-")
    # the in-bench router emits the fleet-side query_trace events; give
    # its bus a stream so the traces are a real artifact
    router_ev = f"{evdir}/router.jsonl"
    tele_events.get_bus().configure(jsonl_path=router_ev)
    lines = _fleet_lines(rng, FLEET_LINES)
    traced = _traced_lines(lines)
    n_conn = max(4, threads)
    t_start = time.monotonic()

    print(f"serve_bench: spawning {n_replicas} fleet replicas "
          f"(catalogue {w_cat.shape}, route={route})", flush=True)
    fleet, router = _fleet_harness(ck, n_replicas, route, sla_ms,
                                   evdir, "rep",
                                   trace_sample=TRACE_SAMPLE)
    try:
        # --- capacity: closed loop, catalogue hot-swap at the half ---
        cap, cap_wall = _closed_window(
            router.address, lines, n_conn, duration_s,
            midpoint=lambda: ckpt_lib.save(
                ck, "CoCoA+", 2, w_cat, None, gap=GAP_TARGET,
                tenant_gaps=[GAP_TARGET] * T_FLEET,
                tenant_cert_ts=[time.time()] * T_FLEET))
        qps = cap.answered / cap_wall
        print(f"serve_bench: fleet capacity {qps:.0f} qps "
              f"({cap.answered} answered / {cap_wall:.2f}s)",
              flush=True)

        # --- tracing A/B: trace=-prefixed lines vs the same window ---
        # one paired window, lines alternating per connection; the
        # traced arm pays the per-line prefix peel on every line and
        # the stamp/emit path on the sampled 1-in-TRACE_SAMPLE.  The
        # overhead is the per-line mean-latency ratio of the two arms
        trc, ab = _paired_window(router.address, traced, lines,
                                 n_conn, duration_s)
        trc_failed, ab_failed = trc.failed, ab.failed
        t_mean = sum(trc.lats) / max(1, len(trc.lats))
        u_mean = sum(ab.lats) / max(1, len(ab.lats))
        traced_qps = trc.answered / max(1e-9, sum(trc.lats) / n_conn)
        trace_overhead_pct = round(
            max(0.0, 100.0 * (t_mean / max(1e-9, u_mean) - 1.0)), 2)
        print(f"serve_bench: tracing A/B {traced_qps:.0f} qps traced, "
              f"per-line {t_mean * 1e3:.3f}ms traced vs "
              f"{u_mean * 1e3:.3f}ms untraced "
              f"({trace_overhead_pct:g}% overhead)", flush=True)

        # --- overload: open loop past capacity — shed, don't queue ---
        if rate_qps <= 0:
            rate_qps = round(4 * qps)
        over, _, offered = _open_window(router.address, lines,
                                        2 * n_conn, duration_s / 2,
                                        rate_qps)
        print(f"serve_bench: overload window offered {offered} "
              f"queries at {rate_qps:g} qps — "
              f"{over.answered} answered, {over.shed} lines shed",
              flush=True)

        # both replicas must observe the injected generation before
        # the kill drill (the victim's swap event dies with it)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(_replica_stream_counts(
                    f"{evdir}/rep{i}.jsonl")[1] >= 1
                   for i in range(n_replicas)):
                break
            time.sleep(0.5)

        # --- the SIGKILL drill: requeue + respawn, zero failures -----
        # the connection is opened BEFORE the kill and the lines go out
        # sequentially right after it, so the first ones race the fleet
        # monitor to the dead replica — the requeue path, not just the
        # rerouted one, is in the drill
        drill = _ClientStats()
        victim = fleet.replicas[0]
        s = socket.create_connection(router.address, timeout=30)
        s.settimeout(60)
        sf = s.makefile("rwb")
        os.kill(victim.pid, signal.SIGKILL)
        print(f"serve_bench: SIGKILLed replica r0 (pid {victim.pid})",
              flush=True)
        for j in range(30):
            t0 = time.monotonic()
            sf.write(lines[j % len(lines)])
            sf.flush()
            drill.record(json.loads(sf.readline()),
                         time.monotonic() - t0)
        s.close()
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and (
                victim.restarts < 1
                or router.replicas_live() < n_replicas):
            time.sleep(0.5)
        respawned = (victim.restarts >= 1
                     and router.replicas_live() == n_replicas)
        tail, _ = _closed_window(router.address, lines, 2, 1.0)
        print(f"serve_bench: kill window answered "
              f"{drill.answered + tail.answered} queries, "
              f"respawned={respawned}", flush=True)

        processes = [1 + r.restarts for r in fleet.replicas]
        shed_total = int(router.shed_total)
        requeued = int(router.requeue_total)
        failed = (int(router.failed_total) + cap.failed + over.failed
                  + trc_failed + ab_failed
                  + drill.failed + tail.failed)
    finally:
        router.stop()
        fleet.stop()
        router.close()

    # --- same-harness 1-replica control --------------------------------
    print("serve_bench: measuring the 1-replica control", flush=True)
    ctl_fleet, ctl_router = _fleet_harness(ck, 1, "rr", sla_ms, evdir,
                                           "ctl")
    try:
        ctl, ctl_wall = _closed_window(ctl_router.address, lines,
                                       n_conn, duration_s)
        failed += ctl.failed + int(ctl_router.failed_total)
    finally:
        ctl_router.stop()
        ctl_fleet.stop()
        ctl_router.close()
        tele_events.get_bus().reset()
    control_qps = ctl.answered / ctl_wall

    # --- the sampled-trace artifact: schema-valid, assemblable -------
    # the row commits the waterfall's verdict (the dominant hop), so a
    # regression that stops traces from assembling fails the gate, not
    # just the dashboard
    from cocoa_tpu.telemetry import schema as tele_schema
    from cocoa_tpu.telemetry import trace_report

    trace_errs = tele_schema.check_file(router_ev)
    if trace_errs:
        print(f"serve_bench: trace stream schema violations: "
              f"{trace_errs[:3]}", file=sys.stderr)
    qts = trace_report.load_query_traces([router_ev])
    wf = trace_report.query_waterfall(qts) if qts else None
    dominant = wf["dominant_hop"] if wf else None
    if wf:
        print(f"serve_bench: {len(qts)} sampled traces — dominant hop "
              f"{dominant} (p99 "
              f"{wf['hops'][dominant]['p99_s'] * 1000.0:.3f}ms)",
              flush=True)

    counts = [_replica_stream_counts(f"{evdir}/rep{i}.jsonl")
              for i in range(n_replicas)]
    # each replica PROCESS compiles one executable per bucket; the
    # respawned victim appends its own warmup to the same stream, so
    # divide by the process count before comparing across replicas
    per_proc = set()
    for (c, _), p in zip(counts, processes):
        per_proc.add(c // p if c % p == 0 else -1)
    compiles = per_proc.pop() if len(per_proc) == 1 else -1
    swaps = sum(1 for _, s in counts if s >= 1)

    lats = sorted(cap.lats)

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0

    return {
        "config": "serve-cpu-fleet", "type": "serve", "device": "cpu",
        "d": D, "tenants": T_FLEET, "replicas": n_replicas,
        "route": route, "threads": n_conn,
        "queries": cap.answered,
        "qps": round(qps, 1),
        "control_qps": round(control_qps, 1),
        "scaling_eff": round(qps / (n_replicas * control_qps), 3),
        "rate_qps": float(rate_qps),
        "traced_qps": round(traced_qps, 1),
        "trace_overhead_pct": trace_overhead_pct,
        "trace_sampled": len(qts),
        "trace_schema_errors": len(trace_errs),
        "dominant_hop": dominant,
        "shed": shed_total, "requeued": requeued, "failed": failed,
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "sla_ms": sla_ms,
        "buckets": "/".join(str(b) for b in BUCKETS),
        "compiles": compiles, "swaps": swaps, "killed": 1,
        "wallclock_s": round(time.monotonic() - t_start, 3),
        "stopped": ("target" if failed == 0 and respawned
                    and swaps >= n_replicas
                    and compiles == len(BUCKETS) else None),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--row", default=None,
                    help="write the results row to this JSONL path")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="traffic window seconds (default 4)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--sla-ms", type=float, default=SLA_MS)
    ap.add_argument("--serveDtype", default="f32",
                    choices=("f32", "bf16", "int8"),
                    help="f32 = the canonical serving row; bf16/int8 = "
                         "the low-precision A/B row vs an f32 control")
    ap.add_argument("--ratio-bar", type=float, default=1.7,
                    help="qps_ratio bar for the A/B self-gate: 1.7 is "
                         "the acceptance bar a COMMITTED row must hold; "
                         "CI fresh re-runs pass a catastrophic floor "
                         "instead (shared-runner wall-clock)")
    ap.add_argument("--correctness-only", action="store_true",
                    help="skip the qps_ratio bar and gate only the "
                         "correctness axes (flips / compiles / swap): "
                         "the int8 A/B row commits under this — XLA's "
                         "CPU backend emulates int8 unpack, so its CPU "
                         "throughput is not the claim, the certificate "
                         "machinery is")
    ap.add_argument("--serveReplicas", type=int, default=0,
                    help="fleet mode: spawn this many REAL CLI scorer "
                         "replicas behind the router and measure "
                         "aggregate qps vs a 1-replica control "
                         "(the serve-cpu-fleet row)")
    ap.add_argument("--route", default="tenant",
                    choices=("rr", "tenant"),
                    help="fleet routing policy for the fleet row")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop offered rate (queries/s) for the "
                         "fleet overload window; 0 = 4x the measured "
                         "capacity")
    ap.add_argument("--trace-bar", type=float, default=TRACE_BAR_PCT,
                    help="max tracing-on qps overhead (%%) the fleet "
                         "A/B may show: the committed row holds the "
                         "default 5%% acceptance bar; CI fresh re-runs "
                         "pass a looser catastrophic bound "
                         "(shared-runner wall-clock)")
    args = ap.parse_args(argv)

    if args.serveReplicas >= 2:
        row = measure_fleet(args.serveReplicas, args.route,
                            args.duration, args.threads, args.sla_ms,
                            args.rate)
        print(json.dumps(row))
        if args.row:
            with open(args.row, "w") as f:
                f.write(json.dumps(row) + "\n")
        failures = []
        if row["failed"] != 0:
            failures.append(f"{row['failed']} failed queries — a dead "
                            f"replica must requeue, never fail")
        if row["compiles"] != len(BUCKETS):
            failures.append(f"compiles per replica process "
                            f"{row['compiles']} != {len(BUCKETS)} — "
                            f"the catalogue or the fleet broke the "
                            f"one-compile-per-(bucket, dtype) pin")
        if row["swaps"] < args.serveReplicas:
            failures.append(f"only {row['swaps']}/{args.serveReplicas} "
                            f"replicas observed the injected catalogue "
                            f"generation")
        if row["stopped"] != "target":
            failures.append("the SIGKILLed replica was not respawned "
                            "and folded back into routing")
        if row["trace_overhead_pct"] > args.trace_bar:
            failures.append(f"tracing overhead "
                            f"{row['trace_overhead_pct']:g}% over the "
                            f"{args.trace_bar:g}% bar — the per-line "
                            f"prefix peel or the sampled stamp/emit "
                            f"path got expensive")
        if row["trace_schema_errors"]:
            failures.append(f"{row['trace_schema_errors']} schema "
                            f"violations in the sampled query_trace "
                            f"stream")
        if row["dominant_hop"] is None:
            failures.append("no sampled query_trace assembled into a "
                            "waterfall — tracing went dark under the "
                            "committed 1-in-"
                            f"{TRACE_SAMPLE} sampling")
        for msg in failures:
            print(f"serve_bench FAIL: {msg}", file=sys.stderr)
        return 1 if failures else 0

    if args.serveDtype != "f32":
        print(f"serve_bench: {args.serveDtype} A/B at d={D_Q} "
              f"(f32 model 2.5 MB vs packed "
              f"{'1.25' if args.serveDtype == 'bf16' else '0.625'} MB)",
              flush=True)
        row = measure_quant(args.serveDtype, args.duration, args.sla_ms)
        print(json.dumps(row))
        if args.row:
            with open(args.row, "w") as f:
                f.write(json.dumps(row) + "\n")
        failures = []
        if (not args.correctness_only
                and row["qps_ratio"] < args.ratio_bar):
            failures.append(f"qps_ratio {row['qps_ratio']} < "
                            f"{args.ratio_bar:g} — the packed "
                            f"{args.serveDtype} path lost its "
                            f"cache-footprint win over f32")
        if row["flips"] != 0:
            failures.append(f"{row['flips']} sign flips at |m32| > 2x "
                            f"the certified bound "
                            f"{row['margin_err_bound']:.3e} — the "
                            f"certificate understated the error")
        if row["compiles"] != EXPECTED_COMPILES_Q:
            failures.append(f"{row['compiles']} scoring compiles, "
                            f"expected {EXPECTED_COMPILES_Q} (one per "
                            f"(bucket, dtype) per scorer)")
        if row["swaps"] < 1:
            failures.append("the mid-measure hot-swap never happened")
        if row["stopped"] != "target":
            failures.append("the quantized form was not the one served "
                            "(certificate fallback fired on synthetic "
                            "calibration — seed drift?)")
        for msg in failures:
            print(f"serve_bench FAIL: {msg}", file=sys.stderr)
        return 1 if failures else 0

    ck = tempfile.mkdtemp(prefix="serve-bench-")
    print(f"serve_bench: training the {N}x{D} model to gap "
          f"{GAP_TARGET:g}", flush=True)
    w_final, rounds, gap = train_checkpoints(ck)
    print(f"serve_bench: certified at round {rounds} (gap {gap:.3e}); "
          f"serving for {args.duration:g}s x {args.threads} clients",
          flush=True)
    row = measure(ck, w_final, rounds, gap, args.duration, args.threads,
                  args.sla_ms)
    print(json.dumps(row))
    if args.row:
        with open(args.row, "w") as f:
            f.write(json.dumps(row) + "\n")
    failures = []
    if row["p99_ms"] > args.sla_ms:
        failures.append(f"p99 {row['p99_ms']}ms exceeds the "
                        f"{args.sla_ms}ms SLA — the row is queries/s AT "
                        f"p99 <= SLA")
    if row["compiles"] != len(BUCKETS):
        failures.append(f"{row['compiles']} scoring compiles for "
                        f"{len(BUCKETS)} buckets — the "
                        f"one-compile-per-bucket contract broke")
    if row["swaps"] < 1:
        failures.append("the mid-bench hot-swap never happened")
    for msg in failures:
        print(f"serve_bench FAIL: {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
