"""The serving benchmark: queries/s at a pinned p99 latency bound, plus
model freshness (gap age), on CPU.

The headline claim of the ``--serve`` path (docs/DESIGN.md §17): batched
margin queries ride a compiled scoring path with statically-shaped
buckets — one XLA compile per bucket, ever — behind an adaptive
micro-batcher, while the model hot-swaps under traffic without dropping
a request.  The bench trains a small model to a certified gap, serves
it from real checkpoint generations (one mid-run hot-swap, so the swap
machinery is in the measured path), hammers the batcher from G client
threads for the duration, and reports

- ``qps``       — answered requests / wall-clock of the traffic window
- ``p50/p99_ms``— per-request latency percentiles (submit → answer),
  measured exactly (every request's own enqueue timestamp)
- ``sla_ms``    — the pinned bound: the run FAILS (exit 1) if p99
  exceeds it — the row is "queries/s AT p99 ≤ SLA", not queries/s alone
- ``gap_age_s`` — the serving model's certificate age at measurement
  end (freshness, the cocoa_model_gap_age_seconds gauge's value)
- ``compiles``  — measured XLA compiles of the scoring executable
  (must equal the bucket count: the one-compile-per-bucket pin)

    python benchmarks/serve_bench.py                 # print the row
    python benchmarks/serve_bench.py --row=out.jsonl # write it (CI gate)

Latency/qps are CPU-measured host wall-clock (no TPU column: serving
latency is dominated by dispatch+fetch, which the tunnel distorts —
the needs-TPU-regen convention applies to the wallclock the day a TPU
is attached).  benchmarks/check_regression.py gates the SLA, the
compile count, and a catastrophic-throughput floor against the
committed row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

CONFIG = "serve-cpu-synth"
# the canonical serving workload: a small certified model, sparse
# queries (nnz ~ 12 of d=256), two buckets, a 50 ms p99 SLA
N, D, K = 2048, 256, 2
LAM, GAP_TARGET = 1e-3, 1e-2
BUCKETS = (64, 256)
MAX_NNZ = 32
SLA_MS = 50.0
QUERY_NNZ = 12


def train_checkpoints(ck: str):
    """Train the model to its certified gap and leave TWO checkpoint
    generations (the second is the mid-bench hot-swap target)."""
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_dense
    from cocoa_tpu.solvers import run_cocoa

    data = synth_dense(N, D, seed=7)
    ds = shard_dataset(data, k=K, layout="dense")
    params = Params(n=N, num_rounds=300, local_iters=max(1, N // K // 10),
                    lam=LAM, gamma=1.0, loss="hinge")
    debug = DebugParams(debug_iter=10, seed=0, chkpt_iter=301,
                        chkpt_dir="")
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                               gap_target=GAP_TARGET)
    gap = traj.records[-1].gap if traj.records else None
    rounds = traj.records[-1].round if traj.records else 0
    w = np.asarray(w)
    # generation 1: the model the server starts on; generation 2: the
    # fresher state the watcher hot-swaps in mid-bench (a genuinely
    # different iterate — here the final w vs a perturbed older one)
    ckpt_lib.save(ck, "CoCoA+", max(1, rounds - 10),
                  (w * 0.95).astype(np.float32), None, gap=gap)
    return w.astype(np.float32), rounds, gap


def measure(ck, w_final, rounds, gap, duration_s: float, threads: int,
            sla_ms: float):
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import serving
    from cocoa_tpu.analysis import sanitize

    with sanitize.watch_compiles() as compiles:
        w0, info = serving.load_model(ckpt_lib.latest(ck, "CoCoA+"))
        slots = serving.ModelSlots(w0, info, dtype=np.float32)
        scorer = serving.BatchScorer(D, dtype=np.float32,
                                     buckets=BUCKETS, max_nnz=MAX_NNZ)
        scorer.warmup(slots.current()[0])
        batcher = serving.MicroBatcher(scorer, slots,
                                       sla_s=sla_ms / 1000.0)
        watcher = serving.SwapWatcher(slots, ck, "CoCoA+",
                                      poll_s=0.05).start()
        stop = threading.Event()
        lock = threading.Lock()
        lats = []
        failed = [0]

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                idx = np.sort(rng.choice(D, size=QUERY_NNZ,
                                         replace=False)).astype(np.int32)
                val = rng.standard_normal(QUERY_NNZ)
                t0 = time.monotonic()
                try:
                    batcher.score_sync(idx, val, timeout=10.0)
                except Exception:
                    with lock:
                        failed[0] += 1
                    continue
                with lock:
                    lats.append(time.monotonic() - t0)

        workers = [threading.Thread(target=client, args=(s,),
                                    daemon=True)
                   for s in range(threads)]
        t_start = time.monotonic()
        for t in workers:
            t.start()
        # the mid-bench hot-swap: the trainer "catches up" halfway in
        time.sleep(duration_s / 2)
        ckpt_lib.save(ck, "CoCoA+", rounds, w_final, None, gap=gap)
        time.sleep(duration_s / 2)
        stop.set()
        for t in workers:
            t.join(10)
        wall = time.monotonic() - t_start
        watcher.stop()
        gap_age = slots.gap_age_s()
        swaps = watcher.swaps_total
        batcher.stop()
    serve_compiles = sum(1 for c in compiles
                         if "serve_margins" in c.name)
    lats.sort()

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000.0

    return {
        "config": CONFIG, "type": "serve", "device": "cpu",
        "n": N, "d": D, "k": K, "lam": LAM,
        "gap": gap, "gap_target": GAP_TARGET, "rounds": int(rounds),
        "queries": len(lats), "threads": threads,
        "qps": round(len(lats) / wall, 1),
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "sla_ms": sla_ms,
        "fill": round(batcher.requests_total
                      / max(1, batcher.slots_total), 3),
        "buckets": "/".join(str(b) for b in BUCKETS),
        "compiles": serve_compiles, "swaps": swaps,
        "gap_age_s": round(gap_age, 3),
        "wallclock_s": round(wall, 3),
        "stopped": ("target" if failed[0] == 0 and swaps >= 1
                    else None),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--row", default=None,
                    help="write the results row to this JSONL path")
    ap.add_argument("--duration", type=float, default=4.0,
                    help="traffic window seconds (default 4)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--sla-ms", type=float, default=SLA_MS)
    args = ap.parse_args(argv)

    ck = tempfile.mkdtemp(prefix="serve-bench-")
    print(f"serve_bench: training the {N}x{D} model to gap "
          f"{GAP_TARGET:g}", flush=True)
    w_final, rounds, gap = train_checkpoints(ck)
    print(f"serve_bench: certified at round {rounds} (gap {gap:.3e}); "
          f"serving for {args.duration:g}s x {args.threads} clients",
          flush=True)
    row = measure(ck, w_final, rounds, gap, args.duration, args.threads,
                  args.sla_ms)
    print(json.dumps(row))
    if args.row:
        with open(args.row, "w") as f:
            f.write(json.dumps(row) + "\n")
    failures = []
    if row["p99_ms"] > args.sla_ms:
        failures.append(f"p99 {row['p99_ms']}ms exceeds the "
                        f"{args.sla_ms}ms SLA — the row is queries/s AT "
                        f"p99 <= SLA")
    if row["compiles"] != len(BUCKETS):
        failures.append(f"{row['compiles']} scoring compiles for "
                        f"{len(BUCKETS)} buckets — the "
                        f"one-compile-per-bucket contract broke")
    if row["swaps"] < 1:
        failures.append("the mid-bench hot-swap never happened")
    for msg in failures:
        print(f"serve_bench FAIL: {msg}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
