"""The fleet benchmark: models certified per second, fleet vs serial.

The headline claim of the --fleet path (docs/DESIGN.md §16): T
independent tenant problems (a log-spaced λ regularization path over T
distinct synthetic datasets — every tenant a DIFFERENT jit cache key on
the solo path) certify through ONE compiled vmapped round at ≥ 10× the
models-per-second of the same tenants run serially through the solo
device loop on CPU, from compile/dispatch amortization alone: the serial
control pays a fresh XLA compile per tenant (λ is baked into every solo
executable) plus a dispatch + fetch per super-block per tenant, while
the fleet pays one compile and one dispatch for everything.

    python benchmarks/fleet_bench.py                  # fleet + serial A/B
    python benchmarks/fleet_bench.py --fleet-only     # the CI-gate mode
    python benchmarks/fleet_bench.py --row=out.jsonl  # write the results row

Rounds and certified counts are backend-independent (the per-tenant
math is the solo math bit-for-bit in map mode and to float ulps in vmap
mode); the wallclock/speedup columns are CPU-measured and re-measured by
``--row`` runs.  benchmarks/check_regression.py gates the fleet-only
rounds + full certification against the committed baseline row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

CONFIG = "fleet-256-synth"
# the canonical fleet workload: T tenants, n=128 x d=64 planted-separator
# problems, λ log-spaced over two decades, one 1e-2 certificate target
N, D, K, FRAC = 128, 64, 2, 0.25
LAM_LO, LAM_HI = 3e-3, 1e-1
GAP_TARGET = 1e-2
ROUNDS, CADENCE = 400, 20


def build(tenants: int):
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.fleet import build_fleet, synth_fleet_specs

    specs = synth_fleet_specs(tenants, n=N, d=D, lam_lo=LAM_LO,
                              lam_hi=LAM_HI, gap_target=GAP_TARGET)
    fleet = build_fleet(specs, k=K, local_iter_frac=FRAC)
    params = Params(n=0, num_rounds=ROUNDS, local_iters=fleet.local_iters,
                    gamma=1.0, loss="hinge")
    debug = DebugParams(debug_iter=CADENCE, seed=0, chkpt_iter=ROUNDS + 1,
                        chkpt_dir="")
    return fleet, params, debug


def run_fleet(fleet, params, debug, lane_exec: str):
    from cocoa_tpu.analysis import sanitize
    from cocoa_tpu.solvers.fleet import run_cocoa_fleet

    t0 = time.perf_counter()
    with sanitize.sanitizer(strict=False) as stats:
        res = run_cocoa_fleet(fleet, params, debug, plus=True,
                              drive_mode="plain", lane_exec=lane_exec,
                              quiet=True)
    wall = time.perf_counter() - t0
    return res, wall, stats.compile_count("run")


def run_serial(fleet, params, debug):
    """The same tenants through the solo device loop, one at a time —
    the per-tenant compile + per-block dispatch/fetch cost the fleet
    amortizes away.  (The per-tenant λ is part of every solo executable's
    cache key, so each tenant pays a fresh XLA compile — exactly the
    production cost of a λ-path sweep today.)"""
    import dataclasses

    from cocoa_tpu.solvers import run_cocoa

    t0 = time.perf_counter()
    certified = 0
    total_rounds = 0
    # jaxlint: allow=fleet-hygiene -- this serial tenant loop IS the
    # measured anti-pattern (the A/B control the fleet is gated against)
    for ti in range(fleet.t):
        ds = fleet.tenant_ds(ti)
        sp = dataclasses.replace(params, n=ds.n,
                                 lam=float(fleet.lams[ti]))
        _, _, traj = run_cocoa(ds, sp, debug, plus=True,
                               gap_target=GAP_TARGET, device_loop=True,
                               quiet=True)
        if traj.stopped == "target":
            certified += 1
        total_rounds += traj.records[-1].round if traj.records else ROUNDS
    wall = time.perf_counter() - t0
    return certified, total_rounds, wall


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tenants", type=int, default=256)
    ap.add_argument("--fleet-only", action="store_true",
                    help="skip the serial control (the CI-gate mode)")
    ap.add_argument("--lanes", default="vmap", choices=("vmap", "map"))
    ap.add_argument("--row", default=None,
                    help="write the benchmarks-results row here")
    args = ap.parse_args(argv)

    fleet, params, debug = build(args.tenants)
    res, fleet_wall, compiles = run_fleet(fleet, params, debug, args.lanes)
    certified = int(res.certified.sum())
    fleet_mps = certified / max(fleet_wall, 1e-9)
    print(f"fleet:  {certified}/{fleet.t} certified, "
          f"{res.rounds_run} rounds, {fleet_wall:.1f}s, "
          f"{fleet_mps:.2f} models/s, {compiles} compile(s)")

    row = {
        "config": CONFIG, "type": "fleet",
        "tenants": int(fleet.t), "certified": certified,
        "rounds": int(res.rounds_run),
        "gap": float(res.final_gap.max()),
        "stopped": "target" if certified == fleet.t else None,
        "gap_target": GAP_TARGET,
        "models_per_second": round(fleet_mps, 3),
        "wallclock_s": round(fleet_wall, 3),
        "compiles": int(compiles),
        "lam_lo": LAM_LO, "lam_hi": LAM_HI,
        "drive_mode": "plain", "lane_exec": args.lanes,
        "n": N, "d": D, "k": K,
        "device": "cpu",
    }
    if not args.fleet_only:
        s_cert, s_rounds, s_wall = run_serial(fleet, params, debug)
        serial_mps = s_cert / max(s_wall, 1e-9)
        row["serial_models_per_second"] = round(serial_mps, 3)
        row["speedup"] = round(fleet_mps / max(serial_mps, 1e-9), 2)
        print(f"serial: {s_cert}/{fleet.t} certified, {s_rounds} total "
              f"rounds, {s_wall:.1f}s, {serial_mps:.2f} models/s")
        print(f"speedup: {row['speedup']}x models/s "
              f"(fleet {fleet_mps:.2f} vs serial {serial_mps:.2f})")

    if args.row:
        with open(args.row, "w") as f:
            f.write(json.dumps(row) + "\n")
        from cocoa_tpu.telemetry import schema as tele_schema

        errs = tele_schema.check_file(args.row, kind="results")
        if errs:
            print(f"results row failed schema: {errs}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
