"""Inner-kernel round-time comparison, slope-measured.

The whole-run wall-clocks in RESULTS.md are the BASELINE-relevant metric
(time to the duality-gap certificate) but, through a tunneled device, carry
seconds of run-to-run dispatch/fetch variance — more than the kernels'
entire compute.  This suite isolates per-round kernel time by the slope
method: each kernel executes chunks of 50 and 200 identical rounds inside
one dispatch each (the chunked driver), the result is fetched to host (the
only honest completion barrier through the tunnel), and

    ms_per_round = (t_200 - t_50) / 150

cancels every fixed cost.  Best of 3 per point.

Configs: the epsilon-like dense problem and the rcv1-like sparse problem
from benchmarks/run.py, CoCoA+ (the flagship).  Kernels:

- ``fori``       — fast-math margins decomposition, XLA fori_loop steps
- ``pallas-seq`` — the sequential Pallas kernels (dense folded-row /
                   sparse lane-blocked), shard-interleaved
- ``block-B``    — the block-coordinate MXU kernel (--blockSize=B,
                   ops/pallas_chain.py lockstep chain)

Writes benchmarks/KERNELS.md + kernel rows into results.jsonl-style lines
on stdout.  Run: ``python benchmarks/kernels.py`` (real TPU).
"""

from __future__ import annotations

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cocoa_tpu.utils import compile_cache

compile_cache.enable()   # persistent XLA cache: regen compiles once, ever


def measure(ds, params, k, *, c_lo=50, c_hi=200, reps=3, rng="reference",
            **kw):
    import jax.numpy as jnp

    from cocoa_tpu.solvers.base import IndexSampler
    from cocoa_tpu.solvers.cocoa import _alg_config, make_chunk_step

    alg = _alg_config(params, k, True)
    sampler = IndexSampler(rng, 0, params.local_iters, ds.counts)
    i_lo = sampler.chunk_indices(1, c_lo)
    i_hi = sampler.chunk_indices(1, c_hi)
    sa = ds.shard_arrays()
    if kw.get("pallas") and ds.layout == "dense":
        from cocoa_tpu.ops.pallas_sdca import fold_rows

        sa = {**sa, "X_folded": fold_rows(sa["X"])}
    if (kw.get("pallas") or kw.get("block")) and ds.layout == "sparse":
        from cocoa_tpu.ops.pallas_sparse import row_lengths

        sa = {**sa, "sp_row_len": row_lengths(sa["sp_values"])}
    step = make_chunk_step(None, params, k, alg, math="fast", **kw)
    d = ds.num_features

    def run(idxs):
        w = jnp.zeros(d, jnp.float32)
        a = jnp.zeros((k, ds.n_shard), jnp.float32)
        w, a = step(w, a, idxs, sa)
        return float(w.sum())   # host fetch: the only real barrier

    run(i_lo)
    run(i_hi)

    def t(idxs):
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            run(idxs)
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return best

    return (t(i_hi) - t(i_lo)) / (c_hi - c_lo)


def main():
    import jax
    import jax.numpy as jnp  # noqa: F401

    import perf
    from cocoa_tpu.config import Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_dense_sharded, synth_sparse

    rows = []

    def add(config, kernel, ds, params, k, *, layout, nnz, path, block=0,
            max_nnz=None, n_hot=0, coverage=0.0, **kw):
        if block:
            kw["block"] = block   # the parts-layer kwarg drives the kernel
        secs = measure(ds, params, k, **kw)
        model = perf.sdca_round_model(params.n, ds.num_features, k,
                                      params.local_iters, layout=layout,
                                      nnz=nnz, path=path, block=block,
                                      max_nnz=max_nnz, n_hot=n_hot,
                                      coverage=coverage)
        row = perf.account(f"{config}/{kernel}", secs, model,
                           steps=k * params.local_iters)
        rows.append(row)
        print(json.dumps(row))

    n, d, k = 400_000, 2000, 8
    eps = synth_dense_sharded(n, d, k, seed=0)
    p_eps = Params(n=n, num_rounds=400, local_iters=n // k // 10, lam=1e-3)
    add("epsilon", "fori", eps, p_eps, k, layout="dense", nnz=None,
        path="fast", pallas=False)
    add("epsilon", "pallas-seq", eps, p_eps, k, layout="dense", nnz=None,
        path="pallas", pallas=True)
    # B sweep under the fused-fits accounting — the measured ranking
    # behind --blockSize=auto (pallas_chain.BLOCK_SIZE_PREFERENCE).  At
    # this shape B=128 rides the fused kernel; B=256 fails fused_fits
    # (the half-tile is ~29 MB against the 14 MB budget) and takes the
    # split path (XLA einsums + chain-only kernel); B=512 additionally
    # fails chain_fits and falls all the way to the XLA fori chain —
    # each row measures exactly the path the auto dispatch would run.
    for b, chain in ((128, "pallas"), (256, "pallas"), (512, "xla")):
        add("epsilon", f"block-{b}", eps, p_eps, k, layout="dense",
            nnz=None, path="block", block=b, pallas=False,
            block_chain=chain)
    # pipelined-vs-serial A/B: block-128 above runs the two-phase
    # software-pipelined scan (the default — block b+1's row-tile gather
    # overlapped with block b's chain kernel); this row pins the serial
    # schedule so the overlap win is a measured number, not an inference
    # (bit-identical trajectories, tests/test_block.py)
    add("epsilon", "block-128-serial", eps, p_eps, k, layout="dense",
        nnz=None, path="block", block=128, pallas=False,
        block_chain="pallas", block_pipeline=False)
    # round 5: the distinctness-licensed glue elimination (permuted
    # sampling, one α scatter + one merged (y,q,α₀) gather per round —
    # docs/DESIGN.md §3b-iii).  Same math; the index stream differs from
    # the reference-rng rows above, but the kernels are value- and
    # index-independent in time, so the per-round comparison holds.
    add("epsilon", "block-128-distinct", eps, p_eps, k, layout="dense",
        nnz=None, path="block", block=128, pallas=False,
        block_chain="pallas", rng="permuted", block_distinct=True)
    add("epsilon", "block-128-distinct-serial", eps, p_eps, k,
        layout="dense", nnz=None, path="block", block=128, pallas=False,
        block_chain="pallas", rng="permuted", block_distinct=True,
        block_pipeline=False)

    n2, d2 = 20242, 47236
    data = synth_sparse(n2, d2, nnz_mean=75, seed=0)
    rc = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32)
    nnz = len(data.values) / n2
    p_rc = Params(n=n2, num_rounds=1500, local_iters=n2 // k // 10,
                  lam=1e-4)
    add("rcv1", "fori", rc, p_rc, k, layout="sparse", nnz=nnz,
        path="fast", pallas=False)
    add("rcv1", "pallas-seq", rc, p_rc, k, layout="sparse", nnz=nnz,
        path="pallas", pallas=True)
    add("rcv1", "block-128", rc, p_rc, k, layout="sparse", nnz=nnz,
        path="block", block=128, pallas=False, block_chain="pallas",
        block_sparse_gram=False)
    # the sparse block-chain kernel: in-kernel (B, B) Gram from the SMEM
    # CSR streams + sparse Δw scatter (ops/pallas_sparse) feeding the same
    # lockstep chain — no (K, B, d) densify (block-128 above keeps the
    # densified path for the A/B)
    add("rcv1", "sparse-block", rc, p_rc, k, layout="sparse", nnz=nnz,
        path="sparse-block", block=128, pallas=False, block_chain="pallas",
        block_sparse_gram=True,
        max_nnz=int(rc.sp_indices.shape[-1]))
    # the hot/cold column split (--hotCols, round 10): the hottest ~2k
    # columns move into a dense MXU panel; the scalar-issue-bound stream
    # merges (97.8% of the measured round) run only the cold residual.
    # hybrid-seq A/Bs against pallas-seq, hybrid-block against
    # sparse-block — same sampled streams, same math (trajectory parity
    # pinned by tests/test_hybrid_sparse.py); the calibrated latency
    # model (perf.predict_sparse_round_ms) expects the seq round to drop
    # from the measured 6.16 ms to ~2.2 ms at 75% coverage.
    from cocoa_tpu.data.hybrid import resolve_hot_cols

    n_hot, split = resolve_hot_cols("auto", data, k, jnp.float32)
    rc_h = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32,
                         hot_cols=n_hot)
    print(json.dumps({"config": "rcv1/hot-split", **{
        kk: split[kk] for kk in ("hot_cols", "coverage",
                                 "residual_mean_nnz", "residual_max_nnz",
                                 "panel_bytes")}}))
    add("rcv1", "hybrid-seq", rc_h, p_rc, k, layout="sparse", nnz=nnz,
        path="hybrid-seq", pallas=True,
        n_hot=n_hot, coverage=split["coverage"])
    add("rcv1", "hybrid-block", rc_h, p_rc, k, layout="sparse", nnz=nnz,
        path="hybrid-block", block=128, pallas=False, block_chain="pallas",
        block_sparse_gram=True, max_nnz=int(rc_h.sp_indices.shape[-1]),
        n_hot=n_hot, coverage=split["coverage"])

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "KERNELS.md")
    cols = ["config", "device", "ms_per_round", "us_per_step",
            "useful_gflops", "physical_gflops", "mfu_pct",
            "physical_mfu_pct", "hbm_floor_ms", "bound"]
    with open(out, "w") as f:
        f.write(
            "# Inner-kernel round times (slope-measured)\n\n"
            "Produced by `python benchmarks/kernels.py` on the attached "
            "TPU.  Per-round time via the 50-vs-200-round slope (fixed "
            "dispatch/fetch costs cancel; best of 3) — the controlled "
            "companion to RESULTS.md's whole-run wall-clocks, which carry "
            "seconds of tunnel variance.  `us_per_step` is the amortized "
            "per-coordinate critical path across the K parallel shards; "
            "accounting per benchmarks/perf.py.\n\n"
        )
        f.write("| " + " | ".join(cols) + " |\n")
        f.write("|" + "---|" * len(cols) + "\n")
        for r in rows:
            f.write("| " + " | ".join(str(r.get(c, "")) for c in cols)
                    + " |\n")
        eps_rows = {r["config"]: r["ms_per_round"] for r in rows}
        seq = eps_rows.get("epsilon/pallas-seq")
        # the -serial rows are the pipelining A/B controls — never the
        # headline, even when tunnel noise ranks one marginally fastest
        contender = lambda c: (c.startswith("epsilon/block")  # noqa: E731
                               and not c.endswith("-serial"))
        blk = min(v for c, v in eps_rows.items() if contender(c))
        if seq and blk:
            best = min(eps_rows, key=lambda c: eps_rows[c]
                       if contender(c) else 1e9)
            stream = ("its permuted index stream (distinctness licenses "
                      "the merged gather / single α scatter; "
                      "reference-stream rows above share the exact "
                      "reference draws)" if "distinct" in best
                      else "the same sampled index stream")
            f.write(
                f"\nHeadline: the block-coordinate kernel ({best.split('/')[1]}) "
                f"runs the epsilon round in {blk} ms vs the sequential "
                f"Pallas kernel's {seq} ms — **{seq / blk:.2f}x** — with "
                f"{stream}, same math (trajectory parity pinned by "
                f"tests/test_block.py).\n"
            )
        pip = eps_rows.get("epsilon/block-128")
        ser = eps_rows.get("epsilon/block-128-serial")
        dpip = eps_rows.get("epsilon/block-128-distinct")
        dser = eps_rows.get("epsilon/block-128-distinct-serial")
        if pip and ser:
            f.write(
                f"\nPipelined-vs-serial A/B (the two-phase block scan — "
                f"block b+1's row-tile gather overlapped with block b's "
                f"chain kernel, ops/local_sdca.local_sdca_block_batched "
                f"``pipeline``): reference-rng {ser} → {pip} ms/round "
                f"(**{ser / pip:.2f}x**)"
                + (f"; permuted+distinct {dser} → {dpip} ms/round "
                   f"(**{dser / dpip:.2f}x**)" if dpip and dser else "")
                + ".  Bit-identical schedules (tests/test_block.py); the "
                  "serial rows exist only as the A/B control.\n"
            )
        rseq = eps_rows.get("rcv1/pallas-seq")
        rdense = eps_rows.get("rcv1/block-128")
        rsp = eps_rows.get("rcv1/sparse-block")
        if rseq and rsp:
            f.write(
                f"\nOn rcv1's sparse layout the densified block path "
                f"(`block-128`: {rdense} ms) loses to the sequential "
                f"kernel ({rseq} ms); the sparse block-chain kernel "
                f"(`sparse-block`: {rsp} ms — in-kernel Gram from the "
                f"SMEM CSR streams, no (B, d) densify, "
                f"ops/pallas_sparse.py) is the sparse `--blockSize` "
                f"path: {rdense / rsp:.2f}x over the densified blocks, "
                f"{rseq / rsp:.2f}x vs sequential.  `--blockSize=auto` "
                f"picks the right kernel per layout.\n"
            )
        hseq = eps_rows.get("rcv1/hybrid-seq")
        hblk = eps_rows.get("rcv1/hybrid-block")
        if rseq and hseq:
            # predicted from the SAME resolved split the rows above ran
            pred = perf.predict_sparse_round_ms(
                k * p_rc.local_iters, nnz, n_hot=n_hot,
                coverage=split["coverage"])
            f.write(
                f"\nHot/cold split A/B (`--hotCols=auto`, docs/DESIGN.md "
                f"§3b-vi): `hybrid-seq` {hseq} ms vs `pallas-seq` {rseq} "
                f"ms (**{rseq / hseq:.2f}x**)"
                + (f"; `hybrid-block` {hblk} ms vs `sparse-block` {rsp} "
                   f"ms (**{rsp / hblk:.2f}x**)" if hblk and rsp else "")
                + f".  The calibrated slot-latency model predicted "
                  f"~{pred:.1f} ms for the hybrid seq round "
                  f"(perf.predict_sparse_round_ms).  Same sampled "
                  f"streams, same math — the split permutes each "
                  f"per-nonzero sum (tests/test_hybrid_sparse.py); "
                  f"`--hotCols=off` is the bit-exact stream control.\n"
            )
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
