#!/usr/bin/env bash
# Fetch the real benchmark datasets (rcv1_train.binary, epsilon_normalized)
# from the LIBSVM dataset mirror into benchmarks/data/, so benchmarks/run.py
# prefers them over the synthetic stand-ins (rows then read rcv1(real) /
# epsilon(real)).
#
# Integrity: this repo is built on an air-gapped machine, so upstream
# sha256 digests cannot be pinned here ahead of time.  Instead:
#   - trust-on-first-use: the first successful download records each file's
#     sha256 into benchmarks/data.sha256 (commit it!); every later fetch
#     verifies against the recorded digest and fails loudly on mismatch.
#   - shape pins: benchmarks/run.py additionally validates the PUBLISHED
#     dataset shapes (rcv1_train.binary: n=20,242 d=47,236; epsilon:
#     n=400,000 d=2,000) at load time, so a wrong/corrupt file cannot
#     silently stand in even on the very first use.
#
# Usage:  bash benchmarks/fetch_data.sh [rcv1|epsilon|all]
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
DATA="$HERE/data"
SUMS="$HERE/data.sha256"
BASE="https://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/binary"
mkdir -p "$DATA"

fetch() {
    local name="$1"           # remote file name (.bz2)
    local out="$DATA/${name%.bz2}"
    if [[ -f "$out" ]]; then
        # verify the DECOMPRESSED file — the one benchmarks actually
        # consume, and the one still around after the .bz2 is deleted
        echo "already present: $out"
        verify "$(basename "$out")"
        return
    fi
    echo "fetching $BASE/$name ..."
    curl -fL --retry 3 -o "$DATA/$name" "$BASE/$name" \
        || wget -O "$DATA/$name" "$BASE/$name"
    echo "decompressing ..."
    bunzip2 -kf "$DATA/$name"
    verify "$(basename "$out")"
    echo "ready: $out  (the .bz2 may be deleted; the digest covers $out)"
}

# Minimum plausible decompressed sizes — a first-use defense independent
# of the download being honest (ADVICE r3: TOFU alone trusts a
# compromised first fetch).  These are deliberately lower bounds, not
# exact pins: this machine is air-gapped, so an exact published byte count
# cannot be confirmed here, and a wrong exact pin would reject good files.
# Truncated/partial downloads (the realistic corruption) fall far below
# these; a same-size wrong file is caught by run.py's (n, d, nnz/row)
# pins at load time.
size_pin() {
    local name="$1" bytes="$2"
    local min=0
    case "$name" in
        rcv1_train.binary)   min=8000000    ;;  # full file is tens of MB
        epsilon_normalized)  min=8000000000 ;;  # full file is ~12 GB
    esac
    if (( min > 0 && bytes < min )); then
        echo "size MISMATCH for $name: got $bytes bytes, expected at" \
             "least $min — truncated or wrong file" >&2
        exit 1
    fi
    echo "size ok: $name ($bytes bytes >= $min)"
}

verify() {
    local name="$1"           # decompressed file name
    local got
    size_pin "$name" "$(stat -c%s "$DATA/$name")"
    got="$(sha256sum "$DATA/$name" | cut -d' ' -f1)"
    if grep -q " $name\$" "$SUMS" 2>/dev/null; then
        local want
        want="$(grep " $name\$" "$SUMS" | cut -d' ' -f1)"
        if [[ "$got" != "$want" ]]; then
            echo "sha256 MISMATCH for $name:" >&2
            echo "  recorded $want" >&2
            echo "  got      $got" >&2
            exit 1
        fi
        echo "sha256 ok: $name"
    else
        echo "$got  $name" >> "$SUMS"
        echo "recorded sha256 (trust-on-first-use): $got  $name"
        echo ">> commit $SUMS so later fetches verify against it"
    fi
}

case "${1:-all}" in
    rcv1)    fetch rcv1_train.binary.bz2 ;;
    epsilon) fetch epsilon_normalized.bz2 ;;
    all)     fetch rcv1_train.binary.bz2; fetch epsilon_normalized.bz2 ;;
    *) echo "usage: $0 [rcv1|epsilon|all]" >&2; exit 2 ;;
esac
