// Fast LIBSVM text parser for cocoa_tpu.
//
// Native-runtime counterpart of the Spark loader (reference:
// OptUtils.scala:11-53).  Semantics match the Python oracle in
// cocoa_tpu/data/libsvm.py exactly:
//   - label token containing '+' or equal to 1 -> +1, else -1
//     (OptUtils.scala:35-37)
//   - 1-based idx:val pairs -> 0-based indices (OptUtils.scala:42)
//
// Two-pass C ABI consumed via ctypes (cocoa_tpu/data/native_loader.py):
//
//   cocoa_libsvm_count(path, &rows, &pairs)  -> upper bounds ('\n' and ':'
//                                               counts; cheap memchr scan)
//   cocoa_libsvm_parse(path, labels, indptr, indices, values,
//                      cap_rows, cap_pairs,
//                      &rows, &pairs)        -> writes DIRECTLY into the
//                                               caller-allocated (numpy)
//                                               buffers, never past the
//                                               given capacities; outputs
//                                               actual row/pair counts
//
// Memory strategy (multi-GB inputs; see native/README.md): the file is
// mmap'd read-only and parsed in place — no text copy, no intermediate
// growable buffers, no copy-out — with MADV_SEQUENTIAL readahead, and
// each consumed 16 MB window released with MADV_DONTNEED so resident text
// stays bounded regardless of file size.  The parse never writes to the
// mapping (the classic '\0'-at-eol trick would COW-dirty every page);
// number parsing is bounded per line instead, and a final line without a
// trailing newline is bounced through a small NUL-terminated copy so
// strtod can never read past the mapping.  Peak RSS is therefore ~the
// parsed CSR arrays alone (~0.8x the text for typical idx:val widths).
// Non-regular files (pipes) are rejected (count returns -1) — the Python
// parser handles those.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#ifndef _WIN32
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#endif

#ifndef __GLIBC__
// memrchr is a GNU extension; portable fallback for other libcs (macOS)
static const void* cocoa_memrchr(const void* s, int c, size_t n) {
  const unsigned char* p = static_cast<const unsigned char*>(s);
  while (n--) {
    if (p[n] == static_cast<unsigned char>(c)) return p + n;
  }
  return nullptr;
}
#define memrchr cocoa_memrchr
#endif

namespace {

struct Sink {
  double* labels;
  int64_t* indptr;
  int32_t* indices;
  double* values;
  int64_t cap_rows;   // hard bounds: a file that GROWS between the count
  int64_t cap_pairs;  // and parse passes must truncate, never overflow
  int64_t* row_off = nullptr;  // optional: absolute byte offset of each
                               // row's line start (the streaming-ingest
                               // row index; nullptr = don't record)
  int64_t rows = 0;
  int64_t pairs = 0;
  bool truncated = false;
};

// Shared numeric grammar (see cocoa_tpu/data/libsvm.py _NUM_CHARS): plain
// ASCII decimal only.  strtod additionally accepts hex floats, "nan(...)"
// and "inf", which Python's float() rejects — restricting both sides to
// this character class makes token validity independent of which parser
// ran.
inline bool is_num_char(char c) {
  return (c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.' ||
         c == 'e' || c == 'E';
}

// Label rule per OptUtils.scala:35-37 ('+' anywhere in the token, or the
// token parsing to 1 under the shared decimal grammar, means +1;
// everything else silently -1).
double parse_label(const char* tok, const char* end) {
  for (const char* p = tok; p < end; ++p) {
    if (*p == '+') return 1.0;
  }
  for (const char* p = tok; p < end; ++p) {
    if (!is_num_char(*p)) return -1.0;
  }
  char* stop = nullptr;
  double v = strtod(tok, &stop);
  // whole-token parse required, like Python float(): "1junk" is -1
  return (stop == end && v == 1.0) ? 1.0 : -1.0;
}

// True for every whitespace byte strtol/strtod would skip (isspace in the
// C locale).  The manual skip loops below must cover this exact set:
// any whitespace they leave in place would let strtol/strtod's own
// leading-whitespace skip run PAST '\n' into the next line (misparse) or
// past the region end (OOB read on an exactly-page-sized mapping).
inline bool is_ws(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

// Parse the lines in [p, fend) into the sink.  Every line in the region
// MUST be newline-terminated or the region itself NUL-terminated (the
// caller guarantees one or the other): strtol/strtod stop at '\n'
// naturally, and the per-pair loop only ever starts a number at a
// non-whitespace byte strictly before the line end (whitespace after
// 'idx:' is treated as a malformed tail), so the parse cannot escape the
// region.
// ``abs_off`` is the absolute file offset of ``p`` (the tail of an
// unterminated final line parses from a bounced copy, so pointer
// arithmetic alone cannot recover file positions for the row index).
void parse_region(const char* p, const char* fend, int64_t abs_off,
                  Sink* out) {
  const char* region_base = p;
  while (p < fend) {
    if (out->rows >= out->cap_rows) {
      out->truncated = true;
      return;
    }
    const char* line_start = p;
    const char* eol = static_cast<const char*>(memchr(p, '\n', fend - p));
    if (!eol) eol = fend;

    // skip leading whitespace; blank lines are skipped entirely
    while (p < eol && is_ws(*p)) ++p;
    if (p < eol) {
      // label token ends at first whitespace
      const char* sp = p;
      while (sp < eol && !is_ws(*sp)) ++sp;
      out->labels[out->rows] = parse_label(p, sp);
      if (out->row_off)
        out->row_off[out->rows] = abs_off + (line_start - region_base);

      // idx:val pairs
      p = sp;
      while (p < eol) {
        while (p < eol && is_ws(*p)) ++p;
        if (p >= eol) break;
        char* stop = nullptr;
        // strtoll, not strtol: on 32-bit-long platforms strtol clamps an
        // overflowing index to LONG_MAX == INT32_MAX and the range guard
        // below would wave it through as a valid aliased index
        long long idx = strtoll(p, &stop, 10);
        if (stop == p || stop > eol) break;  // malformed / ran past eol
        if (stop == eol || *stop != ':') break;  // malformed
        // 1-based index must land in int32 after the -1 shift (idx<1 and
        // strtoll's ERANGE clamp included — LLONG_MAX fails the test):
        // out of range = malformed tail, matching the Python oracle — a
        // silent cast would alias huge indices onto valid features
        if (idx < 1 || idx - 1 > INT32_MAX) break;
        p = stop + 1;
        if (p >= eol) break;  // "idx:" at line end: malformed tail
        if (is_ws(*p)) break;  // "idx: val": strtod would skip past '\n'
        // value must lie entirely within the shared decimal grammar —
        // rejects hex floats / nan / inf up front so strtod cannot accept
        // a form the Python oracle would drop
        const char* vend = p;
        while (vend < eol && is_num_char(*vend)) ++vend;
        if (vend == p) break;  // empty or non-decimal value
        double val = strtod(p, &stop);
        if (stop != vend || stop > eol) break;  // partial parse = junk
        // junk glued to the value ("1:2.0x", "1:2:3"): malformed — pairs
        // are whitespace-delimited, matching the Python oracle's
        // token.partition(':') rule
        if (stop < eol && !is_ws(*stop)) break;
        p = stop;
        if (out->pairs >= out->cap_pairs) {
          out->truncated = true;
          break;
        }
        out->indices[out->pairs] = static_cast<int32_t>(idx - 1);  // 1->0
        out->values[out->pairs] = val;
        ++out->pairs;
      }
      ++out->rows;
      out->indptr[out->rows] = out->pairs;
    }
    p = eol + 1;
  }
}

#ifndef _WIN32
struct Mapping {
  char* buf = nullptr;
  size_t size = 0;
  bool ok = false;
};

Mapping map_file(const char* path) {
  Mapping m;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return m;
  struct stat st;
  if (fstat(fd, &st) != 0 || !S_ISREG(st.st_mode)) {
    close(fd);
    return m;
  }
  m.size = static_cast<size_t>(st.st_size);
  if (m.size == 0) {
    close(fd);
    m.ok = true;  // empty regular file: zero rows, valid
    return m;
  }
  m.buf = static_cast<char*>(
      mmap(nullptr, m.size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (m.buf == MAP_FAILED) {
    m.buf = nullptr;
    return m;
  }
  m.ok = true;
  madvise(m.buf, m.size, MADV_SEQUENTIAL);
  return m;
}
#endif

constexpr size_t kWindow = size_t(16) << 20;

#ifndef _WIN32
// Resolve a raw byte range [lo, hi) to the line-aligned span [s_lo, s_hi)
// it OWNS under the streaming-ingest ownership rule: a line belongs to the
// range containing its first byte.  s_lo is the first line start >= lo
// (lo itself when lo == 0 or the previous byte is '\n'); the last owned
// line (start < hi) is parsed to ITS end, so s_hi runs to the first '\n'
// at or past hi-1 (or EOF).  Ranges that tile the file therefore yield
// spans that tile the newline structure exactly — no row parsed twice,
// none skipped, regardless of where the raw split lands (mid-line, inside
// a malformed tail, on a lone '\r', inside a run of blank lines).
// Returns false when the range owns no lines.
bool resolve_span(const char* buf, size_t size, int64_t lo, int64_t hi,
                  size_t* s_lo, size_t* s_hi) {
  if (lo < 0) lo = 0;
  if (hi > static_cast<int64_t>(size)) hi = static_cast<int64_t>(size);
  if (lo >= hi) return false;
  size_t start;
  if (lo == 0) {
    start = 0;
  } else {
    const char* nl = static_cast<const char*>(
        memchr(buf + (lo - 1), '\n', size - (lo - 1)));
    if (!nl) return false;
    start = static_cast<size_t>(nl - buf) + 1;
  }
  if (start >= static_cast<size_t>(hi)) return false;
  const char* nl2 = static_cast<const char*>(
      memchr(buf + (hi - 1), '\n', size - (hi - 1)));
  *s_lo = start;
  *s_hi = nl2 ? static_cast<size_t>(nl2 - buf) + 1 : size;
  return true;
}

// Windowed parse of the line-aligned span [s_lo, s_hi): the newline-
// terminated body parses in place (consumed pages released with
// MADV_DONTNEED), and a final unterminated line (only possible when the
// span ends at EOF) is bounced through a NUL-terminated copy so strtod
// can never read past the mapping.
void parse_span(const Mapping& m, size_t s_lo, size_t s_hi, Sink* sink) {
  const char* fend = m.buf + s_hi;
  const char* last_nl = static_cast<const char*>(
      memrchr(m.buf + s_lo, '\n', s_hi - s_lo));
  const char* main_end = last_nl ? last_nl + 1 : m.buf + s_lo;
  const char* p = m.buf + s_lo;
  while (p < main_end) {
    const char* wend = p + kWindow;
    if (wend >= main_end) {
      wend = main_end;
    } else {
      wend = static_cast<const char*>(memrchr(p, '\n', wend - p));
      wend = wend ? wend + 1 : main_end;  // pathological: one huge line
    }
    parse_region(p, wend, static_cast<int64_t>(p - m.buf), sink);
    // drop only the newly-consumed pages (page-aligned inner range)
    const long page = sysconf(_SC_PAGESIZE);
    uintptr_t plo = (reinterpret_cast<uintptr_t>(p) + page - 1)
                    / page * page;
    uintptr_t phi = reinterpret_cast<uintptr_t>(wend) / page * page;
    if (phi > plo)
      madvise(reinterpret_cast<void*>(plo), phi - plo, MADV_DONTNEED);
    p = wend;
  }
  if (main_end < fend) {
    size_t tail = fend - main_end;
    char* tbuf = static_cast<char*>(malloc(tail + 1));
    if (tbuf) {
      memcpy(tbuf, main_end, tail);
      tbuf[tail] = '\0';
      parse_region(tbuf, tbuf + tail,
                   static_cast<int64_t>(main_end - m.buf), sink);
      free(tbuf);
    }
  }
}

// Count '\n' and ':' within [s_lo, s_hi) (windowed, pages released).
void count_span(const Mapping& m, size_t s_lo, size_t s_hi,
                int64_t* newlines, int64_t* colons) {
  *newlines = 0;
  *colons = 0;
  for (size_t off = s_lo; off < s_hi; off += kWindow) {
    size_t len = s_hi - off < kWindow ? s_hi - off : kWindow;
    const char* q = m.buf + off;
    const char* qe = q + len;
    while ((q = static_cast<const char*>(memchr(q, ':', qe - q)))) {
      ++*colons;
      ++q;
    }
    q = m.buf + off;
    while ((q = static_cast<const char*>(memchr(q, '\n', qe - q)))) {
      ++*newlines;
      ++q;
    }
    madvise(m.buf + off, len, MADV_DONTNEED);
  }
}
#endif

}  // namespace

extern "C" {

// Upper-bound counts for buffer allocation: rows <= newlines + 1 (final
// unterminated line), pairs <= ':' count.  Returns 0 on success, -1 when
// the file cannot be mmap'd (missing / non-regular — callers fall back).
int cocoa_libsvm_count(const char* path, int64_t* rows_out,
                       int64_t* pairs_out) {
#ifndef _WIN32
  Mapping m = map_file(path);
  if (!m.ok) return -1;
  int64_t colons = 0, newlines = 0;
  for (size_t off = 0; off < m.size; off += kWindow) {
    size_t len = m.size - off < kWindow ? m.size - off : kWindow;
    const char* q = m.buf + off;
    const char* qe = q + len;
    while ((q = static_cast<const char*>(memchr(q, ':', qe - q)))) {
      ++colons;
      ++q;
    }
    q = m.buf + off;
    while ((q = static_cast<const char*>(memchr(q, '\n', qe - q)))) {
      ++newlines;
      ++q;
    }
    madvise(m.buf + off, len, MADV_DONTNEED);
  }
  if (m.buf) munmap(m.buf, m.size);
  *rows_out = newlines + 1;
  *pairs_out = colons;
  return 0;
#else
  (void)path;
  (void)rows_out;
  (void)pairs_out;
  return -1;
#endif
}

// Parse into caller-allocated buffers sized from cocoa_libsvm_count:
// labels (cap_rows), indptr (cap_rows + 1), indices/values (cap_pairs).
// Writes the ACTUAL row/pair counts (<= the capacities).  Returns 0 on
// success, 1 when the file outgrew the capacities between the two passes
// (output truncated — callers should fall back or retry), -1 on open
// failure.
int cocoa_libsvm_parse(const char* path, double* labels, int64_t* indptr,
                       int32_t* indices, double* values, int64_t cap_rows,
                       int64_t cap_pairs, int64_t* rows_out,
                       int64_t* pairs_out) {
#ifndef _WIN32
  Mapping m = map_file(path);
  if (!m.ok) return -1;
  Sink sink{labels, indptr, indices, values, cap_rows, cap_pairs};
  sink.indptr[0] = 0;
  if (m.size) {
    parse_span(m, 0, m.size, &sink);
    munmap(m.buf, m.size);
  }
  *rows_out = sink.rows;
  *pairs_out = sink.pairs;
  return sink.truncated ? 1 : 0;
#else
  (void)path; (void)labels; (void)indptr; (void)indices; (void)values;
  (void)cap_rows; (void)cap_pairs;
  (void)rows_out; (void)pairs_out;
  return -1;
#endif
}

// Upper-bound counts for the byte range [lo, hi) under the streaming-
// ingest ownership rule (see resolve_span): rows <= newlines-in-span + 1,
// pairs <= ':'-count-in-span.  Returns 0 on success, -1 when the file
// cannot be mmap'd.  A range that owns no lines reports 0/0.
int cocoa_libsvm_count_range(const char* path, int64_t lo, int64_t hi,
                             int64_t* rows_out, int64_t* pairs_out) {
#ifndef _WIN32
  Mapping m = map_file(path);
  if (!m.ok) return -1;
  *rows_out = 0;
  *pairs_out = 0;
  size_t s_lo, s_hi;
  if (m.size && resolve_span(m.buf, m.size, lo, hi, &s_lo, &s_hi)) {
    int64_t newlines, colons;
    count_span(m, s_lo, s_hi, &newlines, &colons);
    *rows_out = newlines + 1;
    *pairs_out = colons;
  }
  if (m.buf) munmap(m.buf, m.size);
  return 0;
#else
  (void)path; (void)lo; (void)hi; (void)rows_out; (void)pairs_out;
  return -1;
#endif
}

// Parse the rows OWNED by the byte range [lo, hi) (ownership rule in
// resolve_span) into caller-allocated buffers sized from
// cocoa_libsvm_count_range.  ``row_off`` (cap_rows entries, may be null)
// receives the absolute byte offset of each row's line start — the
// per-row index streaming ingest uses to map shard row ranges back to
// exact byte ranges for pass 2.  Return codes as cocoa_libsvm_parse.
int cocoa_libsvm_parse_range(const char* path, int64_t lo, int64_t hi,
                             double* labels, int64_t* indptr,
                             int32_t* indices, double* values,
                             int64_t* row_off, int64_t cap_rows,
                             int64_t cap_pairs, int64_t* rows_out,
                             int64_t* pairs_out) {
#ifndef _WIN32
  Mapping m = map_file(path);
  if (!m.ok) return -1;
  Sink sink{labels, indptr, indices, values, cap_rows, cap_pairs};
  sink.row_off = row_off;
  sink.indptr[0] = 0;
  size_t s_lo, s_hi;
  if (m.size && resolve_span(m.buf, m.size, lo, hi, &s_lo, &s_hi))
    parse_span(m, s_lo, s_hi, &sink);
  if (m.buf) munmap(m.buf, m.size);
  *rows_out = sink.rows;
  *pairs_out = sink.pairs;
  return sink.truncated ? 1 : 0;
#else
  (void)path; (void)lo; (void)hi; (void)labels; (void)indptr;
  (void)indices; (void)values; (void)row_off; (void)cap_rows;
  (void)cap_pairs; (void)rows_out; (void)pairs_out;
  return -1;
#endif
}

}  // extern "C"
