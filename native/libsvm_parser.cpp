// Fast LIBSVM text parser for cocoa_tpu.
//
// Native-runtime counterpart of the Spark loader (reference:
// OptUtils.scala:11-53).  Semantics match the Python oracle in
// cocoa_tpu/data/libsvm.py exactly:
//   - label token containing '+' or equal to 1 -> +1, else -1
//     (OptUtils.scala:35-37)
//   - 1-based idx:val pairs -> 0-based indices (OptUtils.scala:42)
//
// Exposed through a tiny C ABI consumed via ctypes
// (cocoa_tpu/data/native_loader.py): parse -> query sizes -> fill
// caller-allocated numpy buffers -> free.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Parsed {
  std::vector<double> labels;
  std::vector<int64_t> indptr;
  std::vector<int32_t> indices;
  std::vector<double> values;
};

// Label rule per OptUtils.scala:35-37 ('+' anywhere in the token, or the
// token parsing to 1, means +1; everything else silently -1).
double parse_label(const char* tok, const char* end) {
  for (const char* p = tok; p < end; ++p) {
    if (*p == '+') return 1.0;
  }
  char* stop = nullptr;
  double v = strtod(tok, &stop);
  return (stop != tok && v == 1.0) ? 1.0 : -1.0;
}

}  // namespace

extern "C" {

void* cocoa_parse_libsvm(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;

  // read whole file (datasets at this scale fit host RAM comfortably;
  // epsilon ~12GB text would want mmap, a TODO noted in native/README)
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(malloc(size + 1));
  if (!buf || fread(buf, 1, size, f) != static_cast<size_t>(size)) {
    fclose(f);
    free(buf);
    return nullptr;
  }
  fclose(f);
  buf[size] = '\0';

  auto* out = new Parsed();
  out->indptr.push_back(0);

  char* p = buf;
  char* fend = buf + size;
  while (p < fend) {
    // find end of line
    char* eol = static_cast<char*>(memchr(p, '\n', fend - p));
    if (!eol) eol = fend;
    *eol = '\0';

    // skip leading spaces; blank lines are skipped entirely
    while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
    if (p < eol) {
      // label token ends at first space
      char* sp = p;
      while (sp < eol && *sp != ' ' && *sp != '\t') ++sp;
      out->labels.push_back(parse_label(p, sp));

      // idx:val pairs
      p = sp;
      while (p < eol) {
        while (p < eol && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
        if (p >= eol) break;
        char* stop = nullptr;
        long idx = strtol(p, &stop, 10);
        if (stop == p || *stop != ':') break;  // malformed tail: stop row
        p = stop + 1;
        double val = strtod(p, &stop);
        if (stop == p) break;
        p = stop;
        out->indices.push_back(static_cast<int32_t>(idx - 1));  // 1->0 based
        out->values.push_back(val);
      }
      out->indptr.push_back(static_cast<int64_t>(out->indices.size()));
    }
    p = eol + 1;
  }

  free(buf);
  return out;
}

int64_t cocoa_parsed_n(void* handle) {
  return static_cast<Parsed*>(handle)->labels.size();
}

int64_t cocoa_parsed_nnz(void* handle) {
  return static_cast<Parsed*>(handle)->indices.size();
}

void cocoa_parsed_fill(void* handle, double* labels, int64_t* indptr,
                       int32_t* indices, double* values) {
  auto* parsed = static_cast<Parsed*>(handle);
  memcpy(labels, parsed->labels.data(), parsed->labels.size() * sizeof(double));
  memcpy(indptr, parsed->indptr.data(), parsed->indptr.size() * sizeof(int64_t));
  memcpy(indices, parsed->indices.data(),
         parsed->indices.size() * sizeof(int32_t));
  memcpy(values, parsed->values.data(), parsed->values.size() * sizeof(double));
}

void cocoa_parsed_free(void* handle) { delete static_cast<Parsed*>(handle); }

}  // extern "C"
