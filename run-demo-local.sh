#!/bin/bash
# Demo run — same config as the reference launcher (run-demo-local.sh:2-9):
# all six algorithms on the bundled small dataset, K=4 shards, T=100 rounds,
# H = 0.1·n/K = 50, λ=1e-3.  On a single chip the 4 logical shards run on the
# vmap path; on a ≥4-device mesh they map 1:1 onto devices.
cd "$(dirname "$0")"
exec python -m cocoa_tpu.cli \
  --trainFile=data/small_train.dat \
  --testFile=data/small_test.dat \
  --numFeatures=9947 \
  --numRounds=100 \
  --localIterFrac=0.1 \
  --numSplits=4 \
  --lambda=.001 \
  --justCoCoA=false \
  "$@"
