#!/bin/bash
# TPU-first demo: the same problem as run-demo-local.sh, driven the way a
# TPU run should be — fast-math Pallas kernels, the whole train loop as one
# on-device while_loop (one dispatch, one host fetch), random-reshuffling
# sampling (~25% fewer comm-rounds here, ~5x at epsilon scale; the duality
# gap certificate is exact under any index stream), stopping at the
# certified 1e-4 gap instead of a fixed round budget.  Index tables are
# generated in-jit on the device (--sampling=auto).  Append --blockSize=128
# on large dense problems (H >= a few hundred) for the fused block-
# coordinate MXU kernel (1.36x faster epsilon rounds than the sequential
# kernel with the round-5 distinct path, benchmarks/KERNELS.md), and
# --sigma=auto on randomly-partitioned data: the reference's sigma'=K
# aggregation bound is worst-case — auto tries K/2 (which HALVED the
# certified comm-rounds on the rcv1 config) and falls back to the safe K
# if the divergence guard fires, so a wrong guess costs ~12 evals, not
# the round budget (benchmarks/SWEEPS.md).  Append
# --accel=on --theta=adaptive for the round-12 accelerated outer loop:
# a secant extrapolation of the dual at eval-window boundaries with a
# gap-monitored restart (the rounds themselves are unmodified CoCoA+ and
# the exact gap evaluation stays the certificate — measured 1.76x fewer
# comm rounds to the same gap on rcv1-synth at the safe σ′), plus the adaptive
# local-accuracy ladder — early rounds run H/2 inner steps, tightening
# to the full H near the target, resolved on device from the gap
# estimate (docs/DESIGN.md "Accelerated outer loop"; A/B'd in
# benchmarks/RESULTS.md and SWEEPS.md).
cd "$(dirname "$0")"
exec python -m cocoa_tpu.cli \
  --trainFile=data/small_train.dat \
  --testFile=data/small_test.dat \
  --numFeatures=9947 \
  --numRounds=600 \
  --localIterFrac=0.1 \
  --numSplits=4 \
  --lambda=.001 \
  --justCoCoA=true \
  --math=fast \
  --deviceLoop \
  --rng=permuted \
  --gapTarget=1e-4 \
  "$@"
