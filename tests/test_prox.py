"""ProxCoCoA+ (lasso / elastic net): literal NumPy oracle parity, execution
path equality (exact / fast / Pallas-interpret / chunked / device-loop /
mesh), duality-gap certificate properties, sparse recovery."""

import numpy as np
import jax.numpy as jnp
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.columns import shard_columns
from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import split_sizes
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_prox_cocoa
from cocoa_tpu.utils.prng import sample_indices

K = 4


def _problem(seed=0, n=96, d=48, sparsity=6, noise=0.01):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(n, d)) / np.sqrt(n)
    x_true = np.zeros(d)
    x_true[rng.choice(d, sparsity, replace=False)] = 3 * rng.normal(size=sparsity)
    b = A @ x_true + noise * rng.normal(size=n)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    data = LibsvmData(labels=b, indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=A.reshape(-1), num_features=d)
    return A, b, x_true, data


def _params(d, lam, **kw):
    defaults = dict(n=d, num_rounds=20, local_iters=10, lam=lam,
                    gamma=1.0, smoothing=0.0, loss="lasso")
    defaults.update(kw)
    return Params(**defaults)


_DBG = DebugParams(debug_iter=5, seed=0)


def _oracle_prox(A, b, lam, k, rounds, h, seed, l2=0.0, gamma=1.0):
    """Literal sequential ProxCoCoA+: column shards, per-round frozen r0,
    sigma'-corrected prox-CD steps, additive aggregation — the NumPy ground
    truth the TPU build must match in x64."""
    n, d = A.shape
    sizes = split_sizes(d, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    sigma = k * gamma
    x = np.zeros(d)
    r = -b.astype(np.float64).copy()
    for t in range(1, rounds + 1):
        dv_sum = np.zeros(n)
        for s in range(k):
            lo, hi = offs[s], offs[s + 1]
            cols = A[:, lo:hi]
            idxs = sample_indices(seed, range(t, t + 1), h, hi - lo)[0]
            dv = np.zeros(n)
            dx = np.zeros(hi - lo)
            for j in idxs:
                a_j = cols[:, j]
                q = sigma * (a_j @ a_j)
                z = a_j @ r + sigma * (a_j @ dv)
                a_cur = x[lo + j] + dx[j]
                denom = q + l2
                if denom <= 0:
                    continue
                u = (q * a_cur - z) / denom
                t_new = np.sign(u) * max(abs(u) - lam / denom, 0.0)
                delta = t_new - a_cur
                dx[j] += delta
                dv += a_j * delta
            x[lo:hi] += gamma * dx
            dv_sum += dv
        r = r + gamma * dv_sum
    return x, r


def test_prox_matches_oracle_exact():
    A, b, _, data = _problem()
    d = data.num_features
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.1 * np.max(np.abs(A.T @ b))
    p = _params(d, float(lam))
    x, r, _ = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True, math="exact")
    x_o, r_o = _oracle_prox(A, b, lam, K, p.num_rounds, p.local_iters, 0)
    xs = np.concatenate([np.asarray(x[s])[:c] for s, c in enumerate(ds.counts)])
    np.testing.assert_allclose(xs, x_o, atol=1e-12)
    np.testing.assert_allclose(np.asarray(r)[:len(b)], r_o, atol=1e-12)


@pytest.mark.slow
@pytest.mark.parametrize("l2", [0.0, 0.3])
def test_prox_fast_and_paths_match_exact(l2):
    A, b, _, data = _problem(seed=1)
    d = data.num_features
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.1 * np.max(np.abs(A.T @ b))
    p = _params(d, float(lam), smoothing=l2)
    x0, r0, _ = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True, math="exact")
    for kw in (dict(math="fast", pallas=False),
               dict(math="fast", pallas=False, scan_chunk=5),
               dict(math="fast", pallas=False, device_loop=True),
               dict(math="fast", pallas=True, scan_chunk=5)):
        x1, r1, _ = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True, **kw)
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x0), atol=1e-9,
                                   err_msg=str(kw))
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r0), atol=1e-9,
                                   err_msg=str(kw))


@pytest.mark.slow
def test_prox_sparse_columns_match_dense():
    """The padded-CSC column layout must produce exactly the dense column
    layout's trajectory, on both the fori paths and the sparse Pallas
    kernel (interpret)."""
    A, b, _, data = _problem(seed=7)
    d = data.num_features
    lam = 0.1 * np.max(np.abs(A.T @ b))
    p = _params(d, float(lam))
    ds_d, b_d = shard_columns(data, K, dtype=jnp.float64, layout="dense")
    ds_s, b_s = shard_columns(data, K, dtype=jnp.float64, layout="sparse")
    assert ds_s.layout == "sparse"
    x0, r0, _ = run_prox_cocoa(ds_d, b_d, p, _DBG, quiet=True, math="exact")
    for kw in (dict(math="exact"),
               dict(math="fast", pallas=False),
               dict(math="fast", pallas=True, scan_chunk=5)):
        x1, r1, _ = run_prox_cocoa(ds_s, b_s, p, _DBG, quiet=True, **kw)
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                                   atol=1e-9, err_msg=str(kw))
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r0),
                                   atol=1e-9, err_msg=str(kw))


def test_shard_columns_rejects_degenerate_csc():
    _, _, _, data = _problem(seed=8)
    with np.testing.assert_raises(ValueError):
        shard_columns(data, K, layout="sparse", max_col_nnz=2)


@pytest.mark.slow
def test_prox_mesh_matches_local():
    A, b, _, data = _problem(seed=2)
    d = data.num_features
    lam = 0.1 * np.max(np.abs(A.T @ b))
    p = _params(d, float(lam))
    ds_l, b_l = shard_columns(data, K, dtype=jnp.float64)
    x0, r0, _ = run_prox_cocoa(ds_l, b_l, p, _DBG, quiet=True, math="exact")
    mesh = make_mesh(K)
    ds_m, b_m = shard_columns(data, K, dtype=jnp.float64, mesh=mesh)
    x1, r1, _ = run_prox_cocoa(ds_m, b_m, p, _DBG, quiet=True, math="exact",
                               mesh=mesh)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r0), atol=1e-12)


def test_prox_gap_certificate_and_early_stop():
    A, b, _, data = _problem(seed=3)
    d = data.num_features
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.2 * np.max(np.abs(A.T @ b))
    p = _params(d, float(lam), num_rounds=400, local_iters=24)
    x, r, traj = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True,
                                gap_target=1e-6, math="fast")
    gaps = [rec.gap for rec in traj.records]
    assert all(g is not None and g >= -1e-12 for g in gaps)
    assert traj.records[-1].gap <= 1e-6
    assert traj.records[-1].round < 400
    # the certificate is honest: P(x) − D(u) recomputed directly
    xs = np.concatenate([np.asarray(x[s])[:c] for s, c in enumerate(ds.counts)])
    rr = np.asarray(r)[:len(b)]
    np.testing.assert_allclose(rr, A @ xs - b, atol=1e-10)
    primal = 0.5 * rr @ rr + lam * np.abs(xs).sum()
    s = min(1.0, lam / np.max(np.abs(A.T @ rr)))
    dual = -0.5 * (s * rr) @ (s * rr) - (s * rr) @ b
    assert primal - dual <= 1e-6 + 1e-12


def test_prox_elastic_net_gap_certificate_and_early_stop():
    """VERDICT r2 item 4: the l2 term smooths the L1 conjugate
    (h*(s) = ([|s|−λ]₊)²/(2η)), so elastic net certifies too — gap
    present at every eval, ≥ 0 (weak duality), honest against a direct
    NumPy recomputation, and driving gap-target early stop."""
    A, b, _, data = _problem(seed=4)
    d = data.num_features
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.2 * np.max(np.abs(A.T @ b))
    l2 = 0.5
    p = _params(d, float(lam), smoothing=l2, num_rounds=400,
                local_iters=24)
    x, r, traj = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True,
                                gap_target=1e-6, math="fast")
    gaps = [rec.gap for rec in traj.records]
    assert all(g is not None and g >= -1e-12 for g in gaps)
    assert traj.records[-1].gap <= 1e-6
    assert traj.records[-1].round < 400
    # the certificate is honest: P(x) − D(r) recomputed directly
    xs = np.concatenate([np.asarray(x[s])[:c]
                         for s, c in enumerate(ds.counts)])
    rr = np.asarray(r)[:len(b)]
    np.testing.assert_allclose(rr, A @ xs - b, atol=1e-10)
    primal = (0.5 * rr @ rr + lam * np.abs(xs).sum()
              + 0.5 * l2 * (xs @ xs))
    excess = np.maximum(np.abs(A.T @ rr) - lam, 0.0)
    dual = -0.5 * rr @ rr - rr @ b - (excess @ excess) / (2 * l2)
    np.testing.assert_allclose(traj.records[-1].gap, primal - dual,
                               rtol=1e-6, atol=1e-12)
    assert primal - dual <= 1e-6 + 1e-12


def test_prox_resume_equals_uninterrupted(tmp_path):
    """Checkpoint the (r, x) state at round 6, resume to 12 → identical to
    a straight 12-round run (round-indexed RNG makes this exact)."""
    A, b, _, data = _problem(seed=6)
    d = data.num_features
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.1 * np.max(np.abs(A.T @ b))
    dbg_save = DebugParams(debug_iter=6, seed=0, chkpt_iter=6,
                           chkpt_dir=str(tmp_path))
    p_half = _params(d, float(lam), num_rounds=6)
    run_prox_cocoa(ds, b_dev, p_half, dbg_save, quiet=True, math="exact")

    from cocoa_tpu import checkpoint as ckpt_lib

    path = ckpt_lib.latest(str(tmp_path), "ProxCoCoA+")
    assert path is not None
    meta, r0, x0 = ckpt_lib.load(path)
    assert meta["round"] == 6

    p_full = _params(d, float(lam), num_rounds=12)
    x_a, r_a, _ = run_prox_cocoa(ds, b_dev, p_full, _DBG, quiet=True,
                                 math="exact")
    x_b, r_b, _ = run_prox_cocoa(ds, b_dev, p_full, _DBG, quiet=True,
                                 math="exact", r_init=r0, x_init=x0,
                                 start_round=meta["round"] + 1)
    np.testing.assert_array_equal(np.asarray(x_b), np.asarray(x_a))
    np.testing.assert_array_equal(np.asarray(r_b), np.asarray(r_a))


def test_prox_recovers_sparse_support():
    A, b, x_true, data = _problem(seed=5, noise=0.001)
    ds, b_dev = shard_columns(data, K, dtype=jnp.float64)
    lam = 0.02 * np.max(np.abs(A.T @ b))
    p = _params(data.num_features, float(lam), num_rounds=300, local_iters=24)
    x, r, traj = run_prox_cocoa(ds, b_dev, p, _DBG, quiet=True,
                                gap_target=1e-8, math="fast")
    xs = np.concatenate([np.asarray(x[s])[:c] for s, c in enumerate(ds.counts)])
    support_true = np.abs(x_true) > 0
    # every true-support coordinate is recovered with the right sign
    assert np.all(np.sign(xs[support_true]) == np.sign(x_true[support_true]))