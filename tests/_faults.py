"""Deterministic fault injection for chaos tests and the CI chaos smoke.

Faults are declarative: arm a :class:`FaultPlan` on the elastic
supervisor's ``on_generation`` hook; each :class:`Fault` waits for its
trigger (a file-system predicate — e.g. "a checkpoint at round >= r
exists") on a daemon thread and then applies its actions to the live
worker processes.  Actions are tiny composable closures:

- :func:`sigkill` — SIGKILL one worker (host loss);
- :func:`sigstop` — SIGSTOP one worker (alive but silent: the wedge the
  ``--stallTimeout`` watchdog exists for);
- :func:`truncate_newest_checkpoint` — tear the newest ``.npz`` (the
  torn-write/bit-rot case ``checkpoint.validate`` guards).

Everything is polled and file-based — no wall-clock races — so a chaos
run is reproducible and CI-able: the same plan against the same worker
command produces the same generation/kill/corruption sequence.
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import threading
import time
from typing import Callable, Optional, Sequence


def sigkill(idx: int) -> Callable:
    """Action: SIGKILL worker ``idx`` (simulated host loss — the
    supervisor sees a death; its peers wedge and are torn down)."""
    def act(procs):
        if idx < len(procs) and procs[idx].poll() is None:
            procs[idx].send_signal(signal.SIGKILL)
    return act


def sigstop(idx: int) -> Callable:
    """Action: SIGSTOP worker ``idx`` — alive, silent, making no
    progress.  Death-only supervision polls this forever; only the
    ``--stallTimeout`` watchdog recovers it."""
    def act(procs):
        if idx < len(procs) and procs[idx].poll() is None:
            procs[idx].send_signal(signal.SIGSTOP)
    return act


def truncate_newest_checkpoint(ckdir, keep_bytes: int = 64) -> Callable:
    """Action: tear the most recently WRITTEN ``.npz`` in ``ckdir`` down
    to ``keep_bytes`` — the half-written/corrupt-copy file
    ``checkpoint.validate`` must reject so ``latest`` falls back to the
    previous generation.  Selected by mtime, not filename: a lexical
    sort would rank every ``CoCoA-`` stamp after every ``CoCoA+`` one
    ('+' < '-') and could tear a finished algorithm's file instead of
    the in-flight one a preemption actually interrupts."""
    def act(procs):
        paths = [os.path.join(str(ckdir), f)
                 for f in os.listdir(str(ckdir)) if f.endswith(".npz")]
        if paths:
            newest = max(paths, key=lambda p: (os.path.getmtime(p), p))
            with open(newest, "r+b") as f:
                f.truncate(keep_bytes)
    return act


def truncate_newest_cache_artifact(cache_dir, keep_bytes: int = 64
                                   ) -> Callable:
    """Action: tear the most recently written ``.npy`` slab under an
    ``--ingestCache`` directory down to ``keep_bytes`` — the torn/
    bit-rotted artifact ``slab_cache.ShardCacheView.load`` must reject
    (typed ``ingest_cache_corrupt`` event, artifact evicted) so the
    shard falls back to a cold parse instead of training on garbage.
    Selected by mtime like :func:`truncate_newest_checkpoint`."""
    def act(procs):
        paths = []
        for root, _, files in os.walk(str(cache_dir)):
            paths += [os.path.join(root, f) for f in files
                      if f.endswith(".npy") and "slab-" in root]
        if paths:
            newest = max(paths, key=lambda p: (os.path.getmtime(p), p))
            with open(newest, "r+b") as f:
                f.truncate(keep_bytes)
    return act


def checkpoint_at_least(ckdir, algorithm: str,
                        min_round: int = 1) -> Callable:
    """Trigger: a round-stamped checkpoint for ``algorithm`` at round >=
    ``min_round`` exists — "the run is demonstrably mid-flight"."""
    stamp = re.compile(
        re.escape(algorithm.replace(" ", "_")) + r"-r(\d+)\.npz$")
    def ready() -> bool:
        if not os.path.isdir(str(ckdir)):
            return False
        for f in os.listdir(str(ckdir)):
            m = stamp.search(f)
            if m and int(m.group(1)) >= min_round:
                return True
        return False
    return ready


@dataclasses.dataclass
class Fault:
    """One scheduled fault: on gang generation ``generation``, wait for
    ``trigger`` (None = fire immediately), then apply ``actions`` in
    order to the generation's worker processes."""

    generation: int
    actions: Sequence[Callable]
    trigger: Optional[Callable] = None
    name: str = ""


class FaultPlan:
    """Arms :class:`Fault`\\ s from the supervisor's ``on_generation``
    hook.  ``fired`` records the faults that ran (assert on it);
    ``errors`` records triggers that never came true before
    ``timeout_s`` or after every worker exited — a chaos test must
    assert ``errors == []`` so a silently-unfired fault cannot pass as
    a survived one."""

    def __init__(self, *faults: Fault, poll_s: float = 0.1,
                 timeout_s: float = 180.0):
        self.faults = list(faults)
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        self.fired: list = []
        self.errors: list = []
        self.generations: list = []
        self._threads: list = []

    def on_generation(self, gen: int, procs) -> None:
        """The elastic supervisor hook (``supervise(on_generation=...)``)."""
        self.generations.append(gen)
        for fault in self.faults:
            if fault.generation == gen:
                t = threading.Thread(target=self._run,
                                     args=(fault, list(procs)),
                                     daemon=True)
                t.start()
                self._threads.append(t)

    def _run(self, fault: Fault, procs) -> None:
        name = fault.name or f"fault@gen{fault.generation}"
        deadline = time.monotonic() + self.timeout_s
        while fault.trigger is not None and not fault.trigger():
            if all(p.poll() is not None for p in procs):
                self.errors.append(f"{name}: every worker exited before "
                                   f"the trigger came true")
                return
            if time.monotonic() > deadline:
                self.errors.append(f"{name}: trigger never came true "
                                   f"within {self.timeout_s:g}s")
                return
            time.sleep(self.poll_s)
        for act in fault.actions:
            act(procs)
        self.fired.append(name)

    def join(self, timeout_s: float = 10.0) -> None:
        """Wait for armed fault threads (call before asserting)."""
        for t in self._threads:
            t.join(timeout_s)
