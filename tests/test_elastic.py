"""Unit tests for the elastic supervisor's plumbing (the end-to-end
SIGKILL/gang-restart behavior is tests/test_multihost.py's slow test)."""

import sys

from cocoa_tpu import elastic


def test_strip_elastic_flags():
    argv = ["--trainFile=x", "--elastic=2", "--master=h:1", "--resume",
            "--processId=0", "--numProcesses=2", "--lambda=.01"]
    assert elastic.strip_elastic_flags(argv) == [
        "--trainFile=x", "--lambda=.01"]


def test_supervise_worker_argv_and_resume_flag(monkeypatch):
    """The spawned worker command carries the user flags, the supervisor's
    --master/--processId/--numProcesses — and --resume exactly when
    requested."""
    spawned = []
    real_spawn = elastic._spawn

    def spy(worker_argv, i, n, port, python, module, quiet_tail, resume):
        p = real_spawn(["-c", "pass"], i, n, port, sys.executable,
                       "timeit", True, False)  # harmless real process
        spawned.append(
            [python, "-m", module, *worker_argv,
             f"--master=127.0.0.1:{port}",
             f"--processId={i}", f"--numProcesses={n}",
             *(["--resume"] if resume else [])]
        )
        return p

    monkeypatch.setattr(elastic, "_spawn", spy)
    for resume in (True, False):
        spawned.clear()
        elastic.supervise(["--lambda=.01"], 2, python="py", module="m",
                          resume=resume, poll_s=0.05, max_restarts=0)
        assert len(spawned) == 2
        for i, argv in enumerate(spawned):
            assert argv[:2] == ["py", "-m"] and argv[2] == "m"
            assert "--lambda=.01" in argv
            assert f"--processId={i}" in argv
            assert "--numProcesses=2" in argv
            assert any(a.startswith("--master=127.0.0.1:") for a in argv)
            assert ("--resume" in argv) == resume


def test_supervise_gives_up_after_consecutive_failures():
    rc = elastic.supervise(
        ["-c", "import sys; sys.exit(3)"], 1, python=sys.executable,
        module="timeit", max_restarts=1, poll_s=0.05, resume=False,
    )
    assert rc != 0


def test_supervise_progress_resets_budget(monkeypatch):
    """When progress_token changes between generations the restart streak
    resets; without progress it gives up after max_restarts."""
    calls = {"n": 0}

    class FakeProc:
        def __init__(self):
            calls["n"] += 1

        def poll(self):
            return 3  # always dead

        def send_signal(self, sig):
            pass

        def wait(self, timeout=None):
            return 3

    monkeypatch.setattr(elastic, "_spawn",
                        lambda *a, **k: FakeProc())
    tokens = iter(range(100))  # changes every generation -> streak resets
    stop = {"gen": 0}

    def token():
        stop["gen"] += 1
        if stop["gen"] > 7:
            raise KeyboardInterrupt  # escape the would-be-infinite loop
        return next(tokens)

    try:
        elastic.supervise([], 1, max_restarts=1, poll_s=0.0,
                          resume=False, progress_token=token)
    except KeyboardInterrupt:
        pass
    assert stop["gen"] > 3  # survived past max_restarts because of progress

    # constant token: gives up after max_restarts+1 generations
    calls["n"] = 0
    rc = elastic.supervise([], 1, max_restarts=2, poll_s=0.0,
                           resume=False, progress_token=lambda: 42)
    assert rc == 3
    assert calls["n"] == 3  # initial + 2 restarts
