"""Unit tests for the elastic supervisor's plumbing (the end-to-end
SIGKILL/gang-restart behavior is tests/test_multihost.py's slow test)."""

import sys

from cocoa_tpu import elastic


def test_strip_elastic_flags():
    argv = ["--trainFile=x", "--elastic=2", "--master=h:1", "--resume",
            "--processId=0", "--numProcesses=2", "--lambda=.01"]
    assert elastic.strip_elastic_flags(argv) == [
        "--trainFile=x", "--lambda=.01"]


def test_supervise_worker_argv_and_resume_flag(monkeypatch):
    """The spawned worker command carries the user flags, the supervisor's
    --master/--processId/--numProcesses — and --resume exactly when
    requested."""
    spawned = []
    real_spawn = elastic._spawn

    def spy(worker_argv, i, n, port, python, module, quiet_tail, resume):
        p = real_spawn(["-c", "pass"], i, n, port, sys.executable,
                       "timeit", True, False)  # harmless real process
        spawned.append(
            [python, "-m", module, *worker_argv,
             f"--master=127.0.0.1:{port}",
             f"--processId={i}", f"--numProcesses={n}",
             *(["--resume"] if resume else [])]
        )
        return p

    monkeypatch.setattr(elastic, "_spawn", spy)
    for resume in (True, False):
        spawned.clear()
        elastic.supervise(["--lambda=.01"], 2, python="py", module="m",
                          resume=resume, poll_s=0.05, max_restarts=0,
                          backoff_base_s=0.0)
        assert len(spawned) == 2
        for i, argv in enumerate(spawned):
            assert argv[:2] == ["py", "-m"] and argv[2] == "m"
            assert "--lambda=.01" in argv
            assert f"--processId={i}" in argv
            assert "--numProcesses=2" in argv
            assert any(a.startswith("--master=127.0.0.1:") for a in argv)
            assert ("--resume" in argv) == resume


def test_supervise_gives_up_after_consecutive_failures():
    rc = elastic.supervise(
        ["-c", "import sys; sys.exit(3)"], 1, python=sys.executable,
        module="timeit", max_restarts=1, poll_s=0.05, resume=False,
        backoff_base_s=0.0,
    )
    assert rc != 0


def test_supervise_progress_resets_budget(monkeypatch):
    """When progress_token changes between generations the restart streak
    resets; without progress it gives up after max_restarts."""
    calls = {"n": 0}

    class FakeProc:
        def __init__(self):
            calls["n"] += 1

        def poll(self):
            return 3  # always dead

        def send_signal(self, sig):
            pass

        def wait(self, timeout=None):
            return 3

    monkeypatch.setattr(elastic, "_spawn",
                        lambda *a, **k: FakeProc())
    tokens = iter(range(100))  # changes every generation -> streak resets
    stop = {"gen": 0}

    def token():
        stop["gen"] += 1
        if stop["gen"] > 7:
            raise KeyboardInterrupt  # escape the would-be-infinite loop
        return next(tokens)

    try:
        elastic.supervise([], 1, max_restarts=1, poll_s=0.0,
                          resume=False, progress_token=token,
                          backoff_base_s=0.0)
    except KeyboardInterrupt:
        pass
    assert stop["gen"] > 3  # survived past max_restarts because of progress

    # constant token: gives up after max_restarts+1 generations
    calls["n"] = 0
    rc = elastic.supervise([], 1, max_restarts=2, poll_s=0.0,
                           resume=False, progress_token=lambda: 42,
                           backoff_base_s=0.0)
    assert rc == 3
    assert calls["n"] == 3  # initial + 2 restarts


def test_supervise_stall_timeout_requires_token():
    import pytest

    with pytest.raises(ValueError):
        elastic.supervise([], 1, stall_timeout_s=1.0, resume=False)


def test_supervise_stall_watchdog_restarts_wedged_gang(monkeypatch):
    """A gang that never exits and never advances its progress token is
    killed and restarted by the watchdog, and gives up after the
    consecutive-failure budget (ADVICE r4: death-only supervision polls a
    wedged gang forever)."""
    spawned = {"n": 0}
    killed = {"n": 0}

    class WedgedProc:
        def __init__(self):
            spawned["n"] += 1

        def poll(self):
            return None  # alive forever, making no progress

        def send_signal(self, sig):
            killed["n"] += 1

        def wait(self, timeout=None):
            return -9

    monkeypatch.setattr(elastic, "_spawn", lambda *a, **k: WedgedProc())
    rc = elastic.supervise(
        [], 2, max_restarts=1, poll_s=0.0, resume=False,
        progress_token=lambda: 42, stall_timeout_s=0.05,
        backoff_base_s=0.0,
    )
    assert rc == 1              # no exit code to report -> generic failure
    assert spawned["n"] == 4    # 2 workers x (initial + 1 restart)
    assert killed["n"] == 4     # every wedged worker was killed


def test_supervise_stall_watchdog_progress_keeps_gang_alive(monkeypatch):
    """A live gang whose token keeps changing is never restarted: the
    watchdog clock resets on every change (and the failure streak too)."""
    spawned = {"n": 0}
    ticks = {"n": 0}

    class Proc:
        def __init__(self):
            spawned["n"] += 1

        def poll(self):
            # finish cleanly after enough watchdog polls
            return 0 if ticks["n"] > 20 else None

        def send_signal(self, sig):
            pass

        def wait(self, timeout=None):
            return 0

    def token():
        ticks["n"] += 1
        return ticks["n"]  # changes every poll -> never stalls

    monkeypatch.setattr(elastic, "_spawn", lambda *a, **k: Proc())
    rc = elastic.supervise(
        [], 1, max_restarts=0, poll_s=0.0, resume=False,
        progress_token=token, stall_timeout_s=0.05,
        backoff_base_s=0.0,
    )
    assert rc == 0
    assert spawned["n"] == 1  # one generation, zero restarts
