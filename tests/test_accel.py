"""The accelerated outer loop (--accel / --theta, round 12).

Secant (Anderson-1) extrapolation of the DUAL at eval-window boundaries:
the drivers bank the two previous eval-boundary α snapshots in a
(2, K, n_shard) ``hist`` state leaf; once two consecutive improving
windows are banked, the next chunk opens with the jump α ← α + c·(α−h2)
— c = ρ/(1−ρ) signed and data-derived from the window displacements'
autocorrelation (base.secant_coef) — clipped back into the dual box,
with w advanced by the EXACT correspondence update Σ y·Δα·x/(λn)
(ops/rows.shards_axpy).  The certified pair (w, α) therefore stays a
feasible primal-dual pair and the unmodified duality-gap evaluation
stays the certificate; a gap rise at an eval boundary RESTARTS the bank.
``--theta=adaptive`` adds the Θ local-accuracy ladder: per-round
inner-step counts resolved on device from the current gap estimate
through the same statically-specialized ``lax.switch`` machinery as the
σ′ anneal stages.

What these tests pin:

- ``--accel=off`` is BIT-IDENTICAL to the pre-acceleration code across
  all three drive modes (per-round, host-chunked, device loop);
- the host-chunked and device-loop accelerated drivers make identical
  decisions and produce identical states (accel_host_step is the device
  loop's f32 bit-twin);
- a mid-momentum checkpoint resume (hist leaf + extended sched slots) is
  bit-identical to the uninterrupted run;
- the typed ``momentum_restart`` / ``theta_stage`` events flow through
  the bus identically on the host and device paths, and the sched-leaf
  accel machinery (bank/arm/jump rule, Θ ladder, restart action)
  matches its host twin slot for slot;
- the flag surface validations.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.data.synth import synth_sparse
from cocoa_tpu.solvers import base, run_cocoa
from cocoa_tpu.telemetry import events as tele_events


@pytest.fixture(autouse=True)
def clean_bus():
    tele_events.get_bus().reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()


def _ds(n=512, d=128, k=4, seed=3):
    data = synth_sparse(n, d, nnz_mean=12, seed=seed)
    return shard_dataset(data, k=k, layout="dense", dtype=jnp.float32), data.n


def _run(ds, n, accel=None, theta=None, num_rounds=100, lam=1e-2,
         gap_target=1e-6, debug_iter=10, **kw):
    params = Params(n=n, num_rounds=num_rounds, local_iters=16, lam=lam)
    debug = DebugParams(debug_iter=debug_iter, seed=0,
                        chkpt_iter=kw.pop("chkpt_iter", num_rounds + 1),
                        chkpt_dir=kw.pop("chkpt_dir", ""))
    return run_cocoa(ds, params, debug, plus=True, quiet=True, math="fast",
                     rng="permuted", gap_target=gap_target, accel=accel,
                     theta=theta, **kw)


# --- unit: the schedule arithmetic ------------------------------------------


def test_theta_ladder():
    assert base.theta_ladder(253, False) == (253,)
    # the ladder starts at H/2 — an H/4 rung was measured to COST rounds
    # (the early fast-decay rounds are productive; solvers/base.py note)
    assert base.theta_ladder(253, True) == (126, 253)
    assert base.theta_ladder(16, True) == (8, 16)
    # tiny H collapses duplicate rungs, the full H always last
    assert base.theta_ladder(2, True) == (1, 2)
    assert base.theta_ladder(1, True) == (1,)


def test_sched_init_array_accel_shapes():
    s = np.asarray(base.sched_init_array(7, accel=True))
    assert s.shape == (base.SCHED_LEN + base.ACCEL_LEN,)
    assert s[4] == 7.0
    assert s[base.A_HIST] == 0.0 and s[base.A_JUMP] == 0.0
    assert np.isinf(s[base.A_LASTGAP]) and s[base.A_RESTARTS] == 0.0
    # a plain (5,) restore under accel gains fresh accel slots
    plain = np.asarray(base.sched_init_array(3))
    ext = np.asarray(base.sched_init_array(3, sched_init=plain, accel=True))
    np.testing.assert_array_equal(ext[:base.SCHED_LEN], plain)
    assert ext.shape == (base.SCHED_LEN + base.ACCEL_LEN,)
    # an accel-length restore WITHOUT accel keeps its σ′ head
    back = np.asarray(base.sched_init_array(3, sched_init=ext))
    np.testing.assert_array_equal(back, plain)
    with pytest.raises(ValueError, match="shape"):
        base.sched_init_array(1, sched_init=np.zeros(9, np.float32))


def test_accel_host_step_bank_arm_restart():
    """The window bookkeeping: improving evals BANK α snapshots; two
    banked windows ARM the jump for the next chunk head (and freeze the
    bank); a gap RISE discards the bank (restarts += 1, the bank
    restarts from this eval's α).  All exact f32 arithmetic."""
    s = np.asarray(base.sched_init_array(1, accel=True))
    # first eval: last_gap is inf — bank one window
    s, restarted, staged = base.accel_host_step(s, 1.0, 1, None)
    assert not restarted and s[base.A_HIST] == 1.0
    assert s[base.A_JUMP] == 0.0
    assert s[base.A_LASTGAP] == np.float32(1.0)
    # second improving eval: two windows banked
    s, restarted, _ = base.accel_host_step(s, 0.5, 1, None)
    assert not restarted and s[base.A_HIST] == 2.0
    assert s[base.A_JUMP] == 0.0
    # third improving eval: the jump ARMS and the bank is consumed
    s, restarted, _ = base.accel_host_step(s, 0.25, 1, None)
    assert not restarted
    assert s[base.A_JUMP] == 1.0 and s[base.A_HIST] == 0.0
    # the chunk head clears the armed flag when it takes the jump
    s[base.A_JUMP] = 0.0
    # a RISE restarts: bank discarded, restarted from this eval's α
    s, restarted, _ = base.accel_host_step(s, 0.6, 1, None)
    assert restarted and s[base.A_HIST] == 1.0
    assert s[base.A_JUMP] == 0.0 and s[base.A_RESTARTS] == 1.0


def test_secant_coef():
    """The jump coefficient: c = ρ/(1−min(ρ, cap)) clipped to
    [ACCEL_CMIN, ACCEL_CMAX] — averaging on oscillation, capped
    extrapolation on drift."""
    # pure oscillation ρ = −1 → pairwise averaging c = −0.5 exactly
    assert base.secant_coef(np, np.float32(-1.0)) == np.float32(-0.5)
    # no correlation → no jump
    assert base.secant_coef(np, np.float32(0.0)) == np.float32(0.0)
    # measured rcv1-synth drift ρ ≈ 0.73 → c ≈ 2.7, inside the cap
    c = base.secant_coef(np, np.float32(0.73))
    assert np.isclose(float(c), 0.73 / 0.27, rtol=1e-5)
    # ρ → 1 pole is capped then clipped to CMAX
    assert base.secant_coef(np, np.float32(0.999)) == \
        np.float32(base.ACCEL_CMAX)
    # strong anti-correlation clips at CMIN
    assert base.secant_coef(np, np.float32(-5.0)) == \
        np.float32(base.ACCEL_CMIN)


def test_accel_host_step_theta_ladder_advance():
    """Θ advances on the halve-per-eval stall watch, jumps to the final
    stage near the target, and is inert at the last rung."""
    tgt = 1e-4
    s = np.asarray(base.sched_init_array(1, accel=True))
    # fast-decay phase: gap halves every eval — the loose stage holds
    s, _, staged = base.accel_host_step(s, 8.0, 3, tgt)
    assert not staged and s[base.A_TH_STAGE] == 0.0
    s, _, staged = base.accel_host_step(s, 3.0, 3, tgt)
    assert not staged
    # decay slows below 2x/eval -> one miss fires the watch
    s, _, staged = base.accel_host_step(s, 2.0, 3, tgt)
    assert staged and s[base.A_TH_STAGE] == 1.0
    assert s[base.A_TH_STALL] == 0.0 and np.isinf(s[base.A_TH_BEST])
    # near the target: jump straight to the final stage
    s, _, staged = base.accel_host_step(s, 9e-4, 3, tgt)
    assert staged and s[base.A_TH_STAGE] == 2.0
    # final rung: the ladder is inert
    s, _, staged = base.accel_host_step(s, 8.9e-4, 3, tgt)
    assert not staged and s[base.A_TH_STAGE] == 2.0


# --- accel=off is the pre-acceleration code, bit for bit --------------------


@pytest.mark.parametrize("mode", ["per_round", "chunked", "device_loop"])
def test_accel_off_bit_identical_all_modes(mode):
    ds, n = _ds()
    # the per-round driver pays a per-round dispatch+eval cost (~0.5 s/
    # round on the CI box) — 30 rounds cross three eval boundaries, which
    # is all the two-arm bit-identity needs; the cheap drivers keep the
    # full 100 rounds of schedule evolution
    kw = dict(num_rounds=30)
    if mode == "chunked":
        kw = dict(scan_chunk=1)
    elif mode == "device_loop":
        kw = dict(device_loop=True)
    w_o, a_o, t_o = _run(ds, n, accel="off", **kw)
    w_p, a_p, t_p = _run(ds, n, **kw)
    np.testing.assert_array_equal(np.asarray(w_o), np.asarray(w_p))
    np.testing.assert_array_equal(np.asarray(a_o), np.asarray(a_p))
    assert [r.round for r in t_o.records] == [r.round for r in t_p.records]


@pytest.mark.slow
def test_accel_auto_resolution():
    """auto = on for gap-targeted CoCoA+ runs, off without a target (the
    fixed-round benchmark paths stay bit-comparable)."""
    ds, n = _ds()
    # targetless runs take the slow per-round driver — 30 rounds suffice
    # for the two-arm identity (see test_accel_off_bit_identical_all_modes)
    w_a, _, _ = _run(ds, n, accel="auto", gap_target=None, num_rounds=30)
    w_p, _, _ = _run(ds, n, gap_target=None, num_rounds=30)
    np.testing.assert_array_equal(np.asarray(w_a), np.asarray(w_p))
    # with a target, auto accelerates: the trajectory departs from plain
    w_on, _, _ = _run(ds, n, accel="on", num_rounds=60)
    w_au, _, _ = _run(ds, n, accel="auto", num_rounds=60)
    np.testing.assert_array_equal(np.asarray(w_on), np.asarray(w_au))


# --- host/device parity ------------------------------------------------------


@pytest.mark.parametrize("theta", ["fixed", "adaptive"])
def test_accel_device_loop_identical_to_host(theta):
    ds, n = _ds()
    w_h, a_h, t_h = _run(ds, n, accel="on", theta=theta)
    w_d, a_d, t_d = _run(ds, n, accel="on", theta=theta, device_loop=True)
    np.testing.assert_array_equal(np.asarray(w_h), np.asarray(w_d))
    np.testing.assert_array_equal(np.asarray(a_h), np.asarray(a_d))
    assert [r.round for r in t_h.records] == [r.round for r in t_d.records]


# --- checkpoint / resume -----------------------------------------------------


def test_accel_checkpoint_carries_hist_and_extended_sched(tmp_path):
    ds, n = _ds()
    _run(ds, n, accel="on", theta="adaptive", chkpt_dir=str(tmp_path),
         chkpt_iter=50, device_loop=True)
    path = ckpt_lib.latest(str(tmp_path), "CoCoA+")
    assert path is not None
    meta, arrays = ckpt_lib.load_full(path)
    assert "hist" in arrays
    assert arrays["hist"].shape == (2,) + arrays["alpha"].shape
    assert len(meta["sched"]) == base.SCHED_LEN + base.ACCEL_LEN


@pytest.mark.parametrize("device_loop", [False, True],
                         ids=["chunked", "deviceloop"])
def test_accel_resume_mid_momentum_bit_identical(tmp_path, device_loop):
    """Resume from a mid-run checkpoint (momentum β and Θ watch slots
    mid-flight): the restored run must reproduce the uninterrupted one
    bit for bit."""
    ds, n = _ds()
    ck = str(tmp_path)
    w0, a0, t0 = _run(ds, n, accel="on", theta="adaptive", chkpt_dir=ck,
                      chkpt_iter=50, device_loop=device_loop)
    path = os.path.join(ck, "CoCoA+-r000050.npz")
    meta, arrays = ckpt_lib.load_full(path)
    sched = np.asarray(meta["sched"], np.float32)
    assert sched.shape == (base.SCHED_LEN + base.ACCEL_LEN,)
    w_r, a_r, t_r = _run(
        ds, n, accel="on", theta="adaptive", device_loop=device_loop,
        w_init=arrays["w"], alpha_init=arrays["alpha"],
        hist_init=arrays["hist"], sched_init=sched,
        start_round=meta["round"] + 1)
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w_r))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a_r))


def test_accel_off_resumes_accel_checkpoint(tmp_path):
    """An accel checkpoint restored into an --accel=off run keeps the σ′
    head of the sched vector and simply drops the momentum state (any
    (w, α) is a valid primal-dual pair)."""
    ds, n = _ds()
    ck = str(tmp_path)
    _run(ds, n, accel="on", chkpt_dir=ck, chkpt_iter=50)
    path = os.path.join(ck, "CoCoA+-r000050.npz")
    meta, arrays = ckpt_lib.load_full(path)
    w_r, a_r, t_r = _run(
        ds, n, accel="off", w_init=arrays["w"],
        alpha_init=arrays["alpha"], start_round=meta["round"] + 1)
    assert t_r.records, "resumed run must keep evaluating"


# --- telemetry ---------------------------------------------------------------


def _collect():
    events = []
    tele_events.get_bus().subscribe(events.append)
    return events


def _accel_event_run(device_loop):
    """A run engineered to restart at least once: λ small enough that the
    gap trajectory is non-monotone under extrapolation."""
    ds, n = _ds(n=1024, d=256, k=4, seed=0)
    return _run(ds, n, accel="on", theta="adaptive", lam=1e-4,
                num_rounds=200, debug_iter=5, gap_target=1e-5,
                device_loop=device_loop)


def test_accel_events_host_vs_device_identical():
    """momentum_restart / theta_stage events: same count, same rounds,
    same payloads on the host-chunked and device-loop paths (the
    DeviceTap decode vs the host twin's flags)."""
    def strip(events):
        return [
            {k: v for k, v in e.items() if k not in ("seq", "ts", "pid")}
            for e in events
            if e["event"] in ("momentum_restart", "theta_stage")]

    ev_h = _collect()
    _accel_event_run(device_loop=False)
    host = strip(ev_h)
    tele_events.get_bus().reset()
    ev_d = _collect()
    _accel_event_run(device_loop=True)
    dev = strip(ev_d)
    assert host == dev
    assert any(e["event"] == "theta_stage" for e in host), \
        "the fixture must exercise at least one Θ step"


def test_accel_events_schema_and_metrics(tmp_path):
    from cocoa_tpu.telemetry import schema as tele_schema
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    jsonl = str(tmp_path / "events.jsonl")
    metrics_path = str(tmp_path / "metrics.prom")
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=jsonl, metrics_path=metrics_path)
    _, _, traj = _accel_event_run(device_loop=True)
    assert tele_schema.check_file(jsonl) == []
    text = open(metrics_path).read()
    assert "cocoa_momentum_restarts_total" in text
    import re
    n_restarts = int(re.search(
        r"cocoa_momentum_restarts_total (\d+)", text).group(1))
    with open(jsonl) as f:
        restart_events = [ln for ln in f
                          if '"momentum_restart"' in ln]
    assert n_restarts == len(restart_events)
    if any('"theta_stage"' in ln for ln in open(jsonl)):
        assert "cocoa_theta_stage" in text


def test_accel_telemetry_on_off_bit_identical(tmp_path):
    """The tap/stream machinery is side-effect-only: an accel run with
    every sink active produces bit-identical (w, α) to a silent one."""
    w_s, a_s, _ = _accel_event_run(device_loop=True)
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=str(tmp_path / "e.jsonl"),
                  metrics_path=str(tmp_path / "m.prom"))
    w_t, a_t, _ = _accel_event_run(device_loop=True)
    np.testing.assert_array_equal(np.asarray(w_s), np.asarray(w_t))
    np.testing.assert_array_equal(np.asarray(a_s), np.asarray(a_t))


# --- validations -------------------------------------------------------------


def test_accel_validations():
    ds, n = _ds()
    params = Params(n=n, num_rounds=20, local_iters=8, lam=1e-2)
    debug = DebugParams(debug_iter=5, seed=0)
    with pytest.raises(ValueError, match="auto|on|off"):
        run_cocoa(ds, params, debug, plus=True, quiet=True, accel="fast")
    with pytest.raises(ValueError, match="fixed|adaptive"):
        run_cocoa(ds, params, debug, plus=True, quiet=True, accel="on",
                  theta="warp", gap_target=1e-6)
    # theta=adaptive needs an accelerated run
    with pytest.raises(ValueError, match="accel"):
        run_cocoa(ds, params, debug, plus=True, quiet=True,
                  theta="adaptive", gap_target=1e-6)
    # the trial control stays untouched
    p_auto = dataclasses.replace(params, sigma="auto")
    with pytest.raises(ValueError, match="trial"):
        run_cocoa(ds, p_auto, debug, plus=True, quiet=True, accel="on",
                  sigma_schedule="trial", gap_target=1e-6)
    # momentum restarts ride the eval cadence
    with pytest.raises(ValueError, match="debugIter"):
        run_cocoa(ds, params, DebugParams(debug_iter=0, seed=0),
                  plus=True, quiet=True, accel="on", gap_target=1e-6)


def test_accel_combines_with_sigma_anneal():
    """accel + σ′ anneal share one device loop: the branch table is the
    (σ′ stage × Θ stage) product and both selectors ride the sched
    leaf."""
    ds, n = _ds()
    params = Params(n=n, num_rounds=100, local_iters=16, lam=1e-2,
                    sigma="auto")
    debug = DebugParams(debug_iter=10, seed=0)
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                               math="fast", rng="permuted",
                               gap_target=1e-6, accel="on",
                               theta="adaptive", device_loop=True)
    assert traj.records[-1].sigma is not None


def test_accel_with_hot_cols_hybrid_layout():
    """--accel on a hybrid (--hotCols) sparse layout: the secant jump's
    transpose-apply must scatter the hot-panel contribution as a summed
    (n_hot,) update (regression: a per-shard (K, n_hot) einsum raised a
    broadcast error at trace time, so accel+hotCols could never run)."""
    data = synth_sparse(512, 128, nnz_mean=12, seed=3)
    ds = shard_dataset(data, k=4, layout="sparse", hot_cols=16)
    w, alpha, traj = run_cocoa(
        ds, Params(n=data.n, num_rounds=60, local_iters=16, lam=1e-2),
        DebugParams(debug_iter=10, seed=0), plus=True, quiet=True,
        math="fast", rng="permuted", gap_target=1e-6, accel="on",
        device_loop=True)
    assert np.isfinite(np.asarray(w)).all()
    gaps = [r.gap for r in traj.records if r.gap is not None]
    assert gaps and np.isfinite(gaps[-1]) and gaps[-1] < gaps[0]


def test_shards_axpy_hybrid_matches_dense():
    """shards_axpy on the hybrid split == the dense einsum on the same
    data (the hot/cold split permutes per-coordinate sums only)."""
    from cocoa_tpu.ops import rows as _rows

    data = synth_sparse(256, 64, nnz_mean=10, seed=7)
    dense = shard_dataset(data, k=4, layout="dense")
    hyb = shard_dataset(data, k=4, layout="sparse", hot_cols=8)
    coefs = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, dense.n_shard)),
        jnp.float32)
    vec = jnp.zeros((data.num_features,), jnp.float32)
    out_d = _rows.shards_axpy(coefs, dense.shard_arrays(), vec)
    out_h = _rows.shards_axpy(coefs, hyb.shard_arrays(), vec)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_h),
                               atol=1e-4, rtol=1e-4)


def test_theta_adaptive_degrades_when_accel_auto_resolves_off():
    """theta=adaptive rides accel=auto: on a run where auto resolves OFF
    (plain CoCoA — the CLI's run_all second leg), Θ degrades to the full-H
    schedule instead of raising mid-run; explicit accel=off still
    rejects the contradiction."""
    ds, n = _ds()
    params = Params(n=n, num_rounds=20, local_iters=8, lam=1e-2)
    debug = DebugParams(debug_iter=5, seed=0)
    w, alpha, traj = run_cocoa(ds, params, debug, plus=False, quiet=True,
                               gap_target=1e-6, accel="auto",
                               theta="adaptive")
    assert np.isfinite(np.asarray(w)).all()
    with pytest.raises(ValueError, match="accel"):
        run_cocoa(ds, params, debug, plus=True, quiet=True,
                  gap_target=1e-6, accel="off", theta="adaptive")


def test_accel_host_step_sigma_seam_caps_bank():
    """A σ′ anneal backoff at the same eval boundary is a round-map seam
    exactly like a Θ stage advance: the secant bank caps at the α just
    banked, and an already-armed jump stays armed."""
    sched = np.array(base.sched_init_array(1, accel=True), dtype=np.float32)
    sched[base.A_LASTGAP] = np.float32(1.0)
    sched[base.A_HIST] = np.float32(1.0)
    # improving eval + seam: would bank to 2, capped back to 1
    s, restarted, _ = base.accel_host_step(sched, 0.5, 1, 1e-6, seam=True)
    assert not restarted and s[base.A_HIST] == np.float32(1.0)
    assert s[base.A_JUMP] == np.float32(0.0)
    # armed jump survives the seam (hist already 0 after arming)
    sched[base.A_HIST] = np.float32(2.0)
    sched[base.A_LASTGAP] = np.float32(1.0)
    s, _, _ = base.accel_host_step(sched, 0.5, 1, 1e-6, seam=True)
    assert s[base.A_JUMP] == np.float32(1.0)
    assert s[base.A_HIST] == np.float32(0.0)
