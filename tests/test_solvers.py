"""Outer-loop solver tests: oracle trajectory parity, shard_map-vs-vmap path
equality, primal-dual correspondence, convergence properties."""

import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset, split_sizes
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_cocoa, run_dist_gd, run_minibatch_cd, run_sgd
from cocoa_tpu.utils.prng import sample_indices


def _params(tiny_data, **kw):
    defaults = dict(n=tiny_data.n, num_rounds=5, local_iters=20, lam=0.01,
                    beta=1.0, gamma=1.0)
    defaults.update(kw)
    return Params(**defaults)


def _debug(**kw):
    defaults = dict(debug_iter=-1, seed=0, chkpt_iter=10**9, chkpt_dir="")
    defaults.update(kw)
    return DebugParams(**defaults)


def _oracle_shards(tiny_data, k):
    X = tiny_data.to_dense()
    y = tiny_data.labels
    sizes = split_sizes(tiny_data.n, k)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    return [(X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(k)]


def _sample_fn(seed, t, n_local):
    return sample_indices(seed, range(t, t + 1), 20, n_local)[0]


@pytest.mark.parametrize("plus", [True, False])
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_cocoa_outer_matches_oracle(tiny_data, plus, layout):
    """Full T-round CoCoA trajectory == literal oracle, K=4, matched RNG."""
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout=layout, dtype=jnp.float64)
    p = _params(tiny_data)
    w, alpha, _ = run_cocoa(ds, p, _debug(), plus=plus, quiet=True)
    w_o, alphas_o = oracle.cocoa_outer(
        _oracle_shards(tiny_data, k), np.zeros(tiny_data.num_features),
        p.lam, p.n, p.num_rounds, p.local_iters, p.beta, p.gamma, 0, plus,
        _sample_fn,
    )
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)
    for s in range(k):
        np.testing.assert_allclose(
            np.asarray(alpha[s, : len(alphas_o[s])]), alphas_o[s], atol=1e-12
        )


@pytest.mark.parametrize("plus", [True, False])
def test_mesh_path_equals_local_path(tiny_data, plus):
    """shard_map over 4 real devices == vmap on one device, bit-close."""
    k = 4
    p = _params(tiny_data)
    mesh = make_mesh(k)
    ds_m = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    w_m, a_m, _ = run_cocoa(ds_m, p, _debug(), plus=plus, mesh=mesh, quiet=True)
    w_l, a_l, _ = run_cocoa(ds_l, p, _debug(), plus=plus, quiet=True)
    np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_l), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_m), np.asarray(a_l), atol=1e-12)


@pytest.mark.parametrize("plus", [True, False])
def test_primal_dual_correspondence(tiny_data, plus):
    """Invariant: w == (1/λn)·Σ yᵢαᵢxᵢ after every run (implied by
    CoCoA.scala:181 — both sides scale by the same factor)."""
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=3)
    w, alpha, _ = run_cocoa(ds, p, _debug(), plus=plus, quiet=True)
    X = tiny_data.to_dense()
    y = tiny_data.labels
    sizes = split_sizes(tiny_data.n, k)
    alpha_flat = np.concatenate(
        [np.asarray(alpha[s, : sizes[s]]) for s in range(k)]
    )
    w_expect = (y * alpha_flat) @ X / (p.lam * p.n)
    np.testing.assert_allclose(np.asarray(w), w_expect, atol=1e-10)


def test_duality_gap_decreases_and_nonneg(tiny_data):
    from cocoa_tpu.evals import objectives

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=40, local_iters=30, lam=0.01)
    w, alpha, traj = run_cocoa(
        ds, p, _debug(debug_iter=10), plus=True, quiet=True
    )
    gaps = [r.gap for r in traj.records]
    assert len(gaps) == 4
    assert all(g >= -1e-12 for g in gaps)
    assert gaps[-1] < gaps[0]
    # alpha in the box
    a = np.asarray(alpha)
    assert a.min() >= -1e-15 and a.max() <= 1 + 1e-15


def test_minibatch_cd_matches_oracle(tiny_data):
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4)
    w, alpha, _ = run_minibatch_cd(ds, p, _debug(), quiet=True)

    # oracle outer loop for MbCD (MinibatchCD.scala:34-58)
    scaling = p.beta / (k * p.local_iters)
    w_o = np.zeros(tiny_data.num_features)
    shards = _oracle_shards(tiny_data, k)
    alphas_o = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    for t in range(1, p.num_rounds + 1):
        dw_sum = np.zeros_like(w_o)
        for s, (Xk, yk) in enumerate(shards):
            idxs = _sample_fn(0, t, Xk.shape[0])
            dw, a_new = oracle.minibatch_cd_partition(
                Xk, yk, w_o, alphas_o[s], idxs, p.lam, p.n, scaling
            )
            alphas_o[s] = a_new
            dw_sum += dw
        w_o = w_o + dw_sum * scaling
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)


@pytest.mark.parametrize("local", [True, False])
def test_sgd_matches_oracle(tiny_data, local):
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4)
    w, _ = run_sgd(ds, p, _debug(), local=local, quiet=True)

    # oracle outer loop (SGD.scala:41-67)
    scaling = p.beta / k if local else p.beta / (k * p.local_iters)
    w_o = np.zeros(tiny_data.num_features)
    shards = _oracle_shards(tiny_data, k)
    for t in range(1, p.num_rounds + 1):
        eta = 1.0 / (p.lam * t)
        if not local:
            w_o = w_o * (1.0 - eta * p.lam)
        t_global = (t - 1) * p.local_iters * k
        dw_sum = np.zeros_like(w_o)
        for Xk, yk in shards:
            idxs = _sample_fn(0, t, Xk.shape[0])
            dw_sum += oracle.sgd_partition(Xk, yk, w_o, idxs, p.lam, t_global, local)
        w_o = w_o + dw_sum * (scaling if local else eta * scaling)
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)


def test_dist_gd_matches_oracle(tiny_data):
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4, beta=1.0)
    w, _ = run_dist_gd(ds, p, _debug(), quiet=True)

    w_o = np.zeros(tiny_data.num_features)
    shards = _oracle_shards(tiny_data, k)
    for t in range(1, p.num_rounds + 1):
        eta = 1.0 / (p.beta * t)
        dw_sum = np.zeros_like(w_o)
        for Xk, yk in shards:
            dw_sum += oracle.dist_gd_partition(Xk, yk, w_o, p.lam)
        w_o = w_o + dw_sum * (eta / np.linalg.norm(dw_sum))
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)


def test_evals_match_oracle(tiny_data):
    from cocoa_tpu.evals import objectives

    ds = shard_dataset(tiny_data, k=3, layout="sparse", dtype=jnp.float64)
    X, y = tiny_data.to_dense(), tiny_data.labels
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=tiny_data.num_features))
    lam = 0.01
    assert objectives.primal_objective(ds, w, lam) == pytest.approx(
        oracle.primal_objective(X, y, np.asarray(w), lam), rel=1e-12
    )
    assert objectives.classification_error(ds, w) == pytest.approx(
        oracle.classification_error(X, y, np.asarray(w)), rel=1e-12
    )
    alpha = jnp.asarray(rng.random((3, ds.n_shard)))
    masked_sum = float(np.sum(np.asarray(alpha) * np.asarray(ds.mask)))
    assert objectives.dual_objective(ds, w, alpha, lam) == pytest.approx(
        oracle.dual_objective(np.asarray(w), masked_sum, tiny_data.n, lam),
        rel=1e-12,
    )


def test_gap_target_early_stop(tiny_data):
    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=200, local_iters=50)
    w, alpha, traj = run_cocoa(
        ds, p, _debug(debug_iter=5), plus=True, quiet=True, gap_target=1e-3
    )
    assert traj.records[-1].gap <= 1e-3
    assert traj.records[-1].round < 200


def test_checkpoint_roundtrip(tiny_data, tmp_path):
    from cocoa_tpu import checkpoint as ck

    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4)
    d = _debug(chkpt_iter=2, chkpt_dir=str(tmp_path))
    w, alpha, _ = run_cocoa(ds, p, d, plus=True, quiet=True)
    path = ck.latest(str(tmp_path), "CoCoA+")
    assert path is not None and path.endswith("r000004.npz")
    meta, w_l, a_l = ck.load(path)
    assert meta["round"] == 4
    np.testing.assert_allclose(w_l, np.asarray(w), atol=0)
    np.testing.assert_allclose(a_l, np.asarray(alpha), atol=0)


@pytest.mark.parametrize("use_mesh", [False, True])
@pytest.mark.parametrize("plus", [True, False])
def test_scan_chunk_equals_per_round(tiny_data, use_mesh, plus):
    """Device-side lax.scan over round chunks == the per-round python loop,
    bit-exact, on both execution paths."""
    k = 4
    mesh = make_mesh(k) if use_mesh else None
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    p = _params(tiny_data, num_rounds=7)
    w_loop, a_loop, _ = run_cocoa(ds, p, _debug(), plus=plus, mesh=mesh, quiet=True)
    w_scan, a_scan, _ = run_cocoa(
        ds, p, _debug(), plus=plus, mesh=mesh, quiet=True, scan_chunk=3
    )
    np.testing.assert_allclose(np.asarray(w_scan), np.asarray(w_loop), atol=0)
    np.testing.assert_allclose(np.asarray(a_scan), np.asarray(a_loop), atol=0)


@pytest.mark.parametrize("use_mesh", [False, True])
@pytest.mark.parametrize("plus", [True, False])
def test_device_loop_equals_host_driver(tiny_data, use_mesh, plus):
    """The fully device-resident while_loop driver (one dispatch, one fetch)
    produces the same final state AND the same observable trajectory
    (rounds evaluated, primal, gap, test error) as the host-stepped driver —
    including a num_rounds % debugIter remainder tail."""
    k = 4
    mesh = make_mesh(k) if use_mesh else None
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    test_ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64,
                            mesh=mesh)
    p = _params(tiny_data, num_rounds=7)
    d = _debug(debug_iter=2)
    w_h, a_h, tr_h = run_cocoa(
        ds, p, d, plus=plus, mesh=mesh, test_ds=test_ds, quiet=True
    )
    w_d, a_d, tr_d = run_cocoa(
        ds, p, d, plus=plus, mesh=mesh, test_ds=test_ds, quiet=True,
        device_loop=True,
    )
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_h), atol=0)
    np.testing.assert_allclose(np.asarray(a_d), np.asarray(a_h), atol=0)
    assert [r.round for r in tr_d.records] == [r.round for r in tr_h.records]
    for rh, rd in zip(tr_h.records, tr_d.records):
        assert abs(rh.primal - rd.primal) < 1e-12
        assert abs(rh.gap - rd.gap) < 1e-12
        assert abs(rh.test_error - rd.test_error) < 1e-12


def test_device_loop_off_cadence_resume(tiny_data):
    """A resumed run whose start_round is off the debugIter cadence must
    still evaluate at absolute rounds t % debugIter == 0, matching the
    host-stepped driver (head rounds run host-side up to the boundary)."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p1 = _params(tiny_data, num_rounds=1)
    w1, a1, _ = run_cocoa(ds, p1, _debug(), plus=True, quiet=True)
    p = _params(tiny_data, num_rounds=9)
    d = _debug(debug_iter=2)
    common = dict(plus=True, quiet=True, w_init=w1, alpha_init=a1,
                  start_round=2)
    w_h, a_h, tr_h = run_cocoa(ds, p, d, **common)
    w_d, a_d, tr_d = run_cocoa(ds, p, d, device_loop=True, **common)
    assert [r.round for r in tr_h.records] == [2, 4, 6, 8]
    assert [r.round for r in tr_d.records] == [2, 4, 6, 8]
    for rh, rd in zip(tr_h.records, tr_d.records):
        assert abs(rh.gap - rd.gap) < 1e-12
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_h), atol=0)
    np.testing.assert_allclose(np.asarray(a_d), np.asarray(a_h), atol=0)


def test_device_loop_super_blocks_equal_single_dispatch(tiny_data, monkeypatch):
    """When the run's index table exceeds MAX_IDX_TABLE_BYTES the device loop
    splits into multiple dispatches (bounding device memory); trajectory and
    final state must be identical, including uneven last blocks and an
    early-stop inside a block."""
    from cocoa_tpu.solvers import base

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=10)
    d = _debug(debug_iter=2)
    w_one, a_one, tr_one = run_cocoa(ds, p, d, plus=True, quiet=True,
                                     device_loop=True)
    # force ~2-chunk super-blocks → blocks of 2,2,1 chunks
    monkeypatch.setattr(base, "MAX_IDX_TABLE_BYTES",
                        4 * 2 * d.debug_iter * 4 * p.local_iters)
    base._DEVICE_RUNS.clear()
    w_b, a_b, tr_b = run_cocoa(ds, p, d, plus=True, quiet=True,
                               device_loop=True)
    np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_one), atol=0)
    np.testing.assert_allclose(np.asarray(a_b), np.asarray(a_one), atol=0)
    assert [r.round for r in tr_b.records] == [r.round for r in tr_one.records]
    for r1, rb in zip(tr_one.records, tr_b.records):
        assert abs(r1.gap - rb.gap) < 1e-12
    # early stop inside the second super-block stops at the host round
    target = float(tr_one.records[2].gap) + 1e-15
    _, _, tr_h = run_cocoa(ds, p, d, plus=True, quiet=True, gap_target=target)
    base._DEVICE_RUNS.clear()
    _, _, tr_s = run_cocoa(ds, p, d, plus=True, quiet=True, gap_target=target,
                           device_loop=True)
    assert tr_s.records[-1].round == tr_h.records[-1].round
    base._DEVICE_RUNS.clear()


def test_device_loop_gap_target_early_stop(tiny_data):
    """Device-side early stop halts at the same round the host driver does."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=40)
    d = _debug(debug_iter=2)
    target = 0.08
    _, _, tr_h = run_cocoa(ds, p, d, plus=True, quiet=True, gap_target=target)
    _, _, tr_d = run_cocoa(ds, p, d, plus=True, quiet=True, gap_target=target,
                           device_loop=True)
    assert tr_h.records[-1].gap <= target
    assert tr_d.records[-1].round == tr_h.records[-1].round
    assert abs(tr_d.records[-1].gap - tr_h.records[-1].gap) < 1e-12


def test_resume_equals_uninterrupted(tiny_data, tmp_path):
    """Checkpoint at round 5, resume to 10 → bit-identical to a straight
    10-round run (round-indexed RNG makes rounds independent of history)."""
    from cocoa_tpu import checkpoint as ck

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=10)
    w_full, a_full, _ = run_cocoa(ds, p, _debug(), plus=True, quiet=True)

    d = _debug(chkpt_iter=5, chkpt_dir=str(tmp_path))
    p5 = _params(tiny_data, num_rounds=5)
    run_cocoa(ds, p5, d, plus=True, quiet=True)
    meta, w0, a0 = ck.load(ck.latest(str(tmp_path), "CoCoA+"))
    assert meta["round"] == 5
    w_res, a_res, _ = run_cocoa(
        ds, p, _debug(), plus=True, quiet=True,
        w_init=w0, alpha_init=a0, start_round=6,
    )
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full), atol=0)
    np.testing.assert_allclose(np.asarray(a_res), np.asarray(a_full), atol=0)


def test_sgd_resume_equals_uninterrupted(tiny_data, tmp_path):
    """Local SGD: checkpoint at round 5, resume to 10 → bit-identical to a
    straight 10-round run (VERDICT r1 item 3: the reference checkpoints
    beyond CoCoA — MinibatchCD.scala:54-57 — so the rebuild's resume must
    hold for the whole menu, not just the dual-state family)."""
    from cocoa_tpu import checkpoint as ck

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=10)
    w_full, _ = run_sgd(ds, p, _debug(), local=True, quiet=True)

    d = _debug(chkpt_iter=5, chkpt_dir=str(tmp_path))
    p5 = _params(tiny_data, num_rounds=5)
    run_sgd(ds, p5, d, local=True, quiet=True)
    meta, w0, a0 = ck.load(ck.latest(str(tmp_path), "Local SGD"))
    assert meta["round"] == 5
    assert a0 is None  # SGD has no dual state
    w_res, _ = run_sgd(ds, p, _debug(), local=True, quiet=True,
                       w_init=w0, start_round=6)
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full), atol=0)


def test_dist_gd_resume_equals_uninterrupted(tiny_data, tmp_path):
    """DistGD: same resume contract (deterministic passes — only w and the
    round counter matter)."""
    from cocoa_tpu import checkpoint as ck

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=10)
    w_full, _ = run_dist_gd(ds, p, _debug(), quiet=True)

    d = _debug(chkpt_iter=5, chkpt_dir=str(tmp_path))
    p5 = _params(tiny_data, num_rounds=5)
    run_dist_gd(ds, p5, d, quiet=True)
    meta, w0, _ = ck.load(ck.latest(str(tmp_path), "Dist SGD"))
    assert meta["round"] == 5
    w_res, _ = run_dist_gd(ds, p, _debug(), quiet=True,
                           w_init=w0, start_round=6)
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full), atol=0)


def test_empty_shard_rejected(tiny_data):
    ds = shard_dataset(tiny_data, k=97, layout="dense", dtype=jnp.float64)
    with pytest.raises(ValueError, match="lower numSplits"):
        run_cocoa(ds, _params(tiny_data), _debug(), plus=True, quiet=True)
