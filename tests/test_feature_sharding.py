"""Feature-axis (fp) parallelism: the (dp, fp) 2-D mesh extension.

The reference's only parallelism is data parallelism over example shards
(SURVEY.md §2.2); the feature dimension d is the TPU-native second axis —
w and X's columns split over fp (each device holds d/fp of w and the matching
column block of every row), shard_map stays manual over dp (the one Δw psum
per round), and GSPMD auto-inserts the fp collectives for every
d-contraction.  Correctness bar: identical math to the dp-only and local
paths — same w, same alpha, same duality gap.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.parallel import DP_AXIS, FP_AXIS, make_mesh
from cocoa_tpu.parallel.mesh import has_fp, primal_sharding
from cocoa_tpu.solvers import run_cocoa, run_minibatch_cd, run_sgd

K, FP = 4, 2  # 4 dp x 2 fp = the full virtual 8-device CPU mesh


def _params(data, **kw):
    kw.setdefault("num_rounds", 10)
    kw.setdefault("local_iters", 16)
    kw.setdefault("lam", 0.01)
    return Params(n=data.n, **kw)


def _debug():
    return DebugParams(debug_iter=5, seed=11)


@pytest.fixture(scope="module")
def fp_mesh():
    return make_mesh(K, fp=FP)


def test_mesh_axes(fp_mesh):
    assert fp_mesh.axis_names == (DP_AXIS, FP_AXIS)
    assert fp_mesh.shape[DP_AXIS] == K and fp_mesh.shape[FP_AXIS] == FP
    assert has_fp(fp_mesh) and not has_fp(make_mesh(K)) and not has_fp(None)


def test_x_is_column_sharded(tiny_data, fp_mesh):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                       mesh=fp_mesh)
    d = tiny_data.num_features
    shapes = {s.data.shape for s in ds.X.addressable_shards}
    assert shapes == {(1, ds.n_shard, d // FP)}  # rows over dp, cols over fp
    # labels/alpha-like arrays: dp-sharded, fp-replicated
    assert {s.data.shape for s in ds.labels.addressable_shards} == {(1, ds.n_shard)}


def test_sparse_layout_rejected(tiny_data, fp_mesh):
    from cocoa_tpu.data import synth_sparse

    with pytest.raises(ValueError, match="dense"):
        shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float64,
                      mesh=fp_mesh)
    # auto resolves to dense on an fp mesh even for genuinely sparse data
    # (density < 10%, which auto would otherwise lay out sparse)
    sparse_data = synth_sparse(64, 512, nnz_mean=10, seed=0)
    assert sparse_data.indptr[-1] / (64 * 512) < 0.10
    ds = shard_dataset(sparse_data, k=K, layout="auto", dtype=jnp.float64,
                       mesh=fp_mesh)
    assert ds.layout == "dense"


def test_fp_pads_odd_feature_dim(tiny_data, fp_mesh):
    """d not divisible by fp: columns pad to an fp-and-sublane multiple, the
    pad tail of w stays exactly 0, and the trajectory matches the local
    run.  (Dense layouts always pad d to a multiple of 8 — the Pallas
    folded-row contract — so both runs here land on d=24.)"""
    import dataclasses as dc

    d_odd = tiny_data.num_features - 1  # 23, not divisible by FP=2
    # drop feature 23 from every row so d=23 is valid
    keep = tiny_data.indices < d_odd
    new_nnz = np.cumsum(
        [np.sum(keep[tiny_data.indptr[i]:tiny_data.indptr[i + 1]])
         for i in range(tiny_data.n)])
    odd = dc.replace(
        tiny_data,
        indptr=np.concatenate([[0], new_nnz]).astype(np.int64),
        indices=tiny_data.indices[keep],
        values=tiny_data.values[keep],
        num_features=d_odd,
    )
    params, debug = _params(odd), _debug()

    ds_local = shard_dataset(odd, k=K, layout="dense", dtype=jnp.float64)
    assert ds_local.num_features == d_odd + 1  # sublane multiple
    w0, a0, _ = run_cocoa(ds_local, params, debug, plus=True, quiet=True)
    np.testing.assert_array_equal(np.asarray(w0)[d_odd:], 0.0)

    ds_fp = shard_dataset(odd, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    assert ds_fp.num_features == d_odd + 1  # lcm(fp, 8) multiple
    np.testing.assert_array_equal(np.asarray(ds_fp.X)[..., d_odd:], 0.0)
    w1, a1, _ = run_cocoa(ds_fp, params, debug, plus=True, mesh=fp_mesh,
                          quiet=True)
    np.testing.assert_array_equal(np.asarray(w1)[d_odd:], 0.0)
    np.testing.assert_allclose(np.asarray(w1)[:d_odd],
                               np.asarray(w0)[:d_odd], atol=1e-9)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-9)


@pytest.mark.parametrize("plus", [True, False])
@pytest.mark.parametrize("math", ["exact", "fast"])
def test_cocoa_fp_matches_local(tiny_data, fp_mesh, plus, math):
    params, debug = _params(tiny_data), _debug()
    kw = dict(plus=plus, math=math, quiet=True)

    ds_local = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w0, a0, _ = run_cocoa(ds_local, params, debug, **kw)

    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w1, a1, _ = run_cocoa(ds_fp, params, debug, mesh=fp_mesh, **kw)

    assert w1.sharding.spec == primal_sharding(fp_mesh).spec
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-9)

    gap0 = objectives.duality_gap(ds_local, w0, a0, params.lam)
    gap1 = objectives.duality_gap(ds_fp, w1, a1, params.lam)
    assert gap1 >= -1e-9
    np.testing.assert_allclose(gap1, gap0, atol=1e-9)


def test_cocoa_fp_matches_dp_only(tiny_data, fp_mesh):
    # same K on a (K,) mesh and a (K, FP) mesh — identical trajectories
    params, debug = _params(tiny_data), _debug()
    mesh_dp = make_mesh(K)
    ds_dp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=mesh_dp)
    w0, a0, _ = run_cocoa(ds_dp, params, debug, plus=True, mesh=mesh_dp,
                          quiet=True)

    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w1, a1, _ = run_cocoa(ds_fp, params, debug, plus=True, mesh=fp_mesh,
                          quiet=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-9)


def test_cocoa_fp_scan_chunk(tiny_data, fp_mesh):
    # the device-side scan driver on an fp mesh — same observable trajectory
    params, debug = _params(tiny_data), _debug()
    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w0, a0, _ = run_cocoa(ds_fp, params, debug, plus=True, mesh=fp_mesh,
                          quiet=True)
    w1, a1, _ = run_cocoa(ds_fp, params, debug, plus=True, mesh=fp_mesh,
                          quiet=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-9)


def test_minibatch_cd_fp_matches_local(tiny_data, fp_mesh):
    params, debug = _params(tiny_data), _debug()
    ds_local = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w0, a0, _ = run_minibatch_cd(ds_local, params, debug, quiet=True)
    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w1, a1, _ = run_minibatch_cd(ds_fp, params, debug, mesh=fp_mesh, quiet=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-9)


def test_dist_gd_fp_matches_local(tiny_data, fp_mesh):
    from cocoa_tpu.solvers import run_dist_gd

    params, debug = _params(tiny_data), _debug()
    ds_local = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w0, _ = run_dist_gd(ds_local, params, debug, quiet=True)
    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w1, _ = run_dist_gd(ds_fp, params, debug, mesh=fp_mesh, quiet=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)


@pytest.mark.parametrize("local", [True, False])
def test_sgd_fp_matches_local(tiny_data, fp_mesh, local):
    params, debug = _params(tiny_data), _debug()
    ds_local = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w0, _ = run_sgd(ds_local, params, debug, local=local, quiet=True)
    ds_fp = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                          mesh=fp_mesh)
    w1, _ = run_sgd(ds_fp, params, debug, local=local, mesh=fp_mesh, quiet=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-9)
