"""The shard-granular persistent slab cache (data/slab_cache.py,
docs/DESIGN.md §18).

The contract under test: cached-vs-fresh slabs are BITWISE identical and
so is the downstream (w, α, gap) trajectory; a warm load parses ZERO
bytes; the key invalidates on any source-file identity change (size,
mtime_ns, inode — the coarse-mtime rewrite class included); a torn
artifact falls back to a cold parse with a typed ``ingest_cache_corrupt``
event, never a crash or a wrong slab; warm reads survive a process/mesh
GEOMETRY change (the elastic-shrink re-ingest contract — keys are
shard-granular, not geometry-keyed); and two processes racing to build
the same shard settle on one valid artifact (atomic rename, one writer
wins).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import DEMO_NUM_FEATURES, SMALL_TRAIN

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


def _assert_ds_equal(ds_a, ds_b):
    assert ds_a.layout == ds_b.layout
    assert ds_a.n == ds_b.n
    assert ds_a.num_features == ds_b.num_features
    np.testing.assert_array_equal(ds_a.counts, ds_b.counts)
    arrs_a, arrs_b = ds_a.shard_arrays(), ds_b.shard_arrays()
    assert arrs_a.keys() == arrs_b.keys()
    for f in arrs_a:
        a, b = np.asarray(arrs_a[f]), np.asarray(arrs_b[f])
        assert a.dtype == b.dtype, f
        np.testing.assert_array_equal(a, b, err_msg=f)


def test_warm_stream_build_zero_parse_bitwise(tmp_path):
    """Cold populates, warm maps: zero bytes parsed, slabs bit-identical
    to the uncached control, index scan skipped too."""
    import jax.numpy as jnp

    from cocoa_tpu.data import (SlabCache, load_libsvm, shard_dataset,
                                stream_shard_dataset)
    from cocoa_tpu.data.ingest import build_index

    d = DEMO_NUM_FEATURES
    cold_cache = SlabCache(str(tmp_path / "c"))
    ds_cold, info_cold = stream_shard_dataset(
        SMALL_TRAIN, d, 4, layout="sparse", dtype=jnp.float32,
        cache=cold_cache)
    assert info_cold.cache_status == "miss"
    assert info_cold.bytes_read == os.path.getsize(SMALL_TRAIN)

    warm_cache = SlabCache(str(tmp_path / "c"))   # fresh instance: no
    # in-process state survives — persistence is the whole point
    index = build_index(SMALL_TRAIN, d, cache=warm_cache)
    assert index.scan_bytes == 0 and index.scan_seconds == 0.0
    ds_warm, info = stream_shard_dataset(
        SMALL_TRAIN, d, 4, layout="sparse", dtype=jnp.float32,
        index=index, cache=warm_cache)
    assert info.cache_status == "hit"
    assert info.bytes_read == 0 and info.rows == 0
    assert info.shards_cached == info.shards_total == 4
    assert info.cache_bytes_mapped > 0
    assert info.seconds_saved > 0.0   # the cold run recorded its cost

    ctrl = shard_dataset(load_libsvm(SMALL_TRAIN, d), k=4,
                         layout="sparse", dtype=jnp.float32)
    _assert_ds_equal(ctrl, ds_cold)
    _assert_ds_equal(ctrl, ds_warm)
    # the cached index is bit-identical to a fresh scan
    fresh = build_index(SMALL_TRAIN, d)
    np.testing.assert_array_equal(index.row_off, fresh.row_off)
    np.testing.assert_array_equal(index.row_nnz, fresh.row_nnz)
    np.testing.assert_array_equal(index.hist, fresh.hist)


def test_whole_path_populates_and_warm_loads(tmp_path):
    """shard_dataset(cache=handle) publishes every shard; the zero-parse
    whole-path loader (ingest.load_cached_dataset) then rebuilds the
    identical dataset from the artifacts alone."""
    import jax.numpy as jnp

    from cocoa_tpu.data import SlabCache, load_libsvm, shard_dataset
    from cocoa_tpu.data.ingest import load_cached_dataset

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    cache = SlabCache(str(tmp_path / "c"))
    handle = cache.for_file(SMALL_TRAIN, d)
    ctrl = shard_dataset(data, k=4, layout="sparse", dtype=jnp.float32,
                         cache=handle)
    handle.store_index(
        hist=np.bincount(data.indices, minlength=d), n=data.n,
        total_nnz=int(data.indptr[-1]), max_row_nnz=int(data.max_nnz))

    h2 = SlabCache(str(tmp_path / "c")).for_file(SMALL_TRAIN, d)
    stats = h2.load_index()
    assert stats is not None and not stats.has_rows
    assert stats.n == data.n
    np.testing.assert_array_equal(
        stats.hist, np.bincount(data.indices, minlength=d))
    got = load_cached_dataset(h2, stats, 4, layout="sparse",
                              dtype=jnp.float32)
    assert got is not None
    ds_warm, info = got
    assert info.cache_status == "hit" and info.bytes_read == 0
    _assert_ds_equal(ctrl, ds_warm)


def test_key_invalidates_on_rewrite_and_inode_change(tmp_path):
    """The invalidation contract: a content rewrite (size/mtime change)
    misses; an atomic-rename rewrite with the SAME size and a forged
    identical mtime_ns still misses, because the inode changed — the
    coarse-mtime-filesystem aliasing class (the PR-13 checkpoint-validate
    lesson) cannot serve stale slabs."""
    import jax.numpy as jnp

    from cocoa_tpu.data import SlabCache, stream_shard_dataset

    path = tmp_path / "mut.svm"
    path.write_text("1 1:1.0\n-1 2:2.0\n1 3:3.0\n-1 1:4.0\n")
    root = str(tmp_path / "c")
    _, info = stream_shard_dataset(str(path), 10, 2, layout="sparse",
                                   dtype=jnp.float32,
                                   cache=SlabCache(root))
    assert info.cache_status == "miss"
    _, info = stream_shard_dataset(str(path), 10, 2, layout="sparse",
                                   dtype=jnp.float32,
                                   cache=SlabCache(root))
    assert info.cache_status == "hit"

    # content rewrite (different size): must re-parse
    path.write_text("1 1:9.0 2:9.0\n-1 2:2.0\n1 3:3.0\n-1 1:4.0\n")
    ds2, info = stream_shard_dataset(str(path), 10, 2, layout="sparse",
                                     dtype=jnp.float32,
                                     cache=SlabCache(root))
    assert info.cache_status == "miss"
    assert float(np.asarray(ds2.sp_values).max()) == 9.0

    # same-size atomic-rename rewrite with the mtime forged back: the
    # inode is new, so the key still changes
    st = os.stat(path)
    tmp2 = tmp_path / "mut.svm.new"
    tmp2.write_text("1 1:8.0 2:8.0\n-1 2:2.0\n1 3:3.0\n-1 1:4.0\n")
    os.replace(tmp2, path)
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
    st2 = os.stat(path)
    assert st2.st_size == st.st_size and st2.st_mtime_ns == st.st_mtime_ns
    ds3, info = stream_shard_dataset(str(path), 10, 2, layout="sparse",
                                     dtype=jnp.float32,
                                     cache=SlabCache(root))
    assert info.cache_status == "miss"
    assert float(np.asarray(ds3.sp_values).max()) == 8.0


def test_torn_artifact_falls_back_cold_with_typed_event(tmp_path):
    """The truncate-the-newest fault (tests/_faults.py): the torn slab
    fails load validation, fires ``ingest_cache_corrupt``, is evicted,
    and the shard re-parses cold — the rebuilt dataset stays
    bit-identical and the NEXT run is a clean full hit again."""
    import jax.numpy as jnp

    from _faults import truncate_newest_cache_artifact
    from cocoa_tpu.data import SlabCache, stream_shard_dataset

    root = str(tmp_path / "c")
    ds_ref, _ = stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 4,
                                     layout="sparse", dtype=jnp.float32,
                                     cache=SlabCache(root))
    truncate_newest_cache_artifact(root)([])

    corrupt = []
    cache = SlabCache(root, on_corrupt=lambda **kw: corrupt.append(kw))
    ds, info = stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 4,
                                    layout="sparse", dtype=jnp.float32,
                                    cache=cache)
    assert info.cache_status == "partial"
    assert info.shards_cached == 3 and info.shards_total == 4
    assert len(corrupt) == 1
    assert corrupt[0]["artifact"].startswith("slab-")
    assert cache.corrupt_total == 1
    _assert_ds_equal(ds_ref, ds)

    # the evicted artifact was re-published by the fallback parse
    _, info = stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 4,
                                   layout="sparse", dtype=jnp.float32,
                                   cache=SlabCache(root))
    assert info.cache_status == "hit"


def test_warm_read_across_geometry_change(tmp_path):
    """The elastic-shrink re-ingest contract: artifacts populated under
    one geometry (no mesh) serve a DIFFERENT geometry (2-device
    multiplexed dp mesh, m=2 shards per device) warm — the key is the
    shard, not the process/mesh layout — with zero bytes parsed and the
    assembled dataset bit-identical to a fresh build on that mesh."""
    import jax
    import jax.numpy as jnp

    from cocoa_tpu.data import SlabCache, stream_shard_dataset
    from cocoa_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU backend")
    root = str(tmp_path / "c")
    stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 4,
                         layout="sparse", dtype=jnp.float32,
                         cache=SlabCache(root))

    mesh = make_mesh(2)
    ds_warm, info = stream_shard_dataset(
        SMALL_TRAIN, DEMO_NUM_FEATURES, 4, layout="sparse",
        dtype=jnp.float32, mesh=mesh, cache=SlabCache(root))
    assert info.cache_status == "hit" and info.bytes_read == 0
    ds_fresh, _ = stream_shard_dataset(
        SMALL_TRAIN, DEMO_NUM_FEATURES, 4, layout="sparse",
        dtype=jnp.float32, mesh=mesh)
    _assert_ds_equal(ds_fresh, ds_warm)


@pytest.mark.slow
def test_cached_hybrid_matches_fresh_auto_resolution(tmp_path):
    """``--hotCols=auto`` resolved from the CACHED histogram equals the
    fresh whole-file resolution, the cached residual width equals the
    measured one, and the warm hybrid dataset (panel + residual + eval
    twin) is bit-identical to the fresh build."""
    import jax.numpy as jnp

    from cocoa_tpu.data import SlabCache, load_libsvm, shard_dataset
    from cocoa_tpu.data import hybrid as hybrid_lib
    from cocoa_tpu.data import stream_shard_dataset

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    k, dtype = 2, jnp.float32
    hot_fresh, _ = hybrid_lib.resolve_hot_cols("auto", data, k, dtype)

    root = str(tmp_path / "c")
    ds_cold, icold = stream_shard_dataset(
        SMALL_TRAIN, d, k, layout="sparse", dtype=dtype,
        hot_cols=hot_fresh, eval_dense=True, cache=SlabCache(root))

    cache = SlabCache(root)
    handle = cache.for_file(SMALL_TRAIN, d)
    stats = handle.load_index()
    hot_cached = hybrid_lib.resolve_hot_width("auto", stats.hist,
                                              stats.n, k, dtype)
    assert hot_cached == hot_fresh
    assert handle.load_hybrid_meta(hot_fresh) == icold.residual_max_nnz

    ds_warm, info = stream_shard_dataset(
        SMALL_TRAIN, d, k, layout="sparse", dtype=dtype,
        hot_cols=hot_cached, eval_dense=True, cache=cache)
    assert info.cache_status == "hit" and info.bytes_read == 0
    assert info.residual_max_nnz == icold.residual_max_nnz
    ctrl = shard_dataset(data, k=k, layout="sparse", dtype=dtype,
                         hot_cols=hot_fresh, eval_dense=True)
    _assert_ds_equal(ctrl, ds_warm)


def test_warm_trajectory_bit_identical(tmp_path):
    """The downstream pin: training on warm-loaded slabs produces the
    bit-identical (w, α, gap) trajectory to the uncached control."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import (SlabCache, load_libsvm, shard_dataset,
                                stream_shard_dataset)
    from cocoa_tpu.solvers import run_cocoa

    d = DEMO_NUM_FEATURES
    root = str(tmp_path / "c")
    stream_shard_dataset(SMALL_TRAIN, d, 4, layout="sparse",
                         dtype=jnp.float32, cache=SlabCache(root))
    ds_warm, info = stream_shard_dataset(
        SMALL_TRAIN, d, 4, layout="sparse", dtype=jnp.float32,
        cache=SlabCache(root))
    assert info.cache_status == "hit"
    ds_ctrl = shard_dataset(load_libsvm(SMALL_TRAIN, d), k=4,
                            layout="sparse", dtype=jnp.float32)

    params = Params(n=ds_ctrl.n, num_rounds=5, local_iters=10, lam=0.01)

    def train(ds):
        w, alpha, traj = run_cocoa(ds, params,
                                   DebugParams(debug_iter=1, seed=0),
                                   plus=True, quiet=True)
        return (np.asarray(w), np.asarray(alpha),
                np.asarray([r.gap for r in traj.records]))

    for got, want, name in zip(train(ds_warm), train(ds_ctrl),
                               ("w", "alpha", "gaps")):
        np.testing.assert_array_equal(got, want, err_msg=name)


def test_parallel_cold_parse_bit_identical(monkeypatch):
    """The pass-2 thread-pool fan-out cannot perturb a byte: a forced
    multi-worker parse builds the identical dataset (assembly is keyed
    by shard id; with the pure-Python parser the pool degrades to the
    sequential loop, which passes trivially)."""
    import jax.numpy as jnp

    from cocoa_tpu.data import ingest as ingest_lib
    from cocoa_tpu.data import load_libsvm, shard_dataset
    from cocoa_tpu.data import stream_shard_dataset

    d = DEMO_NUM_FEATURES
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    workers = ingest_lib._pass2_workers(8)
    ds, info = stream_shard_dataset(SMALL_TRAIN, d, 8, layout="sparse",
                                    dtype=jnp.float32)
    assert info.bytes_read == os.path.getsize(SMALL_TRAIN)
    ctrl = shard_dataset(load_libsvm(SMALL_TRAIN, d), k=8,
                         layout="sparse", dtype=jnp.float32)
    _assert_ds_equal(ctrl, ds)
    from cocoa_tpu.data import native_loader

    if native_loader.available():
        assert workers == 4  # the fan-out actually engaged above


def test_publish_failure_degrades_to_uncached(tmp_path, monkeypatch):
    """A cache volume that cannot be written (ENOSPC, lost permission)
    must never kill a run whose data is already parsed: every store
    degrades to uncached operation with one warning, the build completes
    bit-identically, and no temp debris is left behind."""
    import warnings

    import jax.numpy as jnp

    from cocoa_tpu.data import (SlabCache, load_libsvm, shard_dataset,
                                stream_shard_dataset)
    from cocoa_tpu.data import slab_cache as slab_cache_mod

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(slab_cache_mod.np, "save", boom)
    cache = SlabCache(str(tmp_path / "c"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ds, info = stream_shard_dataset(
            SMALL_TRAIN, DEMO_NUM_FEATURES, 4, layout="sparse",
            dtype=jnp.float32, cache=cache)
    assert info.cache_status == "miss"
    assert cache.store_failures > 0
    assert any("continuing uncached" in str(w.message) for w in caught)
    ctrl = shard_dataset(load_libsvm(SMALL_TRAIN, DEMO_NUM_FEATURES),
                         k=4, layout="sparse", dtype=jnp.float32)
    _assert_ds_equal(ctrl, ds)
    assert not any(".tmp." in e for e in os.listdir(tmp_path / "c"))


def test_store_rejects_field_drift(tmp_path):
    """A slab whose field set disagrees with the view's key is a
    LAYOUT_VERSION bug — store must refuse it loudly, never publish a
    mismatched artifact."""
    from cocoa_tpu.data import SlabCache

    cache = SlabCache(str(tmp_path / "c"))
    handle = cache.for_file(SMALL_TRAIN, DEMO_NUM_FEATURES)
    view = handle.view(layout="sparse", k=2, n_shard=16, width=4,
                       n_hot=0, d=DEMO_NUM_FEATURES, dtype=np.float32,
                       eval_dense=False)
    with pytest.raises(ValueError, match="LAYOUT_VERSION"):
        view.store(0, {"labels": np.zeros(16)})


# --- real-process pins (slow: subprocess jax imports) -----------------------

_RACE_WORKER = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import jax.numpy as jnp
from cocoa_tpu.data import SlabCache, stream_shard_dataset
path, cache_dir, out = sys.argv[1], sys.argv[2], sys.argv[3]
ds, info = stream_shard_dataset(path, 9947, 4, layout="sparse",
                                dtype=jnp.float32,
                                cache=SlabCache(cache_dir))
np.savez(out, status=np.array([info.cache_status]),
         **{f: np.asarray(v) for f, v in ds.shard_arrays().items()})
print("RACE_WORKER_DONE", flush=True)
"""


@pytest.mark.slow
def test_two_process_build_race_one_winner(tmp_path):
    """Two processes cold-build the same shard artifacts concurrently:
    both succeed, both datasets are bit-identical to the control, the
    cache holds exactly one valid artifact per shard (atomic rename —
    the loser read or discarded, never clobbered), no temp debris, and
    a third run is a clean full hit."""
    import jax.numpy as jnp

    from cocoa_tpu.data import (SlabCache, load_libsvm, shard_dataset,
                                stream_shard_dataset)

    cache_dir = str(tmp_path / "c")
    worker = tmp_path / "race_worker.py"
    worker.write_text(_RACE_WORKER)
    env = {**os.environ, "PYTHONPATH": f"{ROOT}{os.pathsep}{TESTS}",
           "JAX_PLATFORMS": "cpu"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), SMALL_TRAIN, cache_dir,
             str(tmp_path / f"out{i}.npz")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=ROOT, text=True)
        for i in range(2)
    ]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            assert p.returncode == 0, f"race worker failed:\n{out[-3000:]}"
            assert "RACE_WORKER_DONE" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    ctrl = shard_dataset(load_libsvm(SMALL_TRAIN, DEMO_NUM_FEATURES),
                         k=4, layout="sparse", dtype=jnp.float32)
    arrs_ctrl = {f: np.asarray(v)
                 for f, v in ctrl.shard_arrays().items()}
    for i in range(2):
        got = dict(np.load(tmp_path / f"out{i}.npz"))
        got.pop("status")
        assert got.keys() == arrs_ctrl.keys()
        for f in arrs_ctrl:
            np.testing.assert_array_equal(got[f], arrs_ctrl[f],
                                          err_msg=f"worker{i}: {f}")
    # no leftover temp dirs, one artifact per shard
    entries = os.listdir(cache_dir)
    assert not any(".tmp." in e for e in entries), entries
    assert sum(e.startswith("slab-") for e in entries) == 4
    _, info = stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 4,
                                   layout="sparse", dtype=jnp.float32,
                                   cache=SlabCache(cache_dir))
    assert info.cache_status == "hit" and info.bytes_read == 0


@pytest.mark.slow
def test_elastic_restart_reingests_warm_zero_bytes(tmp_path,
                                                   monkeypatch):
    """The elastic re-ingest pin (runs on ANY jax — single-worker gang):
    a supervised CLI training run with --ingestCache loses its worker to
    a SIGKILL mid-run; the relaunched generation re-ingests entirely
    from the cache — its ingest event reports cache=hit with ZERO bytes
    read — and the run completes its full round budget."""
    from _faults import Fault, FaultPlan, checkpoint_at_least, sigkill
    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import elastic
    from cocoa_tpu.telemetry import events as tele_events
    from cocoa_tpu.telemetry import schema as tele_schema

    # the spawned worker must not inherit the virtual multi-device
    # backend (this container's jax has no shard_map for the mesh path)
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f))
    monkeypatch.setenv(
        "PYTHONPATH",
        f"{ROOT}{os.pathsep}{os.environ.get('PYTHONPATH', '')}")

    ck = tmp_path / "ck"
    ev = tmp_path / "events.jsonl"
    cache_dir = tmp_path / "icache"
    argv = [
        f"--trainFile={SMALL_TRAIN}", f"--numFeatures={DEMO_NUM_FEATURES}",
        "--numSplits=4", "--numRounds=40", "--debugIter=10",
        "--localIterFrac=0.05", "--lambda=0.001", "--justCoCoA=true",
        f"--chkptDir={ck}", "--chkptIter=10", "--quiet",
        f"--ingestCache={cache_dir}", f"--events={ev}",
    ]
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=str(ev))
    try:
        plan = FaultPlan(
            Fault(generation=0, actions=(sigkill(0),),
                  trigger=checkpoint_at_least(ck, "CoCoA+", 10),
                  name="kill-worker"),
        )
        rc = elastic.supervise(argv, 1, max_restarts=3, poll_s=0.05,
                               backoff_base_s=0.0,
                               on_generation=plan.on_generation)
        plan.join()
        assert rc == 0
        assert plan.errors == []
        assert plan.fired == ["kill-worker"]
    finally:
        bus.reset()

    meta, _, _ = ckpt_lib.load(ckpt_lib.latest(str(ck), "CoCoA+"))
    assert meta["round"] == 40
    assert tele_schema.check_file(str(ev)) == []
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    ingests = [r for r in recs if r["event"] == "ingest"]
    assert len(ingests) >= 2   # one per generation
    first, last = ingests[0], ingests[-1]
    assert first["cache"] == "miss" and first["bytes_read"] > 0
    # the relaunched generation re-ingested with ZERO re-parsed bytes
    assert last["cache"] == "hit"
    assert last["bytes_read"] == 0 and last["rows"] == 0
    caches = [r for r in recs if r["event"] == "ingest_cache"]
    assert caches[-1]["status"] == "hit"
    assert caches[-1]["shards_cached"] == caches[-1]["shards_total"] == 4
