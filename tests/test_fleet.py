"""Fleet training (--fleet, solvers/fleet.py): manifest loading + the
static-shape rejections, the T=1 ≡ solo bit-identity pins across all
three drive modes, finished-tenant masking (A bitwise-frozen, B ≡ solo),
the one-compile contract, the partition-rule machinery, and the fleet
telemetry's schema validity.

Bit-identity contract (docs/DESIGN.md §16): the loop-carried STATE
(w, α, hist, sched) is pinned bitwise; the LOGGED gap may differ from
the solo log by ≤ 1 ulp at some evals — the in-loop certificate
reduction's fusion context differs between executables (the solo
device loop's own in-loop eval differs from its standalone eval the
same way) — while both remain exact certificates of the same iterate.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.analysis import sanitize
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.fleet import (
    TenantSpec, build_fleet, fleet_from_datasets, load_fleet_manifest,
    synth_fleet_specs, write_fleet_manifest,
)
from cocoa_tpu.parallel import mesh as mesh_lib
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.solvers.fleet import run_cocoa_fleet
from cocoa_tpu.telemetry import events as tele
from cocoa_tpu.telemetry import schema as tele_schema

DEBUG = DebugParams(debug_iter=10, seed=0, chkpt_iter=10**9, chkpt_dir="")


def _params(fleet, num_rounds, **kw):
    return Params(n=0, num_rounds=num_rounds,
                  local_iters=fleet.local_iters, gamma=1.0, loss="hinge",
                  **kw)


def _solo(fleet, t, num_rounds, gap_target, debug=DEBUG, **kw):
    ds = fleet.tenant_ds(t)
    sp = Params(n=ds.n, num_rounds=num_rounds,
                local_iters=fleet.local_iters, lam=float(fleet.lams[t]),
                gamma=1.0, loss="hinge", sigma=kw.pop("sigma", None))
    return run_cocoa(ds, sp, debug, plus=True, gap_target=gap_target,
                     device_loop=True, quiet=True, **kw)


def _gap_ulp_close(fleet_gaps, solo_records):
    """The logged-gap contract: the gap is primal − dual, each sum
    correct to ~1 ulp AT THE PRIMAL'S SCALE — so the two logs may differ
    by a couple of primal-scale ulps per eval, never more."""
    sg = np.array([r.gap for r in solo_records], np.float32)
    sp = np.array([r.primal for r in solo_records], np.float32)
    fg = np.asarray(fleet_gaps, np.float32)[:len(sg)]
    assert len(fg) == len(sg)
    tol = 4 * np.spacing(np.maximum(np.abs(sp), np.float32(1.0)))
    assert np.all(np.abs(fg - sg) <= tol), (fg, sg)


# --- manifest + loader ------------------------------------------------------


def test_manifest_roundtrip_and_schema(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    specs = synth_fleet_specs(3, n=64, d=16, gap_target=1e-2)
    write_fleet_manifest(path, specs)
    assert tele_schema.check_file(path) == []          # sniffed dialect
    assert tele_schema.check_file(path, kind="fleet") == []
    loaded = load_fleet_manifest(path)
    assert [s.tenant for s in loaded] == [s.tenant for s in specs]
    assert [s.lam for s in loaded] == pytest.approx(
        [s.lam for s in specs])


def test_manifest_rejects_duplicates_and_bad_lines(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"fleet_manifest": {"version": 1}}) + "\n")
        f.write(json.dumps({"tenant": "a", "dataset": "synth:dense:n=8,d=4",
                            "lam": 0.1}) + "\n")
        f.write(json.dumps({"tenant": "a", "dataset": "synth:dense:n=8,d=4",
                            "lam": 0.2}) + "\n")
    with pytest.raises(ValueError, match="duplicates"):
        load_fleet_manifest(path)
    with open(path, "w") as f:
        f.write(json.dumps({"tenant": "a", "lam": 0.1}) + "\n")
    with pytest.raises(ValueError, match="fleet_manifest header"):
        load_fleet_manifest(path)
    # a typoed optional column must fail loudly, not silently change
    # which fleet trains (manifests are user-authored input)
    with open(path, "w") as f:
        f.write(json.dumps({"fleet_manifest": {"version": 1}}) + "\n")
        f.write(json.dumps({"tenant": "a", "dataset": "synth:dense:n=8,d=4",
                            "lam": 0.1, "gap_taget": 1e-3}) + "\n")
    with pytest.raises(ValueError, match="unknown field 'gap_taget'"):
        load_fleet_manifest(path)


def test_build_fleet_rejects_shape_mismatches_with_numbers():
    # mixed d
    with pytest.raises(ValueError, match=r"d=\[8, 16\]"):
        build_fleet([
            TenantSpec("a", "synth:dense:n=64,d=16", 0.1),
            TenantSpec("b", "synth:dense:n=64,d=8", 0.1),
        ], k=2)
    # mixed H (different n at the same localIterFrac)
    with pytest.raises(ValueError, match="H ="):
        build_fleet([
            TenantSpec("a", "synth:dense:n=64,d=16", 0.1),
            TenantSpec("b", "synth:dense:n=256,d=16", 0.1),
        ], k=2, local_iter_frac=0.5)
    # mixed loss phases
    with pytest.raises(ValueError, match="one loss phase"):
        build_fleet([
            TenantSpec("a", "synth:dense:n=64,d=16", 0.1),
            TenantSpec("b", "synth:dense:n=64,d=16", 0.1,
                       loss="smooth_hinge", smoothing=0.5),
        ], k=2)
    # empty shards
    with pytest.raises(ValueError, match="lower numSplits"):
        build_fleet([TenantSpec("a", "synth:dense:n=3,d=16", 0.1)], k=4)


def test_build_fleet_dedupes_shared_dataset_refs(monkeypatch):
    """Tenants sharing a dataset ref parse it ONCE per run (the
    in-process ref memo): a T-tenant fleet over one corpus maps one
    build T times, never T parses — and the stacked slabs are bitwise
    the no-dedupe build's."""
    from cocoa_tpu.data import fleet as fleet_mod

    calls = []
    real = fleet_mod.parse_dataset_ref

    def counting(ref, num_features=0):
        calls.append(ref)
        return real(ref, num_features)

    monkeypatch.setattr(fleet_mod, "parse_dataset_ref", counting)
    shared = "synth:dense:n=64,d=16,seed=3"
    other = "synth:dense:n=64,d=16,seed=4"
    specs = [TenantSpec(tenant=f"t{i}", dataset=shared, lam=0.01)
             for i in range(4)]
    specs.append(TenantSpec(tenant="t4", dataset=other, lam=0.02))
    fleet = build_fleet(specs, k=2)
    # one parse per DISTINCT ref — the parse-count pin
    assert calls == [shared, other]
    assert fleet.t == 5
    # duplicate-ref tenants hold bitwise the same slab
    for t in range(1, 4):
        np.testing.assert_array_equal(np.asarray(fleet.X[0]),
                                      np.asarray(fleet.X[t]))
        np.testing.assert_array_equal(np.asarray(fleet.labels[0]),
                                      np.asarray(fleet.labels[t]))
    assert not np.array_equal(np.asarray(fleet.X[0]),
                              np.asarray(fleet.X[4]))


def test_build_fleet_pads_unequal_tenants_to_common_shape():
    fleet = build_fleet([
        TenantSpec("small", "synth:dense:n=48,d=16,seed=1", 0.1),
        TenantSpec("big", "synth:dense:n=96,d=16,seed=2", 0.1),
    ], k=2, local_iter_frac=0.0)   # H floors at 1 for both
    assert fleet.local_iters == 1
    assert fleet.n_shard == 48    # pad_rows(96/2) — the fleet max
    assert fleet.counts.tolist() == [[24, 24], [48, 48]]
    # the small tenant's padded rows are masked out
    assert float(fleet.mask[0].sum()) == 48.0
    assert float(fleet.mask[1].sum()) == 96.0


# --- T=1 ≡ solo bit-identity across the three drive modes -------------------


def test_t1_fleet_bitidentical_to_solo_plain():
    fleet = build_fleet(synth_fleet_specs(1, n=96, d=32, gap_target=1e-3),
                        k=2, local_iter_frac=0.25)
    res = run_cocoa_fleet(fleet, _params(fleet, 100), DEBUG, plus=True,
                          drive_mode="plain", quiet=True)
    w, a, traj = _solo(fleet, 0, 100, 1e-3)
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(w))
    assert np.array_equal(np.asarray(res.alpha[0]), np.asarray(a))
    _gap_ulp_close(res.traj[:, 0, 1], traj.records)


@pytest.mark.slow
def test_t1_fleet_bitidentical_to_solo_anneal_and_accel():
    fleet = build_fleet(synth_fleet_specs(1, n=96, d=32, gap_target=1e-3),
                        k=2, local_iter_frac=0.25)
    # anneal: sigma=auto starts at K·γ/2 and anneals toward safe
    res = run_cocoa_fleet(fleet, _params(fleet, 200, sigma="auto"), DEBUG,
                          plus=True, drive_mode="anneal", quiet=True)
    w, a, traj = _solo(fleet, 0, 200, 1e-3, sigma="auto",
                       sigma_schedule="anneal")
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(w))
    assert np.array_equal(np.asarray(res.alpha[0]), np.asarray(a))
    _gap_ulp_close(res.traj[:res.evals, 0, 1], traj.records)
    # accel: the per-tenant secant ladder vs the solo --accel=on run
    res = run_cocoa_fleet(fleet, _params(fleet, 200), DEBUG, plus=True,
                          drive_mode="accel", quiet=True)
    w, a, traj = _solo(fleet, 0, 200, 1e-3, accel="on")
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(w))
    assert np.array_equal(np.asarray(res.alpha[0]), np.asarray(a))
    _gap_ulp_close(res.traj[:res.evals, 0, 1], traj.records)


@pytest.mark.slow
def test_fleet_anneal_backs_off_in_lockstep_with_solo():
    """A genuinely diverging σ′ start (the coherent-shards forced-
    divergence config of test_sigma_anneal): the fleet lane must back
    off at the SAME round as the solo schedule and land bit-identical."""
    from test_divergence import _coherent_dataset

    ds, n = _coherent_dataset(k=4)
    fleet = fleet_from_datasets([ds], [1e-4], gap_targets=[1e-3],
                                local_iters=16)
    params = Params(n=0, num_rounds=1600, local_iters=16, sigma=1.0)
    debug = DebugParams(debug_iter=25, seed=0, chkpt_iter=10**9,
                        chkpt_dir="")
    res = run_cocoa_fleet(fleet, params, debug, plus=True,
                          drive_mode="anneal", math="fast", rng="jax",
                          quiet=True, lane_exec="map")
    sp = Params(n=n, num_rounds=1600, local_iters=16, lam=1e-4, sigma=1.0)
    w, a, traj = run_cocoa(ds, sp, debug, plus=True, quiet=True,
                           math="fast", device_loop=True, gap_target=1e-3,
                           rng="jax", sigma_schedule="anneal")
    assert traj.stopped == "target"
    assert bool(res.certified[0])
    assert int(res.cert_round[0]) == traj.records[-1].round
    # the backoff fired (stage 0 -> 1) at the same eval as solo
    stages = res.traj[:res.evals, 0, 3]
    assert stages.max() >= 1.0, "the fleet schedule never backed off"
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(w))
    assert np.array_equal(np.asarray(res.alpha[0]), np.asarray(a))


# --- finished-tenant masking ------------------------------------------------

MIXED_SPECS = [
    TenantSpec("A", "synth:dense:n=96,d=32,seed=7", lam=0.1,
               gap_target=1e-2),
    TenantSpec("B", "synth:dense:n=96,d=32,seed=8", lam=0.001,
               gap_target=1e-4),
]


def test_masking_frozen_tenant_and_solo_parity():
    """The masking contract, in the bit-parity lane mode: tenant A
    certifies early and its (w, α) is bitwise-frozen from that eval on;
    tenant B trains to the end bit-identical to its solo run."""
    debug = DebugParams(debug_iter=5, seed=0, chkpt_iter=10**9,
                        chkpt_dir="")
    fleet = build_fleet(MIXED_SPECS, k=2, local_iter_frac=0.25)
    res = run_cocoa_fleet(fleet, _params(fleet, 150), debug, plus=True,
                          drive_mode="plain", quiet=True, lane_exec="map")
    assert bool(res.certified[0]) and not bool(res.certified[1])
    r_a = int(res.cert_round[0])
    assert 0 < r_a < 150
    # A bitwise-frozen after r_a: a run stopped AT r_a holds the same A
    res_short = run_cocoa_fleet(fleet, _params(fleet, r_a), debug,
                                plus=True, drive_mode="plain", quiet=True,
                                lane_exec="map")
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(res_short.w[0]))
    assert np.array_equal(np.asarray(res.alpha[0]),
                          np.asarray(res_short.alpha[0]))
    # and A's logged certificate is frozen with it
    j_a = r_a // 5 - 1
    assert np.all(res.traj[j_a:, 0, 1] == res.traj[j_a, 0, 1])
    # B ≡ solo, bitwise
    w, a, traj = _solo(fleet, 1, 150, 1e-4, debug=debug)
    assert np.array_equal(np.asarray(res.w[1]), np.asarray(w))
    assert np.array_equal(np.asarray(res.alpha[1]), np.asarray(a))
    _gap_ulp_close(res.traj[:, 1, 1], traj.records)


@pytest.mark.slow
def test_masking_vmap_lane_mode_certifies_and_freezes():
    """The throughput (vmap) lane mode: same masking semantics — A
    frozen bitwise within the fleet's own trajectory, B within ulps of
    its solo run (batched lane reductions round independently)."""
    debug = DebugParams(debug_iter=5, seed=0, chkpt_iter=10**9,
                        chkpt_dir="")
    fleet = build_fleet(MIXED_SPECS, k=2, local_iter_frac=0.25)
    res = run_cocoa_fleet(fleet, _params(fleet, 150), debug, plus=True,
                          drive_mode="plain", quiet=True, lane_exec="vmap")
    assert bool(res.certified[0])
    r_a = int(res.cert_round[0])
    res_short = run_cocoa_fleet(fleet, _params(fleet, r_a), debug,
                                plus=True, drive_mode="plain", quiet=True,
                                lane_exec="vmap")
    assert np.array_equal(np.asarray(res.w[0]), np.asarray(res_short.w[0]))
    w, a, _ = _solo(fleet, 1, 150, 1e-4, debug=debug)
    np.testing.assert_allclose(np.asarray(res.w[1]), np.asarray(w),
                               rtol=1e-4, atol=1e-6)


# --- the one-compile / one-dispatch contract --------------------------------


def test_fleet_compiles_once_and_reuses_the_executable():
    """THE fleet acceptance invariant: one jit(run) compile serves the
    whole fleet — and a second fleet of the same shape reuses it (the
    compile amortization the models/s headline rests on)."""
    fleet = build_fleet(synth_fleet_specs(4, n=64, d=16, gap_target=1e-2),
                        k=2, local_iter_frac=0.25)
    params = _params(fleet, 50)
    with sanitize.sanitizer() as s1:
        run_cocoa_fleet(fleet, params, DEBUG, plus=True,
                        drive_mode="plain", quiet=True)
    assert s1.compile_count("run") == 1, [c.name for c in s1.compiles]
    with sanitize.sanitizer() as s2:
        run_cocoa_fleet(fleet, params, DEBUG, plus=True,
                        drive_mode="plain", quiet=True)
    assert s2.compile_count("run") == 0, [c.name for c in s2.compiles]


# --- telemetry --------------------------------------------------------------


def test_fleet_events_emitted_and_schema_valid(tmp_path):
    """The CI smoke stream: fleet_progress per eval, tenant_certified
    per certification, all schema-valid; the metrics textfile renders
    the fleet gauges."""
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    bus = tele.get_bus()
    events_path = str(tmp_path / "events.jsonl")
    metrics_path = str(tmp_path / "metrics.prom")
    bus.configure(jsonl_path=events_path)
    writer = bus.subscribe(MetricsWriter(metrics_path))
    try:
        fleet = build_fleet(
            synth_fleet_specs(3, n=64, d=16, gap_target=1e-2),
            k=2, local_iter_frac=0.25)
        res = run_cocoa_fleet(fleet, _params(fleet, 60), DEBUG, plus=True,
                              drive_mode="plain", quiet=True)
    finally:
        bus.unsubscribe(writer)
        bus.reset()
    assert tele_schema.check_file(events_path) == []
    recs = [json.loads(l) for l in open(events_path) if l.strip()]
    prog = [r for r in recs if r["event"] == "fleet_progress"]
    cert = [r for r in recs if r["event"] == "tenant_certified"]
    assert len(prog) == res.evals
    assert len(cert) == int(res.certified.sum())
    # the final progress event carries the models/s headline
    assert prog[-1]["models_per_second"] == pytest.approx(
        res.models_per_second)
    assert prog[-1]["certified_total"] == len(cert)
    text = open(metrics_path).read()
    assert "cocoa_fleet_tenants_active" in text
    assert "cocoa_tenants_certified_total " + str(len(cert)) in text
    assert "cocoa_fleet_models_per_second" in text


# --- partition rules --------------------------------------------------------


def test_match_partition_rules_first_match_wins_and_rejects_unmatched():
    from jax.sharding import PartitionSpec as P

    tree = {"w": np.zeros(2), "alpha": np.zeros(2), "sched": np.zeros(2)}
    specs = mesh_lib.match_partition_rules(
        ((r"alpha", P("tenant", None)), (r".*", P("tenant"))), tree)
    assert specs["alpha"] == P("tenant", None)
    assert specs["w"] == P("tenant") and specs["sched"] == P("tenant")
    with pytest.raises(ValueError, match="no partition rule"):
        mesh_lib.match_partition_rules(((r"alpha", P("tenant")),), tree)


def test_fleet_shardings_cover_the_whole_state_and_data_surface():
    from jax.sharding import NamedSharding

    fleet = build_fleet(synth_fleet_specs(2, n=64, d=16), k=2,
                        local_iter_frac=0.25)
    mesh = mesh_lib.make_fleet_mesh(1)   # the degenerate single-chip mesh
    tree = {"data": fleet.shard_arrays(),
            "state": {"w": np.zeros((2, 16)),
                      "alpha": np.zeros((2, 2, fleet.n_shard))}}
    sh = mesh_lib.fleet_shardings(mesh, tree)
    leaves = jax.tree.leaves(
        sh, is_leaf=lambda x: isinstance(x, NamedSharding))
    assert len(leaves) == len(jax.tree.leaves(tree))
    assert all(mesh_lib.TENANT_AXIS in s.spec for s in leaves)
