"""CLI run-level flags: --master parsing (multi-host coordinator), --profile.

The reference's --master selects Spark local vs cluster mode
(hingeDriver.scala:22-23); here local modes keep the single-process path and
host:port values name the jax.distributed coordinator.
"""

import pytest

from cocoa_tpu.cli import parse_args
from cocoa_tpu.parallel.distributed import parse_master


@pytest.mark.parametrize(
    "master,expected",
    [
        (None, None),
        ("", None),
        ("local", None),
        ("local[4]", None),
        ("local[*]", None),
        ("host0:8476", "host0:8476"),
        ("spark://host0:7077", "host0:7077"),  # drop-in for the reference URL
        ("grpc://10.0.0.1:1234", "10.0.0.1:1234"),
        ("justahost", None),  # no port — not a coordinator address
    ],
)
def test_parse_master(master, expected):
    assert parse_master(master) == expected


def test_parse_master_scheme_without_port_errors():
    # an explicit scheme requests cluster mode; silently running local would
    # train one independent copy per host
    with pytest.raises(ValueError, match="no.*port|port"):
        parse_master("spark://host0")


def test_cli_captures_run_level_flags():
    cfg, extras = parse_args(
        ["--master=local[4]", "--profile=/tmp/trace", "--processId=0",
         "--numProcesses=2", "--trainFile=x", "--numFeatures=3"]
    )
    assert extras["master"] == "local[4]"
    assert extras["profile"] == "/tmp/trace"
    assert extras["processId"] == "0"
    assert extras["numProcesses"] == "2"
    assert cfg.train_file == "x"


def test_cli_rejects_unknown_flag():
    with pytest.raises(SystemExit):
        parse_args(["--notAFlag=1"])


@pytest.mark.parametrize(
    "argv",
    [
        ["--trainFile=x", "--numFeatures=3", "--master=spark://host0"],
        ["--trainFile=x", "--numFeatures=3", "--processId=abc"],
        ["--trainFile=x", "--numFeatures=3", "--processId"],
        ["--trainFile=x", "--numFeatures=3", "--loss=nope"],
        ["--trainFile=x", "--numFeatures=3", "--loss=smooth_hinge",
         "--smoothing=0"],
    ],
)
def test_cli_bad_flags_exit_cleanly(argv, capsys):
    # malformed flags follow the CLI convention: 'error: ...' + return 2,
    # not a raw traceback
    from cocoa_tpu.cli import main

    assert main(argv) == 2
    assert "error:" in capsys.readouterr().err
