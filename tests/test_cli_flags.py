"""CLI run-level flags: --master parsing (multi-host coordinator), --profile.

The reference's --master selects Spark local vs cluster mode
(hingeDriver.scala:22-23); here local modes keep the single-process path and
host:port values name the jax.distributed coordinator.
"""

import pytest

from cocoa_tpu.cli import parse_args
from cocoa_tpu.parallel.distributed import parse_master


@pytest.mark.parametrize(
    "master,expected",
    [
        (None, None),
        ("", None),
        ("local", None),
        ("local[4]", None),
        ("local[*]", None),
        ("host0:8476", "host0:8476"),
        ("spark://host0:7077", "host0:7077"),  # drop-in for the reference URL
        ("grpc://10.0.0.1:1234", "10.0.0.1:1234"),
        ("justahost", None),  # no port — not a coordinator address
    ],
)
def test_parse_master(master, expected):
    assert parse_master(master) == expected


def test_parse_master_scheme_without_port_errors():
    # an explicit scheme requests cluster mode; silently running local would
    # train one independent copy per host
    with pytest.raises(ValueError, match="no.*port|port"):
        parse_master("spark://host0")


def test_cli_captures_run_level_flags():
    cfg, extras = parse_args(
        ["--master=local[4]", "--profile=/tmp/trace", "--processId=0",
         "--numProcesses=2", "--trainFile=x", "--numFeatures=3"]
    )
    assert extras["master"] == "local[4]"
    assert extras["profile"] == "/tmp/trace"
    assert extras["processId"] == "0"
    assert extras["numProcesses"] == "2"
    assert cfg.train_file == "x"


def test_cli_rejects_unknown_flag():
    with pytest.raises(SystemExit):
        parse_args(["--notAFlag=1"])


def test_cli_resume_covers_full_menu(tmp_path, capsys):
    """--resume restores every algorithm on the menu, not just the dual-state
    family (VERDICT r1 item 3; parity anchor MinibatchCD.scala:54-57)."""
    from conftest import SMALL_TRAIN as train

    from cocoa_tpu.cli import main

    ck = str(tmp_path / "ck")
    # --mesh=1: the single-chip vmap path, so the test exercises the full
    # resume menu even on jax builds without jax.shard_map (< 0.5) — the
    # restore plumbing under test is identical on both paths
    base = [f"--trainFile={train}", "--numFeatures=9947", "--numRounds=2",
            "--localIterFrac=0.002", "--numSplits=4", "--mesh=1",
            "--lambda=.001",
            "--justCoCoA=false", "--debugIter=1", "--chkptIter=1",
            f"--chkptDir={ck}"]
    assert main(base) == 0
    capsys.readouterr()
    assert main(base + ["--resume"]) == 0
    out = capsys.readouterr().out
    for alg in ("CoCoA+", "CoCoA", "Mini-batch CD", "Mini-batch SGD",
                "Local SGD", "Dist SGD"):
        assert f"resuming {alg} from round 2" in out, alg


@pytest.mark.parametrize(
    "argv",
    [
        ["--trainFile=x", "--numFeatures=3", "--master=spark://host0"],
        ["--trainFile=x", "--numFeatures=3", "--processId=abc"],
        ["--trainFile=x", "--numFeatures=3", "--processId"],
        ["--trainFile=x", "--numFeatures=3", "--loss=nope"],
        ["--trainFile=x", "--numFeatures=3", "--loss=smooth_hinge",
         "--smoothing=0"],
        # --sigmaSchedule: bad value; trial without --sigma=auto; anneal
        # with a sub-safe σ′ but no gap target (the stall watch the
        # backoff rides runs on the gap-target path only)
        ["--trainFile=x", "--numFeatures=3", "--sigmaSchedule=nope"],
        ["--trainFile=x", "--numFeatures=3", "--sigmaSchedule=trial"],
        ["--trainFile=x", "--numFeatures=3", "--sigmaSchedule=trial",
         "--sigma=2.0"],
        ["--trainFile=x", "--numFeatures=3", "--sigmaSchedule=anneal",
         "--sigma=2.0", "--numSplits=4"],
        ["--trainFile=x", "--numFeatures=3", "--sigmaSchedule=anneal",
         "--sigma=2.0", "--numSplits=4", "--gapTarget=1e-3",
         "--divergenceGuard=off"],
        # --warmStart: malformed pair, bad values, non-hinge loss, no evals
        ["--trainFile=x", "--numFeatures=3", "--warmStart=0.1"],
        ["--trainFile=x", "--numFeatures=3", "--warmStart=0.1,abc"],
        ["--trainFile=x", "--numFeatures=3", "--warmStart=0,300"],
        ["--trainFile=x", "--numFeatures=3", "--warmStart=0.1,300",
         "--loss=logistic"],
        ["--trainFile=x", "--numFeatures=3", "--warmStart=0.1,300",
         "--debugIter=0"],
    ],
)
def test_cli_bad_flags_exit_cleanly(argv, capsys):
    # malformed flags follow the CLI convention: 'error: ...' + return 2,
    # not a raw traceback
    from cocoa_tpu.cli import main

    assert main(argv) == 2
    assert "error:" in capsys.readouterr().err


def test_cli_hot_cols_flag_parsing(tmp_path, capsys):
    """--hotCols/--evalDense land in the run-level extras; bad values and
    layout mismatches fail with the CLI convention (error + exit 2)."""
    cfg, extras = parse_args(["--hotCols=auto", "--evalDense=auto"])
    assert extras["hotCols"] == "auto"
    assert extras["evalDense"] == "auto"

    from cocoa_tpu.cli import main
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    path = str(tmp_path / "t.dat")
    write_libsvm(synth_sparse(64, 400, nnz_mean=8, seed=0), path)
    base = [f"--trainFile={path}", "--numFeatures=400", "--numSplits=4",
            "--mesh=1"]
    assert main(base + ["--hotCols=garbage"]) == 2
    assert "auto|off" in capsys.readouterr().err
    assert main(base + ["--hotCols=-3"]) == 2
    assert "error:" in capsys.readouterr().err
    # oversized explicit panel: rejected with the HBM accounting
    import cocoa_tpu.data.hybrid as hybrid

    orig = hybrid.HOT_PANEL_HBM_BUDGET
    hybrid.HOT_PANEL_HBM_BUDGET = 1024
    try:
        assert main(base + ["--hotCols=256"]) == 2
        err = capsys.readouterr().err
        assert "HBM" in err and "MiB" in err
    finally:
        hybrid.HOT_PANEL_HBM_BUDGET = orig


def test_cli_sigma_schedule_and_warm_start_flags():
    """--sigmaSchedule / --warmStart land in the run-level extras (they
    are run_cocoa kwargs, not RunConfig fields)."""
    cfg, extras = parse_args(
        ["--sigma=auto", "--sigmaSchedule=anneal", "--warmStart=0.1,300",
         "--gapTarget=1e-4"])
    assert cfg.sigma == "auto"
    assert extras["sigmaSchedule"] == "anneal"
    assert extras["warmStart"] == "0.1,300"
    assert extras["gapTarget"] == "1e-4"


def test_cli_ingest_flag(tmp_path, capsys):
    """--ingest lands in the run-level extras; bad values and unsupported
    combinations (lasso, fp meshes) fail with the CLI convention — the
    streaming path must reject loudly, never fall back silently."""
    cfg, extras = parse_args(["--ingest=stream"])
    assert extras["ingest"] == "stream"

    from cocoa_tpu.cli import main
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    path = str(tmp_path / "t.dat")
    write_libsvm(synth_sparse(64, 400, nnz_mean=8, seed=0), path)
    base = [f"--trainFile={path}", "--numFeatures=400", "--numSplits=4",
            "--mesh=1", "--numRounds=1", "--debugIter=0"]
    assert main(base + ["--ingest=shard"]) == 2
    assert "stream|whole|auto" in capsys.readouterr().err
    assert main(base + ["--ingest=stream", "--objective=lasso",
                        "--lambda=0.1"]) == 2
    assert "lasso" in capsys.readouterr().err
    # an explicit stream on a single process still streams (exit 0): the
    # replicated build path, byte-range parsed
    assert main(base + ["--ingest=stream", "--quiet"]) == 0
    capsys.readouterr()


def test_cli_ingest_cache_flag(tmp_path, capsys):
    """--ingestCache lands in the run-level extras; lasso and --fleet
    reject it loudly (nothing shard-keyed to cache); a cache-armed run
    warms the SECOND invocation — its ingest event reports cache=hit
    with zero bytes read."""
    cfg, extras = parse_args(["--ingestCache=/tmp/x"])
    assert extras["ingestCache"] == "/tmp/x"

    from cocoa_tpu.cli import main
    from cocoa_tpu.data.fleet import synth_fleet_specs, write_fleet_manifest
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    path = str(tmp_path / "t.dat")
    write_libsvm(synth_sparse(64, 400, nnz_mean=8, seed=0), path)
    cache_dir = str(tmp_path / "icache")
    base = [f"--trainFile={path}", "--numFeatures=400", "--numSplits=4",
            "--mesh=1", "--numRounds=1", "--debugIter=0",
            f"--ingestCache={cache_dir}"]

    assert main(base + ["--objective=lasso", "--lambda=0.1"]) == 2
    assert "lasso" in capsys.readouterr().err

    manifest = str(tmp_path / "fleet.jsonl")
    write_fleet_manifest(manifest, synth_fleet_specs(2, n=32, d=8))
    assert main([f"--fleet={manifest}", "--numSplits=2",
                 f"--ingestCache={cache_dir}"]) == 2
    assert "memo" in capsys.readouterr().err

    # cold run populates, warm run hits with zero parse — checked off
    # the machine-readable ingest events
    ev1, ev2 = str(tmp_path / "e1.jsonl"), str(tmp_path / "e2.jsonl")
    assert main(base + ["--quiet", f"--events={ev1}"]) == 0
    assert main(base + ["--quiet", f"--events={ev2}"]) == 0
    capsys.readouterr()

    import json as _json

    def ingest_events(p):
        return [r for r in map(_json.loads, open(p))
                if r["event"] == "ingest"]

    cold, warm = ingest_events(ev1)[0], ingest_events(ev2)[0]
    assert cold["cache"] == "miss" and cold["bytes_read"] > 0
    assert warm["cache"] == "hit" and warm["bytes_read"] == 0


def test_cli_fleet_flag_hardening(tmp_path, capsys):
    """--fleet's surface is deliberately narrow: every flag that cannot
    mean anything on the one-dispatch tenant-vmapped path is rejected
    LOUDLY with a pointer — never accepted as a silent no-op — and
    malformed manifests fail with the schema checker's line-accurate
    messages."""
    from cocoa_tpu.cli import main
    from cocoa_tpu.data.fleet import synth_fleet_specs, write_fleet_manifest

    man = str(tmp_path / "fleet.jsonl")
    write_fleet_manifest(man, synth_fleet_specs(2, n=48, d=16,
                                                gap_target=1e-2))
    base = [f"--fleet={man}", "--numSplits=2", "--numRounds=20",
            "--debugIter=10", "--localIterFrac=0.25", "--quiet"]

    bad = [
        (["--elastic=2"], "tenant semantics"),
        (["--staleRounds=1"], "host-exchange"),
        (["--overlapComm=on"], "ONE dispatch"),
        (["--resume", "--chkptDir=x"], "v1 surface"),
        (["--chkptDir=" + str(tmp_path)], "v1 surface"),
        (["--warmStart=0.1,20", "--loss=hinge"], "loss phase"),
        (["--hotCols=auto"], "dense-layout only"),
        (["--blockSize=128", "--math=fast"], "shard axes"),
        (["--testFile=x"], "test sets"),
        (["--trainFile=x", "--numFeatures=3"], "manifest"),
        (["--objective=lasso"], "lasso"),
        (["--mesh=4"], "tenant mesh axis"),
        (["--fp=2"], "independent models"),
        (["--sampling=device"], "host-samples"),
        (["--theta=adaptive", "--accel=on", "--gapTarget=1e-3"],
         "table shape"),
        (["--sigma=auto", "--sigmaSchedule=trial", "--gapTarget=1e-3"],
         "anneal"),
        (["--accel=on", "--sigma=auto", "--gapTarget=1e-3"], "fixed safe"),
        (["--fleetLanes=turbo"], "vmap|map"),
        (["--lambda=0.5"], "comes from the manifest"),
        (["--numFeatures=7"], "dataset ref"),
        (["--gapTarget=oops"], "must be a float"),
    ]
    for extra_flags, needle in bad:
        assert main(base + extra_flags) == 2, extra_flags
        err = capsys.readouterr().err
        assert "error:" in err and needle in err, (extra_flags, err)
    # --fleetLanes without --fleet is itself rejected
    assert main(["--fleetLanes=map", "--trainFile=x",
                 "--numFeatures=3"]) == 2
    assert "needs --fleet" in capsys.readouterr().err

    # shape rejections carry the NUMBERS: a tenant that cannot pad to
    # the common static shape names the mismatched dimension
    from cocoa_tpu.data.fleet import TenantSpec

    bad_man = str(tmp_path / "bad.jsonl")
    write_fleet_manifest(bad_man, [
        TenantSpec("a", "synth:dense:n=48,d=16", 0.1, gap_target=1e-2),
        TenantSpec("b", "synth:dense:n=48,d=8", 0.1, gap_target=1e-2),
    ])
    assert main([f"--fleet={bad_man}", "--numSplits=2", "--numRounds=20",
                 "--debugIter=10", "--quiet"]) == 2
    assert "d=[8, 16]" in capsys.readouterr().err

    # and the happy path runs: per-tenant summary + the models/s line
    assert main(base[:-1]) == 0
    out = capsys.readouterr().out
    assert "models/s" in out and "tenant-0000" in out


def test_cli_ingest_stream_whole_same_result(tmp_path, capsys):
    """End-to-end CLI A/B: --ingest=stream and --ingest=whole print the
    same final summary lines (same trained model) on the same file."""
    from conftest import SMALL_TRAIN as train

    from cocoa_tpu.cli import main

    base = [f"--trainFile={train}", "--numFeatures=9947", "--numSplits=4",
            "--mesh=1", "--numRounds=2", "--debugIter=1",
            "--justCoCoA=true"]
    assert main(base + ["--ingest=whole"]) == 0
    whole = [ln for ln in capsys.readouterr().out.splitlines()
             if "primal" in ln.lower() or "gap" in ln.lower()]
    assert main(base + ["--ingest=stream"]) == 0
    stream = [ln for ln in capsys.readouterr().out.splitlines()
              if "primal" in ln.lower() or "gap" in ln.lower()]
    assert whole and whole == stream


def test_cli_serve_flag_hardening(tmp_path, capsys):
    """--serve composes only with its documented flags (the serving
    whitelist): every training flag explicitly passed alongside it is
    rejected LOUDLY with a pointer, never accepted as a silent no-op;
    malformed serve flags and missing prerequisites fail with the CLI
    convention; and a serving-incompatible width is rejected with the
    numbers."""
    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.cli import main

    ck = str(tmp_path / "ck")
    base = ["--serve=0", f"--chkptDir={ck}", "--numFeatures=16"]

    bad = [
        (["--fleet=m.jsonl"], "separate processes"),
        (["--elastic=2"], "outside the gang"),
        (["--sigmaSchedule=trial", "--sigma=auto", "--gapTarget=1e-3"],
         "trainer"),
        (["--gapTarget=1e-4"], "freshness"),
        (["--resume"], "nothing to resume"),
        (["--lambda=0.1"], "background trainer process"),
        (["--numRounds=100"], "background trainer process"),
        (["--deviceLoop"], "background trainer process"),
        (["--overlapComm=on"], "background trainer process"),
        # rejected by the staleness path's own (earlier) loud check
        (["--staleRounds=1"], "host-exchange"),
        (["--accel=on"], "background trainer process"),
        (["--warmStart=0.1,20"], "background trainer process"),
        (["--blockSize=128"], "background trainer process"),
        (["--objective=lasso"], "background trainer process"),
        (["--testFile=x"], "background trainer process"),
        (["--profile=/tmp/t"], "background trainer process"),
        (["--mesh=4"], "background trainer process"),
        (["--hotCols=auto"], "needs --trainFile"),
        # --dtype is the TRAINING precision: serving quantizes at swap
        # time behind --serveDtype, so the training flag is rejected
        # with the redirect instead of silently picking a serve form
        (["--dtype=bfloat16"], "--serveDtype"),
    ]
    for extra_flags, needle in bad:
        assert main(base + extra_flags) == 2, extra_flags
        err = capsys.readouterr().err
        assert "error:" in err and needle in err, (extra_flags, err)

    # serve flags need --serve; malformed values fail before anything runs
    assert main(["--serveBatch=64", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err
    assert main(["--serveSlaMs=50", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err
    assert main(["--serveMaxNnz=64", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err
    assert main(["--serveDtype=bf16", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err
    for bad_flag, needle in [("--serve=notaport", "TCP port"),
                             ("--serve=70000", "TCP port")]:
        assert main([bad_flag, f"--chkptDir={ck}",
                     "--numFeatures=16"]) == 2
        assert needle in capsys.readouterr().err
    for bad_flag, needle in [("--serveBatch=0,64", "ascending bucket"),
                             ("--serveBatch=oops", "ascending bucket"),
                             ("--serveSlaMs=-1", "positive latency"),
                             ("--serveSlaMs=oops", "positive latency"),
                             ("--serveMaxNnz=0", "nonzero budget"),
                             ("--serveMaxNnz=oops", "nonzero budget"),
                             ("--serveDtype=fp8", "f32"),
                             ("--serveDtype=float64", "f32")]:
        assert main(base + [bad_flag]) == 2, bad_flag
        assert needle in capsys.readouterr().err
    # --serve without --chkptDir: no model source to watch
    assert main(["--serve=0", "--numFeatures=16"]) == 2
    assert "--chkptDir" in capsys.readouterr().err

    # serving-incompatible shapes are rejected with the numbers: the
    # checkpoint carries w of width 8, the flag says 16
    ckpt_lib.save(ck, "CoCoA+", 10, np.zeros(8, np.float32), None)
    assert main(base) == 2
    err = capsys.readouterr().err
    assert "(8,)" in err and "--numFeatures=16" in err


def test_cli_fleet_serve_flag_hardening(tmp_path, capsys):
    """--serveReplicas/--serveRoute join the serve whitelist with the
    same loud-rejection convention: malformed values fail in
    milliseconds, the routing policy needs a fleet to route between,
    fleet-incompatible flags point at the v1 surface, and a replica
    count past the detected cores warns with the numbers."""
    import os

    import numpy as np

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.cli import main

    ck = str(tmp_path / "ck")
    base = ["--serve=0", f"--chkptDir={ck}", "--numFeatures=16"]

    # the fleet flags need --serve, like every serve flag
    assert main(["--serveReplicas=2", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err
    assert main(["--serveRoute=rr", f"--chkptDir={ck}",
                 "--numFeatures=16", "--trainFile=x"]) == 2
    assert "needs --serve" in capsys.readouterr().err

    # malformed replica counts fail before any JAX work
    for bad_flag in ("--serveReplicas=0", "--serveReplicas=-3",
                     "--serveReplicas=oops"):
        assert main(base + [bad_flag]) == 2, bad_flag
        assert "replica count" in capsys.readouterr().err

    # the route policy is an enum...
    assert main(base + ["--serveReplicas=2",
                        "--serveRoute=hash"]) == 2
    err = capsys.readouterr().err
    assert "rr/tenant" in err and "'hash'" in err
    # ...and needs a fleet to route between
    for route_only in (["--serveRoute=tenant"],
                       ["--serveReplicas=1", "--serveRoute=tenant"]):
        assert main(base + route_only) == 2, route_only
        assert "--serveReplicas>=2" in capsys.readouterr().err

    # per-replica hot panels are not in the fleet v1 surface
    assert main(base + ["--serveReplicas=2", "--hotCols=auto",
                        "--trainFile=x"]) == 2
    assert "fleet v1 surface" in capsys.readouterr().err

    # oversubscribing the detected cores warns WITH the numbers (paired
    # with a route typo so main exits before spawning anything)
    cores = os.cpu_count() or 1
    assert main(base + [f"--serveReplicas={cores + 1}",
                        "--serveRoute=bogus"]) == 2
    err = capsys.readouterr().err
    assert "oversubscribes" in err
    assert f"--serveReplicas={cores + 1}" in err
    assert f"{cores} detected core(s)" in err

    # a (T, d) catalogue serves f32 only in v1: quantized serving of a
    # catalogue is rejected with the shape and the pointer
    ckpt_lib.save(ck, "CoCoA+", 10, np.zeros((2, 16), np.float32),
                  None)
    assert main(base + ["--serveDtype=int8"]) == 2
    err = capsys.readouterr().err
    assert "(2, 16)" in err and "fleet v1 surface" in err
