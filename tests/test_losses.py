"""Pluggable-loss layer (ops/losses.py): analytic identities, the
Fenchel-Young inequality behind the duality-gap certificate, coordinate-step
optimality, and end-to-end convergence of every solver under each loss.

The reference is hinge-only; these losses are the extension BASELINE.md's
evaluation configs call for (the reference's local-solver boundary is
explicitly designed for swapping objectives — README.md:14, CoCoA.scala:13-14).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.ops import losses
from cocoa_tpu.solvers import run_cocoa, run_dist_gd, run_minibatch_cd, run_sgd

ALL = list(losses.LOSSES)
S = 0.7  # smooth_hinge smoothing used throughout


def _params(data, **kw):
    kw.setdefault("num_rounds", 30)
    kw.setdefault("local_iters", 24)
    kw.setdefault("lam", 0.01)
    return Params(n=data.n, **kw)


def _debug(**kw):
    kw.setdefault("debug_iter", 5)
    kw.setdefault("seed", 3)
    return DebugParams(**kw)


# ---------------------------------------------------------------- analytic

@pytest.mark.parametrize("loss", ALL)
def test_dual_term_finite_at_box_corners_f32(loss):
    # regression: in f32 an eps-clip rounds 1−1e-12 to exactly 1.0, and the
    # logistic entropy hit 0·log(0) = NaN once a coordinate saturated —
    # poisoning the duality gap and any --gapTarget early stop
    a = jnp.asarray([0.0, 1.0, 0.5], dtype=jnp.float32)
    out = losses.dual_term(loss, a, S)
    assert out.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(out)))
    if loss == "logistic":  # entropy is exactly 0 at both corners
        np.testing.assert_allclose(np.asarray(out[:2]), [0.0, 0.0])


@pytest.mark.parametrize("loss", ALL)
def test_grad_factor_is_negative_derivative(loss):
    """g(z) = −ℓ'(z) by central finite differences (away from kinks)."""
    z = np.array([-2.3, -0.4, 0.1, 0.77, 1.9, 3.2])
    if loss == "hinge":
        z = z[np.abs(z - 1.0) > 1e-3]  # kink at z=1
    if loss == "smooth_hinge":
        z = z[(np.abs(z - 1.0) > 1e-3) & (np.abs(z - (1.0 - S)) > 1e-3)]
    eps = 1e-6
    lp = np.asarray(losses.primal(loss, jnp.asarray(z + eps), smoothing=S))
    lm = np.asarray(losses.primal(loss, jnp.asarray(z - eps), smoothing=S))
    g = np.asarray(losses.grad_factor(loss, jnp.asarray(z), smoothing=S))
    np.testing.assert_allclose(-(lp - lm) / (2 * eps), g, atol=1e-5)
    assert np.all(g >= 0.0) and np.all(g <= 1.0)


def test_smooth_hinge_limits():
    """s→0 recovers the hinge everywhere; value sits between the hinge and
    the hinge minus s/2."""
    z = jnp.asarray(np.linspace(-3, 3, 61))
    hinge = np.asarray(losses.primal("hinge", z))
    tiny = np.asarray(losses.primal("smooth_hinge", z, smoothing=1e-9))
    np.testing.assert_allclose(tiny, hinge, atol=1e-8)
    sm = np.asarray(losses.primal("smooth_hinge", z, smoothing=S))
    assert np.all(sm <= hinge + 1e-12)
    assert np.all(sm >= hinge - 0.5 * S - 1e-12)


@pytest.mark.parametrize("loss", ALL)
def test_fenchel_young(loss):
    """ℓ(z) − (−ℓ*(−α)) + z·α ≥ 0 for all α ∈ [0,1] — the inequality that
    makes the duality gap a valid (non-negative) certificate."""
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=200) * 3)
    a = jnp.asarray(rng.random(200))
    lhs = (np.asarray(losses.primal(loss, z, smoothing=S))
           - np.asarray(losses.dual_term(loss, a, smoothing=S))
           + np.asarray(z) * np.asarray(a))
    assert np.all(lhs >= -1e-10)


@pytest.mark.parametrize("loss", ALL)
def test_alpha_step_maximizes_coordinate_dual(loss):
    """The SDCA update maximizes (to clipping) the scalar dual
    D(δ) = dual_term(α+δ) − z·δ/… − qii·δ²/(2λn·λn)… — verified directly:
    the returned α beats ±perturbations of itself on the subproblem."""
    rng = np.random.default_rng(1)
    lam_n = 7.3

    def coord_dual(a_new, a0, z, qii):
        # change in the global dual from moving this coordinate, ×λn·n:
        # n·Δ(−ℓ*(−α))  −  z·Δα  −  qii·Δα²/(2λn)   (derivation in losses.py)
        da = a_new - a0
        return (float(losses.dual_term(loss, jnp.asarray(a_new), smoothing=S))
                - float(losses.dual_term(loss, jnp.asarray(a0), smoothing=S))
                - (z * da + qii * da * da / (2 * lam_n)))

    for _ in range(50):
        a0 = float(rng.random())
        z = float(rng.normal() * 2)
        qii = float(rng.random() * 4 + 0.1)
        a_new = float(losses.alpha_step(
            loss, jnp.asarray(a0), jnp.asarray(z), jnp.asarray(qii), lam_n,
            smoothing=S,
        ))
        assert 0.0 <= a_new <= 1.0
        best = coord_dual(a_new, a0, z, qii)
        for eps in (1e-4, 1e-2, 0.1):
            for cand in (a_new - eps, a_new + eps):
                if 0.0 <= cand <= 1.0:
                    assert coord_dual(cand, a0, z, qii) <= best + 1e-9, (
                        f"{loss}: α={a_new} not optimal vs {cand} "
                        f"(a0={a0}, z={z}, qii={qii})"
                    )


# ---------------------------------------------------------- end-to-end

@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
@pytest.mark.parametrize("plus", [True, False])
def test_cocoa_converges_each_loss(tiny_data, loss, plus):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, loss=loss, smoothing=S)
    w, alpha, traj = run_cocoa(ds, p, _debug(), plus=plus, quiet=True)
    gaps = [r.gap for r in traj.records]
    assert all(g >= -1e-10 for g in gaps), gaps
    assert gaps[-1] < 0.3 * gaps[0], gaps
    assert np.all(np.asarray(alpha) >= 0.0) and np.all(np.asarray(alpha) <= 1.0)
    # primal-dual correspondence w = (1/λn)·Σ yᵢαᵢxᵢ holds for any loss
    X = tiny_data.to_dense()
    y, av = np.asarray(ds.labels).ravel(), np.asarray(alpha).ravel()
    mask = np.asarray(ds.mask).ravel().astype(bool)
    Xp = np.zeros((mask.size, X.shape[1]))
    Xp[np.flatnonzero(mask)] = X  # undo shard padding row-by-row
    w_re = (y[mask] * av[mask]) @ Xp[mask] / (p.lam * p.n)
    np.testing.assert_allclose(np.asarray(w), w_re, atol=1e-10)


@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
def test_fast_math_matches_exact_each_loss(tiny_data, loss):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=8, loss=loss, smoothing=S)
    w_e, a_e, _ = run_cocoa(ds, p, _debug(), plus=True, quiet=True,
                            math="exact")
    w_f, a_f, _ = run_cocoa(ds, p, _debug(), plus=True, quiet=True,
                            math="fast")
    np.testing.assert_allclose(np.asarray(w_f), np.asarray(w_e), atol=1e-8)
    np.testing.assert_allclose(np.asarray(a_f), np.asarray(a_e), atol=1e-8)


@pytest.mark.slow
@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
def test_pallas_interpret_matches_fast_each_loss(tiny_data, loss):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=5, loss=loss, smoothing=S)
    w_f, a_f, _ = run_cocoa(ds, p, _debug(), plus=True, quiet=True,
                            math="fast", pallas=False, scan_chunk=5)
    w_p, a_p, _ = run_cocoa(ds, p, _debug(), plus=True, quiet=True,
                            math="fast", pallas=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_f), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_f), atol=1e-12)


@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
def test_minibatch_cd_converges_each_loss(tiny_data, loss):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=40, loss=loss, smoothing=S)
    w, alpha, traj = run_minibatch_cd(ds, p, _debug(), quiet=True)
    gaps = [r.gap for r in traj.records]
    assert all(g >= -1e-10 for g in gaps)
    assert gaps[-1] < gaps[0]


@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
@pytest.mark.parametrize("local", [True, False])
def test_sgd_decreases_primal_each_loss(tiny_data, loss, local):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=40, loss=loss, smoothing=S)
    w, traj = run_sgd(ds, p, _debug(), local=local, quiet=True)
    primals = [r.primal for r in traj.records]
    assert primals[-1] < primals[0]


@pytest.mark.parametrize("loss", ["smooth_hinge", "logistic"])
def test_dist_gd_decreases_primal_each_loss(tiny_data, loss):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=40, loss=loss, smoothing=S)
    w, traj = run_dist_gd(ds, p, _debug(), quiet=True)
    primals = [r.primal for r in traj.records]
    assert primals[-1] < primals[0]


def test_logistic_gap_reaches_small_values(tiny_data):
    """The Newton coordinate step must be accurate enough to certify tight
    gaps — the whole point of a primal-dual method."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    p = _params(tiny_data, num_rounds=200, local_iters=24, loss="logistic")
    w, alpha, traj = run_cocoa(ds, p, _debug(debug_iter=20), plus=True,
                               quiet=True, gap_target=1e-8)
    assert traj.records[-1].gap <= 1e-8


def test_unknown_loss_rejected(tiny_data):
    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=np.float64)
    p = _params(tiny_data, loss="squared")
    with pytest.raises(ValueError, match="loss must be one of"):
        run_cocoa(ds, p, _debug(), plus=True, quiet=True)
