"""Parser golden tests against the bundled reference data
(/root/reference/data, SURVEY.md §4)."""

import os

import numpy as np
import pytest

from conftest import SMALL_TRAIN  # noqa: E402
from cocoa_tpu.data.libsvm import _parse_label, load_libsvm_python

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_small_train_shape_and_labels(small_train):
    # 2000 rows, balanced 1000/+1000− (SURVEY.md §4); d = 9947
    assert small_train.n == 2000
    assert small_train.num_features == 9947
    assert set(np.unique(small_train.labels)) == {-1.0, 1.0}
    assert int(np.sum(small_train.labels == 1.0)) == 1000


def test_small_test_shape(small_test):
    assert small_test.n == 600
    assert set(np.unique(small_test.labels)) <= {-1.0, 1.0}


def test_first_row_golden(small_train):
    # First line of small_train.dat: label 1, first pair 6:0.0198403253586671
    idx, val = small_train.row(0)
    assert small_train.labels[0] == 1.0
    assert idx[0] == 5  # 1-based → 0-based (OptUtils.scala:42)
    assert val[0] == pytest.approx(0.0198403253586671, abs=0.0)
    # indices strictly within [0, d)
    assert small_train.indices.min() >= 0
    assert small_train.indices.max() < 9947


def test_label_rule_reference_faithful():
    # OptUtils.scala:35-37: '+' or 1 → +1, everything else → −1
    assert _parse_label("+1") == 1.0
    assert _parse_label("1") == 1.0
    assert _parse_label("-1") == -1.0
    assert _parse_label("0") == -1.0
    assert _parse_label("2") == -1.0  # reference quirk #5: silently −1


def test_to_dense_roundtrip(tiny_data):
    dense = tiny_data.to_dense()
    assert dense.shape == (tiny_data.n, tiny_data.num_features)
    i = 3
    idx, val = tiny_data.row(i)
    np.testing.assert_allclose(dense[i, idx], val)
    mask = np.ones(tiny_data.num_features, bool)
    mask[idx] = False
    assert np.all(dense[i, mask] == 0)


def test_native_parser_matches_python_oracle():
    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        import pytest

        pytest.skip("native parser not built (make -C native)")
    nat = native_loader.parse_file(SMALL_TRAIN, 9947)
    py = load_libsvm_python(SMALL_TRAIN, 9947)
    np.testing.assert_array_equal(nat.labels, py.labels)
    np.testing.assert_array_equal(nat.indptr, py.indptr)
    np.testing.assert_array_equal(nat.indices, py.indices)
    np.testing.assert_array_equal(nat.values, py.values)


def test_python_parser_is_fallback_identical(small_train):
    py = load_libsvm_python(SMALL_TRAIN, 9947)
    np.testing.assert_array_equal(py.labels, small_train.labels)
    np.testing.assert_array_equal(py.indptr, small_train.indptr)
    np.testing.assert_array_equal(py.indices, small_train.indices)
    np.testing.assert_array_equal(py.values, small_train.values)


@pytest.mark.slow
def test_native_parse_memory_bounded(tmp_path):
    """native/README.md memory contract: the native parser's RSS delta on
    a big file stays under 1.2x the text size (mmap + windowed
    MADV_DONTNEED + direct-into-numpy two-pass parse; the parsed CSR
    arrays alone are ~0.85x at this nnz density).  Delta, not absolute:
    the interpreter + jax baseline is not the parser's footprint."""
    import subprocess
    import sys

    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        pytest.skip("native parser not built and no toolchain")

    path = tmp_path / "big.svm"
    rng = np.random.default_rng(0)
    rows = []
    for i in range(1000):
        idx = np.sort(rng.choice(40000, 75, replace=False)) + 1
        vals = rng.standard_normal(75)
        rows.append(("+1" if i % 2 else "-1") + " " +
                    " ".join(f"{a}:{v:.6f}" for a, v in zip(idx, vals)))
    block = ("\n".join(rows) + "\n").encode()
    with path.open("wb") as f:
        written = 0
        while written < (80 << 20):
            f.write(block)
            written += len(block)
    size = path.stat().st_size
    code = f"""
import resource, sys
sys.path.insert(0, {str(ROOT)!r})
from cocoa_tpu.data import native_loader
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
d = native_loader.parse_file({str(path)!r}, 40001)
assert d is not None and d.n > 0
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
print(peak - base)
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, check=True)
    delta = int(out.stdout.strip())
    assert delta < 1.2 * size, (delta, size)


def test_native_parser_malformed_whitespace_tails(tmp_path):
    """Whitespace after 'idx:' must end the pair list for that line, never
    let strtod's own whitespace skip run past '\\n' into the next line
    (which misparsed the next line's leading number as this pair's value)
    or past the end of an exactly-page-sized mapping (OOB read)."""
    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        pytest.skip("native parser not built (make -C native)")

    # 'idx: val' — the space after ':' makes the pair malformed; the rest
    # of the line is dropped but the NEXT line must parse intact (the old
    # code attached the next token as this pair's value).
    p1 = tmp_path / "sp.svm"
    p1.write_bytes(b"1 3: \n-1 1:7.0\n")
    d = native_loader.parse_file(str(p1), 10)
    np.testing.assert_array_equal(d.labels, [1.0, -1.0])
    np.testing.assert_array_equal(d.indptr, [0, 0, 1])
    np.testing.assert_array_equal(d.indices, [0])
    np.testing.assert_array_equal(d.values, [7.0])

    # '\v' is whitespace to strtol but was missing from the manual skip
    # set — a line ending '1 \v' must yield zero pairs, not a cross-line
    # number parse.
    p2 = tmp_path / "vt.svm"
    p2.write_bytes(b"1 \v\n-1 1:7.0\n")
    d = native_loader.parse_file(str(p2), 10)
    np.testing.assert_array_equal(d.labels, [1.0, -1.0])
    np.testing.assert_array_equal(d.indptr, [0, 0, 1])

    # Exactly-page-multiple mapping whose LAST line has the malformed
    # 'idx: ' tail: the old whitespace skip could read one byte past the
    # mmap'd region.  Blank pad lines are skipped by the parser.
    import mmap

    p3 = tmp_path / "page.svm"
    head = b"+1 1:1.0\n"
    tail = b"1 2: \n"
    pad = 2 * mmap.PAGESIZE - len(head) - len(tail)
    p3.write_bytes(head + b"\n" * pad + tail)
    assert p3.stat().st_size % mmap.PAGESIZE == 0
    d = native_loader.parse_file(str(p3), 10)
    np.testing.assert_array_equal(d.labels, [1.0, 1.0])
    np.testing.assert_array_equal(d.indptr, [0, 1, 1])
    np.testing.assert_array_equal(d.indices, [0])
    np.testing.assert_array_equal(d.values, [1.0])

    # Native and Python parsers must agree on every malformed-tail rule:
    # earlier pairs kept, rest of the line dropped, later lines intact.
    p4 = tmp_path / "parity.svm"
    p4.write_bytes(
        b"1 1:1.0 3: 5.0\n"      # space after ':'
        b"-1 1:2.0 2:3.0x 4:9\n"  # junk glued to a value
        b"1 1:4.0 2:5:6 4:9\n"    # second ':' in token
        b"-1 3.5:1.0\n"           # non-integer index
        b"1 2 3\n"                # no ':' at all
        b"-1 1:7.0\n"             # clean line after all that
    )
    nat = native_loader.parse_file(str(p4), 10)
    py = load_libsvm_python(str(p4), 10)
    np.testing.assert_array_equal(nat.labels, py.labels)
    np.testing.assert_array_equal(nat.indptr, py.indptr)
    np.testing.assert_array_equal(nat.indices, py.indices)
    np.testing.assert_array_equal(nat.values, py.values)
    np.testing.assert_array_equal(py.labels, [1, -1, 1, -1, 1, -1])
    np.testing.assert_array_equal(py.indptr, [0, 1, 2, 3, 3, 3, 4])

    # Shared-grammar parity: forms exactly one of int()/float() or
    # strtol/strtod would accept must be malformed on BOTH sides —
    # C-only hex floats / nan(...) / inf, Python-only Unicode digits and
    # digit-group underscores — and Unicode whitespace (NBSP) is an
    # ordinary junk byte, not a token delimiter, on both.
    p5 = tmp_path / "grammar.svm"
    p5.write_bytes(
        b"1 1:0x10 2:3.0\n"            # hex float value
        b"1 1:nan(0) 2:3.0\n"          # C-only nan-with-payload
        b"1 1:inf 2:3.0\n"             # C-only inf word
        b"1 \xd9\xa1:2.0\n"            # Arabic-Indic digit index (Python int() accepts)
        b"1 1:1_0.5 2:3.0\n"           # underscored float (Python float() accepts)
        b"1 1:2.0\xc2\xa03:4.0\n"      # NBSP inside the pair list
        b"0x1 1:5.0\n"                 # hex label -> -1 on both
        b"-1 1:7.0\n"                  # clean terminal line
    )
    nat = native_loader.parse_file(str(p5), 10)
    py = load_libsvm_python(str(p5), 10)
    np.testing.assert_array_equal(nat.labels, py.labels)
    np.testing.assert_array_equal(nat.indptr, py.indptr)
    np.testing.assert_array_equal(nat.indices, py.indices)
    np.testing.assert_array_equal(nat.values, py.values)
    np.testing.assert_array_equal(py.labels, [1, 1, 1, 1, 1, 1, -1, -1])
    np.testing.assert_array_equal(py.indptr, [0, 0, 0, 0, 0, 0, 0, 1, 2])
    np.testing.assert_array_equal(py.indices, [0, 0])
    np.testing.assert_array_equal(py.values, [5.0, 7.0])

    # Byte-level parity: lone '\r' is in-line whitespace (NOT a row
    # break — no universal newlines), non-UTF-8 bytes are junk (not a
    # decode crash), and indices that would wrap an int32 cast (or idx<1)
    # are malformed on both sides.
    p6 = tmp_path / "bytes.svm"
    p6.write_bytes(
        b"1 1:2.0\r2:3.0\n"           # '\r' separates pairs, same row
        b"1 1:4.0 \xff 2:6.0\n"       # raw 0xff byte: drops the tail
        b"1 4294967301:2.0 2:8.0\n"   # idx-1 wraps int32: malformed
        b"1 0:9.0 2:8.0\n"            # idx<1: malformed
        b"-1 2147483648:5.0\n"        # idx-1 == INT32_MAX: valid
    )
    nat = native_loader.parse_file(str(p6), 2**31)
    py = load_libsvm_python(str(p6), 2**31)
    np.testing.assert_array_equal(nat.labels, py.labels)
    np.testing.assert_array_equal(nat.indptr, py.indptr)
    np.testing.assert_array_equal(nat.indices, py.indices)
    np.testing.assert_array_equal(nat.values, py.values)
    np.testing.assert_array_equal(py.labels, [1, 1, 1, 1, -1])
    np.testing.assert_array_equal(py.indptr, [0, 2, 1 + 2, 1 + 2, 1 + 2, 2 + 2])
    np.testing.assert_array_equal(py.indices, [0, 1, 0, 2**31 - 1])
    np.testing.assert_array_equal(py.values, [2.0, 3.0, 4.0, 5.0])


# --- byte-range (chunk-boundary) parity -----------------------------------
#
# Streaming ingest (data/ingest.py) parses the file as byte ranges that
# tile it.  The ownership rule — a line belongs to the range containing
# its FIRST byte; the last owned line parses to its own end even past hi
# — must make any tiling parse to exactly the whole-file result, each row
# once, on BOTH parsers, byte-for-byte.  The fixture packs the nastiest
# grammar cases (malformed idx:val tail, a lone '\r', empty lines) so
# every split point lands inside one of them at some sweep position.

_RANGE_FIXTURE = (
    b"1 1:1.0 2:2.5\n"        # clean row
    b"\n"                     # empty line (no row)
    b"-1 3: \n"               # malformed tail: space after ':'
    b"1 1:4.0\r2:3.0\n"       # lone '\r' = in-line whitespace, one row
    b"\r\n"                   # '\r' alone on a line: blank row, dropped
    b"-1 2:3.0x 4:9\n"        # junk glued to a value ends the pair list
    b"1 5:6.25"               # final row without trailing newline
)


def _range_parsers(tmp_path):
    from cocoa_tpu.data import native_loader
    from cocoa_tpu.data.libsvm import load_libsvm_python_range

    parsers = [("python", load_libsvm_python_range)]
    if native_loader.available():
        parsers.append(
            ("native", lambda p, d, lo, hi: native_loader.parse_range(
                p, lo, hi, d)))
    return parsers


def _concat_ranges(parse, path, d, splits):
    """Parse [0,s1), [s1,s2), ..., [sn,size) and concatenate."""
    datas, offs = [], []
    bounds = [0, *splits, os.path.getsize(path)]
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        data, off = parse(path, d, lo, hi)
        datas.append(data)
        offs.append(off)
    labels = np.concatenate([x.labels for x in datas])
    indices = np.concatenate([x.indices for x in datas])
    values = np.concatenate([x.values for x in datas])
    nnzs = np.concatenate([np.diff(x.indptr) for x in datas])
    indptr = np.concatenate([[0], np.cumsum(nnzs)])
    return labels, indptr, indices, values, np.concatenate(offs)


def test_range_parse_tiles_to_whole_every_split(tmp_path):
    """Every single split point of the nasty fixture: the two-range parse
    equals the whole parse byte-for-byte on both parsers (the
    chunk-boundary guarantee streaming ingest stands on)."""
    path = tmp_path / "range.svm"
    path.write_bytes(_RANGE_FIXTURE)
    d = 10
    for name, parse in _range_parsers(tmp_path):
        whole, woff = parse(str(path), d, 0, len(_RANGE_FIXTURE))
        assert whole.n == 5
        np.testing.assert_array_equal(whole.labels, [1, -1, 1, -1, 1])
        for cut in range(len(_RANGE_FIXTURE) + 1):
            labels, indptr, indices, values, offs = _concat_ranges(
                parse, str(path), d, [cut])
            np.testing.assert_array_equal(labels, whole.labels, err_msg=f"{name} cut={cut}")
            np.testing.assert_array_equal(indptr, whole.indptr, err_msg=f"{name} cut={cut}")
            np.testing.assert_array_equal(indices, whole.indices, err_msg=f"{name} cut={cut}")
            np.testing.assert_array_equal(values, whole.values, err_msg=f"{name} cut={cut}")
            np.testing.assert_array_equal(offs, woff, err_msg=f"{name} cut={cut}")


def test_range_parse_native_python_parity_every_split(tmp_path):
    """Native and Python range parsers agree on every split point —
    including the row_off byte offsets (the streaming index rides them)."""
    from cocoa_tpu.data import native_loader
    from cocoa_tpu.data.libsvm import load_libsvm_python_range

    if not native_loader.available():
        pytest.skip("native parser not built (make -C native)")
    path = tmp_path / "parity_range.svm"
    path.write_bytes(_RANGE_FIXTURE)
    d = 10
    for cut in range(len(_RANGE_FIXTURE) + 1):
        for lo, hi in ((0, cut), (cut, len(_RANGE_FIXTURE))):
            py, py_off = load_libsvm_python_range(str(path), d, lo, hi)
            nat, nat_off = native_loader.parse_range(str(path), lo, hi, d)
            np.testing.assert_array_equal(nat.labels, py.labels)
            np.testing.assert_array_equal(nat.indptr, py.indptr)
            np.testing.assert_array_equal(nat.indices, py.indices)
            np.testing.assert_array_equal(nat.values, py.values)
            np.testing.assert_array_equal(nat_off, py_off)


def test_range_parse_three_way_tiling_real_file():
    """Multi-range tilings of the real small_train file reassemble the
    whole parse exactly (both parsers), at awkward uneven boundaries."""
    d = 2**31
    size = os.path.getsize(SMALL_TRAIN)
    for name, parse in _range_parsers(None):
        whole, _ = parse(SMALL_TRAIN, d, 0, size)
        for splits in ([size // 3, 2 * size // 3],
                       [1, size - 1],
                       [997, 998, size // 2 + 13]):
            labels, indptr, indices, values, _ = _concat_ranges(
                parse, SMALL_TRAIN, d, splits)
            np.testing.assert_array_equal(labels, whole.labels)
            np.testing.assert_array_equal(indptr, whole.indptr)
            np.testing.assert_array_equal(indices, whole.indices)
            np.testing.assert_array_equal(values, whole.values)


def test_to_dense_vectorized_semantics():
    """to_dense is one global scatter now; a duplicate column inside a row
    must still keep the LAST occurrence (the per-row fancy-assignment
    semantics it replaced), and empty rows stay zero."""
    from cocoa_tpu.data.libsvm import LibsvmData

    data = LibsvmData(
        labels=np.asarray([1.0, -1.0, 1.0]),
        indptr=np.asarray([0, 3, 3, 5], np.int64),
        indices=np.asarray([2, 0, 2, 1, 4], np.int32),  # row0 dups col 2
        values=np.asarray([5.0, 1.0, 7.0, 2.0, 3.0]),
        num_features=6,
    )
    out = data.to_dense()
    expect = np.zeros((3, 6))
    expect[0, 0], expect[0, 2] = 1.0, 7.0   # last occurrence wins
    expect[2, 1], expect[2, 4] = 2.0, 3.0
    np.testing.assert_array_equal(out, expect)
