"""Parser golden tests against the bundled reference data
(/root/reference/data, SURVEY.md §4)."""

import os

import numpy as np
import pytest

from cocoa_tpu.data.libsvm import _parse_label, load_libsvm_python

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_small_train_shape_and_labels(small_train):
    # 2000 rows, balanced 1000/+1000− (SURVEY.md §4); d = 9947
    assert small_train.n == 2000
    assert small_train.num_features == 9947
    assert set(np.unique(small_train.labels)) == {-1.0, 1.0}
    assert int(np.sum(small_train.labels == 1.0)) == 1000


def test_small_test_shape(small_test):
    assert small_test.n == 600
    assert set(np.unique(small_test.labels)) <= {-1.0, 1.0}


def test_first_row_golden(small_train):
    # First line of small_train.dat: label 1, first pair 6:0.0198403253586671
    idx, val = small_train.row(0)
    assert small_train.labels[0] == 1.0
    assert idx[0] == 5  # 1-based → 0-based (OptUtils.scala:42)
    assert val[0] == pytest.approx(0.0198403253586671, abs=0.0)
    # indices strictly within [0, d)
    assert small_train.indices.min() >= 0
    assert small_train.indices.max() < 9947


def test_label_rule_reference_faithful():
    # OptUtils.scala:35-37: '+' or 1 → +1, everything else → −1
    assert _parse_label("+1") == 1.0
    assert _parse_label("1") == 1.0
    assert _parse_label("-1") == -1.0
    assert _parse_label("0") == -1.0
    assert _parse_label("2") == -1.0  # reference quirk #5: silently −1


def test_to_dense_roundtrip(tiny_data):
    dense = tiny_data.to_dense()
    assert dense.shape == (tiny_data.n, tiny_data.num_features)
    i = 3
    idx, val = tiny_data.row(i)
    np.testing.assert_allclose(dense[i, idx], val)
    mask = np.ones(tiny_data.num_features, bool)
    mask[idx] = False
    assert np.all(dense[i, mask] == 0)


def test_native_parser_matches_python_oracle():
    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        import pytest

        pytest.skip("native parser not built (make -C native)")
    nat = native_loader.parse_file("/root/reference/data/small_train.dat", 9947)
    py = load_libsvm_python("/root/reference/data/small_train.dat", 9947)
    np.testing.assert_array_equal(nat.labels, py.labels)
    np.testing.assert_array_equal(nat.indptr, py.indptr)
    np.testing.assert_array_equal(nat.indices, py.indices)
    np.testing.assert_array_equal(nat.values, py.values)


def test_python_parser_is_fallback_identical(small_train):
    py = load_libsvm_python("/root/reference/data/small_train.dat", 9947)
    np.testing.assert_array_equal(py.labels, small_train.labels)
    np.testing.assert_array_equal(py.indptr, small_train.indptr)
    np.testing.assert_array_equal(py.indices, small_train.indices)
    np.testing.assert_array_equal(py.values, small_train.values)


@pytest.mark.slow
def test_native_parse_memory_bounded(tmp_path):
    """native/README.md memory contract: the native parser's RSS delta on
    a big file stays under 1.2x the text size (mmap + windowed
    MADV_DONTNEED + direct-into-numpy two-pass parse; the parsed CSR
    arrays alone are ~0.85x at this nnz density).  Delta, not absolute:
    the interpreter + jax baseline is not the parser's footprint."""
    import subprocess
    import sys

    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        pytest.skip("native parser not built and no toolchain")

    path = tmp_path / "big.svm"
    rng = np.random.default_rng(0)
    rows = []
    for i in range(1000):
        idx = np.sort(rng.choice(40000, 75, replace=False)) + 1
        vals = rng.standard_normal(75)
        rows.append(("+1" if i % 2 else "-1") + " " +
                    " ".join(f"{a}:{v:.6f}" for a, v in zip(idx, vals)))
    block = ("\n".join(rows) + "\n").encode()
    with path.open("wb") as f:
        written = 0
        while written < (80 << 20):
            f.write(block)
            written += len(block)
    size = path.stat().st_size
    code = f"""
import resource, sys
sys.path.insert(0, {str(ROOT)!r})
from cocoa_tpu.data import native_loader
base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
d = native_loader.parse_file({str(path)!r}, 40001)
assert d is not None and d.n > 0
peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
print(peak - base)
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, check=True)
    delta = int(out.stdout.strip())
    assert delta < 1.2 * size, (delta, size)
