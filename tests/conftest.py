"""Test configuration.

Distributed-without-a-cluster: the reference validates multi-worker behavior
with local-mode Spark + 4 partitions (run-demo-local.sh, hingeDriver.scala:22);
the JAX translation of that trick is a virtual 8-device CPU backend via
``--xla_force_host_platform_device_count`` — the same shard_map/psum code path
as a real TPU mesh.  x64 is enabled so tests can validate against the float64
NumPy oracle (the reference is float64 Breeze throughout).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force CPU even when axon/TPU is tunneled
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize may have force-selected the TPU platform via
# jax.config before we ran; backend init is lazy, so flipping it back here
# (before any jax.devices() call) still lands us on the virtual 8-CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


# the reference checkout's data files when present, else the identical
# copies committed under data/ (CI and reference-less containers); probed
# PER FILE so a partial reference checkout falls back too
_REF_DATA = "/root/reference/data"
_REPO_DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")


def _data_file(name):
    ref = os.path.join(_REF_DATA, name)
    return ref if os.path.exists(ref) else os.path.join(_REPO_DATA, name)


SMALL_TRAIN = _data_file("small_train.dat")
SMALL_TEST = _data_file("small_test.dat")
DEMO_NUM_FEATURES = 9947  # run-demo-local.sh:4


@pytest.fixture(scope="session")
def small_train():
    from cocoa_tpu.data import load_libsvm

    return load_libsvm(SMALL_TRAIN, DEMO_NUM_FEATURES)


@pytest.fixture(scope="session")
def small_test():
    from cocoa_tpu.data import load_libsvm

    return load_libsvm(SMALL_TEST, DEMO_NUM_FEATURES)


@pytest.fixture(scope="session")
def tiny_data():
    """Small synthetic separable-ish dataset for fast solver tests."""
    rng = np.random.default_rng(7)
    n, d = 96, 24
    w_true = rng.normal(size=d)
    X = rng.normal(size=(n, d)) * (rng.random(size=(n, d)) < 0.4)
    y = np.where(X @ w_true + 0.1 * rng.normal(size=n) > 0, 1.0, -1.0)
    from cocoa_tpu.data.libsvm import LibsvmData

    dense_rows = []
    indptr = [0]
    indices = []
    values = []
    for i in range(n):
        nz = np.nonzero(X[i])[0]
        indices.append(nz.astype(np.int32))
        values.append(X[i, nz])
        indptr.append(indptr[-1] + len(nz))
        dense_rows.append(X[i])
    return LibsvmData(
        labels=y.astype(np.float64),
        indptr=np.asarray(indptr, dtype=np.int64),
        indices=np.concatenate(indices),
        values=np.concatenate(values),
        num_features=d,
    )
