"""Gang-wide span tracing, the trace assembler, and the flight recorder
(cocoa_tpu/telemetry/tracing.py / trace_report.py / recorder.py).

What these tests pin:

- **span mechanics**: nesting/parent ids, the decorator form, the error
  attribute, and total inertness when the tracer or the bus is off;
- **the acceptance pin**: tracing-on ``(w, alpha)`` and the sched leaf
  are bit-identical to tracing-off — spans are host-side bookkeeping
  and may not perturb the run, exactly like the PR-4 telemetry bridge;
- **trace_report**: merged multi-worker streams yield a schema-valid
  Chrome/Perfetto trace, a nonempty per-round critical path over LEAF
  spans (no parent/child double counting), and a straggler table whose
  top row names the deliberately-skewed worker × phase;
- **flight recorder**: the ring is bounded, a ``divergence`` event dumps
  it, SIGTERM dumps it (real subprocess), and the supervisor-side
  ``dump_victim`` tail-reads a dead worker's stream — each dump
  validating as the schema checker's ``flightrec`` dialect;
- the satellites: ``--events`` size-capped rotation with the typed
  ``events_rotate`` record, the metrics write debounce (at most one
  rewrite per interval, trailing flush, terminal events bypass), the
  ``cocoa_phase_seconds`` gauge, and the new CLI flag validation;
- **slow, real processes**: a 2-process toy gang under the elastic
  supervisor leaves per-process span streams that trace_report merges
  into one timeline with cross-worker straggler attribution.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import elastic
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import recorder as tele_recorder
from cocoa_tpu.telemetry import schema as tele_schema
from cocoa_tpu.telemetry import trace_report, tracing
from cocoa_tpu.telemetry.metrics import MetricsWriter
from test_divergence import _coherent_dataset

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))

K, LAM = 4, 1e-4


@pytest.fixture(autouse=True)
def clean_bus_and_tracer():
    tele_events.get_bus().reset()
    tracing.reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()
    tracing.reset()


def _collect():
    events = []
    tele_events.get_bus().subscribe(events.append)
    return events


# --- span mechanics ----------------------------------------------------------


def test_span_nesting_parent_ids_and_attrs():
    events = _collect()
    tracing.configure(enabled=True, worker=3)
    with tracing.span("round", round=7) as outer:
        with tracing.span("kv_get", key="a") as inner:
            pass
    spans = [e for e in events if e["event"] == "span"]
    assert [s["phase"] for s in spans] == ["kv_get", "round"]  # close order
    inner_s, outer_s = spans
    assert inner_s["span_id"] == inner and outer_s["span_id"] == outer
    assert inner_s["parent_id"] == outer and outer_s["parent_id"] is None
    assert inner_s["worker"] == outer_s["worker"] == 3
    assert outer_s["round"] == 7 and inner_s["key"] == "a"
    assert 0.0 <= inner_s["dur_s"] <= outer_s["dur_s"]
    assert outer_s["start_ts"] <= inner_s["start_ts"] + 1.0


def test_traced_decorator_and_error_attribute():
    events = _collect()
    tracing.configure(enabled=True)

    @tracing.traced("work", kind="unit")
    def work(x):
        return x + 1

    assert work(1) == 2
    with pytest.raises(ValueError):
        with tracing.span("doomed"):
            raise ValueError("boom")
    spans = [e for e in events if e["event"] == "span"]
    assert spans[0]["phase"] == "work" and spans[0]["kind"] == "unit"
    assert spans[1]["phase"] == "doomed" and spans[1]["error"] == "ValueError"


def test_disabled_tracer_and_inert_bus_emit_nothing(tmp_path):
    events = _collect()
    with tracing.span("x"):            # tracer disabled
        pass
    tele_events.get_bus().reset()      # bus inert (no subscriber/sink)
    tracing.configure(enabled=True)
    with tracing.span("y") as sid:
        pass
    assert sid is None
    assert [e for e in events if e["event"] == "span"] == []


# --- the acceptance pin: tracing must not perturb the run --------------------


def _anneal_run(tmp_path, name):
    """A short σ′-anneal device-loop run with checkpoints (the sched
    leaf rides the checkpoint meta — the on/off comparison reads it
    there, like the telemetry on/off pin)."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=150, local_iters=16, lam=LAM,
                    sigma=1.0)
    debug = DebugParams(debug_iter=25, seed=0, chkpt_iter=75,
                        chkpt_dir=str(tmp_path / name))
    return run_cocoa(ds, params, debug, plus=True, quiet=True, math="fast",
                     device_loop=True, gap_target=1e-3, rng="jax",
                     sigma_schedule="anneal")


def test_tracing_on_vs_off_state_bit_identical(tmp_path):
    """Spans are host-side bookkeeping: a traced run's (w, alpha) and
    sched leaf are bit-identical to an untraced run."""
    tele_events.get_bus().configure(
        jsonl_path=str(tmp_path / "events.jsonl"))
    tracing.configure(enabled=True, worker=0)
    w1, a1, t1 = _anneal_run(tmp_path, "on")
    spans = [json.loads(ln)
             for ln in open(tmp_path / "events.jsonl")
             if json.loads(ln)["event"] == "span"]
    assert spans, "the traced run must actually have emitted spans"
    assert {s["phase"] for s in spans} >= {"local_solve", "checkpoint_save"}

    tele_events.get_bus().reset()
    tracing.reset()
    w2, a2, t2 = _anneal_run(tmp_path, "off")
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    names = sorted(os.listdir(tmp_path / "on"))
    assert names == sorted(os.listdir(tmp_path / "off"))
    for nm in names:
        if nm.endswith(".npz"):
            m1, _, _ = ckpt_lib.load(str(tmp_path / "on" / nm))
            m2, _, _ = ckpt_lib.load(str(tmp_path / "off" / nm))
            assert m1["sched"] == m2["sched"], nm


def test_span_stream_schema_valid_and_round_attributed(tmp_path):
    """The device-loop run's spans validate as events and trace_report
    attributes the ladder's spans to rounds via their own round attrs."""
    ev = str(tmp_path / "events.jsonl")
    tele_events.get_bus().configure(jsonl_path=ev)
    tracing.configure(enabled=True, worker=0)
    _anneal_run(tmp_path, "run")
    assert tele_schema.check_file(ev) == []
    spans = trace_report.load_spans([ev])
    assert spans
    # the device-resident path's super-block spans carry their nominal
    # end round (cadence-aligned blocks: multiples of debugIter=25), and
    # the checkpoint spans their exact round
    rounds = {s["_round"] for s in spans if s["phase"] == "local_solve"}
    assert rounds and all(r % 25 == 0 for r in rounds)
    assert {s["_round"] for s in spans
            if s["phase"] == "checkpoint_save"} >= {75, 150}
    path = trace_report.critical_path(spans)
    assert path and all(p["critical_s"] > 0 for p in path)


# --- trace_report unit -------------------------------------------------------


def _synthetic_streams(tmp_path, skew=0.01, rounds=(1, 2)):
    paths = []
    for w in (0, 1):
        tele_events.get_bus().reset()
        tracing.reset()
        p = str(tmp_path / f"ev{w}.jsonl")
        paths.append(p)
        tele_events.get_bus().configure(jsonl_path=p)
        tracing.configure(enabled=True, worker=w)
        for t in rounds:
            with tracing.span("round", round=t):
                with tracing.span("kv_allgather"):
                    time.sleep(0.002 + (skew if w == 1 else 0.0))
                with tracing.span("local_step"):
                    time.sleep(0.002)
    tele_events.get_bus().reset()
    tracing.reset()
    return paths


def test_trace_report_merge_critical_path_and_stragglers(tmp_path):
    paths = _synthetic_streams(tmp_path)
    spans = trace_report.load_spans(paths)
    assert len(spans) == 12 and len({s["pid"] for s in spans}) == 1
    # leaf-only attribution: the `round` container never shows up in the
    # critical path or the straggler table (its children carry the time)
    cp = trace_report.critical_path(spans)
    assert [c["round"] for c in cp] == [1, 2]
    for c in cp:
        phases = {e["phase"] for e in c["entries"]}
        assert phases == {"kv_allgather", "local_step"}
        assert all(e["workers"] == 2 for e in c["entries"])
        assert c["critical_s"] >= 0.004
    rows = trace_report.stragglers(spans)
    assert rows[0]["worker"] == 1 and rows[0]["phase"] == "kv_allgather"
    assert rows[0]["slack_s"] > 0.01
    assert {(r["worker"], r["phase"]) for r in rows} == {
        (0, "kv_allgather"), (0, "local_step"),
        (1, "kv_allgather"), (1, "local_step")}
    # the metrics rendering carries both gauges, labeled worker x phase
    text = trace_report.metrics_text(spans)
    assert 'cocoa_straggler_slack_seconds{worker="1",' \
           'phase="kv_allgather"}' in text
    assert 'cocoa_phase_seconds{worker="0",phase="local_step"}' in text


def _leaf(worker, phase, start, dur, round_=1, sid=[0], **attrs):
    sid[0] += 1
    return {"event": "span", "phase": phase, "span_id": sid[0],
            "parent_id": None, "worker": worker, "pid": 100 + worker,
            "start_ts": float(start), "dur_s": float(dur),
            "_round": round_, "round": round_, **attrs}


def test_critical_path_charges_overlapped_same_worker_leaves():
    """The ISSUE-12 satellite pin: leaf spans on ONE worker are no
    longer assumed disjoint — an `--overlapComm` collector's kv_get
    runs concurrently with the main thread.  Per worker each wall-clock
    second is charged to exactly one covering span (foreground beats
    the `overlapped` background collector; latest-started owns within a
    class), so hidden exchange time cannot double-count into the
    critical path or the slack table; disjoint spans keep the old
    summed values exactly."""
    # worker 0: a 1.0s local_solve [10, 11) fully hiding a 0.8s
    # background kv_get [10.1, 10.9); worker 1: sequential (sync mode)
    spans = [
        _leaf(0, "local_solve", 10.0, 1.0),
        _leaf(0, "kv_get", 10.1, 0.8, overlapped=True),   # hidden
        _leaf(1, "local_solve", 10.0, 1.0),
        _leaf(1, "kv_get", 11.0, 0.8),       # sequential: fully charged
    ]
    trace_report.attribute_rounds(spans)
    table = trace_report._per_round_phase_durs(spans)
    assert table[1]["local_solve"][0] == pytest.approx(1.0)
    assert table[1]["kv_get"][0] == pytest.approx(0.0)    # fully hidden
    assert table[1]["local_solve"][1] == pytest.approx(1.0)
    assert table[1]["kv_get"][1] == pytest.approx(0.8)
    # the critical path no longer credits worker 0 with 1.8s of a 1.0s
    # wall-clock window: kv_get's slowest worker is now worker 1
    cp = trace_report.critical_path(spans)
    by_phase = {e["phase"]: e for e in cp[0]["entries"]}
    assert by_phase["kv_get"]["worker"] == 1
    assert cp[0]["critical_s"] == pytest.approx(1.8)
    # and the slack table attributes the exchange wait to the worker
    # that actually paid it on its main thread
    rows = trace_report.stragglers(spans)
    kv = {r["worker"]: r["slack_s"] for r in rows
          if r["phase"] == "kv_get"}
    assert kv[1] == pytest.approx(0.8)
    assert kv[0] == pytest.approx(0.0)


def test_charged_same_phase_overlap_unions_not_sums():
    """Two overlapping same-phase leaves on one worker charge their
    UNION (the pre-fix sum double-counted the overlap); a third
    disjoint leaf still adds fully."""
    spans = [
        _leaf(0, "kv_get", 0.0, 1.0),
        _leaf(0, "kv_get", 0.5, 1.0),        # overlaps [0.5, 1.0)
        _leaf(0, "kv_get", 3.0, 0.25),       # disjoint
        _leaf(1, "kv_get", 0.0, 0.1),
    ]
    trace_report.attribute_rounds(spans)
    table = trace_report._per_round_phase_durs(spans)
    assert table[1]["kv_get"][0] == pytest.approx(1.75)   # union, not 2.25
    assert table[1]["kv_get"][1] == pytest.approx(0.1)
    # torn stream (no start_ts): falls back to the span's own duration
    torn = [_leaf(0, "kv_get", 0.0, 0.5)]
    torn[0].pop("start_ts")
    trace_report.attribute_rounds(torn)
    assert trace_report._per_round_phase_durs(torn)[1]["kv_get"][0] \
        == pytest.approx(0.5)


def test_trace_report_chrome_trace_valid_and_checker_has_teeth(tmp_path):
    paths = _synthetic_streams(tmp_path, rounds=(1,))
    spans = trace_report.load_spans(paths)
    trace = trace_report.chrome_trace(spans)
    assert trace_report.check_chrome_trace(trace) == []
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}   # one track per worker
    assert all(e["dur"] >= 0 and isinstance(e["name"], str) for e in xs)
    # the checker rejects what Perfetto would reject
    assert trace_report.check_chrome_trace({"traceEvents": "nope"}) != []
    assert trace_report.check_chrome_trace(
        {"traceEvents": [{"ph": "X", "name": "x", "pid": 0, "tid": 0,
                          "ts": 1.0, "dur": -5.0}]}) != []
    assert trace_report.check_chrome_trace(
        {"traceEvents": [{"ph": "Q", "name": "x", "pid": 0, "tid": 0}]}) \
        != []


def test_trace_report_cli_writes_artifacts(tmp_path, capsys):
    paths = _synthetic_streams(tmp_path, rounds=(1, 2))
    out = str(tmp_path / "trace.json")
    prom = str(tmp_path / "straggler.prom")
    rc = trace_report.main([*paths, f"--trace={out}", f"--metrics={prom}"])
    assert rc == 0
    trace = json.load(open(out))
    assert trace_report.check_chrome_trace(trace) == []
    assert "cocoa_straggler_slack_seconds" in open(prom).read()
    assert "critical path" in capsys.readouterr().out
    # no spans -> exit 1; usage -> exit 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert trace_report.main([str(empty)]) == 1
    assert trace_report.main([]) == 2
    assert trace_report.main(["--bogus"]) == 2


# --- events rotation ---------------------------------------------------------


def test_events_rotation_size_cap_and_typed_event(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=ev, max_bytes=2048)
    for i in range(60):
        bus.emit("host_transfer", label="x" * 40)
    assert os.path.exists(ev + ".1"), "the cap must have rotated"
    assert os.path.getsize(ev + ".1") <= 4096
    head = json.loads(open(ev).readline())
    assert head["event"] == "events_rotate"       # first line of the
    assert head["rotated_to"] == ev + ".1"        # fresh file
    assert head["bytes"] >= 2048
    assert tele_schema.check_file(ev) == []
    assert tele_schema.check_file(ev + ".1") == []
    # rotation keeps exactly one predecessor (~2x the cap on disk, total)
    assert not os.path.exists(ev + ".2")


# --- metrics debounce + phase gauge ------------------------------------------


def _eval_event(t, ts):
    return {"event": "round_eval", "seq": t, "ts": ts, "algorithm": "X",
            "t": t, "primal": 1.0, "gap": 0.5, "test_error": None,
            "sigma": None, "stall": None}


def test_metrics_debounce_coalesces_and_flushes(tmp_path, monkeypatch):
    import cocoa_tpu.telemetry.metrics as metrics_mod

    writes = []
    real_replace = os.replace

    def counting_replace(a, b):
        writes.append(b)
        return real_replace(a, b)

    monkeypatch.setattr(metrics_mod.os, "replace", counting_replace)
    w = MetricsWriter(str(tmp_path / "m.prom"), flush_interval_s=30.0)
    base = len(writes)                  # the __init__ write
    for t in range(1, 21):
        w(_eval_event(t, float(t)))
    # one immediate write (interval elapsed since _last_write=0 epoch is
    # false: first event within interval of init write) — all 20 events
    # coalesce to at most one rewrite
    assert len(writes) - base <= 1
    w.flush()
    text = open(tmp_path / "m.prom").read()
    assert "cocoa_evals_total 20" in text  # the trailing flush converged
    # terminal events bypass the debounce
    before = len(writes)
    w({"event": "run_end", "seq": 99, "ts": 99.0, "algorithm": "X",
       "primal": 1.0, "stopped": "target"})
    assert len(writes) == before + 1


def test_metrics_default_interval_unchanged(tmp_path, monkeypatch):
    """flush_interval_s=0 (the default) keeps the original one-rewrite-
    per-event behavior — nothing changes for existing consumers."""
    import cocoa_tpu.telemetry.metrics as metrics_mod

    writes = []
    real_replace = os.replace
    monkeypatch.setattr(
        metrics_mod.os, "replace",
        lambda a, b: (writes.append(b), real_replace(a, b))[1])
    w = MetricsWriter(str(tmp_path / "m.prom"))
    base = len(writes)
    for t in range(1, 6):
        w(_eval_event(t, float(t)))
    assert len(writes) - base == 5


def test_metrics_phase_seconds_gauge(tmp_path):
    path = str(tmp_path / "m.prom")
    w = MetricsWriter(path)
    for ph, d in (("eval", 0.25), ("local_solve", 1.0), ("eval", 0.25)):
        w({"event": "span", "seq": 1, "ts": 1.0, "phase": ph,
           "span_id": 1, "parent_id": None, "worker": 0,
           "start_ts": 1.0, "dur_s": d})
    text = open(path).read()
    assert 'cocoa_phase_seconds{phase="eval"} 0.5' in text
    assert 'cocoa_phase_seconds{phase="local_solve"} 1.0' in text
    # the supervisor's gang-families sibling never renders phase seconds
    # (it would duplicate the worker's family for textfile collectors)
    g = MetricsWriter(str(tmp_path / "m.gang"), families="gang")
    g({"event": "span", "seq": 1, "ts": 1.0, "phase": "eval",
       "span_id": 1, "parent_id": None, "worker": None,
       "start_ts": 1.0, "dur_s": 1.0})
    assert "cocoa_phase_seconds" not in open(tmp_path / "m.gang").read()


# --- flight recorder ---------------------------------------------------------


def test_recorder_ring_bounded_and_divergence_dump(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=ev)
    rec = tele_recorder.install(bus, ev, capacity=16, signals=False)
    for i in range(50):
        bus.emit("host_transfer", label=f"t{i}")
    assert len(rec.ring) == 16           # bounded
    bus.emit("divergence", algorithm="X", t=100, n_evals=12)
    assert rec.dumps and rec.dumps[-1][0] == "divergence"
    path = ev + ".flightrec"
    assert tele_schema.check_file(path) == []
    lines = [json.loads(ln) for ln in open(path)]
    man = lines[0]["flightrec_manifest"]
    assert man["reason"] == "divergence" and man["n_events"] == 16
    assert lines[-1]["event"] == "divergence"   # the trigger is on the ring
    assert lines[1]["label"] == "t35"           # oldest retained = 50-15


def test_recorder_dump_victim_tails_stream(tmp_path):
    # synthesize a dead worker-1 stream, as the per-process convention
    # lays it out, then dump on its behalf like the supervisor does
    base = str(tmp_path / "events.jsonl")
    stream = tele_recorder.worker_stream_path(base, 1)
    assert stream == base + ".p1"
    with open(stream, "w") as f:
        for t in range(1, 31):
            f.write(json.dumps(
                {"event": "checkpoint_write", "seq": t, "pid": 4242,
                 "ts": float(t), "algorithm": "Toy", "round": t,
                 "path": "x"}) + "\n")
        f.write('{"event": "span", "seq": 31, "pid": 4242, "ts": 31.0, '
                '"phase": "round", "span_id"')   # torn final line (kill)
    out = tele_recorder.dump_victim(base, 1, "worker_died", exit_code=-9,
                                    generation=2, last_n=10)
    assert out == stream + ".flightrec"
    assert tele_schema.check_file(out) == []
    lines = [json.loads(ln) for ln in open(out)]
    man = lines[0]["flightrec_manifest"]
    assert man["reason"] == "worker_died" and man["exit_code"] == -9
    assert man["victim_index"] == 1 and man["generation"] == 2
    assert len(lines) == 11 and lines[-1]["round"] == 30
    # a worker that left no stream yields no dump (and no exception)
    assert tele_recorder.dump_victim(base, 7, "worker_died") is None


def test_recorder_sigterm_dump_real_process(tmp_path):
    """A real subprocess with the recorder installed dies by SIGTERM and
    leaves a validated dump with reason 'sigterm' — and still dies with
    the termination status its supervisor expects."""
    ev = str(tmp_path / "events.jsonl")
    code = f"""
import os, signal
from cocoa_tpu.telemetry import events, recorder
bus = events.get_bus()
bus.configure(jsonl_path={ev!r})
rec = recorder.install(bus, {ev!r})
for i in range(5):
    bus.emit("host_transfer", label=f"t{{i}}")
os.kill(os.getpid(), signal.SIGTERM)
"""
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGTERM
    path = ev + ".flightrec"
    assert tele_schema.check_file(path) == []
    man = json.loads(open(path).readline())["flightrec_manifest"]
    assert man["reason"] == "sigterm" and man["n_events"] == 5


def test_recorder_sigterm_honors_sig_ign(tmp_path):
    """A process that deliberately ignored SIGTERM before the recorder
    installed must still dump — and still survive the signal (the
    handler honors the previous SIG_IGN disposition)."""
    ev = str(tmp_path / "events.jsonl")
    code = f"""
import os, signal
signal.signal(signal.SIGTERM, signal.SIG_IGN)
from cocoa_tpu.telemetry import events, recorder
bus = events.get_bus()
bus.configure(jsonl_path={ev!r})
rec = recorder.install(bus, {ev!r})
bus.emit("host_transfer", label="x")
os.kill(os.getpid(), signal.SIGTERM)
print("survived")
"""
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0 and "survived" in proc.stdout
    man = json.loads(open(ev + ".flightrec").readline())
    assert man["flightrec_manifest"]["reason"] == "sigterm"


def test_flightrec_schema_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.flightrec"
    bad.write_text(json.dumps({"flightrec_manifest": {"reason": "x"}})
                   + "\n" + json.dumps({"event": "nonsense", "seq": 1,
                                        "ts": 1.0}) + "\n")
    errs = tele_schema.check_file(str(bad))
    assert any("n_events" in e for e in errs)
    assert any("nonsense" in e for e in errs)


# --- CLI flag surface --------------------------------------------------------


def test_cli_flag_validation(tmp_path, capsys):
    from cocoa_tpu import cli

    base = [f"--trainFile={ROOT}/data/small_train.dat",
            "--numFeatures=9947", "--numSplits=4", "--numRounds=2",
            "--debugIter=2", "--localIterFrac=0.1", "--quiet"]
    assert cli.main([*base, "--trace"]) == 2            # no sink
    assert cli.main([*base, "--flightRecorder=on"]) == 2  # needs events
    assert cli.main([*base, "--flightRecorder=maybe",
                     f"--events={tmp_path}/e.jsonl"]) == 2
    assert cli.main([*base, "--eventsMaxMB=0",
                     f"--events={tmp_path}/e.jsonl"]) == 2
    assert cli.main([*base, "--eventsMaxMB=4"]) == 2    # needs events
    assert cli.main([*base, "--metricsInterval=1"]) == 2  # needs metrics
    assert cli.main([*base, "--metricsInterval=-1",
                     f"--metrics={tmp_path}/m.prom"]) == 2
    capsys.readouterr()


# --- real-process gang: span streams merge + straggler attribution -----------


def _gang_env(monkeypatch):
    monkeypatch.setenv(
        "PYTHONPATH",
        f"{ROOT}{os.pathsep}{TESTS}{os.pathsep}"
        f"{os.environ.get('PYTHONPATH', '')}")
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f))


@pytest.mark.slow
def test_gang_trace_report_merges_and_names_the_straggler(tmp_path,
                                                          monkeypatch):
    """THE tracing acceptance pin: a REAL 2-process gang (toy worker:
    real rendezvous, per-round KV allgather, checkpoints) run with
    --trace leaves one span stream per process; trace_report merges them
    into a schema-valid Perfetto trace with a nonempty per-round
    critical path, and the straggler table's top row names the
    deliberately-skewed worker 1 × local_step."""
    _gang_env(monkeypatch)
    ck = tmp_path / "ck"
    ev = str(tmp_path / "events.jsonl")
    rc = elastic.supervise(
        [f"--chkptDir={ck}", "--numSplits=4", "--numRounds=8",
         "--chkptIter=4", "--stepSeconds=0.02", "--stepSkew=0.05",
         f"--events={ev}", "--trace"],
        2, module="_gang_worker", max_restarts=0, poll_s=0.05,
        backoff_base_s=0.0)
    assert rc == 0
    streams = [ev, ev + ".p1"]
    for s in streams:
        assert os.path.exists(s), s
        assert tele_schema.check_file(s) == []
    spans = trace_report.load_spans(streams)
    workers = {trace_report.worker_of(s) for s in spans}
    assert workers == {0, 1}

    trace = trace_report.chrome_trace(spans)
    assert trace_report.check_chrome_trace(trace) == []

    path = trace_report.critical_path(spans)
    assert [p["round"] for p in path] == list(range(1, 9))
    assert all(p["critical_s"] > 0 for p in path)
    # both workers reported the per-round phases the path is built from
    for p in path:
        by_phase = {e["phase"]: e for e in p["entries"]}
        assert by_phase["local_step"]["workers"] == 2
        assert by_phase["kv_get"]["workers"] == 2

    rows = trace_report.stragglers(spans)
    assert rows, "straggler table must be nonempty"
    top = rows[0]
    # worker 1 sleeps 50ms longer per round — 8 rounds of ~50ms slack
    assert top["worker"] == 1 and top["phase"] == "local_step"
    assert top["slack_s"] > 0.2


@pytest.mark.slow
def test_gang_metrics_ownership_worker0_vs_supervisor_gang_file(
        tmp_path, monkeypatch):
    """The PR-9 sibling-file contract under a REAL gang, now pinned:
    worker 0 owns `<metrics>` (worker families only — no gang series),
    the supervisor owns `<metrics>.gang` (gang families only), so a
    textfile collector globbing the directory never sees a duplicated
    family."""
    _gang_env(monkeypatch)
    ck = tmp_path / "ck"
    metrics = str(tmp_path / "metrics.prom")
    bus = tele_events.get_bus()
    bus.configure(jsonl_path=str(tmp_path / "events.jsonl"))
    bus.subscribe(MetricsWriter(metrics + ".gang", families="gang"))
    rc = elastic.supervise(
        [f"--chkptDir={ck}", "--numSplits=4", "--numRounds=6",
         "--chkptIter=3", "--stepSeconds=0.02",
         f"--events={tmp_path / 'events.jsonl'}",
         f"--metrics={metrics}"],
        2, module="_gang_worker", max_restarts=0, poll_s=0.05,
        backoff_base_s=0.0)
    assert rc == 0
    worker_text = open(metrics).read()
    gang_text = open(metrics + ".gang").read()

    def families(text):
        return {line.split(" ", 1)[0].split("{", 1)[0]
                for line in text.splitlines()
                if line and not line.startswith("#")}

    wf, gf = families(worker_text), families(gang_text)
    # worker 0 saw its own checkpoint_write events (chkptIter=3)
    assert "cocoa_rounds_total" in wf and "cocoa_evals_total" in wf
    # strictly disjoint families across the sibling files
    assert wf & gf == set(), (wf, gf)
    assert gf == {"cocoa_gang_generations_total"}  # healthy run: no
    #                                              # resize/backoff gauges
    for name in ("cocoa_gang_size", "cocoa_gang_generations_total",
                 "cocoa_restart_backoff_seconds"):
        assert name not in wf
