"""Block-coordinate inner solver (``--blockSize``, VERDICT r1 item 2).

``local_sdca_block`` consumes the SAME sampled index stream as the
sequential fast path and is identical to it in real arithmetic (the running
Δw dot is replaced by cached block Gram contributions — see the kernel
docstring), so the contract tested here is strict trajectory equality to fp
tolerance against ``local_sdca_fast`` / the literal oracle — not just
"convergence parity".  Coverage: all four modes, both layouts, H not a
multiple of B (masked tail), tiny shards (duplicate draws inside a block),
off-fixed-point scaling parameters, the device-loop and mesh paths, and the
CLI flag gating.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from conftest import SMALL_TRAIN  # noqa: E402
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset, split_sizes
from cocoa_tpu.ops.local_sdca import local_sdca_block, local_sdca_fast
from cocoa_tpu.ops.rows import shard_margins
from cocoa_tpu.solvers import run_cocoa, run_minibatch_cd
from cocoa_tpu.utils.prng import sample_indices, sample_indices_per_shard

K = 4
H = 20


def _params(tiny_data, **kw):
    defaults = dict(n=tiny_data.n, num_rounds=10, local_iters=H, lam=0.01,
                    beta=1.0, gamma=1.0)
    defaults.update(kw)
    return Params(**defaults)


_DBG = DebugParams(debug_iter=-1, seed=0)


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0),
                                        ("frozen", 1.0)])
@pytest.mark.parametrize("layout", ["dense", "sparse"])
@pytest.mark.parametrize("block", [1, 8, 37])
def test_block_kernel_matches_fast(tiny_data, mode, sigma, layout, block):
    """Kernel-level equality vs the sequential fast path.  H=37 draws from a
    96-row single shard: duplicate indices inside a block are certain at
    B=37, and B=8 exercises the masked tail (37 = 4·8 + 5)."""
    ds = shard_dataset(tiny_data, k=1, layout=layout, dtype=jnp.float64)
    shard = {k: v[0] for k, v in ds.shard_arrays().items()}
    rng = np.random.default_rng(11)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(np.clip(rng.normal(size=tiny_data.n) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, [tiny_data.n])[0, 0]
    )
    m0 = shard_margins(w, shard)
    da_f, dw_f = local_sdca_fast(m0, alpha, shard, idxs, 0.01, tiny_data.n,
                                 jnp.zeros(d), mode=mode, sigma=sigma)
    da_b, dw_b = local_sdca_block(m0, alpha, shard, idxs, 0.01, tiny_data.n,
                                  jnp.zeros(d), mode=mode, sigma=sigma,
                                  block=block)
    np.testing.assert_allclose(np.asarray(da_b), np.asarray(da_f),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(dw_b), np.asarray(dw_f),
                               rtol=1e-9, atol=1e-12)


def test_block_duplicates_in_block_exact(tiny_data):
    """A pathological stream — every draw the same index — makes the Gram
    self-coupling term carry the whole sequential recurrence."""
    ds = shard_dataset(tiny_data, k=1, layout="dense", dtype=jnp.float64)
    shard = {k: v[0] for k, v in ds.shard_arrays().items()}
    d = tiny_data.num_features
    w = jnp.zeros(d)
    alpha = jnp.zeros(tiny_data.n)
    idxs = jnp.full(16, 3, dtype=jnp.int32)
    m0 = shard_margins(w, shard)
    da_f, dw_f = local_sdca_fast(m0, alpha, shard, idxs, 0.01, tiny_data.n,
                                 jnp.zeros(d), mode="plus", sigma=4.0)
    da_b, dw_b = local_sdca_block(m0, alpha, shard, idxs, 0.01, tiny_data.n,
                                  jnp.zeros(d), mode="plus", sigma=4.0,
                                  block=16)
    np.testing.assert_allclose(np.asarray(da_b), np.asarray(da_f), atol=1e-12)
    np.testing.assert_allclose(np.asarray(dw_b), np.asarray(dw_f), atol=1e-12)


def _shards(tiny_data):
    X = tiny_data.to_dense()
    y = tiny_data.labels
    sizes = split_sizes(tiny_data.n, K)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    return [(X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]])
            for i in range(K)]


def _sample_fn(seed, t, n_local):
    return sample_indices(seed, range(t, t + 1), H, n_local)[0]


@pytest.mark.parametrize("plus,beta,gamma", [
    (True, 1.0, 0.5),    # CoCoA+ off the γ=1 fixed point
    (False, 2.0, 1.0),   # CoCoA averaging off the β=1 fixed point
])
def test_block_solver_matches_oracle(tiny_data, plus, beta, gamma):
    """Full-trajectory oracle match through run_cocoa with block_size — the
    same contract the fast path carries, at off-fixed-point scalings."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=5, beta=beta, gamma=gamma)
    w, alpha, _ = run_cocoa(ds, p, _DBG, plus=plus, quiet=True,
                            math="fast", block_size=8)
    w_o, alphas_o = oracle.cocoa_outer(
        _shards(tiny_data), np.zeros(tiny_data.num_features),
        p.lam, p.n, p.num_rounds, H, beta, gamma, 0, plus, _sample_fn,
    )
    np.testing.assert_allclose(np.asarray(w), w_o, rtol=1e-8, atol=1e-10)
    for s in range(K):
        np.testing.assert_allclose(
            np.asarray(alpha[s, : len(alphas_o[s])]), alphas_o[s],
            rtol=1e-8, atol=1e-10,
        )


def test_block_minibatch_cd_matches_plain(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4, beta=0.5)
    w0, a0, _ = run_minibatch_cd(ds, p, _DBG, quiet=True, math="fast")
    w1, a1, _ = run_minibatch_cd(ds, p, _DBG, quiet=True, math="fast",
                                 block_size=8)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0),
                               rtol=1e-9, atol=1e-12)


def test_block_device_loop_and_mesh_match_host(tiny_data):
    """The block kernel rides the chunked/device-loop drivers and the
    shard_map mesh path unchanged."""
    from cocoa_tpu.parallel import make_mesh

    p = _params(tiny_data, num_rounds=10)
    dbg = DebugParams(debug_iter=5, seed=0)
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w_h, _, traj_h = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                               math="fast", block_size=8)
    w_d, _, traj_d = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                               math="fast", block_size=8, device_loop=True)
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_h), atol=1e-12)
    assert [r.gap for r in traj_d.records] == pytest.approx(
        [r.gap for r in traj_h.records], rel=1e-10)

    mesh = make_mesh(K)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    w_m, _, _ = run_cocoa(ds_m, p, dbg, plus=True, quiet=True,
                          math="fast", block_size=8, mesh=mesh,
                          device_loop=True)
    np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_h),
                               rtol=1e-9, atol=1e-11)


def test_block_sparse_solver_end_to_end(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=10)
    dbg = DebugParams(debug_iter=10, seed=0)
    w_f, _, traj_f = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                               math="fast", pallas=False)
    w_b, _, traj_b = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                               math="fast", block_size=8)
    np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_f),
                               rtol=1e-9, atol=1e-12)
    assert traj_b.records[-1].gap == pytest.approx(traj_f.records[-1].gap,
                                                   rel=1e-8)


def test_block_prox_lasso_matches_plain(tiny_data):
    """The prox mode shares the σ′-scaled read structure; the block kernel
    must carry it unchanged (ProxCoCoA+ lasso end-to-end)."""
    from cocoa_tpu.data.columns import shard_columns
    from cocoa_tpu.solvers import run_prox_cocoa

    ds_c, b = shard_columns(tiny_data, K, dtype=jnp.float64)
    d = tiny_data.num_features
    lam = 0.1 * float(np.max(np.abs(tiny_data.to_dense().T @ tiny_data.labels)))
    p = Params(n=d, num_rounds=10, local_iters=4, lam=lam, loss="lasso",
               smoothing=0.0)
    dbg = DebugParams(debug_iter=10, seed=0)
    x0, r0, traj0 = run_prox_cocoa(ds_c, b, p, dbg, quiet=True, math="fast")
    x1, r1, traj1 = run_prox_cocoa(ds_c, b, p, dbg, quiet=True, math="fast",
                                   block_size=4)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                               rtol=1e-9, atol=1e-12)
    assert traj1.records[-1].gap == pytest.approx(traj0.records[-1].gap,
                                                  rel=1e-8)


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0),
                                        ("frozen", 1.0)])
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_batched_pallas_chain_matches_fast(tiny_data, mode, sigma, layout):
    """The TPU hot path — local_sdca_block_batched with the lockstep Pallas
    chain kernel (interpret mode on CPU) — must match K independent
    sequential fast-path runs: in-block margins, Gram coupling, additive α
    scatter, masked tail (H=37 vs B=128), duplicate draws, and a zero-norm
    row (the qii == 0 branch the compressed hinge chain special-cases)."""
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched

    ds = shard_dataset(tiny_data, k=K, layout=layout, dtype=jnp.float64)
    sa = ds.shard_arrays()
    rng = np.random.default_rng(5)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1)
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode=mode, sigma=sigma,
        block=128, interpret=True,
    )
    for s in range(K):
        shard = {kk: v[s] for kk, v in sa.items()}
        m0 = shard_margins(w, shard)
        da_f, dw_f = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d), mode=mode, sigma=sigma,
        )
        np.testing.assert_allclose(np.asarray(da_b[s]), np.asarray(da_f),
                                   rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(np.asarray(dw_b[s]), np.asarray(dw_f),
                                   rtol=1e-9, atol=1e-12)


@pytest.mark.parametrize("loss,smoothing", [("smooth_hinge", 0.5),
                                            ("logistic", 1.0)])
def test_batched_chain_generic_losses(tiny_data, loss, smoothing):
    """The non-hinge losses ride the chain kernel's generic branch (no
    algebraic collapse; losses.alpha_step runs on (K, 1) columns in the
    chain) — must match the sequential fast path."""
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched

    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    sa = ds.shard_arrays()
    rng = np.random.default_rng(9)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0.01, 0.99)
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode="plus", sigma=4.0,
        loss=loss, smoothing=smoothing, block=128, interpret=True,
    )
    for s in range(K):
        shard = {kk: v[s] for kk, v in sa.items()}
        m0 = shard_margins(w, shard)
        da_f, dw_f = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d), mode="plus", sigma=4.0, loss=loss,
            smoothing=smoothing,
        )
        np.testing.assert_allclose(np.asarray(da_b[s]), np.asarray(da_f),
                                   rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(np.asarray(dw_b[s]), np.asarray(dw_f),
                                   rtol=1e-8, atol=1e-10)


def test_batched_chain_zero_norm_row(tiny_data):
    """qii == 0: the compressed hinge chain must reproduce alpha_step's
    projected-gradient outcome (α → 1) for a zero row in the stream."""
    from cocoa_tpu.data.libsvm import LibsvmData
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched

    rng = np.random.default_rng(3)
    n, d = 64, 16
    X = rng.normal(size=(n, d))
    X[5] = 0.0
    y = np.where(rng.random(n) > 0.5, 1.0, -1.0)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    data = LibsvmData(labels=y, indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=X.reshape(-1), num_features=d)
    ds = shard_dataset(data, k=1, layout="dense", dtype=jnp.float64)
    sa = ds.shard_arrays()
    w = jnp.zeros(d)
    alpha = jnp.zeros((1, ds.n_shard))
    idxs = jnp.asarray([[5, 2, 5, 9]], dtype=jnp.int32)
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, n, mode="plus", sigma=2.0,
        block=128, interpret=True,
    )
    shard = {kk: v[0] for kk, v in sa.items()}
    da_f, dw_f = local_sdca_fast(
        shard_margins(w, shard), alpha[0], shard, idxs[0], 0.01, n,
        jnp.zeros(d), mode="plus", sigma=2.0,
    )
    assert float(da_b[0][5]) == 1.0
    np.testing.assert_allclose(np.asarray(da_b[0]), np.asarray(da_f),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(dw_b[0]), np.asarray(dw_f),
                               atol=1e-12)


def test_block_pallas_chain_through_driver(tiny_data):
    """Driver-integrated Pallas chain (interpret on CPU): the chunked
    per_round_batched routing, scan_chunk forcing, and additive α scatter
    must reproduce the XLA-chain solver trajectory."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=4)
    dbg = DebugParams(debug_iter=4, seed=0)
    w_x, a_x, traj_x = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", block_size=128)
    w_p, a_p, traj_p = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", block_size=128,
                                 block_chain="pallas_interpret")
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_x),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_x),
                               rtol=1e-9, atol=1e-12)


def test_block_pallas_chain_mesh_through_driver(tiny_data):
    """Same, on the shard_map mesh path (per_shard routing)."""
    from cocoa_tpu.parallel import make_mesh

    mesh = make_mesh(K)
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                       mesh=mesh)
    p = _params(tiny_data, num_rounds=4)
    dbg = DebugParams(debug_iter=4, seed=0)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w_x, _, _ = run_cocoa(ds_l, p, dbg, plus=True, quiet=True,
                          math="fast", block_size=128)
    w_p, _, _ = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                          math="fast", block_size=128, mesh=mesh,
                          block_chain="pallas_interpret")
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_x),
                               rtol=1e-9, atol=1e-12)


def test_block_chain_rejects_fp_mesh(tiny_data):
    """The Pallas block chain assumes the full feature axis per device —
    an fp mesh must be rejected exactly like the sequential Pallas path."""
    from cocoa_tpu.parallel import make_mesh

    mesh = make_mesh(4, fp=2)
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64,
                       mesh=mesh)
    p = _params(tiny_data)
    with pytest.raises(ValueError, match="feature-parallel"):
        run_cocoa(ds, p, _DBG, plus=True, quiet=True, math="fast",
                  block_size=128, mesh=mesh, block_chain="pallas_interpret")


def test_chain_vmem_fit_guard():
    """Auto selection must fall back to the XLA chain when the kernel's
    VMEM working set cannot fit (it crashes Mosaic rather than degrading)."""
    from cocoa_tpu.ops.pallas_chain import chain_fits

    assert chain_fits(8, 256, 4)          # the benchmark config
    assert not chain_fits(16, 512, 4)     # 33 MB gq >> 16 MB VMEM


def test_block_requires_fast_math(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    with pytest.raises(ValueError, match="math='fast'"):
        run_cocoa(ds, p, _DBG, plus=True, quiet=True, math="exact",
                  block_size=8)
    with pytest.raises(ValueError, match="Pallas"):
        run_cocoa(ds, p, _DBG, plus=True, quiet=True, math="fast",
                  pallas=True, block_size=8)


def test_cli_block_size_flag(tmp_path, capsys):
    """--blockSize runs the menu through the block kernel and is rejected
    without --math=fast."""
    from cocoa_tpu import cli

    rc = cli.main([
        f"--trainFile={SMALL_TRAIN}",
        "--numFeatures=9947", "--numSplits=4", "--numRounds=5",
        "--localIterFrac=0.05", "--lambda=.001", "--justCoCoA=true",
        "--debugIter=5", "--math=fast", "--blockSize=8", "--mesh=1",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "CoCoA+" in out

    rc = cli.main([
        f"--trainFile={SMALL_TRAIN}",
        "--numFeatures=9947", "--blockSize=8",
    ])
    assert rc == 2
    assert "--math=fast" in capsys.readouterr().err


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0),
                                        ("frozen", 1.0)])
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_fused_block_kernel_matches_fast(tiny_data, mode, sigma, layout):
    """The FUSED per-block kernel (ops/pallas_chain.fused_block — in-kernel
    Gram, margins, equality tile, chain, and Δw update) is the f32
    production path; the float64 parity tests above exercise only the
    legacy split path (fused_fits requires itemsize 4).  This f32
    interpret-mode run must take the fused branch and match the sequential
    fast path to f32 tolerance."""
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched
    from cocoa_tpu.ops.pallas_chain import fused_fits

    ds = shard_dataset(tiny_data, k=K, layout=layout, dtype=jnp.float32)
    sa = ds.shard_arrays()
    d = tiny_data.num_features
    assert fused_fits(K, 128, d, 4, ds.n_shard), \
        "test config must exercise the fused branch"
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode=mode, sigma=sigma,
        block=128, interpret=True,
    )
    for s in range(K):
        shard = {kk: v[s] for kk, v in sa.items()}
        m0 = shard_margins(w, shard)
        da_f, dw_f = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d, jnp.float32), mode=mode, sigma=sigma,
        )
        np.testing.assert_allclose(np.asarray(da_b[s]), np.asarray(da_f),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw_b[s]), np.asarray(dw_f),
                                   rtol=2e-4, atol=1e-6)


@pytest.mark.parametrize("loss,smoothing", [("smooth_hinge", 0.5),
                                            ("logistic", 1.0)])
def test_fused_block_kernel_generic_losses(tiny_data, loss, smoothing):
    """The fused kernel's non-hinge branch (losses.alpha_step on (K, 1)
    columns inside the chain) — the float64 generic-loss tests above only
    pin the legacy split path."""
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched
    from cocoa_tpu.ops.pallas_chain import fused_fits

    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float32)
    sa = ds.shard_arrays()
    d = tiny_data.num_features
    assert fused_fits(K, 128, d, 4, ds.n_shard)
    rng = np.random.default_rng(9)
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(3, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode="plus", sigma=4.0,
        loss=loss, smoothing=smoothing, block=128, interpret=True,
    )
    for s in range(K):
        shard = {kk: v[s] for kk, v in sa.items()}
        m0 = shard_margins(w, shard)
        da_f, dw_f = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d, jnp.float32), mode="plus", sigma=4.0,
            loss=loss, smoothing=smoothing,
        )
        np.testing.assert_allclose(np.asarray(da_b[s]), np.asarray(da_f),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw_b[s]), np.asarray(dw_f),
                                   rtol=2e-4, atol=1e-6)


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0),
                                        ("frozen", 1.0)])
@pytest.mark.parametrize("h", [20, 200])
def test_batched_chain_distinct_matches_per_block(tiny_data, mode, sigma, h):
    """``distinct=True`` (the permuted-mode one-scatter-per-round α update
    — round 5's glue elimination) must be BIT-identical to the per-block
    path when the round's indices really are pairwise distinct per shard:
    the hoisted α₀ gather reads values no earlier block of the round could
    have touched, and each coordinate receives exactly one add.  h=20 is
    the single-block case (masked tail); h=200 > B=128 spans TWO blocks —
    the only case where the distinct path's cross-block structure (hoisted
    α₀ for block 2, deltas-as-scan-outputs ordering, the single post-scan
    scatter) differs from the per-block path at all."""
    from cocoa_tpu.data.synth import synth_dense
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched
    from cocoa_tpu.ops.pallas_chain import fused_fits

    k = 2
    if h > 20:
        # cross-block coverage needs shards with >= h rows (distinct draws)
        data = synth_dense(640, 32, seed=3)
    else:
        data = tiny_data
    # f32: the distinct branch lives on the FUSED path only, and fused_fits
    # requires itemsize 4 — float64 would silently take the split fallback
    # where distinct is a no-op and this test would compare the per-block
    # path against itself (caught in round-5 review)
    ds = shard_dataset(data, k=k, layout="dense", dtype=jnp.float32)
    sa = ds.shard_arrays()
    d = data.num_features
    assert fused_fits(k, 128, d, 4, ds.n_shard), \
        "test config must exercise the fused branch"
    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(k, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    # pairwise-distinct draws: a fresh permutation prefix per shard
    idxs = jnp.asarray(np.stack([
        rng.permutation(int(c))[:h] for c in ds.counts
    ]).astype(np.int32))
    kw = dict(mode=mode, sigma=sigma, block=128, interpret=True)
    da_p, dw_p = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, data.n, **kw)
    da_d, dw_d = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, data.n, distinct=True, **kw)
    # bit-identity, not tolerance: same gathered values (gather commutes
    # with the elementwise qf scale), one add per coordinate either way
    np.testing.assert_array_equal(np.asarray(da_d), np.asarray(da_p))
    np.testing.assert_array_equal(np.asarray(dw_d), np.asarray(dw_p))


@pytest.mark.parametrize("distinct", [False, True])
@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0),
                                        ("frozen", 1.0)])
def test_pipelined_fused_matches_serial_bit_exact(mode, sigma, distinct):
    """The two-phase software-pipelined block scan (row tile for block
    b+1 gathered during block b's chain kernel, riding the scan carry)
    must be BIT-identical to the serial schedule: the prefetch reorders
    memory traffic, never math — every kernel invocation consumes a tile
    gathered from the same indices by the same gather op.  h=200 > B=128
    spans two blocks, the only case where the pipeline differs from the
    serial scan at all; f32 so the fused branch actually runs."""
    from cocoa_tpu.data.synth import synth_dense
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched
    from cocoa_tpu.ops.pallas_chain import fused_fits

    k, h = 2, 200
    data = synth_dense(640, 32, seed=3)
    ds = shard_dataset(data, k=k, layout="dense", dtype=jnp.float32)
    sa = ds.shard_arrays()
    d = data.num_features
    assert fused_fits(k, 128, d, 4, ds.n_shard), \
        "test config must exercise the fused branch"
    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(k, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    if distinct:
        # the distinct license requires pairwise-distinct draws per shard
        idxs = jnp.asarray(np.stack([
            rng.permutation(int(c))[:h] for c in ds.counts
        ]).astype(np.int32))
    else:
        idxs = jnp.asarray(
            sample_indices_per_shard(7, range(1, 2), h, ds.counts)[:, 0, :]
        )
    kw = dict(mode=mode, sigma=sigma, block=128, interpret=True,
              distinct=distinct)
    da_s, dw_s = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, data.n, pipeline=False, **kw)
    da_p, dw_p = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, data.n, pipeline=True, **kw)
    np.testing.assert_array_equal(np.asarray(da_p), np.asarray(da_s))
    np.testing.assert_array_equal(np.asarray(dw_p), np.asarray(dw_s))


def test_pipelined_split_matches_serial_bit_exact(tiny_data):
    """Same schedule contract on the legacy split path (float64 fails
    fused_fits's itemsize gate, so this pins the einsum+chain-kernel
    fallback): the prefetched row tile feeds identical einsums."""
    from cocoa_tpu.ops.local_sdca import local_sdca_block_batched

    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    sa = ds.shard_arrays()
    rng = np.random.default_rng(5)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1)
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 200, ds.counts)[:, 0, :]
    )  # 200 > B=128: two blocks, so the pipeline actually differs
    kw = dict(mode="plus", sigma=4.0, block=128, interpret=True)
    da_s, dw_s = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, pipeline=False, **kw)
    da_p, dw_p = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, pipeline=True, **kw)
    np.testing.assert_array_equal(np.asarray(da_p), np.asarray(da_s))
    np.testing.assert_array_equal(np.asarray(dw_p), np.asarray(dw_s))


def test_pipelined_through_driver_matches_serial(tiny_data):
    """Driver-level A/B: ``block_pipeline`` on/off through run_cocoa
    (chunked driver, interpret chain) produces the same trajectory — the
    flag changes the schedule, never the observable run."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float32)
    p = _params(tiny_data, num_rounds=4)
    dbg = DebugParams(debug_iter=4, seed=0)
    outs = {}
    for pipe in (False, True):
        outs[pipe] = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                               math="fast", block_size=128,
                               block_chain="pallas_interpret",
                               block_pipeline=pipe, scan_chunk=2)
    w_s, a_s, traj_s = outs[False]
    w_p, a_p, traj_p = outs[True]
    np.testing.assert_array_equal(np.asarray(w_p), np.asarray(w_s))
    np.testing.assert_array_equal(np.asarray(a_p), np.asarray(a_s))
    assert [r.gap for r in traj_p.records] == [r.gap for r in traj_s.records]


def test_cli_block_pipeline_flag(tmp_path, capsys):
    """--blockPipeline validates its value and requires --blockSize."""
    from cocoa_tpu import cli

    train = tmp_path / "tiny.dat"
    train.write_text("\n".join(
        ["+1 1:0.5 3:1.0", "-1 2:0.25 4:0.5", "+1 1:0.75",
         "-1 3:0.5 4:0.25"] * 8) + "\n")
    base = [f"--trainFile={train}", "--numFeatures=4", "--numSplits=2",
            "--numRounds=4", "--localIterFrac=0.5", "--lambda=.01",
            "--justCoCoA=true", "--debugIter=2", "--mesh=1"]
    rc = cli.main(base + ["--math=fast", "--blockSize=8",
                          "--blockPipeline=banana"])
    assert rc == 2
    assert "--blockPipeline" in capsys.readouterr().err

    rc = cli.main(base + ["--blockPipeline=on"])
    assert rc == 2
    assert "--blockSize" in capsys.readouterr().err

    rc = cli.main(base + ["--math=fast", "--blockSize=8",
                          "--blockPipeline=off"])
    assert rc == 0
    assert "CoCoA+" in capsys.readouterr().out


def test_block_distinct_through_driver_permuted(tiny_data, monkeypatch):
    """End-to-end: the driver auto-enables the distinct α update for
    permuted sampling exactly when counts % H == 0 (observed via a spy on
    the kernel call — f32 so the fused path actually runs; a float64 run
    would silently take the split fallback where distinct is a no-op),
    and both selections match the no-block fast path on the same permuted
    index stream."""
    # the package re-exports a FUNCTION named local_sdca that shadows the
    # submodule attribute (import ... as resolves via getattr); take the
    # module straight from sys.modules
    import sys as _sys

    import cocoa_tpu.ops.local_sdca  # noqa: F401  (ensure imported)
    from cocoa_tpu.solvers import run_cocoa

    ls_mod = _sys.modules["cocoa_tpu.ops.local_sdca"]
    seen = []
    real = ls_mod.local_sdca_block_batched

    def spy(*args, **kw):
        seen.append(kw.get("distinct", False))
        return real(*args, **kw)

    monkeypatch.setattr(ls_mod, "local_sdca_block_batched", spy)
    # the spy fires at trace time — drop any cached executables so every
    # config in this test really rebuilds (and re-imports) the kernel
    from cocoa_tpu.solvers import cocoa as cocoa_mod

    cocoa_mod._CHUNK_STEPS.clear()
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float32)
    # counts = 24 per shard; H=8 divides -> distinct ON; H=7 -> OFF
    for h, want in ((8, True), (7, False)):
        seen.clear()
        p = Params(n=tiny_data.n, num_rounds=6, local_iters=h, lam=0.01)
        w_b, a_b, _ = run_cocoa(ds, p, DebugParams(debug_iter=3, seed=0),
                                plus=True, quiet=True, math="fast",
                                rng="permuted", block_size=128,
                                block_chain="pallas_interpret",
                                scan_chunk=2)
        assert seen and all(s == want for s in seen), (h, want, seen)
        # the fast path (no blocks) is the ground truth for the same
        # permuted index stream
        w_f, a_f, _ = run_cocoa(ds, p, DebugParams(debug_iter=3, seed=0),
                                plus=True, quiet=True, math="fast",
                                rng="permuted", scan_chunk=2)
        np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_f),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(a_b), np.asarray(a_f),
                                   rtol=2e-4, atol=1e-6)
