"""Per-query distributed tracing (docs/DESIGN.md §22): the
``trace=<id>;`` prefix through the solo server and the fleet router.

What these tests pin:

- **grammar**: the id is 1-32 lowercase hex; the solo server rejects a
  malformed prefix with the numbers, the router leaves it on the line
  (pure relay) so the replica's rejection reaches the client;
- **the off switch**: ``--traceSample=0`` answers a trace-prefixed
  line BYTE-identically to the same line without the prefix — tracing
  off is bit-exact, the acceptance pin;
- **deterministic sampling**: the first trace-prefixed line is always
  sampled, then every Nth; unsampled lines are byte-identical to
  untraced ones;
- **the colon form** (``trace=<id>:<us>;``, the router's upstream
  mark): always stamps the response's ``"trace"`` object, never emits
  the event (the router owns it);
- **real-socket round trips**: the id survives the overflow-forward
  (loaded home replica -> the idle one) and the requeue past a dead
  replica, and the router's ``query_trace`` event carries the hop
  breakdown with per-replica attribution — schema-validated.

The socket tests build compiled serving stacks, so they ride the slow
marker; the tier-1 sweep covers the grammar/prefix units only.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import serving
from cocoa_tpu.serving.router import Router
from cocoa_tpu.serving.server import MarginServer
from cocoa_tpu.telemetry import events as tele
from cocoa_tpu.telemetry import schema as tele_schema

D = 24


@pytest.fixture
def bus(tmp_path):
    b = tele.get_bus()
    b.reset()
    path = tmp_path / "events.jsonl"
    b.configure(jsonl_path=str(path))
    yield path
    b.reset()


def _read_events(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# --- prefix grammar (no sockets, no compiles) --------------------------------


def test_server_peel_trace_forms():
    peel = MarginServer._peel_trace
    assert peel(None, "1:0.5") == (None, "1:0.5")
    assert peel(None, "trace=ab12;1:0.5") == (("ab12", None), "1:0.5")
    tid, rest = peel(None, "trace=ff:2500;1:0.5")
    assert tid == ("ff", 0.0025) and rest == "1:0.5"
    for bad in ("trace=XYZ;1:0.5",          # uppercase
                "trace=;1:0.5",             # empty id
                "trace=" + "a" * 33 + ";1:0.5",   # too long
                "trace=ab:zz;1:0.5",        # non-integer stamp
                "trace=ab"):                # prefix without a query
        with pytest.raises(serving.QueryError):
            peel(None, bad)


def test_router_peel_leaves_malformed_untouched():
    peel = Router._peel_trace
    assert peel(None, "trace=ab;x") == ("ab", "x")
    # a bad id stays ON the line: the replica rejects it with the
    # numbers, the router never swallows input
    assert peel(None, "trace=XYZ;x") == (None, "trace=XYZ;x")
    assert peel(None, "trace=ab") == (None, "trace=ab")
    assert peel(None, "tenant=0;x") == (None, "tenant=0;x")


def test_sampler_first_then_every_nth():
    srv = object.__new__(MarginServer)   # the gate needs no sockets
    srv.trace_sample = 3
    import itertools

    srv._trace_seen = itertools.count()
    assert [srv._sample() for _ in range(7)] == [
        True, False, False, True, False, False, True]
    srv.trace_sample = 0
    assert not srv._sample()


# --- real sockets ------------------------------------------------------------


def _save(ck, w, round_t=10):
    ckpt_lib.save(str(ck), "CoCoA+", round_t,
                  np.asarray(w, np.float32), None, gap=1e-3)


def _stack(ck, n_tenants=None):
    w, info = serving.load_model(ckpt_lib.latest(str(ck), "CoCoA+"))
    slots = serving.ModelSlots(w, info, dtype=np.float32)
    scorer = serving.BatchScorer(D, dtype=np.float32, buckets=(4, 16),
                                 max_nnz=8, n_tenants=n_tenants)
    scorer.warmup(slots.current()[0])
    return serving.MicroBatcher(scorer, slots, sla_s=0.01,
                                algorithm="CoCoA+")


def _serve(batcher, n_tenants=None, trace_sample=0):
    srv = MarginServer(batcher, D, 8, port=0, n_tenants=n_tenants,
                       trace_sample=trace_sample)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _ask_raw(addr, line):
    with socket.create_connection(addr, timeout=10) as s:
        s.sendall((line + "\n").encode())
        return s.makefile("rb").readline()


Q = "1:0.5 3:-0.25"


@pytest.mark.slow
def test_solo_off_and_unsampled_bit_identity(tmp_path, bus):
    rng = np.random.default_rng(3)
    _save(tmp_path / "ck", rng.standard_normal(D))
    batcher = _stack(tmp_path / "ck")
    try:
        # trace_sample=0: the prefix is peeled and IGNORED
        srv = _serve(batcher, trace_sample=0)
        plain = _ask_raw(srv.address, Q)
        assert _ask_raw(srv.address, f"trace=ab;{Q}") == plain
        assert b"trace" not in plain
        srv.close()
        # trace_sample=3: line 0 sampled, 1-2 byte-identical to plain
        srv = _serve(batcher, trace_sample=3)
        plain = _ask_raw(srv.address, Q)
        first = _ask_raw(srv.address, f"trace=ab;{Q}")
        assert b'"trace"' in first
        assert json.loads(first)["trace"]["id"] == "ab"
        for _ in range(2):
            assert _ask_raw(srv.address, f"trace=cd;{Q}") == plain
        srv.close()
    finally:
        batcher.stop()
    evs = [e for e in _read_events(bus)
           if e.get("event") == "query_trace"]
    # only the sampled line emitted, and it is a solo event: no router
    # hops, no replica attribution
    assert len(evs) == 1
    ev = evs[0]
    assert ev["trace_id"] == "ab" and ev["replica"] is None
    assert ev["router_queue_s"] is None and ev["forward_s"] is None
    assert ev["replica_queue_s"] is not None
    assert ev["total_s"] > 0
    assert not tele_schema.check_file(str(bus))


@pytest.mark.slow
def test_solo_colon_form_stamps_but_never_emits(tmp_path, bus):
    rng = np.random.default_rng(4)
    _save(tmp_path / "ck", rng.standard_normal(D))
    batcher = _stack(tmp_path / "ck")
    try:
        # sampling OFF: the colon form (router's upstream mark) still
        # stamps the response — the router that marked it owns the event
        srv = _serve(batcher, trace_sample=0)
        resp = json.loads(_ask_raw(srv.address,
                                   f"trace=beef:1200;{Q}"))
        assert resp["trace"]["id"] == "beef"
        assert resp["trace"]["device_s"] is not None
        srv.close()
    finally:
        batcher.stop()
    assert not [e for e in _read_events(bus)
                if e.get("event") == "query_trace"]


def _dead_listener():
    """A 'replica' that accepts and instantly hangs up — the router
    sees a dead connection and must requeue, exactly like a SIGKILLed
    process whose port is still bound by a respawn race."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)

    def run():
        while True:
            try:
                c, _ = lsock.accept()
                c.close()
            except OSError:
                return

    threading.Thread(target=run, daemon=True).start()
    return lsock


@pytest.mark.slow
def test_router_trace_round_trip_overflow_and_requeue(tmp_path, bus):
    """One fleet, three decision points: plain forward, the
    overflow-forward off a loaded home, and the requeue past a dead
    replica — the trace id survives every one of them, and the router's
    query_trace events attribute each to the replica that answered."""
    T = 2
    rng = np.random.default_rng(5)
    W = rng.standard_normal((T, D)).astype(np.float32)
    _save(tmp_path / "cat", W)
    batcher = _stack(tmp_path / "cat", n_tenants=T)
    dead = _dead_listener()
    try:
        r1 = _serve(batcher, n_tenants=T)
        router = Router([("r0", dead.getsockname()),
                         ("r1", r1.address)],
                        sla_s=0.05, route="tenant", trace_sample=1)
        threading.Thread(target=router.serve_forever,
                         daemon=True).start()
        try:
            # tenant=1 homes on r1 (live): the plain sampled forward
            resp = json.loads(_ask_raw(router.address,
                                       f"trace=0a;tenant=1;{Q}"))
            assert resp["tenant"] == 1 and resp["trace"]["id"] == "0a"
            # tenant=1 again with r1 LOADED past the shed budget — but
            # idle r0 (zero inflight) admits: the overflow-forward...
            # which then finds r0 dead and requeues BACK to r1: both
            # decision points in one line, id intact
            rep1 = router.replicas[1]
            rep1.ewma_s, rep1.inflight = 10.0, 4
            resp = json.loads(_ask_raw(router.address,
                                       f"trace=0b;tenant=1;{Q}"))
            rep1.ewma_s, rep1.inflight = 0.0, 0
            assert resp["trace"]["id"] == "0b"
            assert resp["tenant"] == 1
            assert router.requeue_total >= 1
            # r0 is now marked dead; a tenant=0 line (home r0) probes
            # forward to r1 without ever touching the corpse
            resp = json.loads(_ask_raw(router.address,
                                       f"trace=0c;tenant=0;{Q}"))
            assert resp["trace"]["id"] == "0c"
            # tracing OFF through the SAME fleet is byte-identical
            router.trace_sample = 0
            plain = _ask_raw(router.address, f"tenant=0;{Q}")
            assert _ask_raw(router.address,
                            f"trace=dd;tenant=0;{Q}") == plain
        finally:
            router.stop()
            router.close()
        r1.close()
    finally:
        batcher.stop()
        dead.close()
    evs = {e["trace_id"]: e for e in _read_events(bus)
           if e.get("event") == "query_trace"}
    assert set(evs) == {"0a", "0b", "0c"}
    for ev in evs.values():
        assert ev["replica"] == "r1"
        assert ev["router_queue_s"] is not None
        assert ev["replica_queue_s"] is not None
        assert ev["device_s"] is not None
        assert ev["total_s"] >= ev["router_queue_s"]
    assert evs["0a"]["requeues"] == 0
    assert evs["0b"]["requeues"] >= 1      # died on r0, replayed on r1
    assert evs["0b"]["tenant"] == 1
    # the whole stream — traces plus the requeue's replica_state
    # exemplar — validates against the typed schema
    assert not tele_schema.check_file(str(bus))
    states = [e for e in _read_events(bus)
              if e.get("event") == "replica_state"
              and e.get("state") == "requeue"]
    assert any(s.get("trace_id") == "0b" for s in states)
