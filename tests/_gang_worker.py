"""Minimal REAL-process gang worker for supervisor chaos tests.

Runs under the elastic supervisor exactly like the CLI would
(``python -m _gang_worker <flags> --master=... --processId=i
--numProcesses=n [--resume]``): joins the jax.distributed runtime (real
coordinator rendezvous), splits the K logical shards over the gang,
advances a deterministic round-keyed state with one
``host_allgather_bytes`` exchange per round (the hardened KV path,
exercised against a real coordination service), and checkpoints through
``cocoa_tpu.checkpoint`` — so the supervision mechanics (death
detection, shrink-to-survivors, resume, checkpoint-generation fallback)
run end to end with real processes WITHOUT cross-process XLA
collectives, which the pinned jax lacks on CPU (the real-training chaos
pin is tests/test_chaos.py's slow suite, same guard as the existing
multi-host gang tests).

Two modes:

- **toy** (default): the state is a pure function of (K, rounds) — each
  shard's per-round increment is owner-independent and each w[s]
  receives exactly one nonzero addend per round — so a kill/shrink/
  resume run must reproduce the unfailed control's final checkpoint bit
  for bit, the same invariant the real solvers get from round-keyed
  sampling.
- **--real=cocoa**: an actual CoCoA+ gang over the host-side KV
  exchange — numpy hinge SDCA (tests/oracle.py, the reference-faithful
  local solver) on deterministic per-shard synthetic data, σ′ = K·γ,
  exact duality-gap certificate at the ``--debugIter`` cadence.  This
  is the substrate for the round-barrier levers (docs/DESIGN.md §15):
  ``--overlapComm`` posts this worker's Δw the moment local solve
  finishes and collects peers' payloads on a background thread
  (parallel/distributed.async_host_allgather_bytes), and
  ``--staleRounds=S`` admits a peer's round-r Δw up to S rounds late
  under the safe-γ rule (solvers/cocoa.StaleJoinWindow), draining at
  every eval/checkpoint boundary so the certified gap is evaluated on
  an exact ``w = w(α)`` pair.  Contributions are applied in CANONICAL
  (round, process) order via a recompute from the contribution log, so
  every worker holds a bitwise-identical w at every drained boundary
  and the whole trajectory — including which rounds join when — is a
  pure function of round numbers, never of wall-clock (deterministic
  A/B tests; see StaleJoinWindow's determinism note).

Straggler fixtures: ``--stepSkew=S`` (worker i sleeps ``i*S`` extra per
round — the constant-skew fixture of the tracing tests) and
``--skewEvery=J`` (rotating skew: worker p sleeps the extra S only on
rounds with ``t % J == p % J`` — the transient-straggler fixture the
staleness window can actually absorb; a CONSTANT skew bounds the gang
to the slow worker's average pace no matter the window, bounded-lag
arithmetic, so the A/B acceptance measures the rotating fixture).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

ALGORITHM = "ToyGang"
REAL_ALGORITHM = "GangCoCoA+"

# short KV budget everywhere in this harness: a dead peer must fail
# THIS worker quickly so the supervisor (which already saw the death)
# isn't racing a 10-minute hang in the teardown path
KV_TIMEOUT_S = 30.0
KV_ATTEMPT_S = 2.0

# the phases a worker can block (or hide blocking) on during the
# cross-gang exchange — the ONE definition the acceptance test
# (tests/test_overlap.py), the CI smoke (tests/chaos_smoke.py) and any
# future consumer sum straggler slack over, so the measured bar cannot
# silently drift between them
EXCHANGE_PHASES = ("kv_get", "kv_allgather", "kv_post", "exchange_join")


def supervise_gang(argv, n: int = 2, events=None, **kw):
    """One-shot supervised run of THIS worker module — the launch
    contract shared by the slow tests, the CI chaos smoke, and the
    benchmarks/check_regression gang gates (one place to change if the
    gang ever needs a new required flag or stream convention).

    Returns ``(rc, records)``: the supervisor's exit code and the
    parsed worker-0 events stream (empty when ``events`` is None or the
    file never appeared).  ``kw`` overrides the supervise defaults
    (max_restarts=0, poll_s=0.05, backoff_base_s=0.0, resume=False)."""
    import json

    from cocoa_tpu import elastic

    opts = dict(module="_gang_worker", max_restarts=0, poll_s=0.05,
                backoff_base_s=0.0, resume=False)
    opts.update(kw)
    argv = list(argv) + ([f"--events={events}"] if events else [])
    rc = elastic.supervise(argv, n, **opts)
    records = []
    if events and os.path.exists(str(events)):
        with open(str(events)) as f:
            records = [json.loads(ln) for ln in f if ln.strip()]
    return rc, records


def parse(argv):
    opts = {}
    for a in argv:
        s = a.lstrip("-")
        k, _, v = s.partition("=")
        opts[k] = v if v else "true"
    return opts


def round_increments(t: int, k: int, lo: int, hi: int) -> np.ndarray:
    """The deterministic per-round update for shards [lo, hi): keyed to
    (round, shard) only — never to the process layout."""
    out = np.zeros(k, np.float64)
    for s in range(lo, hi):
        out[s] = ((t * 1000003 + s * 7919) % 104729) / 104729.0
    return out


def _configure_telemetry(opts, pid):
    """The same telemetry surface the real CLI wires — worker 0 owns the
    given path, worker p > 0 streams to `.p<p>`
    (telemetry/recorder.worker_stream_path), spans tagged with the
    worker index — so the supervisor's flight-recorder dump and the
    trace_report merge run against real per-process artifacts here too."""
    from cocoa_tpu.telemetry import events as tele_events
    from cocoa_tpu.telemetry import recorder as tele_recorder
    from cocoa_tpu.telemetry import tracing

    stream = (tele_recorder.worker_stream_path(opts["events"], pid)
              if opts.get("events") else None)
    # same ownership split as the real CLI: worker 0 owns <metrics>; the
    # supervisor owns the sibling <metrics>.gang (families="gang")
    metrics = opts.get("metrics") if pid == 0 else None
    if stream or metrics:
        tele_events.get_bus().configure(jsonl_path=stream,
                                        metrics_path=metrics)
    if opts.get("trace"):
        tracing.configure(enabled=True, worker=pid)


def _skew_sleep(opts, pid, t) -> float:
    """The straggler fixture's extra sleep for worker ``pid`` at round
    ``t`` (see module docstring)."""
    skew_s = float(opts.get("stepSkew", 0.0))
    every = int(opts.get("skewEvery", 0))
    if skew_s <= 0.0:
        return 0.0
    if every > 0:
        return skew_s if t % every == pid % every else 0.0
    return pid * skew_s


def toy_main(opts, pid, nproc) -> int:
    from cocoa_tpu.parallel.distributed import host_allgather_bytes
    from cocoa_tpu.telemetry import tracing

    k = int(opts["numSplits"])
    rounds = int(opts["numRounds"])
    ckdir = opts.get("chkptDir", "")
    ck_iter = int(opts.get("chkptIter", 5))
    step_s = float(opts.get("stepSeconds", 0.05))
    m = k // nproc

    from cocoa_tpu import checkpoint as ckpt_lib

    w = np.zeros(k, np.float64)
    start = 1
    if "resume" in opts and ckdir:
        path = ckpt_lib.latest(ckdir, ALGORITHM)
        if path is not None:
            meta, w0, _ = ckpt_lib.load(path)
            w = np.array(w0, np.float64)
            start = meta["round"] + 1
            print(f"resuming {ALGORITHM} from round {meta['round']} "
                  f"({path})", flush=True)

    for t in range(start, rounds + 1):
        # the round span carries the round number; the nested
        # kv_allgather / local_step / checkpoint_save spans inherit it
        # (trace_report.attribute_rounds), which is what the per-round
        # critical path and the worker x phase straggler table key on
        with tracing.span("round", round=t):
            mine = round_increments(t, k, pid * m, (pid + 1) * m)
            parts = host_allgather_bytes(f"toy{t}", mine.tobytes(),
                                         timeout_s=KV_TIMEOUT_S,
                                         attempt_s=KV_ATTEMPT_S)
            for p in parts:
                w = w + np.frombuffer(p, np.float64)
            with tracing.span("local_step"):
                time.sleep(step_s + _skew_sleep(opts, pid, t))
            if ckdir and t % ck_iter == 0:
                ckpt_lib.save(ckdir, ALGORITHM, t, w, None, seed=0)
    print(f"{ALGORITHM}: done at round {rounds}", flush=True)
    return 0


# --- the real-math CoCoA+ gang (--real=cocoa) --------------------------------


def shard_data(shard: int, n_rows: int, d: int, seed: int):
    """Deterministic synthetic (X, y) for one logical shard — keyed to
    the SHARD, never to its owning process, so a shrunk gang re-derives
    identical data for its inherited shards."""
    rng = np.random.default_rng(970_001 + 131 * shard + seed)
    X = rng.standard_normal((n_rows, d)) / np.sqrt(d)
    w_true = np.random.default_rng(7 + seed).standard_normal(d)
    y = np.where(X @ w_true >= 0.0, 1.0, -1.0)
    flips = rng.random(n_rows) < 0.08   # a non-separable margin band
    return X, np.where(flips, -y, y)


def round_idxs(t: int, shard: int, n_rows: int, h: int,
               seed: int) -> np.ndarray:
    """Round-keyed per-shard coordinate draws: a fresh per-round
    permutation prefix (every dual touched once per full-H round),
    owner-independent like everything else."""
    rng = np.random.default_rng(seed * 1_000_003 + t * 9176 + shard)
    return rng.permutation(n_rows)[:h]


class _GangCocoa:
    """The per-process state of the real-math gang run (see module
    docstring).  All float64 host math — the certificate side of the
    repo's numerics policy."""

    def __init__(self, opts, pid, nproc):
        self.opts = opts
        self.pid = pid
        self.nproc = nproc
        self.k = int(opts["numSplits"])
        if self.k % nproc != 0:
            # main() already rejected this with a stderr message; keep a
            # diagnostic here for any future direct constructor caller
            raise ValueError(
                f"K={self.k} shards cannot divide over {nproc} workers")
        self.m = self.k // nproc
        self.mine = range(pid * self.m, (pid + 1) * self.m)
        self.n_rows = int(opts.get("rowsPerShard", 48))
        self.d = int(opts.get("numFeatures", 24))
        self.h = int(opts.get("localIters", self.n_rows))
        self.lam = float(opts.get("lambda", 0.05))
        self.seed = int(opts.get("seed", 0))
        self.gamma = 1.0
        self.sigma = self.k * self.gamma      # the safe σ′ = K·γ
        self.n = self.k * self.n_rows
        self.data = {s: shard_data(s, self.n_rows, self.d, self.seed)
                     for s in self.mine}
        self.alpha = {s: np.zeros(self.n_rows) for s in self.mine}
        # contribution log: (round, process) -> γ-unscaled Δw.  w is
        # recomputed from it in canonical (round, process) order on
        # every change, so the float addition order — and with it the
        # bitwise w — is identical on every worker at drained
        # boundaries, no matter when each contribution arrived.
        self.contribs: dict = {}
        self.w_base = np.zeros(self.d)
        self.w = self.w_base.copy()

    def recompute_w(self):
        w = self.w_base.copy()
        for key in sorted(self.contribs):
            w = w + self.gamma * self.contribs[key]
        self.w = w

    def local_solve(self, t: int) -> np.ndarray:
        import oracle

        dw_mine = np.zeros(self.d)
        for s in self.mine:
            X, y = self.data[s]
            idxs = round_idxs(t, s, self.n_rows, self.h, self.seed)
            da, dw = oracle.local_sdca(
                X, y, self.w, self.alpha[s], idxs, self.lam, self.n,
                plus=True, sigma=self.sigma)
            self.alpha[s] = self.alpha[s] + self.gamma * da
            dw_mine += dw
        return dw_mine

    def absorb(self, r: int, parts: list):
        """Apply one joined round's peer contributions (own round-r Δw
        was logged at solve time — the owner must never see its own
        progress late)."""
        for q, payload in enumerate(parts):
            if q == self.pid:
                continue
            self.contribs[(r, q)] = np.frombuffer(payload, np.float64)
        self.recompute_w()

    def partials(self):
        """This process's share of the certificate sums: Σ hinge(y·x·w)
        over its rows, Σ α over its duals."""
        loss = 0.0
        a_sum = 0.0
        for s in self.mine:
            X, y = self.data[s]
            loss += float(np.maximum(0.0, 1.0 - y * (X @ self.w)).sum())
            a_sum += float(self.alpha[s].sum())
        return loss, a_sum

    def gap_from_totals(self, loss_total: float, alpha_total: float):
        """The exact hinge duality gap on the ACTUAL (w, α) — the
        unmodified evaluator: P(w) − D(α) with w = w(α) at a drained
        boundary = λ‖w‖² + (Σ hinge)/n − (Σ α)/n."""
        wsq = float(self.w @ self.w)
        primal = 0.5 * self.lam * wsq + loss_total / self.n
        dual = alpha_total / self.n - 0.5 * self.lam * wsq
        return primal, primal - dual

    def alpha_full(self, parts: list) -> np.ndarray:
        """(K, n_rows) α assembled from per-process blocks."""
        out = np.zeros((self.k, self.n_rows))
        for q, payload in enumerate(parts):
            block = np.frombuffer(payload, np.float64).reshape(
                self.m, self.n_rows)
            out[q * self.m:(q + 1) * self.m] = block
        return out


def real_main(opts, pid, nproc) -> int:
    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.parallel import distributed
    from cocoa_tpu.solvers.cocoa import StaleJoinWindow
    from cocoa_tpu.telemetry import events as tele_events
    from cocoa_tpu.telemetry import tracing

    rounds = int(opts["numRounds"])
    ckdir = opts.get("chkptDir", "")
    ck_iter = int(opts.get("chkptIter", 0))
    debug_iter = int(opts.get("debugIter", 5))
    gap_target = (float(opts["gapTarget"]) if opts.get("gapTarget")
                  else None)
    step_s = float(opts.get("stepSeconds", 0.0))
    stale = int(opts.get("staleRounds", 0))
    overlap_flag = str(opts.get("overlapComm", "off")).lower()
    if overlap_flag not in ("auto", "on", "off", "true"):
        print(f"error: --overlapComm must be auto|on|off, got "
              f"{overlap_flag!r}", file=sys.stderr)
        return 2
    overlap = (overlap_flag in ("on", "true")
               or (overlap_flag == "auto" and nproc > 1))
    if ck_iter > 0 and debug_iter > 0 and ck_iter % debug_iter != 0:
        # checkpoints must land on DRAINED boundaries (w = w(α) exactly,
        # so a resumed generation never embeds a half-joined round)
        print(f"error: --chkptIter ({ck_iter}) must be a multiple of "
              f"--debugIter ({debug_iter}) in --real=cocoa mode "
              f"(checkpoints land on drained eval boundaries)",
              file=sys.stderr)
        return 2

    gang = _GangCocoa(opts, pid, nproc)
    window = StaleJoinWindow(stale, algorithm=REAL_ALGORITHM)
    bus = tele_events.get_bus()

    start = 1
    if "resume" in opts and ckdir:
        path = ckpt_lib.latest(ckdir, REAL_ALGORITHM)
        if path is not None:
            meta, w0, a0 = ckpt_lib.load(path)
            gang.w_base = np.array(w0, np.float64)
            gang.recompute_w()
            a0 = np.asarray(a0, np.float64)
            for s in gang.mine:
                gang.alpha[s] = a0[s].copy()
            start = meta["round"] + 1
            print(f"resuming {REAL_ALGORITHM} from round {meta['round']} "
                  f"({path})", flush=True)

    gap = None
    stopped = None
    t = start - 1
    for t in range(start, rounds + 1):
        with tracing.span("round", round=t):
            with tracing.span("local_solve", round=t):
                dw_mine = gang.local_solve(t)
                extra = step_s + _skew_sleep(opts, pid, t)
                if extra > 0:
                    time.sleep(extra)
            # own contribution lands NOW (the local view must advance);
            # the posted payload unblocks peers the moment solve ends
            gang.contribs[(t, pid)] = dw_mine
            gang.recompute_w()
            payload = dw_mine.tobytes()
            if overlap:
                handle = distributed.async_host_allgather_bytes(
                    f"dw{t}", payload, timeout_s=KV_TIMEOUT_S,
                    attempt_s=KV_ATTEMPT_S, trace_attrs={"round": t})
            else:
                handle = distributed.host_allgather_bytes(
                    f"dw{t}", payload, timeout_s=KV_TIMEOUT_S,
                    attempt_s=KV_ATTEMPT_S)
            window.admit(t, handle)
            for r, parts, _late in window.join_due(t):
                gang.absorb(r, parts)

        if debug_iter > 0 and t % debug_iter == 0:
            # eval boundary: DRAIN first, so the certificate sees the
            # exact w = w(α) pair (docs/DESIGN.md §15)
            for r, parts, _late in window.drain(t):
                gang.absorb(r, parts)
            with tracing.span("eval", round=t):
                loss, a_sum = gang.partials()
                parts = distributed.host_allgather_bytes(
                    f"ev{t}", np.array([loss, a_sum]).tobytes(),
                    timeout_s=KV_TIMEOUT_S, attempt_s=KV_ATTEMPT_S)
                totals = np.sum([np.frombuffer(p, np.float64)
                                 for p in parts], axis=0)
                primal, gap = gang.gap_from_totals(totals[0], totals[1])
            bus.emit("round_eval", algorithm=REAL_ALGORITHM, t=t,
                     primal=primal, gap=gap, test_error=None, sigma=None,
                     stall=None)
            if pid == 0:
                print(f"{REAL_ALGORITHM}: round {t} gap {gap:.3e}",
                      flush=True)
            window.on_eval(gap)
            if ckdir and ck_iter > 0 and t % ck_iter == 0:
                a_mine = np.concatenate(
                    [gang.alpha[s] for s in gang.mine])
                parts = distributed.host_allgather_bytes(
                    f"ck{t}", a_mine.tobytes(), timeout_s=KV_TIMEOUT_S,
                    attempt_s=KV_ATTEMPT_S)
                ckpt_lib.save(ckdir, REAL_ALGORITHM, t, gang.w,
                              gang.alpha_full(parts), seed=gang.seed)
            if gap_target is not None and gap <= gap_target:
                stopped = "target"
                break

    # a fixed-round run may still hold pending joins for the tail
    # rounds; land them so the final state is drained (and a final
    # checkpoint, if due, was already written at the last boundary)
    for r, parts, _late in window.drain(t):
        gang.absorb(r, parts)
    bus.emit("run_end", algorithm=REAL_ALGORITHM, stopped=stopped,
             gap=gap, round=t)
    print(f"{REAL_ALGORITHM}: done at round {t}"
          + (f" (gap {gap:.3e})" if gap is not None else ""), flush=True)
    return 0


def main(argv=None) -> int:
    opts = parse(sys.argv[1:] if argv is None else argv)
    pid = int(opts.get("processId", 0))
    nproc = int(opts.get("numProcesses", 1))
    k = int(opts["numSplits"])

    _configure_telemetry(opts, pid)

    from cocoa_tpu.parallel.distributed import maybe_initialize

    maybe_initialize(opts.get("master"), pid, nproc)
    if k % nproc != 0:
        # the same loud divisibility rejection the real dataset builders
        # raise — a supervisor bug (non-divisor relaunch) fails fast here
        print(f"error: K={k} shards cannot divide over {nproc} workers",
              file=sys.stderr)
        return 2

    real = str(opts.get("real", "")).lower()
    if real in ("cocoa", "cocoa+"):
        return real_main(opts, pid, nproc)
    if real:
        print(f"error: --real takes 'cocoa', got {real!r}",
              file=sys.stderr)
        return 2
    return toy_main(opts, pid, nproc)


if __name__ == "__main__":
    sys.exit(main())
