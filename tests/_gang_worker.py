"""Minimal REAL-process gang worker for supervisor chaos tests.

Runs under the elastic supervisor exactly like the CLI would
(``python -m _gang_worker <flags> --master=... --processId=i
--numProcesses=n [--resume]``): joins the jax.distributed runtime (real
coordinator rendezvous), splits the K logical shards over the gang,
advances a deterministic round-keyed state with one
``host_allgather_bytes`` exchange per round (the hardened KV path,
exercised against a real coordination service), and checkpoints through
``cocoa_tpu.checkpoint`` — so the supervision mechanics (death
detection, shrink-to-survivors, resume, checkpoint-generation fallback)
run end to end with real processes WITHOUT cross-process XLA
collectives, which the pinned jax lacks on CPU (the real-training chaos
pin is tests/test_chaos.py's slow suite, same guard as the existing
multi-host gang tests).

The state is a pure function of (K, rounds) — each shard's per-round
increment is owner-independent and each w[s] receives exactly one
nonzero addend per round — so a kill/shrink/resume run must reproduce
the unfailed control's final checkpoint bit for bit, the same invariant
the real solvers get from round-keyed sampling.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

ALGORITHM = "ToyGang"


def parse(argv):
    opts = {}
    for a in argv:
        s = a.lstrip("-")
        k, _, v = s.partition("=")
        opts[k] = v if v else "true"
    return opts


def round_increments(t: int, k: int, lo: int, hi: int) -> np.ndarray:
    """The deterministic per-round update for shards [lo, hi): keyed to
    (round, shard) only — never to the process layout."""
    out = np.zeros(k, np.float64)
    for s in range(lo, hi):
        out[s] = ((t * 1000003 + s * 7919) % 104729) / 104729.0
    return out


def main(argv=None) -> int:
    opts = parse(sys.argv[1:] if argv is None else argv)
    pid = int(opts.get("processId", 0))
    nproc = int(opts.get("numProcesses", 1))
    k = int(opts["numSplits"])
    rounds = int(opts["numRounds"])
    ckdir = opts.get("chkptDir", "")
    ck_iter = int(opts.get("chkptIter", 5))
    step_s = float(opts.get("stepSeconds", 0.05))
    # per-worker step skew (--stepSkew=S): worker i sleeps step_s + i*S —
    # a deterministic straggler for the trace_report attribution tests
    skew_s = float(opts.get("stepSkew", 0.0))

    # --events/--trace: the same telemetry surface the real CLI wires —
    # worker 0 owns the given path, worker p > 0 streams to `.p<p>`
    # (telemetry/recorder.worker_stream_path), spans tagged with the
    # worker index — so the supervisor's flight-recorder dump and the
    # trace_report merge run against real per-process artifacts here too
    from cocoa_tpu.telemetry import events as tele_events
    from cocoa_tpu.telemetry import recorder as tele_recorder
    from cocoa_tpu.telemetry import tracing

    stream = (tele_recorder.worker_stream_path(opts["events"], pid)
              if opts.get("events") else None)
    # same ownership split as the real CLI: worker 0 owns <metrics>; the
    # supervisor owns the sibling <metrics>.gang (families="gang")
    metrics = opts.get("metrics") if pid == 0 else None
    if stream or metrics:
        tele_events.get_bus().configure(jsonl_path=stream,
                                        metrics_path=metrics)
    if opts.get("trace"):
        tracing.configure(enabled=True, worker=pid)

    from cocoa_tpu.parallel.distributed import (host_allgather_bytes,
                                                maybe_initialize)

    maybe_initialize(opts.get("master"), pid, nproc)
    if k % nproc != 0:
        # the same loud divisibility rejection the real dataset builders
        # raise — a supervisor bug (non-divisor relaunch) fails fast here
        print(f"error: K={k} shards cannot divide over {nproc} workers",
              file=sys.stderr)
        return 2
    m = k // nproc

    from cocoa_tpu import checkpoint as ckpt_lib

    w = np.zeros(k, np.float64)
    start = 1
    if "resume" in opts and ckdir:
        path = ckpt_lib.latest(ckdir, ALGORITHM)
        if path is not None:
            meta, w0, _ = ckpt_lib.load(path)
            w = np.array(w0, np.float64)
            start = meta["round"] + 1
            print(f"resuming {ALGORITHM} from round {meta['round']} "
                  f"({path})", flush=True)

    for t in range(start, rounds + 1):
        # the round span carries the round number; the nested
        # kv_allgather / local_step / checkpoint_save spans inherit it
        # (trace_report.attribute_rounds), which is what the per-round
        # critical path and the worker x phase straggler table key on
        with tracing.span("round", round=t):
            mine = round_increments(t, k, pid * m, (pid + 1) * m)
            # short KV budget: a dead peer must fail THIS worker quickly
            # so the supervisor (which already saw the death) isn't
            # racing a 10-minute hang in the teardown path
            parts = host_allgather_bytes(f"toy{t}", mine.tobytes(),
                                         timeout_s=30.0, attempt_s=2.0)
            for p in parts:
                w = w + np.frombuffer(p, np.float64)
            with tracing.span("local_step"):
                time.sleep(step_s + pid * skew_s)
            if ckdir and t % ck_iter == 0:
                ckpt_lib.save(ckdir, ALGORITHM, t, w, None, seed=0)
    print(f"{ALGORITHM}: done at round {rounds}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
