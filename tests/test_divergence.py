"""The σ′ divergence guard (VERDICT r4 item 4) and --sigma=auto fallback.

σ′ = K·γ (CoCoA.scala:45) is the paper's SAFE aggregation bound: it assumes
worst-case cross-shard coherence.  The --sigma override buys comm-rounds on
randomly partitioned data (benchmarks/SWEEPS.md: σ′=K/2 halves the rcv1
certified rounds) but diverges when pushed below the problem's tolerance —
and before this guard, a diverging run burned its entire round budget before
the certificate reported it.  These tests drive a run that PROVABLY needs
σ′ close to K — every shard holds the IDENTICAL rows, the adversarial
coherence the K·γ bound protects against — and pin the bail-out behavior on
both the host-stepped and the device-resident drivers.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.solvers import base, run_cocoa


def _coherent_dataset(k=4, m=32, d=16, seed=0):
    """K identical shards (the same m rows repeated K times): the true
    subproblem coupling is the full σ′ = K, so any σ′ ≪ K overshoots."""
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((m, d))
    X /= np.linalg.norm(X, axis=1, keepdims=True)
    y = np.where(X @ rng.standard_normal(d) >= 0, 1.0, -1.0)
    Xr = np.tile(X, (k, 1))
    yr = np.tile(y, k)
    n = k * m
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    data = LibsvmData(labels=yr, indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=Xr.reshape(-1), num_features=d)
    return shard_dataset(data, k=k, layout="dense", dtype=jnp.float32), n


K, LAM = 4, 1e-4


def _run(sigma, device_loop, num_rounds=400, gap_target=1e-3, rng="jax",
         **kw):
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=num_rounds, local_iters=16, lam=LAM,
                    sigma=sigma)
    debug = DebugParams(debug_iter=4, seed=0)
    return run_cocoa(ds, params, debug, plus=True, quiet=True, math="fast",
                     device_loop=device_loop, gap_target=gap_target, rng=rng,
                     **kw)


def test_gap_watch_windowed_no_improvement():
    w = base._GapWatch(n_evals=3, rel=0.75)
    assert not w.update(1.0)                    # first gap: reset to 1.0
    assert not w.update(0.9) and w.stall == 1   # -10%: not material
    assert not w.update(0.7) and w.stall == 0   # ≤ 0.75×1.0: reset
    assert not w.update(None) and w.stall == 0  # None gap is ignored
    assert not w.update(5.0) and w.stall == 1   # oscillation up
    assert not w.update(0.6) and w.stall == 2   # best=0.6 > 0.75·0.7
    assert w.update(0.55)                       # third stalled eval
    # a converging run that improves ≥25% every eval never trips
    w2 = base._GapWatch(n_evals=3, rel=0.75)
    g = 1.0
    for _ in range(50):
        assert not w2.update(g)
        g *= 0.7


def _bail_run(device_loop):
    """The bail-out pin runs at the calibration cadence 25 (window = 12
    evals = 300 rounds) with a 1600-round budget: at the original cadence
    4 the window is 75 evals = 300 rounds against a 400-round budget, and
    this environment's oscillation pattern improves the best gap just
    often enough that the streak never reaches 75 before the budget ends
    (the guard window is denominated in rounds exactly so cadence does not
    change its strictness — but the budget must leave room for it)."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=1600, local_iters=16, lam=LAM,
                    sigma=1.0)
    debug = DebugParams(debug_iter=25, seed=0)
    return run_cocoa(ds, params, debug, plus=True, quiet=True, math="fast",
                     device_loop=device_loop, gap_target=1e-3, rng="jax")


def test_unsafe_sigma_bails_out_host_driver(capsys):
    _, _, traj = _bail_run(device_loop=False)
    assert traj.stopped == "diverged"
    # the bail-out is the point: far fewer than the full budget
    assert traj.records[-1].round < 1600
    # quiet=True: the message is suppressed, the flag still set
    assert "DIVERGED" not in capsys.readouterr().out


def test_unsafe_sigma_bails_out_device_loop():
    _, _, traj = _bail_run(device_loop=True)
    assert traj.stopped == "diverged"
    assert traj.records[-1].round < 1600


def test_safe_sigma_converges_to_target():
    _, _, traj = _run(sigma=None, device_loop=False)  # σ′ = K·γ
    assert traj.stopped == "target"
    assert traj.records[-1].gap <= 1e-3


def test_fixed_round_runs_never_bail():
    """gap_target=None is the benchmark timing path: it must execute the
    full round budget even while diverging."""
    _, _, traj = _run(sigma=1.0, device_loop=True, num_rounds=40,
                      gap_target=None)
    assert traj.stopped is None
    assert traj.records[-1].round == 40


def test_sigma_auto_trial_converges(capsys):
    """When the aggressive K·γ/2 trial certifies the gap (it does on this
    data — even the adversarially coherent shards tolerate σ′ = K/2 here),
    auto returns the trial's result with no restart.  Pinned on the
    ``--sigmaSchedule=trial`` A/B control (the in-loop anneal schedule is
    the default now — tests/test_sigma_anneal.py)."""
    w, alpha, traj = _run(sigma="auto", device_loop=False,
                          sigma_schedule="trial")
    assert traj.stopped == "target"
    assert traj.records[-1].gap <= 1e-3
    assert "restarting with the safe" not in capsys.readouterr().out


def test_sigma_auto_fallback_on_divergence(tmp_path, monkeypatch, capsys):
    """When the trial diverges, auto deletes the trial's checkpoints and
    restarts with the safe σ′ = K·γ.  The trial's divergence is injected
    (every natural config probed tolerates σ′ = K/2 — which is exactly why
    the aggressive trial is the right default), so this pins the fallback
    MECHANICS: trial → diverged → cleanup → safe rerun → certified."""
    from cocoa_tpu.solvers import cocoa as cocoa_mod
    from cocoa_tpu.utils.logging import Trajectory, RoundRecord

    ds, n = _coherent_dataset(k=K)
    trial_sigma = K / 2.0
    real = cocoa_mod.run_sdca_family
    calls = []

    def spy(ds_, params_, debug_, name_, alg, **kw):
        calls.append(alg[2])            # alg = (mode, scaling, sigma)
        if alg[2] == trial_sigma:
            # simulate a diverged trial that left a checkpoint behind
            (tmp_path / "CoCoA+-r000392.npz").write_bytes(b"x")
            t = Trajectory(name_, quiet=True)
            t.records.append(RoundRecord(round=392, wall_time=None, gap=5.0))
            t.stopped = "diverged"
            return None, None, t
        return real(ds_, params_, debug_, name_, alg, **kw)

    monkeypatch.setattr(cocoa_mod, "run_sdca_family", spy)
    params = Params(n=n, num_rounds=400, local_iters=16, lam=LAM,
                    sigma="auto")
    debug = DebugParams(debug_iter=4, seed=0, chkpt_iter=8,
                        chkpt_dir=str(tmp_path))
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=False,
                               math="fast", gap_target=1e-3, rng="jax",
                               sigma_schedule="trial")
    assert calls[0] == trial_sigma          # aggressive trial first
    assert calls[1] == float(K)             # safe σ′ = K·γ rerun
    assert traj.stopped == "target"
    assert traj.records[-1].gap <= 1e-3
    # the diverged trial's checkpoint is gone; the safe rerun's remain
    names = sorted(p.name for p in tmp_path.iterdir())
    assert "CoCoA+-r000392.npz" not in names
    assert any(p.startswith("CoCoA+-r") for p in names)
    assert "restarting with the safe" in capsys.readouterr().out


def test_sigma_auto_validation():
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=10, local_iters=4, lam=LAM, sigma="auto")
    debug = DebugParams(debug_iter=2, seed=0)
    with pytest.raises(ValueError, match="gapTarget"):
        run_cocoa(ds, params, debug, plus=True, quiet=True)
    # plain CoCoA ignores σ′ entirely: auto degenerates to the default
    # (the reference driver runs both algorithms from one flag set,
    # hingeDriver.scala:84-89 — the CoCoA leg must not reject the flag)
    w_auto, _, _ = run_cocoa(ds, params, debug, plus=False, quiet=True)
    import dataclasses
    w_none, _, _ = run_cocoa(ds, dataclasses.replace(params, sigma=None),
                             debug, plus=False, quiet=True)
    np.testing.assert_array_equal(np.asarray(w_auto), np.asarray(w_none))


def test_stall_window_scales_with_cadence():
    """The guard window is denominated in ROUNDS: fine eval cadences get
    proportionally more evals, so slow-but-steady convergence (~2%/eval
    at cadence 1) is not mislabeled DIVERGED (round-5 review)."""
    assert base.stall_window(25) == base.STALL_EVALS
    assert base.stall_window(1) == base.STALL_ROUNDS
    assert base.stall_window(10) == base.STALL_ROUNDS // 10
    assert base.stall_window(1000) == base.STALL_EVALS  # floor
    # a healthy 2%-per-eval run at cadence 1 survives its 300-eval window
    w = base._GapWatch(n_evals=base.stall_window(1))
    g = 1.0
    for _ in range(600):
        assert not w.update(g)
        g *= 0.98


def test_sigma_auto_resumed_run_skips_trial(capsys):
    """A resumed run (w_init/start_round restored) must not re-trial: auto
    degrades to the safe σ′ immediately, so mid-trial state can never leak
    into a 'fresh' safe run (round-5 review)."""
    import dataclasses

    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=60, local_iters=16, lam=LAM,
                    sigma="auto")
    debug = DebugParams(debug_iter=4, seed=0)
    rng = np.random.default_rng(0)
    w0 = jnp.asarray(rng.normal(size=16) * 0.01, jnp.float32)
    w_auto, _, traj = run_cocoa(ds, params, debug, plus=True, quiet=False,
                                math="fast", gap_target=1e-3, rng="jax",
                                w_init=w0, start_round=5,
                                sigma_schedule="trial")
    out = capsys.readouterr().out
    assert "resumed run continues with the safe" in out
    # identical to an explicit safe resume
    safe = dataclasses.replace(params, sigma=None)
    w_safe, _, _ = run_cocoa(ds, safe, debug, plus=True, quiet=True,
                             math="fast", gap_target=1e-3, rng="jax",
                             w_init=w0, start_round=5)
    np.testing.assert_array_equal(np.asarray(w_auto), np.asarray(w_safe))
