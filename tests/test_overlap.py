"""The round-barrier levers (docs/DESIGN.md §15): overlapped exchanges
(`--overlapComm`) and bounded-staleness CoCoA+ aggregation
(`--staleRounds`).

Fast half: the async exchange handle (post/collect/join semantics, the
host-bytes guard, the comm_overlap accounting), the StaleJoinWindow
policy (round-indexed join windows, drain, gap-rise collapse, the
never-later-than-S bound), the safe-γ partial-aggregation rule, the
metrics gauges, and the CLI flag surface.

Slow half (real 2-process jax.distributed gangs — the `--real=cocoa`
worker of tests/_gang_worker.py, runnable on ANY jax):

- THE acceptance A/B: on the deterministic rotating `--stepSkew` chaos
  gang, exchange-phase `cocoa_straggler_slack_seconds` drops >= 40%
  with `--overlapComm=on --staleRounds=1` vs the synchronous control,
  while both runs certify the same 1e-4 duality gap (actual (w, α),
  unmodified evaluator) and the stale run takes <= 1.25x the control's
  comm rounds;
- the off-switch pin: `--overlapComm=on --staleRounds=0` is
  bit-identical (gap trajectory AND final checkpoint) to the
  synchronous control;
- the staleness bound: no contribution ever joins more than S rounds
  late, and every round's contribution does join;
- the elastic chaos pin: a SIGKILL mid-run with staleness on shrinks to
  the survivor, drops the dead generation's pending stale joins with
  the process, and the resumed run still completes and certifies — no
  deadlock (the bounded KV budget is what guarantees that).
"""

import json
import os
import time

import numpy as np
import pytest

from _faults import Fault, FaultPlan, checkpoint_at_least, sigkill
from _gang_worker import EXCHANGE_PHASES, supervise_gang
from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import elastic
from cocoa_tpu.parallel import distributed
from cocoa_tpu.solvers.cocoa import StaleJoinWindow, partial_gamma
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import schema as tele_schema
from cocoa_tpu.telemetry import trace_report

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def clean_bus():
    tele_events.get_bus().reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()


# --- ExchangeHandle / async allgather ----------------------------------------


def test_async_allgather_single_process_is_immediate(clean_bus, tmp_path):
    ev = tmp_path / "ev.jsonl"
    clean_bus.configure(jsonl_path=str(ev))
    h = distributed.async_host_allgather_bytes("t0", b"payload")
    assert h.done()
    assert h.join() == [b"payload"]
    # re-join returns the cached result without re-emitting
    assert h.join() == [b"payload"]
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    overlaps = [r for r in recs if r["event"] == "comm_overlap"]
    assert len(overlaps) == 1
    assert overlaps[0]["tag"] == "t0"
    assert overlaps[0]["wait_s"] >= 0.0
    assert tele_schema.check_file(str(ev)) == []


def test_async_allgather_rejects_device_values():
    for bad in (np.zeros(3), [b"x"], "str", 7):
        with pytest.raises(TypeError, match="host bytes"):
            distributed.async_host_allgather_bytes("t", bad)


def test_exchange_handle_overlaps_and_accounts(clean_bus, tmp_path):
    """A slow collector runs concurrently with the caller's 'compute';
    hidden_s covers the overlapped portion, wait_s the residual join
    block, and a collector error surfaces at join()."""
    ev = tmp_path / "ev.jsonl"
    clean_bus.configure(jsonl_path=str(ev))

    def collect():
        time.sleep(0.12)
        return ["ok"]

    h = distributed.ExchangeHandle("slow", collect=collect,
                                   attrs={"round": 3})
    time.sleep(0.06)          # caller-side "compute" the exchange hides
    out = h.join()
    assert out == ["ok"]
    rec = [json.loads(ln) for ln in ev.read_text().splitlines()
           if '"comm_overlap"' in ln][0]
    assert rec["round"] == 3
    assert rec["hidden_s"] >= 0.04        # ran while the caller computed
    assert rec["wait_s"] >= 0.02          # and still blocked a little
    # errors propagate at the join barrier, not silently
    def boom():
        raise RuntimeError("peer died")
    h2 = distributed.ExchangeHandle("err", collect=boom)
    with pytest.raises(RuntimeError, match="peer died"):
        h2.join()


def test_async_kv_get_joins_value():
    class Client:
        def blocking_key_value_get(self, key, timeout_ms):
            return f"value-of-{key}"

    h = distributed.async_kv_get(Client(), "k1", timeout_s=1.0,
                                 attempt_s=0.1)
    assert h.join() == "value-of-k1"


# --- StaleJoinWindow policy --------------------------------------------------


def test_stale_window_round_indexed_join_semantics(clean_bus, tmp_path):
    ev = tmp_path / "ev.jsonl"
    clean_bus.configure(jsonl_path=str(ev))
    w = StaleJoinWindow(2, algorithm="T")
    w.admit(1, [b"a"])
    w.admit(2, [b"b"])
    # round 2: cut = 0 — nothing due yet (both inside the window)
    assert w.join_due(2) == []
    # round 3: round 1 expires, exactly 2 rounds late — never more
    out = w.join_due(3)
    assert [(r, late) for r, _, late in out] == [(1, 2)]
    # drain forces the rest, 1 round late
    out = w.drain(3)
    assert [(r, late) for r, _, late in out] == [(2, 1)]
    assert w.pending_rounds() == []
    # duplicate admit is a bug, loudly
    w.admit(4, [b"c"])
    with pytest.raises(ValueError, match="already"):
        w.admit(4, [b"d"])
    w.abort()
    assert w.pending_rounds() == []
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    lates = [r["rounds_late"] for r in recs if r["event"] == "stale_join"]
    assert lates == [2, 1]          # synchronous joins are not events
    assert tele_schema.check_file(str(ev)) == []


def test_stale_window_zero_is_synchronous():
    w = StaleJoinWindow(0)
    w.admit(5, [b"x"])
    out = w.join_due(5)             # joins its own round — the barrier
    assert [(r, late) for r, _, late in out] == [(5, 0)]


def test_stale_window_gap_rise_collapses_then_restores():
    w = StaleJoinWindow(3)
    assert w.on_eval(1.0) is False          # first eval: nothing to compare
    assert w.effective_window() == 3
    assert w.on_eval(2.0) is True           # rise -> synchronous
    assert w.collapsed and w.effective_window() == 0
    w.admit(10, [b"x"])
    out = w.join_due(10)                    # collapsed: joins immediately
    assert [(r, late) for r, _, late in out] == [(10, 0)]
    assert w.on_eval(1.5) is True           # improvement -> restored
    assert not w.collapsed and w.effective_window() == 3


def test_stale_window_rejects_negative():
    with pytest.raises(ValueError, match="staleRounds"):
        StaleJoinWindow(-1)


def test_partial_gamma_identity_and_bounds():
    # the safe scale for a partial aggregate is γ itself (the σ′ = K·γ
    # bound over-covers every subset) — and the rule validates its m
    assert partial_gamma(1.0, 4, 4) == 1.0
    assert partial_gamma(0.5, 8, 1) == 0.5
    for bad in (0, 5):
        with pytest.raises(ValueError):
            partial_gamma(1.0, 4, bad)


# --- metrics gauges ----------------------------------------------------------


def test_metrics_overlap_and_stale_gauges(tmp_path):
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = tmp_path / "m.prom"
    w = MetricsWriter(str(path))
    text = path.read_text()
    assert "cocoa_overlap_hidden_seconds" not in text
    assert "cocoa_stale_joins_total" not in text
    base = {"seq": 1, "ts": 0.0, "pid": 1}
    w({**base, "event": "comm_overlap", "tag": "dw3", "hidden_s": 0.5,
       "wait_s": 0.1})
    w({**base, "event": "comm_overlap", "tag": "dw4", "hidden_s": 0.25,
       "wait_s": 0.0})
    w({**base, "event": "stale_join", "algorithm": "T", "t": 4,
       "round": 3, "rounds_late": 1, "workers": 2})
    w({**base, "event": "stale_join", "algorithm": "T", "t": 6,
       "round": 4, "rounds_late": 2, "workers": 2})
    w({**base, "event": "stale_join", "algorithm": "T", "t": 7,
       "round": 6, "rounds_late": 1, "workers": 2})
    text = path.read_text()
    assert "cocoa_overlap_hidden_seconds 0.75" in text
    assert "cocoa_overlap_wait_seconds 0.1" in text
    assert 'cocoa_stale_joins_total{rounds_late="1"} 2' in text
    assert 'cocoa_stale_joins_total{rounds_late="2"} 1' in text


# --- overlap_io: the device-loop checkpoint-write overlap --------------------


def test_overlap_io_checkpoints_bit_identical(tmp_path):
    """`--overlapComm` on the compiled-collective CLI path overlaps the
    checkpoint WRITE with the next super-block dispatch
    (base.drive_device_full).  The snapshot stays synchronous, so the
    written archives — and the run itself — are bit-identical to the
    synchronous control, and every write has landed by the time the
    driver returns."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_sparse
    from cocoa_tpu.solvers import run_cocoa

    data = synth_sparse(64, 32, nnz_mean=6, seed=4)
    ds = shard_dataset(data, k=2, layout="dense", dtype=jnp.float32)
    p = Params(n=data.n, num_rounds=20, local_iters=8, lam=0.01)

    def run(ckdir, overlap):
        d = DebugParams(debug_iter=5, seed=0, chkpt_iter=5,
                        chkpt_dir=str(ckdir))
        return run_cocoa(ds, p, d, plus=True, quiet=True,
                         device_loop=True, overlap_io=overlap)

    w_s, a_s, _ = run(tmp_path / "sync", False)
    w_o, a_o, _ = run(tmp_path / "overlap", True)
    np.testing.assert_array_equal(np.asarray(w_s), np.asarray(w_o))
    np.testing.assert_array_equal(np.asarray(a_s), np.asarray(a_o))
    for sub in ("sync", "overlap"):
        paths = ckpt_lib.generations(str(tmp_path / sub), "CoCoA+")
        assert paths, f"no checkpoints written under {sub}"
    m_s, ws, as_ = ckpt_lib.load(ckpt_lib.latest(str(tmp_path / "sync"),
                                                 "CoCoA+"))
    m_o, wo, ao = ckpt_lib.load(ckpt_lib.latest(str(tmp_path / "overlap"),
                                                "CoCoA+"))
    assert m_s["round"] == m_o["round"] == 20
    np.testing.assert_array_equal(ws, wo)
    np.testing.assert_array_equal(as_, ao)


# --- kv backoff: slow attempts reset the exponential state -------------------


def test_kv_backoff_resets_after_full_length_attempt(monkeypatch):
    """Fast failures escalate the pause exponentially; a FULL-LENGTH
    attempt proves the coordinator is listening, so the next transient
    fast failure must pause at the BASE again — not at the escalated
    cap, which would stretch the budget deaf (the PR-9 pin's
    slow-attempt corollary)."""
    monkeypatch.setattr(distributed, "_KV_BACKOFF_BASE_S", 0.01)
    monkeypatch.setattr(distributed, "_KV_BACKOFF_CAP_S", 10.0)
    pauses = []
    real_sleep = time.sleep
    monkeypatch.setattr(distributed.time, "sleep",
                        lambda s: (pauses.append(s), real_sleep(0.001)))

    class Client:
        """fast, fast, SLOW (full-length), fast, then succeed."""

        def __init__(self):
            self.calls = 0

        def blocking_key_value_get(self, key, timeout_ms):
            self.calls += 1
            if self.calls in (1, 2, 4):
                raise RuntimeError("UNAVAILABLE: transient")
            if self.calls == 3:
                real_sleep(timeout_ms / 1000.0)
                raise RuntimeError("DEADLINE_EXCEEDED")
            return "ok"

    assert distributed.blocking_kv_get(Client(), "k", timeout_s=30.0,
                                       attempt_s=0.05) == "ok"
    # pauses: base, 2x base after the two fast failures; NO pause after
    # the slow attempt; then BASE again (reset), not 4x base
    assert pauses == pytest.approx([0.01, 0.02, 0.01])


# --- CLI flag surface --------------------------------------------------------


def _cli_spy(monkeypatch):
    calls = {}

    def spy(worker_argv, n_workers, **kw):
        calls["argv"] = worker_argv
        calls["n"] = n_workers
        calls.update(kw)
        return 0

    monkeypatch.setattr("cocoa_tpu.elastic.supervise", spy)
    return calls


BASE_FLAGS = ["--trainFile=x.dat", "--numFeatures=10", "--numSplits=4"]


def test_cli_overlap_and_stale_flag_validation(monkeypatch, capsys):
    from cocoa_tpu import cli

    _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--overlapComm=maybe",
                                  "--elastic=2"]) == 2
    assert "--overlapComm" in capsys.readouterr().err
    assert cli.main(BASE_FLAGS + ["--staleRounds=-1", "--elastic=2"]) == 2
    assert cli.main(BASE_FLAGS + ["--staleRounds=x", "--elastic=2"]) == 2
    capsys.readouterr()
    # S > 0 on the compiled-collective CLI path is rejected loudly, with
    # the pointer to the host-exchange path
    assert cli.main(BASE_FLAGS + ["--staleRounds=1", "--elastic=2"]) == 2
    assert "host-exchange" in capsys.readouterr().err
    # the accepted spellings pass validation and reach the supervisor
    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--overlapComm=on", "--staleRounds=0",
                                  "--elastic=2"]) == 0
    assert calls["n"] == 2
    assert "--overlapComm=on" in calls["argv"]


# --- real-process gang A/B ---------------------------------------------------


def _gang_env(monkeypatch):
    monkeypatch.setenv(
        "PYTHONPATH",
        f"{ROOT}{os.pathsep}{TESTS}{os.pathsep}"
        f"{os.environ.get('PYTHONPATH', '')}")
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f))


# the tuned real-gang problem: certifies the 1e-4 hinge gap in ~130
# synchronous rounds (measured, deterministic — round-keyed sampling and
# round-indexed join windows make every run bit-reproducible)
REAL_FLAGS = ["--real=cocoa", "--numSplits=2", "--numRounds=400",
              "--gapTarget=1e-4", "--lambda=0.01", "--rowsPerShard=64",
              "--numFeatures=32", "--localIters=16"]


def _run_gang(tmp_path, name, extra, n=2, hooks=None):
    ev = str(tmp_path / f"{name}.jsonl")
    rc, evs = supervise_gang(REAL_FLAGS + list(extra), n=n, events=ev,
                             **(hooks or {}))
    assert rc == 0
    return ev, evs


def _gap_trajectory(evs):
    return [(r["t"], r["gap"]) for r in evs
            if r["event"] == "round_eval"]


@pytest.mark.slow
def test_gang_off_switches_bit_identical_and_stale_bounded(tmp_path,
                                                           monkeypatch):
    """`--overlapComm=on --staleRounds=0` must be BIT-identical to the
    synchronous control — same gap trajectory, same final checkpoint
    bytes (overlap changes when the exchange runs, never what it
    carries) — and `--staleRounds=2` never admits a contribution more
    than 2 rounds late while still certifying the same target."""
    _gang_env(monkeypatch)
    ck_a = tmp_path / "ck_a"
    ck_b = tmp_path / "ck_b"
    common = ["--debugIter=5", "--chkptIter=20"]
    _, evs_sync = _run_gang(
        tmp_path, "sync", common + [f"--chkptDir={ck_a}",
                                    "--overlapComm=off", "--staleRounds=0"])
    _, evs_ov = _run_gang(
        tmp_path, "overlap", common + [f"--chkptDir={ck_b}",
                                       "--overlapComm=on",
                                       "--staleRounds=0"])
    assert _gap_trajectory(evs_sync) == _gap_trajectory(evs_ov)
    assert not [r for r in evs_ov if r["event"] == "stale_join"]
    meta_a, w_a, al_a = ckpt_lib.load(ckpt_lib.latest(str(ck_a),
                                                      "GangCoCoA+"))
    meta_b, w_b, al_b = ckpt_lib.load(ckpt_lib.latest(str(ck_b),
                                                      "GangCoCoA+"))
    assert meta_a["round"] == meta_b["round"]
    np.testing.assert_array_equal(w_a, w_b)
    np.testing.assert_array_equal(al_a, al_b)

    # the staleness bound, on a deterministic skewed fixture
    ev, evs_st = _run_gang(
        tmp_path, "stale2",
        common + ["--overlapComm=on", "--staleRounds=2",
                  "--stepSeconds=0.002", "--stepSkew=0.004",
                  "--skewEvery=2"])
    end = [r for r in evs_st if r["event"] == "run_end"][-1]
    assert end["stopped"] == "target"
    lates = [r["rounds_late"] for r in evs_st
             if r["event"] == "stale_join"]
    assert lates and max(lates) <= 2
    assert tele_schema.check_file(ev) == []


@pytest.mark.slow
def test_gang_overlap_stale_cuts_straggler_slack_40pct(tmp_path,
                                                       monkeypatch):
    """THE acceptance A/B (ISSUE 12): on the rotating `--stepSkew`
    2-process chaos gang, the exchange-phase
    cocoa_straggler_slack_seconds drops >= 40% with
    `--overlapComm=on --staleRounds=1` vs the synchronous control,
    while both runs certify the same 1e-4 duality gap (actual (w, α),
    unmodified evaluator) and the stale run needs <= 1.25x the
    control's comm rounds.  Measured margins (local CPU): ~73% slack
    drop and a 1.0x round ratio — the asserted bars leave room for CI
    scheduling noise."""
    _gang_env(monkeypatch)
    skew = ["--debugIter=10", "--trace", "--stepSeconds=0.008",
            "--stepSkew=0.03", "--skewEvery=2"]

    def measure(name, levers):
        ev, evs = _run_gang(tmp_path, name, skew + levers)
        assert tele_schema.check_file(ev) == []
        end = [r for r in evs if r["event"] == "run_end"][-1]
        assert end["stopped"] == "target", f"{name} did not certify"
        assert end["gap"] <= 1e-4
        spans = trace_report.load_spans([ev, ev + ".p1"])
        rows = trace_report.stragglers(spans)
        slack = sum(r["slack_s"] for r in rows
                    if r["phase"] in EXCHANGE_PHASES)
        rounds = max(r["t"] for r in evs if r["event"] == "round_eval")
        return slack, rounds, rows

    ctl_slack, ctl_rounds, _ = measure(
        "control", ["--overlapComm=off", "--staleRounds=0"])
    trt_slack, trt_rounds, trt_rows = measure(
        "treatment", ["--overlapComm=on", "--staleRounds=1"])

    # the gang genuinely waited on the barrier in the control
    assert ctl_slack > 0.5, f"control slack too small to A/B ({ctl_slack})"
    drop = 1.0 - trt_slack / ctl_slack
    assert drop >= 0.40, (
        f"exchange slack only dropped {drop:.0%} "
        f"({ctl_slack:.3f}s -> {trt_slack:.3f}s)")
    assert trt_rounds <= 1.25 * ctl_rounds, (ctl_rounds, trt_rounds)
    # the hidden exchange must not masquerade as compute slack either:
    # the charged accounting keeps local_solve as the top straggler rows
    assert trt_rows[0]["phase"] == "local_solve"


@pytest.mark.slow
def test_gang_resize_with_staleness_drops_pending_joins(tmp_path,
                                                        monkeypatch):
    """The elastic chaos pin: SIGKILL worker 1 mid-run with staleness +
    overlap ON; the supervisor shrinks to the survivor, the dead
    generation's pending stale joins die with its processes (bounded KV
    budget — no deadlock), and the resumed 1-worker run completes and
    certifies from the drained checkpoint."""
    _gang_env(monkeypatch)
    ck = tmp_path / "ck"
    ev = str(tmp_path / "chaos.jsonl")
    tele_events.get_bus().configure(jsonl_path=ev)
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "GangCoCoA+", 20),
              name="kill-worker-1"),
    )
    resizes = []
    rc = elastic.supervise(
        REAL_FLAGS + [f"--events={ev}", f"--chkptDir={ck}",
                      "--debugIter=5", "--chkptIter=20",
                      "--overlapComm=on", "--staleRounds=1",
                      "--stepSeconds=0.01"],
        2, module="_gang_worker", max_restarts=3, poll_s=0.05,
        num_splits=2, shrink="now", backoff_base_s=0.0,
        on_generation=plan.on_generation,
        on_restart=lambda gen, reason, old, new, backoff:
            resizes.append((old, new)),
    )
    plan.join()
    assert rc == 0
    assert plan.errors == []
    assert plan.fired == ["kill-worker-1"]
    assert (2, 1) in resizes
    recs = [json.loads(ln) for ln in open(ev)]
    assert any(r["event"] == "gang_resize" and r["new_size"] == 1
               for r in recs)
    ends = [r for r in recs if r["event"] == "run_end"]
    assert ends and ends[-1]["stopped"] == "target"
    assert ends[-1]["gap"] <= 1e-4
    meta, w, alpha = ckpt_lib.load(ckpt_lib.latest(str(ck), "GangCoCoA+"))
    assert meta["round"] >= 20 and alpha.shape[0] == 2
    assert tele_schema.check_file(ev) == []
