"""Failure recovery: the reference leans on Spark lineage recomputation
(SURVEY.md §5); the rebuild's answer is round-stamped resumable
checkpoints.  This test exercises the full story the way a preempted job
would: a CLI training process is SIGKILLed mid-run, relaunched with
``--resume``, and must finish with EXACTLY the summary of an uninterrupted
run (round-indexed RNG makes the resumed trajectory bit-identical)."""

import os
import signal

import pytest
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
from conftest import SMALL_TRAIN as TRAIN  # noqa: E402

# localIterFrac=1 makes CPU rounds slow enough (H=500 exact-math steps)
# that the SIGKILL reliably lands mid-run, after the first checkpoint but
# well before the final round — the point of the test
BASE = [
    sys.executable, "-m", "cocoa_tpu.cli",
    f"--trainFile={TRAIN}", "--numFeatures=9947", "--numRounds=24",
    "--localIterFrac=1", "--numSplits=4", "--lambda=.001",
    "--justCoCoA=true", "--debugIter=4", "--chkptIter=4",
]


def _run(args, timeout=200):
    return subprocess.run(
        args, cwd=ROOT, env={**os.environ, "PYTHONPATH": ROOT},
        capture_output=True, text=True, timeout=timeout,
    )


def _summary(out: str):
    """The two end-of-run summary blocks (CoCoA+ and CoCoA objective/gap)."""
    return [ln.strip() for ln in out.splitlines()
            if "Total Objective" in ln or "Duality Gap" in ln]


@pytest.mark.slow
@pytest.mark.parametrize("extra", [[], ["--deviceLoop=true"]],
                         ids=["chunked", "deviceloop"])
def test_sigkill_then_resume_matches_uninterrupted(tmp_path, extra):
    """Both checkpointing drivers: the chunked host-stepped path and the
    device loop (VERDICT r2 item 3 — the production driver must survive a
    kill; saves happen at super-block boundaries, chkptIter rounded up to
    the debugIter cadence)."""
    BASE = globals()["BASE"] + extra
    ck = str(tmp_path / "ck")
    os.makedirs(ck)

    # uninterrupted reference run
    ref = _run(BASE + [f"--chkptDir={ck}-ref"])
    assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]
    want = _summary(ref.stdout)
    assert want, ref.stdout[-2000:]

    # start the same run, kill it once the first checkpoint exists.
    # stdout goes to a file, not a PIPE: an undrained pipe could block the
    # child before it ever checkpoints if its output outgrew the OS buffer
    log_path = tmp_path / "killed.log"
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            BASE + [f"--chkptDir={ck}"], cwd=ROOT,
            env={**os.environ, "PYTHONPATH": ROOT},
            stdout=log, stderr=subprocess.STDOUT, text=True,
        )
        try:
            deadline = time.time() + 200
            while time.time() < deadline:
                if any(f.endswith(".npz") for f in os.listdir(ck)):
                    break
                if proc.poll() is not None:
                    out = log_path.read_text()
                    raise AssertionError(
                        f"run finished before any checkpoint appeared:\n"
                        f"{out[-2000:]}"
                    )
                time.sleep(0.1)
            else:
                raise AssertionError(
                    "no checkpoint appeared within the deadline"
                )
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()

    # relaunch with --resume: must pick up a MID-RUN checkpoint (not the
    # final one — otherwise the test proves nothing) and match exactly
    res = _run(BASE + [f"--chkptDir={ck}", "--resume"])
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    resumed = [ln for ln in res.stdout.splitlines() if "resuming" in ln]
    assert resumed, res.stdout[-2000:]
    import re

    m = re.search(r"from round (\d+)", resumed[0])
    assert m and int(m.group(1)) < 24, resumed[0]
    assert _summary(res.stdout) == want
