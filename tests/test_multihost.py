"""Multi-host (multi-process) runtime: the reference validates multi-worker
behavior with local-mode Spark (SURVEY.md §4); the multi-PROCESS analogue
here is two actual OS processes joined through
``parallel/distributed.maybe_initialize`` (the ``--master=host:port`` path),
forming a 2-device global CPU mesh whose psum rides the cross-process
collective backend (Gloo).  The trained w must be identical on every
process AND identical to a single-process run of the same problem — the
multi-host path is the same shard_map/psum code, only the device set
changes.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))

_WORKER = r"""
import json, os, sys
proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from cocoa_tpu.parallel.distributed import maybe_initialize
assert maybe_initialize(f"127.0.0.1:{port}", process_id=proc_id,
                        num_processes=nproc)

import jax.numpy as jnp
import numpy as np
from _multihost_data import build_data
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_cocoa

data = build_data()
assert len(jax.devices()) == nproc  # one CPU device per process
mesh = make_mesh(nproc)
ds = shard_dataset(data, k=nproc, layout="dense", dtype=jnp.float64,
                   mesh=mesh)
params = Params(n=data.n, num_rounds=5, local_iters=10, lam=0.01)
w, alpha, traj = run_cocoa(ds, params, DebugParams(debug_iter=5, seed=0),
                           plus=True, mesh=mesh, quiet=True)
print("RESULT " + json.dumps({
    "w": np.asarray(w).tolist(),
    "gap": float(traj.records[-1].gap),
}), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_run_matches_single_process(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {**os.environ, "PYTHONPATH": f"{ROOT}{os.pathsep}{TESTS}"}
    # workers must not inherit the virtual 8-device flag (1 device each)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=ROOT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=220)
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
            outs.append(out)
    finally:
        # a hung rendezvous must not orphan the sibling worker (it would
        # pin the Gloo port and poison later runs)
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out[-2000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))

    # identical across processes (replicated w is the same global value)
    np.testing.assert_array_equal(results[0]["w"], results[1]["w"])

    # and identical to a single-process run of the same problem
    import jax.numpy as jnp

    from _multihost_data import build_data
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    data = build_data()
    ds = shard_dataset(data, k=2, layout="dense", dtype=jnp.float64)
    params = Params(n=data.n, num_rounds=5, local_iters=10, lam=0.01)
    w, _, traj = run_cocoa(ds, params, DebugParams(debug_iter=5, seed=0),
                           plus=True, quiet=True)
    np.testing.assert_allclose(results[0]["w"], np.asarray(w), atol=1e-12)
    assert abs(results[0]["gap"] - traj.records[-1].gap) < 1e-12


_MEM_WORKER = r"""
import json, os, sys
proc_id, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")

from cocoa_tpu.parallel.distributed import maybe_initialize
assert maybe_initialize(f"127.0.0.1:{port}", process_id=proc_id,
                        num_processes=nproc)

import jax.numpy as jnp
import numpy as np
from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.parallel import make_mesh

# dense n x d, ~128 MB f64 full matrix; each process must only ever hold
# its own ~1/2 shard (host slab + its device buffer)
n, d = 4000, 4000
rng = np.random.default_rng(0)
X = (rng.random((n, d)) < 0.05) * 1.0   # sparse-ish values, dense layout
y = np.where(rng.random(n) > 0.5, 1.0, -1.0)
nz_rows = [np.nonzero(X[i])[0] for i in range(n)]
indptr = np.concatenate([[0], np.cumsum([len(r) for r in nz_rows])])
data = LibsvmData(labels=y, indptr=indptr.astype(np.int64),
                  indices=np.concatenate(nz_rows).astype(np.int32),
                  values=np.concatenate([X[i, r] for i, r in enumerate(nz_rows)]),
                  num_features=d)
del X, nz_rows

def rss():
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")

mesh = make_mesh(nproc)
before = rss()
ds = shard_dataset(data, k=nproc, layout="dense", dtype=jnp.float64, mesh=mesh)
jax.block_until_ready(ds.X)
delta = rss() - before
full = n * d * 8
# one addressable piece per process, and memory well under the full matrix
assert len(ds.X.addressable_shards) == 1
print("RESULT " + json.dumps({"delta": delta, "full": full,
                              "frac": delta / full}), flush=True)
"""


@pytest.mark.slow
def test_elastic_supervisor_recovers_from_sigkill(tmp_path, monkeypatch):
    """VERDICT r3 item 7 (coverage row 23): the --elastic supervisor is the
    all-reduce-runtime analogue of Spark's implicit lineage recovery — a
    SIGKILLed worker brings the gang down, the supervisor relaunches it
    with --resume, and the run completes to the final round with the same
    state an uninterrupted run reaches (resume exactness is pinned by
    tests/test_crash_resume.py; this test pins the supervision mechanics:
    detection, gang teardown, restart, completion)."""
    import signal
    import threading
    import time as _time

    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import elastic
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    data = synth_sparse(96, 64, nnz_mean=8, seed=2)
    train = tmp_path / "train.dat"
    write_libsvm(data, str(train))
    ckdir = tmp_path / "ck"
    rounds = 300
    argv = [
        f"--trainFile={train}", "--numFeatures=64", f"--numRounds={rounds}",
        "--localIterFrac=0.2", "--numSplits=2", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=10", f"--chkptDir={ckdir}",
        "--chkptIter=10", "--dtype=float64",
    ]
    # each worker gets ONE cpu device (2-device global mesh over Gloo)
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ))

    gens = []

    def on_generation(gen, procs):
        gens.append(gen)
        if gen == 0:
            def killer():
                # wait for the run to be demonstrably mid-flight (a first
                # checkpoint exists), then SIGKILL one worker
                for _ in range(600):
                    if ckpt_lib.latest(str(ckdir), "CoCoA+"):
                        break
                    _time.sleep(0.25)
                if procs[1].poll() is None:
                    procs[1].send_signal(signal.SIGKILL)
            threading.Thread(target=killer, daemon=True).start()

    rc = elastic.supervise(argv, 2, max_restarts=3,
                           on_generation=on_generation, quiet_tail=True)
    assert rc == 0
    assert len(gens) >= 2, "the gang was never restarted"
    # the second CoCoA+ pass (justCoCoA runs CoCoA+ then CoCoA) finished:
    # a final-round checkpoint exists for both algorithms
    for alg in ("CoCoA+", "CoCoA"):
        path = ckpt_lib.latest(str(ckdir), alg)
        assert path is not None
        meta, w, a = ckpt_lib.load(path)
        assert meta["round"] == rounds
        assert w.shape == (64,) and a is not None


@pytest.mark.slow
def test_two_process_loading_materializes_only_local_shard(tmp_path):
    """VERDICT r1 item 5: per-process memory stays ~1/K of the dense
    matrix — each process builds only its own shard's host slab and device
    buffer (data/sharding._shard_dataset_distributed), never the full
    (K, n_shard, d) array."""
    worker = tmp_path / "memworker.py"
    worker.write_text(_MEM_WORKER)
    port = _free_port()
    env = {**os.environ, "PYTHONPATH": f"{ROOT}{os.pathsep}{TESTS}"}
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=ROOT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=220)
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out[-2000:]}"
        r = json.loads(lines[-1][len("RESULT "):])
        # own shard host slab (1/2) + its device buffer (1/2) + slack —
        # the old replicated path cost >= 2x full (numpy (K,·,d) + buffers)
        assert r["frac"] < 1.35, r


_WEDGE_WORKER = r"""
import os, sys, time

# Fault injection for the stall-watchdog wedge test: on the FIRST
# generation only (marker file absent), worker 1 lets two checkpoints land
# and then WEDGES inside checkpoint.save — it stops checkpointing but
# stays alive, and worker 0 blocks at the next collective.  No process
# dies, so death-only supervision would poll this gang forever.
marker = os.environ["WEDGE_MARKER"]
proc_id = [a for a in sys.argv[1:] if a.startswith("--processId=")]
proc_id = proc_id[0].split("=", 1)[1] if proc_id else "?"
if proc_id == "1" and not os.path.exists(marker):
    open(marker, "w").write("wedged")
    import cocoa_tpu.checkpoint as _ckpt
    _real_save = _ckpt.save
    _n = [0]
    def _wedging_save(*a, **k):
        _n[0] += 1
        if _n[0] > 2:
            time.sleep(3600)  # alive, silent, making no progress
        return _real_save(*a, **k)
    _ckpt.save = _wedging_save

from cocoa_tpu.cli import main
sys.exit(main(sys.argv[1:]))
"""


@pytest.mark.slow
def test_stall_watchdog_recovers_wedged_but_alive_gang(tmp_path, monkeypatch):
    """VERDICT r5 #6, end-to-end: one worker STOPS CHECKPOINTING but stays
    alive (wedged inside checkpoint.save), its peer blocks in the next
    collective — no death for death-only supervision to see.  The
    --stallTimeout watchdog kills the gang and restarts it from the last
    good checkpoint, and the run completes with the same final state an
    unwedged run reaches (resume exactness itself is pinned by
    tests/test_crash_resume.py; this pins the watchdog mechanics
    end-to-end: detection without a death, teardown, restart, completion).
    """
    import jax as _jax

    if not hasattr(_jax, "shard_map"):
        pytest.skip("the 2-process gang rides the mesh path, which needs "
                    "jax.shard_map (newer jax)")
    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu import elastic
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    data = synth_sparse(96, 64, nnz_mean=8, seed=2)
    train = tmp_path / "train.dat"
    write_libsvm(data, str(train))
    ckdir = tmp_path / "ck"
    marker = tmp_path / "wedged.marker"
    wedge_mod = tmp_path / "wedge_worker.py"
    wedge_mod.write_text(_WEDGE_WORKER)
    rounds = 200
    argv = [
        f"--trainFile={train}", "--numFeatures=64", f"--numRounds={rounds}",
        "--localIterFrac=0.2", "--numSplits=2", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=10", f"--chkptDir={ckdir}",
        "--chkptIter=10", "--dtype=float64",
    ]
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ))
    monkeypatch.setenv("WEDGE_MARKER", str(marker))
    monkeypatch.setenv(
        "PYTHONPATH",
        f"{tmp_path}{os.pathsep}{os.environ.get('PYTHONPATH', '')}")

    def progress_token():
        # the cli.py supervisor's token: the checkpoint directory listing
        if not ckdir.is_dir():
            return None
        return tuple(sorted(f for f in os.listdir(ckdir)
                            if f.endswith(".npz")))

    gens = []
    rc = elastic.supervise(
        argv, 2, max_restarts=3, module="wedge_worker",
        on_generation=lambda gen, procs: gens.append(gen),
        progress_token=progress_token,
        # generous vs compile time, tiny vs the 3600 s wedge: the watchdog
        # is the ONLY thing that can unwedge this gang
        stall_timeout_s=90.0,
    )
    assert rc == 0
    assert marker.exists(), "the fault was never injected"
    assert len(gens) >= 2, "the wedged gang was never restarted"
    # the run completed: final-round checkpoints exist for both algorithms
    for alg in ("CoCoA+", "CoCoA"):
        path = ckpt_lib.latest(str(ckdir), alg)
        assert path is not None
        meta, w, a = ckpt_lib.load(path)
        assert meta["round"] == rounds
        assert w.shape == (64,) and a is not None
    # and bit-identically: an unwedged reference gang (same flags, same
    # 2-process layout) reaches exactly the same final checkpoint state —
    # round-keyed sampling makes restart-resume invisible to the math
    refdir = tmp_path / "ck_ref"
    ref_argv = [a if str(ckdir) not in a else f"--chkptDir={refdir}"
                for a in argv]
    marker.unlink()
    open(marker, "w").write("disarm")  # marker present -> no wedge
    rc_ref = elastic.supervise(
        ref_argv, 2, max_restarts=0, module="wedge_worker",
    )
    assert rc_ref == 0
    for alg in ("CoCoA+", "CoCoA"):
        _, w0, a0 = ckpt_lib.load(ckpt_lib.latest(str(ckdir), alg))
        _, w1, a1 = ckpt_lib.load(ckpt_lib.latest(str(refdir), alg))
        np.testing.assert_array_equal(w0, w1)
        np.testing.assert_array_equal(a0, a1)
