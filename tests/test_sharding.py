"""Sharded dataset layout tests: balanced contiguous splits, padding
invariants, dense/sparse agreement, mesh placement."""

import jax
import numpy as np

from cocoa_tpu.data.sharding import shard_dataset, split_sizes
from cocoa_tpu.parallel import make_mesh


def test_split_sizes_balanced():
    s = split_sizes(2000, 4)
    assert s.tolist() == [500, 500, 500, 500]
    s = split_sizes(10, 3)
    assert s.tolist() == [4, 3, 3]
    assert split_sizes(2, 8).tolist() == [1, 1] + [0] * 6


def test_dense_shards_contiguous(tiny_data):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64)
    assert ds.layout == "dense"
    # 96 rows / 4 shards = 24 each, padded to the 16-row sublane multiple
    assert ds.X.shape == (4, 32, tiny_data.num_features)
    dense = tiny_data.to_dense()
    # shard 1 holds rows 24..48 in order (then padding)
    np.testing.assert_allclose(np.asarray(ds.X[1, :24]), dense[24:48])
    np.testing.assert_allclose(np.asarray(ds.labels[1, :24]), tiny_data.labels[24:48])
    np.testing.assert_allclose(np.asarray(ds.X[1, 24:]), 0.0)
    np.testing.assert_allclose(np.asarray(ds.mask[:, :24]), 1.0)
    np.testing.assert_allclose(np.asarray(ds.mask[:, 24:]), 0.0)


def test_sparse_dense_same_semantics(tiny_data):
    dd = shard_dataset(tiny_data, k=3, layout="dense", dtype=np.float64)
    sd = shard_dataset(tiny_data, k=3, layout="sparse", dtype=np.float64)
    # reconstruct dense rows from padded-CSR and compare
    for s in range(3):
        for i in range(int(sd.counts[s])):
            row = np.zeros(tiny_data.num_features)
            idx = np.asarray(sd.sp_indices[s, i])
            val = np.asarray(sd.sp_values[s, i])
            np.add.at(row, idx, val)
            np.testing.assert_allclose(row, np.asarray(dd.X[s, i]), atol=1e-12)
    np.testing.assert_allclose(
        np.asarray(sd.sq_norms), np.asarray(dd.sq_norms), atol=1e-12
    )


def test_padding_and_sq_norms(tiny_data):
    # 96 rows over 5 shards → sizes [20,19,19,19,19], padded to the 16-row
    # sublane multiple (32)
    ds = shard_dataset(tiny_data, k=5, layout="dense", dtype=np.float64)
    assert ds.counts.tolist() == [20, 19, 19, 19, 19]
    assert ds.n_shard == 32
    m = np.asarray(ds.mask)
    assert np.all(m[1:, 19:] == 0.0)
    assert np.all(np.asarray(ds.X)[1:, 19:] == 0.0)
    dense = tiny_data.to_dense()
    np.testing.assert_allclose(
        np.asarray(ds.sq_norms[0, :20]),
        np.sum(dense[:20] ** 2, axis=1),
        rtol=1e-12,
    )


def test_segment_sq_norms_edge_cases():
    """Trailing empty segments must not steal the last nonzero (the naive
    clamped-reduceat idiom did exactly that), interior empties must be 0,
    and tiny segments must not be absorbed by a global running sum."""
    from cocoa_tpu.data.sharding import segment_sq_norms

    np.testing.assert_array_equal(
        segment_sq_norms(np.array([1., 2., 3.]), np.array([0, 3, 3])),
        [14., 0.])
    np.testing.assert_array_equal(
        segment_sq_norms(np.array([1., 2., 3.]), np.array([0, 1, 3, 3])),
        [1., 13., 0.])
    np.testing.assert_array_equal(
        segment_sq_norms(np.array([1., 2.]), np.array([0, 0, 2])), [0., 5.])
    np.testing.assert_array_equal(
        segment_sq_norms(np.zeros(0), np.array([0, 0])), [0.])
    # exactness: a 1e-9 value after a huge segment must not vanish
    out = segment_sq_norms(np.array([1e5, 1e-9]), np.array([0, 1, 2]))
    np.testing.assert_array_equal(out, [1e10, 1e-18])


def test_auto_layout_picks_sparse_for_sparse_data(small_train):
    ds = shard_dataset(small_train, k=4, layout="auto")
    assert ds.layout == "sparse"  # density ~0.2% on small_train


def test_mesh_placement(tiny_data):
    mesh = make_mesh(4)
    assert mesh.shape["dp"] == 4
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=np.float64, mesh=mesh)
    assert len(ds.X.sharding.device_set) == 4
    # each device holds exactly its shard
    shard_shapes = {s.data.shape for s in ds.X.addressable_shards}
    assert shard_shapes == {(1, 32, tiny_data.num_features)}


def test_make_mesh_too_many_devices():
    import pytest

    with pytest.raises(ValueError):
        make_mesh(100)
