"""JavaRandom must be bit-exact with java.util.Random (the engine behind
scala.util.Random, used at CoCoA.scala:144)."""

import numpy as np
import pytest

from cocoa_tpu.utils.prng import JavaRandom, sample_indices


def test_next_int_seed0_known_sequence():
    # First values of `new java.util.Random(0).nextInt()` — fixed by the
    # Java SE LCG spec.
    r = JavaRandom(0)
    got = [r.next_int() for _ in range(5)]
    assert got == [-1155484576, -723955400, 1033096058, -1690734402, -1557280266]


def test_next_int_bounded_range_and_determinism():
    r1 = JavaRandom(42)
    r2 = JavaRandom(42)
    seq1 = [r1.next_int(500) for _ in range(1000)]
    seq2 = [r2.next_int(500) for _ in range(1000)]
    assert seq1 == seq2
    assert all(0 <= v < 500 for v in seq1)
    # roughly uniform (loose sanity bound)
    assert np.mean(seq1) == pytest.approx(249.5, rel=0.15)


def test_power_of_two_bound_path():
    r = JavaRandom(123)
    vals = [r.next_int(64) for _ in range(2000)]
    assert all(0 <= v < 64 for v in vals)
    assert len(set(vals)) == 64


def test_sample_indices_matches_direct_replay():
    # Round table must equal seeding Random(seed + t) per round
    # (CoCoA.scala:45,144,151).
    tab = sample_indices(seed=5, rounds=range(1, 4), h=10, n_local=33)
    for i, t in enumerate(range(1, 4)):
        r = JavaRandom(5 + t)
        expect = [r.next_int(33) for _ in range(10)]
        assert tab[i].tolist() == expect


def test_vectorized_lcg_bitexact_vs_scalar_many_bounds():
    # The numpy-vectorized path (incl. pow2 fast path and rejection loop)
    # must be bit-exact with the scalar spec implementation.
    from cocoa_tpu.utils.prng import sample_indices_per_shard

    bounds = [1, 2, 7, 64, 500, 1000, 2**31 - 1]
    tab = sample_indices_per_shard(seed=99, rounds=range(0, 5), h=64, n_locals=bounds)
    for k, b in enumerate(bounds):
        for i, t in enumerate(range(0, 5)):
            r = JavaRandom(99 + t)
            expect = [r.next_int(b) for _ in range(64)]
            assert tab[k, i].tolist() == expect, (b, t)


def test_jax_rng_mode_decorrelates_and_stays_in_bounds():
    """--rng=jax: per-(seed, round) keys, draws DECORRELATED across equal
    shards (the statistical improvement over the reference's
    correlated-across-workers seeding), deterministic given the seed, and
    chunk tables consistent with per-round tables."""
    from cocoa_tpu.solvers.base import IndexSampler

    counts = np.array([33, 33, 40])
    s = IndexSampler("jax", seed=5, h=64, counts=counts)
    tab = np.asarray(s.chunk_indices(1, 3))         # (3, K, H)
    assert tab.shape == (3, 3, 64)
    for kk, bound in enumerate(counts):
        assert tab[:, kk].min() >= 0 and tab[:, kk].max() < bound
    # equal-size shards draw DIFFERENT indices (reference mode draws equal)
    assert not np.array_equal(tab[:, 0], tab[:, 1])
    # deterministic + chunk/round consistency
    s2 = IndexSampler("jax", seed=5, h=64, counts=counts)
    np.testing.assert_array_equal(np.asarray(s2.round_indices(2)), tab[1])


def test_jax_rng_mode_converges(tiny_data):
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = Params(n=tiny_data.n, num_rounds=40, local_iters=30, lam=0.01)
    dbg = DebugParams(debug_iter=10, seed=0)
    w, _, traj = run_cocoa(ds, p, dbg, plus=True, quiet=True, rng="jax")
    gaps = [r.gap for r in traj.records]
    assert gaps[-1] < gaps[0] and gaps[-1] < 0.1
    # the chunked path must produce the identical jax-mode trajectory
    w2, _, _ = run_cocoa(ds, p, dbg, plus=True, quiet=True, rng="jax",
                         scan_chunk=10)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w), atol=1e-12)


def test_sample_indices_rejects_empty_shard():
    import pytest

    from cocoa_tpu.utils.prng import sample_indices_per_shard

    with pytest.raises(ValueError):
        sample_indices_per_shard(0, range(1, 2), 4, [5, 0])
